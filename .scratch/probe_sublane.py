"""Probe: per-dispatch cost of (1, Lblk) row ops vs (1, 8, C) slab ops.

Simulates the warp-interpreter's inner loop access pattern: a
lax.while_loop whose body reads two dynamic rows of a VMEM scratch
plane, combines them, and writes one row back (the shape of every
ALU2 handler).  Old layout: rows are (1, Lblk).  New layout: rows are
(1, 8, C) slabs with C = Lblk // 8.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

D = 64
LBLK = 4096
C = LBLK // 8
STEPS = 20000


def build(kind):
    if kind == "old":
        shape = (D, LBLK)

        def srow(ref, i):
            return ref[pl.ds(i, 1), :]

        def wrow(ref, i, v):
            ref[pl.ds(i, 1), :] = v
    else:
        shape = (D, 8, C)

        def srow(ref, i):
            return ref[pl.ds(i, 1), :, :]

        def wrow(ref, i, v):
            ref[pl.ds(i, 1), :, :] = v

    def kernel(x_ref, o_ref, scr, sem):
        cp = pltpu.make_async_copy(x_ref, scr, sem)
        cp.start()
        cp.wait()

        def body(c):
            i, acc = c
            a = srow(scr, i % (D - 2))
            b = srow(scr, (i + 1) % (D - 2))
            wrow(scr, D - 1, a + b ^ (a >> 1))
            return (i + 1, acc + 1)

        def cond(c):
            return c[0] < STEPS

        lax.while_loop(cond, body, (jnp.int32(0), jnp.int32(0)))
        cp = pltpu.make_async_copy(scr, o_ref, sem)
        cp.start()
        cp.wait()

    fn = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
        scratch_shapes=[pltpu.VMEM(shape, jnp.int32),
                        pltpu.SemaphoreType.DMA],
    )
    x = jnp.asarray(np.random.randint(0, 100, shape, np.int32))
    return jax.jit(fn), x


for kind in ("old", "new"):
    try:
        fn, x = build(kind)
        r = fn(x)
        r.block_until_ready()
        t0 = time.perf_counter()
        N = 5
        for _ in range(N):
            r = fn(x)
        r.block_until_ready()
        dt = (time.perf_counter() - t0) / N
        print(f"{kind}: {dt*1e3:.2f} ms/launch, "
              f"{dt/STEPS*1e9:.1f} ns/step, "
              f"{STEPS*LBLK/dt/1e9:.2f} G lane-ops/s")
    except Exception as e:
        print(f"{kind}: FAILED {type(e).__name__}: {str(e)[:500]}")
