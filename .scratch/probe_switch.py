"""Probe 2: isolate what makes the warp-interpreter step cost 590ns.

Adds, one at a time: SMEM-table-driven pc chain, lax.switch over N
handlers, multi-row handlers (slo/shi pairs), carry width.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

D = 64
LBLK = 4096
STEPS = 200000
CODE = 256


def build(nhandlers, rows_per_handler, sub8):
    C = LBLK // 8
    shape = (D, 8, C) if sub8 else (D, LBLK)

    def srow(ref, i):
        return ref[pl.ds(i, 1)] if sub8 else ref[pl.ds(i, 1), :]

    def wrow(ref, i, v):
        if sub8:
            ref[pl.ds(i, 1)] = v
        else:
            ref[pl.ds(i, 1), :] = v

    def kernel(hid_r, a_r, x_ref, o_ref, slo, shi, sem):
        for ref in (slo,):
            cp = pltpu.make_async_copy(x_ref, ref, sem)
            cp.start()
            cp.wait()

        def mk_handler(k):
            def h(c):
                steps, pc, sp = c
                out_sp = (sp + 1) % (D - 2)
                for r in range(rows_per_handler):
                    a = srow(slo, (sp + k) % (D - 2))
                    b = srow(shi, (sp + r) % (D - 2))
                    wrow(slo, out_sp, a + b)
                    wrow(shi, out_sp, a ^ b)
                return (steps, a_r[pc], out_sp)
            return h

        handlers = [mk_handler(k) for k in range(nhandlers)]

        def body(c):
            steps, pc, sp = c
            nc = lax.switch(hid_r[pc], handlers, c)
            return (steps + 1, nc[1], nc[2])

        def cond(c):
            return c[0] < STEPS

        lax.while_loop(cond, body, (jnp.int32(0), jnp.int32(0),
                                    jnp.int32(0)))
        cp = pltpu.make_async_copy(slo, o_ref, sem)
        cp.start()
        cp.wait()

    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM(shape, jnp.int32),
                        pltpu.VMEM(shape, jnp.int32),
                        pltpu.SemaphoreType.DMA],
    )
    fn = pl.pallas_call(
        kernel, grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct(shape, jnp.int32),
    )
    hid = jnp.asarray(np.random.randint(0, nhandlers, CODE, np.int32))
    a = jnp.asarray(np.random.randint(0, CODE, CODE, np.int32))
    x = jnp.asarray(np.random.randint(0, 100, shape, np.int32))
    return jax.jit(fn), (hid, a, x)


for sub8 in (False, True):
    for nh, rph in ((1, 1), (8, 1), (32, 1), (32, 2), (64, 2)):
        try:
            fn, args = build(nh, rph, sub8)
            r = fn(*args)
            r.block_until_ready()
            t0 = time.perf_counter()
            N = 3
            for _ in range(N):
                r = fn(*args)
            r.block_until_ready()
            dt = (time.perf_counter() - t0) / N
            print(f"sub8={sub8} handlers={nh} rows={rph}: "
                  f"{dt/STEPS*1e9:7.1f} ns/step")
        except Exception as e:
            print(f"sub8={sub8} handlers={nh} rows={rph}: FAILED "
                  f"{type(e).__name__}: {str(e)[:300]}")
