"""Driver benchmark: aggregate Wasm interpreter throughput on TPU.

Runs the flagship workload from BASELINE.json config 1 — 4096 concurrent
fib(30) instances executed by the Pallas warp-interpreter (the on-device
dispatch loop, wasmedge_tpu/batch/pallas_engine.py) — and prints ONE JSON
line:

  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value        aggregate retired wasm instructions / second over all lanes
vs_baseline  value / (50 x single-core interpreter ops/s) — the BASELINE.json
             north star is ">=50x aggregate interpreter throughput vs
             single-core CPU", so vs_baseline >= 1.0 meets the bar.  The
             single-core denominator is measured live with the native C++
             scalar engine over the same lowered image when built
             (wasmedge_tpu/native — the honest stand-in for the reference's
             dispatch loop, /root/reference/lib/executor/engine/
             engine.cpp:68-1641, which cannot be built offline: its cmake
             FetchContent needs network); a recorded estimate is the
             fallback (BASELINE.md).
"""

import json
import os as _os
import sys
import time

import numpy as np

LANES = 4096
# BASELINE.json config 1: fib(30) per lane.  BENCH_FIB_N scales the
# flagship down for CPU-container rounds (the r5 floors are TPU
# numbers; a CPU container at ~hundreds of lockstep steps/s cannot
# finish fib(30)x4096 in a bench budget) — the metric name and the
# artifact record the actual n, so a scaled number can never be
# mistaken for the flagship floor.
FIB_N = int(_os.environ.get("BENCH_FIB_N", "30"))
WARMUP_N = 8        # small run to trigger compilation before timing

# Recorded single-core C++ interpreter throughput (wasm instrs/sec) used
# only if the native engine is unavailable.  Methodology note in BASELINE.md.
RECORDED_CPP_INTERP_OPS = 150e6
TARGET_MULTIPLE = 50.0


def _instantiate_fib(conf):
    """Instantiate the flagship fib module under `conf` -> (inst, store)."""
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return inst, store


def _build(lanes):
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure

    import os

    conf = Configure()
    conf.batch.steps_per_launch = 50_000_000
    # Size the per-lane stacks to the workload (fib(30) needs ~180 value
    # slots / 30 frames); smaller state -> bigger lane blocks in VMEM.
    conf.batch.value_stack_depth = 256
    conf.batch.call_stack_depth = 256
    # Flight recorder on by default (events are per-launch, and the
    # flagship is a handful of launches — immeasurable against a
    # 50M-step chunk); the trace artifact ships alongside the bench
    # JSON so a regression investigation starts from attributable
    # timings, not aggregates.  BENCH_OBS=off measures the recorder-
    # DISABLED configuration the r5/r6 floors were taken under — the
    # mode to reach for when separating a suspected obs overhead
    # regression from an engine regression.
    conf.obs.enabled = os.environ.get("BENCH_OBS", "on") != "off"
    inst, store = _instantiate_fib(conf)
    return UniformBatchEngine(inst, store=store, conf=conf, lanes=lanes)


def _emit_trace(rec, default_path):
    """Write the flight-recorder trace next to the bench artifact
    (stdout stays one JSON line for the driver; BENCH_ARTIFACT
    redirects/disables apply like every other artifact)."""
    from wasmedge_tpu.utils.bench_artifact import artifact_path

    path = artifact_path(default_path)
    if path is None or rec is None or not rec.enabled:
        return
    from wasmedge_tpu.obs.trace import export_chrome_trace

    try:
        export_chrome_trace(rec, path)
    except OSError:
        pass  # the artifact is a record, never a bench failure


def _native_baseline_ops():
    """Single-core ops/s, measured live on the native C++ scalar engine."""
    try:
        from wasmedge_tpu.native import scalar_fib_ops_per_sec

        return float(scalar_fib_ops_per_sec(FIB_N)), "cpp-scalar-engine"
    except Exception:
        return RECORDED_CPP_INTERP_OPS, "recorded-estimate"


def _smoke_echo_engine(conf, lanes):
    """Shared smoke scaffolding: echo module + WASI with fd 1 sunk to
    /dev/null, tiny stacks/chunks, flight recorder on.  Returns
    (engine, sink_fd); used by --faults-smoke and --trace-smoke so the
    two CI modes exercise the same construction path."""
    import os

    import bench_echo
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    # small chunks so injected faults land mid-run, after at least one
    # checkpoint exists (echo retires in a few hundred steps per lane)
    conf.batch.steps_per_launch = 100
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    conf.obs.enabled = True
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    sink = os.open(os.devnull, os.O_WRONLY)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(
        Loader(conf).parse_module(bench_echo.build_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes), sink


def faults_smoke() -> int:
    """`bench.py --faults-smoke`: run the echo workload once under a
    single injected launch fault and assert the supervisor recovers —
    the CI guard that supervised execution stays wired end-to-end (the
    recorder is on, so the smoke also asserts the injected incident
    shows up in the trace).  Prints ONE JSON line; emits no benchmark
    artifact (this mode measures recovery, not throughput)."""
    import os
    import tempfile

    from wasmedge_tpu.batch.supervisor import BatchSupervisor
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.testing.faults import Fault, FaultInjector

    # enough iterations that even the FUSED build (batch/fuse.py
    # retires whole runs per dispatch) needs multiple launches, so the
    # at=1 fault lands after the first checkpoint exists
    lanes, iters = 64, 8
    conf = Configure()
    conf.supervisor.checkpoint_every_steps = 100
    conf.supervisor.backoff_base_s = 0.0
    eng, sink = _smoke_echo_engine(conf, lanes)
    inj = FaultInjector([Fault(point="launch", at=1)])
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="faults-smoke-") as d:
        sup = BatchSupervisor(eng, conf=conf, faults=inj,
                              checkpoint_dir=d)
        res = sup.run("echo", [np.full(lanes, iters, np.int64)],
                      max_steps=1_000_000)
    dt = time.perf_counter() - t0
    os.close(sink)
    # the injected incident must be visible in the flight recorder's
    # event stream (mirrored FailureRecord instant on the supervisor
    # track) — the fault harness and the obs subsystem stay wired
    trace_has_incident = "failure/launch" in sup.obs.event_names()
    ok = bool(res.completed.all()) and inj.fired == 1 \
        and any(f.fault_class == "launch" for f in sup.failures) \
        and trace_has_incident
    print(json.dumps({
        "metric": "faults_smoke_echo_recovery",
        "value": 1 if ok else 0,
        "unit": "recovered",
        "ok": ok,
        "injected": inj.fired,
        "failures": [f.fault_class for f in sup.failures],
        "trace_has_incident": trace_has_incident,
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def mesh_faults_smoke() -> int:
    """`bench.py --mesh-faults-smoke`: run the echo workload across 4
    fake CPU devices under one injected device fault and assert the
    mesh supervisor recovers — the CI guard that mesh-level fault
    tolerance (parallel/supervisor.py) stays wired end-to-end,
    mirroring --faults-smoke / --serve-smoke.  Prints ONE JSON line;
    emits no benchmark artifact (this mode measures recovery, not
    throughput)."""
    import os
    import tempfile

    # the fake multi-device mesh must exist before the first jax import
    # (same mechanism as tests/conftest.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.parallel.supervisor import MeshSupervisor
    from wasmedge_tpu.testing.faults import Fault, FaultInjector

    lanes, iters = 64, 2
    conf = Configure()
    conf.supervisor.checkpoint_every_steps = 200
    conf.supervisor.backoff_base_s = 0.0
    eng, sink = _smoke_echo_engine(conf, lanes)
    devices = jax.devices()[:4]
    inj = FaultInjector([Fault(point="device_launch", at=0,
                               match={"device": 1})])
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="mesh-faults-smoke-") as d:
        sup = MeshSupervisor(eng.inst, store=eng.store, conf=conf,
                             devices=devices, faults=inj,
                             checkpoint_dir=d)
        res = sup.run("echo", [np.full(lanes, iters, np.int64)],
                      max_steps=1_000_000)
    os.close(sink)
    # the injected device incident must be visible in the flight
    # recorder's event stream (mirrored FailureRecord instant)
    trace_has_incident = \
        "failure/device_launch" in sup.obs.event_names()
    ok = bool(res.completed.all()) and inj.fired == 1 \
        and any(f.fault_class == "device_launch" for f in sup.failures) \
        and trace_has_incident and len(devices) == 4

    # phase 2 (r15): an injected SHARD-DRIVE fault must demote the
    # supervisor to the threaded per-device rung — fallback-ladder
    # wiring for the single-program mesh drive.  No cadence here, so
    # the shard tier is attempted (and killed) first.
    conf2 = Configure()
    conf2.supervisor.backoff_base_s = 0.0
    eng2, sink2 = _smoke_echo_engine(conf2, lanes)
    inj2 = FaultInjector([Fault(point="shard_launch", at=0)])
    sup2 = MeshSupervisor(eng2.inst, store=eng2.store, conf=conf2,
                          devices=devices, faults=inj2)
    res2 = sup2.run("echo", [np.full(lanes, iters, np.int64)],
                    max_steps=1_000_000)
    os.close(sink2)
    dt = time.perf_counter() - t0
    shard_fell_back = bool(res2.completed.all()) and inj2.fired == 1 \
        and any(f.fault_class == "shard_drive" for f in sup2.failures) \
        and "failure/shard_drive" in sup2.obs.event_names()
    ok = ok and shard_fell_back
    print(json.dumps({
        "metric": "mesh_faults_smoke_echo_recovery",
        "value": 1 if ok else 0,
        "unit": "recovered",
        "ok": ok,
        "devices": len(devices),
        "injected": inj.fired,
        "failures": [f.fault_class for f in sup.failures],
        "trace_has_incident": trace_has_incident,
        "shard_drive_fell_back_to_threaded": shard_fell_back,
        "shard_failures": [f.fault_class for f in sup2.failures],
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def _mesh_env(n: int = 8):
    """Force the virtual n-device CPU mesh (must run before the first
    jax import — same mechanism as tests/conftest.py) and return jax.
    A pre-existing smaller device-count flag is REPLACED, not kept —
    an 8-device artifact must never silently record 4-device numbers —
    and a backend already initialized with fewer devices fails loudly."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n:
        raise SystemExit(
            f"mesh bench needs {n} virtual devices, backend has "
            f"{len(jax.devices())} (jax initialized before _mesh_env?)")
    return jax


def _mesh_parity(jax, report: dict) -> bool:
    """Shard-drive parity block shared by --mesh-smoke and --mesh-bench:
    merged results must be bit-identical to single-device
    execute_batch across device counts, including an uneven
    `lanes % n_devices` split (pad lanes must never retire) and the
    hostcall-heavy echo workload (no duplicated WASI side effects)."""
    import os

    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.parallel.shard_drive import ShardDrive

    ok = True
    # fib, uneven 30 lanes over 8 and 4 devices
    conf = Configure()
    conf.batch.steps_per_launch = 2000
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    inst, store = _instantiate_fib(conf)
    lanes = 30
    ns = (np.arange(lanes, dtype=np.int64) % 11)
    ref = BatchEngine(inst, store=store, conf=conf, lanes=lanes).run(
        "fib", [ns], max_steps=300_000)
    for n in (4, 8):
        res = ShardDrive(inst, store=store, conf=conf,
                         devices=jax.devices()[:n]).run(
            "fib", [ns], max_steps=300_000)
        same = bool((res.results[0] == ref.results[0]).all()
                    and (res.trap == ref.trap).all()
                    and (res.retired == ref.retired).all())
        report[f"fib_parity_{n}dev"] = same
        ok = ok and same
    # hostcall-heavy echo, uneven 20 lanes over 8 devices
    conf_e = Configure()
    ref_eng, sink1 = _smoke_echo_engine(conf_e, 20)
    iters = np.full(20, 2, np.int64)
    eref = ref_eng.run("echo", [iters], max_steps=200_000)
    conf_s = Configure()
    conf_s.obs.enabled = True   # the mesh_round spans must appear
    s_eng, sink2 = _smoke_echo_engine(conf_s, 20)
    drv = ShardDrive(s_eng.inst, store=s_eng.store, conf=conf_s,
                     devices=jax.devices()[:8])
    eres = drv.run("echo", [iters], max_steps=200_000)
    os.close(sink1)
    os.close(sink2)
    echo_same = bool((eres.results[0] == eref.results[0]).all()
                     and (eres.trap == eref.trap).all()
                     and (eres.retired == eref.retired).all())
    # WASI effect parity: the shard drive's engine must have produced
    # exactly the single-device stdout volume (pads write nothing)
    wasi_same = (drv.engine.hostcall_stats["stdout_bytes"]
                 == ref_eng.hostcall_stats["stdout_bytes"])
    spans = "mesh_round" in drv.engine.obs.event_names()
    report["echo_parity_8dev"] = echo_same
    report["echo_wasi_bytes_equal"] = wasi_same
    report["mesh_round_spans"] = spans
    return ok and echo_same and wasi_same and spans


def mesh_smoke() -> int:
    """`bench.py --mesh-smoke`: the pass/fail CI guard for the
    single-program shard drive — bit-identical merged results vs
    single-device execute_batch across device counts (incl. uneven
    splits and the hostcall-heavy echo), per-device mesh_round spans
    present.  Prints ONE JSON line; no artifact."""
    jax = _mesh_env(8)
    t0 = time.perf_counter()
    report: dict = {}
    ok = _mesh_parity(jax, report)
    print(json.dumps({
        "metric": "mesh_smoke_shard_drive_parity",
        "value": 1 if ok else 0,
        "unit": "bit_identical",
        "ok": bool(ok),
        "devices": len(jax.devices()),
        "wall_s": round(time.perf_counter() - t0, 3),
        **report,
    }))
    return 0 if ok else 1


def mesh_bench() -> int:
    """`bench.py --mesh-bench`: threaded vs shard_map drive on the
    8-virtual-device CPU mesh (flagship-shaped fib + hostcall-heavy
    echo).  Emits MESH_r15.json (drive-overhead matrix: per-round
    host-side drive cost across device counts — the shard drive issues
    ONE dispatch per round regardless of device count, so its per-round
    overhead must not scale with devices) and a refreshed
    BENCH_r15.json (obs-off flagship number against the r5 floors).
    CPU-container numbers: virtual devices share host cores, so
    absolute rates are wiring floors, not capacity claims."""
    import os

    jax = _mesh_env(8)

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.parallel.shard_drive import ShardDrive
    from wasmedge_tpu.utils.bench_artifact import emit

    report: dict = {}
    parity_ok = _mesh_parity(jax, report)

    # --- per-round host drive overhead vs device count ---------------
    # tiny chunks make every round host-overhead-dominated: wall /
    # rounds then measures the DRIVE cost per launch boundary, the
    # quantity that scaled with device count on the threaded drive.
    # Each cell runs TWICE under a shared persistent compilation cache
    # and reports the second (warm) run — a cold cell would measure
    # XLA compile-time scaling, not drive overhead.
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="mesh-bench-jit-cache-")
    prev_cache = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)

    def overhead_once(drive: str, n: int):
        conf = Configure()
        conf.batch.steps_per_launch = 64
        conf.batch.value_stack_depth = 128
        conf.batch.call_stack_depth = 64
        conf.supervisor.use_kernel_tier = False   # threaded SIMT rung
        conf.supervisor.backoff_base_s = 0.0
        inst, store = _instantiate_fib(conf)
        lanes = 512
        ns = np.full(lanes, 12, np.int64)
        devices = jax.devices()[:n]
        t0 = time.perf_counter()
        if drive == "shard":
            res = ShardDrive(inst, store=store, conf=conf,
                             devices=devices).run(
                "fib", [ns], max_steps=1_000_000)
        else:
            from wasmedge_tpu.parallel.supervisor import MeshSupervisor

            res = MeshSupervisor(inst, store=store, conf=conf,
                                 devices=devices,
                                 drive="threaded").run(
                "fib", [ns], max_steps=1_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all()
        rounds = max(int(np.ceil(res.steps / 64)), 1)
        return {"wall_s": round(dt, 3), "rounds": rounds,
                "ms_per_round": round(1e3 * dt / rounds, 3)}

    def overhead(drive: str, n: int):
        overhead_once(drive, n)          # populate the compile cache
        return overhead_once(drive, n)   # the warm measurement

    matrix = {}
    try:
        for drive in ("shard", "threaded"):
            for n in (2, 4, 8):
                matrix[f"{drive}_{n}dev"] = overhead(drive, n)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)
    shard_growth = matrix["shard_8dev"]["ms_per_round"] \
        / max(matrix["shard_2dev"]["ms_per_round"], 1e-9)
    threaded_growth = matrix["threaded_8dev"]["ms_per_round"] \
        / max(matrix["threaded_2dev"]["ms_per_round"], 1e-9)

    # --- hostcall-heavy echo throughput, both drives @ 8 devices -----
    def echo_rate(drive: str):
        conf = Configure()
        conf.batch.steps_per_launch = 100
        eng, sink = _smoke_echo_engine(conf, 128)
        conf.obs.enabled = False
        iters = np.full(128, 2, np.int64)
        t0 = time.perf_counter()
        calls = 2 * 128 * 2   # two fd_writes per iteration per lane
        if drive == "shard":
            drv = ShardDrive(eng.inst, store=eng.store, conf=conf,
                             devices=jax.devices()[:8])
            res = drv.run("echo", [iters], max_steps=2_000_000)
        else:
            from wasmedge_tpu.parallel.supervisor import MeshSupervisor

            conf.supervisor.use_kernel_tier = False
            conf.supervisor.backoff_base_s = 0.0
            res = MeshSupervisor(eng.inst, store=eng.store, conf=conf,
                                 devices=jax.devices()[:8],
                                 drive="threaded").run(
                "echo", [iters], max_steps=2_000_000)
        dt = time.perf_counter() - t0
        os.close(sink)
        assert res.completed.all()
        return {"wall_s": round(dt, 3),
                "calls_per_s": round(calls / dt, 1)}

    echo = {"shard": echo_rate("shard"), "threaded": echo_rate("threaded")}

    # the r15 claim: per-round host-side drive overhead no longer
    # scales with device count (threaded grew with n; shard must not)
    ok = bool(parity_ok and shard_growth < max(2.0, 0.75 * threaded_growth))
    out = {
        "metric": "mesh_drive_overhead_threaded_vs_shard",
        "value": round(matrix["shard_8dev"]["ms_per_round"], 3),
        "unit": "ms_per_round_8dev",
        "ok": ok,
        "environment": "cpu-container-virtual-devices",
        "parity": report,
        "overhead_matrix": matrix,
        "shard_overhead_growth_2to8dev": round(shard_growth, 3),
        "threaded_overhead_growth_2to8dev": round(threaded_growth, 3),
        "echo_8dev": echo,
    }
    emit(out, "MESH_r15.json")

    # --- refreshed flagship number (obs off, r5-floor methodology;
    # scaled to fib(16) on CPU containers — the real flagship geometry
    # needs TPU hardware, and the artifact records the actual n) ---
    os.environ["BENCH_OBS"] = "off"
    if jax.default_backend() == "cpu":
        os.environ.setdefault("BENCH_FIB_N", "16")
        global FIB_N
        FIB_N = int(os.environ["BENCH_FIB_N"])
    main()
    return 0 if ok else 1


def trace_smoke() -> int:
    """`bench.py --trace-smoke`: run echo x64 with the flight recorder
    on and validate the emitted Chrome trace_event JSON against the
    schema (obs/trace.py validate_chrome_trace) — the CI guard that the
    observability pipeline stays wired end-to-end.  Prints ONE JSON
    line; no artifact emission."""
    import io
    import json as _json
    import os

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.obs.trace import export_chrome_trace, \
        validate_chrome_trace

    lanes, iters = 64, 2
    conf = Configure()
    conf.batch.tier0_hostcalls = False  # exercise the tier-1 drain path
    eng, sink = _smoke_echo_engine(conf, lanes)
    t0 = time.perf_counter()
    res = eng.run("echo", [np.full(lanes, iters, np.int64)],
                  max_steps=1_000_000)
    dt = time.perf_counter() - t0
    os.close(sink)
    buf = io.StringIO()
    obj = export_chrome_trace(eng.obs, buf)
    _json.loads(buf.getvalue())  # emitted bytes are real JSON
    problems = validate_chrome_trace(obj)
    names = eng.obs.event_names()
    checks = {
        "completed": bool(res.completed.all()),
        "schema_ok": not problems,
        "has_launch_span": "launch" in names,
        "has_serve_span": "serve" in names,
        "has_occupancy_counter": "live_lanes" in names,
        "has_drain_histogram": "fd_write" in eng.obs.hostcalls,
    }
    ok = all(checks.values())
    print(json.dumps({
        "metric": "trace_smoke_echo_schema",
        "value": 1 if ok else 0,
        "unit": "valid",
        "ok": ok,
        **checks,
        "problems": problems[:5],
        "events": len(eng.obs.events),
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def analyze_smoke() -> int:
    """`bench.py --analyze-smoke`: the static-analyzer CI guard.

    1. Analyze the echo + fib bench fixtures; every report must
       validate against the wasmedge-tpu/analysis/v1 schema, with the
       expected verdicts (both unbounded: echo loops, fib recurses).
    2. Soundness against a REAL run: per-invocation static cost bound
       >= the engine's measured retired instructions — trivially for
       the unbounded fixtures (bound = +inf), and meaningfully for a
       bounded straight-line/call fixture whose finite bound must
       dominate the measured count.
    3. A policy-enabled gateway must reject a crafted unbounded-loop
       module at POST /v1/modules with the structured
       StaticPolicyViolation taxonomy (HTTP 400 + violations list),
       while admitting a bounded module.
    4. r19 absint precision: the counted-loop fixture (verdict
       "unbounded" before the abstract interpreter) must report a
       finite bound proven >= the real BatchEngine retired max, and
       the gateway — now under `require_bounded` — must ADMIT it
       while still 400-ing the genuinely unbounded module.

    Prints ONE JSON line; emits no benchmark artifact."""
    import bench_echo
    from wasmedge_tpu.analysis import analyze_validated, validate_report
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.gateway import GatewayTenants
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_counted_loop, build_fib
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.builder import ModuleBuilder
    from wasmedge_tpu.validator import Validator

    t0 = time.perf_counter()
    checks = {}

    def analyzed(data):
        conf = Configure()
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
        return mod, analyze_validated(mod)

    # 1. fixtures analyze + schema-validate with the expected verdicts
    _, a_echo = analyzed(bench_echo.build_module())
    _, a_fib = analyzed(build_fib())
    checks["echo_schema_ok"] = not validate_report(a_echo.to_dict())
    checks["fib_schema_ok"] = not validate_report(a_fib.to_dict())
    checks["echo_unbounded_loop"] = a_echo.cost_bound is None \
        and any(f.has_loop for f in a_echo.funcs)
    checks["fib_unbounded_recursion"] = a_fib.cost_bound is None \
        and any(f.recursive for f in a_fib.funcs)
    checks["echo_tier0_fd_write"] = a_echo.tier0_sites == 2 \
        and a_echo.drain_sites == 0

    # 2. soundness vs a real run.  The unbounded fixtures satisfy the
    # bound as +inf; the bounded fixture pins the finite case.
    def bound_of(a):
        return float("inf") if a.cost_bound is None else a.cost_bound

    b = ModuleBuilder()
    leaf = b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 3), "i32.mul"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 2), "i32.lt_s",
        ("if", "i32"),
        ("local.get", 0), ("call", leaf),
        "else",
        ("local.get", 0), ("i32.const", 5), "i32.add", ("call", leaf),
        "end",
    ], export="f")
    bounded_wasm = b.build()
    mod_b, a_bounded = analyzed(bounded_wasm)
    checks["bounded_schema_ok"] = not validate_report(
        a_bounded.to_dict())
    conf = Configure()
    conf.batch.steps_per_launch = 64
    conf.batch.value_stack_depth = 32
    conf.batch.call_stack_depth = 8
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod_b)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=4)
    res = eng.run("f", [np.array([0, 1, 5, 9], np.int64)],
                  max_steps=10_000)
    checks["bounded_run_completed"] = bool(res.completed.all())
    checks["bound_ge_retired"] = a_bounded.cost_bound is not None \
        and a_bounded.cost_bound >= int(res.retired.max())
    checks["image_carries_analysis"] = \
        getattr(eng.img, "analysis", None) is not None \
        and eng.img.analysis.cost_bound == a_bounded.cost_bound

    # fib under the engine too: bound_of(+inf) >= anything, but the run
    # proves the fixtures the analyzer vetted are the ones that execute
    conf_f = Configure()
    conf_f.batch.steps_per_launch = 4096
    conf_f.batch.value_stack_depth = 128
    conf_f.batch.call_stack_depth = 64
    mod_f = Validator(conf_f).validate(
        Loader(conf_f).parse_module(build_fib()))
    store_f = StoreManager()
    inst_f = Executor(conf_f).instantiate(store_f, mod_f)
    eng_f = BatchEngine(inst_f, store=store_f, conf=conf_f, lanes=4)
    res_f = eng_f.run("fib", [np.full(4, 10, np.int64)],
                      max_steps=1_000_000)
    checks["fib_bound_ge_retired"] = bool(res_f.completed.all()) \
        and bound_of(a_fib) >= int(res_f.retired.max())

    # 4. r19 counted-loop precision: unbounded -> finite sound bound
    counted_wasm = build_counted_loop(64)
    mod_c, a_counted = analyzed(counted_wasm)
    checks["counted_schema_ok"] = not validate_report(
        a_counted.to_dict())
    checks["counted_loop_now_bounded"] = a_counted.bounded \
        and a_counted.funcs[0].has_loop \
        and a_counted.cost_bound is not None
    conf_c = Configure()
    conf_c.batch.steps_per_launch = 256
    conf_c.batch.value_stack_depth = 32
    conf_c.batch.call_stack_depth = 8
    store_c = StoreManager()
    inst_c = Executor(conf_c).instantiate(store_c, mod_c)
    eng_c = BatchEngine(inst_c, store=store_c, conf=conf_c, lanes=4)
    res_c = eng_c.run("count", [np.zeros(4, np.int64)],
                      max_steps=50_000)
    checks["counted_bound_ge_retired"] = bool(
        res_c.completed.all()) and a_counted.cost_bound is not None \
        and a_counted.cost_bound >= int(res_c.retired.max())

    # 3. policy-enabled gateway rejects the crafted unbounded module
    # (now under require_bounded too — the r19 admission-precision
    # policy a pre-absint analyzer would have rejected EVERY loop for)
    bldr = ModuleBuilder()
    bldr.add_function(["i32"], ["i32"], [], [
        ("block", None), ("loop", None), ("br", 0), "end", "end",
        ("local.get", 0)], export="spin")
    unbounded_wasm = bldr.build()
    conf_g = Configure()
    conf_g.batch.steps_per_launch = 128
    tenants = GatewayTenants.from_dict(
        {"analysis": {"max_static_cost": 1_000_000,
                      "max_memory_pages": 16,
                      "require_bounded": True}})
    gw, svc = _start_gateway(conf_g, lanes=2, tenants=tenants)
    try:
        st, doc, _ = _gateway_rpc(
            gw.host, gw.port, "POST", "/v1/modules?name=spin",
            body=unbounded_wasm,
            headers={"Content-Type": "application/wasm"})
        checks["gateway_rejects_unbounded"] = (
            st == 400 and isinstance(doc, dict)
            and doc.get("err", {}).get("name") == "StaticPolicyViolation"
            and any(v.get("limit") == "max_static_cost"
                    for v in doc.get("err", {}).get("violations", [])))
        st, doc, _ = _gateway_rpc(
            gw.host, gw.port, "POST", "/v1/modules?name=ok",
            body=bounded_wasm,
            headers={"Content-Type": "application/wasm"})
        checks["gateway_admits_bounded"] = st == 201 \
            and isinstance(doc, dict) \
            and doc.get("analysis", {}).get("bounded") is True
        # the COUNTED-LOOP module: pre-absint this was "unbounded" and
        # require_bounded would 400 it; now it must ADMIT
        st, doc, _ = _gateway_rpc(
            gw.host, gw.port, "POST", "/v1/modules?name=counted",
            body=counted_wasm,
            headers={"Content-Type": "application/wasm"})
        checks["gateway_admits_counted_loop"] = st == 201 \
            and isinstance(doc, dict) \
            and doc.get("analysis", {}).get("bounded") is True \
            and doc.get("analysis", {}).get("trip_bounded_loops",
                                            0) >= 1
        st, text, _ = _gateway_rpc(gw.host, gw.port, "GET", "/metrics")
        checks["metrics_has_analysis_counters"] = st == 200 \
            and "wasmedge_analysis_policy_rejections_total 1" in text
    finally:
        gw.shutdown(drain=True, timeout_s=60.0)
    dt = time.perf_counter() - t0
    ok = all(checks.values())
    print(json.dumps({
        "metric": "analyze_smoke_static_soundness",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "bounded_cost_bound": a_bounded.cost_bound,
        "bounded_retired_max": int(res.retired.max()),
        "counted_cost_bound": a_counted.cost_bound,
        "counted_retired_max": int(res_c.retired.max()),
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def _fuse_fib_engine(fuse: bool, lanes: int, obs: bool = False):
    """SIMT (BatchEngine) flagship rig at the standard bench geometry
    with the superinstruction-fusion knob pinned — the tier the shard
    drive, the serving layer, and hv oversubscription execute."""
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure

    conf = Configure()
    conf.batch.fuse_superinstructions = fuse
    conf.batch.steps_per_launch = 50_000_000
    conf.batch.value_stack_depth = 256
    conf.batch.call_stack_depth = 256
    conf.obs.enabled = obs
    inst, store = _instantiate_fib(conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def _fuse_echo_engine(conf, lanes, sink_path):
    """Echo engine with fd 1 sunk to a FILE (not /dev/null) so the
    fusion smoke can compare the two runs' stdout byte streams."""
    import os

    import bench_echo
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf.batch.steps_per_launch = 100
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    sink = os.open(sink_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(
        Loader(conf).parse_module(bench_echo.build_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes), sink


def _emit_fusion_report(rep: dict, default_path: str):
    """Write a full realized-fusion report as a sibling artifact file
    (no stdout line — the driver parses exactly one JSON line per
    bench).  Honors the BENCH_ARTIFACT redirects."""
    from wasmedge_tpu.utils.bench_artifact import artifact_path

    path = artifact_path(default_path)
    if path is None:
        return
    try:
        with open(path, "w") as f:
            f.write(json.dumps(rep, indent=2, sort_keys=True,
                               default=int) + "\n")
    except OSError:
        pass


def _compact_fib_engine(compact: bool, lanes: int, chunk: int,
                        forced: bool = False):
    """SIMT flagship rig with the lane-compaction knob pinned (fusion
    stays at its default on both sides — the A/B isolates compaction).
    `forced` pins the policy fully open (smoke geometry: tiny mixes
    would not clear the production cost model)."""
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure

    conf = Configure()
    conf.batch.compact = compact
    conf.batch.steps_per_launch = chunk
    conf.batch.value_stack_depth = 256
    conf.batch.call_stack_depth = 256
    if forced:
        conf.batch.compact_min_interval = 1
        conf.batch.compact_trigger = 0.0
        conf.batch.compact_cost_factor = 0.0
        conf.batch.compact_width_floor = 8
    inst, store = _instantiate_fib(conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def compact_smoke() -> int:
    """`bench.py --compact-smoke`: the lane-compaction CI guard.
    Divergent fib mix with compaction on vs off at identical geometry:
    results bit-identical, >= 1 compaction fired, and strictly fewer
    dispatch slots (steps x dispatch width) when on — i.e. more
    retired instructions per dispatch.  Prints ONE JSON line; emits no
    artifact (correctness guard, not a throughput claim)."""
    t0 = time.perf_counter()
    lanes = 32
    ns = (4 + np.arange(lanes, dtype=np.int64) % 9)
    np.random.default_rng(7).shuffle(ns)
    expect = np.asarray([_fib(int(n)) for n in ns], np.int64)
    res = {}
    stats = None
    for compact in (True, False):
        eng = _compact_fib_engine(compact, lanes, chunk=64, forced=True)
        res[compact] = eng.run("fib", [ns], max_steps=5_000_000)
        if compact:
            stats = dict(eng.compactor.stats)
    a, b = res[True], res[False]
    slots_on = int(stats["dispatch_slots"])
    slots_off = int(b.steps) * lanes
    checks = {
        "correct": bool(a.completed.all()
                        and (np.asarray(a.results[0]) == expect).all()),
        "bit_identical": bool(
            (a.results[0] == b.results[0]).all()
            and (a.trap == b.trap).all()
            and (a.retired == b.retired).all()),
        "compactions_fired": int(stats["fires"]) >= 1,
        "fewer_dispatch_slots": slots_on < slots_off,
    }
    ok = all(checks.values())
    print(json.dumps({
        "metric": "compact_smoke_bit_identity",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "fires": int(stats["fires"]),
        "dispatch_slots_on": slots_on,
        "dispatch_slots_off": slots_off,
        "min_width": int(stats["min_width"]),
        "lanes": lanes,
        "wall_s": round(time.perf_counter() - t0, 3),
    }))
    return 0 if ok else 1


def compact_bench() -> int:
    """`bench.py --compact-bench`: obs-off divergent-mix A/B — lane
    compaction on vs off at identical geometry on the SIMT tier
    (fusion at its default both sides) — plus the flagship
    (already-convergent) guard proving the trigger never regresses a
    convergent workload.  Emits BENCH_r18.json and the realized-fusion
    sibling BENCH_r18.fusion.json.  Geometry scales via BENCH_DIV_* /
    BENCH_FUSE_FIB_N / BENCH_FUSE_LANES / BENCH_COMPACT_CHUNK; the
    metric names record the actual geometry."""
    import os

    import jax

    fib_n = int(os.environ.get("BENCH_FUSE_FIB_N", "15"))
    lanes = int(os.environ.get("BENCH_FUSE_LANES", "4096"))
    div_lanes = int(os.environ.get("BENCH_DIV_LANES", str(lanes)))
    div_lo = int(os.environ.get("BENCH_DIV_LO", "8"))
    div_hi = int(os.environ.get("BENCH_DIV_HI", "14"))
    chunk = int(os.environ.get("BENCH_COMPACT_CHUNK", "2048"))
    out = {"metric": f"compact_ab_fib{div_lo}to{div_hi}_x{div_lanes}",
           "unit": "wasm_instr/s", "backend": jax.default_backend(),
           "obs": False, "div_lanes": div_lanes, "chunk": chunk,
           "fib_n": fib_n, "lanes": lanes}

    # ---- divergent mix A/B: compaction on vs off ----
    ns = div_lo + (np.arange(div_lanes, dtype=np.int64)
                   % (div_hi - div_lo + 1))
    np.random.default_rng(42).shuffle(ns)
    expect = np.asarray([_fib(int(n)) for n in ns], np.int64)
    div = {}
    results = {}
    stats = None
    for compact in (True, False):
        eng = _compact_fib_engine(compact, div_lanes, chunk)
        # warmup runs the FULL mix once: the divergent live-count
        # trajectory is what triggers the narrowed-width variants, so
        # a shrunken warmup would leave their compiles inside the
        # timed region (both arms get the identical warmup)
        eng.run("fib", [ns], max_steps=2_000_000_000)
        t0 = time.perf_counter()
        res = eng.run("fib", [ns], max_steps=2_000_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and \
            (np.asarray(res.results[0], np.int64) == expect).all(), \
            "divergent wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        results[compact] = res
        key = "compact" if compact else "baseline"
        if compact:
            stats = dict(eng.compactor.stats)
            slots = int(stats["dispatch_slots"])
        else:
            slots = int(res.steps) * div_lanes
        div[key] = {
            "ops_per_sec": round(retired / dt, 1),
            "wall_s": round(dt, 2), "steps": int(res.steps),
            "dispatch_slots": slots,
            "retired_per_dispatch_slot": round(retired / max(slots, 1),
                                               4),
        }
        if compact:
            div[key]["compactions"] = int(stats["fires"])
            div[key]["min_width"] = int(stats["min_width"])
            rep = eng.img.fusion_report or {}
            _emit_fusion_report(rep, "BENCH_r18.fusion.json")
            out["realized_fusion"] = {
                "patterns": rep.get("patterns", 0),
                "fused_runs": rep.get("fused_runs", 0),
                "fused_cells": rep.get("fused_cells", 0),
            }
    a, b = results[True], results[False]
    div["bit_identical"] = bool(
        (a.results[0] == b.results[0]).all()
        and (a.trap == b.trap).all() and (a.retired == b.retired).all())
    div["speedup"] = round(div["compact"]["ops_per_sec"]
                           / max(div["baseline"]["ops_per_sec"], 1e-9),
                           4)
    out["divergent_mix"] = div
    out["value"] = div["compact"]["ops_per_sec"]
    out["speedup"] = div["speedup"]

    # ---- flagship guard: convergent workload, trigger must not fire
    # into a regression ----
    flag = {}
    expected = _fib(fib_n)
    for compact in (True, False):
        eng = _compact_fib_engine(compact, lanes, chunk)
        eng.run("fib", [np.full(lanes, WARMUP_N, np.int64)],
                max_steps=10_000_000)
        t0 = time.perf_counter()
        res = eng.run("fib", [np.full(lanes, fib_n, np.int64)],
                      max_steps=500_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and \
            (res.results[0] == expected).all(), "flagship wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        key = "compact" if compact else "baseline"
        flag[key] = {"ops_per_sec": round(retired / dt, 1),
                     "wall_s": round(dt, 2)}
        if compact:
            flag["compactions"] = int(eng.compactor.stats["fires"])
    flag["ratio"] = round(flag["compact"]["ops_per_sec"]
                          / max(flag["baseline"]["ops_per_sec"], 1e-9),
                          4)
    flag["metric"] = f"flagship_fib{fib_n}_x{lanes}_compact_guard"
    out["flagship_guard"] = flag

    ok = (div["speedup"] > 1.0 and div["bit_identical"]
          and div["compact"]["retired_per_dispatch_slot"]
          > div["baseline"]["retired_per_dispatch_slot"]
          and div["compact"]["compactions"] >= 1
          and flag["ratio"] >= 0.95)
    out["ok"] = bool(ok)
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "BENCH_r18.json")
    print(f"# divergent speedup={div['speedup']} "
          f"slots {div['compact']['dispatch_slots']} vs "
          f"{div['baseline']['dispatch_slots']} "
          f"compactions={div['compact']['compactions']} "
          f"min_width={div['compact']['min_width']} "
          f"flagship_ratio={flag['ratio']}", file=sys.stderr)
    return 0 if ok else 1


def fuse_smoke() -> int:
    """`bench.py --fuse-smoke`: the superinstruction-fusion CI guard.
    Asserts (a) the translation pass realizes fused cells on the
    flagship fib image, and (b) fusion on/off is bit-identical on echo
    (WASI/hostcall path, including the stdout byte stream) and fib
    (compute path) at identical geometry, with fewer dispatches when
    on.  Prints ONE JSON line; emits no artifact (this mode checks
    correctness, not throughput)."""
    import os
    import tempfile

    from wasmedge_tpu.common.configure import Configure

    t0 = time.perf_counter()
    lanes = 32
    checks = {}
    # -- fib (pure compute) --
    fib_res = {}
    fused_report = None
    for fuse in (True, False):
        eng = _fuse_fib_engine(fuse, lanes)
        fib_res[fuse] = eng.run("fib", [np.full(lanes, 12, np.int64)],
                                max_steps=5_000_000)
        if fuse:
            # planning is deferred to the first build — read after run
            fused_report = eng.img.fusion_report
    a, b = fib_res[True], fib_res[False]
    checks["fib_realized_runs"] = (fused_report or {}).get(
        "fused_runs", 0) > 0
    checks["fib_bit_identical"] = bool(
        (a.results[0] == b.results[0]).all()
        and (a.trap == b.trap).all() and (a.retired == b.retired).all())
    checks["fib_fewer_dispatches"] = a.steps < b.steps
    # -- echo (hostcall + tier-0 stdout path) --
    echo = {}
    with tempfile.TemporaryDirectory(prefix="fuse-smoke-") as d:
        for fuse in (True, False):
            conf = Configure()
            conf.batch.fuse_superinstructions = fuse
            path = os.path.join(d, f"out-{fuse}")
            eng, sink = _fuse_echo_engine(conf, lanes, path)
            res = eng.run("echo", [np.full(lanes, 2, np.int64)],
                          max_steps=1_000_000)
            os.close(sink)
            echo[fuse] = (res, open(path, "rb").read())
        ra, sa = echo[True]
        rb, sb = echo[False]
        checks["echo_completed"] = bool(ra.completed.all()
                                        and rb.completed.all())
        checks["echo_bit_identical"] = bool(
            (ra.results[0] == rb.results[0]).all()
            and (ra.trap == rb.trap).all()
            and (ra.retired == rb.retired).all())
        checks["echo_stdout_identical"] = sa == sb and len(sa) > 0
    dt = time.perf_counter() - t0
    ok = all(checks.values())
    print(json.dumps({
        "metric": "fuse_smoke_bit_identity",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "fib_steps_fused": int(a.steps),
        "fib_steps_unfused": int(b.steps),
        "fused_runs": (fused_report or {}).get("fused_runs", 0),
        "fused_patterns": (fused_report or {}).get("patterns", 0),
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def fuse_bench() -> int:
    """`bench.py --fuse-bench`: obs-off flagship A/B — the SIMT chunk
    tier with superinstruction fusion on vs off at identical geometry —
    plus re-measured divergent-mix and multi-tenant floors under the
    new default (fusion on).  Emits BENCH_r17.json.  Workload sizes are
    CPU-container-scaled via env (BENCH_FUSE_FIB_N / BENCH_FUSE_LANES /
    BENCH_FUSE_DIV_LO / BENCH_FUSE_DIV_HI); the metric names record the
    actual geometry so a scaled number can never be mistaken for the
    TPU floor."""
    import os

    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.multitenant import (
        MultiTenantBatchEngine, Tenant)
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import (
        build_coremark_kernel, build_fac, build_fib, build_loop_sum)
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    fib_n = int(os.environ.get("BENCH_FUSE_FIB_N", "15"))
    lanes = int(os.environ.get("BENCH_FUSE_LANES", "4096"))
    # the divergent phase scales independently of the flagship (r18:
    # BENCH_DIV_*; the old BENCH_FUSE_DIV_* names stay as fallbacks,
    # and BENCH_DIV_LANES defaults to the flagship width)
    div_lanes = int(os.environ.get("BENCH_DIV_LANES", str(lanes)))
    div_lo = int(os.environ.get(
        "BENCH_DIV_LO", os.environ.get("BENCH_FUSE_DIV_LO", "8")))
    div_hi = int(os.environ.get(
        "BENCH_DIV_HI", os.environ.get("BENCH_FUSE_DIV_HI", "14")))
    import jax

    out = {"metric": f"fusion_ab_fib{fib_n}_x{lanes}",
           "unit": "wasm_instr/s", "backend": jax.default_backend(),
           "obs": False, "lanes": lanes, "fib_n": fib_n}
    expected = _fib(fib_n)

    # ---- flagship A/B: SIMT tier, fusion on vs off ----
    flagship = {}
    for fuse in (True, False):
        eng = _fuse_fib_engine(fuse, lanes)
        eng.run("fib", [np.full(lanes, WARMUP_N, np.int64)],
                max_steps=10_000_000)  # compile
        t0 = time.perf_counter()
        res = eng.run("fib", [np.full(lanes, fib_n, np.int64)],
                      max_steps=500_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and \
            (res.results[0] == expected).all(), "flagship wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        key = "fused" if fuse else "unfused"
        flagship[key] = {
            "ops_per_sec": round(retired / dt, 1),
            "steps": int(res.steps), "wall_s": round(dt, 2),
            "retired": retired,
        }
        if fuse:
            rep = eng.img.fusion_report
            flagship["fused"]["fused_runs"] = rep.get("fused_runs")
            flagship["fused"]["patterns"] = rep.get("patterns")
    flagship["speedup"] = round(
        flagship["fused"]["ops_per_sec"]
        / max(flagship["unfused"]["ops_per_sec"], 1e-9), 4)
    flagship["dispatch_reduction"] = round(
        1.0 - flagship["fused"]["steps"]
        / max(flagship["unfused"]["steps"], 1), 4)
    out["flagship_simt"] = flagship
    out["value"] = flagship["fused"]["ops_per_sec"]
    out["speedup"] = flagship["speedup"]

    def _inst_of(conf, data):
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
        store = StoreManager()
        return Executor(conf).instantiate(store, mod), store

    # ---- divergent mix (floor re-measure, fusion on vs off) ----
    div = {}
    ns = div_lo + (np.arange(div_lanes, dtype=np.int64)
                   % (div_hi - div_lo + 1))
    np.random.default_rng(42).shuffle(ns)
    expect = np.asarray([_fib(int(n)) for n in ns], np.int64)
    for fuse in (True, False):
        conf = Configure()
        conf.batch.fuse_superinstructions = fuse
        conf.batch.steps_per_launch = 50_000_000
        conf.batch.value_stack_depth = 256
        conf.batch.call_stack_depth = 256
        inst, store = _inst_of(conf, build_fib())
        eng = UniformBatchEngine(inst, store=store, conf=conf,
                                 lanes=div_lanes)
        eng.run("fib", [np.maximum(ns - 6, 1)], max_steps=50_000_000)
        t0 = time.perf_counter()
        res = eng.run("fib", [ns], max_steps=2_000_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and \
            (np.asarray(res.results[0], np.int64) == expect).all(), \
            "divergent wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        div["fused" if fuse else "unfused"] = {
            "ops_per_sec": round(retired / dt, 1),
            "wall_s": round(dt, 2)}
        if fuse:
            # the realized-fusion report is the block-selection input
            # ROADMAP #2's kernel-tier follow-on consumes: record it
            # alongside the artifact (trimmed into the JSON, full
            # report as a sibling file below)
            rep = eng.simt.img.fusion_report or {}
            div["realized_fusion"] = {
                "patterns": rep.get("patterns", 0),
                "fused_runs": rep.get("fused_runs", 0),
                "fused_cells": rep.get("fused_cells", 0),
                "candidates": rep.get("candidates", []),
            }
            _emit_fusion_report(rep, "BENCH_r17.fusion.json")
    div["speedup"] = round(div["fused"]["ops_per_sec"]
                           / max(div["unfused"]["ops_per_sec"], 1e-9), 4)
    div["metric"] = f"divergent_fib{div_lo}to{div_hi}_x{div_lanes}"
    out["divergent_mix"] = div

    # ---- multi-tenant mix (floor re-measure, fusion on vs off) ----
    mt_out = {}
    L = max(lanes // 4, 1)
    specs = [
        (build_fib(), "fib", [np.full(L, 13, np.int64)]),
        (build_fac(), "fac", [np.full(L, 12, np.int64)]),
        (build_loop_sum(), "loop_sum", [np.full(L, 1200, np.int64)]),
        (build_coremark_kernel(), "coremark",
         [np.full(L, 4096, np.int64)]),
    ]
    results_by_knob = {}
    for fuse in (True, False):
        conf = Configure()
        conf.batch.fuse_superinstructions = fuse
        conf.batch.steps_per_launch = 50_000_000
        conf.batch.value_stack_depth = 256
        conf.batch.call_stack_depth = 256
        tenants = []
        for data, fn, args in specs:
            inst, store = _inst_of(conf, data)
            tenants.append(Tenant(
                engine=BatchEngine(inst, store=store, conf=conf,
                                   lanes=L),
                func_name=fn, args_lanes=args, lanes=L))
        mt = MultiTenantBatchEngine(tenants, conf=conf)
        mt.run_tenants(max_steps=2000)  # compile
        mt2 = MultiTenantBatchEngine(tenants, conf=conf)
        t0 = time.perf_counter()
        res = mt2.run_tenants(max_steps=4_000_000_000)
        dt = time.perf_counter() - t0
        assert all(r.completed.all() for r in res), "multitenant traps"
        retired = float(sum(np.asarray(r.retired, np.float64).sum()
                            for r in res))
        results_by_knob[fuse] = res
        mt_out["fused" if fuse else "unfused"] = {
            "ops_per_sec": round(retired / dt, 1),
            "wall_s": round(dt, 2)}
    mt_out["bit_identical"] = bool(all(
        (a.results[0] == b.results[0]).all() and (a.trap == b.trap).all()
        and (a.retired == b.retired).all()
        for a, b in zip(results_by_knob[True], results_by_knob[False])))
    mt_out["speedup"] = round(
        mt_out["fused"]["ops_per_sec"]
        / max(mt_out["unfused"]["ops_per_sec"], 1e-9), 4)
    mt_out["metric"] = f"multitenant_mix4_x{4 * L}"
    out["multitenant"] = mt_out

    ok = flagship["speedup"] > 1.0 and mt_out["bit_identical"]
    out["ok"] = bool(ok)
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "BENCH_r17.json")
    print(f"# flagship speedup={flagship['speedup']} "
          f"dispatch_reduction={flagship['dispatch_reduction']} "
          f"divergent speedup={div['speedup']} "
          f"multitenant speedup={mt_out['speedup']}", file=sys.stderr)
    return 0 if ok else 1


def _memfuse_engine(memfuse: bool, lanes: int, data: bytes,
                    chunk: int = 50_000_000):
    """SIMT rig with the r19 memory-run fusion knob pinned (the pure
    superinstruction tier stays at its default on BOTH sides — the
    A/B isolates the licensed load/store run class)."""
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.fuse_memory_runs = memfuse
    conf.batch.steps_per_launch = chunk
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def _memfuse_checksum(n_words: int, passes: int) -> int:
    """Independent numpy oracle for build_memfuse_workload — the SAME
    store pattern as bench_memory's workload, so the one oracle
    serves both (u32 domain)."""
    from bench_memory import expected_checksum

    return expected_checksum(n_words, passes)


def memfuse_smoke() -> int:
    """`bench.py --memfuse-smoke`: the r19 memory-run fusion CI guard.
    Licensed workload: fusion on/off bit-identical with strictly
    fewer dispatches and realized memory runs.  Adversarial fixtures:
    a misaligned store/load mix and an OOB-adjacent loop must REVERT
    to the per-op path (license refused) — bit-identical results and,
    for the OOB fixture, the identical MemoryOutOfBounds trap at the
    identical retired count.  Prints ONE JSON line; no artifact."""
    from wasmedge_tpu.common.errors import ErrCode
    from wasmedge_tpu.models import build_memfuse_workload

    t0 = time.perf_counter()
    lanes = 16
    checks = {}

    def ab(data, chunk=256, max_steps=500_000):
        out = {}
        rep = None
        for memfuse in (True, False):
            eng = _memfuse_engine(memfuse, lanes, data, chunk=chunk)
            out[memfuse] = eng.run(
                "memfuse", [np.zeros(lanes, np.int64)],
                max_steps=max_steps)
            if memfuse:
                rep = eng.img.fusion_report["memory"]
        a, b = out[True], out[False]
        ident = bool((a.results[0] == b.results[0]).all()
                     and (a.trap == b.trap).all()
                     and (a.retired == b.retired).all())
        return a, b, rep, ident

    # -- licensed workload --
    a, b, rep, ident = ab(build_memfuse_workload(96, passes=2))
    checks["licensed_runs_realized"] = rep["mem_runs"] > 0 \
        and rep["licensed_sites"] == 2
    checks["licensed_bit_identical"] = ident and bool(
        a.completed.all())
    checks["licensed_fewer_dispatches"] = a.steps < b.steps
    checks["licensed_correct"] = bool(
        (np.asarray(a.results[0], np.int64) & 0xFFFFFFFF
         == _memfuse_checksum(96, 2)).all())

    # -- misaligned: license refused, per-op both sides --
    a, b, rep, ident = ab(build_memfuse_workload(64, byte_offset=2))
    checks["misaligned_reverted"] = rep["mem_runs"] == 0 \
        and rep["unlicensed_sites"] == 2
    checks["misaligned_bit_identical"] = ident and bool(
        a.completed.all())

    # -- OOB-adjacent: refused, traps identically --
    a, b, rep, ident = ab(build_memfuse_workload(
        64, byte_offset=65400))
    checks["oob_reverted"] = rep["mem_runs"] == 0
    checks["oob_trap_identical"] = ident and bool(
        (np.asarray(a.trap)
         == int(ErrCode.MemoryOutOfBounds)).all())

    dt = time.perf_counter() - t0
    ok = all(checks.values())
    print(json.dumps({
        "metric": "memfuse_smoke_bit_identity",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def memfuse_bench() -> int:
    """`bench.py --memfuse-bench`: obs-off memory-workload A/B — the
    SIMT tier with r19 memory-run fusion on vs off at identical
    geometry (the pure superinstruction tier at its default on both
    sides).  Emits BENCH_r19.json; ok requires fusion-on strictly
    faster with strictly fewer dispatches and bit-identical results.
    Geometry scales via BENCH_MEMFUSE_WORDS / BENCH_MEMFUSE_PASSES /
    BENCH_FUSE_LANES; the metric name records the actual geometry."""
    import os

    import jax

    from wasmedge_tpu.models import build_memfuse_workload

    n_words = int(os.environ.get("BENCH_MEMFUSE_WORDS", "512"))
    passes = int(os.environ.get("BENCH_MEMFUSE_PASSES", "2"))
    lanes = int(os.environ.get("BENCH_FUSE_LANES", "4096"))
    data = build_memfuse_workload(n_words, passes=passes)
    expect = _memfuse_checksum(n_words, passes)
    out = {
        "metric": f"memfuse_ab_{n_words}wx{passes}p_x{lanes}",
        "unit": "wasm_instr/s",
        "backend": jax.default_backend(),
        "obs": False,
        "n_words": n_words, "passes": passes, "lanes": lanes,
    }
    results = {}
    ab = {}
    for memfuse in (True, False):
        eng = _memfuse_engine(memfuse, lanes, data)
        # warmup compiles the step (single chunk covers the full run)
        eng.run("memfuse", [np.zeros(lanes, np.int64)],
                max_steps=2_000_000_000)
        t0 = time.perf_counter()
        res = eng.run("memfuse", [np.zeros(lanes, np.int64)],
                      max_steps=2_000_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and (
            np.asarray(res.results[0], np.int64) & 0xFFFFFFFF
            == expect).all(), "memfuse wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        results[memfuse] = res
        key = "memfuse" if memfuse else "baseline"
        ab[key] = {
            "ops_per_sec": round(retired / dt, 1),
            "wall_s": round(dt, 2),
            "dispatches": int(res.steps),
        }
        if memfuse:
            rep = eng.img.fusion_report
            out["realized"] = {
                "mem_runs": rep["memory"]["mem_runs"],
                "mem_cells": rep["memory"]["mem_cells"],
                "mem_patterns": rep["memory"]["mem_patterns"],
                "licensed_sites": rep["memory"]["licensed_sites"],
            }
            _emit_fusion_report(rep, "BENCH_r19.fusion.json")
    a, b = results[True], results[False]
    ab["bit_identical"] = bool(
        (a.results[0] == b.results[0]).all()
        and (a.trap == b.trap).all()
        and (a.retired == b.retired).all())
    ab["speedup"] = round(ab["memfuse"]["ops_per_sec"]
                          / max(ab["baseline"]["ops_per_sec"], 1e-9),
                          4)
    ab["dispatch_reduction"] = round(
        1.0 - ab["memfuse"]["dispatches"]
        / max(ab["baseline"]["dispatches"], 1), 4)
    out["memory_workload"] = ab
    out["value"] = ab["memfuse"]["ops_per_sec"]
    out["speedup"] = ab["speedup"]
    ok = (ab["speedup"] > 1.0 and ab["bit_identical"]
          and ab["memfuse"]["dispatches"] < ab["baseline"]["dispatches"]
          and out["realized"]["mem_runs"] > 0)
    out["ok"] = bool(ok)
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "BENCH_r19.json")
    print(f"# memfuse speedup={ab['speedup']} dispatches "
          f"{ab['memfuse']['dispatches']} vs "
          f"{ab['baseline']['dispatches']} "
          f"mem_runs={out['realized']['mem_runs']}", file=sys.stderr)
    return 0 if ok else 1


def _tierup_engine(tierup: bool, lanes: int, data: bytes,
                   chunk: int = 50_000_000, obs: bool = False,
                   **batch):
    """SIMT rig with the r20 compiled-function tier knob pinned
    (fusion stays at its default on BOTH sides — the A/B isolates the
    whole-function tier)."""
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.tierup = tierup
    conf.batch.steps_per_launch = chunk
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    if obs:
        conf.obs.enabled = True
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def tierup_smoke() -> int:
    """`bench.py --tierup-smoke`: the r20 compiled-function tier CI
    guard.  The canonical counted loop promotes (device loop under its
    absint trip-bound license) and the driver/leaf call workload runs
    per-call compiled dispatches — both bit-identical to the tier-off
    build with strictly fewer dispatches.  A fuel budget below the
    promoted fuel bound must refuse promotion and land the exhaustion
    trap per-op, bit-identically.  Prints ONE JSON line; no artifact."""
    from wasmedge_tpu.common.errors import ErrCode
    from wasmedge_tpu.models import (build_call_counted_loop,
                                     build_counted_loop)

    t0 = time.perf_counter()
    lanes = 16
    checks = {}

    def ab(data, name, chunk=256, max_steps=2_000_000, **batch):
        out = {}
        rep = None
        for tierup in (True, False):
            eng = _tierup_engine(tierup, lanes, data, chunk=chunk,
                                 **batch)
            out[tierup] = eng.run(name, [np.zeros(lanes, np.int64)],
                                  max_steps=max_steps)
            if tierup:
                rep = eng.img.tierup_report
        a, b = out[True], out[False]
        ident = bool((a.results[0] == b.results[0]).all()
                     and (a.trap == b.trap).all()
                     and (a.retired == b.retired).all())
        return a, b, rep, ident

    # -- canonical counted loop: whole function, one dispatch --
    a, b, rep, ident = ab(build_counted_loop(64), "count")
    promoted = rep["promoted"]
    checks["counted_loop_promoted"] = len(promoted) == 1 \
        and promoted[0]["cost_bound"] == 770
    checks["counted_loop_device_loop"] = bool(
        promoted and promoted[0]["device_loops"] >= 1)
    checks["counted_loop_bit_identical"] = ident and bool(
        a.completed.all())
    checks["counted_loop_fewer_dispatches"] = a.steps < b.steps
    checks["counted_loop_correct"] = bool(
        (np.asarray(a.results[0], np.int64) == 64 * 63 // 2).all())

    # -- driver/leaf: one compiled dispatch per CALL --
    a, b, rep, ident = ab(build_call_counted_loop(32, 16),
                          "call_count")
    checks["call_leaf_only_promoted"] = [
        p["idx"] for p in rep["promoted"]] == [1]
    checks["call_bit_identical"] = ident and bool(a.completed.all())
    checks["call_fewer_dispatches"] = a.steps < b.steps
    checks["call_correct"] = bool(
        (np.asarray(a.results[0], np.int64)
         == 16 * (32 * 31 // 2)).all())

    # -- tight fuel: runtime gate refuses promotion, lands per-op --
    a, b, rep, ident = ab(build_counted_loop(64), "count",
                          fuel_per_launch=300)
    checks["fuel_gate_trap_identical"] = ident and bool(
        (np.asarray(a.trap) == int(ErrCode.CostLimitExceeded)).all())

    dt = time.perf_counter() - t0
    ok = all(checks.values())
    print(json.dumps({
        "metric": "tierup_smoke_bit_identity",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "lanes": lanes,
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def tierup_bench() -> int:
    """`bench.py --tierup-bench`: obs-off A/B — the SIMT tier with the
    r20 compiled-function tier on vs off at identical geometry (fusion
    at its default on both sides).  Emits BENCH_r20.json; ok requires
    tier-on strictly faster with strictly fewer dispatches,
    bit-identical results, >= 1 counted loop promoted as a bounded
    device loop, and the per-function-call dispatch count verified on
    a small obs-on accounting run.  Geometry scales via
    BENCH_TIERUP_N / BENCH_TIERUP_CALLS / BENCH_TIERUP_LANES."""
    import os

    import jax

    from wasmedge_tpu.models import build_call_counted_loop

    n = int(os.environ.get("BENCH_TIERUP_N", "64"))
    calls = int(os.environ.get("BENCH_TIERUP_CALLS", "64"))
    lanes = int(os.environ.get("BENCH_TIERUP_LANES", "1024"))
    data = build_call_counted_loop(n, calls)
    expect = calls * (n * (n - 1) // 2)
    out = {
        "metric": f"tierup_ab_call{calls}x{n}_x{lanes}",
        "unit": "wasm_instr/s",
        "backend": jax.default_backend(),
        "obs": False,
        "n": n, "calls": calls, "lanes": lanes,
    }
    results = {}
    ab = {}
    for tierup in (True, False):
        eng = _tierup_engine(tierup, lanes, data)
        # warmup compiles the step (single chunk covers the full run)
        eng.run("call_count", [np.zeros(lanes, np.int64)],
                max_steps=2_000_000_000)
        t0 = time.perf_counter()
        res = eng.run("call_count", [np.zeros(lanes, np.int64)],
                      max_steps=2_000_000_000)
        dt = time.perf_counter() - t0
        assert res.completed.all() and (
            np.asarray(res.results[0], np.int64) == expect).all(), \
            "tierup wrong result"
        retired = float(np.asarray(res.retired, np.float64).sum())
        results[tierup] = res
        key = "tierup" if tierup else "baseline"
        ab[key] = {
            "ops_per_sec": round(retired / dt, 1),
            "wall_s": round(dt, 2),
            "dispatches": int(res.steps),
        }
        if tierup:
            rep = eng.img.tierup_report
            out["realized"] = {
                "promoted": [
                    {"idx": p["idx"], "cost_bound": p["cost_bound"],
                     "fuel_bound": p["fuel_bound"],
                     "device_loops": p["device_loops"]}
                    for p in rep["promoted"]],
                "device_loops": sum(p["device_loops"]
                                    for p in rep["promoted"]),
            }
    a, b = results[True], results[False]
    ab["bit_identical"] = bool(
        (a.results[0] == b.results[0]).all()
        and (a.trap == b.trap).all()
        and (a.retired == b.retired).all())
    ab["speedup"] = round(ab["tierup"]["ops_per_sec"]
                          / max(ab["baseline"]["ops_per_sec"], 1e-9),
                          4)
    ab["dispatch_reduction"] = round(
        1.0 - ab["tierup"]["dispatches"]
        / max(ab["baseline"]["dispatches"], 1), 4)
    out["call_workload"] = ab

    # per-function-call dispatch accounting (small obs-on run: the
    # tu_ctr plane counts one compiled-body dispatch per lane per CALL)
    acc_lanes = 16
    eng = _tierup_engine(True, acc_lanes, data, obs=True)
    res = eng.run("call_count", [np.zeros(acc_lanes, np.int64)],
                  max_steps=2_000_000_000)
    tu = dict(eng.obs.tierup_counts)
    out["accounting"] = {
        "lanes": acc_lanes,
        "calls_per_lane": calls,
        "compiled_dispatches": tu["dispatches"],
        "retired_comp": tu["retired_comp"],
        "retired_total": tu["retired_total"],
        "dispatch_per_call": tu["dispatches"] == acc_lanes * calls,
    }
    out["value"] = ab["tierup"]["ops_per_sec"]
    out["speedup"] = ab["speedup"]
    ok = (ab["speedup"] > 1.0 and ab["bit_identical"]
          and ab["tierup"]["dispatches"] < ab["baseline"]["dispatches"]
          and out["realized"]["device_loops"] >= 1
          and out["accounting"]["dispatch_per_call"]
          and res.completed.all())
    out["ok"] = bool(ok)
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "BENCH_r20.json")
    print(f"# tierup speedup={ab['speedup']} dispatches "
          f"{ab['tierup']['dispatches']} vs "
          f"{ab['baseline']['dispatches']} promoted="
          f"{len(out['realized']['promoted'])}", file=sys.stderr)
    return 0 if ok else 1


def _serve_workload(seed: int, nreq: int, short_n: int, long_n: int,
                    long_every: int):
    """Seeded mixed request stream: mostly short fib(short_n) with a
    long fib(long_n) every `long_every`-th request — the shape where
    drain-and-refill strands capacity behind stragglers."""
    rng = np.random.RandomState(seed)
    args = np.where(np.arange(nreq) % long_every == long_every - 1,
                    long_n, short_n).astype(np.int64)
    # jitter the short requests a little so entry grouping can't make
    # the baseline's batches artificially uniform
    jitter = rng.randint(-2, 3, size=nreq)
    args = np.where(args == short_n,
                    np.clip(args + jitter, 2, short_n + 2), args)
    return args


def serve_bench(smoke: bool = False) -> int:
    """`bench.py --serve`: mixed short/long request stream through the
    continuous-batching BatchServer vs a drain-and-refill baseline
    (same engine, same request order, packed into successive full
    batches).  Reports sustained req/s, p50/p99 latency, and mean lane
    occupancy for both; emits SERVE_r09.json.  `--serve-smoke` is the
    CI guard: a tiny seeded stream, asserts every future resolves and
    at least one lane was recycled, no artifact emission."""
    import os
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.serve import BatchServer
    from wasmedge_tpu.utils.bench_artifact import percentile

    if smoke:
        lanes, nreq = 4, 24
        short_n, long_n, long_every = 8, 12, 6
        chunk = 256
    else:
        lanes = int(os.environ.get("SERVE_LANES", 32))
        nreq = int(os.environ.get("SERVE_REQUESTS", 160))
        short_n, long_n, long_every = 10, 18, 8
        chunk = 2048

    def fresh_conf():
        conf = Configure()
        conf.batch.steps_per_launch = chunk
        conf.batch.value_stack_depth = 128
        conf.batch.call_stack_depth = 64
        conf.obs.enabled = not smoke
        return conf

    args = _serve_workload(seed=0, nreq=nreq, short_n=short_n,
                           long_n=long_n, long_every=long_every)

    # --- continuous batching (lane recycling) ---
    conf = fresh_conf()
    inst, store = _instantiate_fib(conf)
    server = BatchServer(inst, store=store, conf=conf, lanes=lanes)
    t0 = _time.monotonic()
    futures = [server.submit("fib", [int(n)],
                             tenant=f"t{i % 4}")
               for i, n in enumerate(args)]
    server.run_until_idle()
    cont_wall = _time.monotonic() - t0
    cont_lat = sorted(f.t_done - t0 for f in futures
                      if f.t_done is not None)
    c = server.counters
    # occupancy is TRUE utilization on both sides of the comparison:
    # retired instructions / (device steps * lanes).  Lane-held rounds
    # would flatter continuous batching (a lane that retires at step 1
    # of a round still "holds" the round) and the baseline would score
    # ~1.0 by holding every lane to batch drain — a metric artifact,
    # not a recycling win.
    cont_occ = c["retired_instructions"] / max(server.total * lanes, 1)
    cont_ok = all(f.done and f.error is None for f in futures)

    # --- drain-and-refill baseline: same order, full batches, each
    # batch runs to completion before the next is packed ---
    from wasmedge_tpu.batch.engine import BatchEngine

    conf_b = fresh_conf()
    inst_b, store_b = _instantiate_fib(conf_b)
    eng_b = BatchEngine(inst_b, store=store_b, conf=conf_b, lanes=lanes)
    t0 = _time.monotonic()
    base_lat = []
    base_occ_num = base_occ_den = 0.0
    base_results = []
    for off in range(0, nreq, lanes):
        batch = args[off:off + lanes]
        pad = np.concatenate(
            [batch, np.full(lanes - len(batch), int(batch[0]), np.int64)])
        res = eng_b.run("fib", [pad], max_steps=50_000_000)
        done_t = _time.monotonic() - t0
        base_lat.extend([done_t] * len(batch))
        base_results.extend(int(x) for x in res.results[0][:len(batch)])
        base_occ_num += float(res.retired[:len(batch)].sum())
        base_occ_den += float(res.steps) * lanes
    base_wall = _time.monotonic() - t0
    base_lat.sort()
    base_occ = base_occ_num / max(base_occ_den, 1.0)

    cont_results = [f.result(0)[0] if f.error is None else None
                    for f in futures]
    results_match = cont_results == base_results

    out = {
        "metric": "serve_continuous_vs_drain_refill"
        if not smoke else "serve_smoke",
        "value": round(nreq / cont_wall, 1) if cont_wall > 0 else 0.0,
        "unit": "req/s",
        "ok": bool(cont_ok and results_match
                   and c["recycled_lanes"] > 0),
        "lanes": lanes,
        "requests": nreq,
        "recycled_lanes": c["recycled_lanes"],
        "rounds": c["rounds"],
        "results_match_baseline": results_match,
        "continuous": {
            "wall_s": round(cont_wall, 3),
            "req_per_s": round(nreq / cont_wall, 1),
            "p50_latency_s": round(percentile(cont_lat, 0.5), 4),
            "p99_latency_s": round(percentile(cont_lat, 0.99), 4),
            "occupancy": round(cont_occ, 4),
        },
        "drain_refill": {
            "wall_s": round(base_wall, 3),
            "req_per_s": round(nreq / base_wall, 1),
            "p50_latency_s": round(percentile(base_lat, 0.5), 4),
            "p99_latency_s": round(percentile(base_lat, 0.99), 4),
            "occupancy": round(base_occ, 4),
        },
        "speedup_throughput": round(base_wall / cont_wall, 3)
        if cont_wall > 0 else None,
        "speedup_p99": round(percentile(base_lat, 0.99)
                             / max(percentile(cont_lat, 0.99), 1e-9), 3),
    }
    if smoke:
        print(json.dumps({k: out[k] for k in
                          ("metric", "value", "unit", "ok", "lanes",
                           "requests", "recycled_lanes", "rounds",
                           "results_match_baseline")}))
        return 0 if out["ok"] else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "SERVE_r09.json")
    print(f"# serve lanes={lanes} reqs={nreq} "
          f"cont={cont_wall:.2f}s base={base_wall:.2f}s "
          f"speedup={out['speedup_throughput']}x "
          f"occ {cont_occ:.2f} vs {base_occ:.2f}", file=sys.stderr)
    return 0 if out["ok"] else 1


def oversub_bench(smoke: bool = False) -> int:
    """`bench.py --oversub`: open-loop mixed short/long request stream
    through an oversubscribed BatchServer (4x virtual:physical lane
    ratio — lane-memory virtualization, wasmedge_tpu/hv/) vs the same
    stream through a no-oversub baseline server.  The hv server admits
    the whole stream immediately (admitted concurrency > physical
    lanes, the ROADMAP #4 capacity multiplier) and rotates cold lanes
    through the host-side SwapStore; the baseline queues everything
    beyond the lane count.  Emits OVERSUB_r14.json.

    `--oversub-smoke` is the CI guard: a tiny stream, asserts every
    future resolves, swaps happened in BOTH directions, and results
    are bit-identical to the unswapped reference — no artifact."""
    import os
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.serve import BatchServer
    from wasmedge_tpu.utils.bench_artifact import percentile

    if smoke:
        lanes, ratio, nreq = 4, 4, 24
        short_n, long_n, long_every = 8, 12, 6
        chunk = 256
    else:
        lanes = int(os.environ.get("OVERSUB_LANES", 8))
        ratio = int(os.environ.get("OVERSUB_RATIO", 4))
        nreq = int(os.environ.get("OVERSUB_REQUESTS", 96))
        short_n, long_n, long_every = 10, 18, 8
        chunk = 2048

    args = _serve_workload(seed=14, nreq=nreq, short_n=short_n,
                           long_n=long_n, long_every=long_every)

    def run(oversub: bool):
        conf = Configure()
        conf.batch.steps_per_launch = chunk
        conf.batch.value_stack_depth = 128
        conf.batch.call_stack_depth = 64
        conf.obs.enabled = not smoke
        if oversub:
            conf.hv.max_virtual_lanes = lanes * ratio
        inst, store = _instantiate_fib(conf)
        server = BatchServer(inst, store=store, conf=conf, lanes=lanes)
        t0 = _time.monotonic()
        # open loop: the whole stream arrives up front, regardless of
        # completion — exactly the shape where admission capped at the
        # physical lane count leaves the queue deep
        futures = [server.submit("fib", [int(n)],
                                 tenant=f"t{i % 4}")
                   for i, n in enumerate(args)]
        peak_admitted = 0
        while server.step():
            peak_admitted = max(peak_admitted, server.in_flight)
        wall = _time.monotonic() - t0
        lat = sorted(f.t_done - t0 for f in futures
                     if f.t_done is not None)
        results = [f.result(0)[0] if f.error is None else None
                   for f in futures]
        hv = server.hv_stats()
        return {
            "wall_s": round(wall, 3),
            "req_per_s": round(nreq / wall, 1) if wall > 0 else 0.0,
            "p50_latency_s": round(percentile(lat, 0.5), 4),
            "p99_latency_s": round(percentile(lat, 0.99), 4),
            "peak_admitted_concurrency": peak_admitted,
            "swaps_in": hv["swaps_in"] if hv else 0,
            "swaps_out": hv["swaps_out"] if hv else 0,
            "resolved": all(f.done for f in futures),
            "results": results,
            "counters": dict(server.counters),
        }

    base = run(oversub=False)
    over = run(oversub=True)
    results_match = over["results"] == base["results"]
    ok = bool(
        base["resolved"] and over["resolved"] and results_match
        and over["swaps_in"] > 0 and over["swaps_out"] > 0
        and over["peak_admitted_concurrency"] > lanes)
    out = {
        "metric": "oversub_smoke" if smoke
        else "oversub_4x_vs_no_oversub",
        "value": over["req_per_s"],
        "unit": "req/s",
        "ok": ok,
        "lanes": lanes,
        "virtual_lanes": lanes * ratio,
        "requests": nreq,
        "results_match_baseline": results_match,
        "admitted_concurrency": over["peak_admitted_concurrency"],
        "baseline_admitted_concurrency":
            base["peak_admitted_concurrency"],
        "swaps_in": over["swaps_in"],
        "swaps_out": over["swaps_out"],
        "oversub": {k: over[k] for k in
                    ("wall_s", "req_per_s", "p50_latency_s",
                     "p99_latency_s")},
        "no_oversub": {k: base[k] for k in
                       ("wall_s", "req_per_s", "p50_latency_s",
                        "p99_latency_s")},
    }
    if smoke:
        print(json.dumps(out))
        return 0 if ok else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "OVERSUB_r14.json")
    print(f"# oversub lanes={lanes} virt={lanes * ratio} reqs={nreq} "
          f"admitted_peak={out['admitted_concurrency']} "
          f"swaps={out['swaps_out']}/{out['swaps_in']} "
          f"over={over['wall_s']}s base={base['wall_s']}s",
          file=sys.stderr)
    return 0 if ok else 1


def _gateway_rpc(host, port, method, path, body=None, headers=None,
                 timeout=120.0):
    """One stdlib-HTTP round trip to the gateway (real sockets — the
    bench measures the wire protocol, not in-process calls)."""
    import json as _json
    from http.client import HTTPConnection

    c = HTTPConnection(host, port, timeout=timeout)
    try:
        if isinstance(body, dict):
            body = _json.dumps(body).encode()
        c.request(method, path, body=body, headers=headers or {})
        r = c.getresponse()
        raw = r.read()
        retry_after = r.getheader("Retry-After")
    finally:
        c.close()
    try:
        doc = _json.loads(raw)
    except Exception:
        doc = raw.decode(errors="replace")
    return r.status, doc, retry_after


def _start_gateway(conf, lanes, tenants=None):
    from wasmedge_tpu.gateway import Gateway, GatewayService

    svc = GatewayService(conf=conf, lanes=lanes, tenants=tenants)
    gw = Gateway(svc, host="127.0.0.1", port=0).start()
    return gw, svc


def gateway_smoke() -> int:
    """`bench.py --gateway-smoke`: start the gateway on an ephemeral
    port, register the echo module OVER HTTP at runtime, drive a small
    mixed-tenant echo stream through real sockets, flood one
    rate-limited tenant until it draws a 429, and assert every accepted
    request resolves + the gateway shuts down cleanly.  The CI guard
    that the network layer stays wired end-to-end; prints ONE JSON
    line, emits no artifact."""
    import time as _time

    import bench_echo
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.gateway import GatewayTenants

    conf = Configure()
    conf.batch.steps_per_launch = 128
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    conf.obs.enabled = True
    # rate 1/s with burst 4: after 4 banked tokens, a tight loop of 10
    # CANNOT be outrun by refill no matter how slow the CI machine is
    # — the 429 assertion is deterministic, not a timing race
    tenants = GatewayTenants.from_dict({"tenants": {
        "flood": {"rate_per_s": 1.0, "burst": 4},
        "t0": {}, "t1": {},
    }})
    t0 = time.perf_counter()
    gw, svc = _start_gateway(conf, lanes=8, tenants=tenants)
    checks = {}
    try:
        # registration rides a LISTED tenant: with a policy table
        # present, unlisted tenants may not register (can_register)
        st, doc, _ = _gateway_rpc(
            gw.host, gw.port, "POST", "/v1/modules?name=echo&tenant=t0",
            body=bench_echo.build_module(),
            headers={"Content-Type": "application/wasm"})
        checks["registered_over_http"] = st == 201
        # mixed-tenant echo stream, async + poll (each request = 2
        # fd_write hostcalls per iteration through the tier-1 drain)
        ids = []
        for i in range(12):
            st, doc, _ = _gateway_rpc(
                gw.host, gw.port, "POST", "/v1/invoke",
                body={"module": "echo", "func": "echo", "args": [2],
                      "tenant": f"t{i % 2}", "async": True})
            if st == 202:
                ids.append(doc["request_id"])
        checks["accepted"] = len(ids) == 12
        # flood one tenant past its token bucket: burst 4 at 1/s —
        # a tight loop of 10 must draw at least one 429
        flood_429 = 0
        for _ in range(10):
            st, doc, retry_after = _gateway_rpc(
                gw.host, gw.port, "POST", "/v1/invoke",
                body={"module": "echo", "func": "echo", "args": [1],
                      "tenant": "flood", "async": True})
            if st == 202:
                ids.append(doc["request_id"])
            elif st == 429:
                flood_429 += 1
                checks.setdefault("retry_after_header",
                                  retry_after is not None)
        checks["flood_saw_429"] = flood_429 >= 1
        # every ACCEPTED request resolves ok
        deadline = _time.monotonic() + 60.0
        done = {}
        while len(done) < len(ids) and _time.monotonic() < deadline:
            for rid in ids:
                if rid in done:
                    continue
                st, doc, _ = _gateway_rpc(gw.host, gw.port, "GET",
                                          f"/v1/requests/{rid}")
                if isinstance(doc, dict) \
                        and doc.get("status") != "pending":
                    done[rid] = (st, doc)
            _time.sleep(0.02)
        checks["all_resolved"] = len(done) == len(ids) and all(
            st == 200 and doc.get("ok") for st, doc in done.values())
        st, doc, _ = _gateway_rpc(gw.host, gw.port, "GET", "/v1/status")
        checks["status_ok"] = st == 200 and doc.get("generation") == 1
        st, text, _ = _gateway_rpc(gw.host, gw.port, "GET", "/metrics")
        checks["metrics_has_http_counter"] = \
            st == 200 and "wasmedge_gateway_http_requests_total" in text
    finally:
        gw.shutdown(drain=True, timeout_s=60.0)
    checks["clean_shutdown"] = svc.status()["in_flight"] == 0 \
        if "in_flight" in svc.status() else True
    dt = time.perf_counter() - t0
    ok = all(checks.values())
    print(json.dumps({
        "metric": "gateway_smoke_http_echo",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "flood_429": flood_429,
        "requests": len(ids),
        "wall_s": round(dt, 3),
    }))
    return 0 if ok else 1


def gateway_bench() -> int:
    """`bench.py --gateway`: open- and closed-loop request streams over
    real sockets against the HTTP gateway, reporting the latency SLO
    numbers (p50/p99 via utils/bench_artifact.percentile), sustained
    throughput, and reject/deadline counts.  Emits SERVE_r11.json.

    closed loop: W workers, each a serial sync-invoke client — models
    a fixed client population; throughput is the capacity number.
    open loop: requests fired at a fixed arrival rate regardless of
    completions — models external traffic; p99 shows queueing delay."""
    import os
    import threading
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.utils.bench_artifact import percentile

    lanes = int(os.environ.get("GATEWAY_LANES", 32))
    nreq = int(os.environ.get("GATEWAY_REQUESTS", 160))
    workers = int(os.environ.get("GATEWAY_WORKERS", 8))
    rate = float(os.environ.get("GATEWAY_RATE", 120.0))
    deadline_ms = int(os.environ.get("GATEWAY_DEADLINE_MS", 30_000))

    conf = Configure()
    conf.batch.steps_per_launch = 2048
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    gw, svc = _start_gateway(conf, lanes=lanes)
    st, doc, _ = _gateway_rpc(
        gw.host, gw.port, "POST", "/v1/modules?name=fib",
        body=build_fib(), headers={"Content-Type": "application/wasm"})
    assert st == 201, doc
    args = _serve_workload(seed=0, nreq=nreq, short_n=10, long_n=18,
                           long_every=8)
    counts = {"429": 0, "504": 0, "other": 0}
    lock = threading.Lock()

    def invoke(n, tenant, lat_sink, t_sched=None):
        t_send = _time.monotonic()
        st, doc, _ = _gateway_rpc(
            gw.host, gw.port, "POST", "/v1/invoke",
            body={"module": "fib", "func": "fib", "args": [int(n)],
                  "tenant": tenant, "deadline_ms": deadline_ms})
        t_done = _time.monotonic()
        with lock:
            if st == 200 and isinstance(doc, dict) and doc.get("ok"):
                # open-loop latency anchors at the SCHEDULED send time:
                # a client that falls behind its schedule still pays
                lat_sink.append(t_done - (t_sched if t_sched is not None
                                          else t_send))
            elif st == 429:
                counts["429"] += 1
            elif st == 504:
                counts["504"] += 1
            else:
                counts["other"] += 1

    # --- closed loop: W serial clients, nreq total ---
    closed_lat = []
    per_worker = nreq // workers
    t0 = _time.monotonic()
    threads = []
    for w in range(workers):
        chunk = args[w * per_worker:(w + 1) * per_worker]

        def drive(chunk=chunk, w=w):
            for n in chunk:
                invoke(n, f"t{w % 4}", closed_lat)

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    closed_wall = _time.monotonic() - t0
    closed_n = workers * per_worker

    # --- open loop: fixed arrival rate, one thread per in-flight req ---
    open_lat = []
    t0 = _time.monotonic()
    threads = []
    for i, n in enumerate(args):
        t_sched = t0 + i / rate
        now = _time.monotonic()
        if t_sched > now:
            _time.sleep(t_sched - now)
        t = threading.Thread(target=invoke,
                             args=(n, f"t{i % 4}", open_lat, t_sched),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    open_wall = _time.monotonic() - t0
    gw.shutdown(drain=True, timeout_s=120.0)

    closed_lat.sort()
    open_lat.sort()
    ok = bool(closed_lat and open_lat
              and counts["other"] == 0
              and len(closed_lat) + len(open_lat) + counts["429"]
              + counts["504"] == closed_n + nreq)
    out = {
        "metric": "gateway_open_closed_loop_fib",
        "value": round(closed_n / closed_wall, 1)
        if closed_wall > 0 else 0.0,
        "unit": "req/s",
        "ok": ok,
        "lanes": lanes,
        "deadline_ms": deadline_ms,
        "rejected_429": counts["429"],
        "deadline_504": counts["504"],
        "failed_other": counts["other"],
        "closed_loop": {
            "workers": workers,
            "requests": closed_n,
            "wall_s": round(closed_wall, 3),
            "req_per_s": round(closed_n / closed_wall, 1),
            "p50_latency_s": round(percentile(closed_lat, 0.5), 4)
            if closed_lat else None,
            "p99_latency_s": round(percentile(closed_lat, 0.99), 4)
            if closed_lat else None,
        },
        "open_loop": {
            "target_rate_per_s": rate,
            "requests": nreq,
            "wall_s": round(open_wall, 3),
            "req_per_s": round(len(open_lat) / open_wall, 1)
            if open_wall > 0 else 0.0,
            "p50_latency_s": round(percentile(open_lat, 0.5), 4)
            if open_lat else None,
            "p99_latency_s": round(percentile(open_lat, 0.99), 4)
            if open_lat else None,
        },
    }
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "SERVE_r11.json")
    print(json.dumps(out))
    print(f"# gateway lanes={lanes} closed={closed_n}req/"
          f"{closed_wall:.2f}s open={nreq}req@{rate}/s/"
          f"{open_wall:.2f}s 429={counts['429']} 504={counts['504']}",
          file=sys.stderr)
    return 0 if ok else 1


def chaos_bench(smoke: bool = False) -> int:
    """`bench.py --chaos`: live-traffic chaos test of the durable
    gateway (r13 acceptance).  An open-loop HTTP client fleet submits
    async requests at a fixed arrival rate while a seeded fault
    schedule (testing/faults.gateway_chaos_schedule: engine
    launch/serve faults, a generation build/swap fault, durable-journal
    write faults, HTTP delay/drop) runs underneath — and mid-stream the
    gateway process is KILLED (Gateway.kill(): no drain, no flush) and
    restarted with resume=True over the same state dir.  Asserts:

      - every accepted (202) request id reaches exactly one terminal
        outcome — resolved, or machine-readably rejected (err taxonomy
        in the body) — and the outcome is stable across repeat polls
      - zero accepted ids are lost across the kill/restart (no 404s)
      - the registered module set (including the one registered
        through a rolled-back-then-retried swap) is fully present
        post-resume
      - the swap fault rolled back atomically (rollbacks >= 1) and the
        pre-kill fault schedule actually fired

    Emits CHAOS_r13.json.  `--chaos-smoke` is the CI guard: a short
    serial schedule, one in-process kill/restart, the same zero-lost /
    exactly-once assertions, no artifact emission."""
    import os
    import shutil
    import tempfile
    import threading
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.gateway import Gateway, GatewayService
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.testing.faults import (
        Fault,
        FaultInjector,
        gateway_chaos_schedule,
    )
    from wasmedge_tpu.utils.builder import ModuleBuilder

    seed = int(os.environ.get("CHAOS_SEED", 13))
    if smoke:
        lanes, nreq, rate = 4, 16, 200.0
        fib_lo, fib_hi = 8, 12
        # launch at=0: the very first serving launch faults and the
        # server recovers from scratch — deterministic regardless of
        # how many rounds run before the kill
        schedule = [Fault(point="launch", at=0),
                    Fault(point="generation_build", at=1),
                    Fault(point="journal_write", at=6)]
    else:
        lanes = int(os.environ.get("CHAOS_LANES", 8))
        nreq = int(os.environ.get("CHAOS_REQUESTS", 96))
        rate = float(os.environ.get("CHAOS_RATE", 40.0))
        fib_lo, fib_hi = 8, 16
        schedule = gateway_chaos_schedule(seed)
    reg_at, kill_at = nreq // 3, nreq // 2

    def fresh_conf():
        conf = Configure()
        conf.batch.steps_per_launch = 128
        conf.batch.value_stack_depth = 64
        conf.batch.call_stack_depth = 32
        conf.obs.enabled = not smoke
        return conf

    def build_dbl():
        b = ModuleBuilder()
        b.add_function(["i64"], ["i64"], [],
                       [("local.get", 0), ("i64.const", 2), "i64.mul",
                        ("i64.const", 7), "i64.add"], export="dbl")
        return b.build()

    state_dir = tempfile.mkdtemp(prefix="chaos-gw-")
    inj = FaultInjector(schedule)
    t0 = time.perf_counter()
    svc = GatewayService(conf=fresh_conf(), lanes=lanes, faults=inj,
                         state_dir=state_dir)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gw = Gateway(svc, port=0).start()
    addr = {"host": gw.host, "port": gw.port}

    accepted = []          # ids the CLIENT holds a 202 for
    rejected_mr = []       # machine-readable submit rejections
    transport_errors = [0]
    outcomes = {}          # id -> (status, doc) first terminal poll
    lock = threading.Lock()
    stop_poll = threading.Event()

    def poll_once(rid):
        try:
            st, doc, _ = _gateway_rpc(addr["host"], addr["port"], "GET",
                                      f"/v1/requests/{rid}", timeout=30.0)
        except OSError:
            return False   # dropped/killed wire: retry later
        if not isinstance(doc, dict) or doc.get("status") == "pending":
            return False
        with lock:
            outcomes.setdefault(rid, (st, doc))
        return True

    def poller():
        while not stop_poll.is_set():
            with lock:
                todo = [r for r in accepted if r not in outcomes]
            if not todo:
                _time.sleep(0.02)
                continue
            for rid in todo:
                poll_once(rid)
                if stop_poll.is_set():
                    return
            _time.sleep(0.01)

    pollers = [threading.Thread(target=poller, daemon=True)
               for _ in range(1 if smoke else 3)]
    for t in pollers:
        t.start()

    def submit(n):
        try:
            st, doc, _ = _gateway_rpc(
                addr["host"], addr["port"], "POST",
                "/v1/invoke?async=1",
                body={"module": "fib", "func": "fib", "args": [int(n)]},
                timeout=30.0)
        except OSError:
            transport_errors[0] += 1
            return
        if st == 202 and isinstance(doc, dict):
            with lock:
                accepted.append(doc["request_id"])
        elif isinstance(doc, dict) and isinstance(doc.get("err"), dict) \
                and "name" in doc["err"]:
            rejected_mr.append((st, doc["err"]["name"]))
        else:
            transport_errors[0] += 1

    def register_dbl():
        """Draw the armed swap fault (503 + Retry-After, rolled back),
        then retry until the registration lands."""
        saw_503 = False
        for _ in range(6):
            st, doc, _ = _gateway_rpc(
                addr["host"], addr["port"], "POST",
                "/v1/modules?name=dbl", body=build_dbl(),
                headers={"Content-Type": "application/wasm"},
                timeout=180.0)
            if st == 201:
                return saw_503, True
            if st == 503:
                saw_503 = True
                _time.sleep(0.1)
                continue
            return saw_503, False
        return saw_503, False

    checks = {}
    rng_args = np.random.RandomState(seed).randint(
        fib_lo, fib_hi + 1, size=nreq)
    saw_rollback_503 = dbl_registered = False
    restarted = False
    pre_kill_counters = {}
    t_sched0 = _time.monotonic()
    for i, n in enumerate(rng_args):
        t_sched = t_sched0 + i / rate
        now = _time.monotonic()
        if t_sched > now:
            _time.sleep(t_sched - now)
        if i == reg_at:
            saw_rollback_503, dbl_registered = register_dbl()
        if i == kill_at:
            # THE crash: no drain, no flush — then resume from disk
            pre_kill_counters = dict(svc.counters)
            gw.kill()
            inj2 = FaultInjector([])   # calm weather after the storm
            svc = GatewayService(conf=fresh_conf(), lanes=lanes,
                                 faults=inj2, state_dir=state_dir,
                                 resume=True)
            gw = Gateway(svc, port=0).start()
            addr["host"], addr["port"] = gw.host, gw.port
            restarted = True
        submit(n)

    # drain: every accepted id must reach ONE terminal outcome
    deadline = _time.monotonic() + (120.0 if smoke else 300.0)
    while _time.monotonic() < deadline:
        with lock:
            if len(outcomes) == len(accepted):
                break
        _time.sleep(0.05)
    stop_poll.set()
    for t in pollers:
        t.join(timeout=5.0)

    # exactly-once: a second poll of every id must repeat the outcome
    stable = lost = resolved = rejected_after = 0
    for rid in accepted:
        first = outcomes.get(rid)
        try:
            st, doc, _ = _gateway_rpc(addr["host"], addr["port"], "GET",
                                      f"/v1/requests/{rid}", timeout=30.0)
        except OSError:
            st, doc = None, None
        if first is None:
            lost += 1
            continue
        if st == 404 and isinstance(doc, dict) \
                and doc.get("err", {}).get("detail") != "pruned":
            lost += 1
            continue
        if isinstance(doc, dict) and doc.get("ok") and \
                first[1].get("ok") and \
                doc.get("result") == first[1].get("result"):
            stable += 1
        elif isinstance(doc, dict) and not doc.get("ok") \
                and not first[1].get("ok"):
            stable += 1
        if first[1].get("ok"):
            resolved += 1
        else:
            rejected_after += 1
    st, status_doc, _ = _gateway_rpc(addr["host"], addr["port"], "GET",
                                     "/v1/status", timeout=60.0)
    st_m, metrics_text, _ = _gateway_rpc(addr["host"], addr["port"],
                                         "GET", "/metrics", timeout=60.0)
    gw.shutdown(drain=True, timeout_s=120.0)
    shutil.rmtree(state_dir, ignore_errors=True)
    dt = time.perf_counter() - t0

    gcounters = status_doc.get("gateway", {}) if isinstance(
        status_doc, dict) else {}
    checks["accepted_all_terminal"] = len(outcomes) == len(accepted)
    checks["zero_ids_lost"] = lost == 0
    checks["outcomes_stable"] = stable == len(accepted)
    checks["restarted_mid_stream"] = restarted
    checks["modules_present_post_resume"] = isinstance(
        status_doc, dict) and set(status_doc.get("modules", {})) >= (
        {"fib", "dbl"} if dbl_registered else {"fib"})
    checks["swap_fault_rolled_back"] = (not any(
        f.point in ("generation_build", "generation_swap")
        for f in schedule)) or (saw_rollback_503 and dbl_registered)
    checks["pre_kill_faults_fired"] = inj.fired >= 1
    checks["restart_counted"] = gcounters.get("restarts", 0) >= 1 \
        and "wasmedge_gateway_restarts_total" in str(metrics_text)
    ok = all(checks.values())
    out = {
        "metric": "gateway_chaos_smoke" if smoke
        else "gateway_chaos_open_loop",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "seed": seed,
        "lanes": lanes,
        "requests": nreq,
        "accepted": len(accepted),
        "rejected_machine_readable": len(rejected_mr),
        "transport_errors": transport_errors[0],
        "resolved_ok": resolved,
        "rejected_after_accept": rejected_after,
        "injected_pre_kill": inj.log,
        "restarts": gcounters.get("restarts", 0),
        # rollbacks is a per-process counter: the swap fault fired (and
        # rolled back) in the PRE-kill process
        "rollbacks": max(gcounters.get("rollbacks", 0),
                         pre_kill_counters.get("rollbacks", 0)),
        "wall_s": round(dt, 3),
    }
    if smoke:
        print(json.dumps(out))
        return 0 if ok else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "CHAOS_r13.json")
    print(json.dumps(out))
    print(f"# chaos lanes={lanes} reqs={nreq} accepted={len(accepted)} "
          f"lost={lost} restarts={gcounters.get('restarts')} "
          f"rollbacks={gcounters.get('rollbacks')} wall={dt:.1f}s",
          file=sys.stderr)
    return 0 if ok else 1


def federation_bench(smoke: bool = False) -> int:
    """`bench.py --federation`: the r16 fleet-federation acceptance —
    TWO gateways federated over localhost ephemeral ports (in-process
    services + real sockets, with `Gateway.kill()` as the supported
    simulated SIGKILL, the r13 chaos precedent):

      - the guest module registers over HTTP on peer A only; peer B
        becomes servable through the peer-replicated module store
      - an open-loop async stream submits through BOTH peers (routing
        forwards across the fleet); retryable 503/429 rejections
        (suspect owner, strict-replication failure) are retried per
        their Retry-After — the machine-readable contract in action
      - one parked (swapped) virtual lane cross-host-MIGRATES A -> B
        before the kill; its result must be bit-identical to the
        unmigrated same-argument reference
      - peer A is KILLED mid-stream (no drain, no flush); B's
        heartbeat state machine declares it dead, adopts its
        replicated journal (ids accepted by A answer from B), and
        re-queues its own forwards — every accepted id reaches exactly
        one stable terminal outcome, zero ids lost
      - the full module set stays servable from the survivor

    Emits FLEET_r16.json.  `--federation-smoke` is the CI guard: a
    short stream, same assertions, no artifact."""
    import os
    import threading
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.fleet import FleetConfig
    from wasmedge_tpu.gateway import Gateway, GatewayService
    from wasmedge_tpu.models import build_fib

    seed = int(os.environ.get("FLEET_SEED", 16))
    if smoke:
        lanes, nreq, rate = 4, 14, 60.0
        fib_lo, fib_hi = 8, 12
    else:
        lanes = int(os.environ.get("FLEET_LANES", 4))
        nreq = int(os.environ.get("FLEET_REQUESTS", 48))
        rate = float(os.environ.get("FLEET_RATE", 24.0))
        fib_lo, fib_hi = 8, 14
    kill_at = nreq // 2

    def fresh_conf():
        conf = Configure()
        conf.batch.steps_per_launch = 128
        conf.batch.value_stack_depth = 64
        conf.batch.call_stack_depth = 32
        conf.hv.max_virtual_lanes = 3 * lanes   # parking -> migratable
        return conf

    def fleet_cfg(peers=()):
        return FleetConfig(peers=peers, heartbeat_s=0.1,
                           suspect_after=2, dead_after=3,
                           backoff_base_s=0.02, request_timeout_s=5.0)

    t0 = time.perf_counter()
    svc_a = GatewayService(conf=fresh_conf(), lanes=lanes,
                           fleet=fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=fresh_conf(), lanes=lanes,
        fleet=fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    a = {"host": gw_a.host, "port": gw_a.port}
    b = {"host": gw_b.host, "port": gw_b.port}

    # -- module registers on A ONLY, over the wire --------------------
    st, doc, _ = _gateway_rpc(a["host"], a["port"], "POST",
                              "/v1/modules?name=fib", body=build_fib(),
                              headers={"Content-Type":
                                       "application/wasm"},
                              timeout=180.0)
    assert st == 201, (st, doc)
    # ...and replicates to B (heartbeat manifest sync)
    deadline = _time.monotonic() + 120.0
    replicated = False
    while _time.monotonic() < deadline:
        st, doc, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                  "/v1/status", timeout=30.0)
        if st == 200 and "fib" in (doc.get("modules") or {}):
            replicated = True
            break
        _time.sleep(0.05)

    accepted = {}          # id -> fib arg
    rejected_mr = []
    transport_errors = [0]
    outcomes = {}
    lock = threading.Lock()
    stop_poll = threading.Event()
    a_dead = threading.Event()

    def poll_once(rid):
        # post-kill, ids accepted by A answer from B only after
        # adoption: a 404 is "not yet", never a terminal outcome (a
        # genuinely lost id fails the drain deadline instead)
        try:
            st, doc, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                      f"/v1/requests/{rid}",
                                      timeout=30.0)
        except OSError:
            return False
        if st == 404 or not isinstance(doc, dict) \
                or doc.get("status") == "pending":
            return False
        with lock:
            outcomes.setdefault(rid, (st, doc))
        return True

    def poller():
        while not stop_poll.is_set():
            with lock:
                todo = [r for r in accepted if r not in outcomes]
            if not todo:
                _time.sleep(0.02)
                continue
            for rid in todo:
                poll_once(rid)
                if stop_poll.is_set():
                    return
            _time.sleep(0.01)

    pollers = [threading.Thread(target=poller, daemon=True)
               for _ in range(1 if smoke else 2)]
    for t in pollers:
        t.start()

    def submit(peer, n):
        """One async submit with bounded retry of the RETRYABLE
        classes (suspect owner 503, strict-replication 503,
        backpressure 429) — the Retry-After contract exercised."""
        for _ in range(8):
            try:
                st, doc, after = _gateway_rpc(
                    peer["host"], peer["port"], "POST",
                    "/v1/invoke?async=1",
                    body={"module": "fib", "func": "fib",
                          "args": [int(n)]}, timeout=30.0)
            except OSError:
                transport_errors[0] += 1
                return
            if st == 202 and isinstance(doc, dict):
                with lock:
                    accepted[doc["request_id"]] = int(n)
                return
            err = doc.get("err") if isinstance(doc, dict) else None
            if isinstance(err, dict) and err.get("retryable"):
                rejected_mr.append((st, err.get("name"),
                                    err.get("detail")))
                _time.sleep(min(float(after or 0.2), 0.3))
                continue
            if isinstance(err, dict):
                rejected_mr.append((st, err.get("name"),
                                    err.get("detail")))
                return
            transport_errors[0] += 1
            return

    # -- the stream: alternate peers pre-kill, survivor-only after ----
    rng = np.random.RandomState(seed)
    args_stream = rng.randint(fib_lo, fib_hi + 1, size=nreq)
    migrated_id = None
    migrated_arg = None
    restarted = False
    t_sched0 = _time.monotonic()
    for i, n in enumerate(args_stream):
        t_sched = t_sched0 + i / rate
        now = _time.monotonic()
        if t_sched > now:
            _time.sleep(t_sched - now)
        if i == kill_at:
            # -- cross-host migration first: pressure-burst A so its
            # hv layer parks a lane, then ship one parked vlane A -> B
            # and keep its id for the bit-identical check
            for _ in range(2 * lanes + 2):
                submit(a, fib_hi + 2)
            mig_deadline = _time.monotonic() + (30.0 if smoke else 60.0)
            while _time.monotonic() < mig_deadline:
                st, doc, _ = _gateway_rpc(a["host"], a["port"], "GET",
                                          "/v1/fleet/status",
                                          timeout=30.0)
                swapped = [r for r in (doc.get("swapped") or [])
                           if r in accepted] if st == 200 else []
                if swapped:
                    rid = swapped[0]
                    st, doc, _ = _gateway_rpc(
                        a["host"], a["port"], "POST",
                        "/v1/fleet/migrate_out",
                        body={"id": rid,
                              "peer": f"{b['host']}:{b['port']}"},
                        timeout=30.0)
                    if st == 200 and isinstance(doc, dict) \
                            and doc.get("ok"):
                        migrated_id = rid
                        migrated_arg = accepted[rid]
                    break
                _time.sleep(0.05)
            # -- THE kill: no drain, no flush, heartbeats just stop
            gw_a.kill()
            a_dead.set()
            restarted = True
        peer = b if a_dead.is_set() or (i % 2 == 0) else a
        submit(peer, n)

    # -- drain --------------------------------------------------------
    deadline = _time.monotonic() + (180.0 if smoke else 420.0)
    while _time.monotonic() < deadline:
        with lock:
            if len(outcomes) == len(accepted):
                break
        _time.sleep(0.05)
    stop_poll.set()
    for t in pollers:
        t.join(timeout=5.0)

    def fibv(n):
        x, y = 0, 1
        for _ in range(n):
            x, y = y, x + y
        return x

    # exactly one STABLE terminal outcome per accepted id, and every
    # ok outcome carries the right cells (server-side correctness is
    # client-visible)
    stable = lost = resolved = wrong = 0
    for rid, n in accepted.items():
        first = outcomes.get(rid)
        if first is None:
            lost += 1
            continue
        try:
            st, doc, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                      f"/v1/requests/{rid}",
                                      timeout=30.0)
        except OSError:
            st, doc = None, None
        if isinstance(doc, dict) and doc.get("ok") \
                and first[1].get("ok") \
                and doc.get("result") == first[1].get("result"):
            stable += 1
        elif isinstance(doc, dict) and not doc.get("ok") \
                and not first[1].get("ok"):
            stable += 1
        if first[1].get("ok"):
            resolved += 1
            if first[1].get("result") != [fibv(n)]:
                wrong += 1

    # migrated-lane bit-identity: the migrated id resolved with the
    # SAME cells as the unmigrated same-argument oracle
    mig_ok = migrated_id is not None
    if mig_ok:
        out_m = outcomes.get(migrated_id)
        mig_ok = out_m is not None and out_m[1].get("ok") \
            and out_m[1].get("result") == [fibv(migrated_arg)]

    st, status_b, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                   "/v1/status", timeout=60.0)
    st_m, metrics_b, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                      "/metrics", timeout=60.0)
    fleet_b = status_b.get("fleet", {}) if isinstance(status_b, dict) \
        else {}
    gw_b.shutdown(drain=True, timeout_s=120.0)
    dt = time.perf_counter() - t0

    checks = {
        "module_replicated_to_peer": replicated,
        "accepted_all_terminal": len(outcomes) == len(accepted),
        "zero_ids_lost": lost == 0,
        "outcomes_stable": stable == len(accepted),
        "results_correct": wrong == 0,
        "peer_killed_mid_stream": restarted,
        "peer_declared_dead": fleet_b.get("peer_states", {}).get(
            f"{a['host']}:{a['port']}", {}).get("state") == "dead",
        "modules_servable_from_survivor": isinstance(status_b, dict)
        and set(status_b.get("modules", {})) >= {"fib"},
        "migrated_lane_bit_identical": mig_ok,
        "fleet_metrics_exported":
            "wasmedge_fleet_peers" in str(metrics_b)
            and "wasmedge_fleet_migrations_total" in str(metrics_b),
    }
    ok = all(checks.values())
    out = {
        "metric": "fleet_federation_smoke" if smoke
        else "fleet_federation_open_loop",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "seed": seed,
        "lanes_per_peer": lanes,
        "peers": 2,
        "requests": nreq,
        "accepted": len(accepted),
        "rejected_retryable_then_retried": len(rejected_mr),
        "transport_errors": transport_errors[0],
        "resolved_ok": resolved,
        "migrated_id": migrated_id,
        "adoptions": fleet_b.get("adoptions", 0),
        "forward_requeues": fleet_b.get("forward_requeues", 0),
        "wall_s": round(dt, 3),
    }
    if smoke:
        print(json.dumps(out))
        return 0 if ok else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "FLEET_r16.json")
    print(json.dumps(out))
    print(f"# federation peers=2 lanes={lanes} reqs={nreq} "
          f"accepted={len(accepted)} lost={lost} "
          f"adoptions={fleet_b.get('adoptions')} "
          f"requeues={fleet_b.get('forward_requeues')} "
          f"migrated={migrated_id} wall={dt:.1f}s", file=sys.stderr)
    return 0 if ok else 1


def elastic_bench(smoke: bool = False) -> int:
    """`bench.py --elastic`: the r21 elastic-fleet acceptance — one
    JOIN, one live RESHARD, and one clean LEAVE mid-stream under
    open-loop load, with seeded gossip-drop weather
    (testing/faults.churn_schedule):

      - gateway A serves on 2 of the 4 virtual devices; B is a static
        boot peer; the stream alternates submits across live peers
      - mid-stream a THIRD gateway C joins by announcing itself to
        seed A: the bumped membership view gossips fleet-wide, C syncs
        the module set on its first heartbeat, and C must take traffic
        (its first 202) within ONE heartbeat of becoming servable —
        and actually COMPLETE requests
      - A live-reshards 2 -> 4 devices over POST /v1/reshard while
        lanes are resident: no drain, zero resident requests dropped,
        every result still fib-oracle-correct (bit-identity is the
        serve path's grow-only-pool construction, pinned per-lane in
        tests/test_elastic.py)
      - B announces departure over POST /v1/fleet/leave and shuts
        down: survivors unroute it as churn (never degradation), and
        every id B accepted still reaches one stable terminal outcome
        (clean drain + replicated-journal adoption after the left
        peer's heartbeats stop)
      - every accepted id fleet-wide: exactly one STABLE terminal
        outcome, zero lost, zero wrong cells

    Emits ELASTIC_r21.json.  `--elastic-smoke` is the CI guard: a
    short stream, same assertions, no artifact."""
    import os
    import threading
    import time as _time

    jax = _mesh_env(8)

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.fleet import FleetConfig
    from wasmedge_tpu.gateway import Gateway, GatewayService
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.testing.faults import FaultInjector, churn_schedule

    seed = int(os.environ.get("ELASTIC_SEED", 21))
    if smoke:
        lanes, nreq, rate = 4, 12, 40.0
        fib_lo, fib_hi = 8, 12
    else:
        lanes = int(os.environ.get("ELASTIC_LANES", 4))
        nreq = int(os.environ.get("ELASTIC_REQUESTS", 36))
        rate = float(os.environ.get("ELASTIC_RATE", 18.0))
        fib_lo, fib_hi = 8, 14
    heartbeat_s = 0.1
    join_at, reshard_at, leave_at = nreq // 3, nreq // 2, (2 * nreq) // 3

    def fresh_conf():
        conf = Configure()
        conf.batch.steps_per_launch = 128
        conf.batch.value_stack_depth = 64
        conf.batch.call_stack_depth = 32
        conf.hv.max_virtual_lanes = 3 * lanes
        return conf

    def fleet_cfg(peers=()):
        return FleetConfig(peers=peers, heartbeat_s=heartbeat_s,
                           suspect_after=2, dead_after=3,
                           backoff_base_s=0.02, request_timeout_s=5.0)

    t0 = time.perf_counter()
    # seeded churn weather on the seed gateway: dropped gossip merges
    # must only DELAY convergence
    inj = FaultInjector(churn_schedule(seed, gossip_drops=2, max_at=4))
    svc_a = GatewayService(conf=fresh_conf(), lanes=lanes,
                           devices=jax.devices()[:2], faults=inj,
                           fleet=fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=fresh_conf(), lanes=lanes,
        fleet=fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    a = {"host": gw_a.host, "port": gw_a.port}
    b = {"host": gw_b.host, "port": gw_b.port}
    c = None          # joins mid-stream
    gw_c = None

    st, doc, _ = _gateway_rpc(a["host"], a["port"], "POST",
                              "/v1/modules?name=fib", body=build_fib(),
                              headers={"Content-Type":
                                       "application/wasm"},
                              timeout=180.0)
    assert st == 201, (st, doc)
    deadline = _time.monotonic() + 120.0
    replicated = False
    while _time.monotonic() < deadline:
        st, doc, _ = _gateway_rpc(b["host"], b["port"], "GET",
                                  "/v1/status", timeout=30.0)
        if st == 200 and "fib" in (doc.get("modules") or {}):
            replicated = True
            break
        _time.sleep(0.05)

    accepted = {}            # id -> (fib arg, accepting peer dict)
    rejected_mr = []
    transport_errors = [0]
    outcomes = {}
    lock = threading.Lock()
    stop_poll = threading.Event()
    b_gone = threading.Event()

    def poll_at(peer, rid):
        try:
            return _gateway_rpc(peer["host"], peer["port"], "GET",
                                f"/v1/requests/{rid}", timeout=30.0)
        except OSError:
            return None, None, None

    def poll_once(rid):
        _, (n, peer) = rid, accepted[rid]
        if peer is b and b_gone.is_set():
            peer = a          # departed peer's ids adopt to survivors
        st, doc, _ = poll_at(peer, rid)
        if st == 404 and isinstance(doc, dict):
            # r21 poll redirection: follow the machine-readable
            # owner_hint instead of blind survivor polling
            hint = (doc.get("err") or {}).get("owner_hint")
            url = (hint or {}).get("url", "")
            if ":" in url:
                host, _, port = url.rpartition(":")
                try:
                    st, doc, _ = poll_at({"host": host,
                                          "port": int(port)}, rid)
                except ValueError:
                    return False
        if st in (None, 404) or not isinstance(doc, dict) \
                or doc.get("status") == "pending":
            return False
        with lock:
            outcomes.setdefault(rid, (st, doc))
        return True

    def poller():
        while not stop_poll.is_set():
            with lock:
                todo = [r for r in accepted if r not in outcomes]
            if not todo:
                _time.sleep(0.02)
                continue
            for rid in todo:
                poll_once(rid)
                if stop_poll.is_set():
                    return
            _time.sleep(0.01)

    pollers = [threading.Thread(target=poller, daemon=True)
               for _ in range(1 if smoke else 2)]
    for t in pollers:
        t.start()

    def submit(peer, n):
        for _ in range(8):
            try:
                st, doc, after = _gateway_rpc(
                    peer["host"], peer["port"], "POST",
                    "/v1/invoke?async=1",
                    body={"module": "fib", "func": "fib",
                          "args": [int(n)]}, timeout=30.0)
            except OSError:
                transport_errors[0] += 1
                return None
            if st == 202 and isinstance(doc, dict):
                with lock:
                    accepted[doc["request_id"]] = (int(n), peer)
                return doc["request_id"]
            err = doc.get("err") if isinstance(doc, dict) else None
            if isinstance(err, dict) and err.get("retryable"):
                rejected_mr.append((st, err.get("name"),
                                    err.get("detail")))
                _time.sleep(min(float(after or 0.2), 0.3))
                continue
            if isinstance(err, dict):
                rejected_mr.append((st, err.get("name"),
                                    err.get("detail")))
                return None
            transport_errors[0] += 1
            return None

    rng = np.random.RandomState(seed)
    args_stream = rng.randint(fib_lo, fib_hi + 1, size=nreq)
    joined = resharded = left = False
    join_first_202_s = None
    join_to_servable_s = None
    reshard_reply = None
    t_sched0 = _time.monotonic()
    for i, n in enumerate(args_stream):
        t_sched = t_sched0 + i / rate
        now = _time.monotonic()
        if t_sched > now:
            _time.sleep(t_sched - now)
        if i == join_at and not joined:
            # -- THE join: C announces itself to seed A only ----------
            svc_c = GatewayService(
                conf=fresh_conf(), lanes=lanes,
                fleet=fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
            gw_c = Gateway(svc_c, port=0).start()
            c = {"host": gw_c.host, "port": gw_c.port}
            t_join = _time.monotonic()
            # module sync rides C's first heartbeat; "takes traffic
            # within one heartbeat" is measured from servable (module
            # synced + generation built) to the first accepted 202 —
            # a burst de-flakes the measurement
            sv_deadline = _time.monotonic() + 180.0
            while _time.monotonic() < sv_deadline:
                st, doc, _ = _gateway_rpc(c["host"], c["port"], "GET",
                                          "/v1/status", timeout=30.0)
                # servable = module synced AND a serving generation
                # swapped in ("serve" counters only exist with one)
                if st == 200 and "fib" in (doc.get("modules") or {}) \
                        and "serve" in doc:
                    break
                _time.sleep(0.01)
            t_servable = _time.monotonic()
            join_to_servable_s = t_servable - t_join
            for _ in range(20):
                if submit(c, int(n)) is not None:
                    join_first_202_s = _time.monotonic() - t_servable
                    break
            joined = True
            continue
        if i == reshard_at and not resharded:
            # -- THE reshard: A grows 2 -> 4 devices, lanes resident --
            st, reshard_reply, _ = _gateway_rpc(
                a["host"], a["port"], "POST", "/v1/reshard",
                body={"devices": 4}, timeout=300.0)
            resharded = st == 200 and isinstance(reshard_reply, dict) \
                and bool(reshard_reply.get("ok"))
        if i == leave_at and not left:
            # -- THE leave: B says goodbye, drains, and goes ----------
            st, doc, _ = _gateway_rpc(b["host"], b["port"], "POST",
                                      "/v1/fleet/leave", body={},
                                      timeout=30.0)
            left = st == 200 and isinstance(doc, dict) \
                and bool(doc.get("ok"))
            gw_b.shutdown(drain=True, timeout_s=120.0)
            b_gone.set()
        peers_live = [a] + ([c] if joined and c else []) \
            + ([] if b_gone.is_set() else [b])
        submit(peers_live[i % len(peers_live)], n)

    deadline = _time.monotonic() + (180.0 if smoke else 420.0)
    while _time.monotonic() < deadline:
        with lock:
            if len(outcomes) == len(accepted):
                break
        _time.sleep(0.05)
    stop_poll.set()
    for t in pollers:
        t.join(timeout=5.0)

    def fibv(n):
        x, y = 0, 1
        for _ in range(n):
            x, y = y, x + y
        return x

    stable = lost = resolved = wrong = 0
    for rid, (n, _peer) in accepted.items():
        first = outcomes.get(rid)
        if first is None:
            lost += 1
            continue
        poll_once(rid)      # idempotent re-read through the same path
        peer = a if _peer is b and b_gone.is_set() else _peer
        st, doc, _ = poll_at(peer, rid)
        if st == 404 and isinstance(doc, dict):
            hint = (doc.get("err") or {}).get("owner_hint")
            url = (hint or {}).get("url", "")
            if ":" in url:
                host, _, port = url.rpartition(":")
                st, doc, _ = poll_at({"host": host,
                                      "port": int(port)}, rid)
        if isinstance(doc, dict) and doc.get("ok") \
                and first[1].get("ok") \
                and doc.get("result") == first[1].get("result"):
            stable += 1
        elif isinstance(doc, dict) and not doc.get("ok") \
                and not first[1].get("ok"):
            stable += 1
        if first[1].get("ok"):
            resolved += 1
            if first[1].get("result") != [fibv(n)]:
                wrong += 1

    st, status_a, _ = _gateway_rpc(a["host"], a["port"], "GET",
                                   "/v1/status", timeout=60.0)
    st_m, metrics_a, _ = _gateway_rpc(a["host"], a["port"], "GET",
                                      "/metrics", timeout=60.0)
    st_c, status_c, _ = _gateway_rpc(c["host"], c["port"], "GET",
                                     "/v1/status", timeout=60.0) \
        if c else (None, {}, None)
    fleet_a = status_a.get("fleet", {}) if isinstance(status_a, dict) \
        else {}
    b_state = fleet_a.get("peer_states", {}).get(
        f"{b['host']}:{b['port']}", {})
    serve_a = status_a.get("serve", {}) if isinstance(status_a, dict) \
        else {}
    if gw_c is not None:
        gw_c.shutdown(drain=True, timeout_s=120.0)
    gw_a.shutdown(drain=True, timeout_s=120.0)
    dt = time.perf_counter() - t0

    checks = {
        "module_replicated_to_peer": replicated,
        "accepted_all_terminal": len(outcomes) == len(accepted),
        "zero_ids_lost": lost == 0,
        "outcomes_stable": stable == len(accepted),
        "results_correct": wrong == 0,
        "peer_joined_mid_stream": joined,
        "join_within_one_heartbeat": join_first_202_s is not None
        and join_first_202_s <= heartbeat_s,
        "joined_peer_completed_requests": isinstance(status_c, dict)
        and int((status_c.get("gateway") or {})
                .get("completed", 0)) >= 1,
        "reshard_applied_live": resharded
        and isinstance(status_a, dict) and status_a.get("devices") == 4
        and int(serve_a.get("reshards", 0)) >= 1,
        "zero_resident_lanes_dropped":
            int(serve_a.get("killed", 0)) == 0
            and int(serve_a.get("trapped", 0)) == 0,
        "peer_left_cleanly": left and b_state.get("left") is True,
        "membership_epoch_advanced":
            int(fleet_a.get("membership_epoch", 0)) >= 3,
        "elastic_metrics_exported":
            "wasmedge_fleet_membership_epoch" in str(metrics_a)
            and "wasmedge_reshards_total" in str(metrics_a),
    }
    ok = all(checks.values())
    out = {
        "metric": "elastic_fleet_smoke" if smoke
        else "elastic_fleet_open_loop",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "seed": seed,
        "lanes_per_peer": lanes,
        "requests": nreq,
        "accepted": len(accepted),
        "rejected_retryable_then_retried": len(rejected_mr),
        "transport_errors": transport_errors[0],
        "resolved_ok": resolved,
        "join_to_servable_s": round(join_to_servable_s, 4)
        if join_to_servable_s is not None else None,
        "join_first_202_s": round(join_first_202_s, 4)
        if join_first_202_s is not None else None,
        "reshard": {k: reshard_reply.get(k) for k in
                    ("devices", "old_devices", "lanes", "old_lanes",
                     "resident", "direction")}
        if isinstance(reshard_reply, dict) else None,
        "gossip_drops_fired": inj.fired,
        "membership_epoch": fleet_a.get("membership_epoch"),
        "adoptions": fleet_a.get("adoptions", 0),
        "wall_s": round(dt, 3),
    }
    if smoke:
        print(json.dumps(out))
        return 0 if ok else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "ELASTIC_r21.json")
    print(json.dumps(out))
    print(f"# elastic peers=2+1 lanes={lanes} reqs={nreq} "
          f"accepted={len(accepted)} lost={lost} "
          f"join_202={join_first_202_s} reshard={resharded} "
          f"epoch={fleet_a.get('membership_epoch')} wall={dt:.1f}s",
          file=sys.stderr)
    return 0 if ok else 1


def coldstart_bench(smoke: bool = False) -> int:
    """`bench.py --coldstart` / `--coldstart-smoke`: the r22 cold-start
    wall.  One gateway with every imagestore knob on registers K
    modules one at a time — the acceptance pins are DETERMINISTIC
    counters, not wall-clock: each module lowers exactly once across
    all K generation builds, each module's image segment builds exactly
    once (the SegmentCache hit count proves every prior segment was
    reused verbatim), and a module with a nontrivial `_initialize`
    returns bit-identical results through the snapshot path and the
    template-init path.  Registration latency per module count and
    snapshot-vs-init-replay p50/p99 ride along as the reported curve.
    Emits COLDSTART_r22.json (smoke: prints one JSON line only)."""
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.gateway import GatewayService
    from wasmedge_tpu.utils.bench_artifact import percentile
    from wasmedge_tpu.utils.builder import ModuleBuilder

    nmod = 3 if smoke else 8
    nreq = 4 if smoke else 24

    def _conf(segmented=False, compile_cache=False, snapshots=False):
        conf = Configure()
        conf.batch.steps_per_launch = 256
        conf.batch.value_stack_depth = 128
        conf.batch.call_stack_depth = 64
        conf.imagestore.segmented = segmented
        conf.imagestore.compile_cache = compile_cache
        conf.imagestore.snapshots = snapshots
        return conf

    def build_affine(mul, add):
        b = ModuleBuilder()
        b.add_function(["i64"], ["i64"], [],
                       [("local.get", 0), ("i64.const", mul), "i64.mul",
                        ("i64.const", add), "i64.add"], export="f")
        return b.build()

    def build_lazyinit():
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_global("i32", True, [("i32.const", 0)])
        b.add_global("i64", True, [("i64.const", 0)])
        b.add_function([], [], [],
                       [("i32.const", 1), ("global.set", 0),
                        ("i64.const", 7), ("global.set", 1),
                        ("i32.const", 0), ("i64.const", 42),
                        ("i64.store", 3, 0)], export="_initialize")
        b.add_function(["i64"], ["i64"], [],
                       [("global.get", 0), "i32.eqz",
                        ("if", None), ("call", 0), "end",
                        ("local.get", 0), ("global.get", 1), "i64.add",
                        ("i32.const", 0), ("i64.load", 3, 0),
                        "i64.add"], export="compute")
        return b.build()

    def _invoke(svc, func, args, module):
        req = svc.submit(func, args, module=module, tenant="default")
        assert svc.wait(req, timeout_s=120.0)
        return req.future.result(0)

    t0 = time.perf_counter()
    checks = {}
    svc = GatewayService(conf=_conf(segmented=True, compile_cache=True,
                                    snapshots=True), lanes=4)
    reg_s = []
    snap_lat = []
    try:
        for k in range(nmod):
            t = time.perf_counter()
            svc.register_module(f"m{k}",
                                wasm_bytes=build_affine(2 + k, 3 * k))
            reg_s.append(round(time.perf_counter() - t, 4))
        t = time.perf_counter()
        svc.register_module("lazy", wasm_bytes=build_lazyinit())
        reg_s.append(round(time.perf_counter() - t, 4))
        nregs = nmod + 1
        # the counter pins: registering module N+1 lowered nothing
        # twice and rebuilt no existing segment
        seg = svc.registry.segment_cache.stats()
        checks["lowered_once_each"] = \
            svc.registry.lowered_count == nregs
        checks["segment_builds"] = seg["builds"] == nregs
        checks["segment_hits"] = \
            seg["hits"] == nregs * (nregs - 1) // 2
        checks["snapshot_captured"] = \
            svc.snapshot_counts.get("captured", 0) == 1
        ok_results = True
        for k in range(nmod):
            ok_results &= _invoke(svc, "f", [10], module=f"m{k}") \
                == [10 * (2 + k) + 3 * k]
        checks["affine_results"] = ok_results
        snap_res = []
        for i in range(nreq):
            t = time.perf_counter()
            snap_res.append(
                _invoke(svc, "compute", [i], module="lazy")[0])
            snap_lat.append(time.perf_counter() - t)
        checks["snapshot_installs"] = \
            svc.snapshot_counts.get("installs", 0) >= nreq
    finally:
        svc.shutdown()
    # init-replay reference: same module, every knob off (the r21 path)
    ref = GatewayService(conf=_conf(), lanes=4)
    ref_lat = []
    try:
        ref.register_module("lazy", wasm_bytes=build_lazyinit())
        ref_res = []
        for i in range(nreq):
            t = time.perf_counter()
            ref_res.append(
                _invoke(ref, "compute", [i], module="lazy")[0])
            ref_lat.append(time.perf_counter() - t)
    finally:
        ref.shutdown()
    checks["snapshot_bitidentical"] = snap_res == ref_res
    dt = time.perf_counter() - t0
    ok = all(checks.values())
    snap_lat.sort()
    ref_lat.sort()
    out = {
        "metric": "coldstart_registration_and_snapshot_admission",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "modules": nmod + 1,
        "registration_s": reg_s,
        "registration_last_over_first":
            round(reg_s[-1] / max(reg_s[0], 1e-9), 3),
        "snapshot_p50_s": round(percentile(snap_lat, 0.50), 4),
        "snapshot_p99_s": round(percentile(snap_lat, 0.99), 4),
        "init_replay_p50_s": round(percentile(ref_lat, 0.50), 4),
        "init_replay_p99_s": round(percentile(ref_lat, 0.99), 4),
        "wall_s": round(dt, 3),
    }
    if smoke:
        print(json.dumps(out))
        return 0 if ok else 1
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "COLDSTART_r22.json")
    print(f"# coldstart modules={nmod + 1} reg_s={reg_s} "
          f"snap_p50={out['snapshot_p50_s']} "
          f"replay_p50={out['init_replay_p50_s']} wall={dt:.1f}s",
          file=sys.stderr)
    return 0 if ok else 1


def _build_echo_await():
    """go(n): fd_write "pre|", await_event, fd_write the wake payload
    then "post"; returns payload-length + n.  The stdout stream across
    a park must be byte-identical to a never-parked run."""
    from wasmedge_tpu.utils.builder import ModuleBuilder

    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_active_data(0, [("i32.const", 256)], b"pre|")
    b.add_active_data(0, [("i32.const", 264)], b"post")

    def write(buf_instrs, len_instrs):
        return [
            ("i32.const", 0), *buf_instrs, ("i32.store", 2, 0),
            ("i32.const", 4), *len_instrs, ("i32.store", 2, 0),
            ("i32.const", 1), ("i32.const", 0), ("i32.const", 1),
            ("i32.const", 32), ("call", 0), "drop",
        ]

    b.add_function(["i64"], ["i64"], [], [
        *write([("i32.const", 256)], [("i32.const", 4)]),
        ("i32.const", 64), ("i32.const", 16), ("i32.const", 40),
        ("call", 1), "drop",
        *write([("i32.const", 64)],
               [("i32.const", 40), ("i32.load", 2, 0)]),
        *write([("i32.const", 264)], [("i32.const", 4)]),
        ("i32.const", 40), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="go")
    return b.build()


def suspend_bench(smoke: bool = False) -> int:
    """`bench.py --suspend` / `--suspend-smoke`: the r23 guest
    suspend/resume acceptance (effects/ — parked sessions, external
    wake, streamed output).

    Smoke (CI guard, one JSON line, no artifact): one session parks on
    `wasmedge.await_event` (zero resident lanes while parked), an
    external wake over the wire resolves it, and its streamed stdout
    is byte-identical to a run whose wake pre-delivered — the park is
    invisible in the byte stream.

    Full (emits SUSPEND_r23.json): N sessions hold parked at ~zero
    resident lanes, the parked population survives one gateway
    kill/restart exactly-once (restored as PARKED, nothing re-run),
    and the wake-to-first-output latency distribution is reported."""
    import tempfile as _tempfile
    import time as _time

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.gateway import GatewayService
    from wasmedge_tpu.utils.bench_artifact import percentile

    def _conf():
        conf = Configure()
        conf.batch.steps_per_launch = 128
        conf.batch.value_stack_depth = 64
        conf.batch.call_stack_depth = 16
        conf.effects.suspend = True
        conf.obs.enabled = True
        return conf

    wasm = _build_echo_await()
    t0 = time.perf_counter()
    checks = {}

    if smoke:
        gw, svc = _start_gateway(_conf(), lanes=2)
        try:
            svc.register_module("echoawait", wasm_bytes=wasm,
                                source="boot")
            payload = b"wake-00"
            want = [len(payload) + 7]
            # run A: genuinely parks, then an external wake resolves it
            req_a = svc.submit("go", [7], module="echoawait")
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                if svc.status().get("sessions", {}).get("parked") == 1:
                    break
                _time.sleep(0.01)
            sessions = svc.status().get("sessions", {})
            checks["parked"] = sessions.get("parked") == 1
            # zero resident lanes while parked: the session costs no
            # physical lane, only its SwapStore blob
            checks["zero_resident_while_parked"] = \
                len(svc.current.server._bindings) == 0
            st, doc, _ = _gateway_rpc(
                gw.host, gw.port, "POST",
                f"/v1/requests/{req_a.id}/wake", body=payload)
            checks["wake_202"] = st == 202 and doc.get("ok") is True
            checks["resolved"] = svc.wait(req_a, timeout_s=120.0) \
                and req_a.future.result(0) == want
            st, stream_a, _ = _gateway_rpc(
                gw.host, gw.port, "GET",
                f"/v1/requests/{req_a.id}/stream")
            stream_a = stream_a.encode() \
                if isinstance(stream_a, str) else stream_a
            # run B: wake queued immediately (pre-delivery) — whether
            # or not it briefly parks, the byte stream must match
            req_b = svc.submit("go", [7], module="echoawait")
            svc.wake(req_b.id, payload)
            checks["resolved_predelivered"] = \
                svc.wait(req_b, timeout_s=120.0) \
                and req_b.future.result(0) == want
            st, stream_b, _ = _gateway_rpc(
                gw.host, gw.port, "GET",
                f"/v1/requests/{req_b.id}/stream")
            stream_b = stream_b.encode() \
                if isinstance(stream_b, str) else stream_b
            checks["stream_bytes_identical"] = \
                stream_a == stream_b == b"pre|" + payload + b"post"
        finally:
            gw.shutdown()
        ok = all(checks.values())
        print(json.dumps({
            "metric": "suspend_smoke_park_wake_stream",
            "value": 1 if ok else 0, "unit": "ok", "ok": ok,
            **checks, "wall_s": round(time.perf_counter() - t0, 3)}))
        return 0 if ok else 1

    # ---- full: N parked at ~zero resident lanes, kill/restart
    # exactly-once, wake-to-first-output latency
    nsess = 12
    lanes = 4
    payloads = [("wake-%02d" % i).encode() for i in range(nsess)]
    stale = _tempfile.mkdtemp(prefix="suspend-bench-")
    svc = GatewayService(conf=_conf(), lanes=lanes, state_dir=stale)
    svc.register_module("echoawait", wasm_bytes=wasm, source="boot")
    ids = [svc.submit("go", [10 + i], module="echoawait").id
           for i in range(nsess)]
    deadline = _time.monotonic() + 180
    while _time.monotonic() < deadline:
        if svc.status().get("sessions", {}).get("parked") == nsess:
            break
        _time.sleep(0.02)
    sessions = svc.status().get("sessions", {})
    checks["parked_at_scale"] = sessions.get("parked") == nsess
    resident = len(svc.current.server._bindings)
    checks["zero_resident_while_parked"] = resident == 0
    # cadence-1 serve checkpoint (state_dir forces it) lands at the
    # parking round's boundary; give the drive loop a beat to write it
    _time.sleep(0.5)
    svc.kill()

    svc2 = GatewayService(conf=_conf(), lanes=lanes, state_dir=stale,
                          resume=True)
    gw = None
    wake_lat = []
    try:
        from wasmedge_tpu.gateway import Gateway

        gw = Gateway(svc2, host="127.0.0.1", port=0).start()
        sessions = svc2.status().get("sessions", {})
        # exactly-once restore: the whole population is back PARKED
        # (parks==0 on the new process — nothing re-ran from scratch)
        checks["restore_parked_population"] = \
            sessions.get("parked") == nsess
        checks["restore_exactly_once"] = sessions.get("parks") == 0
        checks["restart_counted"] = svc2.counters["restarts"] == 1
        ok_first = True
        for i, rid in enumerate(ids):
            buf = svc2.stream_of(rid)
            start = buf.end if buf is not None else 0
            t = time.perf_counter()
            st, doc, _ = _gateway_rpc(
                gw.host, gw.port, "POST",
                f"/v1/requests/{rid}/wake", body=payloads[i])
            if st != 202:
                ok_first = False
                break
            lat = None
            while time.perf_counter() - t < 60:
                buf = buf if buf is not None else svc2.stream_of(rid)
                if buf is None:
                    _time.sleep(0.002)
                    continue
                data, nxt, closed = buf.read(start, timeout=0.05)
                if data:
                    lat = time.perf_counter() - t
                    break
                if closed:
                    break
            if lat is None:
                ok_first = False
                break
            wake_lat.append(lat)
        checks["wake_first_output"] = ok_first \
            and len(wake_lat) == nsess
        ok_res = True
        ok_stream = True
        for i, rid in enumerate(ids):
            state, req = svc2.request_state(rid)
            ok_res &= state == "ok" and svc2.wait(req, timeout_s=120.0) \
                and req.future.result(0) == [len(payloads[i]) + 10 + i]
            buf = svc2.stream_of(rid)
            # pre-park bytes were streamed (and flushed) before the
            # kill — the restored stream replays from the restore
            # point, so the post-wake suffix is the contract here
            # (at-least-once scoping, README "Durable sessions")
            data = b""
            if buf is not None:
                off = 0
                while True:
                    chunk, off, closed = buf.read(off, timeout=0.2)
                    if chunk:
                        data += chunk
                    elif closed or chunk == b"":
                        break
            ok_stream &= data.endswith(payloads[i] + b"post")
        checks["results_exact"] = ok_res
        checks["streams_post_wake_exact"] = ok_stream
        sessions = svc2.status().get("sessions", {})
        checks["all_resumed"] = sessions.get("parked") == 0 \
            and sessions.get("resumes") == nsess
    finally:
        if gw is not None:
            gw.shutdown()
        else:
            svc2.shutdown()
    dt = time.perf_counter() - t0
    ok = all(checks.values())
    wake_lat.sort()
    out = {
        "metric": "suspend_park_wake_durability",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "sessions": nsess,
        "lanes": lanes,
        "resident_lanes_during_park": resident,
        "wake_to_first_output_p50_s":
            round(percentile(wake_lat, 0.50), 4) if wake_lat else None,
        "wake_to_first_output_p99_s":
            round(percentile(wake_lat, 0.99), 4) if wake_lat else None,
        "wall_s": round(dt, 3),
    }
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "SUSPEND_r23.json")
    print(f"# suspend sessions={nsess} lanes={lanes} "
          f"resident_during_park={resident} "
          f"wake_p50={out['wake_to_first_output_p50_s']} "
          f"wake_p99={out['wake_to_first_output_p99_s']} "
          f"wall={dt:.1f}s", file=sys.stderr)
    return 0 if ok else 1


def integrity_bench(smoke: bool = False) -> int:
    """`bench.py --integrity` / `--integrity-smoke`: the r24 silent-
    data-corruption defense acceptance (wasmedge_tpu/integrity/ —
    shadow-audit lanes, at-rest scrubbing, quarantine).

    Smoke (CI guard, one JSON line, no artifact): ONE injected bit
    flip per storage class — a BatchState lane plane, a SwapStore
    payload, a checkpoint member, a compile-cache entry — and every
    one is detected (audit divergence / scrub verdict), with the
    final results bit-identical to an unflipped run.

    Full (emits INTEGRITY_r24.json): the seeded `bitflip_campaign`
    drives every class twice with distinct seeds/arrivals; every flip
    must be detected AND repaired-or-masked (mirror heal, peer-replica
    restore, quarantine + older-member resume, evict + fresh lower) —
    zero silent corruptions — and the audited flagship stays within
    10% of the audit-off throughput."""
    import hashlib as _hashlib
    import tempfile as _tempfile

    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.supervisor import BatchSupervisor
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.hv.swapstore import SwapStore
    from wasmedge_tpu.imagestore.compilecache import CompileCache
    from wasmedge_tpu.integrity import Scrubber
    from wasmedge_tpu.testing.faults import (
        BitFlip,
        FaultInjector,
        bitflip_campaign,
        flip_bit_bytes,
        flip_file,
    )

    lanes = 16

    def _conf(audit=False, **integ):
        c = Configure()
        c.batch.steps_per_launch = 100
        c.batch.rng_seed = 7
        c.supervisor.backoff_base_s = 0.0
        c.supervisor.checkpoint_every_steps = 200
        c.integrity.audit = audit
        if audit:
            # detection legs audit every boundary at FULL width: the
            # campaign's guarantee is "every flip detected", so the
            # sampled subset must always contain the flipped lane
            # (audit.py: full-width audits are positional, never skip)
            c.integrity.audit_every = 1
            c.integrity.audit_lanes = lanes
        for k, v in integ.items():
            setattr(c.integrity, k, v)
        return c

    def fib_sup(c, faults=None, ckpt_dir=None, resume=False):
        inst, store = _instantiate_fib(c)
        eng = BatchEngine(inst, store=store, conf=c, lanes=lanes)
        return BatchSupervisor(eng, faults=faults,
                               checkpoint_dir=ckpt_dir, resume=resume)

    fib_args = [(np.arange(lanes) % 11).astype(np.int64)]
    want = np.array([_fib(n % 11) for n in range(lanes)])

    def plane_leg(seed, at, checks, tag):
        """Audited run vs an injected lane-plane flip: detected (audit
        divergence -> integrity FailureRecord) and masked (rollback +
        re-execution, exact results)."""
        inj = FaultInjector([], flips=[
            BitFlip(point="corrupt_plane", at=at, seed=seed)])
        d = _tempfile.mkdtemp(prefix="integrity-plane-")
        sup = fib_sup(_conf(audit=True), faults=inj, ckpt_dir=d)
        res = sup.run("fib", fib_args, max_steps=500_000)
        stats = sup.engine._audit_hook.stats
        checks[f"{tag}_flipped"] = inj.flipped == 1
        checks[f"{tag}_detected"] = stats["divergence"] >= 1 and \
            "integrity" in [f.fault_class for f in sup.failures]
        checks[f"{tag}_masked"] = bool(
            res.completed.all() and (res.results[0] == want).all())

    def swap_leg(seed, checks, tag, both_copies=False):
        """SwapStore rot: a bad memory copy heals from the disk
        mirror; rot in BOTH copies repairs from a (peer-replica)
        fetch closure — either way the payload reads back bit-exact."""
        d = _tempfile.mkdtemp(prefix="integrity-swap-")
        store = SwapStore(dir=d)
        payload = np.random.RandomState(seed).bytes(4096)
        key = store.put(payload)
        replica = {key: payload}
        store._mem[key] = flip_bit_bytes(store._mem[key], seed=seed)
        if both_copies:
            flip_file(store._path(key), seed=seed + 1)
        scrub = Scrubber(
            Configure().integrity,
            swap_stores=lambda: [("swap", store, False)],
            fetch_blob=replica.get)
        delta = scrub.scrub_once()
        checks[f"{tag}_detected"] = delta["corrupt"] == 1
        checks[f"{tag}_repaired"] = delta["repaired"] == 1
        checks[f"{tag}_bit_identical"] = store.get(key) == payload

    def checkpoint_leg(seed, checks, tag):
        """A rotted newest checkpoint member is quarantined by the
        scrubber; a resume over the same lineage falls back to the
        older member and completes bit-exact."""
        d = _tempfile.mkdtemp(prefix="integrity-ckpt-")
        sup = fib_sup(_conf(), ckpt_dir=d)
        sup.run("fib", fib_args, max_steps=500_000)
        members = sorted(_os.path.join(d, fn) for fn in _os.listdir(d)
                         if fn.endswith(".npz"))
        checks[f"{tag}_has_lineage"] = len(members) >= 1
        flip_file(members[-1], seed=seed)
        scrub = Scrubber(Configure().integrity,
                         checkpoints=lambda: members)
        delta = scrub.scrub_once()
        checks[f"{tag}_detected"] = delta["quarantined_members"] == 1 \
            and not _os.path.exists(members[-1])
        sup2 = fib_sup(_conf(), ckpt_dir=d, resume=True)
        res = sup2.run("fib", fib_args, max_steps=500_000)
        checks[f"{tag}_masked"] = bool(
            res.completed.all() and (res.results[0] == want).all())

    def cache_leg(seed, checks, tag, peer_repair=False):
        """A rotted WTIC entry is caught by the scrub verify; with a
        peer replica it restores bit-exact, without one it is evicted
        so the next load is a clean miss (fresh lower, never rot)."""
        d = _tempfile.mkdtemp(prefix="integrity-cache-")
        cc = CompileCache()
        cc.enable(d)
        payload = np.random.RandomState(seed + 1).bytes(2048)
        sha = _hashlib.sha256(payload).hexdigest()
        cc.store(sha, payload)
        replica = {sha: cc.entry_bytes(sha)} if peer_repair else {}
        flip_file(cc._path(sha), seed=seed)
        with cc._lock:
            cc._payloads.pop(sha, None)
        checks[f"{tag}_detected"] = not cc.verify_entry(sha)
        scrub = Scrubber(Configure().integrity,
                         compile_cache=lambda: cc,
                         fetch_cache_entry=replica.get)
        delta = scrub.scrub_once()
        if peer_repair:
            checks[f"{tag}_repaired"] = delta["repaired"] == 1 and \
                cc.load(sha) == payload
        else:
            checks[f"{tag}_evicted"] = delta["evicted"] == 1 and \
                cc.load(sha) is None   # clean miss -> fresh lower

    t0 = time.perf_counter()
    checks = {}

    if smoke:
        plane_leg(seed=42, at=1, checks=checks, tag="plane")
        swap_leg(seed=7, checks=checks, tag="swap")
        checkpoint_leg(seed=13, checks=checks, tag="checkpoint")
        cache_leg(seed=29, checks=checks, tag="cache")
        ok = all(checks.values())
        print(json.dumps({
            "metric": "integrity_smoke_flip_per_class",
            "value": 1 if ok else 0, "unit": "ok", "ok": ok,
            **checks, "wall_s": round(time.perf_counter() - t0, 3)}))
        return 0 if ok else 1

    # ---- full: seeded campaign over every storage class ------------------
    campaign = bitflip_campaign(seed=1234, n_per_class=2)
    for f in campaign:
        tag = f"{f['cls']}{f['index']}"
        if f["cls"] == "plane":
            plane_leg(seed=f["seed"], at=f["at"], checks=checks, tag=tag)
        elif f["cls"] == "swap":
            swap_leg(seed=f["seed"], checks=checks, tag=tag,
                     both_copies=bool(f["index"] % 2))
        elif f["cls"] == "checkpoint":
            checkpoint_leg(seed=f["seed"], checks=checks, tag=tag)
        elif f["cls"] == "cache":
            cache_leg(seed=f["seed"], checks=checks, tag=tag,
                      peer_repair=bool(f["index"] % 2))
    detected = sum(1 for k, v in checks.items()
                   if k.endswith("_detected") and v)
    silent = sum(1 for k, v in checks.items()
                 if k.endswith(("_detected", "_masked", "_repaired",
                                "_evicted")) and not v)

    # ---- integrity-off bit-identity + audit-on throughput ratio ----------
    def timed_run(sup, reps=3):
        sup.run("work", perf_args, max_steps=5_000_000)  # warm compile
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            r = sup.run("work", perf_args, max_steps=5_000_000)
            best = min(best, time.perf_counter() - t)
        return best, r

    # long runs over MANY boundaries, so the sampled audit cadence
    # (~1/audit_every of boundaries, each replaying one slice at
    # audit_lanes width) is what the ratio measures — not one audit
    # landing in a three-launch run.  The summation module gives each
    # lane tens of thousands of steps where fib tops out at hundreds.
    def work_sup(audit=False):
        from wasmedge_tpu.executor import Executor
        from wasmedge_tpu.loader import Loader
        from wasmedge_tpu.runtime.store import StoreManager
        from wasmedge_tpu.testing.faults import build_selective_runaway
        from wasmedge_tpu.validator import Validator

        c = _conf()
        c.batch.steps_per_launch = 400
        c.integrity.audit = audit
        mod = Validator(c).validate(
            Loader(c).parse_module(build_selective_runaway()))
        store = StoreManager()
        inst = Executor(c).instantiate(store, mod)
        eng = BatchEngine(inst, store=store, conf=c, lanes=lanes)
        return BatchSupervisor(eng)

    perf_ns = 6000 + 137 * np.arange(lanes)
    perf_args = [perf_ns.astype(np.int64)]
    perf_want = np.array([int(n) * (int(n) - 1) // 2 for n in perf_ns])
    off_sup = work_sup()
    off_s, off_res = timed_run(off_sup)
    # flagship audit cadence: the DEFAULT sampled knobs (audit_every=16,
    # audit_lanes=2), not the every-boundary setting the detection legs use
    on_sup = work_sup(audit=True)
    on_s, on_res = timed_run(on_sup)
    ratio = on_s / off_s if off_s > 0 else float("inf")
    on_stats = dict(on_sup.engine._audit_hook.stats)
    checks["audit_sampled_nonzero"] = on_stats["audits"] >= 1
    checks["integrity_off_no_hooks"] = \
        getattr(off_sup.engine, "_audit_hook", None) is None and \
        getattr(off_sup.engine, "_flip_hook", None) is None
    checks["audit_on_bit_identical"] = bool(
        (on_res.results[0] == off_res.results[0]).all()
        and (on_res.results[0] == perf_want).all()
        and (on_res.trap == off_res.trap).all()
        and (on_res.retired == off_res.retired).all())
    checks["audit_overhead_within_10pct"] = ratio <= 1.10

    dt = time.perf_counter() - t0
    ok = all(checks.values()) and silent == 0
    out = {
        "metric": "integrity_sdc_defense",
        "value": 1 if ok else 0,
        "unit": "ok",
        "ok": ok,
        **checks,
        "campaign_flips": len(campaign),
        "campaign_detected": detected,
        "silent_corruptions": silent,
        "audit_off_s": round(off_s, 4),
        "audit_on_s": round(on_s, 4),
        "audit_boundaries": on_stats["boundaries"],
        "audits_sampled": on_stats["audits"],
        "audit_overhead_ratio": round(ratio, 4),
        "wall_s": round(dt, 3),
    }
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "INTEGRITY_r24.json")
    print(f"# integrity flips={len(campaign)} detected={detected} "
          f"silent={silent} audit_overhead={ratio:.3f} "
          f"wall={dt:.1f}s", file=sys.stderr)
    return 0 if ok else 1


def main():
    eng = _build(LANES)

    # Warm up: compile the kernel + result path.
    eng.run("fib", [np.full(LANES, WARMUP_N, np.int64)],
            max_steps=10_000_000)

    t0 = time.perf_counter()
    res = eng.run("fib", [np.full(LANES, FIB_N, np.int64)],
                  max_steps=500_000_000)
    dt = time.perf_counter() - t0

    if not res.completed.all():
        print(json.dumps({"metric": "bench_failed",
                          "value": 0, "unit": "", "vs_baseline": 0}))
        sys.exit(1)
    expected = _fib(FIB_N)
    if not (res.results[0] == expected).all():
        print(json.dumps({"metric": "bench_wrong_result",
                          "value": 0, "unit": "", "vs_baseline": 0}))
        sys.exit(1)

    total_retired = float(np.asarray(res.retired, np.float64).sum())
    agg_ops = total_retired / dt
    base_ops, base_src = _native_baseline_ops()
    vs = agg_ops / (TARGET_MULTIPLE * base_ops)

    engine = "pallas" if getattr(eng, "pallas", None) is not None else "xla"
    import jax

    out = {
        "metric": f"aggregate_wasm_ops_per_sec_fib{FIB_N}_x{LANES}",
        "value": round(agg_ops, 1),
        "unit": "wasm_instr/s",
        "vs_baseline": round(vs, 4),
        "engine": engine,
        "backend": jax.default_backend(),
        "fib_n": FIB_N,
        "lanes": LANES,
        "obs": bool(eng.obs.enabled),
        "steps": int(res.steps),
        "wall_s": round(dt, 3),
        "baseline_ops_per_sec": round(base_ops, 1),
        "baseline_source": base_src,
    }
    from wasmedge_tpu.utils.bench_artifact import emit

    emit(out, "BENCH_r15.json")
    _emit_trace(eng.obs, "BENCH_r15.trace.json")
    # extra context on stderr (driver only parses stdout JSON)
    print(f"# engine={engine} lanes={LANES} steps={res.steps} wall={dt:.2f}s "
          f"retired_total={total_retired:.3g} baseline={base_ops:.3g} "
          f"({base_src}) target={TARGET_MULTIPLE}x", file=sys.stderr)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


if __name__ == "__main__":
    if "--faults-smoke" in sys.argv[1:]:
        sys.exit(faults_smoke())
    if "--mesh-faults-smoke" in sys.argv[1:]:
        sys.exit(mesh_faults_smoke())
    if "--mesh-smoke" in sys.argv[1:]:
        sys.exit(mesh_smoke())
    if "--mesh-bench" in sys.argv[1:]:
        sys.exit(mesh_bench())
    if "--trace-smoke" in sys.argv[1:]:
        sys.exit(trace_smoke())
    if "--serve-smoke" in sys.argv[1:]:
        sys.exit(serve_bench(smoke=True))
    if "--serve" in sys.argv[1:]:
        sys.exit(serve_bench())
    if "--analyze-smoke" in sys.argv[1:]:
        sys.exit(analyze_smoke())
    if "--fuse-smoke" in sys.argv[1:]:
        sys.exit(fuse_smoke())
    if "--fuse-bench" in sys.argv[1:]:
        sys.exit(fuse_bench())
    if "--memfuse-smoke" in sys.argv[1:]:
        sys.exit(memfuse_smoke())
    if "--memfuse-bench" in sys.argv[1:]:
        sys.exit(memfuse_bench())
    if "--tierup-smoke" in sys.argv[1:]:
        sys.exit(tierup_smoke())
    if "--tierup-bench" in sys.argv[1:]:
        sys.exit(tierup_bench())
    if "--compact-smoke" in sys.argv[1:]:
        sys.exit(compact_smoke())
    if "--compact-bench" in sys.argv[1:]:
        sys.exit(compact_bench())
    if "--gateway-smoke" in sys.argv[1:]:
        sys.exit(gateway_smoke())
    if "--gateway" in sys.argv[1:]:
        sys.exit(gateway_bench())
    if "--chaos-smoke" in sys.argv[1:]:
        sys.exit(chaos_bench(smoke=True))
    if "--chaos" in sys.argv[1:]:
        sys.exit(chaos_bench())
    if "--federation-smoke" in sys.argv[1:]:
        sys.exit(federation_bench(smoke=True))
    if "--federation" in sys.argv[1:]:
        sys.exit(federation_bench())
    if "--oversub-smoke" in sys.argv[1:]:
        sys.exit(oversub_bench(smoke=True))
    if "--oversub" in sys.argv[1:]:
        sys.exit(oversub_bench())
    if "--elastic-smoke" in sys.argv[1:]:
        sys.exit(elastic_bench(smoke=True))
    if "--elastic" in sys.argv[1:]:
        sys.exit(elastic_bench())
    if "--coldstart-smoke" in sys.argv[1:]:
        sys.exit(coldstart_bench(smoke=True))
    if "--coldstart" in sys.argv[1:]:
        sys.exit(coldstart_bench())
    if "--suspend-smoke" in sys.argv[1:]:
        sys.exit(suspend_bench(smoke=True))
    if "--suspend" in sys.argv[1:]:
        sys.exit(suspend_bench())
    if "--integrity-smoke" in sys.argv[1:]:
        sys.exit(integrity_bench(smoke=True))
    if "--integrity" in sys.argv[1:]:
        sys.exit(integrity_bench())
    main()
