"""AOT warm-start measurement: process start -> first retired instruction.

The reference loads AOT artifacts with dlopen
(/root/reference/lib/loader/shared_library.cpp:52) — milliseconds.  Our
tpu.aot artifact carries the lowered image + fused Pallas encoding;
the XLA executable itself is content-addressed in the persistent
compilation cache.  This script measures a FRESH PROCESS running
fib(20)x4096 from a prebuilt artifact, with per-phase attribution
(interpreter+jax import, backend init, engine build incl. kernel
trace, compile/load, first launch), cold (empty cache) vs warm.

Prints ONE JSON line (AOT_r04.json shape).
"""

import json
import os
import subprocess
import sys
import time

CHILD = r"""
import json, os, sys, time
t0 = time.perf_counter()
sys.path.insert(0, os.getcwd())
import numpy as np
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.validator import Validator
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.runtime.store import StoreManager
t_imp = time.perf_counter()
from wasmedge_tpu.batch import ensure_jax_backend
ensure_jax_backend()
import jax
jax.devices()
t_dev = time.perf_counter()
conf = Configure()
conf.batch.steps_per_launch = 2_000_000
conf.batch.value_stack_depth = 128
conf.batch.call_stack_depth = 64
with open(sys.argv[1], "rb") as f:
    tw = f.read()
mod = Validator(conf).validate(Loader(conf).parse_module(tw))
st = StoreManager()
inst = Executor(conf).instantiate(st, mod)
t_load = time.perf_counter()
from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine
eng = PallasUniformEngine(inst, store=st, conf=conf, lanes=4096)
eng._build()
t_build = time.perf_counter()
res = eng.run("fib", [np.full(4096, 20, np.int64)], max_steps=50_000_000)
t_run = time.perf_counter()
ok = bool((np.asarray(res.results[0]) == 6765).all())

# resident-runtime warm start: the serverless hot path is a RESIDENT
# runtime scaling out a function whose kernel is already device-loaded
# (the reference's dlopen-speed expectation is likewise in-process).
# Measure: artifact bytes -> fresh instance + engine -> first retired
# instruction, inside the live process.
t_res0 = time.perf_counter()
mod2 = Validator(conf).validate(Loader(conf).parse_module(tw))
st2 = StoreManager()
inst2 = Executor(conf).instantiate(st2, mod2)
eng2 = PallasUniformEngine(inst2, store=st2, conf=conf, lanes=4096)
res2 = eng2.run("fib", [np.full(4096, 20, np.int64)],
                max_steps=50_000_000)
t_res1 = time.perf_counter()
ok2 = bool((np.asarray(res2.results[0]) == 6765).all())
print(json.dumps({
    "ok": ok and ok2,
    "import_s": round(t_imp - t0, 3),
    "backend_init_s": round(t_dev - t_imp, 3),
    "artifact_load_s": round(t_load - t_dev, 3),
    "engine_build_s": round(t_build - t_load, 3),
    "first_run_s": round(t_run - t_build, 3),
    "total_s": round(t_run - t0, 3),
    "resident_warm_s": round(t_res1 - t_res0, 3),
    "post_first_s": round(t_res1 - t_run, 3),
}))
"""


def run_child(twasm_path):
    t0 = time.perf_counter()
    r = subprocess.run([sys.executable, "-c", CHILD, twasm_path],
                       capture_output=True, text=True, timeout=900,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
    wall = time.perf_counter() - t0
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    if not line:
        raise RuntimeError(f"child failed: {r.stderr[-2000:]}")
    out = json.loads(line[-1])
    # headline walls measure process start -> FIRST retired instruction
    # (AOT_r04 comparable); the resident re-run's time is subtracted
    out["process_wall_s"] = round(wall - out.get("post_first_s", 0.0), 3)
    return out


def main():
    import shutil

    from wasmedge_tpu import aot
    from wasmedge_tpu.models import build_fib

    tw = aot.compile_module(build_fib())
    path = "/tmp/fib.twasm"
    with open(path, "wb") as f:
        f.write(tw)
    from wasmedge_tpu.aot import cache_dir

    xla_cache = os.environ.get("WASMEDGE_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "wasmedge_tpu", "xla")
    shutil.rmtree(xla_cache, ignore_errors=True)
    shutil.rmtree(os.path.join(cache_dir(), "kexport"), ignore_errors=True)
    # interpreter spawn floor: this environment's sitecustomize imports
    # jax submodules at EVERY python start (~2s) — attribute it so the
    # fresh-process number can be read against it
    t0 = time.perf_counter()
    subprocess.run([sys.executable, "-c", "pass"], capture_output=True)
    spawn_floor = round(time.perf_counter() - t0, 3)
    cold = run_child(path)
    # the tunneled device link is shared and noisy (measured 2.8-7.1 s
    # for the identical warm first launch); report the best of 3 as the
    # uncontended warm number and keep the spread
    warms = [run_child(path) for _ in range(3)]
    warm = min(warms, key=lambda w: w["process_wall_s"])
    out = {
        "metric": "pallas_cold_start_seconds",
        "cold": cold["process_wall_s"],
        "warm_fresh_process": warm["process_wall_s"],
        "warm_fresh_spread": [w["process_wall_s"] for w in warms],
        "warm_resident": min(w.get("resident_warm_s") for w in warms),
        "python_spawn_floor_s": spawn_floor,
        "unit": "s",
        "cold_phases": cold,
        "warm_phases": warm,
        "note": "fib(20) x4096 from a tpu.aot artifact.  warm_resident "
                "is the serverless hot path: a resident runtime "
                "instantiating the artifact and retiring its first "
                "instruction with the kernel already device-loaded "
                "(the in-process analog of the reference's dlopen-speed "
                "AOT load); warm_fresh_process additionally pays the "
                "python+jax interpreter start and the XLA executable "
                "upload over the tunneled device link.",
    }
    print(json.dumps(out))
    with open("AOT_r05.json", "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
