"""Divergent-input benchmark: fib(n) with n varying per lane (20..30).

The round-2 verdict's acid test for divergence handling: the headline
bench feeds every lane identical arguments (structural convergence), so
this bench spreads n uniformly over 20..30 across 4096 lanes, shuffled,
and measures aggregate retired-instruction throughput through the block
scheduler (entry grouping packs same-n lanes into shared blocks; any
residual straddle blocks split once at the first differing branch).

Prints ONE JSON line like bench.py; vs_baseline uses the same
50x-single-core north star.
"""

import json
import sys
import time

import numpy as np

LANES = 4096
N_LO, N_HI = 20, 30
TARGET_MULTIPLE = 10.0   # VERDICT r2 bar: divergent bench >= 10x one core
RECORDED_CPP_INTERP_OPS = 150e6


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def main():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.steps_per_launch = 50_000_000
    conf.batch.value_stack_depth = 256
    conf.batch.call_stack_depth = 256
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)

    rng = np.random.default_rng(42)
    ns = N_LO + (np.arange(LANES, dtype=np.int64) % (N_HI - N_LO + 1))
    rng.shuffle(ns)

    # warmup: same shape of divergence, small n, to compile all geometries
    warm = ns - 14
    eng.run("fib", [warm], max_steps=10_000_000)

    t0 = time.perf_counter()
    res = eng.run("fib", [ns], max_steps=2_000_000_000)
    dt = time.perf_counter() - t0

    ok = bool(res.completed.all())
    expect = np.asarray([_fib(int(n)) for n in ns], np.int64)
    correct = bool((np.asarray(res.results[0], np.int64) == expect).all())
    total_retired = float(np.asarray(res.retired, np.float64).sum())
    agg = total_retired / dt

    try:
        from wasmedge_tpu.native import scalar_fib_ops_per_sec

        base_ops, base_src = float(scalar_fib_ops_per_sec(30)), \
            "cpp-scalar-engine"
    except Exception:
        base_ops, base_src = RECORDED_CPP_INTERP_OPS, "recorded-estimate"
    vs = agg / (TARGET_MULTIPLE * base_ops)

    out = {
        "metric": f"divergent_fib{N_LO}to{N_HI}_wasm_ops_per_sec_x{LANES}",
        "value": round(agg, 1),
        "unit": "wasm_instr/s",
        "ok": ok and correct,
        "vs_baseline": round(vs, 4),
        "wall_s": round(dt, 2),
    }
    print(json.dumps(out))
    pallas = getattr(eng, "pallas", None)
    print(f"# splits={getattr(pallas, 'splits', '?')} "
          f"fell_back={getattr(eng, 'fell_back_to_simt', '?')} "
          f"baseline={base_ops:.3g} ({base_src}) target={TARGET_MULTIPLE}x",
          file=sys.stderr)
    if not (ok and correct):
        sys.exit(1)


if __name__ == "__main__":
    main()
