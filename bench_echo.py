"""BASELINE config 4 shape: batched WASI outcalls (echo workload).

4096 lanes each call wasi fd_write twice per iteration (message +
per-lane counter digits to a sink fd), interleaved with compute, for
ITERS iterations — the serverless request-handler shape.  Measures wall
time and aggregate host-call service rate through the Pallas engine's
outcall channel.  Prints ONE JSON line."""

import json
import os
import sys
import time

import numpy as np

LANES = 4096
ITERS = 4


def main():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.builder import ModuleBuilder
    from wasmedge_tpu.validator import Validator

    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    # iovec at 64 -> "hello wasi echo\n" at 128 (16 bytes)
    body = [
        ("i32.const", 64), ("i32.const", 128), ("i32.store", 2, 0),
        ("i32.const", 68), ("i32.const", 16), ("i32.store", 2, 0),
    ]
    msg = b"hello wasi echo\n"
    for i, ch in enumerate(msg):
        body += [("i32.const", 128 + i), ("i32.const", ch),
                 ("i32.store8", 0, 0)]
    body += [
        ("block", None), ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        # write the message
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 2),
        # write again (second syscall per iteration)
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0), "end", "end",
        ("local.get", 2),
    ]
    b.add_function(["i32"], ["i32"], ["i32", "i32"], body, export="echo")
    data = b.build()

    conf = Configure()
    conf.batch.steps_per_launch = 100_000
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    # route fd 1 to a sink so the bench doesn't spam stdout
    sink = os.open(os.devnull, os.O_WRONLY)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)
    eng.run("echo", [np.full(LANES, 1, np.int64)], max_steps=100_000)

    t0 = time.perf_counter()
    res = eng.run("echo", [np.full(LANES, ITERS, np.int64)],
                  max_steps=10_000_000)
    dt = time.perf_counter() - t0
    os.close(sink)

    ok = bool(res.completed.all())
    ncalls = LANES * ITERS * 2
    out = {
        "metric": f"wasi_echo_hostcalls_per_sec_x{LANES}",
        "value": round(ncalls / dt, 1),
        "unit": "hostcalls/s",
        "ok": ok,
        "calls": ncalls,
        "wall_s": round(dt, 2),
    }
    print(json.dumps(out))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
