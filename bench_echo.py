"""BASELINE config 4 shape: batched WASI outcalls (echo workload).

4096 lanes each call wasi fd_write twice per iteration (message +
nwritten bookkeeping to a sink fd), interleaved with compute, for ITERS
iterations — the serverless request-handler shape.  Measures wall time
and aggregate host-call service rate through the batch engines' three-
tier hostcall pipeline (batch/hostcall.py):

  tier 0  pure calls retired in-kernel (zero device<->host round trips)
  tier 1  parked lanes drained by SoA-vectorized WASI implementations
  tier 2  CPU drain overlapped with device compute (block scheduler)

Prints ONE JSON line and records it to ECHO_r06.json (BENCH_ARTIFACT
overrides the path; =off disables the file)."""

import os
import sys
import time

import numpy as np

LANES = int(os.environ.get("ECHO_LANES", 4096))
ITERS = int(os.environ.get("ECHO_ITERS", 4))


def build_module():
    from wasmedge_tpu.utils.builder import ModuleBuilder

    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    # iovec at 64 -> "hello wasi echo\n" at 128 (16 bytes)
    body = [
        ("i32.const", 64), ("i32.const", 128), ("i32.store", 2, 0),
        ("i32.const", 68), ("i32.const", 16), ("i32.store", 2, 0),
    ]
    msg = b"hello wasi echo\n"
    for i, ch in enumerate(msg):
        body += [("i32.const", 128 + i), ("i32.const", ch),
                 ("i32.store8", 0, 0)]
    body += [
        ("block", None), ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        # write the message
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 2),
        # write again (second syscall per iteration)
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0), "end", "end",
        ("local.get", 2),
    ]
    b.add_function(["i32"], ["i32"], ["i32", "i32"], body, export="echo")
    return b.build()


def _backend():
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def hostcall_stats(eng):
    """Aggregate pipeline counters from whichever engines actually ran."""
    from wasmedge_tpu.batch.engine import new_hostcall_stats

    out = new_hostcall_stats()
    seen = set()
    for e in (eng, getattr(eng, "simt", None),
              getattr(getattr(eng, "pallas", None), "simt", None)):
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        st = getattr(e, "hostcall_stats", None)
        if st:
            for k, v in st.items():
                out[k] = out.get(k, 0) + v
    return out


def main():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.bench_artifact import emit
    from wasmedge_tpu.validator import Validator

    data = build_module()
    conf = Configure()
    conf.batch.steps_per_launch = 100_000
    # Size the per-lane stacks to the workload (bench.py precedent):
    # the echo handler needs ~16 value slots / 2 frames; smaller state
    # planes mean cheaper per-step updates everywhere.
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    # route fd 1 to a sink so the bench doesn't spam stdout
    sink = os.open(os.devnull, os.O_WRONLY)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)
    eng.run("echo", [np.full(LANES, 1, np.int64)], max_steps=100_000)

    t0 = time.perf_counter()
    res = eng.run("echo", [np.full(LANES, ITERS, np.int64)],
                  max_steps=10_000_000)
    dt = time.perf_counter() - t0
    os.close(sink)

    ok = bool(res.completed.all())
    ncalls = LANES * ITERS * 2
    tiers = hostcall_stats(eng)
    out = {
        "metric": f"wasi_echo_hostcalls_per_sec_x{LANES}",
        "value": round(ncalls / dt, 1),
        "unit": "hostcalls/s",
        "ok": ok,
        "calls": ncalls,
        "wall_s": round(dt, 3),
        "per_lane_calls_per_sec": round(ncalls / dt / LANES, 3),
        "lanes": LANES,
        "iters": ITERS,
        "tier0_calls": tiers["tier0_calls"],
        "tier0_fd_write": tiers["tier0_fd_write"],
        "tier1_calls": tiers["tier1_calls"],
        "tier1_vectorized": tiers["tier1_vectorized"],
        "serve_rounds": tiers["serve_rounds"],
        # tier-0 calls complete in-kernel: zero device<->host round
        # trips is witnessed by serve_rounds == 0
        "zero_roundtrip": bool(tiers["tier0_calls"] >= ncalls
                               and tiers["serve_rounds"] == 0),
        "backend": _backend(),
    }
    if LANES == 4096 and ITERS == 4:
        # recorded context, NOT measured by this run: r5's number came
        # from 1x TPU v5e behind a tunnel; the seed numbers are the
        # unmodified seed bench on the r6 build container (CPU, 2 vCPU).
        # The seed ran with default stack geometry (1024/512); the r6
        # pipeline measured 2,793 calls/s under that SAME geometry
        # (pipeline-only gain: 5.2x) before the workload-sized stacks
        # above were applied on top.
        out["reference"] = {
            "note": "hardcoded prior measurements for comparison",
            "r5_tpu_calls_per_sec": 1935.0,
            "seed_same_container_cpu_calls_per_sec": 533.6,
            "r6_same_container_default_geometry_calls_per_sec": 2793.0,
        }
    emit(out, "ECHO_r06.json")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
