"""BASELINE config 2 benchmark: load/store-dominated memory workload.

4096 lanes each run a write-then-xor-checksum pass over their own linear
memory (wasmedge_tpu/models/programs.py build_memory_workload) plus the
CoreMark-flavored kernel (MAC + state machine + CRC over memory).  With
watermark-sized memory planes (one page resident instead of the declared
max) both stay on the Pallas fast path — this is the number the round-2
verdict said was missing ("no load/store-dominated workload has a
recorded throughput number").

Prints ONE JSON line; vs_baseline = value / (50 x live single-core
native-engine throughput), the same north star as bench.py.
"""

import json
import sys
import time

import numpy as np

LANES = 4096
N_WORDS = 8192          # words written + checksummed per pass
PASSES = 64             # write+checksum cycles per invocation — enough
                        # device work that the handful of fixed host-link
                        # round trips (~100ms each on a tunneled TPU) stay
                        # under a few percent of the wall time, so the
                        # number measures the ENGINE, not the link
COREMARK_N = 65536
TARGET_MULTIPLE = 50.0
RECORDED_CPP_INTERP_OPS = 150e6


def expected_checksum(n: int, passes: int) -> int:
    """Independent numpy oracle for build_memory_workload(passes):
    pass p (counter counts passes..1) stores word i = i*0x9E3779B1 ^
    (p-1) — so passes=1 stores exactly the original single-pass
    pattern — then xors all n words into the running accumulator."""
    acc = np.uint32(0)
    i = np.arange(n, dtype=np.uint32)
    for p in range(passes, 0, -1):
        words = (i * np.uint32(0x9E3779B1)) ^ np.uint32(p - 1)
        acc ^= np.bitwise_xor.reduce(words)
    return int(acc)


def main():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_coremark_kernel, build_memory_workload
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.steps_per_launch = 50_000_000
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64

    def make(data):
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
        store = StoreManager()
        inst = Executor(conf).instantiate(store, mod)
        return UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)

    eng_mem = make(build_memory_workload(passes=PASSES))
    eng_cm = make(build_coremark_kernel())

    # correctness: engine-vs-scalar parity at small n on the SAME
    # module, plus the independent numpy oracle for the timed run
    mod = Validator(conf).validate(
        Loader(conf).parse_module(build_memory_workload(passes=PASSES)))
    st = StoreManager()
    inst = Executor(conf).instantiate(st, mod)
    expect_small = Executor(conf).invoke(st, inst.find_func("mem_checksum"),
                                         [128])[0]
    assert int(expect_small) & 0xFFFFFFFF == \
        expected_checksum(128, PASSES), "numpy oracle disagrees with scalar"
    expect_mem = expected_checksum(N_WORDS, PASSES)

    # warmup/compile
    eng_mem.run("mem_checksum", [np.full(LANES, 1024, np.int64)],
                max_steps=10_000_000)
    eng_cm.run("coremark", [np.full(LANES, 256, np.int64)],
               max_steps=10_000_000)

    t0 = time.perf_counter()
    r1 = eng_mem.run("mem_checksum", [np.full(LANES, N_WORDS, np.int64)],
                     max_steps=2_000_000_000)
    r2 = eng_cm.run("coremark", [np.full(LANES, COREMARK_N, np.int64)],
                    max_steps=2_000_000_000)
    dt = time.perf_counter() - t0

    ok = bool(r1.completed.all() and r2.completed.all())
    ok = ok and bool(
        (np.asarray(r1.results[0], np.int64) & 0xFFFFFFFF
         == int(expect_mem) & 0xFFFFFFFF).all())
    on_fast_path = not (eng_mem.fell_back_to_simt or eng_cm.fell_back_to_simt)
    retired = float(np.asarray(r1.retired, np.float64).sum()
                    + np.asarray(r2.retired, np.float64).sum())
    agg = retired / dt

    try:
        from wasmedge_tpu.native import scalar_fib_ops_per_sec

        base_ops, base_src = float(scalar_fib_ops_per_sec(30)), \
            "cpp-scalar-engine"
    except Exception:
        base_ops, base_src = RECORDED_CPP_INTERP_OPS, "recorded-estimate"
    vs = agg / (TARGET_MULTIPLE * base_ops)

    out = {
        "metric": f"memory_workload_wasm_ops_per_sec_x{LANES}",
        "value": round(agg, 1),
        "unit": "wasm_instr/s",
        "ok": ok,
        "on_fast_path": on_fast_path,
        "vs_baseline": round(vs, 4),
        "wall_s": round(dt, 2),
    }
    print(json.dumps(out))
    print(f"# baseline={base_ops:.3g} ({base_src}) target={TARGET_MULTIPLE}x",
          file=sys.stderr)
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
