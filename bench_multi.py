"""BASELINE config 5 benchmark: heterogeneous serverless mix.

4096 lanes split across four different tenant modules (fib, fac,
loop_sum, coremark-kernel) executed concurrently in one batch via the
multi-tenant engine (Pallas fast path when tenant lanes align to kernel
blocks).  Prints one JSON line; the driver's headline metric stays in
bench.py (config 1)."""

import json
import sys
import time

import numpy as np


def main():
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.multitenant import MultiTenantBatchEngine, Tenant
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import (
        build_coremark_kernel, build_fac, build_fib, build_loop_sum)
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.steps_per_launch = 50_000_000
    conf.batch.value_stack_depth = 256
    conf.batch.call_stack_depth = 256

    def inst_of(data):
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
        store = StoreManager()
        return Executor(conf).instantiate(store, mod), store

    L = 1024
    # Four tenants with enough per-tenant work that the batch's fixed
    # host-link round trips amortize (the fac tenant stays deliberately
    # short — serverless mixes have quick jobs whose lanes drain early).
    specs = [
        (build_fib(), "fib", [np.full(L, 30, np.int64)]),
        (build_fac(), "fac", [np.full(L, 20, np.int64)]),
        (build_loop_sum(), "loop_sum", [np.full(L, 16_000_000, np.int64)]),
        (build_coremark_kernel(), "coremark",
         [np.full(L, 262144, np.int64)]),
    ]
    tenants = []
    for data, fn, args in specs:
        inst, store = inst_of(data)
        tenants.append(Tenant(
            engine=BatchEngine(inst, store=store, conf=conf, lanes=L),
            func_name=fn, args_lanes=args, lanes=L))
    mt = MultiTenantBatchEngine(tenants, conf=conf)
    # warmup/compile
    mt.run_tenants(max_steps=2000)

    mt2 = MultiTenantBatchEngine(tenants, conf=conf)
    t0 = time.perf_counter()
    res = mt2.run_tenants(max_steps=4_000_000_000)
    dt = time.perf_counter() - t0
    ok = all(r.completed.all() for r in res)
    retired = float(sum(np.asarray(r.retired, np.float64).sum() for r in res))
    agg = retired / dt
    # vs_baseline normalization, same north star as every other artifact:
    # value / (50 x live single-core native-engine throughput) — measured
    # in the same run so the denominator can't drift between artifacts
    try:
        from wasmedge_tpu.native import scalar_fib_ops_per_sec

        base_ops, base_src = float(scalar_fib_ops_per_sec(30)), \
            "cpp-scalar-engine"
    except Exception:
        base_ops, base_src = 150e6, "recorded-estimate"
    vs = agg / (50.0 * base_ops)
    out = {"metric": "multitenant_mix4_wasm_ops_per_sec_x4096",
           "value": round(agg, 1), "unit": "wasm_instr/s",
           "ok": ok, "used_pallas": mt2.used_pallas,
           "vs_baseline": round(vs, 4), "baseline_src": base_src,
           "wall_s": round(dt, 2)}
    print(json.dumps(out))
    if not ok:
        for i, r in enumerate(res):
            print(f"# tenant {i}: traps {set(np.asarray(r.trap).tolist())}",
                  file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
