"""BASELINE config 3 benchmark: v128-dense batched execution.

4096 lanes run a v128-dominated kernel (i32x4 lane math + shuffles +
unaligned v128 memory traffic) through the Pallas warp-interpreter —
the reference executes the whole 0xFD SIMD page in its one interpreter
hot loop (lib/executor/engine/engine.cpp ~700-1610); round 4's kernel
could not, so SIMD modules fell off the fast path to the XLA SIMT
engine.  This artifact records both rates and their ratio.

Prints ONE JSON line; vs_baseline follows the same 50x-single-core
north star as bench.py.
"""

import json
import sys
import time

import numpy as np

LANES = 4096
N_ITERS = 1_000_000
TARGET_MULTIPLE = 50.0
RECORDED_CPP_INTERP_OPS = 150e6

_SRC = """
(module
  (memory 1)
  (func (export "vloop") (param i32) (result i32)
    (local $acc v128)
    (local $mul v128)
    (local $i i32)
    (local.set $acc (v128.const i32x4 1 2 3 4))
    (local.set $mul (v128.const i32x4 3 5 7 11))
    (block (loop
      (br_if 1 (i32.ge_u (local.get $i) (local.get 0)))
      (local.set $acc
        (i32x4.add
          (i32x4.mul (local.get $acc) (local.get $mul))
          (i32x4.splat (local.get $i))))
      (local.set $acc
        (v128.xor (local.get $acc)
                  (i8x16.shuffle 4 5 6 7 0 1 2 3 12 13 14 15 8 9 10 11
                                 (local.get $acc) (local.get $acc))))
      (local.set $i (i32.add (local.get $i) (i32.const 1)))
      (br 0)))
    (v128.store offset=5 (i32.const 32) (local.get $acc))
    (local.set $acc (v128.load offset=5 (i32.const 32)))
    (i32.add
      (i32x4.extract_lane 0 (local.get $acc))
      (i32.add (i32x4.extract_lane 1 (local.get $acc))
               (i32.add (i32x4.extract_lane 2 (local.get $acc))
                        (i32x4.extract_lane 3 (local.get $acc)))))))
"""


def main():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.wat import parse_wat
    from wasmedge_tpu.validator import Validator

    wasm = parse_wat(_SRC)

    def make():
        conf = Configure()
        conf.batch.steps_per_launch = 50_000_000
        conf.batch.value_stack_depth = 64
        conf.batch.call_stack_depth = 16
        mod = Validator(conf).validate(Loader(conf).parse_module(wasm))
        store = StoreManager()
        inst = Executor(conf).instantiate(store, mod)
        return UniformBatchEngine(inst, store=store, conf=conf,
                                  lanes=LANES), conf

    # scalar oracle at small n on the same module
    conf0 = Configure()
    mod = Validator(conf0).validate(Loader(conf0).parse_module(wasm))
    st = StoreManager()
    inst0 = Executor(conf0).instantiate(st, mod)
    expect_small = Executor(conf0).invoke(
        st, inst0.find_func("vloop"), [64])[0]

    def run(eng, n):
        t0 = time.perf_counter()
        res = eng.run("vloop", [np.full(LANES, n, np.int64)],
                      max_steps=2_000_000_000)
        v = int(res.results[0][0])
        dt = time.perf_counter() - t0
        retired = float(np.asarray(res.retired, np.float64).sum())
        return res, v, retired / dt, dt

    eng_p, _ = make()
    on_pallas = eng_p.pallas is not None and eng_p.pallas.eligible
    res, v_small, _, _ = run(eng_p, 64)  # warm + correctness
    ok = bool(res.completed.all()) and \
        all(int(x) == int(expect_small) for x in res.results[0])
    res, _v, rate_pallas, dt_p = run(eng_p, N_ITERS)
    ok = ok and bool(res.completed.all())

    # No on-TPU SIMT comparison: the XLA per-step v128 path faults the
    # TPU worker beyond a few thousand steps (pre-existing — r4 never
    # ran it on hardware at scale; its v128 coverage was CPU-side).
    # The Pallas path above IS the fix: same workload, sustained.

    try:
        from wasmedge_tpu.native import scalar_fib_ops_per_sec

        base_ops, base_src = float(scalar_fib_ops_per_sec(30)), \
            "cpp-scalar-engine"
    except Exception:
        base_ops, base_src = RECORDED_CPP_INTERP_OPS, "recorded-estimate"

    out = {
        "metric": f"simd_v128_wasm_ops_per_sec_x{LANES}",
        "value": round(rate_pallas, 1),
        "unit": "wasm_instr/s",
        "ok": ok,
        "on_pallas_path": bool(on_pallas),
        "simt_note": "no on-TPU fallback comparison: the XLA per-step "
                     "v128 path faults the TPU worker beyond a few "
                     "thousand steps (pre-existing); the Pallas path "
                     "sustains the workload",
        "vs_baseline": round(rate_pallas / (TARGET_MULTIPLE * base_ops), 4),
        "wall_s": round(dt_p, 2),
    }
    print(json.dumps(out))
    print(f"# baseline={base_ops:.3g} ({base_src})", file=sys.stderr)
    if not (ok and on_pallas):
        sys.exit(1)


if __name__ == "__main__":
    main()
