/* Out-of-process C embedder: compile fib.wasm to twasm, run fib(24)
 * through the shim, print the result.  Usage: example_fib fib.wasm */
#include "wasmedge_tpu.h"
#include <stdio.h>

int main(int argc, char **argv) {
    if (argc < 2) { fprintf(stderr, "usage: %s fib.wasm\n", argv[0]); return 2; }
    if (we_init()) { fprintf(stderr, "init: %s\n", we_last_error()); return 1; }
    printf("wasmedge_tpu %u.%u\n", we_version_major(), we_version_minor());
    we_vm *vm = we_vm_create();
    if (!vm) { fprintf(stderr, "vm: %s\n", we_last_error()); return 1; }
    long long args[1] = {24}, results[1];
    int n = we_vm_run_i64(vm, argv[1], "fib", args, 1, results, 1);
    if (n < 0) { fprintf(stderr, "run: %s\n", we_last_error()); return 1; }
    printf("fib(24) = %lld\n", results[0]);
    we_vm_delete(vm);
    we_shutdown();
    return results[0] == 46368 ? 0 : 1;
}
