/* CPython-embedding shim implementing wasmedge_tpu.h.
 *
 * One interpreter per process; every entry point grabs the GIL-less
 * single-threaded happy path (call we_init first).  Mirrors how the
 * reference's language bindings sit on its C API: this file is the only
 * place that knows Python exists.
 */
#include "wasmedge_tpu.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdio.h>
#include <string.h>
#include <stdlib.h>

static PyObject *g_capi = NULL;
static char g_err[1024];

static void set_err_from_py(void) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value) {
        PyObject *s = PyObject_Str(value);
        if (s) {
            snprintf(g_err, sizeof g_err, "%s", PyUnicode_AsUTF8(s));
            Py_DECREF(s);
        }
    }
    Py_XDECREF(type); Py_XDECREF(value); Py_XDECREF(tb);
}

const char *we_last_error(void) { return g_err; }

int we_init(void) {
    if (g_capi) return 0;
    if (!Py_IsInitialized()) Py_Initialize();
    const char *root = getenv("WASMEDGE_TPU_PYROOT");
    if (root) {
        PyObject *sys_path = PySys_GetObject("path");
        PyObject *p = PyUnicode_FromString(root);
        PyList_Insert(sys_path, 0, p);
        Py_DECREF(p);
    }
    g_capi = PyImport_ImportModule("wasmedge_tpu.capi");
    if (!g_capi) { set_err_from_py(); return -1; }
    return 0;
}

void we_shutdown(void) {
    Py_XDECREF(g_capi);
    g_capi = NULL;
}

struct we_vm { PyObject *ctx; };

we_vm *we_vm_create(void) {
    if (we_init()) return NULL;
    PyObject *ctx = PyObject_CallMethod(g_capi, "we_VMCreate", NULL);
    if (!ctx) { set_err_from_py(); return NULL; }
    we_vm *vm = (we_vm *)malloc(sizeof *vm);
    vm->ctx = ctx;
    return vm;
}

void we_vm_delete(we_vm *vm) {
    if (!vm) return;
    Py_XDECREF(vm->ctx);
    free(vm);
}

int we_vm_run_i64(we_vm *vm, const char *wasm_path, const char *func,
                  const long long *args, int nargs,
                  long long *results, int max_results) {
    PyObject *params = PyList_New(nargs);
    for (int i = 0; i < nargs; i++)
        PyList_SET_ITEM(params, i, PyLong_FromLongLong(args[i]));
    /* raw 64-bit cells: coerced to the declared param types on the
     * Python side (we_VMRunWasmFromFileCells) */
    PyObject *pair = PyObject_CallMethod(
        g_capi, "we_VMRunWasmFromFileCells", "OssO", vm->ctx, wasm_path,
        func, params);
    Py_DECREF(params);
    if (!pair) { set_err_from_py(); return -1; }
    PyObject *res = PyTuple_GetItem(pair, 0);
    PyObject *vals = PyTuple_GetItem(pair, 1);
    if (!res || !vals) { set_err_from_py(); Py_DECREF(pair); return -1; }
    PyObject *ok = PyObject_CallMethod(g_capi, "we_ResultOK", "O", res);
    if (!ok) { set_err_from_py(); Py_DECREF(pair); return -1; }
    if (!PyObject_IsTrue(ok)) {
        long c = -1;
        PyObject *code = PyObject_CallMethod(g_capi, "we_ResultGetCode",
                                             "O", res);
        PyObject *msg = PyObject_CallMethod(g_capi, "we_ResultGetMessage",
                                            "O", res);
        if (msg) {
            const char *m = PyUnicode_AsUTF8(msg);
            snprintf(g_err, sizeof g_err, "%s", m ? m : "unknown error");
        } else {
            set_err_from_py();
        }
        if (code) c = PyLong_AsLong(code);
        Py_DECREF(ok); Py_XDECREF(code); Py_XDECREF(msg); Py_DECREF(pair);
        return c > 0 ? -(int)c : -1;
    }
    Py_DECREF(ok);
    int n = (int)PyList_Size(vals);
    for (int i = 0; i < n && i < max_results; i++) {
        PyObject *cell = PyObject_CallMethod(
            g_capi, "we_ValueGetI64", "O", PyList_GetItem(vals, i));
        if (!cell) { set_err_from_py(); Py_DECREF(pair); return -1; }
        results[i] = PyLong_AsLongLong(cell);
        Py_DECREF(cell);
    }
    Py_DECREF(pair);
    return n;
}

/* -- typed values + staged pipeline (C++ SDK substrate) ---------------- */

static PyObject *value_to_py(const we_value *v) {
    switch (v->kind) {
    case WE_I32:
        return PyObject_CallMethod(g_capi, "we_ValueGenI32", "i", v->of.i32);
    case WE_I64:
        return PyObject_CallMethod(g_capi, "we_ValueGenI64", "L", v->of.i64);
    case WE_F32:
        return PyObject_CallMethod(g_capi, "we_ValueGenF32", "f", v->of.f32);
    default:
        return PyObject_CallMethod(g_capi, "we_ValueGenF64", "d", v->of.f64);
    }
}

static int value_from_py(PyObject *cell, we_value *out) {
    PyObject *ty = PyObject_GetAttrString(cell, "type");
    const char *t = ty ? PyUnicode_AsUTF8(ty) : NULL;
    /* only the four numeric kinds cross this ABI; v128/refs would
     * silently truncate, so refuse them instead */
    if (!t || (strcmp(t, "i32") && strcmp(t, "i64") && strcmp(t, "f32")
               && strcmp(t, "f64"))) {
        snprintf(g_err, sizeof g_err,
                 "result type %s not representable as we_value",
                 t ? t : "?");
        Py_XDECREF(ty);
        return -1;
    }
    const char *getter = "we_ValueGetI64";
    out->kind = WE_I64;
    if (strcmp(t, "i32") == 0) { out->kind = WE_I32; getter = "we_ValueGetI32"; }
    else if (strcmp(t, "f32") == 0) { out->kind = WE_F32; getter = "we_ValueGetF32"; }
    else if (strcmp(t, "f64") == 0) { out->kind = WE_F64; getter = "we_ValueGetF64"; }
    Py_XDECREF(ty);
    PyObject *raw = PyObject_CallMethod(g_capi, getter, "O", cell);
    if (!raw) { set_err_from_py(); return -1; }
    switch (out->kind) {
    case WE_I32: out->of.i32 = (int32_t)PyLong_AsLong(raw); break;
    case WE_I64: out->of.i64 = PyLong_AsLongLong(raw); break;
    case WE_F32: out->of.f32 = (float)PyFloat_AsDouble(raw); break;
    default: out->of.f64 = PyFloat_AsDouble(raw); break;
    }
    Py_DECREF(raw);
    return 0;
}

/* Result-object -> 0 / negative error code (sets g_err). */
static int check_result(PyObject *res) {
    PyObject *ok = PyObject_CallMethod(g_capi, "we_ResultOK", "O", res);
    if (!ok) { set_err_from_py(); return -1; }
    if (PyObject_IsTrue(ok)) { Py_DECREF(ok); return 0; }
    Py_DECREF(ok);
    long c = -1;
    PyObject *code = PyObject_CallMethod(g_capi, "we_ResultGetCode", "O", res);
    PyObject *msg = PyObject_CallMethod(g_capi, "we_ResultGetMessage", "O", res);
    if (msg && PyUnicode_Check(msg)) {
        const char *m = PyUnicode_AsUTF8(msg);
        snprintf(g_err, sizeof g_err, "%s", m ? m : "unknown error");
    }
    if (code) c = PyLong_AsLong(code);
    Py_XDECREF(code); Py_XDECREF(msg);
    return c > 0 ? -(int)c : -1;
}

static PyObject *strv_to_list(const char *const *sv) {
    PyObject *lst = PyList_New(0);
    for (; sv && *sv; sv++) {
        PyObject *s = PyUnicode_FromString(*sv);
        PyList_Append(lst, s);
        Py_DECREF(s);
    }
    return lst;
}

we_vm *we_vm_create_ex(unsigned host_flags, const char *const *wasi_args,
                       const char *const *wasi_envs,
                       const char *const *wasi_preopens) {
    if (we_init()) return NULL;
    PyObject *conf = PyObject_CallMethod(g_capi, "we_ConfigureCreate", NULL);
    if (!conf) { set_err_from_py(); return NULL; }
    if (host_flags & WE_HOST_WASI) {
        PyObject *r = PyObject_CallMethod(
            g_capi, "we_ConfigureAddHostRegistration", "Os", conf, "wasi");
        if (!r) { set_err_from_py(); Py_DECREF(conf); return NULL; }
        Py_DECREF(r);
    }
    PyObject *ctx = PyObject_CallMethod(g_capi, "we_VMCreate", "O", conf);
    Py_DECREF(conf);
    if (!ctx) { set_err_from_py(); return NULL; }
    if (host_flags & WE_HOST_WASI) {
        PyObject *wasi = PyObject_CallMethod(
            g_capi, "we_VMGetImportModuleContext", "Os", ctx, "wasi");
        if (wasi && wasi != Py_None) {
            /* args[0] is argv[0] (the program name), like the CLI
             * (reference: wasmedger.cpp:216-221) */
            PyObject *dirs = strv_to_list(wasi_preopens);
            PyObject *args = strv_to_list(
                wasi_args && wasi_args[0] ? wasi_args + 1 : wasi_args);
            PyObject *envs = strv_to_list(wasi_envs);
            PyObject *r;
            if (wasi_args && wasi_args[0]) {
                r = PyObject_CallMethod(
                    g_capi, "we_ImportObjectInitWASI", "OOOOs", wasi,
                    dirs, args, envs, wasi_args[0]);
            } else {
                r = PyObject_CallMethod(
                    g_capi, "we_ImportObjectInitWASI", "OOOO", wasi,
                    dirs, args, envs);
            }
            int failed = (r == NULL);
            if (failed) set_err_from_py();
            Py_XDECREF(r); Py_DECREF(dirs); Py_DECREF(args); Py_DECREF(envs);
            if (failed) { Py_XDECREF(wasi); Py_DECREF(ctx); return NULL; }
        }
        Py_XDECREF(wasi);
    }
    we_vm *vm = (we_vm *)malloc(sizeof *vm);
    vm->ctx = ctx;
    return vm;
}

static int staged_call(we_vm *vm, const char *method, const char *arg) {
    PyObject *res = arg
        ? PyObject_CallMethod(g_capi, method, "Os", vm->ctx, arg)
        : PyObject_CallMethod(g_capi, method, "O", vm->ctx);
    if (!res) { set_err_from_py(); return -1; }
    int rc = check_result(res);
    Py_DECREF(res);
    return rc;
}

int we_vm_load_file(we_vm *vm, const char *wasm_path) {
    return staged_call(vm, "we_VMLoadWasmFromFile", wasm_path);
}

int we_vm_validate(we_vm *vm) {
    return staged_call(vm, "we_VMValidate", NULL);
}

int we_vm_instantiate(we_vm *vm) {
    return staged_call(vm, "we_VMInstantiate", NULL);
}

static int execute_common(we_vm *vm, PyObject *pair, we_value *results,
                          int max_results) {
    if (!pair) { set_err_from_py(); return -1; }
    PyObject *res = PyTuple_GetItem(pair, 0);
    PyObject *vals = PyTuple_GetItem(pair, 1);
    if (!res || !vals) { set_err_from_py(); Py_DECREF(pair); return -1; }
    int rc = check_result(res);
    if (rc < 0) { Py_DECREF(pair); return rc; }
    int n = (int)PyList_Size(vals);
    for (int i = 0; i < n && i < max_results; i++) {
        if (value_from_py(PyList_GetItem(vals, i), &results[i]) < 0) {
            Py_DECREF(pair);
            return -1;
        }
    }
    Py_DECREF(pair);
    return n;
}

int we_vm_execute(we_vm *vm, const char *func, const we_value *args,
                  int nargs, we_value *results, int max_results) {
    PyObject *params = PyList_New(nargs);
    for (int i = 0; i < nargs; i++) {
        PyObject *v = value_to_py(&args[i]);
        if (!v) { set_err_from_py(); Py_DECREF(params); return -1; }
        PyList_SET_ITEM(params, i, v);
    }
    PyObject *pair = PyObject_CallMethod(
        g_capi, "we_VMExecute", "OsO", vm->ctx, func, params);
    Py_DECREF(params);
    return execute_common(vm, pair, results, max_results);
}

int we_vm_run(we_vm *vm, const char *wasm_path, const char *func,
              const we_value *args, int nargs, we_value *results,
              int max_results) {
    int rc;
    if ((rc = we_vm_load_file(vm, wasm_path)) < 0) return rc;
    if ((rc = we_vm_validate(vm)) < 0) return rc;
    if ((rc = we_vm_instantiate(vm)) < 0) return rc;
    return we_vm_execute(vm, func, args, nargs, results, max_results);
}

int we_vm_wasi_exit_code(we_vm *vm) {
    PyObject *wasi = PyObject_CallMethod(
        g_capi, "we_VMGetImportModuleContext", "Os", vm->ctx, "wasi");
    if (!wasi || wasi == Py_None) { Py_XDECREF(wasi); return -1; }
    PyObject *c = PyObject_CallMethod(
        g_capi, "we_ImportObjectWASIGetExitCode", "O", wasi);
    Py_DECREF(wasi);
    if (!c) { set_err_from_py(); return -1; }
    int rc = (int)PyLong_AsLong(c);
    Py_DECREF(c);
    return rc;
}

int we_vm_wasi_has_exited(we_vm *vm) {
    PyObject *wasi = PyObject_CallMethod(
        g_capi, "we_VMGetImportModuleContext", "Os", vm->ctx, "wasi");
    if (!wasi || wasi == Py_None) { Py_XDECREF(wasi); return 0; }
    PyObject *c = PyObject_CallMethod(
        g_capi, "we_ImportObjectWASIHasExited", "O", wasi);
    Py_DECREF(wasi);
    if (!c) { set_err_from_py(); return 0; }
    int rc = PyObject_IsTrue(c);
    Py_DECREF(c);
    return rc;
}

int we_vm_function_list(we_vm *vm, char **names, int max_names) {
    PyObject *lst = PyObject_CallMethod(g_capi, "we_VMGetFunctionList",
                                        "O", vm->ctx);
    if (!lst) { set_err_from_py(); return -1; }
    int n = (int)PyList_Size(lst);
    if (names) {
        for (int i = 0; i < n && i < max_names; i++) {
            PyObject *entry = PyList_GetItem(lst, i);
            PyObject *nm = PyTuple_GetItem(entry, 0);
            const char *s = nm ? PyUnicode_AsUTF8(nm) : NULL;
            names[i] = strdup(s ? s : "");
        }
    }
    Py_DECREF(lst);
    return n;
}

int we_vm_register_file(we_vm *vm, const char *name, const char *path) {
    PyObject *res = PyObject_CallMethod(
        g_capi, "we_VMRegisterModuleFromFile", "Oss", vm->ctx, name, path);
    if (!res) { set_err_from_py(); return -1; }
    int rc = check_result(res);
    Py_DECREF(res);
    return rc;
}

int we_compile(const char *in_path, const char *out_path) {
    if (we_init()) return -1;
    PyObject *comp = PyObject_CallMethod(g_capi, "we_CompilerCreate", NULL);
    if (!comp) { set_err_from_py(); return -1; }
    PyObject *res = PyObject_CallMethod(g_capi, "we_CompilerCompile",
                                        "Oss", comp, in_path, out_path);
    Py_DECREF(comp);
    if (!res) { set_err_from_py(); return -1; }
    PyObject *ok = PyObject_CallMethod(g_capi, "we_ResultOK", "O", res);
    int rc = PyObject_IsTrue(ok) ? 0 : -1;
    Py_DECREF(ok); Py_DECREF(res);
    return rc;
}

unsigned we_version_major(void) {
    if (we_init()) return 0;
    PyObject *v = PyObject_CallMethod(g_capi, "we_VersionGetMajor", NULL);
    unsigned r = (unsigned)PyLong_AsUnsignedLong(v);
    Py_DECREF(v);
    return r;
}

unsigned we_version_minor(void) {
    if (we_init()) return 0;
    PyObject *v = PyObject_CallMethod(g_capi, "we_VersionGetMinor", NULL);
    unsigned r = (unsigned)PyLong_AsUnsignedLong(v);
    Py_DECREF(v);
    return r;
}
