/* CPython-embedding shim implementing wasmedge_tpu.h.
 *
 * One interpreter per process; every entry point grabs the GIL-less
 * single-threaded happy path (call we_init first).  Mirrors how the
 * reference's language bindings sit on its C API: this file is the only
 * place that knows Python exists.
 */
#include "wasmedge_tpu.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdio.h>
#include <string.h>
#include <stdlib.h>

static PyObject *g_capi = NULL;
static char g_err[1024];

static void set_err_from_py(void) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value) {
        PyObject *s = PyObject_Str(value);
        if (s) {
            snprintf(g_err, sizeof g_err, "%s", PyUnicode_AsUTF8(s));
            Py_DECREF(s);
        }
    }
    Py_XDECREF(type); Py_XDECREF(value); Py_XDECREF(tb);
}

const char *we_last_error(void) { return g_err; }

int we_init(void) {
    if (g_capi) return 0;
    if (!Py_IsInitialized()) Py_Initialize();
    const char *root = getenv("WASMEDGE_TPU_PYROOT");
    if (root) {
        PyObject *sys_path = PySys_GetObject("path");
        PyObject *p = PyUnicode_FromString(root);
        PyList_Insert(sys_path, 0, p);
        Py_DECREF(p);
    }
    g_capi = PyImport_ImportModule("wasmedge_tpu.capi");
    if (!g_capi) { set_err_from_py(); return -1; }
    return 0;
}

void we_shutdown(void) {
    Py_XDECREF(g_capi);
    g_capi = NULL;
}

struct we_vm { PyObject *ctx; };

we_vm *we_vm_create(void) {
    if (we_init()) return NULL;
    PyObject *ctx = PyObject_CallMethod(g_capi, "we_VMCreate", NULL);
    if (!ctx) { set_err_from_py(); return NULL; }
    we_vm *vm = (we_vm *)malloc(sizeof *vm);
    vm->ctx = ctx;
    return vm;
}

void we_vm_delete(we_vm *vm) {
    if (!vm) return;
    Py_XDECREF(vm->ctx);
    free(vm);
}

int we_vm_run_i64(we_vm *vm, const char *wasm_path, const char *func,
                  const long long *args, int nargs,
                  long long *results, int max_results) {
    PyObject *params = PyList_New(nargs);
    for (int i = 0; i < nargs; i++) {
        PyObject *v = PyObject_CallMethod(g_capi, "we_ValueGenI64", "L",
                                          args[i]);
        if (!v) { set_err_from_py(); Py_DECREF(params); return -1; }
        PyList_SET_ITEM(params, i, v);
    }
    PyObject *pair = PyObject_CallMethod(
        g_capi, "we_VMRunWasmFromFile", "OssO", vm->ctx, wasm_path, func,
        params);
    Py_DECREF(params);
    if (!pair) { set_err_from_py(); return -1; }
    PyObject *res = PyTuple_GetItem(pair, 0);
    PyObject *vals = PyTuple_GetItem(pair, 1);
    if (!res || !vals) { set_err_from_py(); Py_DECREF(pair); return -1; }
    PyObject *ok = PyObject_CallMethod(g_capi, "we_ResultOK", "O", res);
    if (!ok) { set_err_from_py(); Py_DECREF(pair); return -1; }
    if (!PyObject_IsTrue(ok)) {
        long c = -1;
        PyObject *code = PyObject_CallMethod(g_capi, "we_ResultGetCode",
                                             "O", res);
        PyObject *msg = PyObject_CallMethod(g_capi, "we_ResultGetMessage",
                                            "O", res);
        if (msg) {
            const char *m = PyUnicode_AsUTF8(msg);
            snprintf(g_err, sizeof g_err, "%s", m ? m : "unknown error");
        } else {
            set_err_from_py();
        }
        if (code) c = PyLong_AsLong(code);
        Py_DECREF(ok); Py_XDECREF(code); Py_XDECREF(msg); Py_DECREF(pair);
        return c > 0 ? -(int)c : -1;
    }
    Py_DECREF(ok);
    int n = (int)PyList_Size(vals);
    for (int i = 0; i < n && i < max_results; i++) {
        PyObject *cell = PyObject_CallMethod(
            g_capi, "we_ValueGetI64", "O", PyList_GetItem(vals, i));
        if (!cell) { set_err_from_py(); Py_DECREF(pair); return -1; }
        results[i] = PyLong_AsLongLong(cell);
        Py_DECREF(cell);
    }
    Py_DECREF(pair);
    return n;
}

int we_compile(const char *in_path, const char *out_path) {
    if (we_init()) return -1;
    PyObject *comp = PyObject_CallMethod(g_capi, "we_CompilerCreate", NULL);
    if (!comp) { set_err_from_py(); return -1; }
    PyObject *res = PyObject_CallMethod(g_capi, "we_CompilerCompile",
                                        "Oss", comp, in_path, out_path);
    Py_DECREF(comp);
    if (!res) { set_err_from_py(); return -1; }
    PyObject *ok = PyObject_CallMethod(g_capi, "we_ResultOK", "O", res);
    int rc = PyObject_IsTrue(ok) ? 0 : -1;
    Py_DECREF(ok); Py_DECREF(res);
    return rc;
}

unsigned we_version_major(void) {
    if (we_init()) return 0;
    PyObject *v = PyObject_CallMethod(g_capi, "we_VersionGetMajor", NULL);
    unsigned r = (unsigned)PyLong_AsUnsignedLong(v);
    Py_DECREF(v);
    return r;
}

unsigned we_version_minor(void) {
    if (we_init()) return 0;
    PyObject *v = PyObject_CallMethod(g_capi, "we_VersionGetMinor", NULL);
    unsigned r = (unsigned)PyLong_AsUnsignedLong(v);
    Py_DECREF(v);
    return r;
}
