/* wasmedge_tpu C embedding interface.
 *
 * The moral analog of the reference's wasmedge.h for this framework
 * (reference: /root/reference/include/api/wasmedge/wasmedge.h): a C host
 * links against the shim (shim.c), which embeds CPython and drives the
 * wasmedge_tpu.capi surface — the same way the reference's Rust bindings
 * are an FFI layer over its C API (bindings/rust/wasmedge-sys).
 *
 * Build: cc -c shim.c $(python3-config --includes)
 *        cc example_fib.c shim.o $(python3-config --embed --ldflags)
 * Set WASMEDGE_TPU_PYROOT to the repo root if wasmedge_tpu is not on the
 * default Python path.
 */
#ifndef WASMEDGE_TPU_H
#define WASMEDGE_TPU_H

#ifdef __cplusplus
extern "C" {
#endif

typedef struct we_vm we_vm;

/* Initialize the embedded runtime (idempotent). Returns 0 on success. */
int we_init(void);
void we_shutdown(void);

we_vm *we_vm_create(void);
void we_vm_delete(we_vm *vm);

/* Run `func` from the wasm/twasm file with 64-bit integer arguments.
 * Results are written to `results` (up to max_results cells).
 * Returns the number of results, or a negative engine error code. */
int we_vm_run_i64(we_vm *vm, const char *wasm_path, const char *func,
                  const long long *args, int nargs,
                  long long *results, int max_results);

/* Compile wasm -> universal twasm (tpu.aot section). 0 on success. */
int we_compile(const char *in_path, const char *out_path);

/* Last error message (valid until the next call on the same thread). */
const char *we_last_error(void);

unsigned we_version_major(void);
unsigned we_version_minor(void);

#ifdef __cplusplus
}
#endif
#endif /* WASMEDGE_TPU_H */
