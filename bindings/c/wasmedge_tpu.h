/* wasmedge_tpu C embedding interface.
 *
 * The moral analog of the reference's wasmedge.h for this framework
 * (reference: /root/reference/include/api/wasmedge/wasmedge.h): a C host
 * links against the shim (shim.c), which embeds CPython and drives the
 * wasmedge_tpu.capi surface — the same way the reference's Rust bindings
 * are an FFI layer over its C API (bindings/rust/wasmedge-sys).  The
 * typed C++ SDK (../cpp/wasmedge_tpu.hpp) sits on this ABI the way
 * wasmedge-sdk sits on wasmedge-sys.
 *
 * Build: cc -c shim.c $(python3-config --includes)
 *        cc example_fib.c shim.o $(python3-config --embed --ldflags)
 * Set WASMEDGE_TPU_PYROOT to the repo root if wasmedge_tpu is not on the
 * default Python path.
 */
#ifndef WASMEDGE_TPU_H
#define WASMEDGE_TPU_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct we_vm we_vm;

/* Typed wasm value crossing the ABI (reference: WasmEdge_Value). */
typedef enum we_valkind {
  WE_I32 = 0,
  WE_I64 = 1,
  WE_F32 = 2,
  WE_F64 = 3
} we_valkind;

typedef struct we_value {
  int32_t kind; /* we_valkind */
  union {
    int32_t i32;
    int64_t i64;
    float f32;
    double f64;
  } of;
} we_value;

/* Initialize the embedded runtime (idempotent). Returns 0 on success. */
int we_init(void);
void we_shutdown(void);

/* flags for we_vm_create_ex */
#define WE_HOST_WASI 1u

we_vm *we_vm_create(void);
/* host_flags: WE_HOST_* host-module registrations.  wasi_args /
 * wasi_envs ("K=V") / wasi_preopens ("guest:host" or "dir") are
 * NULL-terminated string arrays applied to the WASI module (any may be
 * NULL). */
we_vm *we_vm_create_ex(unsigned host_flags, const char *const *wasi_args,
                       const char *const *wasi_envs,
                       const char *const *wasi_preopens);
void we_vm_delete(we_vm *vm);

/* -- staged pipeline (reference: VMLoadWasm/Validate/Instantiate) ------ */
int we_vm_load_file(we_vm *vm, const char *wasm_path);
int we_vm_validate(we_vm *vm);
int we_vm_instantiate(we_vm *vm);

/* Execute an export of the instantiated module with typed values.
 * Returns the number of results (written to `results`, up to
 * max_results), or a negative engine error code. */
int we_vm_execute(we_vm *vm, const char *func, const we_value *args,
                  int nargs, we_value *results, int max_results);

/* One-shot: load+validate+instantiate+execute (typed values). */
int we_vm_run(we_vm *vm, const char *wasm_path, const char *func,
              const we_value *args, int nargs, we_value *results,
              int max_results);

/* WASI exit code of the last command run (after executing _start). */
int we_vm_wasi_exit_code(we_vm *vm);

/* 1 only after the guest called proc_exit (distinguishes proc_exit(0)
 * from a guest that trapped or returned without exiting). */
int we_vm_wasi_has_exited(we_vm *vm);

/* Exported function listing of the instantiated module.  Returns the
 * count; when `names` is non-NULL writes up to max_names entries of
 * newly malloc'd strings the caller frees. */
int we_vm_function_list(we_vm *vm, char **names, int max_names);

/* Register a module file under a namespace for cross-module imports. */
int we_vm_register_file(we_vm *vm, const char *name, const char *path);

/* Run `func` from the wasm/twasm file with 64-bit integer arguments.
 * Results are written to `results` (up to max_results cells).
 * Returns the number of results, or a negative engine error code. */
int we_vm_run_i64(we_vm *vm, const char *wasm_path, const char *func,
                  const long long *args, int nargs,
                  long long *results, int max_results);

/* Compile wasm -> universal twasm (tpu.aot section). 0 on success. */
int we_compile(const char *in_path, const char *out_path);

/* Last error message (valid until the next call on the same thread). */
const char *we_last_error(void);

unsigned we_version_major(void);
unsigned we_version_minor(void);

#ifdef __cplusplus
}
#endif
#endif /* WASMEDGE_TPU_H */
