// C++ SDK example: staged fib + a WASI command program, out of process.
//
// Build (from bindings/cpp):
//   cc -c ../c/shim.c $(python3-config --includes)
//   c++ -std=c++17 example_sdk.cc shim.o $(python3-config --embed --ldflags)
//
// Usage: example_sdk fib.wasm <n> [wasi.wasm expected_exit]

#include <cstdio>
#include <cstdlib>

#include "wasmedge_tpu.hpp"

int main(int argc, char **argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s fib.wasm n [wasi.wasm exit]\n", argv[0]);
    return 2;
  }
  // staged pipeline with typed values
  wetpu::Vm vm;
  if (!vm.valid()) {
    std::fprintf(stderr, "vm create failed: %s\n", we_last_error());
    return 2;
  }
  for (auto step : {vm.load(argv[1]), vm.validate(), vm.instantiate()}) {
    if (!step) {
      std::fprintf(stderr, "stage failed: %s\n", step.error().message.c_str());
      return 1;
    }
  }
  auto fns = vm.function_list();
  if (!fns || fns->empty()) {
    std::fprintf(stderr, "no exports listed\n");
    return 1;
  }
  auto r = vm.execute("fib", {wetpu::Value::i32(std::atoi(argv[2]))});
  if (!r) {
    std::fprintf(stderr, "execute failed (%d): %s\n", r.error().code,
                 r.error().message.c_str());
    return 1;
  }
  std::printf("fib=%d exports=%zu\n", (*r)[0].as_i32(), fns->size());

  // trap maps to a typed error, and the VM stays usable
  auto bad = vm.execute("fib", {wetpu::Value::f32(1.0f)});
  if (bad) {
    std::fprintf(stderr, "arity/type mismatch not surfaced\n");
    return 1;
  }
  std::printf("typed-error=%d\n", bad.error().code);

  if (argc >= 5) {
    wetpu::WasiConfig ws;
    ws.args = {"guest", "one", "two"};
    wetpu::Vm wasi_vm{ws};
    auto code = wasi_vm.run_wasi_command(argv[3]);
    if (!code) {
      std::fprintf(stderr, "wasi run failed: %s\n",
                   code.error().message.c_str());
      return 1;
    }
    std::printf("wasi-exit=%d want=%d\n", *code, std::atoi(argv[4]));
    if (*code != std::atoi(argv[4])) return 1;
  }
  std::puts("SDK OK");
  return 0;
}
