// wasmedge_tpu C++ SDK: typed host-language bindings over the C shim.
//
// The analog of the reference's high-level Rust SDK
// (/root/reference/bindings/rust/wasmedge-sdk/src/vm.rs) for this
// framework: RAII VM with a staged or one-shot pipeline, a tagged Value
// type, and error mapping — all over the C ABI in
// ../c/wasmedge_tpu.h exactly the way wasmedge-sdk sits on
// wasmedge-sys.  Header-only C++17; link shim.o and the embedded
// CPython (see the header's build line).
//
//   namespace wetpu;
//   wetpu::Vm vm;                                  // plain VM
//   auto r = vm.run("app.wasm", "fib", {wetpu::Value::i64(20)});
//   if (r) int64_t out = (*r)[0].as_i64();
//
//   wetpu::WasiConfig ws; ws.args = {"app", "hello"};
//   wetpu::Vm wasi_vm{ws};                         // WASI command VM
//   wasi_vm.run_wasi_command("app.wasm");          // -> exit code

#ifndef WASMEDGE_TPU_HPP
#define WASMEDGE_TPU_HPP

#include <cstdint>
#include <cstdlib>
#include <optional>
#include <string>
#include <utility>
#include <vector>

#include "../c/wasmedge_tpu.h"

namespace wetpu {

// -- values -----------------------------------------------------------------

enum class ValKind { I32 = WE_I32, I64 = WE_I64, F32 = WE_F32, F64 = WE_F64 };

class Value {
 public:
  static Value i32(int32_t v) {
    Value x(ValKind::I32);
    x.raw_.of.i32 = v;
    return x;
  }
  static Value i64(int64_t v) {
    Value x(ValKind::I64);
    x.raw_.of.i64 = v;
    return x;
  }
  static Value f32(float v) {
    Value x(ValKind::F32);
    x.raw_.of.f32 = v;
    return x;
  }
  static Value f64(double v) {
    Value x(ValKind::F64);
    x.raw_.of.f64 = v;
    return x;
  }
  static Value from_raw(const we_value &raw) {
    Value x(static_cast<ValKind>(raw.kind));
    x.raw_ = raw;
    return x;
  }

  ValKind kind() const { return static_cast<ValKind>(raw_.kind); }
  int32_t as_i32() const { return raw_.of.i32; }
  int64_t as_i64() const { return raw_.of.i64; }
  float as_f32() const { return raw_.of.f32; }
  double as_f64() const { return raw_.of.f64; }
  const we_value &raw() const { return raw_; }

 private:
  explicit Value(ValKind k) : raw_{} { raw_.kind = static_cast<int32_t>(k); }
  we_value raw_;
};

// -- errors -----------------------------------------------------------------

// Engine error codes surface as their positive ErrCode value (the C ABI
// returns them negated); -1 means a binding-level failure.
struct Error {
  int code = -1;
  std::string message;
};

// Minimal expected<T, Error> (the SDK requires only this shape).
template <typename T>
class Result {
 public:
  Result(T value) : value_(std::move(value)) {}        // NOLINT(runtime/explicit)
  Result(Error err) : error_(std::move(err)) {}        // NOLINT(runtime/explicit)

  explicit operator bool() const { return value_.has_value(); }
  const T &operator*() const { return *value_; }
  T &operator*() { return *value_; }
  const T *operator->() const { return &*value_; }
  const Error &error() const { return *error_; }

 private:
  std::optional<T> value_;
  std::optional<Error> error_;
};

inline Error last_error(int rc) {
  return Error{rc < -1 ? -rc : rc, we_last_error() ? we_last_error() : ""};
}

// -- configuration ----------------------------------------------------------

struct WasiConfig {
  std::vector<std::string> args;      // argv (args[0] = program name)
  std::vector<std::string> envs;      // "KEY=VALUE"
  std::vector<std::string> preopens;  // "guest_dir:host_dir" or "dir"
};

// -- the VM -----------------------------------------------------------------

class Vm {
 public:
  Vm() : vm_(we_vm_create()) {}
  explicit Vm(const WasiConfig &wasi) {
    auto argv = c_strv(wasi.args);
    auto envv = c_strv(wasi.envs);
    auto prev = c_strv(wasi.preopens);
    vm_ = we_vm_create_ex(WE_HOST_WASI, argv.data(), envv.data(),
                          prev.data());
  }
  ~Vm() { reset(); }
  Vm(Vm &&o) noexcept : vm_(o.vm_) { o.vm_ = nullptr; }
  Vm &operator=(Vm &&o) noexcept {
    if (this != &o) {
      reset();
      vm_ = o.vm_;
      o.vm_ = nullptr;
    }
    return *this;
  }
  Vm(const Vm &) = delete;
  Vm &operator=(const Vm &) = delete;

  bool valid() const { return vm_ != nullptr; }

  // -- staged pipeline (reference Vm::load_wasm/validate/instantiate) ----
  Result<bool> load(const std::string &wasm_path) {
    return unit(we_vm_load_file(vm_, wasm_path.c_str()));
  }
  Result<bool> validate() { return unit(we_vm_validate(vm_)); }
  Result<bool> instantiate() { return unit(we_vm_instantiate(vm_)); }

  // Execute an export of the instantiated module.
  Result<std::vector<Value>> execute(const std::string &func,
                                     const std::vector<Value> &args = {}) {
    std::vector<we_value> raw(args.size());
    for (size_t i = 0; i < args.size(); i++) raw[i] = args[i].raw();
    we_value out[16];
    int n = we_vm_execute(vm_, func.c_str(), raw.data(),
                          static_cast<int>(raw.size()), out, 16);
    return values(n, out);
  }

  // One-shot load+validate+instantiate+execute (Vm::run_func analog).
  Result<std::vector<Value>> run(const std::string &wasm_path,
                                 const std::string &func,
                                 const std::vector<Value> &args = {}) {
    std::vector<we_value> raw(args.size());
    for (size_t i = 0; i < args.size(); i++) raw[i] = args[i].raw();
    we_value out[16];
    int n = we_vm_run(vm_, wasm_path.c_str(), func.c_str(), raw.data(),
                      static_cast<int>(raw.size()), out, 16);
    return values(n, out);
  }

  // WASI command mode: run _start, return the guest's exit code
  // (the reference CLI's command-mode semantics, wasmedger.cpp:223-236).
  Result<int> run_wasi_command(const std::string &wasm_path) {
    we_value out[1];
    int n = we_vm_run(vm_, wasm_path.c_str(), "_start", nullptr, 0, out, 1);
    if (we_vm_wasi_has_exited(vm_))  // proc_exit unwinds as a "trap"
      return we_vm_wasi_exit_code(vm_);
    if (n < 0) return last_error(n);  // genuine trap / setup failure
    return 0;                         // _start returned normally
  }

  // Exported function names of the instantiated module.
  Result<std::vector<std::string>> function_list() {
    int n = we_vm_function_list(vm_, nullptr, 0);
    if (n < 0) return last_error(n);
    std::vector<char *> raw(static_cast<size_t>(n), nullptr);
    we_vm_function_list(vm_, raw.data(), n);
    std::vector<std::string> out;
    for (char *p : raw) {
      out.emplace_back(p ? p : "");
      std::free(p);
    }
    return out;
  }

  // Register a module file under an import namespace.
  Result<bool> register_module(const std::string &name,
                               const std::string &wasm_path) {
    return unit(we_vm_register_file(vm_, name.c_str(), wasm_path.c_str()));
  }

 private:
  void reset() {
    if (vm_) we_vm_delete(vm_);
    vm_ = nullptr;
  }
  static std::vector<const char *> c_strv(const std::vector<std::string> &v) {
    std::vector<const char *> out;
    for (const auto &s : v) out.push_back(s.c_str());
    out.push_back(nullptr);
    return out;
  }
  Result<bool> unit(int rc) {
    if (rc < 0) return last_error(rc);
    return true;
  }
  Result<std::vector<Value>> values(int n, const we_value *out) {
    if (n < 0) return last_error(n);
    std::vector<Value> vals;
    for (int i = 0; i < n && i < 16; i++)
      vals.push_back(Value::from_raw(out[i]));
    return vals;
  }

  we_vm *vm_ = nullptr;
};

// -- AOT compiler -----------------------------------------------------------

class Compiler {
 public:
  // wasm -> universal twasm (tpu.aot section), the reference's
  // wasmedgec analog.
  static Result<bool> compile(const std::string &in_path,
                              const std::string &out_path) {
    int rc = we_compile(in_path.c_str(), out_path.c_str());
    if (rc < 0) return last_error(rc);
    return true;
  }
};

}  // namespace wetpu

#endif  // WASMEDGE_TPU_HPP
