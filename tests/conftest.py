"""Test config: force JAX onto a virtual 8-device CPU mesh (no TPU needed).

Must run before any jax import, hence env mutation at conftest import time.
The driver's dryrun_multichip uses the same mechanism.
"""

import os

# Force, not setdefault: the session env pins JAX_PLATFORMS to the TPU
# plugin, but tests must be deterministic IEEE CPU (the TPU flushes f32
# denormals to zero — a documented batch-engine divergence, see
# wasmedge_tpu/batch/__init__.py).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin ignores JAX_PLATFORMS; only the config knob wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: minutes-scale suite, skipped by --fast")
    config.addinivalue_line(
        "markers", "faults: deterministic fault-injection suite "
        "(supervised execution; tier-1 fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "obs: observability suite (flight recorder, trace/"
        "metrics export; tier-1 fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "serve: continuous-batching serving suite (request "
        "queue, lane recycling, fairness; tier-1 fast, runs under "
        "-m 'not slow')")
    config.addinivalue_line(
        "markers", "analysis: static bytecode analyzer suite (CFG/"
        "cost/divergence reports, gateway admission policy; tier-1 "
        "fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "hv: lane-memory virtualization suite (swap store, "
        "eviction policy, oversubscribed serving; tier-1 fast, runs "
        "under -m 'not slow')")
    config.addinivalue_line(
        "markers", "fuse: SIMT superinstruction-fusion suite "
        "(translation pass, fused-dispatch bit-exactness, ladder "
        "demotion; tier-1 fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "tierup: compiled-function tier suite (whole-"
        "function promotion, per-call dispatch, demotion ladder; "
        "tier-1 fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "compact: divergence-aware lane-compaction suite "
        "(PC-sorted regrouping, serving/hv/checkpoint permutation "
        "remap; tier-1 fast, runs under -m 'not slow')")
    config.addinivalue_line(
        "markers", "effects: guest suspend/resume suite (parked "
        "sessions, external wake, streamed output; tier-1 fast, runs "
        "under -m 'not slow')")
    config.addinivalue_line(
        "markers", "integrity: silent-corruption defense suite "
        "(shadow-audit lanes, at-rest scrubbing, device quarantine; "
        "tier-1 fast, runs under -m 'not slow')")


def pytest_addoption(parser):
    parser.addoption(
        "--fast", action="store_true", default=False,
        help="run only the fast subset (skip @pytest.mark.slow suites)")


# Known minutes-scale suites are auto-marked slow so --fast works
# without touching each file; NEW slow files should carry
# `pytestmark = pytest.mark.slow` themselves (the marker is the
# mechanism, this list is back-compat).
_SLOW_FILES = {
    "test_spec.py", "test_batch_parity.py", "test_batch_simd.py",
    "test_pallas_engine.py", "test_pallas_hbm.py", "test_optimistic.py",
    "test_mesh.py", "test_scheduler.py", "test_simd.py",
}


def pytest_collection_modifyitems(config, items):
    """`pytest --fast` (or `-m "not slow"`) skips the slow suites —
    an iteration loop in ~minutes instead of the >60-minute nightly
    wall.  The slow suites stay the default so `python -m pytest
    tests/ -x -q` remains the full bar."""
    import pytest as _pytest

    for item in items:
        if item.fspath.basename in _SLOW_FILES:
            item.add_marker(_pytest.mark.slow)
    if not config.getoption("--fast"):
        return
    skip = _pytest.mark.skip(reason="slow suite (run without --fast)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
