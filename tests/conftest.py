"""Test config: force JAX onto a virtual 8-device CPU mesh (no TPU needed).

Must run before any jax import, hence env mutation at conftest import time.
The driver's dryrun_multichip uses the same mechanism.
"""

import os

# Force, not setdefault: the session env pins JAX_PLATFORMS to the TPU
# plugin, but tests must be deterministic IEEE CPU (the TPU flushes f32
# denormals to zero — a documented batch-engine divergence, see
# wasmedge_tpu/batch/__init__.py).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin ignores JAX_PLATFORMS; only the config knob wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
