"""Shared test helpers: build/instantiate/run in one line.

This is our analog of the reference's SpecTest callback seam
(/root/reference/test/spec/spectest.h:62-90): `run_wasm` drives any engine
through the same load->validate->instantiate->invoke staging, so parity
suites can swap engines underneath unchanged tests.
"""

from __future__ import annotations

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.validator import Validator


def load_validate(data: bytes, conf: Configure | None = None):
    conf = conf or Configure()
    return Validator(conf).validate(Loader(conf).parse_module(data))


def instantiate(data: bytes, conf: Configure | None = None, imports=None):
    conf = conf or Configure()
    mod = load_validate(data, conf)
    store = StoreManager()
    ex = Executor(conf)
    if imports:
        for obj in imports:
            ex.register_import_object(store, obj)
    inst = ex.instantiate(store, mod)
    return ex, store, inst


def run_wasm(data: bytes, func: str, args=(), conf: Configure | None = None,
             imports=None):
    ex, store, inst = instantiate(data, conf, imports)
    fi = inst.find_func(func)
    assert fi is not None, f"export {func} not found"
    return ex.invoke(store, fi, list(args))


def single_func(params, results, locals_, body, export="f") -> bytes:
    b = ModuleBuilder()
    b.add_function(params, results, locals_, body, export=export)
    return b.build()
