"""Generate the round-4 proposal corpus: SIMD, bulk memory, table ops,
reference types and tail calls.

Like _generate.py, every expected value is computed by the plain-Python
oracle below — deliberately independent of any engine in this framework,
mirroring how the official testsuite's expectations encode the spec's
semantics directly (reference seam:
/root/reference/test/spec/spectest.cpp:213-217).  Run
`python tests/spec/_generate_r4.py` to regenerate simd.wast,
bulk_memory.wast, table.wast, ref_types.wast and tail_call.wast in
place; tests/test_spec.py runs them through every engine.

SIMD coverage note: modules take i64 params and build v128 internally
(splat / replace_lane) and fold results back to i64, so the same
assertions also run on the batch engines, whose entry ABI is 64-bit
lane cells.  Inputs for float ops are packed normal-range floats — the
f32 subnormal-flush divergence of the XLA path is covered (and skipped)
by f32_subnormal.wast, not here.
"""

import math
import os
import struct

HERE = os.path.dirname(os.path.abspath(__file__))

MASK = {8: 0xFF, 16: 0xFFFF, 32: 0xFFFFFFFF, 64: (1 << 64) - 1}


def u(v, w):
    return v & MASK[w]


def s(v, w):
    v &= MASK[w]
    return v - (1 << w) if v >= (1 << (w - 1)) else v


def lanes(v, n, w):
    return [(v >> (w * k)) & MASK[w] for k in range(n)]


def pack(ls, w):
    v = 0
    for k, x in enumerate(ls):
        v |= (x & MASK[w]) << (w * k)
    return v


# -- float lane helpers (struct gives exact IEEE binary32 rounding) ---------
def f32b(x: float) -> int:
    return struct.unpack("<I", struct.pack("<f", x))[0]


def bf32(b: int) -> float:
    return struct.unpack("<f", struct.pack("<I", b & MASK[32]))[0]


def f64b(x: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", x))[0]


def bf64(b: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", b & MASK[64]))[0]


F32_CANON = 0x7FC00000
F64_CANON = 0x7FF8000000000000


def _fbin(op, a, b, w):
    """One float lane op on bit patterns; canonical-NaN outputs (the
    engines canonicalize arithmetic NaNs)."""
    fa = bf32(a) if w == 32 else bf64(a)
    fb = bf32(b) if w == 32 else bf64(b)
    if op in ("eq", "ne", "lt", "gt", "le", "ge"):
        r = {"eq": fa == fb, "ne": fa != fb, "lt": fa < fb,
             "gt": fa > fb, "le": fa <= fb, "ge": fa >= fb}[op]
        return MASK[w] if r else 0
    if op == "pmin":
        return b if fb < fa else a
    if op == "pmax":
        return b if fa < fb else a
    if op == "min":
        if math.isnan(fa) or math.isnan(fb):
            return F32_CANON if w == 32 else F64_CANON
        if fa == fb:  # ±0 ordering
            sa = a >> (w - 1)
            return a if sa else b
        r = min(fa, fb)
    elif op == "max":
        if math.isnan(fa) or math.isnan(fb):
            return F32_CANON if w == 32 else F64_CANON
        if fa == fb:
            sa = a >> (w - 1)
            return b if sa else a
        r = max(fa, fb)
    else:
        try:
            r = {"add": fa + fb, "sub": fa - fb, "mul": fa * fb,
                 "div": (fa / fb) if fb != 0 else (
                     math.inf if fa > 0 else -math.inf) if fa != 0
                 else math.nan}[op]
        except OverflowError:
            r = math.inf if (fa > 0) == (fb > 0) else -math.inf
    if isinstance(r, float) and math.isnan(r):
        return F32_CANON if w == 32 else F64_CANON
    return f32b(r) if w == 32 else f64b(r)


def _fun(op, a, w):
    fa = bf32(a) if w == 32 else bf64(a)
    if op == "abs":
        return a & (MASK[w] >> 1)
    if op == "neg":
        return a ^ (1 << (w - 1))
    if math.isnan(fa):
        return F32_CANON if w == 32 else F64_CANON
    if op == "sqrt":
        r = math.sqrt(fa) if fa >= 0 else math.nan
    elif op == "ceil":
        r = math.ceil(fa) if math.isfinite(fa) else fa
        r = math.copysign(r, fa) if r == 0 else r
    elif op == "floor":
        r = math.floor(fa) if math.isfinite(fa) else fa
        r = math.copysign(r, fa) if r == 0 else r
    elif op == "trunc":
        r = math.trunc(fa) if math.isfinite(fa) else fa
        r = math.copysign(r, fa) if r == 0 else r
    else:  # nearest (round-half-even)
        if math.isfinite(fa):
            fl = math.floor(fa)
            d = fa - fl
            if d < 0.5:
                r = fl
            elif d > 0.5:
                r = fl + 1
            else:
                r = fl if fl % 2 == 0 else fl + 1
            r = math.copysign(r, fa) if r == 0 else float(r)
        else:
            r = fa
    if isinstance(r, float) and math.isnan(r):
        return F32_CANON if w == 32 else F64_CANON
    return f32b(r) if w == 32 else f64b(r)


# -- SIMD op oracle (v128 as 128-bit int) -----------------------------------
def v_int_bin(op, shape_w, a, b):
    n = 128 // shape_w
    la, lb = lanes(a, n, shape_w), lanes(b, n, shape_w)
    out = []
    for x, y in zip(la, lb):
        sx, sy = s(x, shape_w), s(y, shape_w)
        hi_s = (1 << (shape_w - 1)) - 1
        lo_s = -(1 << (shape_w - 1))
        if op == "add":
            r = x + y
        elif op == "sub":
            r = x - y
        elif op == "mul":
            r = x * y
        elif op == "add_sat_s":
            r = max(lo_s, min(hi_s, sx + sy))
        elif op == "sub_sat_s":
            r = max(lo_s, min(hi_s, sx - sy))
        elif op == "add_sat_u":
            r = min(MASK[shape_w], x + y)
        elif op == "sub_sat_u":
            r = max(0, x - y)
        elif op == "min_s":
            r = min(sx, sy)
        elif op == "max_s":
            r = max(sx, sy)
        elif op == "min_u":
            r = min(x, y)
        elif op == "max_u":
            r = max(x, y)
        elif op == "avgr_u":
            r = (x + y + 1) >> 1
        elif op == "q15mulr_sat_s":
            r = max(lo_s, min(hi_s, (sx * sy + 0x4000) >> 15))
        elif op in ("eq", "ne", "lt_s", "lt_u", "gt_s", "gt_u",
                    "le_s", "le_u", "ge_s", "ge_u"):
            c = {"eq": x == y, "ne": x != y, "lt_s": sx < sy,
                 "lt_u": x < y, "gt_s": sx > sy, "gt_u": x > y,
                 "le_s": sx <= sy, "le_u": x <= y, "ge_s": sx >= sy,
                 "ge_u": x >= y}[op]
            r = MASK[shape_w] if c else 0
        else:
            raise KeyError(op)
        out.append(u(r, shape_w))
    return pack(out, shape_w)


def v_oracle(name, a, b=None, imm=None):
    """Evaluate one v128 op by name on 128-bit ints."""
    if name == "v128.and":
        return a & b
    if name == "v128.or":
        return a | b
    if name == "v128.xor":
        return a ^ b
    if name == "v128.andnot":
        return a & ~b & ((1 << 128) - 1)
    if name == "v128.not":
        return ~a & ((1 << 128) - 1)
    if name == "v128.bitselect":
        return (a & imm) | (b & ~imm & ((1 << 128) - 1))
    if name == "v128.any_true":
        return int(a != 0)
    px, op = name.split(".", 1)
    shapes = {"i8x16": 8, "i16x8": 16, "i32x4": 32, "i64x2": 64,
              "f32x4": 32, "f64x2": 64}
    w = shapes[px]
    n = 128 // w
    if px.startswith("f"):
        if op in ("add", "sub", "mul", "div", "min", "max", "pmin",
                  "pmax", "eq", "ne", "lt", "gt", "le", "ge"):
            return pack([_fbin(op, x, y, w) for x, y in
                         zip(lanes(a, n, w), lanes(b, n, w))], w)
        if op in ("abs", "neg", "sqrt", "ceil", "floor", "trunc",
                  "nearest"):
            return pack([_fun(op, x, w) for x in lanes(a, n, w)], w)
        if op == "splat":
            return pack([a & MASK[w]] * n, w)
        if op == "extract_lane":
            return lanes(a, n, w)[imm]
        if op == "replace_lane":
            ls = lanes(a, n, w)
            ls[imm] = b & MASK[w]
            return pack(ls, w)
        if op.startswith("convert_i32x4") or op.startswith(
                "convert_low_i32x4"):
            signed = op.endswith("_s")
            src = lanes(a, 4, 32)[:n]
            out = []
            for x in src:
                xv = s(x, 32) if signed else x
                out.append(f32b(float(xv)) if w == 32 else f64b(float(xv)))
            return pack(out, w)
        if op == "demote_f64x2_zero":
            return pack([f32b(bf64(x)) if not math.isnan(bf64(x))
                         else F32_CANON for x in lanes(a, 2, 64)] + [0, 0],
                        32)
        if op == "promote_low_f32x4":
            return pack([F64_CANON if math.isnan(bf32(x))
                         else f64b(bf32(x)) for x in lanes(a, 4, 32)[:2]],
                        64)
        raise KeyError(name)
    # integer shapes
    if op == "splat":
        return pack([a & MASK[w]] * n, w)
    if op in ("extract_lane", "extract_lane_u"):
        return lanes(a, n, w)[imm]
    if op == "extract_lane_s":
        return u(s(lanes(a, n, w)[imm], w), 64)
    if op == "replace_lane":
        ls = lanes(a, n, w)
        ls[imm] = b & MASK[w]
        return pack(ls, w)
    if op in ("abs", "neg"):
        out = []
        for x in lanes(a, n, w):
            sx = s(x, w)
            out.append(u(-sx if (op == "neg" or sx < 0) else sx, w))
        return pack(out, w)
    if op == "popcnt":
        return pack([bin(x).count("1") for x in lanes(a, n, w)], w)
    if op == "all_true":
        return int(all(x != 0 for x in lanes(a, n, w)))
    if op == "bitmask":
        m = 0
        for k, x in enumerate(lanes(a, n, w)):
            m |= (x >> (w - 1)) << k
        return m
    if op in ("shl", "shr_s", "shr_u"):
        sh = (b % w)
        out = []
        for x in lanes(a, n, w):
            if op == "shl":
                out.append(u(x << sh, w))
            elif op == "shr_u":
                out.append(x >> sh)
            else:
                out.append(u(s(x, w) >> sh, w))
        return pack(out, w)
    if op == "swizzle":
        xb = lanes(a, 16, 8)
        sel = lanes(b, 16, 8)
        return pack([xb[t] if t < 16 else 0 for t in sel], 8)
    if op == "shuffle":
        src = lanes(a, 16, 8) + lanes(b, 16, 8)
        return pack([src[t] for t in imm], 8)
    if op.startswith("narrow_"):
        sw = w * 2
        signed_out = op.endswith("_s")
        lo_, hi_ = ((-(1 << (w - 1)), (1 << (w - 1)) - 1)
                    if signed_out else (0, MASK[w]))
        vals = [s(x, sw) for x in lanes(a, 128 // sw, sw)] + \
               [s(x, sw) for x in lanes(b, 128 // sw, sw)]
        return pack([u(max(lo_, min(hi_, v)), w) for v in vals], w)
    if op.startswith("extend_"):
        sw = w // 2
        low = "_low_" in op
        signed = op.endswith("_s")
        src = lanes(a, 128 // sw, sw)
        src = src[:n] if low else src[n:]
        return pack([u(s(x, sw) if signed else x, w) for x in src], w)
    if op.startswith("extadd_pairwise"):
        sw = w // 2
        signed = op.endswith("_s")
        src = lanes(a, 128 // sw, sw)
        if signed:
            src = [s(x, sw) for x in src]
        return pack([u(src[2 * k] + src[2 * k + 1], w) for k in range(n)],
                    w)
    if op.startswith("extmul_"):
        sw = w // 2
        low = "_low_" in op
        signed = op.endswith("_s")
        xa = lanes(a, 128 // sw, sw)
        xb = lanes(b, 128 // sw, sw)
        xa = xa[:n] if low else xa[n:]
        xb = xb[:n] if low else xb[n:]
        if signed:
            xa = [s(x, sw) for x in xa]
            xb = [s(x, sw) for x in xb]
        return pack([u(x * y, w) for x, y in zip(xa, xb)], w)
    if op == "dot_i16x8_s":
        ha = [s(x, 16) for x in lanes(a, 8, 16)]
        hb = [s(x, 16) for x in lanes(b, 8, 16)]
        return pack([u(ha[2 * k] * hb[2 * k] + ha[2 * k + 1] *
                       hb[2 * k + 1], 32) for k in range(4)], 32)
    if op.startswith("trunc_sat_f32x4") or op.startswith(
            "trunc_sat_f64x2"):
        signed = "_s" in op.split("trunc_sat_")[1]
        src_w = 32 if "f32x4" in op else 64
        src = lanes(a, 128 // src_w, src_w)[:4 if src_w == 32 else 2]
        lo_, hi_ = ((-(1 << 31), (1 << 31) - 1) if signed
                    else (0, MASK[32]))
        out = []
        for x in src:
            f = bf32(x) if src_w == 32 else bf64(x)
            if math.isnan(f):
                out.append(0)
            else:
                out.append(u(max(lo_, min(hi_, math.trunc(f))), 32))
        while len(out) < 4:
            out.append(0)
        return pack(out, 32)
    return v_int_bin(op, w, a, b)


# -- wast emission ----------------------------------------------------------
def i64c(v):
    return f"(i64.const {s(v, 64)})"


def i32c(v):
    return f"(i32.const {s(v, 32)})"


def fold128():
    """v128 (on stack) -> i64: lane0 ^ 3*lane1."""
    return ("(local.set 2) "
            "(i64.xor (i64x2.extract_lane 0 (local.get 2)) "
            "(i64.mul (i64x2.extract_lane 1 (local.get 2)) "
            "(i64.const 3)))")


def fold_py(v):
    l0, l1 = lanes(v, 2, 64)
    return u(l0 ^ u(l1 * 3, 64), 64)


K1 = 0x9E3779B97F4A7C15
K2 = 0xC2B2AE3D27D4EB4F


def vec_a():
    """wat expr building v128 local $a from i64 param 0 (scrambled)."""
    return ("(i64x2.replace_lane 1 (i64x2.splat (local.get 0)) "
            f"(i64.mul (local.get 0) (i64.const {s(K1, 64)})))")


def vec_b():
    return ("(i64x2.replace_lane 1 (i64x2.splat (local.get 1)) "
            f"(i64.xor (local.get 1) (i64.const {s(K2, 64)})))")


def vec_a_py(x):
    return pack([u(x, 64), u(x * K1, 64)], 64)


def vec_b_py(y):
    return pack([u(y, 64), u(y ^ K2, 64)], 64)


INT_PAIRS = [
    (0, 0), (1, 2), (0xFFFFFFFFFFFFFFFF, 1),
    (0x8000000000000000, 0x7FFFFFFFFFFFFFFF),
    (0x0102030405060708, 0x1112131415161718),
    (0x8081828384858687, 0x00FF00FF00FF00FF),
    (0x7F80FF017FFF8000, 0x0101010101010101),
    (0xDEADBEEFCAFEBABE, 0x0123456789ABCDEF),
    (0x8000000180000001, 0xFFFFFFFE00000002),
    (0x00007FFF00008000, 0xFFFF8000FFFF7FFF),
    (0x55AA55AA55AA55AA, 0xAA55AA55AA55AA55),
    (0x0000000100000002, 0x0000000300000004),
]


def float_pairs(w):
    """i64 args packing two normal floats per arg."""
    vals = [0.0, -0.0, 1.0, -1.5, 2.25, 100.5, -3.75, 0.5, 7.0, -2.0,
            1234.5, -0.125]
    out = []
    if w == 32:
        for i in range(0, len(vals) - 3, 2):
            x = pack([f32b(vals[i]), f32b(vals[i + 1])], 32) & MASK[64]
            y = pack([f32b(vals[i + 2]), f32b(vals[i + 3])], 32) & MASK[64]
            out.append((x, y))
        out.append((pack([f32b(0.0), f32b(-0.0)], 32),
                    pack([f32b(-0.0), f32b(0.0)], 32)))
    else:
        for i in range(0, len(vals) - 1, 2):
            out.append((f64b(vals[i]), f64b(vals[i + 1])))
        out.append((f64b(0.0), f64b(-0.0)))
    return out


def gen_simd(path):
    from wasmedge_tpu.batch.simdops import (
        V1_NAMES, V2_NAMES, VSHIFT_NAMES, VTEST_NAMES)

    # no memory: the whole module stays inside the batch engines'
    # entry subset, so these assertions run on tpu_batch too (the v128
    # load/store roundtrip lives in bulk_memory.wast instead)
    mod = ["(module"]
    asserts = []

    def add_func(name, body):
        mod.append(
            f'  (func (export "{name}") (param i64 i64) (result i64)'
            f" (local v128) {body})")

    def scrambled(fn_name, apply_expr, oracle, pairs=INT_PAIRS,
                  plain=False):
        va, vb = ("(i64x2.splat (local.get 0))",
                  "(i64x2.splat (local.get 1))") if plain \
            else (vec_a(), vec_b())
        add_func(fn_name, apply_expr(va, vb) + " " + fold128())
        for x, y in pairs:
            a = (pack([u(x, 64)] * 2, 64) if plain else vec_a_py(x))
            b = (pack([u(y, 64)] * 2, 64) if plain else vec_b_py(y))
            asserts.append(
                f'(assert_return (invoke "{fn_name}" {i64c(x)} {i64c(y)})'
                f" {i64c(fold_py(oracle(a, b)))})")

    # binary families (ints scrambled, floats plain normal-range)
    for name in V2_NAMES:
        fn = name.replace(".", "_")
        is_f = name.split(".")[0] in ("f32x4", "f64x2")
        pairs = float_pairs(32 if name.startswith("f32x4") else 64) \
            if is_f else INT_PAIRS
        scrambled(fn, lambda va, vb, name=name: f"({name} {va} {vb})",
                  lambda a, b, name=name: v_oracle(name, a, b),
                  pairs=pairs, plain=is_f)
    # unary families
    for name in V1_NAMES:
        fn = "u_" + name.replace(".", "_")
        is_f = ("f32x4" in name or "f64x2" in name)
        pairs = float_pairs(32 if "f32x4" in name else 64) \
            if is_f else INT_PAIRS
        scrambled(fn, lambda va, vb, name=name: f"({name} {va})",
                  lambda a, b, name=name: v_oracle(name, a),
                  pairs=pairs, plain=is_f)
    # test/bitmask family -> i64 result via extend
    for name in VTEST_NAMES:
        fn = "t_" + name.replace(".", "_")
        add_func(fn, f"(i64.extend_i32_u ({name} {vec_a()}))")
        for x, y in INT_PAIRS:
            asserts.append(
                f'(assert_return (invoke "{fn}" {i64c(x)} {i64c(y)})'
                f" {i64c(v_oracle(name, vec_a_py(x)))})")
    # shifts: amount from param 1
    for name in VSHIFT_NAMES:
        fn = "s_" + name.replace(".", "_")
        add_func(fn, f"({name} {vec_a()} "
                     "(i32.wrap_i64 (local.get 1))) " + fold128())
        for x, _ in INT_PAIRS[:8]:
            for sh in (0, 1, 7, 13, 31, 63):
                asserts.append(
                    f'(assert_return (invoke "{fn}" {i64c(x)} '
                    f"{i64c(sh)}) "
                    f"{i64c(fold_py(v_oracle(name, vec_a_py(x), sh)))})")
    # lane extract/replace at literal lanes + shuffle/swizzle/bitselect
    for shape, nl in (("i8x16", 16), ("i16x8", 8), ("i32x4", 4),
                      ("i64x2", 2)):
        for lane in sorted({0, nl // 2, nl - 1}):
            sfx = ("_s" if shape in ("i8x16", "i16x8") else "")
            nm = f"{shape}.extract_lane{sfx}"
            fn = f"x_{shape}_{lane}"
            body = f"({nm} {lane} {vec_a()})"
            if shape != "i64x2":
                body = f"(i64.extend_i32_s {body})"
            add_func(fn, body)
            for x, y in INT_PAIRS[:6]:
                want = v_oracle(nm, vec_a_py(x), imm=lane)
                if shape != "i64x2":
                    want = u(s(want, 64 if sfx else 32)
                             if not sfx else want, 64)
                asserts.append(
                    f'(assert_return (invoke "{fn}" {i64c(x)} {i64c(y)})'
                    f" {i64c(want)})")
            rn = f"{shape}.replace_lane"
            fn = f"r_{shape}_{lane}"
            src = "(i32.wrap_i64 (local.get 1))" if shape != "i64x2" \
                else "(local.get 1)"
            add_func(fn, f"({rn} {lane} {vec_a()} {src}) " + fold128())
            for x, y in INT_PAIRS[:6]:
                want = v_oracle(rn, vec_a_py(x),
                                u(y, 64 if shape == "i64x2" else 32),
                                imm=lane)
                asserts.append(
                    f'(assert_return (invoke "{fn}" {i64c(x)} {i64c(y)})'
                    f" {i64c(fold_py(want))})")
    shuf = [0, 17, 2, 19, 4, 21, 6, 23, 8, 25, 10, 27, 12, 29, 14, 31]
    add_func("shuffle", "(i8x16.shuffle " + " ".join(map(str, shuf)) +
             f" {vec_a()} {vec_b()}) " + fold128())
    add_func("bitsel", f"(v128.bitselect {vec_a()} {vec_b()} "
             "(v128.const i64x2 0x00FF00FF00FF00FF "
             "0xFFFF0000FFFF0000)) " + fold128())
    mask = pack([0x00FF00FF00FF00FF, 0xFFFF0000FFFF0000], 64)
    for x, y in INT_PAIRS:
        a, b = vec_a_py(x), vec_b_py(y)
        asserts.append(
            f'(assert_return (invoke "shuffle" {i64c(x)} {i64c(y)}) '
            f"{i64c(fold_py(v_oracle('i8x16.shuffle', a, b, imm=shuf)))})")
        asserts.append(
            f'(assert_return (invoke "bitsel" {i64c(x)} {i64c(y)}) '
            f"{i64c(fold_py(v_oracle('v128.bitselect', a, b, imm=mask)))})")
    mod.append(")")
    _write(path, mod, asserts, "SIMD v128 semantics")


def gen_bulk(path):
    seg = bytes(range(1, 33))  # 32 bytes, passive
    mem = bytearray(65536)
    mod = [
        "(module",
        "  (memory 1)",
        '  (data $p "' + "".join(f"\\{b:02x}" for b in seg) + '")',
        '  (func (export "fill") (param i32 i32 i32)',
        "    (memory.fill (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "copy") (param i32 i32 i32)',
        "    (memory.copy (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "init") (param i32 i32 i32)',
        "    (memory.init $p (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "drop") (data.drop $p))',
        '  (func (export "ld8") (param i32) (result i32)',
        "    (i32.load8_u (local.get 0)))",
        '  (func (export "ld32") (param i32) (result i32)',
        "    (i32.load (local.get 0)))",
        '  (func (export "vmemrt") (param i64 i64) (result i64) '
        "(local v128)",
        "    (v128.store (i32.const 1024) (i64x2.replace_lane 1 "
        "(i64x2.splat (local.get 0)) (local.get 1)))",
        "    (v128.store offset=16 (i32.const 1024) "
        "(v128.load (i32.const 1024)))",
        "    (local.set 2 (v128.load offset=16 (i32.const 1024)))",
        "    (i64.xor (i64x2.extract_lane 0 (local.get 2)) "
        "(i64.mul (i64x2.extract_lane 1 (local.get 2)) (i64.const 3))))",
        ")",
    ]
    asserts = []
    for x, y in ((0, 0), (0x0123456789ABCDEF, 0xFEDCBA9876543210),
                 ((1 << 64) - 1, 1), (0x55AA55AA55AA55AA, 0x8000000000000000)):
        want = u(x ^ u(y * 3, 64), 64)
        asserts.append(f'(assert_return (invoke "vmemrt" {i64c(x)} '
                       f"{i64c(y)}) {i64c(want)})")

    def fill(d, v, n):
        asserts.append(f'(assert_return (invoke "fill" {i32c(d)} '
                       f"{i32c(v)} {i32c(n)}))")
        mem[d:d + n] = bytes([v & 0xFF]) * n

    def copy(d, sr, n):
        asserts.append(f'(assert_return (invoke "copy" {i32c(d)} '
                       f"{i32c(sr)} {i32c(n)}))")
        mem[d:d + n] = bytes(mem[sr:sr + n])

    def init(d, sr, n):
        asserts.append(f'(assert_return (invoke "init" {i32c(d)} '
                       f"{i32c(sr)} {i32c(n)}))")
        mem[d:d + n] = seg[sr:sr + n]

    def check(addrs):
        for a in addrs:
            asserts.append(f'(assert_return (invoke "ld8" {i32c(a)}) '
                           f"{i32c(mem[a])})")

    fill(0, 0xAB, 64)
    check([0, 1, 63, 64])
    fill(100, 0x5A, 1)
    fill(101, 0, 0)          # zero length is a no-op
    check([99, 100, 101])
    init(200, 0, 32)
    check([200, 215, 231, 232])
    init(300, 8, 8)
    init(310, 31, 1)
    init(311, 32, 0)         # at-end zero init ok
    check([300, 307, 310, 311])
    copy(400, 200, 32)       # disjoint
    check([400, 431, 432])
    copy(410, 400, 16)       # overlap forward (dst > src)
    check(list(range(400, 434)))
    copy(395, 400, 16)       # overlap backward
    check(list(range(393, 418)))
    fill(65530, 0x77, 6)     # fill to the very end
    check([65530, 65535])
    copy(0, 65520, 16)
    check([0, 15, 16])
    # traps: range past end (note: no partial writes observable after)
    asserts.append('(assert_trap (invoke "fill" (i32.const 65530) '
                   '(i32.const 1) (i32.const 7)) '
                   '"out of bounds memory access")')
    asserts.append('(assert_trap (invoke "copy" (i32.const 65530) '
                   '(i32.const 0) (i32.const 7)) '
                   '"out of bounds memory access")')
    asserts.append('(assert_trap (invoke "copy" (i32.const 0) '
                   '(i32.const 65530) (i32.const 7)) '
                   '"out of bounds memory access")')
    asserts.append('(assert_trap (invoke "init" (i32.const 0) '
                   '(i32.const 0) (i32.const 33)) '
                   '"out of bounds memory access")')
    asserts.append('(assert_trap (invoke "init" (i32.const 65535) '
                   '(i32.const 0) (i32.const 2)) '
                   '"out of bounds memory access")')
    # zero-length at boundary must NOT trap
    asserts.append('(assert_return (invoke "fill" (i32.const 65536) '
                   '(i32.const 0) (i32.const 0)))')
    asserts.append('(assert_return (invoke "copy" (i32.const 65536) '
                   '(i32.const 0) (i32.const 0)))')
    # ...but one past it must
    asserts.append('(assert_trap (invoke "fill" (i32.const 65537) '
                   '(i32.const 0) (i32.const 0)) '
                   '"out of bounds memory access")')
    # after data.drop, init of n>0 traps, n=0 passes
    asserts.append('(assert_return (invoke "drop"))')
    asserts.append('(assert_return (invoke "drop"))')  # double drop ok
    asserts.append('(assert_trap (invoke "init" (i32.const 0) '
                   '(i32.const 0) (i32.const 1)) '
                   '"out of bounds memory access")')
    asserts.append('(assert_return (invoke "init" (i32.const 0) '
                   '(i32.const 0) (i32.const 0)))')
    check(list(range(0, 48)))
    _write(path, mod, asserts, "bulk memory: fill/copy/init/drop")


def gen_table(path):
    funcs = [11, 22, 33, 44, 55]
    mod = [
        "(module",
        "  (table $t 10 20 funcref)",
        "  (table $u 4 funcref)",
    ]
    for i, v in enumerate(funcs):
        mod.append(f"  (func $f{i} (result i32) (i32.const {v}))")
    mod += [
        "  (elem $e func $f0 $f1 $f2 $f3 $f4)",
        "  (elem (table $t) (i32.const 0) $f0 $f1)",
        '  (func (export "call") (param i32) (result i32)',
        "    (call_indirect $t (result i32) (local.get 0)))",
        '  (func (export "callu") (param i32) (result i32)',
        "    (call_indirect $u (result i32) (local.get 0)))",
        '  (func (export "size") (result i32) (table.size $t))',
        '  (func (export "grow") (param i32) (result i32)',
        "    (table.grow $t (ref.null func) (local.get 0)))",
        '  (func (export "fillnull") (param i32 i32)',
        "    (table.fill $t (local.get 0) (ref.null func) (local.get 1)))",
        '  (func (export "fillf4") (param i32 i32)',
        "    (table.fill $t (local.get 0) (ref.func $f4) (local.get 1)))",
        '  (func (export "init") (param i32 i32 i32)',
        "    (table.init $t $e (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "copy") (param i32 i32 i32)',
        "    (table.copy $t $t (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "xcopy") (param i32 i32 i32)',
        "    (table.copy $u $t (local.get 0) (local.get 1) (local.get 2)))",
        '  (func (export "edrop") (elem.drop $e))',
        '  (func (export "isnull") (param i32) (result i32)',
        "    (ref.is_null (table.get $t (local.get 0))))",
        '  (func (export "setget") (param i32 i32) (result i32)',
        "    (table.set $t (local.get 0) (table.get $t (local.get 1)))",
        "    (ref.is_null (table.get $t (local.get 0))))",
        ")",
    ]
    # oracle model: table t (size 10, max 20) of func VALUES (None=null)
    t = [11, 22] + [None] * 8
    tu = [None] * 4
    asserts = []

    def call(i):
        if i >= len(t):
            asserts.append(f'(assert_trap (invoke "call" {i32c(i)}) '
                           '"undefined element")')
        elif t[i] is None:
            asserts.append(f'(assert_trap (invoke "call" {i32c(i)}) '
                           '"uninitialized element")')
        else:
            asserts.append(f'(assert_return (invoke "call" {i32c(i)}) '
                           f"{i32c(t[i])})")

    def sweep():
        for i in (0, 1, 2, 5, 9, len(t), 25):
            call(i)

    sweep()
    asserts.append(f'(assert_return (invoke "size") {i32c(len(t))})')
    asserts.append(f'(assert_return (invoke "grow" (i32.const 4)) '
                   f"{i32c(len(t))})")
    t += [None] * 4
    asserts.append(f'(assert_return (invoke "size") {i32c(len(t))})')
    # grow beyond max fails with -1
    asserts.append('(assert_return (invoke "grow" (i32.const 100)) '
                   '(i32.const -1))')
    asserts.append(f'(assert_return (invoke "init" (i32.const 4) '
                   f"(i32.const 1) (i32.const 3)))")
    t[4:7] = funcs[1:4]
    sweep()
    call(6)
    asserts.append('(assert_return (invoke "fillf4" (i32.const 8) '
                   '(i32.const 3)))')
    t[8:11] = [funcs[4]] * 3
    call(8)
    call(10)
    asserts.append('(assert_return (invoke "copy" (i32.const 11) '
                   '(i32.const 4) (i32.const 3)))')
    t[11:14] = t[4:7]
    call(11)
    call(13)
    # overlapping copy backward
    asserts.append('(assert_return (invoke "copy" (i32.const 3) '
                   '(i32.const 4) (i32.const 4)))')
    t[3:7] = t[4:8]
    sweep()
    # cross-table copy u <- t
    asserts.append('(assert_return (invoke "xcopy" (i32.const 0) '
                   '(i32.const 3) (i32.const 4)))')
    tu[0:4] = t[3:7]
    for i in range(4):
        if tu[i] is None:
            asserts.append(f'(assert_trap (invoke "callu" {i32c(i)}) '
                           '"uninitialized element")')
        else:
            asserts.append(f'(assert_return (invoke "callu" {i32c(i)}) '
                           f"{i32c(tu[i])})")
    # fill with null then observe
    asserts.append('(assert_return (invoke "fillnull" (i32.const 4) '
                   '(i32.const 2)))')
    t[4:6] = [None, None]
    call(4)
    call(5)
    asserts.append('(assert_return (invoke "isnull" (i32.const 4)) '
                   '(i32.const 1))')
    asserts.append('(assert_return (invoke "isnull" (i32.const 3)) '
                   '(i32.const 0))')
    asserts.append('(assert_return (invoke "setget" (i32.const 9) '
                   '(i32.const 3)) (i32.const 0))')
    t[9] = t[3]
    call(9)
    # oob table ops trap
    asserts.append('(assert_trap (invoke "fillnull" (i32.const 13) '
                   '(i32.const 2)) "out of bounds table access")')
    asserts.append('(assert_trap (invoke "copy" (i32.const 13) '
                   '(i32.const 0) (i32.const 2)) '
                   '"out of bounds table access")')
    asserts.append('(assert_trap (invoke "init" (i32.const 0) '
                   '(i32.const 4) (i32.const 2)) '
                   '"out of bounds table access")')
    # zero-length at boundary ok, past-boundary traps
    asserts.append(f'(assert_return (invoke "fillnull" {i32c(len(t))} '
                   '(i32.const 0)))')
    asserts.append(f'(assert_trap (invoke "fillnull" {i32c(len(t) + 1)} '
                   '(i32.const 0)) "out of bounds table access")')
    # elem.drop then init traps (n>0), ok (n=0)
    asserts.append('(assert_return (invoke "edrop"))')
    asserts.append('(assert_return (invoke "edrop"))')
    asserts.append('(assert_trap (invoke "init" (i32.const 0) '
                   '(i32.const 0) (i32.const 1)) '
                   '"out of bounds table access")')
    asserts.append('(assert_return (invoke "init" (i32.const 0) '
                   '(i32.const 0) (i32.const 0)))')
    sweep()
    _write(path, mod, asserts, "table mutation + call_indirect")


def gen_ref_types(path):
    mod = [
        "(module",
        "  (table $t 8 externref)",
        # exported => declared, so ref.func $id is valid
        '  (func $id (export "idf") (param i32) (result i32) '
        "(local.get 0))",
        '  (func (export "null_f") (result i32)',
        "    (ref.is_null (ref.null func)))",
        '  (func (export "null_e") (result i32)',
        "    (ref.is_null (ref.null extern)))",
        '  (func (export "fref") (result i32)',
        "    (ref.is_null (ref.func $id)))",
        '  (func (export "eset") (param i32 externref)',
        "    (table.set $t (local.get 0) (local.get 1)))",
        '  (func (export "eget") (param i32) (result externref)',
        "    (table.get $t (local.get 0)))",
        '  (func (export "eisnull") (param i32) (result i32)',
        "    (ref.is_null (table.get $t (local.get 0))))",
        '  (func (export "select_r") (param externref externref i32) '
        "(result externref)",
        "    (select (result externref) (local.get 0) (local.get 1) "
        "(local.get 2)))",
        ")",
    ]
    asserts = [
        '(assert_return (invoke "null_f") (i32.const 1))',
        '(assert_return (invoke "null_e") (i32.const 1))',
        '(assert_return (invoke "fref") (i32.const 0))',
    ]
    for i in range(8):
        asserts.append(f'(assert_return (invoke "eisnull" {i32c(i)}) '
                       '(i32.const 1))')
    # externref values flow through invoke as ref.extern handles
    asserts.append('(assert_return (invoke "eset" (i32.const 3) '
                   '(ref.extern 7)))')
    asserts.append('(assert_return (invoke "eisnull" (i32.const 3)) '
                   '(i32.const 0))')
    asserts.append('(assert_return (invoke "eget" (i32.const 3)) '
                   '(ref.extern 7))')
    asserts.append('(assert_return (invoke "eget" (i32.const 4)) '
                   '(ref.null))')
    asserts.append('(assert_return (invoke "select_r" (ref.extern 5) '
                   '(ref.extern 6) (i32.const 1)) (ref.extern 5))')
    asserts.append('(assert_return (invoke "select_r" (ref.extern 5) '
                   '(ref.extern 6) (i32.const 0)) (ref.extern 6))')
    asserts.append('(assert_trap (invoke "eget" (i32.const 8)) '
                   '"out of bounds table access")')
    _write(path, mod, asserts, "reference types: null/func/extern refs")


def gen_tail_call(path):
    mod = [
        "(module",
        "  (table $t 2 funcref)",
        '  (func $even (export "even") (param i64) (result i32)',
        "    (if (result i32) (i64.eqz (local.get 0))",
        "      (then (i32.const 1))",
        "      (else (return_call $odd (i64.sub (local.get 0) "
        "(i64.const 1))))))",
        '  (func $odd (export "odd") (param i64) (result i32)',
        "    (if (result i32) (i64.eqz (local.get 0))",
        "      (then (i32.const 0))",
        "      (else (return_call $even (i64.sub (local.get 0) "
        "(i64.const 1))))))",
        '  (func $count (export "count") (param i64 i64) (result i64)',
        "    (if (result i64) (i64.eqz (local.get 0))",
        "      (then (local.get 1))",
        "      (else (return_call $count (i64.sub (local.get 0) "
        "(i64.const 1)) (i64.add (local.get 1) (i64.const 1))))))",
        '  (func $fac_acc (param i64 i64) (result i64)',
        "    (if (result i64) (i64.eqz (local.get 0))",
        "      (then (local.get 1))",
        "      (else (return_call_indirect (param i64 i64) (result i64)",
        "        (i64.sub (local.get 0) (i64.const 1))",
        "        (i64.mul (local.get 0) (local.get 1))",
        "        (i32.const 0)))))",
        '  (func (export "fac") (param i64) (result i64)',
        "    (return_call $fac_acc (local.get 0) (i64.const 1)))",
        '  (func $burn (export "burn") (param i64) (result i64)',
        "    (if (result i64) (i64.eqz (local.get 0))",
        "      (then (i64.const 0))",
        "      (else (i64.add (i64.const 1) (call $burn (i64.sub "
        "(local.get 0) (i64.const 1)))))))",
        "  (elem (i32.const 0) $fac_acc $count)",
        ")",
    ]
    asserts = []
    for n, want in ((0, 1), (1, 0), (7, 0), (100, 1), (100001, 0)):
        asserts.append(f'(assert_return (invoke "even" {i64c(n)}) '
                       f"{i32c(want)})")
    # tail calls run in constant stack: 200k alternating frames
    asserts.append('(assert_return (invoke "even" (i64.const 200000)) '
                   '(i32.const 1))')
    for n in (0, 1, 5, 50000):
        asserts.append(f'(assert_return (invoke "count" {i64c(n)} '
                       f"(i64.const 0)) {i64c(n)})")

    def fac(n):
        r = 1
        for k in range(2, n + 1):
            r = u(r * k, 64)
        return r

    for n in (0, 1, 5, 12, 25):
        asserts.append(f'(assert_return (invoke "fac" {i64c(n)}) '
                       f"{i64c(fac(n))})")
    # ordinary deep recursion still exhausts the stack (contrast case)
    asserts.append('(assert_exhaustion (invoke "burn" '
                   '(i64.const 100000000)) "call stack exhausted")')
    _write(path, mod, asserts, "tail calls: constant-stack recursion")


def _write(path, mod_lines, asserts, title):
    with open(path, "w") as f:
        f.write(f";; {title} — generated by _generate_r4.py\n")
        f.write(";; (independent oracle: plain Python arithmetic; "
                "do not edit by hand)\n")
        f.write("\n".join(mod_lines))
        f.write("\n")
        f.write("\n".join(asserts))
        f.write("\n")
    print(f"{os.path.basename(path)}: {len(asserts)} assertions")


def main():
    import sys
    sys.path.insert(0, os.path.join(HERE, os.pardir, os.pardir))
    gen_simd(os.path.join(HERE, "simd.wast"))
    gen_bulk(os.path.join(HERE, "bulk_memory.wast"))
    gen_table(os.path.join(HERE, "table.wast"))
    gen_ref_types(os.path.join(HERE, "ref_types.wast"))
    gen_tail_call(os.path.join(HERE, "tail_call.wast"))


if __name__ == "__main__":
    main()
