;; malformed binaries (load phase) and invalid modules (validation phase)
(assert_malformed (module binary "") "unexpected end")
(assert_malformed (module binary "\00asm") "unexpected end")
(assert_malformed (module binary "\01asm\01\00\00\00") "magic header not detected")
(assert_malformed (module binary "\00asm\02\00\00\00") "unknown binary version")
;; truncated section payload
(assert_malformed (module binary "\00asm\01\00\00\00\01\05\01") "unexpected end")
;; function/code count mismatch
(assert_malformed
  (module binary
    "\00asm\01\00\00\00"
    "\01\04\01\60\00\00"      ;; type ()->()
    "\03\02\01\00")           ;; func section: 1 func, no code section
  "function and code section have inconsistent lengths")

(assert_invalid (module (func (result i32))) "type mismatch")
(assert_invalid (module (func (result i32) (i64.const 1))) "type mismatch")
(assert_invalid (module (func (i32.add (i32.const 1)))) "type mismatch")
(assert_invalid (module (func (drop (i32.const 1)) (drop))) "type mismatch")
(assert_invalid (module (func (local.get 0))) "unknown local")
(assert_invalid (module (func (param i32) (local.get 1))) "unknown local")
(assert_invalid (module (func (br 1))) "unknown label")
(assert_invalid (module (func (result i32) (block (result i32) (br 0)))) "type mismatch")
(assert_invalid
  (module (global $g i32 (i32.const 1))
          (func (global.set $g (i32.const 2))))
  "global is immutable")
(assert_invalid (module (func (i32.load (i32.const 0)))) "unknown memory")
(assert_invalid (module (memory 1) (func (i32.load (f32.const 0) ))) "type mismatch")
(assert_invalid (module (func (call 5))) "unknown function")
(assert_invalid (module (func (unreachable) (i64.add)) (memory 1)) "")
