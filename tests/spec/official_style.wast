;; Official-testsuite syntax stress: the gnarliest *syntactic* shapes the
;; upstream corpus uses, as a standing proof the harness ingests
;; official-style scripts unchanged (reference driver:
;; /root/reference/test/spec/spectest.cpp:150-217).  Hand-authored; the
;; expectations are trivial constants checked by inspection.

;; -- named blocks / branches, deeply nested, by-name label refs --------
(module $labels
  (func (export "nested") (param i32) (result i32)
    (block $outer (result i32)
      (block $mid
        (block $inner
          (br_if $inner (i32.eq (local.get 0) (i32.const 0)))
          (br_if $mid (i32.eq (local.get 0) (i32.const 1)))
          (br $outer (i32.const 30)))
        ;; fell out of $inner: local 0 == 0
        (br $outer (i32.const 10)))
      ;; fell out of $mid: local 0 == 1
      (i32.const 20)))
  (func (export "loopname") (param i32) (result i32)
    (local $acc i32)
    (block $done
      (loop $again
        (br_if $done (i32.eqz (local.get 0)))
        (local.set $acc (i32.add (local.get $acc) (local.get 0)))
        (local.set 0 (i32.sub (local.get 0) (i32.const 1)))
        (br $again)))
    (local.get $acc))
  (func (export "ifname") (param i32) (result i32)
    (if $sel (result i32) (local.get 0)
      (then (br $sel (i32.const 7)))
      (else (i32.const 8)))))
(assert_return (invoke "nested" (i32.const 0)) (i32.const 10))
(assert_return (invoke "nested" (i32.const 1)) (i32.const 20))
(assert_return (invoke "nested" (i32.const 2)) (i32.const 30))
(assert_return (invoke "loopname" (i32.const 5)) (i32.const 15))
(assert_return (invoke "ifname" (i32.const 1)) (i32.const 7))
(assert_return (invoke "ifname" (i32.const 0)) (i32.const 8))

;; -- multi-module register chain ---------------------------------------
(module $provider
  (global (export "base") i32 (i32.const 40))
  (func (export "mul3") (param i32) (result i32)
    (i32.mul (local.get 0) (i32.const 3))))
(register "chain1" $provider)
(module $middle
  (import "chain1" "mul3" (func $m3 (param i32) (result i32)))
  (import "chain1" "base" (global $b i32))
  (func (export "combine") (param i32) (result i32)
    (i32.add (call $m3 (local.get 0)) (global.get $b))))
(register "chain2" $middle)
(module
  (import "chain2" "combine" (func $c (param i32) (result i32)))
  (func (export "top") (param i32) (result i32)
    (i32.add (call $c (local.get 0)) (i32.const 1))))
(assert_return (invoke "top" (i32.const 2)) (i32.const 47))
;; invoke against an earlier NAMED module while a later one is active
(assert_return (invoke $provider "mul3" (i32.const 9)) (i32.const 27))
(assert_return (invoke $middle "combine" (i32.const 1)) (i32.const 43))

;; -- module quote / binary forms ---------------------------------------
(assert_malformed
  (module quote "(func (export \"f\") (result i32) (i32.const")
  "unexpected end")
(assert_malformed (module quote "(func) (oops)") "unknown")
(module binary
  "\00asm\01\00\00\00"
  "\01\05\01\60\00\01\7f"        ;; type () -> i32
  "\03\02\01\00"                 ;; one function
  "\07\05\01\01\62\00\00"        ;; export "b"
  "\0a\06\01\04\00\41\2c\0b")    ;; body: i32.const 44
(assert_return (invoke "b") (i32.const 44))

;; -- NaN payload / class asserts ---------------------------------------
(module $nans
  (func (export "cnan32") (result f32)
    (f32.add (f32.const nan) (f32.const 1)))
  (func (export "anan64") (result f64)
    (f64.sub (f64.const nan:0x4000000000000) (f64.const inf)))
  (func (export "paynan") (result f32) (f32.const nan:0x200000))
  (func (export "negz") (result f32)
    (f32.mul (f32.const -0x0p+0) (f32.const 0x1p+0))))
(assert_return (invoke "cnan32") (f32.const nan:canonical))
(assert_return (invoke "anan64") (f64.const nan:arithmetic))
(assert_return (invoke "paynan") (f32.const nan:0x200000))
(assert_return (invoke "negz") (f32.const -0x0p+0))

;; -- hex/underscore literal forms, folded+plain mixing ------------------
(module
  (func (export "lits") (result i64)
    i64.const 0x10
    (i64.const 1_000_000)
    i64.add
    (i64.add (i64.const -0x8000_0000_0000_0000))))
(assert_return (invoke "lits") (i64.const -9223372036853775792))
