"""Abstract-interpretation value-range analysis (analysis/absint.py).

Pins the r19 tentpole's contracts:

  - counted loops flip the seed's blanket "unbounded" verdict to a
    finite SOUND cost bound, EXACT on the canonical latch-tested
    fixture (cost_bound == the engine's measured retired max);
  - the CFG edge cases the interpreter leans on: br_table entry tables
    as loop back-edges, nested-loop widening termination, and a
    self-recursive function staying honestly "unbounded";
  - memory-effect facts: licensed (proven in-bounds + aligned) sites
    vs refused misaligned / OOB-adjacent ones, and the proven
    page-touch bound with its hv budget seeding;
  - the report schema: absint keys validate, PRE-absint reports still
    validate (back-compat), and the reconciliation rules fire.

Fast by construction (pure-python analysis, tiny engine rigs): tier-1.
"""

import numpy as np
import pytest

from wasmedge_tpu.analysis import analyze_validated, validate_report
from wasmedge_tpu.analysis.policy import AnalysisPolicy
from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.models import (
    build_counted_loop,
    build_fib,
    build_loop_sum,
    build_memfuse_workload,
)
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate, load_validate

pytestmark = pytest.mark.analysis


def analyzed(data: bytes):
    mod = load_validate(data)
    return mod, analyze_validated(mod)


def engine_of(data: bytes, lanes=4, **batch):
    conf = Configure()
    conf.batch.steps_per_launch = batch.pop("steps_per_launch", 256)
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


class TestTripBounds:
    def test_counted_loop_exact_bound(self):
        """The admission-precision flagship: verdict unbounded ->
        finite, and EXACT on the canonical fixture."""
        n = 64
        _, a = analyzed(build_counted_loop(n))
        f = a.funcs[0]
        assert f.has_loop and a.bounded
        assert f.loops == [{"head": 0, "trip_bound": n}]
        eng = engine_of(build_counted_loop(n))
        res = eng.run("count", [np.zeros(4, np.int64)],
                      max_steps=50_000)
        assert res.completed.all()
        assert a.cost_bound == int(res.retired.max())  # exact, pinned

    def test_head_tested_loop_sound_bound(self):
        """Exit-at-head / unconditional-back-edge shape (the
        build_loop_sum lowering) with a CONSTANT limit: sound finite
        bound >= measured (the +1-head-execution slack is allowed,
        undercounting is not)."""
        n = 37
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], ["i32", "i32"], [
            ("block", None), ("loop", None),
            ("local.get", 1), ("i32.const", n), "i32.ge_u",
            ("br_if", 1),
            ("local.get", 2), ("local.get", 1), "i32.add",
            ("local.set", 2),
            ("local.get", 1), ("i32.const", 1), "i32.add",
            ("local.set", 1),
            ("br", 0),
            "end", "end",
            ("local.get", 2)], export="f")
        data = b.build()
        _, a = analyzed(data)
        assert a.bounded and a.cost_bound is not None
        res = engine_of(data).run("f", [np.zeros(4, np.int64)],
                                  max_steps=50_000)
        assert res.completed.all()
        assert a.cost_bound >= int(res.retired.max())
        assert int(np.asarray(res.results[0])[0]) == n * (n - 1) // 2

    def test_countdown_ne_zero_shape(self):
        """Decrement-to-zero with a raw brnz value test (the tee/br_if
        idiom) is a counted loop too."""
        n = 9
        b = ModuleBuilder()
        b.add_function([], ["i32"], ["i32", "i32"], [
            ("i32.const", n), ("local.set", 0),
            ("block", None), ("loop", None),
            ("local.get", 1), ("i32.const", 3), "i32.add",
            ("local.set", 1),
            ("local.get", 0), ("i32.const", 1), "i32.sub",
            ("local.tee", 0), ("br_if", 0),
            "end", "end",
            ("local.get", 1)], export="f")
        data = b.build()
        _, a = analyzed(data)
        assert a.bounded
        res = engine_of(data).run("f", [], max_steps=50_000)
        assert res.completed.all()
        assert (np.asarray(res.results[0]) == 3 * n).all()
        assert a.cost_bound >= int(res.retired.max())

    def test_param_limited_loop_stays_unbounded(self):
        """No static limit -> the seed's honest verdict survives."""
        _, a = analyzed(build_loop_sum())
        assert not a.bounded
        assert a.funcs[0].loops[0]["trip_bound"] is None

    def test_nested_counted_loops_bound_and_terminate(self):
        """Nested widening terminates and the loop-nest composition
        multiplies trips (outer x inner), staying sound."""
        outer, inner = 7, 11
        b = ModuleBuilder()
        b.add_function([], ["i32"], ["i32", "i32", "i32"], [
            ("block", None), ("loop", None),            # outer: j
            ("i32.const", 0), ("local.set", 1),
            ("block", None), ("loop", None),            # inner: i
            ("local.get", 2), ("i32.const", 1), "i32.add",
            ("local.set", 2),
            ("local.get", 1), ("i32.const", 1), "i32.add",
            ("local.set", 1),
            ("local.get", 1), ("i32.const", inner), "i32.lt_u",
            ("br_if", 0),
            "end", "end",
            ("local.get", 0), ("i32.const", 1), "i32.add",
            ("local.set", 0),
            ("local.get", 0), ("i32.const", outer), "i32.lt_u",
            ("br_if", 0),
            "end", "end",
            ("local.get", 2)], export="f")
        data = b.build()
        _, a = analyzed(data)
        f = a.funcs[0]
        assert a.bounded and a.cost_bound is not None
        trips = sorted(l["trip_bound"] for l in f.loops)
        assert trips == [outer, inner]
        res = engine_of(data).run("f", [], max_steps=100_000)
        assert res.completed.all()
        assert (np.asarray(res.results[0]) == outer * inner).all()
        assert a.cost_bound >= int(res.retired.max())

    def test_brtable_back_edge_stays_honest(self):
        """A loop whose back edge rides a br_table entry table: the
        interpreter must terminate and keep the honest unbounded
        verdict (no conditional-compare trip pattern exists)."""
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], ["i32"], [
            ("block", None), ("loop", None),
            ("local.get", 1), ("i32.const", 1), "i32.add",
            ("local.set", 1),
            ("local.get", 1), ("i32.const", 3), "i32.rem_u",
            ("br_table", [0, 0], 1),     # both entries: back edges
            "end", "end",
            ("local.get", 1)], export="f")
        mod, a = analyzed(b.build())
        f = a.funcs[0]
        assert f.has_loop
        assert not a.bounded
        assert all(l["trip_bound"] is None for l in f.loops)
        # the brtable rows really are the CFG back edges
        heads = [blk for blk in f.cfg.blocks if blk.is_loop_head]
        assert heads and any(
            heads[0].start in blk.succ for blk in f.cfg.blocks
            if blk.kind == "br_table")

    def test_self_recursion_stays_unbounded(self):
        _, a = analyzed(build_fib())
        assert not a.bounded
        assert a.funcs[0].recursive
        # absint must not fabricate loop facts for recursion
        assert a.summary()["trip_bounded_loops"] == 0


class TestMemoryFacts:
    def test_licensed_sites_proven(self):
        _, a = analyzed(build_memfuse_workload(256, passes=2))
        facts = a.funcs[0].mem_facts
        scalar = [m for m in facts if m["kind"] in ("load", "store")]
        assert len(scalar) == 2 and all(m["licensed"] for m in scalar)
        for m in scalar:
            assert m["lo"] == 0 and m["hi"] == 255 * 4
            assert m["align"] >= 4 and m["in_bounds"] and m["aligned"]
        assert a.licensed_pcs == frozenset(m["pc"] for m in scalar)
        assert a.mem_pages_touch_bound == 1

    def test_misaligned_refused(self):
        _, a = analyzed(build_memfuse_workload(64, byte_offset=2))
        scalar = [m for m in a.funcs[0].mem_facts
                  if m["kind"] in ("load", "store")]
        assert scalar and all(not m["licensed"] for m in scalar)
        assert all(m["in_bounds"] and not m["aligned"] for m in scalar)
        assert a.licensed_sites == 0 and a.unlicensed_sites == 2

    def test_oob_adjacent_refused(self):
        # 16385 words * 4 bytes overruns the single 64 KiB page
        _, a = analyzed(build_memfuse_workload(16385))
        scalar = [m for m in a.funcs[0].mem_facts
                  if m["kind"] in ("load", "store")]
        assert scalar and all(not m["in_bounds"] for m in scalar)
        assert a.licensed_sites == 0
        assert a.mem_pages_touch_bound == 2  # finite, just over a page

    def test_refinement_severed_by_clobbering_write(self):
        """A comparison computed on a local's ENTRY value must not
        refine the interval of its POST-clobber value: compute
        `i <u 10` first, then i := param (opaque) + 1, branch on the
        stale comparison — the load at i*4 is genuinely unbounded and
        must NOT be licensed (the one shape that would break the
        fused path's bit-identity by skipping a real trap)."""
        b = ModuleBuilder()
        b.add_memory(1, 1)
        # locals: 0=param, 1=i
        b.add_function(["i32"], ["i32"], ["i32"], [
            ("local.get", 1), ("i32.const", 10), "i32.lt_u",  # entry i
            ("local.get", 0), ("local.set", 1),               # clobber
            ("local.get", 1), ("i32.const", 1), "i32.add",
            ("local.set", 1),
            ("if", "i32"),                                    # stale cmp
            ("local.get", 1), ("i32.const", 4), "i32.mul",
            ("i32.load", 2, 0),
            "else",
            ("i32.const", 0),
            "end",
        ], export="f")
        _, a = analyzed(b.build())
        loads = [m for m in a.funcs[0].mem_facts if m["kind"] == "load"]
        assert loads and not loads[0]["licensed"]
        assert not loads[0]["in_bounds"]
        assert a.licensed_sites == 0

    def test_hostcalls_void_touch_bound(self):
        import bench_echo

        _, a = analyzed(bench_echo.build_module())
        assert a.tier0_sites + a.drain_sites > 0
        assert a.mem_pages_touch_bound is None

    def test_hv_budget_seeds_from_touch_bound(self):
        """A module declaring more pages than it can touch is charged
        the PROVEN touch, not the declaration."""
        from wasmedge_tpu.hv.policy import (
            _geometry_lane_bytes, effective_lane_bytes)

        b = ModuleBuilder()
        b.add_memory(4, 4)          # 4 pages declared + resident
        b.add_function(["i32"], ["i32"], ["i32", "i32"], [
            ("block", None), ("loop", None),
            ("local.get", 1), ("i32.const", 4), "i32.mul",
            ("local.get", 1), ("i32.store", 2, 0),
            ("local.get", 1), ("i32.const", 1), "i32.add",
            ("local.set", 1),
            ("local.get", 1), ("i32.const", 16), "i32.lt_u",
            ("br_if", 0),
            "end", "end",
            ("local.get", 2)], export="f")
        eng = engine_of(b.build(), memory_pages_per_lane=4)
        a = eng.img.analysis
        assert a.mem_pages_touch_bound == 1
        assert a.mem_pages_bound == 4
        eff = effective_lane_bytes(eng)
        geo = _geometry_lane_bytes(eng)
        assert eff <= geo - 3 * 65536  # 3 untouched pages reclaimed

    def test_policy_max_pages_touched(self):
        proven, _ = AnalysisPolicy(max_memory_pages_touched=1), None
        _, a_ok = analyzed(build_memfuse_workload(64))
        assert proven.evaluate(a_ok) == []
        from wasmedge_tpu.models import build_memory_workload

        _, a_bad = analyzed(build_memory_workload())  # param-driven
        v = proven.evaluate(a_bad)
        assert v and v[0]["limit"] == "max_memory_pages_touched"
        assert v[0]["actual"] == "unbounded"


class TestReportSchema:
    def _doc(self, data=None):
        mod, a = analyzed(data or build_memfuse_workload(64))
        return a.to_dict()

    def test_absint_report_validates(self):
        assert validate_report(self._doc()) == []

    def test_pre_absint_report_back_compat(self):
        """A report WITHOUT the r19 keys (what older artifacts and
        peers emit) must still validate."""
        doc = self._doc()
        doc["summary"].pop("mem_pages_touch_bound")
        doc["summary"].pop("licensed_mem_sites")
        doc["summary"].pop("unlicensed_mem_sites")
        doc["summary"].pop("trip_bounded_loops")
        doc["memory"].pop("pages_touch_bound")
        for f in doc["funcs"]:
            f.pop("loops")
            f.pop("mem_facts")
        assert validate_report(doc) == []

    def test_bounded_with_unbounded_loop_flagged(self):
        doc = self._doc()
        fn = next(f for f in doc["funcs"] if f["has_loop"])
        fn["loops"][0]["trip_bound"] = None
        assert any("unbounded loop" in p for p in validate_report(doc))

    def test_license_without_proof_flagged(self):
        doc = self._doc()
        fn = doc["funcs"][0]
        fact = next(m for m in fn["mem_facts"]
                    if m["kind"] in ("load", "store"))
        fact["aligned"] = False
        assert any("licensed without" in p for p in validate_report(doc))

    def test_mem_run_license_reconciliation(self):
        """licensed runs must be a superset of realized runs: an
        unlicensed load/store inside a fused mem run is flagged."""
        from wasmedge_tpu.batch.fuse import plan_fusion
        from wasmedge_tpu.batch.image import build_device_image

        conf = Configure()
        mod = load_validate(build_memfuse_workload(64), conf)
        a = analyze_validated(mod)
        img = build_device_image(mod.lowered, mod=mod)
        doc = a.to_dict()
        doc["fusion"] = plan_fusion(img, conf.batch, analysis=a)
        assert doc["fusion"]["memory"]["mem_runs"] > 0
        assert validate_report(doc) == []
        # forge: revoke one license the planner consumed
        head, n, _ = doc["fusion"]["mem_runs"][0]
        for f in doc["funcs"]:
            for m in f["mem_facts"]:
                if head <= m["pc"] < head + n:
                    m["licensed"] = False
                    m["aligned"] = False
        assert any("unlicensed load/store" in p
                   for p in validate_report(doc))
        # count drift in the memory section is flagged too
        doc2 = self._doc()
        doc2["fusion"] = plan_fusion(
            build_device_image(load_validate(
                build_memfuse_workload(64)).lowered),
            conf.batch, analysis=analyze_validated(
                load_validate(build_memfuse_workload(64))))
        doc2["fusion"]["memory"]["mem_runs"] += 1
        assert any("disagrees with the realized run list" in p
                   for p in validate_report(doc2))

    def test_cli_disasm_annotates_trips_and_mem(self, tmp_path):
        import json

        from wasmedge_tpu.cli import analyze_command

        wasm = tmp_path / "m.wasm"
        wasm.write_bytes(build_memfuse_workload(64))
        out = tmp_path / "report.json"
        rc = analyze_command([str(wasm), "--disasm", "--out",
                              str(out)])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert validate_report(doc) == []
        dis = doc["disasm"]
        assert "trip<=64" in dis
        assert "licensed" in dis and "mem@" in dis
        assert "memfused=" in dis
        # and the unbounded marking still renders for honest loops
        wasm2 = tmp_path / "u.wasm"
        wasm2.write_bytes(build_loop_sum())
        out2 = tmp_path / "u.json"
        assert analyze_command([str(wasm2), "--disasm", "--out",
                                str(out2)]) == 0
        assert "trip=unbounded" in json.loads(out2.read_text())["disasm"]
