"""Static bytecode analyzer (wasmedge_tpu/analysis/, marker `analysis`).

Pins the r12 acceptance contract:

  - CFG construction over the lowered image: leaders from branch/
    brtable/call targets, edges including the full brtable entry table,
    loop/back-edge marking
  - SOUNDNESS: a bounded function's static cost bound dominates the
    engine's measured retired-instruction count; loops, recursion, and
    dynamic calls verdict "unbounded" instead of guessing
  - superinstruction n-gram census emitted as block metadata
  - hostcall inventory split tier-0-serviceable vs drain-required with
    the image build's exact fd-safety gates
  - static memory/stack footprint bounds
  - the report schema stays machine-readable (validate_report)
  - batchability() rejection taxonomy pinned reason-by-reason
  - LoweredModule.disasm round-trips every opcode in the lop_name table
  - gateway admission: policy-enabled POST /v1/modules rejects with the
    structured StaticPolicyViolation taxonomy; flag mode warns; the
    registry probe cache spares a rejected-then-retried module the
    second lowering
  - tools/lint_jit_purity.py runs clean over the jitted chunk bodies

Speed discipline: tier-1 fast — one tiny BatchEngine compile for the
soundness pin, gateway tests never invoke (registration builds engines
but first-launch jit never runs).
"""

import json
import tempfile
from http.client import HTTPConnection

import numpy as np
import pytest

from wasmedge_tpu.analysis import (
    AnalysisPolicy,
    AnalysisRejection,
    ModuleAnalysis,
    analyze_validated,
    build_func_cfg,
    validate_report,
)
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, rejection_info
from wasmedge_tpu.common.opcodes import NAME_TO_ID
from wasmedge_tpu.models import build_fib, build_loop_sum
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.validator.image import (
    LOP_BR,
    NUM_LOPS,
    FuncMeta,
    LoweredModule,
    lop_name,
)

pytestmark = pytest.mark.analysis


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def load(data: bytes, conf=None):
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.validator import Validator

    conf = conf or Configure()
    return Validator(conf).validate(Loader(conf).parse_module(data))


def analyzed(data: bytes, conf=None):
    mod = load(data, conf)
    return mod, analyze_validated(mod)


def instantiate(data: bytes, conf):
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.runtime.store import StoreManager

    mod = load(data, conf)
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return inst, store


def tiny_conf():
    conf = Configure()
    conf.batch.steps_per_launch = 64
    conf.batch.value_stack_depth = 32
    conf.batch.call_stack_depth = 8
    return conf


def build_bounded() -> bytes:
    """if/else + a straight-line callee: finite, exactly boundable."""
    b = ModuleBuilder()
    leaf = b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 3), "i32.mul"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 2), "i32.lt_s",
        ("if", "i32"),
        ("local.get", 0), ("call", leaf),
        "else",
        ("local.get", 0), ("i32.const", 5), "i32.add", ("call", leaf),
        "end",
    ], export="f")
    return b.build()


def build_unbounded() -> bytes:
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("block", None), ("loop", None), ("br", 0), "end", "end",
        ("local.get", 0)], export="spin")
    return b.build()


def synth_func(ops, nresults=1, nparams=1) -> LoweredModule:
    """A hand-built LoweredModule with one defined function — the unit
    vehicle for pinning batchability()/analyzer behavior per opcode
    without fighting the wasm validator."""
    lm = LoweredModule()
    for op, a, b_, c, imm in ops:
        lm.emit(op, a, b_, c, imm)
    lm.funcs.append(FuncMeta(
        type_idx=0, nparams=nparams, nresults=nresults,
        nlocals=nparams, entry_pc=0, end_pc=lm.code_len - 1,
        max_height=4))
    return lm


# ---------------------------------------------------------------------------
# CFG
# ---------------------------------------------------------------------------

class TestCFG:
    def test_straightline_single_block(self):
        _, a = analyzed(build_bounded())
        leaf = a.funcs[0]
        assert len(leaf.cfg.blocks) == 1
        blk = leaf.cfg.blocks[0]
        assert blk.succ == () and blk.kind == "return"
        assert not leaf.has_loop and leaf.bounded

    def test_if_else_edges_and_max_arm_cost(self):
        _, a = analyzed(build_bounded())
        f = a.func_by_idx(a.exports["f"])
        brz = f.cfg.blocks[0]
        assert brz.kind == "brz"
        # conditional: branch target + fallthrough, in that order
        assert len(brz.succ) == 2 and brz.succ[1] == brz.end + 1
        # bound takes the MAX arm (else arm is longer) + callee cost
        assert f.cost_bound == 13

    def test_loop_back_edge_detected(self):
        _, a = analyzed(build_loop_sum())
        f = a.funcs[0]
        assert f.has_loop and not f.recursive
        assert not f.bounded and f.cost_bound is None
        heads = [b for b in f.cfg.blocks if b.is_loop_head]
        assert heads, "loop head not marked"
        assert any(b.in_loop for b in f.cfg.blocks)
        # the back edge points AT a loop head
        starts = {b.start for b in heads}
        assert any(set(b.succ) & starts for b in f.cfg.blocks
                   if b.in_loop)

    def test_brtable_entry_table_edges(self):
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("block", None), ("block", None), ("block", None),
            ("local.get", 0),
            ("br_table", [0, 1], 2),
            "end", ("i32.const", 10), ("return",),
            "end", ("i32.const", 20), ("return",),
            "end", ("i32.const", 30),
        ], export="sel")
        mod, a = analyzed(b.build())
        f = a.funcs[0]
        tbl = [blk for blk in f.cfg.blocks if blk.kind == "br_table"]
        assert len(tbl) == 1
        # 2 targets + default, all distinct arms
        assert tbl[0].brtable_entries == 3
        assert len(tbl[0].succ) == 3
        cfg = build_func_cfg(mod.lowered, 0)
        starts = {blk.start for blk in cfg.blocks}
        assert set(tbl[0].succ) <= starts
        # data-dependent multiway = the dominant divergence driver
        assert f.divergence >= 3

    def test_recursion_unbounded(self):
        _, a = analyzed(build_fib())
        f = a.funcs[0]
        assert f.recursive and not f.has_loop
        assert f.cost_bound is None and f.value_stack_bound is None \
            and f.call_depth_bound is None
        assert not a.bounded


# ---------------------------------------------------------------------------
# cost soundness vs the real engine
# ---------------------------------------------------------------------------

class TestSoundness:
    def test_cost_bound_dominates_retired(self):
        from wasmedge_tpu.batch.engine import BatchEngine

        conf = tiny_conf()
        data = build_bounded()
        _, a = analyzed(data, conf)
        inst, store = instantiate(data, conf)
        eng = BatchEngine(inst, store=store, conf=conf, lanes=4)
        res = eng.run("f", [np.array([0, 1, 5, 9], np.int64)],
                      max_steps=10_000)
        assert res.completed.all()
        assert a.cost_bound is not None
        assert a.cost_bound >= int(res.retired.max())
        # the bound is TIGHT on this fixture (longest path is taken by
        # lanes >= 2): an overcounting regression shows up here
        assert a.cost_bound == int(res.retired.max())

    def test_device_image_carries_analysis(self):
        from wasmedge_tpu.batch.engine import BatchEngine

        conf = tiny_conf()
        inst, store = instantiate(build_fib(), conf)
        eng = BatchEngine(inst, store=store, conf=conf, lanes=2)
        a = eng.img.analysis
        assert isinstance(a, ModuleAnalysis)
        assert not a.bounded and a.funcs[0].recursive

    def test_stack_and_depth_bounds(self):
        _, a = analyzed(build_bounded())
        f = a.func_by_idx(a.exports["f"])
        leaf = a.funcs[0]
        # leaf frame: 1 local + max_height; caller adds its own frame
        assert leaf.call_depth_bound == 1 and f.call_depth_bound == 2
        assert leaf.value_stack_bound is not None
        assert f.value_stack_bound > leaf.value_stack_bound


# ---------------------------------------------------------------------------
# superinstruction census
# ---------------------------------------------------------------------------

class TestNgrams:
    def test_census_ranks_repeated_sequence(self):
        b = ModuleBuilder()
        body = []
        for _ in range(6):
            body += [("local.get", 0), ("i32.const", 7), "i32.xor",
                     ("local.set", 0)]
        body += [("local.get", 0)]
        b.add_function(["i32"], ["i32"], [], body, export="f")
        _, a = analyzed(b.build())
        assert a.superinstructions, "census empty"
        top = a.superinstructions[0]
        # the 4-gram body of the repeated unit wins on saved dispatches
        assert top["ops"] == ["local.get", "i32.const", "i32.xor",
                              "local.set"]
        assert top["count"] == 6 and top["n"] == 4
        assert top["saved_dispatches"] == 18
        f = a.funcs[0]
        # emitted as block metadata: the hosting block lists the winner
        assert any(0 in ng for ng in f.block_ngrams)

    def test_loop_occurrences_outweigh_straightline(self):
        # the same 2-gram once in a loop vs 3x straight-line: loop wins
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], ["i32"], [
            # straight-line: 3 x (i32.const, i32.add)
            ("local.get", 0),
            ("i32.const", 1), "i32.add",
            ("i32.const", 2), "i32.add",
            ("i32.const", 3), "i32.add",
            ("local.set", 1),
            # loop: 1 x (i32.const, i32.sub) per iteration
            ("block", None), ("loop", None),
            ("local.get", 1), "i32.eqz", ("br_if", 1),
            ("local.get", 1), ("i32.const", 1), "i32.sub",
            ("local.set", 1),
            ("br", 0), "end", "end",
            ("local.get", 1),
        ], export="f")
        _, a = analyzed(b.build())
        by_ops = {tuple(c["ops"]): c for c in a.superinstructions}
        in_loop = by_ops[("i32.const", "i32.sub")]
        straight = by_ops[("i32.const", "i32.add")]
        assert in_loop["count"] == 1 and straight["count"] == 3
        assert in_loop["weight"] > straight["weight"]

    def test_ngrams_never_span_control(self):
        _, a = analyzed(build_fib())
        for c in a.superinstructions:
            for name in c["ops"]:
                assert name not in ("call", "return", "lop.br",
                                    "lop.brz", "lop.brnz", "br_table")


# ---------------------------------------------------------------------------
# hostcall inventory
# ---------------------------------------------------------------------------

class TestHostcalls:
    def test_echo_fd_write_is_tier0(self):
        import bench_echo

        _, a = analyzed(bench_echo.build_module())
        assert a.tier0_sites == 2 and a.drain_sites == 0
        sites = [s for f in a.funcs for s in f.hostcall_sites]
        assert all(s.kind == "fd_write" and s.tier0 for s in sites)
        assert all(s.import_name == "wasi_snapshot_preview1.fd_write"
                   for s in sites)

    def test_fd_unsafe_import_degrades_fd_write(self):
        # an fd_-family sibling import makes fd_write drain-required
        # (the kernel's "fd 1/2 is a plain sink" assumption is stale),
        # exactly like build_device_image's t0_fdwrite_safe gate
        b = ModuleBuilder()
        fdw = b.import_func("wasi_snapshot_preview1", "fd_write",
                            ["i32", "i32", "i32", "i32"], ["i32"])
        fdc = b.import_func("wasi_snapshot_preview1", "fd_close",
                            ["i32"], ["i32"])
        b.add_memory(1, 1)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("call", fdc), "drop",
            ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
            ("i32.const", 32), ("call", fdw),
        ], export="f")
        mod, a = analyzed(b.build())
        by_kind = {s.kind: s for f in a.funcs
                   for s in f.hostcall_sites}
        assert not by_kind["fd_write"].tier0
        assert not by_kind["fd_close"].tier0
        assert a.drain_sites == 2 and a.tier0_sites == 0
        # and the image build agrees with the analyzer's gate
        from wasmedge_tpu.batch.image import build_device_image

        img = build_device_image(mod.lowered, mod=mod)
        assert not img.t0_fdwrite_safe

    def test_zero_min_memory_still_counts_as_memory(self):
        # (memory 0) with min=0 and no max is still a memory: tier-0
        # classification must match the image build's has_memory gate,
        # not infer memory-lessness from pages_init == 0
        b = ModuleBuilder()
        clk = b.import_func("wasi_snapshot_preview1", "clock_time_get",
                            ["i32", "i64", "i32"], ["i32"])
        b.add_memory(0)
        b.add_function([], ["i32"], [], [
            ("i32.const", 0), ("i64.const", 0), ("i32.const", 8),
            ("call", clk)], export="f")
        _, a = analyzed(b.build())
        sites = [s for f in a.funcs for s in f.hostcall_sites]
        assert len(sites) == 1 and sites[0].tier0

    def test_clock_without_memory_not_tier0(self):
        b = ModuleBuilder()
        clk = b.import_func("wasi_snapshot_preview1", "clock_time_get",
                            ["i32", "i64", "i32"], ["i32"])
        b.add_function([], ["i32"], [], [
            ("i32.const", 0), ("i64.const", 0), ("i32.const", 8),
            ("call", clk)], export="f")
        _, a = analyzed(b.build())
        sites = [s for f in a.funcs for s in f.hostcall_sites]
        assert len(sites) == 1 and not sites[0].tier0


# ---------------------------------------------------------------------------
# footprint bounds
# ---------------------------------------------------------------------------

class TestFootprint:
    def test_pages_bound_no_grow_is_initial(self):
        import bench_echo

        _, a = analyzed(bench_echo.build_module())
        assert a.mem_grow_sites == 0 and a.mem_pages_bound == 1

    def test_grow_with_declared_max(self):
        b = ModuleBuilder()
        b.add_memory(1, 4)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("memory.grow", 0)], export="grow")
        _, a = analyzed(b.build())
        assert a.mem_grow_sites == 1
        assert a.mem_pages_init == 1 and a.mem_pages_bound == 4

    def test_grow_without_max_unbounded(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("memory.grow", 0)], export="grow")
        _, a = analyzed(b.build())
        assert a.mem_pages_bound is None


# ---------------------------------------------------------------------------
# report schema
# ---------------------------------------------------------------------------

class TestReportSchema:
    @pytest.mark.parametrize("builder", [build_fib, build_loop_sum,
                                         build_bounded,
                                         build_unbounded])
    def test_fixture_reports_validate(self, builder):
        _, a = analyzed(builder())
        assert validate_report(a.to_dict()) == []

    def test_schema_catches_drift(self):
        _, a = analyzed(build_bounded())
        doc = a.to_dict()
        doc["summary"]["bounded"] = False  # disagrees with cost_bound
        assert validate_report(doc)
        doc2 = a.to_dict()
        del doc2["funcs"][0]["blocks"][0]["cost"]
        assert validate_report(doc2)
        doc3 = a.to_dict()
        doc3["funcs"][1]["blocks"][0]["succ"] = [999999]
        assert validate_report(doc3)
        assert validate_report({"schema": "nope"})

    def test_analyze_cli_end_to_end(self, tmp_path):
        from wasmedge_tpu import cli

        p = tmp_path / "fib.wasm"
        p.write_bytes(build_fib())
        out_path = tmp_path / "report.json"
        rc = cli.main(["analyze", str(p), "--disasm", "--out",
                       str(out_path)])
        assert rc == 0
        doc = json.loads(out_path.read_text())
        assert validate_report(doc) == []
        assert "lop.brz" in doc["disasm"]
        assert doc["exports"] == {"fib": 0}

    def test_annotated_disasm_marks_blocks(self):
        mod, a = analyzed(build_loop_sum())
        text = a.annotated_disasm(mod.lowered)
        assert ";; func 0" in text and "loop-head" in text
        assert "cost unbounded" in text


# ---------------------------------------------------------------------------
# disasm round-trip (satellite: every lowered opcode prints a name)
# ---------------------------------------------------------------------------

class TestDisasm:
    def test_every_opcode_roundtrips_through_disasm(self):
        for op in range(NUM_LOPS):
            name = lop_name(op)
            assert name and not name.isdigit(), f"opcode {op} unnamed"
            lm = LoweredModule()
            lm.emit(op)
            line = lm.disasm(0, 1)
            assert name in line, \
                f"opcode {op} ({name}) prints as raw int: {line!r}"

    def test_out_of_range_opcode_is_loud(self):
        lm = LoweredModule()
        lm.emit(NUM_LOPS + 7)
        with pytest.raises(ValueError, match="outside the lowered ISA"):
            lm.disasm(0, 1)
        # a NEGATIVE id used to index the opcode table from the end and
        # print a plausible but wrong name — now loud, never aliased
        with pytest.raises(ValueError, match="outside the lowered ISA"):
            lop_name(-5)


# ---------------------------------------------------------------------------
# batchability rejection taxonomy (satellite: one test per reason)
# ---------------------------------------------------------------------------

class TestBatchability:
    def test_happy_path(self):
        from wasmedge_tpu.batch.image import batchability

        mod = load(build_fib())
        assert batchability(mod.lowered) is None

    def test_unservable_import(self):
        from wasmedge_tpu.batch.image import batchability

        b = ModuleBuilder()
        b.import_func("env", "mystery", ["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [],
                       [("local.get", 0), ("call", 0)], export="f")
        mod = load(b.build())
        reason = batchability(mod.lowered, host_imports=None)
        assert reason == "unservable imported function env.mystery"
        # ... and servable when the host backs it
        assert batchability(mod.lowered, host_imports={0}) is None

    def test_multi_memory(self):
        from wasmedge_tpu.batch.engine import BatchEngine
        from wasmedge_tpu.batch.image import batchability
        from wasmedge_tpu.common.configure import Proposal

        b = ModuleBuilder()
        b.add_memory(1, 1)
        b.add_memory(1, 1)
        b.add_function(["i32"], ["i32"], [], [("local.get", 0)],
                       export="f")
        conf = Configure()
        conf.add_proposal(Proposal.MultiMemories)
        inst, store = instantiate(b.build(), conf)
        assert len(inst.memories) == 2
        assert batchability(inst.lowered, n_memories=2) \
            == "multiple memories"
        with pytest.raises(ValueError, match="multiple memories"):
            BatchEngine(inst, store=store, conf=conf, lanes=1)

    def test_multi_value_results(self):
        from wasmedge_tpu.batch.image import batchability

        lm = synth_func([(NAME_TO_ID["local.get"], 0, 0, 0, 0),
                         (NAME_TO_ID["local.get"], 0, 0, 0, 0),
                         (NAME_TO_ID["return"], 0, 2, 0, 0)],
                        nresults=2)
        assert batchability(lm) == "multi-value results"

    def test_multi_value_branch_arity(self):
        from wasmedge_tpu.batch.image import batchability

        lm = synth_func([(LOP_BR, 1, 2, 0, 0),
                         (NAME_TO_ID["return"], 0, 1, 0, 0)])
        assert batchability(lm) == "multi-value branch arity"

    def test_unsupported_op(self):
        from wasmedge_tpu.batch.image import batchability

        lm = synth_func([(NAME_TO_ID["v128.load8x8_s"], 0, 0, 0, 0),
                         (NAME_TO_ID["return"], 0, 1, 0, 0)])
        assert batchability(lm) == "unsupported op v128.load8x8_s"

    def test_table_not_zero(self):
        from wasmedge_tpu.batch.image import batchability

        lm = synth_func([(NAME_TO_ID["table.get"], 1, 0, 0, 0),
                         (NAME_TO_ID["return"], 0, 1, 0, 0)])
        assert batchability(lm) == "table.get on table != 0"

    def test_v128_entry_signature(self):
        from wasmedge_tpu.batch.engine import check_batch_entry

        b = ModuleBuilder()
        b.add_function(["v128"], ["i32"], [], [
            ("local.get", 0), "i8x16.all_true"], export="f")
        inst, _ = instantiate(b.build(), Configure())
        with pytest.raises(ValueError, match="v128"):
            check_batch_entry(inst, "f")


# ---------------------------------------------------------------------------
# admission policy
# ---------------------------------------------------------------------------

class TestPolicy:
    def test_evaluate_limits(self):
        _, unb = analyzed(build_unbounded())
        _, bnd = analyzed(build_bounded())
        pol = AnalysisPolicy(max_static_cost=1000)
        assert [v["limit"] for v in pol.evaluate(unb)] \
            == ["max_static_cost"]
        assert pol.evaluate(bnd) == []
        assert AnalysisPolicy(max_static_cost=5).evaluate(bnd)
        assert AnalysisPolicy(require_bounded=True).evaluate(unb)
        assert AnalysisPolicy(max_call_depth=1).evaluate(bnd)
        assert AnalysisPolicy(max_call_depth=2).evaluate(bnd) == []
        # missing analysis never passes an enforcing policy
        assert AnalysisPolicy(require_bounded=True).evaluate(None)
        assert AnalysisPolicy().evaluate(None) == []

    def test_memory_and_hostcall_limits(self):
        import bench_echo

        _, echo = analyzed(bench_echo.build_module())
        assert AnalysisPolicy(max_memory_pages=1).evaluate(echo) == []
        assert AnalysisPolicy(max_memory_pages=0).evaluate(echo)
        # echo's fd_write is tier-0-serviceable: tier0-only admits it
        assert AnalysisPolicy(
            tier0_only_hostcalls=True).evaluate(echo) == []

    def test_rejection_info_carries_violations(self):
        exc = AnalysisRejection("m", [{"limit": "max_static_cost",
                                       "allowed": 5,
                                       "actual": "unbounded",
                                       "message": "x"}])
        info = rejection_info(exc)
        assert info["code"] == int(ErrCode.StaticPolicyViolation)
        assert info["name"] == "StaticPolicyViolation"
        assert not info["retryable"]
        assert info["violations"][0]["limit"] == "max_static_cost"

    def test_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown analysis"):
            AnalysisPolicy.from_dict({"max_cost": 5})

    def test_lint_jit_purity_clean(self):
        import os

        from tools.lint_jit_purity import run_lint

        root = os.path.join(os.path.dirname(__file__), "..")
        assert run_lint(root) == []


# ---------------------------------------------------------------------------
# gateway admission over real sockets
# ---------------------------------------------------------------------------

def rpc(gw, method, path, body=None, headers=None, timeout=120.0):
    c = HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        data = json.dumps(body).encode() if isinstance(body, dict) \
            else body
        c.request(method, path, body=data, headers=headers or {})
        r = c.getresponse()
        raw = r.read()
    finally:
        c.close()
    try:
        doc = json.loads(raw)
    except Exception:
        doc = raw.decode(errors="replace")
    return r.status, doc


@pytest.fixture(scope="module")
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="analysis-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


class TestGatewayAdmission:
    @pytest.fixture()
    def gw(self, _compile_cache):
        from wasmedge_tpu.gateway import (
            Gateway, GatewayService, GatewayTenants)

        conf = Configure()
        conf.batch.steps_per_launch = 128
        tenants = GatewayTenants.from_dict({
            "analysis": {"max_static_cost": 1_000_000},
            "tenants": {
                "strict": {},
                "audit": {"analysis": {"require_bounded": True,
                                       "enforce": False}},
                "free": {"analysis": {"enforce": True}},
            },
        })
        svc = GatewayService(conf=conf, lanes=2, tenants=tenants)
        gw = Gateway(svc, port=0).start()
        yield gw
        gw.shutdown(drain=True, timeout_s=60.0)

    def test_policy_rejects_unbounded_on_the_wire(self, gw):
        st, doc = rpc(gw, "POST", "/v1/modules?name=spin&tenant=strict",
                      body=build_unbounded(),
                      headers={"Content-Type": "application/wasm"})
        assert st == 400
        err = doc["err"]
        assert err["name"] == "StaticPolicyViolation"
        assert err["code"] == int(ErrCode.StaticPolicyViolation)
        assert err["retryable"] is False
        assert err["violations"][0]["limit"] == "max_static_cost"
        assert err["violations"][0]["actual"] == "unbounded"
        # nothing registered, no generation swapped
        assert gw.service.registry.names == []
        st, doc = rpc(gw, "GET", "/v1/status")
        assert doc["generation"] == 0
        assert doc["gateway"]["policy_rejected"] == 1
        assert doc["analysis"]["policy_rejected"] == 1

    def test_bounded_admits_with_summary(self, gw):
        st, doc = rpc(gw, "POST", "/v1/modules?name=ok&tenant=strict",
                      body=build_bounded(),
                      headers={"Content-Type": "application/wasm"})
        assert st == 201 and doc["ok"]
        assert doc["analysis"]["bounded"] is True
        assert doc["analysis"]["cost_bound"] == 13
        assert "analysis_warnings" not in doc

    def test_flag_mode_registers_with_warnings(self, gw):
        st, doc = rpc(gw, "POST", "/v1/modules?name=spin&tenant=audit",
                      body=build_unbounded(),
                      headers={"Content-Type": "application/wasm"})
        assert st == 201 and doc["ok"]
        assert doc["analysis"]["bounded"] is False
        warns = doc["analysis_warnings"]
        assert warns[0]["limit"] == "require_bounded"
        assert "spin" in gw.service.registry.names

    def test_boot_registration_skips_default_policy(self, gw):
        # operator-supplied boot modules (tenant=None: CLI --module,
        # VM.gateway()) are trusted — a strict file-level default for
        # HTTP registrants must not abort gateway startup on them
        info = gw.service.register_module(
            "bootspin", wasm_bytes=build_unbounded(), source="boot")
        assert info["analysis"]["bounded"] is False
        assert "analysis_warnings" not in info
        assert "bootspin" in gw.service.registry.names

    def test_tenant_policy_overrides_default(self, gw):
        # "free" carries its OWN empty enforcing policy: no limits set,
        # so the unbounded module admits — per-tenant wins over default
        st, doc = rpc(gw, "POST", "/v1/modules?name=spin2&tenant=free",
                      body=build_unbounded(),
                      headers={"Content-Type": "application/wasm"})
        assert st == 201 and doc["ok"]

    def test_probe_cache_spares_second_lowering(self, gw):
        svc = gw.service
        data = build_unbounded()
        base = svc.registry.lowered_count
        st, _ = rpc(gw, "POST", "/v1/modules?name=a&tenant=strict",
                    body=data,
                    headers={"Content-Type": "application/wasm"})
        assert st == 400
        assert svc.registry.lowered_count == base + 1
        # rejected-then-fixed: same bytes under a permissive tenant
        # adopt the stashed probe engine — no second lowering
        st, doc = rpc(gw, "POST", "/v1/modules?name=b&tenant=free",
                      body=data,
                      headers={"Content-Type": "application/wasm"})
        assert st == 201 and doc["module"] == "b"
        assert svc.registry.lowered_count == base + 1
        # adoption retargets the guest-visible argv[0]: a cache hit is
        # not observably different from a fresh registration
        assert svc.registry.get("b").wasi.env.args[0] == "b"

    def test_metrics_export_analysis_counters(self, gw):
        from wasmedge_tpu.obs.metrics import parse_prometheus

        rpc(gw, "POST", "/v1/modules?name=spin&tenant=strict",
            body=build_unbounded(),
            headers={"Content-Type": "application/wasm"})
        rpc(gw, "POST", "/v1/modules?name=ok&tenant=strict",
            body=build_bounded(),
            headers={"Content-Type": "application/wasm"})
        st, text = rpc(gw, "GET", "/metrics")
        assert st == 200
        parsed = parse_prometheus(text)
        assert parsed[("wasmedge_analysis_policy_rejections_total",
                       frozenset())] == 1.0
        assert parsed[("wasmedge_analysis_modules_total",
                       frozenset({("verdict", "bounded")}))] == 1.0
        assert parsed[("wasmedge_analysis_modules_total",
                       frozenset({("verdict", "unbounded")}))] == 1.0
