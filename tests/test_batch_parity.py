"""Batch-engine parity suite: the tpu_batch engine must be bit-exact with
the scalar oracle, lane by lane — values AND trap codes.

This is the conformance centerpiece SURVEY.md §4 calls for: the same
modules run through both engines via the same staging, so the batch engine
is tested by the exact corpus that tests the oracle. One mega-module with a
function per opcode keeps it to a single XLA compile.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.errors import TrapError
from wasmedge_tpu.common.opcodes import OPCODES
from wasmedge_tpu.batch.image import _UNSUPPORTED_PREFIXES
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

# -- edge-case input vectors by signature char ------------------------------
I32_EDGES = [0, 1, 2, -1, -2, 0x7FFFFFFF, -0x80000000, 0x12345678,
             -0x12345678, 31, 32, 33, 0xFFFF]
I64_EDGES = [0, 1, -1, 2**63 - 1, -(2**63), 0x123456789ABCDEF,
             -0x123456789ABCDEF, 63, 64, 2**32, -(2**32), 0xFFFFFFFF]
F32_EDGES_BITS = [
    0x00000000, 0x80000000,  # +-0
    0x3F800000, 0xBF800000,  # +-1
    0x3FC00000,              # 1.5
    0x7F800000, 0xFF800000,  # +-inf
    0x7FC00000, 0xFFC00001,  # nans
    0x00000001,              # denormal
    0x4F000000,              # 2^31 (f32)
    0x4EFFFFFF,              # just under 2^31
    0xCF000000,              # -2^31
    0x42280000,              # 42.0
]

F64_EDGES_BITS = [
    0x0000000000000000, 0x8000000000000000,   # +-0
    0x3FF0000000000000, 0xBFF0000000000000,   # +-1
    0x3FF8000000000000,                       # 1.5
    0x7FF0000000000000, 0xFFF0000000000000,   # +-inf
    0x7FF8000000000000, 0xFFF8000000000001,   # nans
    0x0000000000000001,                       # min subnormal
    0x43E0000000000000,                       # 2^63
    0x43DFFFFFFFFFFFFF,                       # just under 2^63
    0xC3E0000000000000,                       # -2^63
    0x4045000000000000,                       # 42.0
    0x3FB999999999999A,                       # 0.1
    0x7FEFFFFFFFFFFFFF,                       # max finite
]

_EDGES = {"i": I32_EDGES, "I": I64_EDGES, "f": F32_EDGES_BITS,
          "F": F64_EDGES_BITS}

# f32 ops that are bitwise or integer-domain in the batch engine stay exact
# for denormal inputs even on FTZ hardware; arithmetic ops flush subnormals
# on XLA CPU/TPU (documented divergence), so the denormal edge is excluded.
_DENORMAL_SAFE = {
    "f32.eq", "f32.ne", "f32.lt", "f32.gt", "f32.le", "f32.ge",
    "f32.min", "f32.max", "f32.abs", "f32.neg", "f32.copysign",
    "i32.reinterpret_f32", "f32.reinterpret_i32",
}
_DENORMAL_BITS = {0x00000001}


def _cells(ch, vals):
    if ch == "i":
        return [v & 0xFFFFFFFF for v in vals]
    if ch == "I" or ch == "F":
        return [v & 0xFFFFFFFFFFFFFFFF for v in vals]
    return list(vals)  # f32/f64 bit patterns already


def _batch_supported(name: str) -> bool:
    # (the former _UNSUPPORTED_NAMES set emptied out in r05: the table/
    # segment/tail-call families joined the batch subset)
    return not any(name.startswith(p) for p in _UNSUPPORTED_PREFIXES)


def _plain_ops():
    """All no-immediate ops with a value signature the batch engine takes."""
    out = []
    for info in OPCODES:
        if info.imm != "none" or info.sig is None:
            continue
        if not _batch_supported(info.name):
            continue
        pops, pushes = info.sig.split("->")
        if any(c not in "iIfF" for c in pops + pushes):
            continue
        out.append((info.name, pops, pushes))
    return out


_SIG_STR = {"i": "i32", "I": "i64", "f": "f32", "F": "f64"}


@pytest.fixture(scope="module")
def parity_rig():
    """One module with a function per op; instantiated for both engines."""
    b = ModuleBuilder()
    ops = _plain_ops()
    for name, pops, pushes in ops:
        params = [_SIG_STR[c] for c in pops]
        results = [_SIG_STR[c] for c in pushes]
        body = [("local.get", i) for i in range(len(params))] + [name]
        b.add_function(params, results, [], body, export=name)
    ex, store, inst = instantiate(b.build())
    from wasmedge_tpu.batch import BatchEngine
    return ops, ex, store, inst, {}


def _lane_inputs(pops, name=""):
    """Cartesian edge-case grid over the op's parameter types."""
    if not pops:
        return [[]]
    cols = []
    for c in pops:
        vals = _EDGES[c]
        if c == "f" and name not in _DENORMAL_SAFE:
            vals = [v for v in vals if v not in _DENORMAL_BITS]
        cols.append(_cells(c, vals))
    if len(cols) == 1:
        return [[v] for v in cols[0]]
    grid = []
    for a in cols[0]:
        for bb in cols[1]:
            grid.append([a, bb])
    return grid


def test_opcode_parity(parity_rig):
    from wasmedge_tpu.batch import BatchEngine

    ops, ex, store, inst, _ = parity_rig
    # group runs by arity so lane counts match within one engine instance
    failures = []
    eng_cache = {}
    for name, pops, pushes in ops:
        lanes_in = _lane_inputs(pops, name)
        L = len(lanes_in)
        # scalar oracle per lane
        want_vals, want_traps = [], []
        fi = inst.find_func(name)
        for args in lanes_in:
            try:
                out = ex.invoke_raw(store, fi, list(args))
                want_vals.append(out[0] if out else 0)
                want_traps.append(-1)
            except TrapError as e:
                want_vals.append(None)
                want_traps.append(int(e.code))
        # batch engine: one run, L lanes
        if L not in eng_cache:
            eng_cache[L] = BatchEngine(inst, store=store, lanes=L)
        eng = eng_cache[L]
        args_cols = []
        for i in range(len(pops)):
            args_cols.append(np.array([lanes_in[k][i] for k in range(L)],
                                      dtype=np.uint64).astype(np.int64))
        res = eng.run(name, args_cols, max_steps=4000)
        got_trap = res.trap
        got = res.results[0] if res.results else np.zeros(L, np.int64)
        for k in range(L):
            wt = want_traps[k]
            gt = int(got_trap[k])
            if wt != gt:
                failures.append(
                    f"{name} lane {k} args={lanes_in[k]}: trap {wt} vs {gt}")
                continue
            if wt == -1:
                wv = want_vals[k] & 0xFFFFFFFFFFFFFFFF
                gv = int(got[k]) & 0xFFFFFFFFFFFFFFFF
                if wv != gv:
                    failures.append(
                        f"{name} lane {k} args={[hex(a) for a in lanes_in[k]]}:"
                        f" {wv:#x} vs {gv:#x}")
    assert not failures, "\n".join(failures[:40]) + f"\n({len(failures)} total)"


class TestProgramParity:
    def _compare(self, data, func, arg_lanes, max_steps=2_000_000, conf=None):
        from wasmedge_tpu.batch import BatchEngine

        # fresh instance per scalar lane: batch lanes are share-nothing, so
        # the oracle must not leak global/memory state across lanes
        want_vals, want_traps = [], []
        for a in arg_lanes:
            ex, store, inst = instantiate(data, conf)
            fi = inst.find_func(func)
            try:
                out = ex.invoke_raw(store, fi, [a & 0xFFFFFFFFFFFFFFFF])
                want_vals.append(out[0] if out else 0)
                want_traps.append(-1)
            except TrapError as e:
                want_vals.append(None)
                want_traps.append(int(e.code))
        # fresh instance for batch (scalar run may have mutated memory)
        ex2, store2, inst2 = instantiate(data, conf)
        eng = BatchEngine(inst2, store=store2, lanes=len(arg_lanes),
                          conf=conf)
        res = eng.run(func, [np.asarray(arg_lanes, np.int64)],
                      max_steps=max_steps)
        for k in range(len(arg_lanes)):
            assert int(res.trap[k]) == want_traps[k], f"lane {k} trap"
            if want_traps[k] == -1:
                got = int(res.results[0][k]) & 0xFFFFFFFFFFFFFFFF
                want = want_vals[k] & 0xFFFFFFFFFFFFFFFF
                assert got == want, f"lane {k}: {want:#x} != {got:#x}"

    def test_fib_divergent(self):
        from wasmedge_tpu.models import build_fib
        self._compare(build_fib(), "fib", list(range(16)))

    def test_fac_i64(self):
        from wasmedge_tpu.models import build_fac
        self._compare(build_fac(), "fac", list(range(1, 21)))

    def test_loop_sum(self):
        from wasmedge_tpu.models import build_loop_sum
        self._compare(build_loop_sum(), "loop_sum", [0, 1, 7, 100, 1000])

    def test_memory_workload(self):
        from wasmedge_tpu.models import build_memory_workload
        self._compare(build_memory_workload(), "mem_checksum",
                      [0, 1, 5, 64, 1000])

    def test_coremark_kernel(self):
        from wasmedge_tpu.models import build_coremark_kernel
        self._compare(build_coremark_kernel(), "coremark", [1, 10, 100, 500])

    def test_br_table(self):
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("block", None), ("block", None), ("block", None),
            ("local.get", 0), ("br_table", [0, 1], 2),
            "end", ("i32.const", 10), "return",
            "end", ("i32.const", 20), "return",
            "end", ("i32.const", 30),
        ], export="f")
        self._compare(b.build(), "f", [0, 1, 2, 3, 100, -1])

    def test_call_indirect(self):
        b = ModuleBuilder()
        add = b.add_function(["i32", "i32"], ["i32"], [],
                             [("local.get", 0), ("local.get", 1), "i32.add"])
        sub = b.add_function(["i32", "i32"], ["i32"], [],
                             [("local.get", 0), ("local.get", 1), "i32.sub"])
        voidf = b.add_function([], [], [], [])
        b.add_table("funcref", 5)
        b.add_active_elem(0, [("i32.const", 0)], [add, sub, voidf])
        ti = b.add_type(["i32", "i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("i32.const", 30), ("i32.const", 12),
            ("local.get", 0), ("call_indirect", ti, 0),
        ], export="dispatch")
        # lanes: ok, ok, sig mismatch, null, undefined
        self._compare(b.build(), "dispatch", [0, 1, 2, 3, 99])

    def test_call_indirect_empty_table(self):
        # ADVICE r2: size-0 table made u_lt(b-1, v0) underflow so no index
        # was ever UndefinedElement; every index must trap
        b = ModuleBuilder()
        b.add_table("funcref", 0)
        ti = b.add_type([], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("call_indirect", ti, 0),
        ], export="dispatch")
        self._compare(b.build(), "dispatch", [0, 1, -1, 99])

    def test_globals_and_memory(self):
        b = ModuleBuilder()
        b.add_memory(1, 2)
        b.add_global("i64", True, [("i64.const", 7)])
        b.add_function(["i32"], ["i64"], [], [
            ("global.get", 0),
            ("local.get", 0), ("local.get", 0), ("i32.store", 2, 0),
            ("local.get", 0), ("i64.load32_u", 2, 0),
            "i64.add", ("global.set", 0),
            ("global.get", 0),
        ], export="f")
        self._compare(b.build(), "f", [0, 4, 100, 65532, 65533])

    def test_memory_grow_and_size(self):
        from wasmedge_tpu.common.configure import Configure
        conf = Configure()
        conf.batch.memory_pages_per_lane = 3
        b = ModuleBuilder()
        b.add_memory(1, 3)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), "memory.grow", "drop",
            "memory.size",
            ("i32.const", 16), "i32.mul",
            ("local.get", 0), "memory.grow",
            "i32.add",
        ], export="f")
        self._compare(b.build(), "f", [0, 1, 2, 5], conf=conf)

    def test_trap_isolation(self):
        # one lane traps mid-run; others must complete unaffected
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("i32.const", 100), ("local.get", 0), "i32.div_s",
        ], export="f")
        self._compare(b.build(), "f", [1, 2, 0, 5, -1])

    def test_unreachable_and_oob(self):
        b = ModuleBuilder()
        b.add_memory(1, 1)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.load", 2, 0),
        ], export="f")
        self._compare(b.build(), "f", [0, 65532, 65533, 70000, -4])

    def test_deep_recursion_exhaustion(self):
        from wasmedge_tpu.common.configure import Configure
        conf = Configure()
        conf.runtime.max_call_depth = 64
        conf.batch.call_stack_depth = 64
        b = ModuleBuilder()
        # count down, recursing; lane with big n exhausts the call stack
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.const", 0), "i32.le_s",
            ("if", "i32"),
            ("i32.const", 0),
            "else",
            ("local.get", 0), ("i32.const", 1), "i32.sub", ("call", 0),
            ("i32.const", 1), "i32.add",
            "end",
        ], export="f")
        self._compare(b.build(), "f", [0, 10, 63, 64, 200], conf=conf)

    def test_fuel_limit(self):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.batch import BatchEngine
        from wasmedge_tpu.models import build_fib
        from wasmedge_tpu.common.errors import ErrCode

        conf = Configure()
        conf.batch.fuel_per_launch = 500
        ex, store, inst = instantiate(build_fib())
        eng = BatchEngine(inst, store=store, lanes=4, conf=conf)
        res = eng.run("fib", [np.array([1, 5, 20, 25], np.int64)])
        assert int(res.trap[0]) == -1  # cheap lane finishes
        assert int(res.trap[2]) == int(ErrCode.CostLimitExceeded)
        assert int(res.trap[3]) == int(ErrCode.CostLimitExceeded)


class TestUniformEngine:
    """Converged fast path must agree with the scalar oracle, and its
    divergence handoff to SIMT must be seamless (same final results)."""

    def _compare_uniform(self, data, func, arg_lanes, conf=None,
                         expect_fallback=None, max_steps=2_000_000):
        from wasmedge_tpu.batch import UniformBatchEngine

        want_vals, want_traps = [], []
        for a in arg_lanes:
            ex, store, inst = instantiate(data, conf)
            fi = inst.find_func(func)
            try:
                out = ex.invoke_raw(store, fi, [a & 0xFFFFFFFFFFFFFFFF])
                want_vals.append(out[0] if out else 0)
                want_traps.append(-1)
            except TrapError as e:
                want_vals.append(None)
                want_traps.append(int(e.code))
        ex2, store2, inst2 = instantiate(data, conf)
        eng = UniformBatchEngine(inst2, store=store2, lanes=len(arg_lanes),
                                 conf=conf)
        res = eng.run(func, [np.asarray(arg_lanes, np.int64)],
                      max_steps=max_steps)
        if expect_fallback is not None:
            assert eng.fell_back_to_simt == expect_fallback
        for k in range(len(arg_lanes)):
            assert int(res.trap[k]) == want_traps[k], \
                f"lane {k} trap {want_traps[k]} vs {int(res.trap[k])}"
            if want_traps[k] == -1:
                got = int(res.results[0][k]) & 0xFFFFFFFFFFFFFFFF
                want = want_vals[k] & 0xFFFFFFFFFFFFFFFF
                assert got == want, f"lane {k}: {want:#x} != {got:#x}"

    def test_converged_fib(self):
        from wasmedge_tpu.models import build_fib
        self._compare_uniform(build_fib(), "fib", [13] * 8,
                              expect_fallback=False)

    def test_divergent_fib_falls_back(self):
        from wasmedge_tpu.models import build_fib
        self._compare_uniform(build_fib(), "fib", list(range(10)),
                              expect_fallback=True)

    def test_converged_memory_workload(self):
        from wasmedge_tpu.models import build_memory_workload
        self._compare_uniform(build_memory_workload(), "mem_checksum",
                              [64] * 4, expect_fallback=False)

    def test_converged_i64_fac(self):
        from wasmedge_tpu.models import build_fac
        self._compare_uniform(build_fac(), "fac", [15] * 4,
                              expect_fallback=False)

    def test_uniform_trap_all_lanes(self):
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("i32.const", 1), ("local.get", 0), "i32.div_u",
        ], export="f")
        self._compare_uniform(b.build(), "f", [0, 0, 0], expect_fallback=False)

    def test_partial_trap_diverges(self):
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("i32.const", 100), ("local.get", 0), "i32.div_s",
        ], export="f")
        self._compare_uniform(b.build(), "f", [2, 0, 5], expect_fallback=True)

    def test_partial_oob_diverges(self):
        b = ModuleBuilder()
        b.add_memory(1, 1)
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.load", 2, 0),
        ], export="f")
        self._compare_uniform(b.build(), "f", [0, 70000, 8],
                              expect_fallback=True)

    def test_memory_grow_no_declared_max(self):
        # no-max memory: growth ceiling = memory_pages_per_lane knob
        from wasmedge_tpu.common.configure import Configure
        conf = Configure()
        conf.batch.memory_pages_per_lane = 4
        conf.runtime.max_memory_pages = 4  # align the scalar oracle's limit
        b = ModuleBuilder()
        b.add_memory(1)  # no max
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), "memory.grow", "drop", "memory.size",
        ], export="f")
        self._compare_uniform(b.build(), "f", [1, 1], conf=conf,
                              expect_fallback=False)

    def test_memory_grow_from_zero_min(self):
        # (memory 0) with no max: grow must still succeed up to the knob
        from wasmedge_tpu.common.configure import Configure
        conf = Configure()
        conf.batch.memory_pages_per_lane = 4
        conf.runtime.max_memory_pages = 4
        b = ModuleBuilder()
        b.add_memory(0)  # min 0, no max
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), "memory.grow", "drop", "memory.size",
        ], export="f")
        self._compare_uniform(b.build(), "f", [2, 2], conf=conf,
                              expect_fallback=False)

    def test_engine_factory(self):
        from wasmedge_tpu.batch import make_engine, UniformBatchEngine, BatchEngine
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.models import build_fib

        ex, store, inst = instantiate(build_fib())
        conf = Configure()
        assert isinstance(make_engine(inst, store, conf, lanes=2),
                          UniformBatchEngine)
        conf.batch.uniform = False
        assert isinstance(make_engine(inst, store, conf, lanes=2), BatchEngine)
