"""v128 on the batch (SIMT) engine: lane-parallel parity vs the scalar
oracle.

BASELINE config 3's requirement ("v128 lane ops in the *batched* numeric
path").  The op bodies are GENERATED from batch/simdops.py's supported-op
tables, so any op added to the batch subset is automatically parity-
checked here; each module chains every op of a family and folds the
results into one i64 accumulator, so one compile covers the family."""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.batch.simdops import (
    V1_NAMES,
    V2_NAMES,
    VSHIFT_NAMES,
    VSPLAT_NAMES,
    VTEST_NAMES,
)
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

LANES = 8


def fold(acc_local, av128_expr):
    """acc ^= e0 ^ (e1 * 3) of the v128 in local `av128_expr` position."""
    return av128_expr + [
        ("local.tee", 3),
        ("i64x2.extract_lane", 0),
        ("local.get", acc_local), "i64.xor",
        ("local.get", 3), ("i64x2.extract_lane", 1),
        ("i64.const", 3), "i64.mul", "i64.xor",
        ("local.set", acc_local),
    ]


def build_sweep(op_bodies):
    """f(x: i64, y: i64) -> i64 chaining per-op bodies over v128 locals.

    locals: 2=a(v128 built from x), 3=scratch v128, 4=acc(i64),
            5=b(v128 built from y)"""
    b = ModuleBuilder()
    body = [
        ("local.get", 0), "i64x2.splat",
        ("local.get", 0), ("i64.const", 0x9E3779B97F4A7C15 - 2**64),
        "i64.mul", ("i64x2.replace_lane", 1),
        ("local.set", 2),
        ("local.get", 1), "i64x2.splat",
        ("local.get", 1), ("i64.const", 0xC2B2AE3D27D4EB4F - 2**64),
        "i64.xor", ("i64x2.replace_lane", 1),
        ("local.set", 5),
    ]
    for op_body in op_bodies:
        body += fold(4, op_body)
    body += [("local.get", 4)]
    b.add_function(["i64", "i64"], ["i64"], ["v128", "v128", "i64", "v128"],
                   body, export="f")
    return b.build()


def check_parity(data, args_list):
    from wasmedge_tpu.batch import BatchEngine

    conf = Configure()
    conf.batch.steps_per_launch = 50_000
    ex, store, inst = instantiate(data, conf)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=LANES)
    assert eng.img.has_simd
    args = [np.asarray(a, np.int64) for a in args_list]
    res = eng.run("f", args, max_steps=500_000)
    for lane in range(LANES):
        s_ex, s_store, s_inst = instantiate(data, Configure())
        expect = s_ex.invoke(s_store, s_inst.find_func("f"),
                             [int(a[lane]) for a in args])
        assert res.trap[lane] == -1, f"lane {lane} trapped {res.trap[lane]}"
        got = int(res.results[0][lane]) & (2**64 - 1)
        want = int(expect[0]) & (2**64 - 1)
        assert got == want, f"lane {lane}: {got:#x} != {want:#x}"
    return res


def rand_args(seed):
    rng = np.random.default_rng(seed)
    return [rng.integers(-2**63, 2**63 - 1, LANES, np.int64),
            rng.integers(-2**63, 2**63 - 1, LANES, np.int64)]


# f32 arithmetic on the batch path inherits the scalar batch ALU's one
# documented divergence: XLA flushes f32 subnormals (the spec corpus
# likewise skips 'subnormal' files for the batched run).  Random 64-bit
# patterns hit that, so these ops are parity-checked with normal-range
# float inputs in test_float_family_parity instead.
_F32_FTZ_SENSITIVE = {"f32x4.add", "f32x4.sub", "f32x4.mul", "f32x4.div",
                      "f32x4.sqrt", "f32x4.demote_f64x2_zero"}


# The family sweeps are CHUNKED: one module per ~20 ops.  A single
# module chaining all ~230 ops makes one enormous XLA step function
# (the f64 softfloat subgraphs alone are huge) whose compile dominates
# the suite; smaller modules compile in seconds each.
_CHUNK = 20


def _chunks(names):
    names = [n for n in names if n not in _F32_FTZ_SENSITIVE]
    return [names[i:i + _CHUNK] for i in range(0, len(names), _CHUNK)]


@pytest.mark.parametrize("ops", _chunks(V2_NAMES),
                         ids=lambda c: c[0].replace(".", "_"))
def test_v2_family_parity(ops):
    bodies = [[("local.get", 2), ("local.get", 5), op] for op in ops]
    check_parity(build_sweep(bodies), rand_args(1))


@pytest.mark.parametrize("ops", _chunks(V1_NAMES),
                         ids=lambda c: c[0].replace(".", "_"))
def test_v1_family_parity(ops):
    bodies = [[("local.get", 2), op] for op in ops]
    check_parity(build_sweep(bodies), rand_args(2))


def test_vtest_family_parity():
    # vtest produce i32: wrap into a splat so fold() sees a v128
    bodies = [[("local.get", 2), op, "i32x4.splat"] for op in VTEST_NAMES]
    bodies += [[("local.get", 5), op, "i32x4.splat"] for op in VTEST_NAMES]
    check_parity(build_sweep(bodies), rand_args(2))


def _float_args(seed, f64=False):
    """i64 lane args packing normal-range floats (exponents near 1.0):
    no subnormal inputs and no subnormal-producing products/sums."""
    rng = np.random.default_rng(seed)
    if f64:
        vals = rng.uniform(-8.0, 8.0, LANES)
        vals[vals == 0] = 1.5
        return [np.asarray([np.float64(v).view(np.int64) for v in vals],
                           np.int64)]
    lo = np.asarray([np.float32(v).view(np.int32) for v in
                     rng.uniform(-8.0, 8.0, LANES)], np.int64) & 0xFFFFFFFF
    hi = np.asarray([np.float32(v).view(np.int32) for v in
                     rng.uniform(0.1, 4.0, LANES)], np.int64) & 0xFFFFFFFF
    return [lo | (hi << 32)]


def build_float_sweep(op_bodies):
    """Like build_sweep but v128 locals are built WITHOUT bit scrambling
    (splat keeps the packed normal floats intact)."""
    b = ModuleBuilder()
    body = [
        ("local.get", 0), "i64x2.splat", ("local.set", 2),
        ("local.get", 1), "i64x2.splat", ("local.set", 5),
    ]
    for op_body in op_bodies:
        body += fold(4, op_body)
    body += [("local.get", 4)]
    b.add_function(["i64", "i64"], ["i64"], ["v128", "v128", "i64", "v128"],
                   body, export="f")
    return b.build()


def test_float_f32_family_parity():
    """Every f32x4 op (incl. the FTZ-sensitive arithmetic) with
    normal-range inputs, bit-exact against the scalar oracle."""
    f32_v2 = [n for n in V2_NAMES if n.startswith("f32x4.")]
    f32_v1 = [n for n in V1_NAMES if n.startswith("f32x4.")
              and "convert" not in n and "demote" not in n]
    bodies = [[("local.get", 2), ("local.get", 5), op]
              for op in f32_v2]
    bodies += [[("local.get", 2), op] for op in f32_v1]
    bodies += [[("local.get", 2), "f64x2.promote_low_f32x4",
                "f32x4.demote_f64x2_zero"]]
    a32 = _float_args(11)[0]
    b32 = _float_args(12)[0]
    check_parity(build_float_sweep(bodies), [a32, b32])


@pytest.mark.parametrize("half", [0, 1])
def test_float_f64_family_parity(half):
    f64_v2 = [n for n in V2_NAMES if n.startswith("f64x2.")]
    f64_v1 = [n for n in V1_NAMES if n.startswith("f64x2.")
              and "convert" not in n and "promote" not in n]
    ops = (f64_v2 + f64_v1)
    ops = ops[:len(ops) // 2] if half == 0 else ops[len(ops) // 2:]
    bodies = []
    for op in ops:
        if op in {n for n in V2_NAMES}:
            bodies.append([("local.get", 2), ("local.get", 5), op])
        else:
            bodies.append([("local.get", 2), op])
    a64 = _float_args(13, f64=True)[0]
    b64 = _float_args(14, f64=True)[0]
    check_parity(build_float_sweep(bodies), [a64, b64])


def test_shift_and_splat_family_parity():
    bodies = []
    for i, op in enumerate(VSHIFT_NAMES):
        bodies.append([("local.get", 2),
                       ("local.get", 1), "i32.wrap_i64",
                       ("i32.const", i), "i32.add", op])
    for op in VSPLAT_NAMES:
        if op.startswith("i64x2"):
            bodies.append([("local.get", 0), op])
        elif op.startswith("f64x2"):
            bodies.append([("local.get", 0), "f64.reinterpret_i64", op])
        elif op.startswith("f32x4"):
            bodies.append([("local.get", 0), "i32.wrap_i64",
                           "f32.reinterpret_i32", op])
        else:
            bodies.append([("local.get", 0), "i32.wrap_i64", op])
    check_parity(build_sweep(bodies), rand_args(3))


def test_lane_ops_shuffle_swizzle_bitselect_parity():
    k1 = int.from_bytes(bytes(range(16)), "little")
    shuf = [0, 17, 2, 19, 4, 21, 6, 23, 8, 25, 10, 27, 12, 29, 14, 31]
    bodies = [
        # extract/replace at several lanes and widths
        [("local.get", 2),
         ("local.get", 2), ("i8x16.extract_lane_s", 3), ("i32.const", 1),
         "i32.add", ("i8x16.replace_lane", 9)],
        [("local.get", 2),
         ("local.get", 5), ("i8x16.extract_lane_u", 15),
         ("i16x8.replace_lane", 2)],
        [("local.get", 2),
         ("local.get", 5), ("i16x8.extract_lane_s", 5), ("i32.const", 7),
         "i32.mul", ("i32x4.replace_lane", 1)],
        [("local.get", 2),
         ("local.get", 5), ("i16x8.extract_lane_u", 7),
         ("i32x4.replace_lane", 3)],
        [("local.get", 2),
         ("local.get", 5), ("i32x4.extract_lane", 2),
         ("i8x16.replace_lane", 0)],
        # bitselect and constant masks
        [("local.get", 2), ("local.get", 5), ("v128.const", k1),
         "v128.bitselect"],
        # static shuffle interleaving both operands, then swizzle
        [("local.get", 2), ("local.get", 5), ("i8x16.shuffle", shuf)],
        [("local.get", 2), ("local.get", 5), "i8x16.swizzle"],
        [("v128.const", k1)],
    ]
    check_parity(build_sweep(bodies), rand_args(4))


def test_v128_memory_roundtrip_parity():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    body = [
        # build a vector from both params, store at unaligned + aligned
        ("local.get", 0), "i64x2.splat",
        ("local.get", 1), ("i64x2.replace_lane", 1), ("local.set", 2),
        ("i32.const", 16), ("local.get", 2), ("v128.store", 0, 0),
        ("i32.const", 37), ("local.get", 2), ("v128.store", 0, 0),
        # reload both, xor, fold to i64
        ("i32.const", 16), ("v128.load", 0, 0),
        ("i32.const", 37), ("v128.load", 0, 0),
        "v128.xor",
        ("i32.const", 33), ("v128.load", 0, 0),
        "v128.and",
        ("local.tee", 3),
        ("i64x2.extract_lane", 0),
        ("local.get", 3), ("i64x2.extract_lane", 1),
        "i64.xor",
    ]
    b.add_function(["i64", "i64"], ["i64"], ["v128", "v128"], body,
                   export="f")
    check_parity(b.build(), rand_args(5))


def test_v128_oob_load_traps():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    body = [
        ("local.get", 0), "i32.wrap_i64", ("v128.load", 0, 0),
        ("i64x2.extract_lane", 0),
    ]
    b.add_function(["i64", "i64"], ["i64"], [], body, export="f")
    from wasmedge_tpu.batch import BatchEngine
    from wasmedge_tpu.common.errors import ErrCode

    conf = Configure()
    conf.batch.steps_per_launch = 10_000
    ex, store, inst = instantiate(b.build(), conf)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=LANES)
    addrs = np.asarray([0, 65521, 65528, 8, 65535, 16, 70000, 60000],
                       np.int64)
    res = eng.run("f", [addrs, np.zeros(LANES, np.int64)],
                  max_steps=100_000)
    oob = (addrs + 16 > 65536)
    assert (res.trap[oob] == int(ErrCode.MemoryOutOfBounds)).all()
    assert (res.trap[~oob] == -1).all()


def test_simd_module_falls_off_pallas_to_simt():
    from wasmedge_tpu.batch.uniform import UniformBatchEngine

    b = ModuleBuilder()
    body = [("local.get", 0), "i32.wrap_i64", "i32x4.splat",
            ("i32x4.extract_lane", 2), "i64.extend_i32_s"]
    b.add_function(["i64", "i64"], ["i64"], [], body, export="f")
    conf = Configure()
    conf.batch.interpret = True
    conf.batch.steps_per_launch = 10_000
    ex, store, inst = instantiate(b.build(), conf)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)
    xs = np.arange(LANES, dtype=np.int64) - 3
    res = eng.run("f", [xs, xs], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([int(np.int32(x)) for x in xs])).all()
