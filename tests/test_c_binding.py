"""Out-of-process C embedding: build bindings/c's shim + example with the
system C compiler and run fib through it — proving the embedding surface
is usable from outside Python (the reference's bindings/rust analog)."""

import os
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
CDIR = os.path.join(ROOT, "bindings", "c")


def _config_tool():
    exe = f"python{sys.version_info.major}.{sys.version_info.minor}-config"
    cfg = shutil.which(exe) or shutil.which("python3-config")
    if cfg is None:
        pytest.skip("python3-config not available")
    return cfg


def _python_config(*flags):
    out = subprocess.run([_config_tool(), *flags], capture_output=True,
                         text=True)
    if out.returncode != 0:
        pytest.skip(f"python3-config {' '.join(flags)} failed")
    return out.stdout.split()


def test_c_example_runs_fib(tmp_path):
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None:
        pytest.skip("no C compiler")
    includes = _python_config("--includes")
    embed = subprocess.run([_config_tool(), "--embed", "--ldflags"],
                           capture_output=True, text=True)
    ldflags = embed.stdout.split() if embed.returncode == 0 \
        else _python_config("--ldflags")
    exe = tmp_path / "example_fib"
    build = subprocess.run(
        [cc, os.path.join(CDIR, "example_fib.c"),
         os.path.join(CDIR, "shim.c"), "-I", CDIR, "-o", str(exe)]
        + includes + ldflags,
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    from wasmedge_tpu.models import build_fib

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    env = dict(os.environ, WASMEDGE_TPU_PYROOT=ROOT)
    run = subprocess.run([str(exe), str(wasm)], capture_output=True,
                         text=True, env=env, timeout=300)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "fib(24) = 46368" in run.stdout


def test_cpp_sdk_fib_and_wasi(tmp_path):
    """The typed C++ SDK (bindings/cpp) out of process: staged fib with
    typed values + error mapping, and a WASI command program with argv
    and an exit code — the wasmedge-sdk analog over the C shim
    (reference: bindings/rust/wasmedge-sdk/src/vm.rs)."""
    cxx = shutil.which("c++") or shutil.which("g++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    includes = _python_config("--includes")
    embed = subprocess.run([_config_tool(), "--embed", "--ldflags"],
                           capture_output=True, text=True)
    ldflags = embed.stdout.split() if embed.returncode == 0 \
        else _python_config("--ldflags")
    cppdir = os.path.join(ROOT, "bindings", "cpp")
    exe = tmp_path / "example_sdk"
    build = subprocess.run(
        [cxx, "-std=c++17", os.path.join(cppdir, "example_sdk.cc"),
         os.path.join(CDIR, "shim.c"), "-I", CDIR, "-o", str(exe)]
        + includes + ldflags,
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.utils.wat import parse_wat

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    # WASI guest: exit code = number of argv entries * 10
    wasi_wat = """(module
      (import "wasi_snapshot_preview1" "args_sizes_get"
        (func $sizes (param i32 i32) (result i32)))
      (import "wasi_snapshot_preview1" "proc_exit"
        (func $exit (param i32)))
      (memory (export "memory") 1)
      (func (export "_start")
        (drop (call $sizes (i32.const 0) (i32.const 4)))
        (call $exit (i32.mul (i32.load (i32.const 0)) (i32.const 10)))))"""
    wasi = tmp_path / "guest.wasm"
    wasi.write_bytes(parse_wat(wasi_wat))
    env = dict(os.environ, WASMEDGE_TPU_PYROOT=ROOT)
    run = subprocess.run(
        [str(exe), str(wasm), "20", str(wasi), "30"],
        capture_output=True, text=True, env=env, timeout=300)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "fib=6765" in run.stdout
    assert "wasi-exit=30 want=30" in run.stdout
    assert "SDK OK" in run.stdout
