"""Out-of-process C embedding: build bindings/c's shim + example with the
system C compiler and run fib through it — proving the embedding surface
is usable from outside Python (the reference's bindings/rust analog)."""

import os
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
CDIR = os.path.join(ROOT, "bindings", "c")


def _config_tool():
    exe = f"python{sys.version_info.major}.{sys.version_info.minor}-config"
    cfg = shutil.which(exe) or shutil.which("python3-config")
    if cfg is None:
        pytest.skip("python3-config not available")
    return cfg


def _python_config(*flags):
    out = subprocess.run([_config_tool(), *flags], capture_output=True,
                         text=True)
    if out.returncode != 0:
        pytest.skip(f"python3-config {' '.join(flags)} failed")
    return out.stdout.split()


def test_c_example_runs_fib(tmp_path):
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None:
        pytest.skip("no C compiler")
    includes = _python_config("--includes")
    embed = subprocess.run([_config_tool(), "--embed", "--ldflags"],
                           capture_output=True, text=True)
    ldflags = embed.stdout.split() if embed.returncode == 0 \
        else _python_config("--ldflags")
    exe = tmp_path / "example_fib"
    build = subprocess.run(
        [cc, os.path.join(CDIR, "example_fib.c"),
         os.path.join(CDIR, "shim.c"), "-I", CDIR, "-o", str(exe)]
        + includes + ldflags,
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    from wasmedge_tpu.models import build_fib

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    env = dict(os.environ, WASMEDGE_TPU_PYROOT=ROOT)
    run = subprocess.run([str(exe), str(wasm)], capture_output=True,
                         text=True, env=env, timeout=300)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "fib(24) = 46368" in run.stdout
