"""Embedding-API suite: unit coverage + the spec corpus through the VM
family — the reference's APIUnitTest + APIVMCoreTest pattern
(test/api/APIUnitTest.cpp, APIVMCoreTest.cpp:1-244)."""

import glob
import os

import numpy as np
import pytest

from wasmedge_tpu import capi as C
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.spec import SpecTest
from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.utils.builder import ModuleBuilder

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# value / result / configure units
# ---------------------------------------------------------------------------

def test_value_roundtrips():
    assert C.we_ValueGetI32(C.we_ValueGenI32(-5)) == -5
    assert C.we_ValueGetI32(C.we_ValueGenI32(0x7FFFFFFF)) == 0x7FFFFFFF
    assert C.we_ValueGetI64(C.we_ValueGenI64(-(2**63))) == -(2**63)
    assert C.we_ValueGetF32(C.we_ValueGenF32(1.5)) == 1.5
    assert C.we_ValueGetF64(C.we_ValueGenF64(-2.25)) == -2.25
    v = C.we_ValueGenF32(float("nan"))
    assert C.we_ValueGetF32(v) != C.we_ValueGetF32(v)  # NaN


def test_wasi_host_registration_via_capi():
    conf = C.we_ConfigureCreate()
    C.we_ConfigureAddHostRegistration(conf, "wasi")
    vm = C.we_VMCreate(conf)
    assert vm.vm.wasi_module is not None
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "args_sizes_get",
                  ["i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function([], ["i32"], [], [
        ("i32.const", 0), ("i32.const", 8), ("call", 0),
    ], export="f")
    res, out = C.we_VMRunWasmFromBuffer(vm, b.build(), "f")
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 0  # Errno.SUCCESS


def test_arity_mismatch_is_result():
    vm = C.we_VMCreate()
    res, out = C.we_VMRunWasmFromBuffer(vm, build_fib(), "fib", [])
    assert not C.we_ResultOK(res)
    assert C.we_ResultGetCode(res) == int(ErrCode.FuncSigMismatch)


def test_missing_file_is_result():
    vm = C.we_VMCreate()
    res, out = C.we_VMRunWasmFromFile(vm, "/nonexistent/x.wasm", "f")
    assert not C.we_ResultOK(res)
    assert C.we_ResultGetCode(res) == int(ErrCode.IllegalPath)


def test_configure_knobs():
    conf = C.we_ConfigureCreate()
    C.we_ConfigureAddProposal(conf, "tail-call")
    assert C.we_ConfigureHasProposal(conf, "tail-call")
    assert C.we_ConfigureHasProposal(conf, "simd")  # default-on
    C.we_ConfigureRemoveProposal(conf, "tail-call")
    assert not C.we_ConfigureHasProposal(conf, "tail-call")
    C.we_ConfigureAddHostRegistration(conf, "wasi")
    assert C.we_ConfigureHasHostRegistration(conf, "wasi")
    C.we_ConfigureSetMaxMemoryPage(conf, 16)
    assert C.we_ConfigureGetMaxMemoryPage(conf) == 16
    C.we_ConfigureSetEngine(conf, "native")
    assert C.we_ConfigureGetEngine(conf) == "native"
    C.we_ConfigureStatisticsSetInstructionCounting(conf, True)
    assert C.we_ConfigureStatisticsIsInstructionCounting(conf)


# ---------------------------------------------------------------------------
# staged pipeline (APIStepsCoreTest model)
# ---------------------------------------------------------------------------

def test_staged_pipeline():
    conf = C.we_ConfigureCreate()
    loader = C.we_LoaderCreate(conf)
    res, mod = C.we_LoaderParseFromBuffer(loader, build_fib())
    assert C.we_ResultOK(res)
    assert C.we_ASTModuleListExports(mod) == [("fib", "func")]
    validator = C.we_ValidatorCreate(conf)
    assert C.we_ResultOK(C.we_ValidatorValidate(validator, mod))
    store = C.we_StoreCreate()
    ex = C.we_ExecutorCreate(conf)
    res, inst = C.we_ExecutorInstantiate(ex, store, mod)
    assert C.we_ResultOK(res)
    fi = C.we_ModuleInstanceFindFunction(inst, "fib")
    assert fi is not None
    res, out = C.we_ExecutorInvoke(ex, store, fi, [C.we_ValueGenI32(10)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 55


def test_malformed_module_result():
    loader = C.we_LoaderCreate()
    res, mod = C.we_LoaderParseFromBuffer(loader, b"\x00asm\x02\x00\x00\x00")
    assert not C.we_ResultOK(res)
    assert C.we_ResultGetCode(res) == int(ErrCode.MalformedVersion)
    assert mod is None


# ---------------------------------------------------------------------------
# VM family
# ---------------------------------------------------------------------------

def test_vm_run_wasm():
    vm = C.we_VMCreate()
    res, out = C.we_VMRunWasmFromBuffer(vm, build_fib(), "fib",
                                        [C.we_ValueGenI32(12)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 144
    funcs = C.we_VMGetFunctionList(vm)
    assert funcs[0][0] == "fib"
    ft = C.we_VMGetFunctionType(vm, "fib")
    assert len(ft.params) == 1 and len(ft.results) == 1


def test_vm_trap_result():
    b = ModuleBuilder()
    b.add_function([], [], [], [("unreachable",)], export="boom")
    vm = C.we_VMCreate()
    res, out = C.we_VMRunWasmFromBuffer(vm, b.build(), "boom")
    assert not C.we_ResultOK(res)
    assert C.we_ResultGetCode(res) == int(ErrCode.Unreachable)


def test_vm_register_and_imports():
    lib = ModuleBuilder()
    lib.add_function(["i32"], ["i32"], [],
                     [("local.get", 0), ("i32.const", 2), "i32.mul"],
                     export="double")
    vm = C.we_VMCreate()
    assert C.we_ResultOK(
        C.we_VMRegisterModuleFromBuffer(vm, "lib", lib.build()))
    res, out = C.we_VMExecuteRegistered(vm, "lib", "double",
                                        [C.we_ValueGenI32(21)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 42

    # host import object + wasm importing it
    imp = C.we_ImportObjectCreate("env")
    seen = []
    C.we_ImportObjectAddFunction(imp, "note", ["i32"], ["i32"],
                                 lambda mem, x: (seen.append(x), x + 1)[1])
    assert C.we_ResultOK(C.we_VMRegisterModuleFromImport(vm, imp))
    user = ModuleBuilder()
    user.import_func("env", "note", ["i32"], ["i32"])
    user.add_function(["i32"], ["i32"], [],
                      [("local.get", 0), ("call", 0)], export="f")
    res, out = C.we_VMRunWasmFromBuffer(vm, user.build(), "f",
                                        [C.we_ValueGenI32(7)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 8
    assert seen == [7]


def test_vm_async_execute_and_cancel():
    b = ModuleBuilder()
    b.add_function([], [], [], [("loop",), ("br", 0), ("end",)],
                   export="spin")
    vm = C.we_VMCreate()
    assert C.we_ResultOK(C.we_VMLoadWasmFromBuffer(vm, b.build()))
    assert C.we_ResultOK(C.we_VMValidate(vm))
    assert C.we_ResultOK(C.we_VMInstantiate(vm))
    h = C.we_VMAsyncExecute(vm, "spin")
    assert not C.we_AsyncWaitFor(h, 100)
    C.we_AsyncCancel(h)
    res, _ = C.we_AsyncGet(h)
    assert C.we_ResultGetCode(res) == int(ErrCode.Terminated)


def test_vm_async_f64_roundtrip():
    """Raw float cells must survive the async (typed) path unchanged."""
    b = ModuleBuilder()
    b.add_function(["f64"], ["f64"], [],
                   [("local.get", 0)], export="id")
    vm = C.we_VMCreate()
    assert C.we_ResultOK(C.we_VMLoadWasmFromBuffer(vm, b.build()))
    assert C.we_ResultOK(C.we_VMValidate(vm))
    assert C.we_ResultOK(C.we_VMInstantiate(vm))
    h = C.we_VMAsyncExecute(vm, "id", [C.we_ValueGenF64(1.5)])
    res, out = C.we_AsyncGet(h)
    assert C.we_ResultOK(res)
    assert C.we_ValueGetF64(out[0]) == 1.5


def test_vm_statistics():
    conf = C.we_ConfigureCreate()
    C.we_ConfigureStatisticsSetInstructionCounting(conf, True)
    vm = C.we_VMCreate(conf)
    res, out = C.we_VMRunWasmFromBuffer(vm, build_fib(), "fib",
                                        [C.we_ValueGenI32(10)])
    assert C.we_ResultOK(res)
    stat = C.we_VMGetStatisticsContext(vm)
    assert C.we_StatisticsGetInstrCount(stat) > 100


def test_memory_and_global_accessors():
    b = ModuleBuilder()
    b.add_memory(1, 2, export="mem")
    b.add_global("i64", True, [("i64.const", -7)], export="g")
    b.add_function([], [], [], [], export="noop")
    vm = C.we_VMCreate()
    res, _ = C.we_VMRunWasmFromBuffer(vm, b.build(), "noop")
    assert C.we_ResultOK(res)
    inst = vm.vm.active_module
    mem = C.we_ModuleInstanceFindMemory(inst, "mem")
    assert C.we_MemoryInstanceGetPageSize(mem) == 1
    assert C.we_ResultOK(C.we_MemoryInstanceSetData(mem, 8, b"\xAA\xBB"))
    res, data = C.we_MemoryInstanceGetData(mem, 8, 2)
    assert data == b"\xAA\xBB"
    assert C.we_ResultOK(C.we_MemoryInstanceGrowPage(mem, 1))
    assert C.we_MemoryInstanceGetPageSize(mem) == 2
    assert not C.we_ResultOK(C.we_MemoryInstanceGrowPage(mem, 10))
    g = C.we_ModuleInstanceFindGlobal(inst, "g")
    gv = C.we_GlobalInstanceGetValue(g)
    assert gv.type == "i64"
    assert C.we_ValueGetI64(gv) == -7


def test_vm_batch_extension():
    vm = C.we_VMCreate()
    assert C.we_ResultOK(C.we_VMLoadWasmFromBuffer(vm, build_fib()))
    assert C.we_ResultOK(C.we_VMValidate(vm))
    assert C.we_ResultOK(C.we_VMInstantiate(vm))
    res, batch = C.we_VMBatchExecute(
        vm, "fib", [np.full(8, 10, np.int64)], lanes=8)
    assert C.we_ResultOK(res)
    assert (batch.results[0] == 55).all()


def test_vm_batch_weighted_cost_table_gas():
    """A non-uniform cost table set through the C API drives the batch
    engine's fuel: the weighted kill fires where flat per-instruction
    counting would not (reference: CostTab-weighted gas,
    include/common/statistics.h:85-98)."""
    from wasmedge_tpu.common.errors import ErrCode
    from wasmedge_tpu.common.opcodes import NAME_TO_ID
    from wasmedge_tpu.common.statistics import _NUM_COST_SLOTS

    def make_vm(limit, table=None):
        conf = C.we_ConfigureCreate()
        C.we_ConfigureStatisticsSetCostMeasuring(conf, True)
        vm = C.we_VMCreate(conf)
        stat = C.we_VMGetStatisticsContext(vm)
        C.we_StatisticsSetCostLimit(stat, limit)
        if table is not None:
            C.we_StatisticsSetCostTable(stat, table)
        assert C.we_ResultOK(C.we_VMLoadWasmFromBuffer(vm, build_fib()))
        assert C.we_ResultOK(C.we_VMValidate(vm))
        assert C.we_ResultOK(C.we_VMInstantiate(vm))
        return vm

    # fib(15) retires ~10k instructions / ~1.2k i32.add ops.  A flat
    # budget of 100k completes easily...
    vm = make_vm(100_000)
    res, ok = C.we_VMBatchExecute(vm, "fib", [np.full(4, 15, np.int64)],
                                  lanes=4)
    assert C.we_ResultOK(res) and (ok.trap == -1).all()
    # ...but the same budget with i32.add weighted 1000x must kill every
    # lane with the gas trap: ~1.2k adds * 1000 >> 100k
    table = [1] * _NUM_COST_SLOTS
    table[int(NAME_TO_ID["i32.add"])] = 1000
    vm = make_vm(100_000, table)
    res, killed = C.we_VMBatchExecute(vm, "fib",
                                      [np.full(4, 15, np.int64)], lanes=4)
    assert C.we_ResultOK(res)
    assert (killed.trap == int(ErrCode.CostLimitExceeded)).all()
    # a uniform-weight run under the same table geometry still completes
    vm = make_vm(100_000, [1] * _NUM_COST_SLOTS)
    res, ok2 = C.we_VMBatchExecute(vm, "fib", [np.full(4, 15, np.int64)],
                                   lanes=4)
    assert C.we_ResultOK(res) and (ok2.trap == -1).all()


# ---------------------------------------------------------------------------
# the spec corpus through the capi VM family (APIVMCoreTest model)
# ---------------------------------------------------------------------------

def _capi_spec_callbacks(conf=None):
    vm = C.we_VMCreate(conf)
    bytes_of = {}  # handle -> module bytes (register replays them)

    def on_module(name, data):
        if name:
            res = C.we_VMRegisterModuleFromBuffer(vm, name.lstrip("$"), data)
            _raise(res)
            h = ("named", name.lstrip("$"))
            bytes_of[h] = data
            return h
        res = C.we_VMLoadWasmFromBuffer(vm, data)
        _raise(res)
        _raise(C.we_VMValidate(vm))
        _raise(C.we_VMInstantiate(vm))
        h = ("active", None)
        bytes_of[h] = data
        return h

    def _raise(res):
        if not C.we_ResultOK(res):
            code = ErrCode(C.we_ResultGetCode(res))
            from wasmedge_tpu.common.errors import (
                LoadError, ValidationError)
            msg = C.we_ResultGetMessage(res)
            if int(code) < 0x40:
                raise LoadError(code, msg)
            if int(code) < 0x80:
                raise ValidationError(code, msg)
            raise TrapError(code, msg)

    def on_invoke(handle, field, raw_args):
        kind, name = handle
        params = [C.we_Value("raw", a) for a in raw_args]
        if kind == "named":
            res, out = C.we_VMExecuteRegistered(vm, name, field, params)
        else:
            res, out = C.we_VMExecute(vm, field, params)
        _raise(res)
        return [v.raw for v in out]

    def on_register(handle, as_name):
        # replay the module bytes under the new namespace (the C API has
        # no alias-an-instance entry; state-aliasing register chains are
        # covered by the scalar harness)
        data = bytes_of.get(handle)
        if data is None:
            raise TrapError(ErrCode.FuncNotFound,
                            "register of unknown module")
        _raise(C.we_VMRegisterModuleFromBuffer(vm, as_name, data))

    return SpecTest(on_module, on_invoke, on_register)


def test_spec_corpus_through_capi():
    corpus = sorted(glob.glob(os.path.join(HERE, "spec", "*.wast")))
    assert corpus
    from wasmedge_tpu.spec import _conf_for_file

    total_passed = 0
    for path in corpus:
        # per-file proposal gating, as run_corpus does (tail_call.wast
        # needs the TailCall proposal enabled)
        st = _capi_spec_callbacks(_conf_for_file(path))
        with open(path) as f:
            rep = st.run_script(f.read(), os.path.basename(path))
        detail = "\n".join(str(x) for x in rep.failures[:10])
        assert rep.failed == 0, f"{path}: {rep.failed} failed\n{detail}"
        total_passed += rep.passed
    assert total_passed > 9900


# ---------------------------------------------------------------------------
# round-3 families: types, instance creation, ImportObjectAdd*, Compiler
# ---------------------------------------------------------------------------

def test_function_type_contexts():
    ft = C.we_FunctionTypeCreate(["i32", "i64"], ["f64"])
    assert C.we_FunctionTypeGetParametersLength(ft) == 2
    assert C.we_FunctionTypeGetParameters(ft) == ["i32", "i64"]
    assert C.we_FunctionTypeGetReturnsLength(ft) == 1
    assert C.we_FunctionTypeGetReturns(ft) == ["f64"]
    C.we_FunctionTypeDelete(ft)


def test_table_memory_global_types_and_instances():
    tt = C.we_TableTypeCreate("funcref", 4, 8)
    assert C.we_TableTypeGetRefType(tt) == "funcref"
    assert C.we_TableTypeGetLimit(tt) == (4, 8)
    tab = C.we_TableInstanceCreate(tt)
    assert C.we_TableInstanceGetSize(tab) == 4
    res = C.we_TableInstanceSetData(tab, 2, 7)
    assert C.we_ResultOK(res)
    res, ref = C.we_TableInstanceGetData(tab, 2)
    assert C.we_ResultOK(res) and ref == 7
    res, _ = C.we_TableInstanceGetData(tab, 99)
    assert not C.we_ResultOK(res)
    assert C.we_ResultOK(C.we_TableInstanceGrow(tab, 2))
    assert C.we_TableInstanceGetSize(tab) == 6

    mt = C.we_MemoryTypeCreate(1, 2)
    assert C.we_MemoryTypeGetLimit(mt) == (1, 2)
    mem = C.we_MemoryInstanceCreate(mt)
    assert C.we_MemoryInstanceGetPageSize(mem) == 1

    gt = C.we_GlobalTypeCreate("i64", True)
    assert C.we_GlobalTypeGetValType(gt) == "i64"
    assert C.we_GlobalTypeGetMutability(gt)
    g = C.we_GlobalInstanceCreate(gt, C.we_Value("i64", -5))
    assert C.we_GlobalInstanceGetGlobalType(g).mutable


def test_import_object_add_table_memory_global():
    """A module importing a host table/memory/global through the
    ImportObjectAdd* family (reference: ImportObjectAddTable etc.)."""
    imp = C.we_ImportObjectCreate("env")
    tab = C.we_TableInstanceCreate(C.we_TableTypeCreate("funcref", 2, 2))
    mem = C.we_MemoryInstanceCreate(C.we_MemoryTypeCreate(1, 1))
    glob = C.we_GlobalInstanceCreate(C.we_GlobalTypeCreate("i32", False),
                                     C.we_Value("i32", 41))
    C.we_ImportObjectAddTable(imp, "t", tab)
    C.we_ImportObjectAddMemory(imp, "m", mem)
    C.we_ImportObjectAddGlobal(imp, "g", glob)

    b = ModuleBuilder()
    b.import_table("env", "t", "funcref", 2, 2)
    b.import_memory("env", "m", 1, 1)
    b.import_global("env", "g", "i32", False)
    b.add_function([], ["i32"], [], [
        ("i32.const", 64), ("i32.const", 7), ("i32.store", 2, 0),
        ("i32.const", 64), ("i32.load", 2, 0),
        ("global.get", 0), "i32.add",
    ], export="f")
    vm = C.we_VMCreate()
    assert C.we_ResultOK(C.we_VMRegisterModuleFromImport(vm, imp))
    res, out = C.we_VMRunWasmFromBuffer(vm, b.build(), "f", [])
    assert C.we_ResultOK(res), res
    assert C.we_ValueGetI32(out[0]) == 48
    # the host memory instance saw the guest's store
    assert mem.load(64, 4, False) == 7


def test_compiler_family(tmp_path):
    from wasmedge_tpu.models import build_fib

    src = tmp_path / "fib.wasm"
    out = tmp_path / "fib.twasm"
    src.write_bytes(build_fib())
    comp = C.we_CompilerCreate()
    res = C.we_CompilerCompile(comp, str(src), str(out))
    assert C.we_ResultOK(res)
    data = out.read_bytes()
    assert b"tpu.aot" in data
    # buffer variant round-trips and still runs through the VM
    res, buf = C.we_CompilerCompileFromBuffer(comp, build_fib())
    assert C.we_ResultOK(res)
    vm = C.we_VMCreate()
    res, outv = C.we_VMRunWasmFromBuffer(vm, bytes(buf), "fib",
                                         [C.we_Value("i32", 12)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(outv[0]) == 144
    C.we_CompilerDelete(comp)


def test_version_and_listings():
    assert C.we_VersionGet().startswith("0.9.1")
    assert C.we_VersionGetMajor() == 0
    assert C.we_VersionGetMinor() == 9
    b = ModuleBuilder()
    b.add_memory(1, 1, export="m")
    b.add_global("i32", False, [("i32.const", 3)], export="g")
    b.add_function([], ["i32"], [], [("i32.const", 1)], export="f")
    vm = C.we_VMCreate()
    assert C.we_ResultOK(C.we_VMLoadWasmFromBuffer(vm, b.build()))
    assert C.we_ResultOK(C.we_VMValidate(vm))
    assert C.we_ResultOK(C.we_VMInstantiate(vm))
    inst = C.we_VMGetActiveModule(vm)
    assert C.we_ModuleInstanceListFunctionLength(inst) == 1
    assert C.we_ModuleInstanceListMemory(inst) == ["m"]
    assert C.we_ModuleInstanceListGlobal(inst) == ["g"]


# ---------------------------------------------------------------------------
# round-4 parity families: String, ref Values, Compiler knobs,
# Import/Export type contexts, Store find/list remainder, standalone
# host FunctionInstance, memory pointers, VM ASTModule/async-run forms
# (reference: wasmedge.h families; parity table in CAPI_PARITY.md)
# ---------------------------------------------------------------------------

def _fib_mod():
    conf = C.we_ConfigureCreate()
    loader = C.we_LoaderCreate(conf)
    res, mod = C.we_LoaderParseFromBuffer(loader, build_fib())
    assert C.we_ResultOK(res)
    return conf, mod


def test_string_family():
    s = C.we_StringCreateByCString("hello")
    assert C.we_StringIsEqual(s, C.we_StringWrap("hello"))
    assert not C.we_StringIsEqual(s, C.we_StringCreateByCString("world"))
    b = C.we_StringCreateByBuffer(b"hello world", 5)
    assert C.we_StringIsEqual(s, b)
    assert C.we_StringCopy(3, s) == "hel"
    C.we_StringDelete(s)


def test_result_constants():
    assert C.we_ResultOK(C.we_Result_Success)
    assert not C.we_ResultOK(C.we_Result_Terminate)
    assert not C.we_ResultOK(C.we_Result_Fail)
    assert C.we_ResultGetCode(C.we_Result_Terminate) == int(
        ErrCode.Terminated)


def test_ref_values():
    st = C.we_StoreCreate()
    null = C.we_ValueGenNullRef("funcref")
    assert C.we_ValueIsNullRef(null)
    fr = C.we_ValueGenFuncRef(7)
    assert not C.we_ValueIsNullRef(fr)
    assert C.we_ValueGetFuncRef(fr) == 7
    obj = {"k": 1}
    er = C.we_ValueGenExternRef(st, obj)
    assert C.we_ValueGetExternRef(st, er) is obj
    v = C.we_ValueGenV128((1 << 100) | 5)
    assert C.we_ValueGetV128(v) == (1 << 100) | 5


def test_compiler_configure_knobs():
    conf = C.we_ConfigureCreate()
    assert C.we_ConfigureCompilerGetOptimizationLevel(conf) == "O3"
    C.we_ConfigureCompilerSetOptimizationLevel(conf, "Os")
    assert C.we_ConfigureCompilerGetOptimizationLevel(conf) == "Os"
    C.we_ConfigureCompilerSetOutputFormat(conf, "Native")
    assert C.we_ConfigureCompilerGetOutputFormat(conf) == "Native"
    for setter, getter in (
            (C.we_ConfigureCompilerSetDumpIR,
             C.we_ConfigureCompilerIsDumpIR),
            (C.we_ConfigureCompilerSetGenericBinary,
             C.we_ConfigureCompilerIsGenericBinary),
            (C.we_ConfigureCompilerSetInterruptible,
             C.we_ConfigureCompilerIsInterruptible)):
        assert getter(conf) is False
        setter(conf, True)
        assert getter(conf) is True


def test_import_export_type_contexts():
    b = ModuleBuilder()
    b.import_func("env", "h", ["i32"], ["i32"])
    b.add_memory(1, 4)
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("call", 0),
    ], export="go")
    conf = C.we_ConfigureCreate()
    loader = C.we_LoaderCreate(conf)
    _res, mod = C.we_LoaderParseFromBuffer(loader, b.build())
    assert C.we_ASTModuleListImportsLength(mod) == 1
    its = C.we_ASTModuleListImportTypes(mod)
    it = its[0]
    assert C.we_ImportTypeGetModuleName(it) == "env"
    assert C.we_ImportTypeGetExternalName(it) == "h"
    assert C.we_ImportTypeGetExternalType(it) == "func"
    ft = C.we_ImportTypeGetFunctionType(it)
    assert len(ft.params) == 1 and len(ft.results) == 1
    assert C.we_ImportTypeGetTableType(it) is None
    assert C.we_ASTModuleListExportsLength(mod) >= 1
    ets = C.we_ASTModuleListExportTypes(mod)
    go = [e for e in ets if C.we_ExportTypeGetExternalName(e) == "go"][0]
    assert C.we_ExportTypeGetExternalType(go) == "func"
    ft2 = C.we_ExportTypeGetFunctionType(go)
    assert len(ft2.params) == 1
    # tuple-compat iteration (pre-round-4 shape)
    m, n, k = it
    assert (m, n, k) == ("env", "h", "func")
    C.we_ASTModuleDelete(mod)


def test_limit_is_equal():
    from wasmedge_tpu.loader.ast import Limit

    assert C.we_LimitIsEqual(Limit(1, 4), Limit(1, 4))
    assert not C.we_LimitIsEqual(Limit(1, 4), Limit(1, 5))
    assert not C.we_LimitIsEqual(Limit(1, None), Limit(1, 4))


def test_store_find_and_list_families():
    b = ModuleBuilder()
    b.add_memory(1, 2, export="mem")
    b.add_global("i32", True, [("i32.const", 7)], export="g")
    b.add_function([], ["i32"], [], [("i32.const", 3)], export="f")
    data = b.build()
    conf = C.we_ConfigureCreate()
    vm = C.we_VMCreate(conf)
    assert C.we_ResultOK(C.we_VMRegisterModuleFromBuffer(vm, "m", data))
    res, _ = C.we_VMRunWasmFromBuffer(vm, data, "f")
    assert C.we_ResultOK(res)
    store = C.we_VMGetStoreContext(vm)
    assert C.we_StoreGetActiveModule(store) is not None
    assert C.we_StoreFindFunction(store, "f") is not None
    assert C.we_StoreFindMemory(store, "mem") is not None
    assert C.we_StoreFindGlobal(store, "g") is not None
    assert C.we_StoreFindTable(store, "nope") is None
    assert C.we_StoreListFunction(store) == ["f"]
    assert C.we_StoreListFunctionLength(store) == 1
    assert C.we_StoreListMemory(store) == ["mem"]
    assert C.we_StoreListMemoryLength(store) == 1
    assert C.we_StoreListGlobal(store) == ["g"]
    assert C.we_StoreListGlobalLength(store) == 1
    assert C.we_StoreListTable(store) == []
    assert C.we_StoreListTableLength(store) == 0
    # registered variants
    assert C.we_StoreFindMemoryRegistered(store, "m", "mem") is not None
    assert C.we_StoreFindGlobalRegistered(store, "m", "g") is not None
    assert C.we_StoreFindTableRegistered(store, "m", "nope") is None
    assert C.we_StoreListFunctionRegistered(store, "m") == ["f"]
    assert C.we_StoreListFunctionRegisteredLength(store, "m") == 1
    assert C.we_StoreListMemoryRegistered(store, "m") == ["mem"]
    assert C.we_StoreListMemoryRegisteredLength(store, "m") == 1
    assert C.we_StoreListGlobalRegisteredLength(store, "m") == 1
    assert C.we_StoreListTableRegisteredLength(store, "m") == 0


def test_function_instance_create_and_executor_invoke_registered():
    ft = C.we_FunctionTypeCreate(["i32", "i32"], ["i32"])
    seen = []

    def host(data, mem, vals):
        seen.append(data)
        a = C.we_ValueGetI32(vals[0])
        bb = C.we_ValueGetI32(vals[1])
        return C.we_Result_Success, [C.we_ValueGenI32(a * bb)]

    fi = C.we_FunctionInstanceCreate(ft, host, data="tok")
    imp = C.we_ImportObjectCreate("env")
    imp.add_func("mul", fi)
    b = ModuleBuilder()
    b.import_func("env", "mul", ["i32", "i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 6), ("call", 0),
    ], export="six_times")
    conf = C.we_ConfigureCreate()
    vm = C.we_VMCreate(conf)
    assert C.we_ResultOK(C.we_VMRegisterModuleFromImport(vm, imp))
    res, out = C.we_VMRunWasmFromBuffer(
        vm, b.build(), "six_times", [C.we_ValueGenI32(7)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 42
    assert seen == ["tok"]
    # ExecutorInvokeRegistered against the named host module
    ex = C.we_ExecutorCreate(conf)
    store = C.we_VMGetStoreContext(vm)
    res, out = C.we_ExecutorInvokeRegistered(
        ex, store, "env", "mul",
        [C.we_ValueGenI32(3), C.we_ValueGenI32(5)])
    assert C.we_ResultOK(res)
    assert C.we_ValueGetI32(out[0]) == 15


def test_function_instance_create_binding():
    ft = C.we_FunctionTypeCreate(["i32"], ["i32"])

    def wrap(binding, data, mem, vals):
        assert binding == "BIND" and data == "DATA"
        return C.we_Result_Success, [
            C.we_ValueGenI32(C.we_ValueGetI32(vals[0]) + 1)]

    fi = C.we_FunctionInstanceCreateBinding(ft, wrap, binding="BIND",
                                            data="DATA")
    imp = C.we_ImportObjectCreate("env")
    assert C.we_ImportObjectGetModuleName(imp) == "env"
    imp.add_func("inc", fi)
    b = ModuleBuilder()
    b.import_func("env", "inc", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("call", 0)], export="f")
    vm = C.we_VMCreate(C.we_ConfigureCreate())
    C.we_VMRegisterModuleFromImport(vm, imp)
    res, out = C.we_VMRunWasmFromBuffer(vm, b.build(), "f",
                                        [C.we_ValueGenI32(41)])
    assert C.we_ResultOK(res) and C.we_ValueGetI32(out[0]) == 42


def test_memory_pointers():
    b = ModuleBuilder()
    b.add_memory(1, 2, export="mem")
    b.add_function([], ["i32"], [], [
        ("i32.const", 16), ("i32.load", 2, 0)], export="peek")
    vm = C.we_VMCreate(C.we_ConfigureCreate())
    res, _ = C.we_VMRunWasmFromBuffer(vm, b.build(), "peek")
    assert C.we_ResultOK(res)
    mem = C.we_StoreFindMemory(C.we_VMGetStoreContext(vm), "mem")
    view = C.we_MemoryInstanceGetPointer(mem, 16, 4)
    view[:4] = (1234567).to_bytes(4, "little")
    res, out = C.we_VMExecute(vm, "peek")
    assert C.we_ValueGetI32(out[0]) == 1234567
    const = C.we_MemoryInstanceGetPointerConst(mem, 16, 4)
    assert const == (1234567).to_bytes(4, "little")
    with pytest.raises(TrapError):
        C.we_MemoryInstanceGetPointer(mem, 65536 - 2, 4)


def test_vm_astmodule_and_file_forms(tmp_path):
    conf, mod = _fib_mod()
    vm = C.we_VMCreate(conf)
    res, out = C.we_VMRunWasmFromASTModule(vm, mod, "fib",
                                           [C.we_ValueGenI32(10)])
    assert C.we_ResultOK(res) and C.we_ValueGetI32(out[0]) == 55
    # load-from-AST staged form
    vm2 = C.we_VMCreate(C.we_ConfigureCreate())
    assert C.we_ResultOK(C.we_VMLoadWasmFromASTModule(vm2, mod))
    assert C.we_ResultOK(C.we_VMValidate(vm2))
    assert C.we_ResultOK(C.we_VMInstantiate(vm2))
    res, out = C.we_VMExecute(vm2, "fib", [C.we_ValueGenI32(9)])
    assert C.we_ValueGetI32(out[0]) == 34
    # register-from-AST / from-file
    vm3 = C.we_VMCreate(C.we_ConfigureCreate())
    assert C.we_ResultOK(C.we_VMRegisterModuleFromASTModule(vm3, "m", mod))
    assert C.we_VMGetFunctionTypeRegistered(vm3, "m", "fib") is not None
    assert C.we_VMGetFunctionTypeRegistered(vm3, "m", "nope") is None
    p = tmp_path / "fib.wasm"
    p.write_bytes(build_fib())
    vm4 = C.we_VMCreate(C.we_ConfigureCreate())
    assert C.we_ResultOK(C.we_VMRegisterModuleFromFile(vm4, "f", str(p)))
    res, out = C.we_VMExecuteRegistered(vm4, "f", "fib",
                                        [C.we_ValueGenI32(8)])
    assert C.we_ResultOK(res) and C.we_ValueGetI32(out[0]) == 21


def test_vm_async_run_family(tmp_path):
    conf, mod = _fib_mod()
    vm = C.we_VMCreate(conf)
    h = C.we_VMAsyncRunWasmFromBuffer(vm, build_fib(), "fib",
                                      [C.we_ValueGenI32(10)])
    C.we_AsyncWait(h)
    assert C.we_AsyncGetReturnsLength(h) == 1
    res, out = C.we_AsyncGet(h)
    assert C.we_ResultOK(res) and C.we_ValueGetI32(out[0]) == 55
    C.we_AsyncDelete(h)
    h = C.we_VMAsyncRunWasmFromASTModule(vm, mod, "fib",
                                         [C.we_ValueGenI32(9)])
    res, out = C.we_AsyncGet(h)
    assert C.we_ValueGetI32(out[0]) == 34
    p = tmp_path / "fib.wasm"
    p.write_bytes(build_fib())
    h = C.we_VMAsyncRunWasmFromFile(vm, str(p), "fib",
                                    [C.we_ValueGenI32(8)])
    res, out = C.we_AsyncGet(h)
    assert C.we_ValueGetI32(out[0]) == 21
    # registered async
    vm2 = C.we_VMCreate(C.we_ConfigureCreate())
    C.we_VMRegisterModuleFromBuffer(vm2, "m", build_fib())
    h = C.we_VMAsyncExecuteRegistered(vm2, "m", "fib",
                                      [C.we_ValueGenI32(7)])
    res, out = C.we_AsyncGet(h)
    assert C.we_ResultOK(res) and C.we_ValueGetI32(out[0]) == 13


def test_vm_get_import_module_context():
    conf = C.we_ConfigureCreate()
    C.we_ConfigureAddHostRegistration(conf, "wasi")
    vm = C.we_VMCreate(conf)
    assert C.we_VMGetImportModuleContext(vm, "wasi") is not None
    assert C.we_VMGetImportModuleContext(vm, "wasmedge_process") is None
    C.we_LoaderDelete(None)
    C.we_ValidatorDelete(None)
    C.we_ExecutorDelete(None)
    C.we_ImportObjectDelete(None)
    C.we_FunctionInstanceDelete(None)


def test_capi_parity_table_complete():
    """Every reference export has a we_* counterpart (CAPI_PARITY.md is
    generated from this same diff)."""
    import re

    hdr = open("/root/reference/include/api/wasmedge/wasmedge.h").read()
    ref = set("we_" + m[len("WasmEdge_"):] for m in re.findall(
        r"WasmEdge_[A-Za-z0-9_]+(?= *\()", hdr))
    ref = {r for r in ref if not r.endswith("_t")}
    have = set(dir(C))
    missing = sorted(r for r in ref if r not in have)
    assert not missing, missing
