"""Checkpoint/resume of in-flight batches (SURVEY.md §5.4): a run
interrupted at an arbitrary step boundary, saved, restored (optionally in
a fresh engine) and continued must bit-match an uninterrupted run."""

import numpy as np
import pytest

from wasmedge_tpu.batch import BatchEngine
from wasmedge_tpu.batch.checkpoint import load, save
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.models import build_fib, build_memory_workload
from tests.helpers import instantiate


def make(data, lanes=16):
    conf = Configure()
    conf.batch.steps_per_launch = 100
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def run_all(eng, func, args, max_steps=500_000):
    state = eng.initial_state(eng.inst.exports[func][1], args)
    state, total = eng.run_from_state(state, 0, max_steps)
    return state, total


def test_interrupt_resume_bitmatch(tmp_path):
    args = [(np.arange(16) % 13).astype(np.int64)]
    ref_eng = make(build_fib())
    ref_state, ref_total = run_all(ref_eng, "fib", args)

    eng = make(build_fib())
    state = eng.initial_state(eng.inst.exports["fib"][1], args)
    # run a slice, checkpoint mid-flight, drop everything
    state, total = eng.run_from_state(state, 0, 700)
    assert (np.asarray(state.trap) == 0).any()  # genuinely in-flight
    ckpt = tmp_path / "batch.ckpt"
    save(ckpt, eng, state, total)

    # fresh engine from the same module; restore and finish
    eng2 = make(build_fib())
    state2, total2 = load(ckpt, eng2)
    assert total2 == total
    state2, total2 = eng2.run_from_state(state2, total2, 500_000)

    for name in ("trap", "retired", "stack_lo", "stack_hi", "mem"):
        a = np.asarray(getattr(ref_state, name))
        b = np.asarray(getattr(state2, name))
        assert (a == b).all(), f"{name} diverged after resume"
    assert total2 == ref_total


def test_checkpoint_refuses_wrong_image(tmp_path):
    eng = make(build_fib())
    state = eng.initial_state(eng.inst.exports["fib"][1],
                              [np.full(16, 9, np.int64)])
    state, total = eng.run_from_state(state, 0, 300)
    ckpt = tmp_path / "c.ckpt"
    save(ckpt, eng, state, total)
    other = make(build_memory_workload())
    with pytest.raises(ValueError, match="different module image"):
        load(ckpt, other)
    small = make(build_fib(), lanes=8)
    with pytest.raises(ValueError, match="lanes"):
        load(ckpt, small)
    conf = Configure()
    conf.batch.steps_per_launch = 100
    conf.batch.value_stack_depth = 128
    ex, store, inst = instantiate(build_fib(), conf)
    other_geom = BatchEngine(inst, store=store, conf=conf, lanes=16)
    with pytest.raises(ValueError, match="geometry"):
        load(ckpt, other_geom)


def test_checkpoint_save_is_atomic(tmp_path, monkeypatch):
    """An interrupted save must never leave a truncated .npz at the
    target path nor clobber the previous good snapshot (the supervisor's
    resume path depends on this)."""
    import os

    eng = make(build_fib())
    state = eng.initial_state(eng.inst.exports["fib"][1],
                              [np.full(16, 9, np.int64)])
    state, total = eng.run_from_state(state, 0, 300)
    ckpt = tmp_path / "c.ckpt"
    save(ckpt, eng, state, total)
    good = ckpt.read_bytes()

    state2, total2 = eng.run_from_state(state, total, 600)
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("simulated crash mid-save")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="simulated crash"):
        save(ckpt, eng, state2, total2)
    monkeypatch.setattr(os, "replace", real_replace)
    # previous snapshot intact and loadable; no temp litter left behind
    # (the r24 integrity sidecar is durable output, not litter — and it
    # must still describe the SURVIVING snapshot, not the torn save)
    assert ckpt.read_bytes() == good
    assert sorted(p.name for p in tmp_path.iterdir()) == \
        ["c.ckpt", "c.ckpt.sha256"]
    import hashlib

    assert (tmp_path / "c.ckpt.sha256").read_bytes().decode() == \
        hashlib.sha256(good).hexdigest()
    restored, rtotal = load(ckpt, make(build_fib()))
    assert rtotal == total


def test_checkpoint_refuses_corrupt_control_planes(tmp_path):
    # ADVICE r2: the image hash proved provenance but the restored control
    # planes were trusted verbatim — a crafted npz with wild pc/fp/sp
    # wrap-indexed other frames' rows instead of being refused.
    import io

    eng = make(build_fib())
    state = eng.initial_state(eng.inst.exports["fib"][1],
                              [np.full(16, 9, np.int64)])
    state, total = eng.run_from_state(state, 0, 300)
    ckpt = tmp_path / "c.ckpt"
    save(ckpt, eng, state, total)

    def tamper(plane, vals):
        with np.load(ckpt, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files if k != "meta"}
            meta = str(z["meta"])
        bad = arrays[f"state_{plane}"].copy()
        bad[..., 0] = vals
        arrays[f"state_{plane}"] = bad
        buf = io.BytesIO()
        np.savez_compressed(buf, meta=meta, **arrays)
        p = tmp_path / f"bad_{plane}.ckpt"
        p.write_bytes(buf.getvalue())
        return p

    for plane, vals in (("pc", -1), ("pc", 10 ** 6), ("fp", -3),
                        ("sp", 10 ** 6), ("call_depth", -1),
                        ("mem_pages", 10 ** 6), ("trap", -77)):
        fresh = make(build_fib())
        with pytest.raises(ValueError, match="refused"):
            load(tamper(plane, vals), fresh)
