"""CLI + PO parser + AOT artifact tests.

Mirrors the reference's test/po (flag parser incl. subcommands) and the
aot cache/universal-output coverage in test/aot/AOTcoreTest.cpp.
"""

import io
import os

import pytest

from wasmedge_tpu import aot
from wasmedge_tpu.cli import compile_command, main, run_command
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.loader.loader import Loader
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.utils.po import ArgumentParser, ListOpt, Option, Toggle
from wasmedge_tpu.validator.validator import Validator


# ---------------------------------------------------------------------------
# PO parser (reference: test/po/subcommand.cpp pattern)
# ---------------------------------------------------------------------------
def test_po_options_and_positional():
    p = ArgumentParser("t")
    p.add_option("name", Option("a name", default="x"))
    p.add_option("count", Option("a count", typ=int))
    p.add_option("verbose", Toggle("verbosity"))
    p.add_option("dir", ListOpt("dirs"))
    p.add_positional("file")
    assert p.parse(["--name=alice", "--count", "3", "--verbose",
                    "--dir", "a", "--dir", "b", "f.wasm", "x", "y"])
    assert p._opts["name"].value == "alice"
    assert p._opts["count"].value == 3
    assert p._opts["verbose"].value is True
    assert p._opts["dir"].value == ["a", "b"]
    assert p.positional_values == ["f.wasm"]
    assert p.rest == ["x", "y"]


def test_po_errors_and_help():
    p = ArgumentParser("t")
    p.add_option("x", Option("x"))
    with pytest.raises(ValueError):
        p.parse(["--nope"])
    with pytest.raises(ValueError):
        p.parse(["--x"])  # missing value
    buf = io.StringIO()
    assert p.parse(["--help"], out=buf) is False
    assert "usage:" in buf.getvalue()


def test_po_subcommands():
    p = ArgumentParser("tool")
    sub = p.sub_command("go", "go somewhere")
    sub.add_option("fast", Toggle("speed"))
    sub.add_positional("place")
    assert p.parse(["go", "--fast", "home"])
    assert p.selected_subcommand == "go"
    assert sub._opts["fast"].value and sub.positional_values == ["home"]


# ---------------------------------------------------------------------------
# AOT artifact: universal twasm roundtrip + fallback + cache
# ---------------------------------------------------------------------------
def test_universal_artifact_roundtrip():
    wasm = build_fib()
    art = aot.compile_module(wasm)
    assert art[:len(wasm)] == wasm  # original bytes preserved
    conf = Configure()
    mod = Loader(conf).parse_module(art)
    v = Validator(conf)
    v.validate(mod)
    assert mod.validated and mod.lowered is not None
    # runs identically from the precompiled image
    from tests.helpers import run_wasm

    assert run_wasm(art, "fib", [10]) == [55]


def test_artifact_tamper_falls_back():
    wasm = build_fib()
    art = bytearray(aot.compile_module(wasm))
    # flip a byte inside the original module region -> hash mismatch
    art[30] ^= 0x01
    conf = Configure()
    try:
        mod = Loader(conf).parse_module(bytes(art))
    except Exception:
        return  # corrupt enough to fail load: acceptable
    # if it still loads, the AOT section must NOT be trusted
    payload = aot.extract_precompiled(
        mod.source_bytes, [(c.name, c.data, c.start) for c in mod.customs])
    assert payload is None


def _artifact_with_image(wasm: bytes, img) -> bytes:
    """Build a universal twasm whose tpu.aot section carries `img` with a
    *correct* content hash — the attack verify_image() must stop."""
    import hashlib
    import struct

    payload = aot.serialize_image(img)
    digest = hashlib.sha256(wasm).digest()
    body = struct.pack("<I", aot.AOT_VERSION) + digest + payload
    name = aot.SECTION_NAME.encode()
    content = aot._uleb(len(name)) + name + body
    return wasm + b"\x00" + aot._uleb(len(content)) + content


def _validated_fib():
    conf = Configure()
    wasm = build_fib()
    mod = Validator(conf).validate(Loader(conf).parse_module(wasm))
    return wasm, mod


def test_verify_image_accepts_honest_image():
    wasm, mod = _validated_fib()
    img = aot.deserialize_image(aot.serialize_image(mod.lowered))
    aot.verify_image(img, mod)  # must not raise


@pytest.mark.parametrize("tamper", ["local", "branch", "call", "underflow",
                                    "neg_keep", "trunc_imm", "float_meta"])
def test_verify_image_rejects_tampered(tamper):
    from wasmedge_tpu.common.opcodes import NAME_TO_ID
    from wasmedge_tpu.validator.image import LOP_BR, LOP_BRNZ, LOP_BRZ

    wasm, mod = _validated_fib()
    img = aot.deserialize_image(aot.serialize_image(mod.lowered))
    if tamper == "local":
        pc = img.op.index(NAME_TO_ID["local.get"])
        img.a[pc] = 999  # cross-frame read
    elif tamper == "branch":
        pc = next(i for i, o in enumerate(img.op) if o in (LOP_BRZ, LOP_BRNZ))
        img.a[pc] = img.code_len + 17  # jump out of the code image
    elif tamper == "call":
        pc = img.op.index(NAME_TO_ID["call"])
        img.a[pc] = 55  # nonexistent function
    elif tamper == "underflow":
        pc = img.op.index(NAME_TO_ID["local.get"])
        img.op[pc] = NAME_TO_ID["drop"]  # stack underflow at entry
    elif tamper == "neg_keep":
        # negative keep makes every height inequality vacuously pass while
        # the engine's slice semantics leave the stack taller than verified
        pc = next(i for i, o in enumerate(img.op) if o == LOP_BR)
        img.b[pc] = -2
        img.c[pc] = 4
    elif tamper == "trunc_imm":
        img.imm = img.imm[:-3]  # plane shorter than the code image
    elif tamper == "float_meta":
        img.funcs[0].nparams = float(img.funcs[0].nparams)
    img.finalize()
    with pytest.raises(ValueError):
        aot.verify_image(img, mod)


def test_malicious_embedded_image_falls_back_to_validation():
    from wasmedge_tpu.common.opcodes import NAME_TO_ID

    wasm, mod0 = _validated_fib()
    bad = aot.deserialize_image(aot.serialize_image(mod0.lowered))
    pc = bad.op.index(NAME_TO_ID["local.get"])
    bad.a[pc] = 999
    bad.finalize()
    art = _artifact_with_image(wasm, bad)

    conf = Configure()
    mod = Validator(conf).validate(Loader(conf).parse_module(art))
    # full body validation must have produced the honest lowering,
    # not the crafted image
    assert mod.validated
    assert mod.lowered.a[pc] != 999
    from tests.helpers import run_wasm

    assert run_wasm(art, "fib", [10]) == [55]


def test_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    wasm = build_fib()
    a1 = aot.compile_cached(wasm)
    path = aot.cache_path(wasm)
    assert os.path.exists(path)
    a2 = aot.compile_cached(wasm)  # served from cache
    assert a1 == a2


# ---------------------------------------------------------------------------
# CLI runner
# ---------------------------------------------------------------------------
def _write_fib(tmp_path):
    p = tmp_path / "fib.wasm"
    p.write_bytes(build_fib())
    return str(p)


def test_cli_reactor(tmp_path, capsys):
    path = _write_fib(tmp_path)
    rc = run_command(["--reactor", path, "fib", "10"])
    assert rc == 0
    assert "[55]" in capsys.readouterr().out


def test_cli_command_mode_exit_code(tmp_path):
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "proc_exit", ["i32"], [])
    b.add_function([], [], [], [("i32.const", 3), ("call", 0)],
                   export="_start")
    p = tmp_path / "exit3.wasm"
    p.write_bytes(b.build())
    assert run_command([str(p)]) == 3


def test_cli_gas_limit(tmp_path, capsys):
    path = _write_fib(tmp_path)
    rc = run_command(["--reactor", "--gas-limit", "100", path, "fib", "25"])
    assert rc == 1  # gas exhausted -> trap
    err = capsys.readouterr().err
    assert "cost limit exceeded" in err


def test_cli_compile_and_run(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    src = _write_fib(tmp_path)
    out = str(tmp_path / "fib.twasm")
    assert compile_command([src, out]) == 0
    assert os.path.exists(out)
    rc = run_command(["--reactor", out, "fib", "11"])
    assert rc == 0
    assert "[89]" in capsys.readouterr().out


def test_cli_batch(tmp_path, capsys):
    path = _write_fib(tmp_path)
    rc = run_command(["--reactor", "--batch", "8", path, "fib", "10"])
    assert rc == 0
    assert "8/8 lanes completed" in capsys.readouterr().out


def test_cli_main_dispatch(tmp_path, capsys):
    assert main(["version"]) == 0
    assert "wasmedge-tpu" in capsys.readouterr().out
    assert main([]) == 0  # help
