"""CLI-level WASI end-to-end: real guest programs through the runner.

The reference validates its CLI against the wasi-test corpus of real
guest binaries (/root/reference/utils/wasi-test/run-wasi-test.sh:1-50).
This is our equivalent: each test authors a complete WASI *program*
(command-module `_start`, WASI imports, argv/env/preopened fs/clock/
random in one guest), runs it as a SUBPROCESS of
`python -m wasmedge_tpu.cli run ...`, and checks stdout/exit codes —
the full user-visible path (text front-end -> loader -> validator ->
engine -> WASI host layer -> OS), not library shortcuts.
"""

import os
import subprocess
import sys

import pytest

PRE = ('(import "wasi_snapshot_preview1" "{name}" '
       "(func ${alias} (param {params}) (result i32)))")


def wasi_import(name, params, alias=None):
    return PRE.format(name=name, alias=alias or name, params=params)


def run_cli(tmp_path, wat_src, *flags, guest_args=(), name="prog.wat"):
    p = tmp_path / name
    p.write_text(wat_src)
    cmd = [sys.executable, "-m", "wasmedge_tpu.cli", "run",
           *flags, str(p), *guest_args]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=300, cwd="/root/repo")


def test_hello_stdout(tmp_path):
    src = f"""
(module
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (memory 1)
  (data (i32.const 0) "hello, wasi\\n")
  (func (export "_start")
    (i32.store (i32.const 16) (i32.const 0))
    (i32.store (i32.const 20) (i32.const 12))
    (drop (call $fd_write (i32.const 1) (i32.const 16) (i32.const 1)
                          (i32.const 24)))))
"""
    r = run_cli(tmp_path, src)
    assert r.returncode == 0, r.stderr
    assert r.stdout == "hello, wasi\n"


def test_exit_code(tmp_path):
    src = """
(module
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (func (export "_start") (call $exit (i32.const 42))))
"""
    r = run_cli(tmp_path, src)
    assert r.returncode == 42


def test_argv_echo(tmp_path):
    """args_sizes_get + args_get; prints the raw argv buffer (NUL-joined
    args) and exits with argc."""
    src = f"""
(module
  {wasi_import("args_sizes_get", "i32 i32")}
  {wasi_import("args_get", "i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (func (export "_start")
    (drop (call $args_sizes_get (i32.const 0) (i32.const 4)))
    (drop (call $args_get (i32.const 16) (i32.const 256)))
    (i32.store (i32.const 8) (i32.const 256))
    (i32.store (i32.const 12) (i32.load (i32.const 4)))
    (drop (call $fd_write (i32.const 1) (i32.const 8) (i32.const 1)
                          (i32.const 520)))
    (call $exit (i32.load (i32.const 0)))))
"""
    r = run_cli(tmp_path, src, guest_args=("one", "two2"))
    # argv[0] is the program name; exit code = argc
    assert r.returncode == 3
    parts = r.stdout.split("\x00")
    assert parts[1] == "one" and parts[2] == "two2"


def test_env_passthrough(tmp_path):
    src = f"""
(module
  {wasi_import("environ_sizes_get", "i32 i32")}
  {wasi_import("environ_get", "i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (memory 1)
  (func (export "_start")
    (drop (call $environ_sizes_get (i32.const 0) (i32.const 4)))
    (drop (call $environ_get (i32.const 16) (i32.const 256)))
    (i32.store (i32.const 8) (i32.const 256))
    (i32.store (i32.const 12) (i32.load (i32.const 4)))
    (drop (call $fd_write (i32.const 1) (i32.const 8) (i32.const 1)
                          (i32.const 520)))))
"""
    r = run_cli(tmp_path, src, "--env", "GREETING=bonjour",
                "--env", "WHO=wasm")
    assert r.returncode == 0, r.stderr
    env = dict(kv.split("=", 1) for kv in r.stdout.split("\x00") if "=" in kv)
    assert env["GREETING"] == "bonjour"
    assert env["WHO"] == "wasm"


def test_file_create_write(tmp_path):
    """path_open(create) + fd_write + fd_close in a preopened dir; the
    host checks the resulting file bytes."""
    host_dir = tmp_path / "sandbox"
    host_dir.mkdir()
    src = f"""
(module
  {wasi_import("path_open", "i32 i32 i32 i32 i32 i64 i64 i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  {wasi_import("fd_close", "i32")}
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) "out.txt")
  (data (i32.const 32) "written from wasm")
  (func (export "_start") (local i32)
    ;; open fd 3 (first preopen) / "out.txt" with create|write rights
    (if (i32.ne (call $path_open (i32.const 3) (i32.const 0)
                     (i32.const 0) (i32.const 7)
                     (i32.const 9)          ;; oflags: CREAT|TRUNC
                     (i64.const 0x64) (i64.const 0)
                     (i32.const 0) (i32.const 100))
                (i32.const 0))
      (then (call $exit (i32.const 7))))
    (local.set 0 (i32.load (i32.const 100)))
    (i32.store (i32.const 64) (i32.const 32))
    (i32.store (i32.const 68) (i32.const 17))
    (if (i32.ne (call $fd_write (local.get 0) (i32.const 64)
                     (i32.const 1) (i32.const 72)) (i32.const 0))
      (then (call $exit (i32.const 8))))
    (drop (call $fd_close (local.get 0)))))
"""
    r = run_cli(tmp_path, src, "--dir", f"/:{host_dir}")
    assert r.returncode == 0, (r.stderr, r.stdout)
    assert (host_dir / "out.txt").read_bytes() == b"written from wasm"


def test_file_read_roundtrip(tmp_path):
    host_dir = tmp_path / "sandbox"
    host_dir.mkdir()
    (host_dir / "in.txt").write_bytes(b"content-from-host\n")
    src = f"""
(module
  {wasi_import("path_open", "i32 i32 i32 i32 i32 i64 i64 i32 i32")}
  {wasi_import("fd_read", "i32 i32 i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) "in.txt")
  (func (export "_start") (local i32)
    (if (i32.ne (call $path_open (i32.const 3) (i32.const 0)
                     (i32.const 0) (i32.const 6)
                     (i32.const 0)
                     (i64.const 0x2) (i64.const 0)
                     (i32.const 0) (i32.const 100))
                (i32.const 0))
      (then (call $exit (i32.const 7))))
    (local.set 0 (i32.load (i32.const 100)))
    (i32.store (i32.const 64) (i32.const 512))
    (i32.store (i32.const 68) (i32.const 128))
    (drop (call $fd_read (local.get 0) (i32.const 64) (i32.const 1)
                         (i32.const 72)))
    ;; echo what was read to stdout
    (i32.store (i32.const 64) (i32.const 512))
    (i32.store (i32.const 68) (i32.load (i32.const 72)))
    (drop (call $fd_write (i32.const 1) (i32.const 64) (i32.const 1)
                          (i32.const 76)))))
"""
    r = run_cli(tmp_path, src, "--dir", f"/:{host_dir}")
    assert r.returncode == 0, r.stderr
    assert r.stdout == "content-from-host\n"


def test_seek_and_reread(tmp_path):
    host_dir = tmp_path / "sandbox"
    host_dir.mkdir()
    (host_dir / "seek.txt").write_bytes(b"0123456789")
    src = f"""
(module
  {wasi_import("path_open", "i32 i32 i32 i32 i32 i64 i64 i32 i32")}
  {wasi_import("fd_read", "i32 i32 i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (import "wasi_snapshot_preview1" "fd_seek"
    (func $fd_seek (param i32 i64 i32 i32) (result i32)))
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) "seek.txt")
  (func (export "_start") (local i32)
    (if (i32.ne (call $path_open (i32.const 3) (i32.const 0)
                     (i32.const 0) (i32.const 8)
                     (i32.const 0)
                     (i64.const 0x26) (i64.const 0)
                     (i32.const 0) (i32.const 100))
                (i32.const 0))
      (then (call $exit (i32.const 7))))
    (local.set 0 (i32.load (i32.const 100)))
    ;; seek to offset 6 from start, read 4 bytes -> "6789"
    (drop (call $fd_seek (local.get 0) (i64.const 6) (i32.const 0)
                         (i32.const 104)))
    (i32.store (i32.const 64) (i32.const 512))
    (i32.store (i32.const 68) (i32.const 4))
    (drop (call $fd_read (local.get 0) (i32.const 64) (i32.const 1)
                         (i32.const 72)))
    (i32.store (i32.const 64) (i32.const 512))
    (i32.store (i32.const 68) (i32.load (i32.const 72)))
    (drop (call $fd_write (i32.const 1) (i32.const 64) (i32.const 1)
                          (i32.const 76)))))
"""
    r = run_cli(tmp_path, src, "--dir", f"/:{host_dir}")
    assert r.returncode == 0, r.stderr
    assert r.stdout == "6789"


def test_clock_and_random(tmp_path):
    """clock_time_get yields a positive time; random_get fills bytes;
    prints ok when both behave."""
    src = f"""
(module
  (import "wasi_snapshot_preview1" "clock_time_get"
    (func $clk (param i32 i64 i32) (result i32)))
  {wasi_import("random_get", "i32 i32")}
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) "ok\\n")
  (func (export "_start")
    (if (i32.ne (call $clk (i32.const 0) (i64.const 0) (i32.const 16))
                (i32.const 0))
      (then (call $exit (i32.const 7))))
    (if (i64.le_s (i64.load (i32.const 16)) (i64.const 0))
      (then (call $exit (i32.const 8))))
    ;; 32 random bytes; all-zero would be astronomically unlikely
    (if (i32.ne (call $random_get (i32.const 32) (i32.const 32))
                (i32.const 0))
      (then (call $exit (i32.const 9))))
    (if (i64.eqz (i64.or (i64.load (i32.const 32))
                         (i64.or (i64.load (i32.const 40))
                                 (i64.or (i64.load (i32.const 48))
                                         (i64.load (i32.const 56))))))
      (then (call $exit (i32.const 10))))
    (i32.store (i32.const 64) (i32.const 0))
    (i32.store (i32.const 68) (i32.const 3))
    (drop (call $fd_write (i32.const 1) (i32.const 64) (i32.const 1)
                          (i32.const 72)))))
"""
    r = run_cli(tmp_path, src)
    assert r.returncode == 0, r.stderr
    assert r.stdout == "ok\n"


def test_stderr_stream(tmp_path):
    src = f"""
(module
  {wasi_import("fd_write", "i32 i32 i32 i32")}
  (memory 1)
  (data (i32.const 0) "to-stdout;")
  (data (i32.const 16) "to-stderr;")
  (func (export "_start")
    (i32.store (i32.const 32) (i32.const 0))
    (i32.store (i32.const 36) (i32.const 10))
    (drop (call $fd_write (i32.const 1) (i32.const 32) (i32.const 1)
                          (i32.const 48)))
    (i32.store (i32.const 32) (i32.const 16))
    (i32.store (i32.const 36) (i32.const 10))
    (drop (call $fd_write (i32.const 2) (i32.const 32) (i32.const 1)
                          (i32.const 48)))))
"""
    r = run_cli(tmp_path, src)
    assert r.returncode == 0
    assert r.stdout == "to-stdout;"
    assert "to-stderr;" in r.stderr


def test_readdir_counts_entries(tmp_path):
    host_dir = tmp_path / "sandbox"
    host_dir.mkdir()
    for name in ("a.txt", "b.txt", "c.txt"):
        (host_dir / name).write_text(name)
    src = f"""
(module
  {wasi_import("path_open", "i32 i32 i32 i32 i32 i64 i64 i32 i32")}
  (import "wasi_snapshot_preview1" "fd_readdir"
    (func $rd (param i32 i32 i32 i64 i32) (result i32)))
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) ".")
  (func (export "_start") (local i32 i32 i32 i32)
    ;; open the preopen root itself ("."), then readdir it
    (if (i32.ne (call $path_open (i32.const 3) (i32.const 1)
                     (i32.const 0) (i32.const 1)
                     (i32.const 0)
                     (i64.const 0x4000) (i64.const 0)
                     (i32.const 0) (i32.const 100))
                (i32.const 0))
      (then (call $exit (i32.const 7))))
    (local.set 0 (i32.load (i32.const 100)))
    (drop (call $rd (local.get 0) (i32.const 1024) (i32.const 4096)
                    (i64.const 0) (i32.const 104)))
    ;; walk dirents counting entries: dirent = 24 bytes + namelen
    (local.set 1 (i32.const 1024))
    (local.set 2 (i32.const 0))
    (block
      (loop
        (br_if 1 (i32.ge_u (local.get 1)
                           (i32.add (i32.const 1024)
                                    (i32.load (i32.const 104)))))
        (local.set 2 (i32.add (local.get 2) (i32.const 1)))
        (local.set 1 (i32.add (local.get 1)
                     (i32.add (i32.const 24)
                              (i32.load (i32.add (local.get 1)
                                                 (i32.const 16))))))
        (br 0)))
    ;; exit code = number of entries seen (. .. a b c may vary by impl;
    ;; the host asserts >= 3)
    (call $exit (local.get 2))))
"""
    r = run_cli(tmp_path, src, "--dir", f"/:{host_dir}")
    assert r.returncode >= 3, (r.returncode, r.stderr)


def test_gas_limit_kills_infinite_loop(tmp_path):
    src = """
(module
  (memory 1)
  (func (export "_start")
    (block (loop (br 0)))))
"""
    r = run_cli(tmp_path, src, "--enable-gas-measuring",
                "--gas-limit", "100000")
    assert r.returncode != 0
    assert "cost" in (r.stderr + r.stdout).lower() or r.returncode != 0


def test_reactor_mode_typed_args(tmp_path):
    src = """
(module
  (func (export "mul") (param i32 i32) (result i32)
    (i32.mul (local.get 0) (local.get 1))))
"""
    p = tmp_path / "re.wat"
    p.write_text(src)
    r = subprocess.run([sys.executable, "-m", "wasmedge_tpu.cli", "run",
                        "--reactor", str(p), "mul", "6", "7"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "42" in r.stdout


def test_sandbox_escape_refused(tmp_path):
    """A guest path reaching outside the preopen must be refused (the
    VINode capability model, reference vinode.cpp)."""
    host_dir = tmp_path / "sandbox"
    host_dir.mkdir()
    (tmp_path / "secret.txt").write_text("outside")
    src = f"""
(module
  {wasi_import("path_open", "i32 i32 i32 i32 i32 i64 i64 i32 i32")}
  (import "wasi_snapshot_preview1" "proc_exit" (func $exit (param i32)))
  (memory 1)
  (data (i32.const 0) "../secret.txt")
  (func (export "_start")
    ;; errno must be nonzero (NOTCAPABLE/ACCES), exit with it
    (call $exit (call $path_open (i32.const 3) (i32.const 0)
                     (i32.const 0) (i32.const 13)
                     (i32.const 0)
                     (i64.const 0x2) (i64.const 0)
                     (i32.const 0) (i32.const 100)))))
"""
    r = run_cli(tmp_path, src, "--dir", f"/:{host_dir}")
    assert r.returncode != 0, "sandbox escape must fail"
