"""Divergence-aware lane compaction (batch/compact.py) — ISSUE 14.

Pins the PC-sorted lane regrouping pass and its hard guarantees:

  - compaction on/off bit-identical (results, traps, retired) on the
    single-device SIMT engine, the shard-drive mesh (per-shard
    permutations only), the multi-tenant concatenated image, and both
    fused and unfused builds;
  - the serving layer's lane->request bindings, recycling, hv
    swapping, checkpoints, and the exactly-once stdout cursor all
    follow their lane through a fired permutation;
  - the anti-thrash quantum and the cost model are deterministic pure
    functions of the mirrors;
  - every built permutation is a bijection (shard-blocked included);
  - `Configure.batch.compact` defaults OFF (the seed path by
    construction) and checkpoints refuse a permuted snapshot when
    compaction is unavailable.

Fast by construction (tiny lane counts, short chunks): tier-1.
"""

import os
import tempfile

import numpy as np
import pytest

from wasmedge_tpu.batch.compact import (
    LaneCompactor,
    build_permutation,
    compact_decision,
    estimate_breaks,
    live_mask,
)
from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.image import TRAP_DONE, TRAP_HOSTCALL
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.models import build_fib, build_loop_sum
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.compact

LANES = 16


def fib_ref(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def make_conf(compact=True, fuse=True, forced=True, **batch):
    conf = Configure()
    conf.batch.compact = compact
    conf.batch.fuse_superinstructions = fuse
    conf.batch.steps_per_launch = 48
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    if forced:
        # tiny test mixes would not clear the production cost model:
        # pin the policy fully open so fires are deterministic
        conf.batch.compact_min_interval = 1
        conf.batch.compact_trigger = 0.0
        conf.batch.compact_cost_factor = 0.0
        conf.batch.compact_width_floor = 4
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    return conf


def instantiate(data, conf):
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return inst, store


def make_engine(conf, lanes=LANES, data=None):
    inst, store = instantiate(data or build_fib(), conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def div_args(lanes=LANES, lo=4, hi=11, seed=3):
    ns = (lo + np.arange(lanes) % (hi - lo + 1)).astype(np.int64)
    np.random.default_rng(seed).shuffle(ns)
    return ns


def assert_results_identical(a, b):
    for ra, rb in zip(a.results, b.results):
        assert (np.asarray(ra) == np.asarray(rb)).all()
    assert (np.asarray(a.trap) == np.asarray(b.trap)).all()
    assert (np.asarray(a.retired) == np.asarray(b.retired)).all()


# ---------------------------------------------------------------------------
# policy: bijection, quantum, cost model — pure-function determinism
# ---------------------------------------------------------------------------
def test_permutation_is_a_bijection():
    rng = np.random.default_rng(0)
    for n in (1, 7, 32, 257):
        pc = rng.integers(0, 50, n).astype(np.int64)
        trap = rng.choice([0, 0, 0, TRAP_DONE, 3, TRAP_HOSTCALL],
                          n).astype(np.int64)
        perm = build_permutation(pc, trap)
        assert sorted(perm.tolist()) == list(range(n))


def test_permutation_shard_blocked_is_a_bijection_within_shards():
    rng = np.random.default_rng(1)
    n, shards = 32, [slice(0, 8), slice(8, 16), slice(16, 32)]
    pc = rng.integers(0, 9, n).astype(np.int64)
    trap = rng.choice([0, 0, TRAP_DONE], n).astype(np.int64)
    perm = build_permutation(pc, trap, shard_slices=shards)
    assert sorted(perm.tolist()) == list(range(n))
    for sl in shards:   # no cross-device moves
        assert all(sl.start <= s < sl.stop for s in perm[sl])


def test_permutation_sorts_live_prefix_by_pc_stable():
    pc = np.asarray([9, 2, 9, 2, 5], np.int64)
    trap = np.asarray([0, TRAP_DONE, 0, 0, 0], np.int64)
    perm = build_permutation(pc, trap)
    # live lanes grouped by pc ascending (no divergence scores here),
    # original position breaking ties; the dead lane sinks to the tail
    assert perm.tolist() == [3, 4, 0, 2, 1]


def test_divergence_bias_groups_high_scores_first():
    pc = np.asarray([1, 7, 1, 7], np.int64)
    trap = np.zeros(4, np.int64)
    dscore = np.zeros(8, np.int64)
    dscore[7] = 5   # pc 7 is the high-divergence neighbourhood
    perm = build_permutation(pc, trap, dscore=dscore)
    assert perm.tolist() == [1, 3, 0, 2]


def test_function_key_groups_lanes_per_function():
    """r20 satellite: the engine-global function ordinal is the
    PRIMARY live key — lanes in the same function become contiguous
    even when a finer key (divergence, pc) would interleave them."""
    from wasmedge_tpu.batch.compact import function_key

    # two "functions": entry pcs 0 and 10; lanes alternate between them
    pc = np.asarray([12, 1, 11, 3, 10, 2], np.int64)
    trap = np.zeros(6, np.int64)
    fnkey = np.asarray([0] * 10 + [1] * 10, np.int64)
    # divergence says pc 12 is hottest — WITHOUT fnkey it would lead
    dscore = np.zeros(20, np.int64)
    dscore[12] = 9
    perm = build_permutation(pc, trap, dscore=dscore, fnkey=fnkey)
    # fn 0 lanes (pcs 1,2,3) first in pc order, then fn 1 lanes with
    # the divergence bias ordering inside the function group
    assert perm.tolist() == [1, 5, 3, 0, 4, 2]
    # same geometry WITHOUT the function key: divergence leads
    assert build_permutation(pc, trap, dscore=dscore).tolist() \
        == [0, 1, 5, 3, 4, 2]

    # function_key derives the ordinal plane from the image f_entry
    class _Img:
        f_entry = np.asarray([0, 10, -1], np.int64)   # one import
        code_len = 20

    fk = function_key(_Img())
    assert fk is not None
    assert fk.tolist() == [0] * 10 + [1] * 10

    class _Broken:
        f_entry = None
        code_len = 20

    assert function_key(_Broken()) is None   # never raises


def test_anti_thrash_quantum():
    pc = np.asarray([3, 1, 3, 1], np.int64)
    trap = np.zeros(4, np.int64)
    conf = make_conf(forced=False)
    conf.batch.compact_min_interval = 4
    conf.batch.compact_trigger = 0.0
    conf.batch.compact_cost_factor = 0.0
    early = compact_decision(pc, trap, 4, 48, 3, conf.batch, False)
    assert not early.fire and early.reason == "interval"
    due = compact_decision(pc, trap, 4, 48, 4, conf.batch, False)
    assert due.fire


def test_cost_model_deterministic_and_gating():
    pc = np.asarray([3, 1, 3, 1], np.int64)
    trap = np.zeros(4, np.int64)
    knobs = make_conf(forced=False).batch
    # breaks=3, ideal=1 -> win=2; cost model: win*spl >= factor*lanes
    a = compact_decision(pc, trap, 4, 48, 99, knobs, False)
    b = compact_decision(pc, trap, 4, 48, 99, knobs, False)
    assert a == b          # same mirrors -> same decision, always
    assert a.fire          # 2*48 >= 4.0*4
    knobs.compact_cost_factor = 1000.0
    c = compact_decision(pc, trap, 4, 48, 99, knobs, False)
    assert not c.fire and c.reason == "cost"
    # an idle population never fires
    idle = compact_decision(pc, np.full(4, TRAP_DONE, np.int64),
                            4, 48, 99, knobs, False)
    assert not idle.fire and idle.reason == "idle"


def test_estimate_breaks_and_live_mask():
    pc = np.asarray([5, 5, 9, 5], np.int64)
    trap = np.asarray([0, 0, 0, TRAP_HOSTCALL], np.int64)
    assert live_mask(trap).all()   # hostcall-parked lanes stay live
    breaks, ideal, unique, largest = estimate_breaks(pc, live_mask(trap))
    assert (breaks, ideal, unique) == (2, 1, 2)
    assert largest == pytest.approx(0.75)


def test_estimate_breaks_shard_blocked_ideal():
    # each shard already PC-sorted: a shard-blocked permutation can
    # buy nothing, so win must be 0 (a global ideal would leave
    # win > 0 forever and the mesh policy would fire no-ops every
    # quantum)
    pc = np.asarray([3, 3, 7, 7, 3, 3, 7, 7], np.int64)
    live = np.ones(8, bool)
    shards = [slice(0, 4), slice(4, 8)]
    breaks, ideal, unique, largest = estimate_breaks(pc, live, shards)
    assert breaks == ideal == 2     # per-shard minimum already met
    assert unique == 2 and largest == pytest.approx(0.5)
    # unsorted within a shard still shows a win
    pc2 = np.asarray([7, 3, 7, 3, 3, 3, 7, 7], np.int64)
    b2, i2, _, _ = estimate_breaks(pc2, live, shards)
    assert b2 - i2 > 0


def test_compact_defaults_off():
    conf = Configure()
    assert conf.batch.compact is False
    eng = make_engine(conf)
    eng.run("fib", [div_args()], max_steps=200_000)
    assert eng.compactor is None   # seed path by construction


# ---------------------------------------------------------------------------
# cohort parity: single device / fused & unfused / multitenant / mesh
# ---------------------------------------------------------------------------
def _ab(conf_on, conf_off, lanes=LANES, ns=None):
    ns = div_args(lanes) if ns is None else ns
    on = make_engine(conf_on, lanes).run("fib", [ns],
                                         max_steps=500_000)
    off_eng = make_engine(conf_off, lanes)
    off = off_eng.run("fib", [ns], max_steps=500_000)
    return on, off, ns


def test_single_device_bit_identical_and_correct():
    conf_on = make_conf(compact=True)
    eng = make_engine(conf_on)
    ns = div_args()
    on = eng.run("fib", [ns], max_steps=500_000)
    off = make_engine(make_conf(compact=False)).run(
        "fib", [ns], max_steps=500_000)
    assert eng.compactor.stats["fires"] >= 1
    assert eng.compactor.stats["min_width"] < LANES  # narrowing fired
    assert_results_identical(on, off)
    expect = np.asarray([fib_ref(int(n)) for n in ns], np.int64)
    assert (np.asarray(on.results[0]) == expect).all()
    # packing strictly reduced dispatch slots (retired/dispatch up)
    assert eng.compactor.stats["dispatch_slots"] < on.steps * LANES


def test_unfused_build_bit_identical():
    on, off, _ = _ab(make_conf(compact=True, fuse=False),
                     make_conf(compact=False, fuse=False))
    assert_results_identical(on, off)


def test_fused_vs_unfused_under_compaction():
    on_f, off_f, ns = _ab(make_conf(compact=True, fuse=True),
                          make_conf(compact=False, fuse=True))
    assert_results_identical(on_f, off_f)


def test_repeat_runs_reset_mapping():
    # a second run() on the same engine must start from the identity
    # mapping, not compose onto the previous run's permutation
    conf = make_conf(compact=True)
    eng = make_engine(conf)
    ns = div_args()
    expect = np.asarray([fib_ref(int(n)) for n in ns], np.int64)
    for _ in range(2):
        res = eng.run("fib", [ns], max_steps=500_000)
        assert (np.asarray(res.results[0]) == expect).all()


def test_multitenant_concat_image_bit_identical():
    from wasmedge_tpu.batch.multitenant import (
        MultiTenantBatchEngine, Tenant)

    def build(compact):
        conf = make_conf(compact=compact)
        tenants = []
        for data, fn, args in (
                (build_fib(), "fib", [div_args(8, 4, 9, seed=5)]),
                (build_loop_sum(), "loop_sum",
                 [(20 + 13 * np.arange(8)).astype(np.int64)])):
            inst, store = instantiate(data, conf)
            tenants.append(Tenant(
                engine=BatchEngine(inst, store=store, conf=conf,
                                   lanes=8),
                func_name=fn, args_lanes=args, lanes=8))
        return MultiTenantBatchEngine(tenants, conf=conf)

    mt_on = build(True)
    res_on = mt_on.run_tenants(max_steps=500_000)
    res_off = build(False).run_tenants(max_steps=500_000)
    assert mt_on.compactor is not None \
        and mt_on.compactor.stats["fires"] >= 1
    for a, b in zip(res_on, res_off):
        assert_results_identical(a, b)
        assert a.completed.all()


def test_shard_drive_mesh_bit_identical():
    from wasmedge_tpu.parallel.shard_drive import ShardDrive

    ns = div_args(22, 4, 9)   # uneven split: pads ride the last shard
    res = {}
    drives = {}
    for compact in (True, False):
        conf = make_conf(compact=compact, forced=True)
        inst, store = instantiate(build_fib(), conf)
        drv = ShardDrive(inst, store=store, conf=conf, devices=4)
        drives[compact] = drv
        res[compact] = drv.run("fib", [ns], max_steps=500_000)
    comp = drives[True].engine.compactor
    assert comp is not None and comp.stats["fires"] >= 1
    assert comp.narrow is False   # global width pinned by the sharding
    assert_results_identical(res[True], res[False])
    expect = np.asarray([fib_ref(int(n)) for n in ns], np.int64)
    assert (np.asarray(res[True].results[0]) == expect).all()


# ---------------------------------------------------------------------------
# checkpoint: the permutation rides the snapshot
# ---------------------------------------------------------------------------
def test_checkpoint_lane_src_roundtrip_and_refusal():
    from wasmedge_tpu.batch import checkpoint
    from wasmedge_tpu.batch.compact import arm

    conf = make_conf(compact=True)
    eng = make_engine(conf)
    arm(eng)
    ns = div_args()
    state = eng.initial_state(eng.export_func_idx("fib"), [ns])
    state, total = eng.run_from_state(state, 0, 96)   # two boundaries
    assert eng.compactor.stats["fires"] >= 1
    assert not eng.compactor.identity
    src = eng.compactor.src.copy()
    with tempfile.TemporaryDirectory(prefix="compact-ckpt-") as d:
        path = os.path.join(d, "ckpt.npz")
        checkpoint.save(path, eng, state, total)
        # fresh engine, compact on: src restores with the state and the
        # resumed run finishes bit-identical to an uncompacted one
        eng2 = make_engine(make_conf(compact=True))
        arm(eng2)
        st2, tot2 = checkpoint.load(path, eng2)
        assert (eng2.compactor.src == src).all()
        st2, tot2 = eng2.run_from_state(st2, tot2, 500_000)
        order = eng2.compactor.restore_order()
        got = np.asarray(st2.stack_lo)[0, order]
        ref = make_engine(make_conf(compact=False)).run(
            "fib", [ns], max_steps=500_000)
        assert (got == np.asarray(ref.results[0]).astype(
            np.uint64).astype(np.uint32).view(np.int32)).all()
        # compact-off engine must refuse the permuted snapshot loudly
        eng3 = make_engine(make_conf(compact=False))
        with pytest.raises(ValueError, match="lane compaction"):
            checkpoint.load(path, eng3)
        # ...and so must an externally-managed engine even with the
        # knob ON (what BatchSupervisor.run() marks before lineage
        # adoption: supervised rungs run uncompacted, so arming a
        # compactor they would discard = silent lane shuffle)
        eng4 = make_engine(make_conf(compact=True))
        eng4._compact_external = True
        with pytest.raises(ValueError, match="lane compaction"):
            checkpoint.load(path, eng4)


def test_supervised_run_is_uncompacted_and_marked():
    from wasmedge_tpu.batch.supervisor import BatchSupervisor

    conf = make_conf(compact=True)
    conf.supervisor.use_kernel_tier = False
    eng = make_engine(conf)
    ns = div_args()
    res = BatchSupervisor(eng, conf=conf).run("fib", [ns],
                                              max_steps=500_000)
    assert eng._compact_external and eng.compactor is None
    ref = make_engine(make_conf(compact=False)).run(
        "fib", [ns], max_steps=500_000)
    assert_results_identical(res, ref)


# ---------------------------------------------------------------------------
# serving: bindings / recycling / hv / checkpoints / stdout follow lanes
# ---------------------------------------------------------------------------
def _serve_conf(lanes=8, **kw):
    conf = make_conf(compact=True, **kw)
    conf.batch.lanes = lanes
    return conf


def _fib_server(conf, lanes=8, **kw):
    from wasmedge_tpu.serve.server import BatchServer

    inst, store = instantiate(build_fib(), conf)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


def test_serving_bindings_follow_lanes_through_permutation():
    conf = _serve_conf()
    srv = _fib_server(conf)
    ns = [11, 4, 9, 6, 12, 5, 10, 7, 8, 13, 4, 9, 12, 6]
    futs = [(n, srv.submit("fib", [n])) for n in ns]
    srv.run_until_idle()
    assert srv._compactor.stats["fires"] >= 1
    assert srv.engine.compactor is None   # engine-level pass disarmed
    for n, f in futs:
        assert f.result(5)[0] == fib_ref(n)
    c = srv.counters
    assert c["completed"] == len(ns) and c["recycled_lanes"] > 0
    srv.shutdown()


def test_serving_hv_swap_through_permutation():
    conf = _serve_conf(lanes=4)
    conf.hv.max_virtual_lanes = 12
    conf.hv.min_resident_rounds = 1
    srv = _fib_server(conf, lanes=4)
    ns = [10, 5, 9, 6, 11, 7, 8, 12, 4, 9, 10, 6]
    futs = [(n, srv.submit("fib", [n])) for n in ns]
    srv.run_until_idle()
    for n, f in futs:
        assert f.result(5)[0] == fib_ref(n)
    assert srv._compactor.stats["fires"] >= 1
    assert srv.hv.counters["swaps_in"] > 0
    srv.shutdown()


def test_serving_checkpoint_resume_through_permutation():
    with tempfile.TemporaryDirectory(prefix="compact-serve-") as d:
        conf = _serve_conf()
        conf.serve.checkpoint_every_rounds = 2
        srv = _fib_server(conf, checkpoint_dir=d)
        ns = [12, 5, 11, 6, 13, 7, 10, 8, 12, 9, 11, 5]
        futs = {}
        for n in ns:
            f = srv.submit("fib", [n])
            futs[f.request_id] = n
        srv.run_until_idle(max_rounds=6)
        assert srv._compactor.stats["fires"] >= 1
        assert srv._lineage.newest() is not None
        # simulated crash: a fresh server adopts the lineage — the
        # binding journal was remapped under the same lock as every
        # permutation, so adopted ids resolve to THEIR results
        conf2 = _serve_conf()
        conf2.serve.checkpoint_every_rounds = 2
        srv2 = _fib_server(conf2, checkpoint_dir=d, resume=True)
        assert srv2.adopted   # something was in flight at the snapshot
        srv2.run_until_idle()
        for rid, fut in srv2.adopted.items():
            assert fut.result(5)[0] == fib_ref(futs[rid])
        srv2.shutdown()
        srv.shutdown(drain=False)


def test_serving_stdout_exactly_once_through_permutation():
    import bench_echo
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.serve.server import BatchServer

    def run(compact, sink_path):
        conf = _serve_conf(lanes=4)
        conf.batch.compact = compact
        conf.batch.steps_per_launch = 24
        wasi = WasiModule()
        wasi.init_wasi(dirs=[], prog_name="echo")
        sink = os.open(sink_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
        wasi.env.fds[1].os_fd = sink
        mod = Validator(conf).validate(
            Loader(conf).parse_module(bench_echo.build_module()))
        store = StoreManager()
        ex = Executor(conf)
        ex.register_import_object(store, wasi)
        inst = ex.instantiate(store, mod)
        srv = BatchServer(inst, store=store, conf=conf, lanes=4)
        # VARIED iteration counts: identical args would keep every
        # lane perfectly convergent and the policy would (correctly)
        # never fire.  The message bytes are identical per write, so
        # the on/off byte STREAMS still compare equal regardless of
        # drain interleaving — only the total count is placement-
        # sensitive, and exactly-once pins it below.
        iters = [1, 6, 2, 5, 3, 4, 1, 6, 2, 5]
        futs = [srv.submit("echo", [k]) for k in iters]
        srv.run_until_idle()
        rets = [f.result(5)[0] for f in futs]
        srv.shutdown()
        os.close(sink)
        with open(sink_path, "rb") as f:
            return rets, f.read(), srv, iters

    with tempfile.TemporaryDirectory(prefix="compact-stdout-") as d:
        rets_on, bytes_on, srv_on, iters = run(True, os.path.join(d, "on"))
        rets_off, bytes_off, _, _ = run(False, os.path.join(d, "off"))
    assert srv_on._compactor.stats["fires"] >= 1
    assert rets_on == rets_off
    assert bytes_on == bytes_off and len(bytes_on) > 0
    # exactly-once: 2 fd_writes x 16 bytes per iteration per request,
    # no duplicates or losses through any fired permutation
    assert len(bytes_on) == sum(2 * 16 * k for k in iters)


# ---------------------------------------------------------------------------
# observability: convergence gauges, compact instants, Prometheus
# ---------------------------------------------------------------------------
def test_obs_convergence_and_compaction_metrics():
    from wasmedge_tpu.obs.metrics import (
        parse_prometheus, render_prometheus)

    conf = make_conf(compact=True)
    conf.obs.enabled = True
    eng = make_engine(conf)
    eng.run("fib", [div_args()], max_steps=500_000)
    rec = eng.obs
    assert rec.compactions_total >= 1
    assert rec.convergence["rounds"] >= 1
    assert "compact" in rec.event_names()
    text = render_prometheus(recorder=rec)
    parsed = parse_prometheus(text)   # {(name, labels_frozenset): val}
    names = {k[0] for k in parsed}
    assert "wasmedge_compactions_total" in names
    assert parsed[("wasmedge_compactions_total", frozenset())] >= 1
    assert "wasmedge_convergence_unique_pcs" in names
    assert "wasmedge_convergence_largest_group_fraction" in names
    assert "wasmedge_compaction_latency_seconds_count" in names


def test_obs_off_bit_identical_and_noop_recorder():
    from wasmedge_tpu.obs.recorder import NULL_RECORDER

    NULL_RECORDER.observe_convergence(3, 0.5)   # must be a no-op
    NULL_RECORDER.observe_compaction(0.1)
    conf = make_conf(compact=True)   # obs off
    eng = make_engine(conf)
    ns = div_args()
    res = eng.run("fib", [ns], max_steps=500_000)
    ref = make_engine(make_conf(compact=False)).run(
        "fib", [ns], max_steps=500_000)
    assert_results_identical(res, ref)


# ---------------------------------------------------------------------------
# satellite: divergence-aware fusion pattern selection
# ---------------------------------------------------------------------------
def test_fusion_divergence_bias_off_is_bit_identical_planning():
    from wasmedge_tpu.batch.fuse import plan_fusion
    from wasmedge_tpu.batch.image import build_device_image

    def plan(bias):
        conf = Configure()
        conf.batch.fuse_divergence_bias = bias
        mod = Validator(conf).validate(
            Loader(conf).parse_module(build_fib()))
        img = build_device_image(mod.lowered, mod=mod)
        rep = plan_fusion(img, conf.batch)
        return img, rep

    img0, rep0 = plan(0.0)
    imgd, repd = plan(0.0)
    assert rep0["divergence_bias"] == 0.0
    assert np.array_equal(np.asarray(getattr(img0, "fuse_len", [])),
                          np.asarray(getattr(imgd, "fuse_len", [])))
    # candidates carry divergence + planned-vs-realized delta fields
    for row in rep0["candidates"]:
        assert "divergence" in row
        assert row["delta_runs"] == row["planned"] - row["realized_runs"]
    # bias > 0 still plans valid non-overlapping runs, reports the knob
    imgb, repb = plan(4.0)
    assert repb["divergence_bias"] == 4.0
    for row in repb["candidates"]:
        assert "adjusted_saved_dispatches" in row
    if getattr(imgb, "fuse_len", None) is not None:
        flen = np.asarray(imgb.fuse_len)
        # runs never overlap: inside a run, no other head
        for pc in np.nonzero(flen >= 2)[0]:
            assert (flen[pc + 1:pc + int(flen[pc])] == 0).all()


def test_fusion_report_validates_with_deltas():
    from wasmedge_tpu.analysis import analyze_validated
    from wasmedge_tpu.analysis.report import validate_report
    from wasmedge_tpu.batch.fuse import plan_fusion
    from wasmedge_tpu.batch.image import build_device_image

    conf = Configure()
    mod = Validator(conf).validate(
        Loader(conf).parse_module(build_fib()))
    analysis = analyze_validated(mod)
    doc = analysis.to_dict()
    img = build_device_image(mod.lowered, mod=mod)
    doc["fusion"] = plan_fusion(img, conf.batch, analysis=analysis)
    assert validate_report(doc) == []
