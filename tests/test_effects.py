"""Guest suspend/resume via effect handlers (wasmedge_tpu/effects/,
marker `effects`).

Pins the r23 acceptance contract:
  - `wasmedge.await_event` with no pending payload PARKS the lane at
    the next launch boundary (serialized through the SwapStore, zero
    resident lanes) and an external wake re-enters it bit-identically
    to never having parked (results AND streamed stdout)
  - a pure-clock `poll_oneoff` parks with a deterministic timer and
    the timer wake delivers exactly the host-path event tail
  - the deadline clock PAUSES while a session waits on an explicit
    wake; timer sleeps keep their absolute deadline
  - fault seams: a faulted `session_park` leaves the lane resident and
    retries; a faulted `session_wake` re-queues the wake, never loses it
  - parked sessions survive a cross-process checkpoint/resume and wake
    exactly-once under their original ids
  - the effects-off configuration is inert: no `_effects` attribute,
    the `wasmedge` import falls back to Errno.AGAIN, wake() refuses

Speed discipline: tier-1 fast — tiny guest modules, lanes=2, chunk
128, and a module-scoped JAX compilation cache.
"""

import struct
import tempfile
import time

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import WasmError
from wasmedge_tpu.effects import StreamBuf, effects_import_object
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.host.wasi import WasiModule
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.serve import BatchServer, DeadlineExceeded
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.effects


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="effects-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _conf(effects=True, obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 128
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    conf.obs.enabled = obs
    conf.effects.suspend = effects
    return conf


def _await_mod():
    """wait(n) -> await_event(buf=64, len=8, nwritten=32); returns
    first-payload-word + n (proves both delivery and that the guest's
    own state survived the park)."""
    b = ModuleBuilder()
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i64"], ["i64"], [], [
        ("i32.const", 64), ("i32.const", 8), ("i32.const", 32),
        ("call", 0), "drop",
        ("i32.const", 64), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="wait")
    return b.build()


def _sleep_mod(ns):
    """nap(n) -> poll_oneoff over ONE monotonic-clock subscription of
    `ns` nanoseconds; returns n + nevents (= n + 1)."""
    sub = bytearray(48)
    sub[0:8] = (0xAB).to_bytes(8, "little")       # userdata
    sub[8] = 0                                    # tag CLOCK
    sub[16:20] = (1).to_bytes(4, "little")        # clockid MONOTONIC
    sub[24:32] = int(ns).to_bytes(8, "little")    # timeout
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "poll_oneoff",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_active_data(0, [("i32.const", 64)], bytes(sub))
    b.add_function(["i64"], ["i64"], [], [
        ("i32.const", 64), ("i32.const", 128), ("i32.const", 1),
        ("i32.const", 192), ("call", 0), "drop",
        ("i32.const", 192), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="nap")
    return b.build()


def _echo_await_mod():
    """go(n): write "pre|", await_event, write the payload then "post";
    returns payload-length + n.  The stdout stream across a park must
    be byte-identical to a never-parked run."""
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_active_data(0, [("i32.const", 256)], b"pre|")
    b.add_active_data(0, [("i32.const", 264)], b"post")

    def write(buf_instrs, len_instrs):
        return [
            ("i32.const", 0), *buf_instrs, ("i32.store", 2, 0),
            ("i32.const", 4), *len_instrs, ("i32.store", 2, 0),
            ("i32.const", 1), ("i32.const", 0), ("i32.const", 1),
            ("i32.const", 32), ("call", 0), "drop",
        ]

    b.add_function(["i64"], ["i64"], [], [
        *write([("i32.const", 256)], [("i32.const", 4)]),
        ("i32.const", 64), ("i32.const", 16), ("i32.const", 40),
        ("call", 1), "drop",
        *write([("i32.const", 64)],
               [("i32.const", 40), ("i32.load", 2, 0)]),
        *write([("i32.const", 264)], [("i32.const", 4)]),
        ("i32.const", 40), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="go")
    return b.build()


def _server(wasm, conf=None, lanes=2, wasi=False, sink=None, **kw):
    conf = conf or _conf()
    mod = Validator(conf).validate(Loader(conf).parse_module(wasm))
    store = StoreManager()
    ex = Executor(conf)
    if wasi:
        w = WasiModule()
        w.init_wasi(dirs=[], prog_name="effects-test")
        if sink is not None:
            w.env.fds[1].os_fd = sink
        ex.register_import_object(store, w)
    ex.register_import_object(store, effects_import_object())
    inst = ex.instantiate(store, mod)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


# ---------------------------------------------------------------------------
# StreamBuf unit semantics
# ---------------------------------------------------------------------------
def test_streambuf_dedupe_window_and_close():
    buf = StreamBuf(cap=8)
    buf.append(0, b"abcd")
    buf.append(2, b"cdef")        # crash-replay overlap: deduped
    chunk, off, closed = buf.read(0, timeout=0)
    assert (chunk, off, closed) == (b"abcdef", 6, False)
    assert buf.read(6, timeout=0) == (None, 6, False)   # bare timeout
    buf.append(6, b"ghijkl")      # 12 logical bytes > cap 8: window
    chunk, off, closed = buf.read(0, timeout=0)
    assert chunk == b"efghijkl" and off == 12           # snapped forward
    buf.close(error=None)
    assert buf.read(12, timeout=0) == (b"", 12, True)
    assert buf.read(3, timeout=0)[0] == b"efghijkl"     # late replay


# ---------------------------------------------------------------------------
# park -> external wake -> resolve
# ---------------------------------------------------------------------------
def test_await_event_parks_and_http_wake_resolves():
    srv = _server(_await_mod(), lanes=2)
    fut = srv.submit("wait", [5])
    srv.run_until_idle()
    # parked: zero resident lanes, the session holds no device capacity
    assert not fut.done
    assert srv.effects.in_flight() == 1
    assert not srv._bindings and len(srv._free) == 2
    st = srv.session_stats()
    assert st["parked"] == 1 and st["parks"] == 1
    rid = fut.request_id
    assert srv.wake(rid, struct.pack("<I", 41)) == "parked"
    srv.run_until_idle()
    assert fut.result(0)[0] == 41 + 5
    st = srv.session_stats()
    assert st["parked"] == 0 and st["resumes"] == 1
    assert st["wakes_http"] == 1 and st["delivered"] == 1
    # the server remains a normal server: a second request round-trips
    f2 = srv.submit("wait", [7])
    srv.run_until_idle()
    assert srv.wake(f2.request_id, struct.pack("<I", 1)) == "parked"
    srv.run_until_idle()
    assert f2.result(0)[0] == 8


def test_wake_before_park_delivers_without_parking():
    srv = _server(_await_mod(), lanes=2)
    fut = srv.submit("wait", [9])
    # the wake lands before the request ever reaches await_event: the
    # payload pre-delivers at the call and the session never parks
    assert srv.wake(fut.request_id, struct.pack("<I", 100)) \
        in ("pending", "unknown")
    srv.run_until_idle()
    assert fut.result(0)[0] == 109
    st = srv.session_stats()
    assert st["parks"] == 0 and st["delivered"] == 1


def test_timer_park_and_timer_wake():
    srv = _server(_sleep_mod(60_000_000), wasi=True, lanes=2)  # 60ms
    fut = srv.submit("nap", [10])
    srv.run_until_idle()
    assert srv.effects.in_flight() == 1 and not srv._bindings
    time.sleep(0.08)
    srv.run_until_idle()
    assert fut.result(0)[0] == 11    # n + the single clock event
    st = srv.session_stats()
    assert st["wakes_timer"] == 1 and st["parks"] == 1
    assert st["park_seconds"]["count"] == 1
    assert st["park_seconds"]["sum"] >= 0.05


# ---------------------------------------------------------------------------
# deadline semantics while parked
# ---------------------------------------------------------------------------
def test_timer_park_still_honors_deadline():
    srv = _server(_sleep_mod(10_000_000_000), wasi=True, lanes=2)
    fut = srv.submit("nap", [1], deadline_s=0.05)   # sleep 10s >> 50ms
    srv.run_until_idle()
    assert srv.effects.in_flight() == 1
    time.sleep(0.1)
    srv.run_until_idle()
    with pytest.raises(DeadlineExceeded):
        fut.result(0)
    assert srv.effects.in_flight() == 0
    assert srv.counters["killed"] >= 1


# ---------------------------------------------------------------------------
# streamed stdout: parked run byte-identical to never-parked run
# ---------------------------------------------------------------------------
def _echo_await_run(payload, park):
    import os

    sink = os.open(os.devnull, os.O_WRONLY)
    try:
        srv = _server(_echo_await_mod(), wasi=True, sink=sink, lanes=2)
        fut = srv.submit("go", [3])
        if park:
            srv.run_until_idle()
            assert srv.effects.in_flight() == 1
            # the pre-park output is already streaming
            chunk, _, closed = srv.stream_of(fut.request_id).read(
                0, timeout=0)
            assert chunk == b"pre|" and not closed
            srv.wake(fut.request_id, payload)
        else:
            srv.wake(fut.request_id, payload)   # pre-delivered
        srv.run_until_idle()
        assert fut.result(0)[0] == len(payload) + 3
        buf = srv.stream_of(fut.request_id)
        chunk, off, closed = buf.read(0, timeout=1.0)
        assert closed and buf.error is None
        return chunk
    finally:
        os.close(sink)


def test_stream_bytes_identical_across_park():
    payload = b"DATA1234"
    parked = _echo_await_run(payload, park=True)
    direct = _echo_await_run(payload, park=False)
    assert parked == b"pre|" + payload + b"post"
    assert parked == direct


# ---------------------------------------------------------------------------
# fault seams (testing/faults.py)
# ---------------------------------------------------------------------------
def test_faulted_park_leaves_lane_resident_and_retries():
    inj = FaultInjector([Fault(point="session_park", at=0)])
    srv = _server(_await_mod(), lanes=2, faults=inj)
    fut = srv.submit("wait", [4])
    srv.step()
    # first boundary: the park faulted -> the lane stays RESIDENT
    assert inj.fired == 1
    assert srv.effects.in_flight() == 0 and len(srv._bindings) == 1
    assert srv.session_stats()["park_faults"] == 1
    srv.run_until_idle()
    # retried at the next boundary: parked for real now
    assert srv.effects.in_flight() == 1 and not srv._bindings
    assert srv.session_stats()["parks"] == 1
    srv.wake(fut.request_id, struct.pack("<I", 2))
    srv.run_until_idle()
    assert fut.result(0)[0] == 6


def test_faulted_wake_requeues_not_lost():
    inj = FaultInjector([Fault(point="session_wake", at=0)])
    srv = _server(_await_mod(), lanes=2, faults=inj)
    fut = srv.submit("wait", [8])
    srv.run_until_idle()
    assert srv.effects.in_flight() == 1
    srv.wake(fut.request_id, struct.pack("<I", 30))
    srv.run_until_idle()
    # the faulted wake was re-queued and retried, never dropped
    assert inj.fired == 1
    assert fut.result(0)[0] == 38
    st = srv.session_stats()
    assert st["wake_faults"] == 1 and st["wakes_http"] == 1


# ---------------------------------------------------------------------------
# durability: parked sessions survive a cross-process resume
# ---------------------------------------------------------------------------
def test_parked_session_survives_cross_process_resume():
    with tempfile.TemporaryDirectory(prefix="effects-resume-") as d:
        srv = _server(_await_mod(), lanes=2, checkpoint_dir=d)
        fut = srv.submit("wait", [7])
        srv.run_until_idle()
        assert srv.effects.in_flight() == 1
        srv.checkpoint()
        rid = fut.request_id
        del srv, fut   # "process" dies with the session parked

        srv2 = _server(_await_mod(), lanes=2, checkpoint_dir=d,
                       resume=True)
        # adopted as a PARKED session (not requeued from scratch)
        assert list(srv2.adopted) == [rid]
        assert rid in srv2.effects.parked_ids()
        assert srv2.wake(rid, struct.pack("<I", 41)) == "parked"
        srv2.run_until_idle()
        assert srv2.adopted[rid].result(0)[0] == 41 + 7
        # exactly-once: fresh ids order after the adopted one
        f2 = srv2.submit("wait", [1])
        assert f2.request_id > rid


def test_wake_delivered_then_crash_is_not_lost():
    # a payload delivered to a PARKED session just before the crash
    # rides the journal (hex payloads) and still wakes the resume
    with tempfile.TemporaryDirectory(prefix="effects-resume2-") as d:
        srv = _server(_await_mod(), lanes=2, checkpoint_dir=d)
        fut = srv.submit("wait", [2])
        srv.run_until_idle()
        srv.wake(fut.request_id, struct.pack("<I", 9))
        srv.checkpoint()   # wake queued/journaled, not yet installed
        rid = fut.request_id
        del srv, fut

        srv2 = _server(_await_mod(), lanes=2, checkpoint_dir=d,
                       resume=True)
        srv2.run_until_idle()
        assert srv2.adopted[rid].result(0)[0] == 11


# ---------------------------------------------------------------------------
# effects off: bit-identical inert configuration
# ---------------------------------------------------------------------------
def test_effects_off_is_inert():
    srv = _server(_await_mod(), conf=_conf(effects=False), lanes=2)
    assert srv.effects is None
    assert not hasattr(srv.engine, "_effects")
    fut = srv.submit("wait", [9])
    srv.run_until_idle()
    # the fallback host body returns Errno.AGAIN with zero bytes: the
    # guest completes immediately with the untouched buffer (= 0 + n)
    assert fut.result(0)[0] == 9
    assert srv.session_stats() is None
    assert srv.stream_of(fut.request_id) is None
    with pytest.raises(WasmError):
        srv.wake(fut.request_id)


def test_effects_metrics_render_and_status_block():
    from wasmedge_tpu.obs.metrics import (
        parse_prometheus,
        render_prometheus,
    )

    srv = _server(_await_mod(), lanes=2)
    fut = srv.submit("wait", [1])
    srv.run_until_idle()
    m = parse_prometheus(render_prometheus(
        session_stats=srv.session_stats()))
    assert m[("wasmedge_sessions_parked", frozenset())] == 1
    assert m[("wasmedge_session_parks_total", frozenset())] == 1
    srv.wake(fut.request_id, b"\x01\x00\x00\x00")
    srv.run_until_idle()
    m = parse_prometheus(render_prometheus(
        session_stats=srv.session_stats()))
    assert m[("wasmedge_sessions_parked", frozenset())] == 0
    assert m[("wasmedge_session_wakes_total",
              frozenset({("source", "http")}))] == 1
    assert m[("wasmedge_session_park_seconds_count", frozenset())] == 1
    # obs-off/effects-off renders bit-identically to no kwarg at all
    assert render_prometheus(session_stats=None) == render_prometheus()
