"""Elastic fleet (r21): live resharding + autoscale policy.

Pins the r21 acceptance contract deterministically, on the
conftest-forced 8-device virtual CPU mesh:

  - live reshard, grow (2 -> 4 devices): resident lanes ride through a
    mid-stream device-set change with results bit-identical to an
    unresharded single-device reference — no drain, no re-queue
  - live reshard, shrink (4 -> 2 devices): the lane width holds and
    re-splits across fewer devices, same bit-identity
  - hv-swapped virtual lanes (parked in the SwapStore at reshard time)
    and compaction-permuted lanes ride through the move too
  - a `reshard_install` fault rolls the server back onto the OLD mesh
    with every resident lane intact, and the retry succeeds
  - the gateway tier: GatewayService.reshard moves the RUNNING
    generation and future generations inherit the new geometry;
    wasmedge_reshards_total{direction} renders; a gateway that never
    reshards emits no reshard series at all
  - the autoscale ladder (gateway/autoscale.py) is deterministic:
    spike -> raise_virtual -> reshard_grow -> shed, calm reverses,
    cooldown holds between actions; autoscale-off gateways carry no
    controller, no status key, no metric series (r16 identity)

Speed discipline mirrors tests/test_serve_mesh.py: tiny geometry, a
module-scoped JAX persistent compile cache, tier-1 fast.
"""

import tempfile

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.serve import BatchServer
from wasmedge_tpu.testing.faults import Fault, FaultInjector, InjectedFault
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="elastic-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(hv_virtual=None, compact=False, obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    if hv_virtual is not None:
        conf.hv.max_virtual_lanes = hv_virtual
    if compact:
        # hair-trigger policy: compact at every eligible boundary
        conf.batch.compact = True
        conf.batch.compact_min_interval = 1
        conf.batch.compact_trigger = 0.0
        conf.batch.compact_cost_factor = 0.0
    return conf


def _server(conf, lanes, **kw):
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


NS = [5, 11, 12, 7, 3, 12, 9, 2, 10, 6, 12, 11, 8, 12, 4, 9]


def _mesh_devices(n):
    import jax

    devs = jax.devices()[:n]
    assert len(devs) == n, "virtual device mesh missing"
    return devs


@pytest.fixture(scope="module")
def ref_results(_compile_cache):
    """The unresharded single-device reference every bit-identity
    assertion compares against."""
    srv = _server(_conf(), lanes=6)
    futs = [srv.submit("fib", [n]) for n in NS]
    srv.run_until_idle()
    ref = [f.result(0)[0] for f in futs]
    assert ref == [_fib(n) for n in NS]
    return ref


# ---------------------------------------------------------------------------
# live reshard: the running server moves device sets mid-stream
# ---------------------------------------------------------------------------
def test_reshard_grow_2_to_4_resident_lanes_bit_identical(ref_results):
    srv = _server(_conf(), lanes=6, devices=_mesh_devices(2))
    futs = [srv.submit("fib", [n]) for n in NS]
    for _ in range(2):
        srv.step()
    assert srv.in_flight > 0          # resident lanes mid-request
    out = srv.reshard(devices=_mesh_devices(4))
    # grow-only pool: 6 lanes over 2 devices pads to 8 over 4 — the
    # resident lanes keep their global indices and their columns
    assert out == {"ok": True, "devices": 4, "old_devices": 2,
                   "lanes": 8, "old_lanes": 6,
                   "resident": out["resident"]}
    assert out["resident"] > 0
    assert srv.lanes == 8 and srv.engine.mesh is not None
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results
    assert srv.counters["reshards"] == 1
    c = srv.counters
    assert c["submitted"] == c["completed"] + c["trapped"] \
        + c["expired"] + c["killed"] + c["rejected"]


def test_reshard_shrink_4_to_2_keeps_lane_width(ref_results):
    srv = _server(_conf(), lanes=8, devices=_mesh_devices(4))
    futs = [srv.submit("fib", [n]) for n in NS]
    for _ in range(2):
        srv.step()
    out = srv.reshard(devices=_mesh_devices(2))
    assert out["devices"] == 2 and out["old_devices"] == 4
    assert out["lanes"] == 8 == out["old_lanes"]   # width holds
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results


def test_reshard_idle_server_serves_new_work(ref_results):
    """An IDLE reshard (resident=0) must leave the server fully
    servable: the next admitted requests run to completion on the new
    mesh with bit-identical results — no drain state leaks into the
    rebuilt launch path."""
    srv = _server(_conf(), lanes=6, devices=_mesh_devices(2))
    warm = [srv.submit("fib", [n]) for n in NS[:4]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in warm] == ref_results[:4]
    out = srv.reshard(devices=_mesh_devices(4))
    assert out["ok"] and out["resident"] == 0
    futs = [srv.submit("fib", [n]) for n in NS]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results
    assert srv.counters["trapped"] == 0 and srv.counters["killed"] == 0


def test_reshard_with_hv_swapped_vlanes_rides_through(ref_results):
    """Oversubscribed server: requests parked in the SwapStore at
    reshard time reinstall onto the NEW geometry bit-identically."""
    srv = _server(_conf(hv_virtual=16), lanes=6,
                  devices=_mesh_devices(2))
    futs = [srv.submit("fib", [n]) for n in NS]
    for _ in range(8):
        srv.step()
        if srv.list_swapped():
            break
    assert srv.list_swapped(), "no vlane parked before the reshard"
    out = srv.reshard(devices=_mesh_devices(4))
    assert out["lanes"] == 8
    assert srv.hv.lanes == 8           # hv pool resized with the move
    assert srv.hv.virtual_cap == 16    # explicit cap survives
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results
    hv = srv.hv_stats()
    assert hv["swaps_out"] > 0 and hv["swaps_in"] > 0


def test_reshard_with_compaction_permutation_applied(ref_results):
    """A lane permutation already applied by the compactor is part of
    the running state: it moves with the reshard, and the compactor
    itself is rebuilt over the new geometry and keeps firing."""
    srv = _server(_conf(compact=True, obs=True), lanes=6,
                  devices=_mesh_devices(2))
    futs = [srv.submit("fib", [n]) for n in NS]
    for _ in range(8):
        srv.step()
        if any(e["name"] == "compact" for e in srv.obs.events):
            break
    assert any(e["name"] == "compact" for e in srv.obs.events), \
        "no compaction fired before the reshard"
    old_compactor = srv._compactor
    srv.reshard(devices=_mesh_devices(4))
    assert srv._compactor is not None
    assert srv._compactor is not old_compactor
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results
    assert any(e["name"] == "reshard" for e in srv.obs.events)


def test_reshard_install_fault_rolls_back_then_retry_succeeds(
        ref_results):
    inj = FaultInjector([Fault(point="reshard_install", at=0)])
    srv = _server(_conf(), lanes=6, devices=_mesh_devices(2),
                  faults=inj)
    futs = [srv.submit("fib", [n]) for n in NS]
    for _ in range(2):
        srv.step()
    resident = srv.in_flight
    with pytest.raises(InjectedFault):
        srv.reshard(devices=_mesh_devices(4))
    # fail-closed: the OLD mesh keeps serving, nothing dropped
    assert srv.lanes == 6
    assert srv.in_flight == resident
    assert srv.counters["reshards"] == 0
    assert inj.log == [("reshard_install", 0)]
    out = srv.reshard(devices=_mesh_devices(4))   # arrival 1: clean
    assert out["ok"] and srv.lanes == 8
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref_results
    assert srv.counters["reshards"] == 1


def test_gateway_reshard_rejects_bad_device_counts_pre_mutation():
    from wasmedge_tpu.gateway.service import GatewayService

    gw = GatewayService(conf=_conf(), lanes=4,
                        devices=_mesh_devices(2))
    try:
        gw.register_module("fib", build_fib())
        reqs = [gw.submit("fib", [n], module="fib") for n in NS[:4]]
        with pytest.raises(ValueError):
            gw.reshard(n_devices=64)   # more than the mesh has
        with pytest.raises(ValueError):
            gw.reshard(n_devices=0)
        assert gw.status()["devices"] == 2   # nothing moved
        assert gw.status()["reshards"] == {}
        gw.current.server.run_until_idle()
        assert [r.future.result(5)[0] for r in reqs] \
            == [_fib(n) for n in NS[:4]]
    finally:
        gw.shutdown()


# ---------------------------------------------------------------------------
# gateway tier: service-level reshard + metrics
# ---------------------------------------------------------------------------
def test_gateway_reshard_moves_generation_and_future_builds(
        ref_results):
    from wasmedge_tpu.gateway.service import GatewayService
    from wasmedge_tpu.obs.metrics import parse_prometheus

    gw = GatewayService(conf=_conf(), lanes=6,
                        devices=_mesh_devices(2))
    try:
        gw.register_module("fib", build_fib())
        reqs = [gw.submit("fib", [n], module="fib") for n in NS]
        out = gw.reshard(n_devices=4)
        assert out["ok"] and out["direction"] == "grow"
        assert out["lanes"] == 8
        srv = gw.current.server
        srv.run_until_idle()
        assert [r.future.result(5)[0] for r in reqs] == ref_results
        st = gw.status()
        assert st["devices"] == 4
        assert st["reshards"] == {"grow": 1}
        assert st["lanes"] == 8        # future generations inherit
        m = parse_prometheus(gw.metrics_text())
        assert ("wasmedge_reshards_total",
                frozenset({("direction", "grow")})) in m
        # a second registration builds AT the resharded geometry
        gw.register_module("fib2", build_fib())
        assert gw.current.server.lanes == 8
        assert gw.current.server.engine.mesh is not None
    finally:
        gw.shutdown()


def test_gateway_without_reshard_emits_no_reshard_series():
    from wasmedge_tpu.gateway.service import GatewayService
    from wasmedge_tpu.obs.metrics import render_prometheus

    gw = GatewayService(conf=_conf(), lanes=2)
    try:
        gw.register_module("fib", build_fib())
        text = gw.metrics_text()
        assert "wasmedge_reshards_total" not in text
        assert "wasmedge_autoscale" not in text
        assert gw.autoscale is None
        assert "autoscale" not in gw.status()
        assert "reshards" not in gw.current.server.counters or \
            gw.current.server.counters["reshards"] == 0
    finally:
        gw.shutdown()
    # and the bare renderer stays r16-shaped with the new args absent
    assert "wasmedge_reshards" not in render_prometheus()
    assert "wasmedge_autoscale" not in render_prometheus()


# ---------------------------------------------------------------------------
# autoscale: the deterministic spike/calm ladder
# ---------------------------------------------------------------------------
class _StubHv:
    def __init__(self, virtual_cap):
        self.virtual_cap = virtual_cap


class _StubServer:
    def __init__(self, lanes=4, hv_cap=4, queue_cap=16):
        import threading

        self.lanes = lanes
        self.hv = _StubHv(hv_cap)
        self.queue = []
        self.in_flight = 0
        self._lock = threading.Lock()
        self.k = type("K", (), {"queue_capacity": queue_cap})()


class _StubSvc:
    """Just enough GatewayService surface for the controller: the
    ladder's decisions are pure functions of these signals, so the
    stub makes every branch deterministic and instant."""

    def __init__(self, srv, devices=2):
        from wasmedge_tpu.obs.recorder import NULL_RECORDER

        self._srv = srv
        self.devices = list(range(devices)) if devices > 1 else None
        self.force_degraded = False
        self.obs = NULL_RECORDER
        self.resharded_to = []
        self.reshard_fails = False
        self.current = type("G", (), {"server": srv})()

    def reshard(self, n_devices=None, devices=None):
        if self.reshard_fails:
            raise RuntimeError("reshard rolled back")
        self.resharded_to.append(n_devices)
        self.devices = list(range(n_devices)) if n_devices > 1 else None
        return {"ok": True, "lanes": self._srv.lanes}


def _ctl(svc, **kw):
    from wasmedge_tpu.gateway.autoscale import (AutoscaleConfig,
                                                AutoscaleController)

    kw.setdefault("enabled", True)
    kw.setdefault("auto_tick", False)
    kw.setdefault("cooldown_ticks", 0)
    return AutoscaleController(svc, AutoscaleConfig(**kw))


def test_autoscale_spike_ladder_virtual_then_reshard_then_shed():
    srv = _StubServer(lanes=4, hv_cap=4, queue_cap=16)
    svc = _StubSvc(srv, devices=2)
    ctl = _ctl(svc, max_virtual_factor=2.0, device_ladder=[2, 4])
    srv.queue = [None] * 16            # saturated
    assert ctl.tick() == "raise_virtual"
    assert srv.hv.virtual_cap == 8     # +lanes, clamped at 2.0x
    assert ctl.tick() == "reshard_grow"
    assert svc.resharded_to == [4]
    assert ctl.tick() == "shed"        # ladder exhausted
    assert svc.force_degraded is True
    assert ctl.tick() is None          # already shedding: nothing left
    assert ctl.actions == {"raise_virtual": 1, "lower_virtual": 0,
                           "reshard_grow": 1, "reshard_shrink": 0,
                           "shed": 1, "unshed": 0}


def test_autoscale_calm_ladder_reverses_and_restores_base():
    srv = _StubServer(lanes=4, hv_cap=4, queue_cap=16)
    svc = _StubSvc(srv, devices=2)
    ctl = _ctl(svc, max_virtual_factor=2.0, device_ladder=[2, 4])
    srv.queue = [None] * 16
    for _ in range(3):
        ctl.tick()                     # raise + grow + shed
    srv.queue = []                     # traffic gone
    srv.in_flight = 0
    assert ctl.tick() == "unshed"
    assert svc.force_degraded is False
    assert ctl.tick() == "reshard_shrink"
    assert svc.resharded_to == [4, 2]
    assert ctl.tick() == "lower_virtual"
    assert srv.hv.virtual_cap == 4     # back at the recorded base
    assert ctl.tick() is None          # fully unwound


def test_autoscale_cooldown_holds_between_actions():
    srv = _StubServer(lanes=4, hv_cap=4, queue_cap=16)
    svc = _StubSvc(srv, devices=2)
    ctl = _ctl(svc, cooldown_ticks=2, max_virtual_factor=4.0)
    srv.queue = [None] * 16
    assert ctl.tick() == "raise_virtual"
    assert ctl.tick() is None          # cooldown 2
    assert ctl.tick() is None          # cooldown 1
    assert ctl.tick() == "raise_virtual"


def test_autoscale_failed_reshard_falls_through_to_shed():
    srv = _StubServer(lanes=4, hv_cap=8, queue_cap=16)   # hv at ceil
    svc = _StubSvc(srv, devices=2)
    svc.reshard_fails = True
    ctl = _ctl(svc, max_virtual_factor=2.0, device_ladder=[2, 4])
    srv.queue = [None] * 16
    assert ctl.tick() == "shed"        # rollback absorbed, degrade
    assert svc.force_degraded is True


def test_autoscale_in_band_takes_no_action():
    srv = _StubServer(lanes=4, hv_cap=4, queue_cap=16)
    svc = _StubSvc(srv, devices=2)
    ctl = _ctl(svc, device_ladder=[2, 4])
    srv.queue = [None] * 8             # 50%: between watermarks
    assert ctl.tick() is None
    assert ctl.actions["raise_virtual"] == 0


def test_autoscale_actions_render_as_metrics():
    from wasmedge_tpu.obs.metrics import (parse_prometheus,
                                          render_prometheus)

    srv = _StubServer()
    svc = _StubSvc(srv)
    ctl = _ctl(svc, max_virtual_factor=2.0)
    srv.queue = [None] * 16
    ctl.tick()
    m = parse_prometheus(render_prometheus(
        autoscale_actions=dict(ctl.actions)))
    assert m[("wasmedge_autoscale_actions_total",
              frozenset({("action", "raise_virtual")}))] == 1.0
    assert ("wasmedge_autoscale_actions_total",
            frozenset({("action", "shed")})) in m


def test_gateway_constructs_controller_only_when_enabled():
    from wasmedge_tpu.gateway.autoscale import AutoscaleConfig
    from wasmedge_tpu.gateway.service import GatewayService

    off = GatewayService(conf=_conf(), lanes=2,
                         autoscale=AutoscaleConfig(enabled=False))
    try:
        assert off.autoscale is None   # r16 identity by construction
    finally:
        off.shutdown()
    on = GatewayService(conf=_conf(), lanes=2,
                        autoscale=AutoscaleConfig(
                            enabled=True, auto_tick=False))
    try:
        assert on.autoscale is not None
        assert on.autoscale._thread is None   # manual-tick: no timer
        assert on.status()["autoscale"]["enabled"] is True
        assert "wasmedge_autoscale_actions_total" in on.metrics_text()
    finally:
        on.shutdown()
