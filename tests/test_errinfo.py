"""Structured ErrInfo records (reference: errinfo.h:1-299): failures
carry typed context chains the CLI prints under the headline message."""

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errinfo import (
    InfoBoundary, InfoFile, InfoInstruction, InfoMismatch, format_records)
from wasmedge_tpu.common.errors import ErrCode, LoadError, ValidationError
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.validator import Validator
from wasmedge_tpu.utils.builder import ModuleBuilder, uleb


def test_loader_records_offset_and_section():
    # type section whose functype param vector is truncated
    data = (b"\x00asm\x01\x00\x00\x00"
            b"\x01\x04\x01\x60\x02\x7f")  # 2 params declared, 1 present
    with pytest.raises(LoadError) as ei:
        Loader(Configure()).parse_module(data)
    e = ei.value
    assert e.records, "no ErrInfo records attached"
    text = e.formatted()
    assert "byte offset" in text
    assert "section Type" in text


def test_parse_file_records_filename(tmp_path):
    p = tmp_path / "bad.wasm"
    p.write_bytes(b"\x00asm\x02\x00\x00\x00")
    with pytest.raises(LoadError) as ei:
        Loader(Configure()).parse_file(str(p))
    assert any(isinstance(r, InfoFile) for r in ei.value.records)
    assert "bad.wasm" in ei.value.formatted()


def test_validator_records_instruction_context():
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("i64.const", 1), "i32.add"],
                   export="f")
    mod = Loader(Configure()).parse_module(b.build())
    with pytest.raises(ValidationError) as ei:
        Validator(Configure()).validate(mod)
    text = ei.value.formatted()
    assert "in instruction i32.add" in text
    assert "function 0" in text


def test_record_rendering():
    recs = [InfoInstruction("i32.load", pc=7),
            InfoBoundary(0x10000, 4, 0xFFFF),
            InfoMismatch("i32", "f64")]
    out = format_records(recs)
    assert "in instruction i32.load at pc 7" in out
    assert "exceeds limit 0xffff" in out
    assert "expected i32, got f64" in out
