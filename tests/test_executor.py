"""Scalar engine integration tests: control flow, calls, locals/globals,
memory, tables, traps — the reference's test/executor + test/spec role for
the core proposal, with modules built programmatically."""

import pytest

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.runtime.hostfunc import ImportObject
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import run_wasm, single_func, instantiate


class TestControl:
    def test_fib(self):
        from wasmedge_tpu.models import build_fib
        data = build_fib()
        assert run_wasm(data, "fib", [10]) == [55]
        assert run_wasm(data, "fib", [20]) == [6765]

    def test_fac_i64(self):
        from wasmedge_tpu.models import build_fac
        assert run_wasm(build_fac(), "fac", [12]) == [479001600]
        assert run_wasm(build_fac(), "fac", [20]) == [2432902008176640000]

    def test_loop_sum(self):
        from wasmedge_tpu.models import build_loop_sum
        assert run_wasm(build_loop_sum(), "loop_sum", [100]) == [4950]

    def test_block_br_values(self):
        # br carrying a value out of nested blocks
        data = single_func([], ["i32"], [], [
            ("block", "i32"),
            ("block", None),
            ("i32.const", 7), ("br", 1),
            "end",
            ("i32.const", 99),
            "end",
        ])
        assert run_wasm(data, "f") == [7]

    def test_loop_with_params(self):
        # multi-value: loop with a parameter (needs a type index blocktype)
        b = ModuleBuilder()
        ti = b.add_type(["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0),
            ("loop", ti),
            # param on stack: if > 0, decrement and continue
            ("local.set", 0),
            ("local.get", 0), ("i32.const", 0), "i32.gt_s",
            ("if", None),
            ("local.get", 0), ("i32.const", 1), "i32.sub", ("br", 1),
            "end",
            ("local.get", 0),
            "end",
        ], export="f")
        assert run_wasm(b.build(), "f", [5]) == [0]

    def test_br_table(self):
        data = single_func(["i32"], ["i32"], [], [
            ("block", None), ("block", None), ("block", None),
            ("local.get", 0), ("br_table", [0, 1], 2),
            "end", ("i32.const", 10), "return",
            "end", ("i32.const", 20), "return",
            "end", ("i32.const", 30),
        ])
        assert run_wasm(data, "f", [0]) == [10]
        assert run_wasm(data, "f", [1]) == [20]
        assert run_wasm(data, "f", [2]) == [30]
        assert run_wasm(data, "f", [100]) == [30]

    def test_select(self):
        data = single_func(["i32"], ["i32"], [], [
            ("i32.const", 111), ("i32.const", 222), ("local.get", 0), "select",
        ])
        assert run_wasm(data, "f", [1]) == [111]
        assert run_wasm(data, "f", [0]) == [222]

    def test_unreachable_trap(self):
        with pytest.raises(TrapError) as e:
            run_wasm(single_func([], [], [], ["unreachable"]), "f")
        assert e.value.code == ErrCode.Unreachable

    def test_multivalue_return(self):
        data = single_func(["i32"], ["i32", "i32"], [], [
            ("local.get", 0), ("i32.const", 1), "i32.add",
            ("local.get", 0), ("i32.const", 2), "i32.add",
        ])
        assert run_wasm(data, "f", [10]) == [11, 12]

    def test_call_stack_exhaustion(self):
        b = ModuleBuilder()
        b.add_function([], [], [], [("call", 0)], export="f")
        with pytest.raises(TrapError) as e:
            run_wasm(b.build(), "f")
        assert e.value.code == ErrCode.CallStackExhausted


class TestCallIndirect:
    def _mod(self):
        b = ModuleBuilder()
        add = b.add_function(["i32", "i32"], ["i32"], [],
                             [("local.get", 0), ("local.get", 1), "i32.add"])
        sub = b.add_function(["i32", "i32"], ["i32"], [],
                             [("local.get", 0), ("local.get", 1), "i32.sub"])
        other = b.add_function([], [], [], [])
        b.add_table("funcref", 4)
        b.add_active_elem(0, [("i32.const", 0)], [add, sub, other])
        ti = b.add_type(["i32", "i32"], ["i32"])
        b.add_function(["i32", "i32", "i32"], ["i32"], [], [
            ("local.get", 1), ("local.get", 2),
            ("local.get", 0), ("call_indirect", ti, 0),
        ], export="dispatch")
        return b.build()

    def test_dispatch(self):
        data = self._mod()
        assert run_wasm(data, "dispatch", [0, 30, 12]) == [42]
        assert run_wasm(data, "dispatch", [1, 30, 12]) == [18]

    def test_sig_mismatch(self):
        with pytest.raises(TrapError) as e:
            run_wasm(self._mod(), "dispatch", [2, 0, 0])
        assert e.value.code == ErrCode.IndirectCallTypeMismatch

    def test_uninitialized(self):
        with pytest.raises(TrapError) as e:
            run_wasm(self._mod(), "dispatch", [3, 0, 0])
        assert e.value.code == ErrCode.UninitializedElement

    def test_undefined(self):
        with pytest.raises(TrapError) as e:
            run_wasm(self._mod(), "dispatch", [100, 0, 0])
        assert e.value.code == ErrCode.UndefinedElement


class TestGlobals:
    def test_global_get_set(self):
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", 10)])
        b.add_function([], ["i32"], [], [
            ("global.get", 0), ("i32.const", 5), "i32.add", ("global.set", 0),
            ("global.get", 0),
        ], export="f")
        assert run_wasm(b.build(), "f") == [15]

    def test_imported_global_in_init(self):
        from wasmedge_tpu.runtime.instance import GlobalInstance
        from wasmedge_tpu.loader.ast import GlobalType
        from wasmedge_tpu.common.types import ValType
        imp = ImportObject("env")
        imp.add_global("base", GlobalInstance(GlobalType(ValType.I32, False), 100))
        b = ModuleBuilder()
        b.import_global("env", "base", "i32", mutable=False)
        b.add_global("i32", False, [("global.get", 0)])
        b.add_function([], ["i32"], [], [("global.get", 1)], export="f")
        assert run_wasm(b.build(), "f", imports=[imp]) == [100]


class TestMemory:
    def test_load_store(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function(["i32", "i32"], ["i32"], [], [
            ("local.get", 0), ("local.get", 1), ("i32.store", 2, 0),
            ("local.get", 0), ("i32.load", 2, 0),
        ], export="f")
        assert run_wasm(b.build(), "f", [100, -123]) == [-123]

    def test_subword_and_offset(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function([], ["i32", "i32", "i64"], [], [
            ("i32.const", 8), ("i32.const", 0x80FF), ("i32.store", 2, 0),
            ("i32.const", 8), ("i32.load8_s", 0, 0),    # -1
            ("i32.const", 8), ("i32.load8_u", 0, 1),    # 0x80
            ("i32.const", 0), ("i64.load32_u", 2, 8),   # 0x80FF via offset
        ], export="f")
        assert run_wasm(b.build(), "f") == [-1, 0x80, 0x80FF]

    def test_oob_trap(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function([], ["i32"], [], [
            ("i32.const", 65533), ("i32.load", 2, 0),
        ], export="f")
        with pytest.raises(TrapError) as e:
            run_wasm(b.build(), "f")
        assert e.value.code == ErrCode.MemoryOutOfBounds

    def test_grow_and_size(self):
        b = ModuleBuilder()
        b.add_memory(1, 3)
        b.add_function([], ["i32", "i32", "i32", "i32"], [], [
            "memory.size",
            ("i32.const", 1), "memory.grow",
            ("i32.const", 5), "memory.grow",  # beyond max -> -1
            "memory.size",
        ], export="f")
        assert run_wasm(b.build(), "f") == [1, 1, -1, 2]

    def test_active_data_init(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_active_data(0, [("i32.const", 4)], b"\x2a\x00\x00\x00")
        b.add_function([], ["i32"], [], [
            ("i32.const", 4), ("i32.load", 2, 0),
        ], export="f")
        assert run_wasm(b.build(), "f") == [42]

    def test_bulk_fill_copy(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function([], ["i32"], [], [
            ("i32.const", 0), ("i32.const", 0xAB), ("i32.const", 8), "memory.fill",
            ("i32.const", 16), ("i32.const", 0), ("i32.const", 4), "memory.copy",
            ("i32.const", 16), ("i32.load", 2, 0),
        ], export="f")
        assert run_wasm(b.build(), "f") == [-0x54545455]  # 0xABABABAB signed

    def test_memory_init_passive(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.data_count = 1
        b.add_passive_data(b"\x01\x02\x03\x04")
        b.add_function([], ["i32"], [], [
            ("i32.const", 20), ("i32.const", 1), ("i32.const", 2), ("memory.init", 0),
            ("i32.const", 20), ("i32.load16_u", 0, 0),
        ], export="f")
        assert run_wasm(b.build(), "f") == [0x0302]


class TestHostFuncs:
    def test_host_call(self):
        seen = []

        def logger(mem, x):
            seen.append(x)
            return x * 2

        imp = ImportObject("env")
        imp.add_py_func("double", logger, ["i32"], ["i32"])
        b = ModuleBuilder()
        f = b.import_func("env", "double", ["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("call", f),
            ("local.get", 0), ("call", f),
            "i32.add",
        ], export="f")
        assert run_wasm(b.build(), "f", [21], imports=[imp]) == [84]
        assert seen == [21, 21]

    def test_host_memory_access(self):
        def peek(mem, addr):
            return mem.load(addr, 4, False)

        imp = ImportObject("env")
        imp.add_py_func("peek", peek, ["i32"], ["i32"])
        b = ModuleBuilder()
        f = b.import_func("env", "peek", ["i32"], ["i32"])
        b.add_memory(1)
        b.add_function([], ["i32"], [], [
            ("i32.const", 12), ("i32.const", 777), ("i32.store", 2, 0),
            ("i32.const", 12), ("call", f),
        ], export="f")
        assert run_wasm(b.build(), "f", imports=[imp]) == [777]

    def test_unknown_import(self):
        from wasmedge_tpu.common.errors import InstantiationError
        b = ModuleBuilder()
        b.import_func("nosuch", "fn", [], [])
        b.add_function([], [], [], [], export="f")
        with pytest.raises(InstantiationError):
            instantiate(b.build())


class TestCrossModule:
    def test_import_func_from_registered_module(self):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.executor import Executor
        from wasmedge_tpu.loader import Loader
        from wasmedge_tpu.runtime.store import StoreManager
        from wasmedge_tpu.validator import Validator

        conf = Configure()
        store = StoreManager()
        ex = Executor(conf)

        lib = ModuleBuilder()
        lib.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.const", 3), "i32.mul",
        ], export="triple")
        libmod = Validator(conf).validate(Loader(conf).parse_module(lib.build()))
        ex.register_module(store, libmod, "lib")

        app = ModuleBuilder()
        f = app.import_func("lib", "triple", ["i32"], ["i32"])
        app.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("call", f), ("call", f),
        ], export="nine_x")
        appmod = Validator(conf).validate(Loader(conf).parse_module(app.build()))
        inst = ex.instantiate(store, appmod)
        assert ex.invoke(store, inst.find_func("nine_x"), [7]) == [63]


class TestStartAndStats:
    def test_start_function(self):
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", 0)])
        s = b.add_function([], [], [], [("i32.const", 99), ("global.set", 0)])
        b.set_start(s)
        b.add_function([], ["i32"], [], [("global.get", 0)], export="f")
        assert run_wasm(b.build(), "f") == [99]

    def test_statistics_and_gas(self):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.common.statistics import Statistics
        from wasmedge_tpu.executor import Executor
        from wasmedge_tpu.loader import Loader
        from wasmedge_tpu.runtime.store import StoreManager
        from wasmedge_tpu.validator import Validator
        from wasmedge_tpu.models import build_fib

        conf = Configure()
        conf.statistics.instr_counting = True
        conf.statistics.cost_measuring = True
        stat = Statistics(conf)
        mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
        store = StoreManager()
        ex = Executor(conf, stat)
        inst = ex.instantiate(store, mod)
        ex.invoke(store, inst.find_func("fib"), [10])
        assert stat.instr_count > 100
        # gas limit enforcement
        stat2 = Statistics(conf)
        stat2.set_cost_limit(50)
        ex2 = Executor(conf, stat2)
        with pytest.raises(TrapError) as e:
            ex2.invoke(store, inst.find_func("fib"), [15])
        assert e.value.code == ErrCode.CostLimitExceeded
