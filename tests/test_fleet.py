"""Multi-host fleet federation (wasmedge_tpu/fleet/, marker `serve`).

Pins the r16 acceptance contract deterministically:

  - peer-replicated module store: a module registered on gateway A is
    servable on gateway B after a sync tick, results bit-identical
  - consistent routing: rendezvous ownership is deterministic and
    moves only the dead peer's keys; a request routed to a SUSPECT
    owner refuses retryably (PeerSuspect + Retry-After, pinned again
    in test_gateway.py's taxonomy suite)
  - failover: a killed peer's replicated journal is adopted by the
    survivor — resolved ids replay exactly-once from the replicated
    result cache, unresolved ids re-queue at-least-once under their
    ORIGINAL ids
  - peer partition / heartbeat flap: the suspect→dead state machine
    under the peer_send/peer_recv/peer_heartbeat fault seams
    (testing/faults.partition_schedule), with exponential probe
    backoff and per-incarnation adoption
  - cross-host lane migration: a parked vlane's SwapStore entry ships
    hash-verified and continues on the peer bit-identically; a
    mid-migration peer failure re-adopts the lane locally (a request
    is never lost)
  - solo-mode fallback: a fleet with no peers is bit-identical to the
    non-federated gateway (no routing, no replication, no id-space
    rebase)

Determinism discipline: every fleet controller here runs with
auto_tick=False — tests drive tick()/poll_forwards() by hand, so seam
arrival counters never race a timer.  Speed discipline: tier-1 fast —
one shared live pair (module fixture) carries every test that does not
kill a peer; kill tests build their own minimal pair at the same tiny
geometry under the shared JAX compile cache.
"""

import base64
import json
import tempfile
import time
from http.client import HTTPConnection

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import rejection_info
from wasmedge_tpu.fleet import (
    FleetConfig,
    PeerSuspect,
    PeerUnreachable,
    rendezvous_owner,
    rendezvous_ranked,
)
from wasmedge_tpu.gateway import Gateway, GatewayService
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.testing.faults import (
    Fault,
    FaultInjector,
    partition_schedule,
)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="fleet-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(hv=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    if hv:
        conf.hv.max_virtual_lanes = 8
    return conf


def _fleet_cfg(peers=(), **kw):
    kw.setdefault("auto_tick", False)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("suspect_after", 2)
    kw.setdefault("dead_after", 3)
    return FleetConfig(peers=peers, **kw)


def _pair(hv=False, fib_on_a=True, faults_b=None):
    """Gateway A (no peers configured; learns B from its inbound
    heartbeat) + gateway B federated with A, both manual-tick."""
    svc_a = GatewayService(conf=_conf(hv=hv), lanes=2,
                           fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    if fib_on_a:
        svc_a.register_module("fib", wasm_bytes=build_fib(),
                              source="boot")
    svc_b = GatewayService(conf=_conf(hv=hv), lanes=2,
                           fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]),
                           faults=faults_b)
    gw_b = Gateway(svc_b, port=0).start()
    return gw_a, gw_b


def rpc(gw, method, path, body=None, headers=None, timeout=120.0):
    c = HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        data = json.dumps(body).encode() if isinstance(body, dict) \
            else body
        c.request(method, path, body=data, headers=headers or {})
        r = c.getresponse()
        raw = r.read()
        hdrs = dict(r.getheaders())
    finally:
        c.close()
    try:
        doc = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        doc = raw.decode(errors="replace")
    return r.status, doc, hdrs


def _drain(svc, reqs, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if svc.fleet is not None:
            svc.fleet.poll_forwards()
        if all(r.future.done for r in reqs):
            return
        time.sleep(0.02)
    raise TimeoutError(
        f"undone: {[r.id for r in reqs if not r.future.done]}")


@pytest.fixture(scope="module")
def fleet_pair(_compile_cache):
    """The shared live pair (hv on, fib registered on A).  Tests must
    stay order-independent: read state, never assume a peer's liveness
    view beyond what they themselves tick."""
    gw_a, gw_b = _pair(hv=True)
    gw_b.service.fleet.tick()   # learn manifest + sync fib onto B
    gw_b.service.fleet.tick()
    yield gw_a, gw_b
    gw_b.shutdown()
    gw_a.shutdown()


# ---------------------------------------------------------------------------
# routing: deterministic ownership, minimal churn
# ---------------------------------------------------------------------------
def test_rendezvous_owner_deterministic_and_minimal_churn():
    peers = ["10.0.0.1:8080", "10.0.0.2:8080", "10.0.0.3:8080"]
    owners = {k: rendezvous_owner(k, peers) for k in range(500)}
    # deterministic: same inputs, same owners
    assert owners == {k: rendezvous_owner(k, peers) for k in range(500)}
    # every peer owns a nonempty share
    assert set(owners.values()) == set(peers)
    # removing one peer moves ONLY its keys (each to its runner-up)
    dead = peers[1]
    survivors = [p for p in peers if p != dead]
    for k, owner in owners.items():
        new = rendezvous_owner(k, survivors)
        if owner != dead:
            assert new == owner, "a survivor's key must never move"
        else:
            assert new == rendezvous_ranked(k, peers)[1]
    assert rendezvous_owner(7, []) is None
    assert rendezvous_owner(7, ["only"]) == "only"


# ---------------------------------------------------------------------------
# peer-replicated module store
# ---------------------------------------------------------------------------
def test_module_replication_makes_peer_servable(fleet_pair):
    gw_a, gw_b = fleet_pair
    svc_b = gw_b.service
    # the fixture's sync ticks replicated fib (registered only on A)
    assert "fib" in svc_b.registry.names
    rm = svc_b.registry.get("fib")
    assert rm.sha256 == gw_a.service.registry.get("fib").sha256
    assert rm.source.startswith("fleet/")
    # servable on B with bit-identical results: force the LOCAL path
    # (routing is exercised separately) and compare against the oracle
    req = svc_b._submit_local("fib", [11], module="fib")
    _drain(svc_b, [req])
    assert req.future.result(0)[0] == _fib(11)
    assert svc_b.fleet.counters["modules_synced"] >= 1
    # idempotent: another tick re-fetches nothing
    before = svc_b.fleet.counters["modules_synced"]
    svc_b.fleet.tick()
    assert svc_b.fleet.counters["modules_synced"] == before


def test_module_blob_route_serves_verified_bytes(fleet_pair):
    import hashlib

    gw_a, _ = fleet_pair
    sha = gw_a.service.registry.get("fib").sha256
    c = HTTPConnection(gw_a.host, gw_a.port, timeout=30.0)
    try:
        c.request("GET", f"/v1/fleet/modules/{sha}")
        r = c.getresponse()
        data = r.read()
        assert r.status == 200
    finally:
        c.close()
    assert hashlib.sha256(data).hexdigest() == sha
    st, _, _ = rpc(gw_a, "GET", "/v1/fleet/modules/" + "0" * 64)
    assert st == 404


# ---------------------------------------------------------------------------
# consistent routing + forwarded execution
# ---------------------------------------------------------------------------
def test_routing_forwards_to_owner_and_resolves(fleet_pair):
    gw_a, gw_b = fleet_pair
    svc_b = gw_b.service
    reqs = [svc_b.submit("fib", [9 + (i % 3)], module="fib")
            for i in range(6)]
    _drain(svc_b, reqs)
    for r in reqs:
        assert r.future.result(0)[0] == _fib(r.args[0])
    # with both peers alive, rendezvous split some ids to A: the
    # forward path actually ran (deterministic given the ids drawn)
    ids = [r.id for r in reqs]
    members = sorted(svc_b.fleet.members())
    owners = {rid: rendezvous_owner(rid, members) for rid in ids}
    expected_remote = sum(1 for o in owners.values()
                          if o != svc_b.fleet.self_id)
    assert svc_b.fleet.counters["forwards"] >= min(expected_remote, 1)


def test_execute_route_is_idempotent(fleet_pair):
    gw_a, gw_b = fleet_pair
    body = {"id": 987654321001, "edge": "test-edge", "module": "fib",
            "func": "fib", "args": [8], "tenant": "default"}
    st1, d1, _ = rpc(gw_a, "POST", "/v1/fleet/execute", body=body)
    st2, d2, _ = rpc(gw_a, "POST", "/v1/fleet/execute", body=body)
    assert st1 == 200 and d1["ok"] and d1["request_id"] == body["id"]
    assert st2 == 200 and d2.get("dedup"), \
        "a retried forward must acknowledge, not double-queue"
    st, doc = None, {"status": "pending"}
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline \
            and doc.get("status") == "pending":
        st, doc, _ = rpc(gw_a, "GET", f"/v1/requests/{body['id']}")
        time.sleep(0.02)
    assert st == 200 and doc["ok"] and doc["result"] == [_fib(8)]


# ---------------------------------------------------------------------------
# suspect→dead state machine under deterministic partitions
# ---------------------------------------------------------------------------
def test_partition_drives_suspect_then_dead_then_recovery():
    inj = FaultInjector(partition_schedule([("B", "A")], at=0, times=3))
    svc_a = GatewayService(conf=_conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=_conf(), lanes=2, faults=inj,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"], self_id="B"))
    gw_b = Gateway(svc_b, port=0).start()
    # the partition matches dst by PEER ID (A's id is its address)
    for f in inj.faults:
        f.match = {"src": "B", "dst": f"{gw_a.host}:{gw_a.port}"}
    try:
        fl = svc_b.fleet
        pid = f"{gw_a.host}:{gw_a.port}"
        fl.tick()   # miss 1: still alive (below suspect_after=2)
        assert fl.peer_states()[pid]["state"] == "alive"
        fl.tick()   # miss 2 -> suspect
        assert fl.peer_states()[pid]["state"] == "suspect"
        fl.tick()   # miss 3 -> dead (dead_after=3) + adoption trigger
        assert fl.peer_states()[pid]["state"] == "dead"
        # partition healed (times=3): next probe recovers the peer
        fl.tick()
        assert fl.peer_states()[pid]["state"] == "alive"
        assert fl.peer_states()[pid]["transitions"] >= 3
        assert inj.fired == 3
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


def test_probe_backoff_gates_dead_peer_probes():
    """A missing peer's probes back off exponentially: with a real
    backoff base, consecutive ticks inside the window do NOT probe
    (the streak only advances when a probe actually fires)."""
    inj = FaultInjector(partition_schedule([("B", "dead:1")], at=0,
                                           times=1000))
    svc_b = GatewayService(
        conf=_conf(), lanes=2, faults=inj,
        fleet=_fleet_cfg(["dead:1"], self_id="B",
                         backoff_base_s=30.0))
    gw_b = Gateway(svc_b, port=0).start()
    try:
        fl = svc_b.fleet
        fl.tick()
        assert inj.counts.get("peer_send") == 1
        st = fl.peer_states()["dead:1"]
        assert st["streak"] == 1
        for _ in range(5):   # all inside the 30s backoff window
            fl.tick()
        assert inj.counts.get("peer_send") == 1, \
            "backoff must gate re-probes of a missing peer"
        assert fl.peer_states()["dead:1"]["streak"] == 1
    finally:
        gw_b.shutdown()


def test_heartbeat_flap_never_reaches_dead_and_never_adopts():
    """A flapping link (every probe window: one miss, one success)
    oscillates alive<->alive/suspect but never crosses dead_after, so
    failover adoption never fires on a flap."""
    faults = []
    for k in range(4):   # misses at probe arrivals 0, 2, 4, 6
        faults.append(Fault(point="peer_heartbeat", at=2 * k,
                            match={"src": "B"}))
    inj = FaultInjector(faults)
    svc_a = GatewayService(conf=_conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=_conf(), lanes=2, faults=inj,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"], self_id="B"))
    gw_b = Gateway(svc_b, port=0).start()
    try:
        fl = svc_b.fleet
        pid = f"{gw_a.host}:{gw_a.port}"
        states = []
        for _ in range(8):
            fl.tick()
            states.append(fl.peer_states()[pid]["state"])
        assert "dead" not in states
        assert fl.counters["adoptions"] == 0
        assert fl.counters["heartbeats_ok"] >= 3
        assert fl.counters["heartbeats_missed"] >= 3
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


# ---------------------------------------------------------------------------
# suspect-owner rejection: the machine-readable retryable contract
# ---------------------------------------------------------------------------
def test_suspect_owner_rejection_is_retryable_with_retry_after():
    svc_a = GatewayService(conf=_conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=_conf(), lanes=2,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    svc_b.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        fl = svc_b.fleet
        pid = f"{gw_a.host}:{gw_a.port}"
        fl.tick()                    # alive handshake
        gw_a.kill()                  # A stops answering
        fl.tick()
        fl.tick()                    # 2 misses -> suspect (not dead)
        assert fl.peer_states()[pid]["state"] == "suspect"
        # some id will route to the suspect owner within a few draws
        saw = None
        for _ in range(16):
            try:
                r = svc_b.submit("fib", [5], module="fib")
                r.future.wait(120.0)
            except PeerSuspect as e:
                saw = e
                break
        assert saw is not None, "no submission routed to the suspect " \
                                "owner in 16 draws (improbable)"
        info = rejection_info(saw)
        assert info["retryable"] is True
        assert info["retry_after_s"] > 0
        assert info["detail"] == "peer_suspect"
        # ... and on the wire: 503 + Retry-After + the same body, never
        # a bare string (pinned again in test_gateway.py)
        saw_http = None
        for _ in range(16):
            st, doc, hdrs = rpc(gw_b, "POST", "/v1/invoke",
                                body={"module": "fib", "func": "fib",
                                      "args": [5]})
            if st == 503 and isinstance(doc, dict) \
                    and doc.get("err", {}).get("detail") \
                    == "peer_suspect":
                saw_http = (st, doc, hdrs)
                break
        assert saw_http is not None
        st, doc, hdrs = saw_http
        assert doc["err"]["retryable"] is True
        assert "Retry-After" in hdrs
    finally:
        gw_b.shutdown()
        # gw_a already killed


# ---------------------------------------------------------------------------
# failover: replicated-journal adoption
# ---------------------------------------------------------------------------
def test_peer_death_adopts_journal_exactly_once_and_at_least_once():
    gw_a, gw_b = _pair(hv=False)
    svc_a, svc_b = gw_a.service, gw_b.service
    try:
        svc_b.fleet.tick()
        svc_b.fleet.tick()
        assert "fib" in svc_b.registry.names
        # 1) a request RESOLVED on A before the kill: its outcome rides
        #    the replicated result cache
        done = svc_a._submit_local("fib", [10], module="fib")
        assert done.future.wait(120.0)
        svc_a.finalize(done)     # journal + replicate the resolution
        # 2) a request still UNRESOLVED at the kill (a fib the tiny
        #    server won't finish instantly)
        pend = svc_a._submit_local("fib", [20], module="fib")
        rid_done, rid_pend = done.id, pend.id
        # both ids are in B's replica of A (strict accept replication +
        # the finalize push)
        pid = svc_a.fleet.self_id
        replica = svc_b.fleet.peers[pid].replica
        assert replica is not None
        assert rid_pend in [e["id"] for e in replica["unresolved"]]
        assert rid_done in [e["id"] for e in replica["resolved"]]
        sub_before = svc_b.current.server.counters["submitted"]
        gw_a.kill()
        for _ in range(4):   # miss, miss->suspect, miss->dead+adopt
            svc_b.fleet.tick()
        assert svc_b.fleet.peer_states()[pid]["state"] == "dead"
        # exactly-once: the resolved id answers from the replicated
        # cache WITHOUT re-executing (no new server submission for it)
        st, req = svc_b.request_state(rid_done)
        assert st == "ok" and req.future.done
        assert req.future.result(0)[0] == _fib(10)
        # at-least-once: the unresolved id re-queued under its
        # ORIGINAL id and completes on the survivor
        st, req2 = svc_b.request_state(rid_pend)
        assert st == "ok"
        assert req2.future.wait(180.0)
        assert req2.future.result(0)[0] == _fib(20)
        assert svc_b.current.server.counters["submitted"] \
            == sub_before + 1, "only the unresolved id re-executes"
        assert svc_b.fleet.counters["adoptions"] == 1
        assert svc_b.fleet.counters["adoptions_replayed"] >= 1
        # the adoption is pinned in the fleet metrics too
        from wasmedge_tpu.obs.metrics import parse_prometheus

        m = parse_prometheus(svc_b.metrics_text())
        assert m[("wasmedge_fleet_adoptions_total",
                  frozenset())] == 1.0
        assert m[("wasmedge_fleet_peers",
                  frozenset({("state", "dead")}))] == 1.0
    finally:
        gw_b.shutdown()


def test_edge_requeues_its_own_forward_when_owner_dies():
    """A forward whose OWNER dies re-queues locally at the edge under
    the original id — and the dead owner's replica entry for it is
    skipped by adoption (the edge is alive and handles its own)."""
    gw_a, gw_b = _pair(hv=False)
    svc_a, svc_b = gw_a.service, gw_b.service
    try:
        svc_b.fleet.tick()
        svc_b.fleet.tick()
        pid = svc_a.fleet.self_id
        # draw submissions until one forwards to A.  The work itself is
        # tiny — what keeps the forward UNRESOLVED at the edge is that
        # nobody calls poll_forwards() before the kill, so even if A
        # finished it, B never fetched the outcome and must re-execute
        # (the at-least-once scope of cross-host re-queue)
        fw = None
        for _ in range(16):
            r = svc_b.submit("fib", [12], module="fib")
            if r.id in svc_b.fleet._forwards:
                fw = r
                break
        assert fw is not None, "no draw routed to A in 16 tries"
        gw_a.kill()
        for _ in range(4):
            svc_b.fleet.tick()
        assert svc_b.fleet.peer_states()[pid]["state"] == "dead"
        assert fw.id not in svc_b.fleet._forwards
        assert svc_b.fleet.counters["forward_requeues"] >= 1
        _drain(svc_b, [fw], timeout_s=180.0)
        assert fw.future.result(0)[0] == _fib(12)
    finally:
        gw_b.shutdown()


# ---------------------------------------------------------------------------
# cross-host lane migration
# ---------------------------------------------------------------------------
def _park_one(svc, n=14, count=6):
    """Oversubscribe until some vlane is SWAPPED; returns (reqs, rid)."""
    reqs = [svc._submit_local("fib", [n], module="fib")
            for _ in range(count)]
    server = svc.current.server
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        swapped = server.list_swapped()
        if swapped:
            return reqs, swapped[0]
        time.sleep(0.01)
    raise TimeoutError("no vlane parked")


def test_migration_roundtrip_bit_identical(fleet_pair):
    gw_a, gw_b = fleet_pair
    svc_a, svc_b = gw_a.service, gw_b.service
    # B must see A alive to accept its relay polls; A learned B already
    reqs, rid = _park_one(svc_a)
    out = svc_a.fleet.migrate_out(rid, svc_b.fleet.self_id)
    assert out["ok"] and out["request_id"] == rid
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        svc_a.fleet.poll_forwards()
        if all(r.future.done for r in reqs):
            break
        time.sleep(0.02)
    for r in reqs:
        assert r.future.done
        # bit-identical to the unmigrated oracle — the migrated lane's
        # mid-run state continued on B through the jitted column-set
        # install and produced the same cells
        assert r.future.result(0)[0] == _fib(14)
    assert svc_a.fleet.counters["migrations_out"] >= 1
    assert svc_b.fleet.counters["migrations_in"] >= 1
    # the migrated id is pollable on BOTH ends with the same outcome
    st_a, doc_a, _ = rpc(gw_a, "GET", f"/v1/requests/{rid}")
    st_b, doc_b, _ = rpc(gw_b, "GET", f"/v1/requests/{rid}")
    assert st_a == st_b == 200
    assert doc_a["result"] == doc_b["result"] == [_fib(14)]
    from wasmedge_tpu.obs.metrics import parse_prometheus

    m = parse_prometheus(svc_a.metrics_text())
    assert m[("wasmedge_fleet_migrations_total",
              frozenset({("direction", "out")}))] >= 1.0


def test_mid_migration_peer_death_readopts_locally():
    """The receiver dies before acking the migration: the vlane is
    re-adopted locally exactly as exported and the request completes
    here — never lost, never double-resolved."""
    gw_a, gw_b = _pair(hv=True)
    svc_a, svc_b = gw_a.service, gw_b.service
    try:
        svc_b.fleet.tick()
        svc_b.fleet.tick()
        # A must know B to migrate to it
        assert svc_b.fleet.self_id in svc_a.fleet.peers
        reqs, rid = _park_one(svc_a)
        gw_b.kill()   # the receiver is gone; A has not noticed yet
        with pytest.raises((PeerUnreachable, KeyError)):
            svc_a.fleet.migrate_out(rid, svc_b.fleet.self_id)
        assert svc_a.fleet.counters["migrations_out"] == 0
        # the lane is back (swapped or re-queued) and completes locally
        _drain(svc_a, reqs, timeout_s=180.0)
        for r in reqs:
            assert r.future.result(0)[0] == _fib(14)
    finally:
        gw_a.shutdown()


def test_migrate_corrupt_blob_rejected_by_hash(fleet_pair):
    """The receiving side verifies payload-vs-key BEFORE touching any
    server state: a tampered blob is refused machine-readably."""
    _, gw_b = fleet_pair
    body = {"edge": "evil", "entry": {
        "id": 424242424242, "func": "fib:fib", "args": [5],
        "tenant": "default", "key": "0" * 64, "stdout_pos": 0},
        "blob_b64": base64.b64encode(b"not the keyed bytes").decode()}
    st, doc, _ = rpc(gw_b, "POST", "/v1/fleet/migrate", body=body)
    assert st >= 400
    state, _req = gw_b.service.request_state(424242424242)
    assert state == "unknown"


def _await_mod() -> bytes:
    """wait(n) -> await_event(buf=64, len=8, nwritten=32); returns
    first-payload-word + n (proves delivery and guest-state survival
    across the migration)."""
    from wasmedge_tpu.utils.builder import ModuleBuilder

    b = ModuleBuilder()
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i64"], ["i64"], [], [
        ("i32.const", 64), ("i32.const", 8), ("i32.const", 32),
        ("call", 0), "drop",
        ("i32.const", 64), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="wait")
    return b.build()


def test_parked_session_migrates_cross_host_and_wakes_bit_identically():
    """An effects PARKED SESSION (guest suspended on await_event, r23)
    ships through the SAME hash-verified migration path as a swapped
    vlane: B adopts it parked (zero resident lanes burned), a wake
    delivered to B's wire resolves it bit-identically to a
    never-migrated oracle, and the id is pollable on both ends."""
    import struct

    def conf():
        c = _conf()
        c.effects.suspend = True
        return c

    svc_a = GatewayService(conf=conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_a.register_module("awaitmod", wasm_bytes=_await_mod(),
                          source="boot")
    svc_b = GatewayService(
        conf=conf(), lanes=2,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    try:
        svc_b.fleet.tick()   # learn manifest + replicate awaitmod
        svc_b.fleet.tick()
        assert "awaitmod" in svc_b.registry.names
        payload = struct.pack("<I", 900)

        # never-migrated oracle on A: park -> wake -> resolve
        oracle = svc_a._submit_local("wait", [5], module="awaitmod")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if oracle.id in svc_a.current.server.list_swapped():
                break
            time.sleep(0.01)
        else:
            raise TimeoutError("oracle never parked")
        svc_a.wake(oracle.id, payload)
        assert svc_a.wait(oracle, timeout_s=120.0)
        want = oracle.future.result(0)
        assert want == [905]

        # the migrated run: park on A, ship to B, wake on B's wire
        req = svc_a._submit_local("wait", [5], module="awaitmod")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if req.id in svc_a.current.server.list_swapped():
                break
            time.sleep(0.01)
        else:
            raise TimeoutError("session never parked")
        out = svc_a.fleet.migrate_out(req.id, svc_b.fleet.self_id)
        assert out["ok"] and out["request_id"] == req.id
        # B holds it PARKED (not running); A no longer does
        assert req.id in svc_b.current.server.list_swapped()
        assert req.id not in svc_a.current.server.list_swapped()
        assert svc_b.status()["sessions"]["parked"] == 1
        st, doc, _ = rpc(gw_b, "POST",
                         f"/v1/requests/{req.id}/wake", body=payload)
        assert st == 202 and doc["state"] == "parked"
        # the relay poll resolves the sender-side future bit-identically
        _drain(svc_a, [req], timeout_s=180.0)
        assert req.future.result(0) == want
        assert svc_a.fleet.counters["migrations_out"] >= 1
        assert svc_b.fleet.counters["migrations_in"] >= 1
        # pollable on BOTH ends with the same outcome
        st_a, doc_a, _ = rpc(gw_a, "GET", f"/v1/requests/{req.id}")
        st_b, doc_b, _ = rpc(gw_b, "GET", f"/v1/requests/{req.id}")
        assert st_a == st_b == 200
        assert doc_a["result"] == doc_b["result"] == want
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


def test_wake_forwards_to_parked_sessions_owner():
    """Fleet-routed wakes (r24): POST /v1/requests/<id>/wake hitting a
    member that does NOT hold the session forwards to the id's
    rendezvous owner over the r16 routing table — any member is a
    valid wake edge, and the forwarded wake resolves the session
    bit-identically to a locally-delivered one."""
    import struct

    def conf():
        c = _conf()
        c.effects.suspend = True
        return c

    svc_a = GatewayService(conf=conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_a.register_module("awaitmod", wasm_bytes=_await_mod(),
                          source="boot")
    svc_b = GatewayService(
        conf=conf(), lanes=2,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    try:
        svc_b.fleet.tick()   # learn manifest + replicate awaitmod
        svc_b.fleet.tick()
        payload = struct.pack("<I", 900)
        # park sessions on A until one's id rendezvous-routes to A in
        # B's view (ids are random draws; a handful suffices)
        req = None
        for _ in range(12):
            r = svc_a._submit_local("wait", [5], module="awaitmod")
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if r.id in svc_a.current.server.list_swapped():
                    break
                time.sleep(0.01)
            else:
                raise TimeoutError("session never parked")
            if rendezvous_owner(r.id, svc_b.fleet.members()) \
                    == svc_a.fleet.self_id:
                req = r
                break
            svc_a.wake(r.id, payload)   # resolve the unused draw
            _drain(svc_a, [r], timeout_s=120.0)
        assert req is not None, "no id routed to A in 12 draws " \
                                "(improbable)"
        # the wake lands on B's wire; B holds nothing for this id
        st, doc, _ = rpc(gw_b, "POST",
                         f"/v1/requests/{req.id}/wake", body=payload)
        assert st == 202 and doc["ok"]
        assert doc["state"] == "parked"   # the OWNER's resolution
        assert doc["owner"] == svc_a.fleet.self_id
        _drain(svc_a, [req], timeout_s=180.0)
        assert req.future.result(0) == [905]
        assert svc_b.fleet.counters["wakes_forwarded"] == 1
        assert svc_a.fleet.counters["wakes_received"] == 1
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


def test_wake_to_suspect_owner_is_retryable_503():
    """A wake whose owner is SUSPECT refuses retryably (503 +
    Retry-After, detail peer_suspect) instead of guessing: the wake is
    still queued locally at-least-once, and the client retries once
    the owner's probes recover."""
    def conf():
        c = _conf()
        c.effects.suspend = True
        return c

    svc_a = GatewayService(conf=conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_b = GatewayService(
        conf=conf(), lanes=2,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    svc_b.register_module("awaitmod", wasm_bytes=_await_mod(),
                          source="boot")
    try:
        fl = svc_b.fleet
        pid = f"{gw_a.host}:{gw_a.port}"
        fl.tick()                    # alive handshake
        gw_a.kill()                  # A stops answering
        fl.tick()
        fl.tick()                    # 2 misses -> suspect (not dead)
        assert fl.peer_states()[pid]["state"] == "suspect"
        rid = next(k for k in range(10_000, 10_200)
                   if rendezvous_owner(k, fl.members()) == pid)
        st, doc, hdrs = rpc(gw_b, "POST",
                            f"/v1/requests/{rid}/wake", body=b"")
        assert st == 503
        assert doc["err"]["retryable"] is True
        assert doc["err"]["detail"] == "peer_suspect"
        assert "Retry-After" in hdrs
        assert fl.counters["suspect_rejections"] >= 1
        assert fl.counters["wakes_forwarded"] == 0
    finally:
        gw_b.shutdown()


# ---------------------------------------------------------------------------
# solo-mode fallback
# ---------------------------------------------------------------------------
def test_solo_fleet_bit_identical_to_plain_gateway():
    """A fleet with NO peers must be the non-federated gateway:
    identical results, no id-space rebase, no routing, no replication,
    no background thread, no fleet health check."""
    from wasmedge_tpu.serve.queue import peek_request_ids

    plain = GatewayService(conf=_conf(), lanes=2)
    plain.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gw_p = Gateway(plain, port=0).start()
    solo = GatewayService(conf=_conf(), lanes=2,
                          fleet=FleetConfig(peers=[]))
    solo.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gw_s = Gateway(solo, port=0).start()
    try:
        assert solo.fleet._thread is None
        before = peek_request_ids()
        r_p = [plain.submit("fib", [n], module="fib") for n in (6, 7, 8)]
        r_s = [solo.submit("fib", [n], module="fib") for n in (6, 7, 8)]
        for rp, rs in zip(r_p, r_s):
            assert rp.future.wait(120.0) and rs.future.wait(120.0)
            assert rp.future.result(0) == rs.future.result(0)
        # no id-space rebase: solo ids continue the plain sequence
        # (a peered fleet rebases to a hashed base; solo must NOT)
        assert peek_request_ids() <= before + 6
        assert solo.fleet.counters["forwards"] == 0
        assert solo.fleet.counters["heartbeats_ok"] == 0
        # solo adds no fleet health check (bit-identical health shape)
        assert "fleet" not in solo.health()["checks"]
        assert "fleet" not in plain.health()["checks"]
    finally:
        gw_s.shutdown()
        gw_p.shutdown()


# ---------------------------------------------------------------------------
# fleet health + metrics
# ---------------------------------------------------------------------------
def test_fleet_health_degrades_on_missing_peer_and_sheds():
    from wasmedge_tpu.gateway import GatewayTenants
    from wasmedge_tpu.gateway.health import ShedLoad

    tenants = GatewayTenants.from_dict({
        "tenants": {"gold": {"weight": 4.0}, "free": {"weight": 0.5}}})
    inj = FaultInjector(partition_schedule([("B", "dead:1")], at=0,
                                           times=1000))
    svc = GatewayService(conf=_conf(), lanes=2, tenants=tenants,
                         faults=inj,
                         fleet=_fleet_cfg(["dead:1"], self_id="B"))
    gw = Gateway(svc, port=0).start()
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        h = svc.health()
        assert h["checks"]["fleet"]["ok"]          # optimistic boot
        svc.fleet.tick()
        svc.fleet.tick()                            # -> suspect
        h = svc.health()
        assert not h["checks"]["fleet"]["ok"]
        assert h["status"] == "degraded"
        # fleet-wide degradation sheds the lowest weight tier at the
        # edge, retryably — paying traffic keeps flowing
        with pytest.raises(ShedLoad) as ei:
            svc.submit("fib", [5], module="fib", tenant="free")
        assert rejection_info(ei.value)["retryable"] is True
        # gold traffic keeps flowing — an id that happens to route to
        # the suspect owner refuses retryably; the retry (a fresh id)
        # lands, which IS the documented client contract
        req = None
        for _ in range(16):
            try:
                req = svc.submit("fib", [5], module="fib",
                                 tenant="gold")
                break
            except PeerSuspect:
                continue
        assert req is not None
        assert req.future.wait(120.0)
        assert req.future.result(0)[0] == _fib(5)
    finally:
        gw.shutdown()


def test_fleet_metrics_render_and_parse(fleet_pair):
    from wasmedge_tpu.obs.metrics import parse_prometheus

    gw_a, gw_b = fleet_pair
    st, text, _ = rpc(gw_b, "GET", "/metrics")
    assert st == 200
    m = parse_prometheus(text if isinstance(text, str)
                         else text.decode())
    assert ("wasmedge_fleet_peers",
            frozenset({("state", "alive")})) in m
    assert ("wasmedge_fleet_migrations_total",
            frozenset({("direction", "in")})) in m
    assert ("wasmedge_fleet_adoptions_total", frozenset()) in m
    # obs stays off by default on these gateways: federation never
    # force-enables the recorder (fleet instants ride NULL_RECORDER)
    assert not gw_b.service.obs.enabled
    # and a non-federated render emits NO fleet series at all
    from wasmedge_tpu.obs.metrics import render_prometheus

    assert "wasmedge_fleet" not in render_prometheus()


# ---------------------------------------------------------------------------
# elastic membership (r21): gossip join/leave, owner hints, churn health
# ---------------------------------------------------------------------------
def _solo(peers=(), faults=None, **fleet_kw):
    svc = GatewayService(conf=_conf(), lanes=2, faults=faults,
                         fleet=_fleet_cfg(peers, **fleet_kw))
    return Gateway(svc, port=0).start()


def test_join_gossips_to_full_fleet_convergence():
    """A new gateway announces itself to ONE seed and the whole fleet
    learns it: the seed's bumped membership view rides every heartbeat
    until the views converge (same epoch, same member set), and the
    joined peer is rendezvous-routable everywhere."""
    gw_a = _solo()                                  # seed, no peers
    addr_a = f"{gw_a.host}:{gw_a.port}"
    gw_b = _solo([addr_a])
    gw_c = None
    try:
        fa, fb = gw_a.service.fleet, gw_b.service.fleet
        fb.tick()                                   # B introduces itself
        assert fa.view.epoch == 1                   # join = origin event
        assert sorted(fa.members()) == sorted([fa.self_id, fb.self_id])
        gw_c = _solo([addr_a])                      # C joins via seed A
        fc = gw_c.service.fleet
        fc.tick()
        assert fa.view.epoch == 2
        # C learned B from A's heartbeat RESPONSE (gossip piggyback)
        assert sorted(fc.members()) \
            == sorted([fa.self_id, fb.self_id, fc.self_id])
        fb.tick()                                   # B pulls the view
        assert sorted(fb.members()) == sorted(fc.members())
        assert fb.view.epoch == fc.view.epoch == fa.view.epoch == 2
        assert fb.counters["gossip_merges"] > 0
        # a clean join NEVER trips fleet degradation (satellite: churn
        # vs genuine loss) — no gateway's fleet check goes unhealthy
        # (these bare gateways serve no modules, so overall status
        # reflects the generation check; the FLEET check is the pin)
        for gw in (gw_a, gw_b, gw_c):
            checks = gw.service.health()["checks"]
            assert checks.get("fleet", {"ok": True})["ok"]
    finally:
        for gw in (gw_c, gw_b, gw_a):
            if gw is not None:
                gw.shutdown()


def test_leave_unroutes_peer_and_health_stays_clean():
    """POST /v1/fleet/leave: the departing gateway broadcasts its own
    departure; survivors drop it from the rendezvous universe, report
    it as churn (never degradation), and refuse to resurrect the
    departed identity when it heartbeats again."""
    gw_a = _solo()
    addr_a = f"{gw_a.host}:{gw_a.port}"
    gw_b = _solo([addr_a])
    try:
        fa, fb = gw_a.service.fleet, gw_b.service.fleet
        fb.tick()                                   # join handshake
        assert fb.self_id in fa.members()
        st, doc, _ = rpc(gw_b, "POST", "/v1/fleet/leave", body={})
        assert st == 200 and doc["ok"] and doc["peer_id"] == fb.self_id
        assert fb.self_left
        # the direct broadcast already unrouted B on A
        assert fa.members() == [fa.self_id]
        assert fa.view.status_of(fb.self_id) == "left"
        snap = fa.stats()
        assert snap["left_peers"] == 1
        # left is expected absence: the departed peer leaves the
        # fleet-capacity tally entirely (no fleet check remains for a
        # fleet whose only peer left), and the churn check SHOWS the
        # departure without ever failing
        h = gw_a.service.health()
        assert "fleet" not in h["checks"]
        assert "churn" in h["checks"] and h["checks"]["churn"]["ok"]
        assert "left" in h["checks"]["churn"]["detail"]
        # a duplicate leave is a dedup ack, not a second epoch bump
        epoch = fa.view.epoch
        st, doc, _ = rpc(gw_a, "POST", "/v1/fleet/leave",
                         body={"peer_id": fb.self_id})
        assert st == 200 and doc.get("dedup") is True
        assert fa.view.epoch == epoch
        # left dominates: the departed identity heartbeating again
        # stays unroutable (a rejoin is a NEW host:port identity)
        fb.tick()
        assert fa.members() == [fa.self_id]
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


def test_owner_hint_redirects_poll_on_non_owner(fleet_pair):
    """Satellite pin: GET /v1/requests/<id> on a gateway that never
    accepted the id answers 404 with a machine-readable owner_hint
    (303-style) naming the id's rendezvous owner — so a client whose
    issuing peer died knows WHERE to poll."""
    gw_a, gw_b = fleet_pair
    fb = gw_b.service.fleet
    members = fb.members()
    assert len(members) >= 2
    owner_a = next(rid for rid in range(10 ** 9, 10 ** 9 + 4096)
                   if rendezvous_owner(rid, members) != fb.self_id)
    st, doc, _ = rpc(gw_b, "GET", f"/v1/requests/{owner_a}")
    assert st == 404
    err = doc["err"]
    assert err["detail"] == "not_owner" and err["retryable"] is True
    hint = err["owner_hint"]
    assert hint["peer"] == rendezvous_owner(owner_a, members)
    assert hint["url"] and "membership_epoch" in hint
    # an unknown id this gateway ITSELF owns gets the plain 404 (no
    # hint to give — polling elsewhere would not help)
    owned = next(rid for rid in range(10 ** 9, 10 ** 9 + 4096)
                 if rendezvous_owner(rid, members) == fb.self_id)
    st, doc, _ = rpc(gw_b, "GET", f"/v1/requests/{owned}")
    assert st == 404
    assert "owner_hint" not in doc.get("err", {})


def test_membership_gossip_drop_delays_but_never_breaks_convergence():
    """The membership_gossip fault seam drops exactly one piggybacked
    view merge: the heartbeat it rode still counts for liveness, and
    the next exchange re-gossips — convergence is delayed, never
    broken (the CRDT merge is order/loss tolerant)."""
    from wasmedge_tpu.testing.faults import churn_schedule

    sched = churn_schedule(seed=7, gossip_drops=2, max_at=0)
    assert all(f.point == "membership_gossip" and f.at == 0
               for f in sched)
    gw_a = _solo()
    addr_a = f"{gw_a.host}:{gw_a.port}"
    inj = FaultInjector([Fault(point="membership_gossip", at=0,
                               times=2)])
    gw_b = _solo([addr_a], faults=inj)
    gw_c = None
    try:
        fa, fb = gw_a.service.fleet, gw_b.service.fleet
        gw_c = _solo([addr_a])
        gw_c.service.fleet.tick()                   # A knows C
        fb.tick()                                   # drop 1
        assert fb.counters["heartbeats_ok"] == 1    # liveness intact
        assert fb.counters["gossip_merges"] == 0
        assert gw_c.service.fleet.self_id not in fb.members()
        fb.tick()                                   # drop 2
        fb.tick()                                   # goes through
        assert gw_c.service.fleet.self_id in fb.members()
        assert fb.view.epoch == fa.view.epoch
        assert inj.fired == 2
    finally:
        for gw in (gw_c, gw_b, gw_a):
            if gw is not None:
                gw.shutdown()


def test_joining_peer_grace_window_is_churn_not_degradation():
    """A runtime-joined peer that goes quiet inside its churn grace
    window reads as 'joining' (it may still be compiling its first
    generation) — health stays clean.  Past the window, the same
    silence is genuine degradation."""
    gw_a = _solo(churn_grace_s=1.5)
    addr_a = f"{gw_a.host}:{gw_a.port}"
    gw_b = _solo([addr_a])
    try:
        fa, fb = gw_a.service.fleet, gw_b.service.fleet
        fb.tick()                                   # B joins A
        gw_b.shutdown()                             # ...and vanishes
        fa.tick()
        fa.tick()                                   # misses -> suspect
        snap = fa.stats()
        assert snap["peers"]["joining"] == 1        # inside the window
        assert snap["peers"]["suspect"] == 0
        h = gw_a.service.health()
        assert h["checks"]["fleet"]["ok"]           # churn, not loss
        assert h["checks"]["churn"]["ok"]
        time.sleep(1.6)                             # window expires
        snap = fa.stats()
        assert snap["peers"]["joining"] == 0
        assert snap["peers"]["suspect"] + snap["peers"]["dead"] == 1
        assert not gw_a.service.health(fresh=True)["checks"]["fleet"]["ok"]
    finally:
        gw_a.shutdown()


def test_membership_epoch_metric_and_static_fleet_stays_epoch_zero(
        fleet_pair):
    from wasmedge_tpu.obs.metrics import parse_prometheus

    gw_a, gw_b = fleet_pair
    st, text, _ = rpc(gw_b, "GET", "/metrics")
    assert st == 200
    m = parse_prometheus(text if isinstance(text, str)
                         else text.decode())
    key = ("wasmedge_fleet_membership_epoch", frozenset())
    assert key in m
    # the shared pair's A side admits B at runtime (asymmetric list):
    # the epoch is whatever the views converged to — both sides agree
    assert m[key] == float(gw_a.service.fleet.view.epoch)
    # a fleet whose peers all arrive boot-configured never bumps:
    # static membership is bit-identical to r16 (epoch 0 forever)
    gw_s = _solo()
    try:
        assert gw_s.service.fleet.view.epoch == 0
    finally:
        gw_s.shutdown()
