"""SIMT-tier superinstruction fusion (batch/fuse.py) — ISSUE 13.

Pins the translation pass (analyzer candidates -> fused dispatch cells
in the device image) and its hard guarantees:

  - fusion on/off bit-identical to each other AND to the gas-metered
    scalar engine (results, traps, retired counts);
  - a lane whose pc sits mid-run executes the original per-op stream
    (residue handoff / resume-from-state), bit-exactly;
  - gas exhaustion lands at the correct op with per-op attribution even
    when the budget runs out mid-superinstruction (flat AND weighted);
  - opcode histogram == retired under fusion (per-constituent op_id);
  - the degradation ladder gains a rung: a fused-step fault demotes to
    the unfused SIMT build (checkpoints transfer) before scalar;
  - planning is block-local (never spans leaders/branches/terminators),
    non-overlapping, and reported planned-vs-realized per candidate.

Fast by construction (tiny lane counts, short chunks): tier-1.
"""

import io
import json
import os

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.fuse import (
    cell_eligible,
    fusion_active,
    plan_fusion,
)
from wasmedge_tpu.batch.image import TRAP_DONE, build_device_image
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fib, build_loop_sum
from tests.helpers import instantiate, load_validate

pytestmark = pytest.mark.fuse

LANES = 16


def fib_ref(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def make_conf(fuse=True, **batch):
    conf = Configure()
    conf.batch.fuse_superinstructions = fuse
    conf.batch.steps_per_launch = 200
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    return conf


def make_engine(data, conf, lanes=LANES, mesh=None):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes,
                       mesh=mesh)


def div_args(lanes=LANES, lo=4, hi=12):
    return [(lo + np.arange(lanes) % (hi - lo + 1)).astype(np.int64)]


def assert_results_identical(a, b):
    for ra, rb in zip(a.results, b.results):
        assert (np.asarray(ra) == np.asarray(rb)).all()
    assert (np.asarray(a.trap) == np.asarray(b.trap)).all()
    assert (np.asarray(a.retired) == np.asarray(b.retired)).all()


# ---------------------------------------------------------------------------
# translation pass: planning invariants
# ---------------------------------------------------------------------------
class TestPlanning:
    def test_fib_realizes_runs_within_blocks(self):
        conf = Configure()
        mod = load_validate(build_fib(), conf)
        img = build_device_image(mod.lowered, mod=mod)
        report = plan_fusion(img, conf.batch)
        assert report["enabled"] and report["fused_runs"] > 0
        assert report["patterns"] >= 1
        flen = np.asarray(img.fuse_len)
        fpat = np.asarray(img.fuse_pat)
        analysis = img.analysis
        # block spans: [start, end] per basic block, terminator excluded
        # for non-fallthrough blocks (the planner's own rule, re-derived
        # here from the r12 CFG so a planner regression can't self-pin)
        spans = []
        for f in analysis.funcs:
            for b in f.cfg.blocks:
                end = b.end if b.kind == "fallthrough" else b.end - 1
                spans.append((b.start, end))
        covered = np.zeros(flen.shape[0], bool)
        for head, n, k in report["runs"]:
            assert n >= 2
            assert flen[head] == n and fpat[head] == k
            assert 0 <= k < len(img.fuse_patterns)
            assert len(img.fuse_patterns[k]) == n
            # strictly inside ONE block (never spans a leader/terminator)
            assert any(s <= head and head + n - 1 <= e for s, e in spans)
            # no overlap between runs
            assert not covered[head:head + n].any()
            covered[head:head + n] = True
            # every constituent cell is an eligible pure stack/ALU op
            for j in range(n):
                assert cell_eligible(int(img.cls[head + j]),
                                     int(img.sub[head + j]))
        # non-head cells carry no fuse metadata
        heads = {r[0] for r in report["runs"]}
        for p in np.nonzero(flen)[0]:
            assert int(p) in heads
        # report arithmetic: realized counts reconcile
        assert report["fused_cells"] == int(flen.sum())
        assert report["fused_runs"] == sum(
            c["realized_runs"] for c in report["candidates"])
        for c in report["candidates"]:
            assert c["realized_runs"] <= c["planned"]

    def test_knob_off_plans_nothing(self):
        conf = make_conf(fuse=False)
        eng = make_engine(build_fib(), conf, lanes=4)
        eng.run("fib", [np.full(4, 5, np.int64)], max_steps=10_000)
        assert eng.img.fuse_len is None
        assert getattr(eng.img, "fusion_report", None) is None
        assert not fusion_active(eng.img, conf.batch)

    def test_knob_on_engine_plans_at_build(self):
        conf = make_conf()
        eng = make_engine(build_fib(), conf, lanes=4)
        # planning is deferred: a merely-constructed engine must not
        # have paid the analyzer (r12 lazy-analysis guarantee)
        assert getattr(eng.img, "fusion_report", None) is None
        eng.run("fib", [np.full(4, 5, np.int64)], max_steps=10_000)
        assert eng.img.fusion_report["fused_runs"] > 0
        assert fusion_active(eng.img, conf.batch)

    def test_top_k_zero_plans_nothing(self):
        conf = make_conf(fuse_top_k=0)
        eng = make_engine(build_fib(), conf, lanes=4)
        eng.run("fib", [np.full(4, 5, np.int64)], max_steps=10_000)
        assert eng.img.fuse_len is None
        assert not fusion_active(eng.img, conf.batch)


# ---------------------------------------------------------------------------
# bit-exactness: fused vs unfused SIMT vs gas-metered scalar
# ---------------------------------------------------------------------------
class TestBitExact:
    def test_fused_matches_unfused_and_scalar(self):
        from wasmedge_tpu.batch.supervisor import scalar_rerun

        args = div_args()
        res = {}
        for fuse in (True, False):
            conf = make_conf(fuse=fuse)
            eng = make_engine(build_fib(), conf)
            res[fuse] = eng.run("fib", args, max_steps=200_000)
            if fuse:
                assert fusion_active(eng.img, conf.batch)
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        # fewer dispatches is the whole point
        assert res[True].steps < res[False].steps
        expected = [fib_ref(int(n)) for n in args[0]]
        assert (res[True].results[0] == expected).all()
        # gas-metered scalar engine parity (the ladder's bottom rung)
        from wasmedge_tpu.batch.engine import check_batch_entry

        conf = make_conf()
        ex, store, inst = instantiate(build_fib(), conf)
        cells, trap, recs = scalar_rerun(
            inst, conf, "fib", check_batch_entry(inst, "fib"),
            args, np.arange(LANES), max_steps=200_000)
        assert not recs
        assert (trap == TRAP_DONE).all()
        assert (cells[0].view(np.int64) == res[True].results[0]).all()

    def test_mid_run_resume_executes_per_op(self):
        """A state whose pcs sit MID-superinstruction (exported at an
        arbitrary step boundary of the unfused build) resumes on the
        fused build bit-exactly: mid-run lanes step per-op to the next
        head, head lanes take the fused dispatch."""
        args = div_args()
        conf_f = make_conf(steps_per_launch=1)
        fused = make_engine(build_fib(), conf_f)
        fused._plan_fusion()  # planning is deferred to first build
        flen = np.asarray(fused.img.fuse_len)
        midrun = np.zeros(flen.shape[0] + 1, bool)
        for h in np.nonzero(flen >= 2)[0]:
            midrun[h + 1:h + flen[h]] = True

        conf_u = make_conf(fuse=False, steps_per_launch=1)
        unfused = make_engine(build_fib(), conf_u)
        fi = unfused.export_func_idx("fib")
        state = unfused.initial_state(fi, args)
        total = 0
        hit = False
        for _ in range(200):
            state, total = unfused.run_from_state(state, total, total + 1)
            pcs = np.asarray(state.pc)[np.asarray(state.trap) == 0]
            if midrun[np.clip(pcs, 0, flen.shape[0] - 1)].any():
                hit = True
                break
        assert hit, "never reached a mid-superinstruction pc"
        # resume the SAME state on BOTH builds (host snapshot: the chunk
        # loop donates its input buffers); finish bit-identically
        import jax.numpy as jnp

        def replica():
            return state._replace(**{
                n: jnp.asarray(np.asarray(getattr(state, n)).copy())
                for n in state._fields
                if getattr(state, n) is not None})

        sf, tf = fused.run_from_state(replica(), total, 200_000)
        su, tu = unfused.run_from_state(replica(), total, 200_000)
        assert tf < tu  # the fused continuation used fewer dispatches
        for plane in ("pc", "sp", "retired", "trap", "stack_lo",
                      "stack_hi", "glob_lo", "glob_hi", "mem"):
            assert (np.asarray(getattr(sf, plane))
                    == np.asarray(getattr(su, plane))).all(), plane
        assert (np.asarray(sf.trap) == TRAP_DONE).all()

    def test_divergent_uniform_handoff(self):
        """The uniform engine's divergence handoff lands mid-stream on
        the fused SIMT build (the residue seam named by the ISSUE)."""
        from wasmedge_tpu.batch.uniform import UniformBatchEngine

        args = div_args()
        out = {}
        for fuse in (True, False):
            conf = make_conf(fuse=fuse)
            ex, store, inst = instantiate(build_fib(), conf)
            eng = UniformBatchEngine(inst, store=store, conf=conf,
                                     lanes=LANES)
            out[fuse] = eng.run("fib", args, max_steps=200_000)
        assert out[True].completed.all()
        assert_results_identical(out[True], out[False])
        expected = [fib_ref(int(n)) for n in args[0]]
        assert (out[True].results[0] == expected).all()


# ---------------------------------------------------------------------------
# gas: exhaustion mid-superinstruction lands at the correct op
# ---------------------------------------------------------------------------
class TestGas:
    def _exhaust(self, conf_extra):
        """Run fused and unfused builds from the same initial state with
        a per-lane fuel ramp wide enough that exhaustion sweeps across
        every stream position — including positions strictly inside a
        fused run.  Returns (fused_state, unfused_state, fused_img)."""
        import jax.numpy as jnp

        args = [np.full(LANES, 10, np.int64)]
        states = {}
        img_f = None
        for fuse in (True, False):
            conf = make_conf(fuse=fuse, fuel_per_launch=1_000_000,
                             **conf_extra)
            eng = make_engine(build_fib(), conf)
            if fuse:
                img_f = eng.img
            fi = eng.export_func_idx("fib")
            st = eng.initial_state(fi, args)
            fuel = 20 + 3 * np.arange(LANES, dtype=np.int32)
            st = st._replace(fuel=jnp.asarray(fuel))
            states[fuse] = eng.run_from_state(st, 0, 200_000)[0]
        return states[True], states[False], img_f

    def _pin(self, sf, su, img):
        for plane in ("pc", "sp", "fp", "retired", "trap", "fuel"):
            a = np.asarray(getattr(sf, plane))
            b = np.asarray(getattr(su, plane))
            assert (a == b).all(), f"{plane} diverged under gas"
        trap = np.asarray(sf.trap)
        assert (trap == int(ErrCode.CostLimitExceeded)).any()
        # at least one exhaustion pc sits strictly INSIDE a fused run
        flen = np.asarray(img.fuse_len)
        midrun = np.zeros(flen.shape[0], bool)
        for h in np.nonzero(flen >= 2)[0]:
            midrun[h + 1:h + flen[h]] = True
        pcs = np.asarray(sf.pc)[trap == int(ErrCode.CostLimitExceeded)]
        assert midrun[np.clip(pcs, 0, flen.shape[0] - 1)].any(), \
            "fuel ramp never exhausted mid-superinstruction"

    def test_flat_gas_mid_run(self):
        self._pin(*self._exhaust({}))

    def test_weighted_gas_mid_run(self):
        from wasmedge_tpu.common.statistics import _NUM_COST_SLOTS

        table = tuple(1 + (i % 3) for i in range(_NUM_COST_SLOTS))
        self._pin(*self._exhaust({"cost_table": table}))


# ---------------------------------------------------------------------------
# obs: histogram == retired per constituent op; fused/unfused split
# ---------------------------------------------------------------------------
class TestObs:
    def _obs_run(self, fuse):
        conf = make_conf(fuse=fuse)
        conf.obs.enabled = True
        conf.obs.opcode_histogram = True
        eng = make_engine(build_fib(), conf)
        res = eng.run("fib", div_args(), max_steps=200_000)
        return eng, res

    def test_histogram_equals_retired_under_fusion(self):
        engs, ress = {}, {}
        for fuse in (True, False):
            engs[fuse], ress[fuse] = self._obs_run(fuse)
        assert_results_identical(ress[True], ress[False])
        cf = engs[True].obs.opcode_counts
        cu = engs[False].obs.opcode_counts
        assert cf is not None and cu is not None
        # per-constituent attribution: the fused histogram is IDENTICAL
        # to the unfused one, and both equal total retired
        assert (cf == cu).all()
        assert cf.sum() == np.asarray(ress[True].retired).sum()

    def test_fused_counters_and_prometheus(self):
        from wasmedge_tpu.obs.metrics import (
            parse_prometheus, render_prometheus)

        eng, res = self._obs_run(True)
        fc = eng.obs.fused_counts
        retired = int(np.asarray(res.retired, np.int64).sum())
        assert fc["dispatches"] > 0
        assert fc["retired_fused"] >= 2 * fc["dispatches"]
        assert fc["retired_total"] == retired
        text = render_prometheus(recorder=eng.obs)
        fams = parse_prometheus(text)
        assert fams[("wasmedge_fused_dispatches_total",
                     frozenset())] == fc["dispatches"]
        rf = fams[("wasmedge_retired_by_path_total",
                   frozenset({("path", "fused")}))]
        ru = fams[("wasmedge_retired_by_path_total",
                   frozenset({("path", "unfused")}))]
        assert rf == fc["retired_fused"]
        assert rf + ru == retired

    def test_unfused_run_exports_no_fused_metrics(self):
        from wasmedge_tpu.obs.metrics import render_prometheus

        eng, _res = self._obs_run(False)
        assert eng.obs.fused_counts["dispatches"] == 0
        assert "wasmedge_fused_dispatches_total" not in \
            render_prometheus(recorder=eng.obs)


# ---------------------------------------------------------------------------
# mesh + multi-tenant: fused planes ride the shard drive and concat
# ---------------------------------------------------------------------------
class TestComposition:
    def test_shard_drive_fused_parity(self):
        from wasmedge_tpu.parallel.mesh import lane_mesh

        args = div_args(32, 4, 11)
        out = {}
        for fuse in (True, False):
            conf = make_conf(fuse=fuse)
            out[fuse] = make_engine(build_fib(), conf, lanes=32,
                                    mesh=lane_mesh(8)).run(
                "fib", args, max_steps=200_000)
        solo = make_engine(build_fib(), make_conf(), lanes=32).run(
            "fib", args, max_steps=200_000)
        assert out[True].completed.all()
        assert_results_identical(out[True], out[False])
        assert_results_identical(out[True], solo)

    def test_multitenant_concat_fused_parity(self):
        from wasmedge_tpu.batch.multitenant import (
            MultiTenantBatchEngine, Tenant)

        L = 8
        out = {}
        for fuse in (True, False):
            conf = make_conf(fuse=fuse)
            tenants = []
            for data, fn, args in (
                    (build_fib(), "fib", div_args(L, 4, 9)),
                    (build_loop_sum(), "loop_sum",
                     [np.full(L, 25, np.int64)])):
                ex, store, inst = instantiate(data, conf)
                tenants.append(Tenant(
                    engine=BatchEngine(inst, store=store, conf=conf,
                                       lanes=L),
                    func_name=fn, args_lanes=args, lanes=L))
            mt = MultiTenantBatchEngine(tenants, conf=conf)
            if fuse:
                img = mt.img
                assert img.fuse_len is not None
                assert img.fusion_report["fused_cells"] == \
                    int(np.asarray(img.fuse_len).sum())
                assert len(img.fuse_patterns) <= 16
            out[fuse] = mt.run_tenants(max_steps=200_000)
        for a, b in zip(out[True], out[False]):
            assert a.completed.all()
            assert_results_identical(a, b)


# ---------------------------------------------------------------------------
# ladder: fused-step fault demotes fused -> unfused SIMT -> scalar
# ---------------------------------------------------------------------------
@pytest.mark.faults
class TestLadder:
    def _sup(self, tmp_path, inj, sub, **sup):
        from wasmedge_tpu.batch.supervisor import BatchSupervisor

        conf = make_conf(steps_per_launch=100)
        conf.supervisor.backoff_base_s = 0.0
        conf.supervisor.checkpoint_every_steps = 200
        conf.supervisor.max_retries = 2
        for k, v in sup.items():
            setattr(conf.supervisor, k, v)
        return BatchSupervisor(make_engine(build_fib(), conf),
                               faults=inj,
                               checkpoint_dir=str(tmp_path / sub))

    def test_fused_fault_demotes_to_unfused_simt(self, tmp_path):
        from wasmedge_tpu.testing.faults import Fault, FaultInjector

        args = div_args()
        ref = self._sup(tmp_path, None, "ref").run(
            "fib", args, max_steps=200_000)
        # launches 2..4 fault: the fused rung has checkpointed by then,
        # exhausts its retries, and the unfused rung must ADOPT the
        # fused rung's checkpoint instead of replaying from scratch
        inj = FaultInjector([Fault(point="launch", at=2, times=3)])
        sup = self._sup(tmp_path, inj, "a")
        res = sup.run("fib", args, max_steps=200_000)
        assert inj.fired == 3
        assert res.completed.all()
        assert_results_identical(res, ref)
        classes = [f.fault_class for f in sup.failures]
        assert classes.count("launch") == 3
        assert "demote" in classes
        # the demoted engine really is the unfused build, resumed from
        # the fused rung's lineage — and its conf.batch agrees with its
        # cfg, so the obs plane allocator can never disagree with the
        # step builder about fusion_active
        assert sup.engine.cfg.fuse_superinstructions is False
        assert sup.engine.conf.batch.fuse_superinstructions is False
        assert sup._restored_from is not None

    def test_full_ladder_to_scalar(self, tmp_path):
        from wasmedge_tpu.testing.faults import Fault, FaultInjector

        args = div_args()
        inj = FaultInjector([Fault(point="launch", at=0, times=1000)])
        sup = self._sup(tmp_path, inj, "b")
        res = sup.run("fib", args, max_steps=200_000)
        assert res.completed.all()
        expected = [fib_ref(int(n)) for n in args[0]]
        assert (res.results[0] == expected).all()
        classes = [f.fault_class for f in sup.failures]
        # 3 launch faults on the fused rung + 3 on the unfused rung
        assert classes.count("launch") == 6
        assert classes.count("demote") == 2

    def test_demotion_does_not_leak_into_next_run(self, tmp_path):
        from wasmedge_tpu.testing.faults import Fault, FaultInjector

        args = div_args()
        inj = FaultInjector([Fault(point="launch", at=0, times=3)])
        sup = self._sup(tmp_path, inj, "d")
        res = sup.run("fib", args, max_steps=200_000)
        assert res.completed.all()
        assert sup.engine.cfg.fuse_superinstructions is False
        # a later run() on the same supervisor starts from the pristine
        # (fused) engine again — one demotion never de-fuses forever
        res2 = sup.run("fib", args, max_steps=200_000)
        assert res2.completed.all()
        assert sup.engine.cfg.fuse_superinstructions is True
        assert_results_identical(res, res2)

    def test_knob_off_ladder_has_no_unfused_rung(self, tmp_path):
        from wasmedge_tpu.batch.supervisor import BatchSupervisor
        from wasmedge_tpu.testing.faults import Fault, FaultInjector

        conf = make_conf(fuse=False, steps_per_launch=100)
        conf.supervisor.backoff_base_s = 0.0
        conf.supervisor.max_retries = 2
        inj = FaultInjector([Fault(point="launch", at=0, times=1000)])
        sup = BatchSupervisor(make_engine(build_fib(), conf),
                              faults=inj,
                              checkpoint_dir=str(tmp_path / "c"))
        res = sup.run("fib", div_args(), max_steps=200_000)
        assert res.completed.all()
        classes = [f.fault_class for f in sup.failures]
        assert classes.count("launch") == 3  # one SIMT rung only
        assert classes.count("demote") == 1


# ---------------------------------------------------------------------------
# report schema + analyze CLI
# ---------------------------------------------------------------------------
class TestReport:
    def _report(self):
        from wasmedge_tpu.analysis import analyze_validated, validate_report

        conf = Configure()
        mod = load_validate(build_fib(), conf)
        analysis = analyze_validated(mod)
        img = build_device_image(mod.lowered, mod=mod)
        doc = analysis.to_dict()
        doc["fusion"] = plan_fusion(img, conf.batch, analysis=analysis)
        return doc, validate_report

    def test_fusion_section_validates(self):
        doc, validate_report = self._report()
        assert validate_report(doc) == []
        assert doc["fusion"]["fused_runs"] > 0
        assert any(c["realized_runs"] for c in doc["fusion"]["candidates"])

    def test_fusion_section_bad_counts_flagged(self):
        doc, validate_report = self._report()
        doc["fusion"]["candidates"][0]["realized_runs"] = 10 ** 6
        problems = validate_report(doc)
        assert any("realized_runs > planned" in p for p in problems)
        assert any("disagrees" in p for p in problems)

    def test_cli_analyze_disasm_marks_fused_runs(self, tmp_path):
        from wasmedge_tpu.cli import analyze_command

        path = str(tmp_path / "fib.wasm")
        with open(path, "wb") as f:
            f.write(build_fib())
        out, err = io.StringIO(), io.StringIO()
        rc = analyze_command([path, "--disasm"], out=out, err=err)
        assert rc == 0, err.getvalue()
        doc = json.loads(out.getvalue())
        assert doc["fusion"]["fused_runs"] > 0
        assert "fused=" in doc["disasm"]
