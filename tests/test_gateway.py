"""Network-facing serving gateway (wasmedge_tpu/gateway/, marker `serve`).

Pins the r11 acceptance contract over REAL sockets (every HTTP
assertion here goes through a bound ephemeral port, never an
in-process shortcut):

  - runtime module registration: POST /v1/modules validates/compiles
    through the standard pipeline; register-then-invoke results are
    bit-identical to a solo execute_batch run of the same module on a
    cold-start multi-module image, while in-flight requests from the
    PREVIOUS generation finish on the old image, unperturbed
  - rejection taxonomy on the wire: unknown module/func -> 404, bad or
    unbatchable wasm -> 400, duplicate name -> 409, backpressure ->
    429 + Retry-After, deadline -> 504, auth -> 401/403
  - the machine-readable rejection contract (ErrCode + retryable flag,
    common/errors.rejection_info) both in-process and in HTTP bodies
  - per-tenant policy: API-key auth stub, token-bucket rate limiting,
    quota/weight wired into the FairQueue

Speed discipline: tier-1 fast.  Engine compiles dominate gateway
tests, so the suite shares ONE long-lived gateway (module fixture) for
everything that doesn't need special knobs, keeps every pool at the
same tiny geometry (so the module-scoped JAX persistent cache turns
repeat builds into deserializations), and registers exactly one module
at runtime across the whole file (each registration compiles a fresh
concatenated image — that is the feature, pay for it once).  Tests
against the shared gateway are order-independent: they read
generation/module state instead of assuming it.
"""

import base64
import json
import tempfile
import time
from http.client import HTTPConnection

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, WasmError, rejection_info
from wasmedge_tpu.gateway import Gateway, GatewayService, GatewayTenants
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.utils.builder import ModuleBuilder

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="gateway-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def build_dbl() -> bytes:
    """A second guest for runtime registration: dbl(n) = 2n + 7."""
    b = ModuleBuilder()
    b.add_function(["i64"], ["i64"], [],
                   [("local.get", 0), ("i64.const", 2), "i64.mul",
                    ("i64.const", 7), "i64.add"],
                   export="dbl")
    return b.build()


def build_unlinkable() -> bytes:
    """Imports a host function nothing provides: instantiation fails."""
    b = ModuleBuilder()
    b.import_func("env", "mystery", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="f")
    return b.build()


def _conf(obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    return conf


def _gateway(conf=None, lanes=2, tenants=None, fib=True):
    svc = GatewayService(conf=conf or _conf(), lanes=lanes,
                         tenants=tenants)
    if fib:
        svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    return Gateway(svc, port=0).start()


@pytest.fixture(scope="module")
def gw_main(_compile_cache):
    """The shared gateway: obs on, 2 lanes, fib preloaded.  Tests must
    stay order-independent against it (read state, don't assume it)."""
    gw = _gateway(conf=_conf(obs=True), lanes=2)
    yield gw
    gw.shutdown()


def rpc(gw, method, path, body=None, headers=None, timeout=120.0):
    c = HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        data = json.dumps(body).encode() if isinstance(body, dict) \
            else body
        c.request(method, path, body=data, headers=headers or {})
        r = c.getresponse()
        raw = r.read()
        hdrs = dict(r.getheaders())
    finally:
        c.close()
    try:
        doc = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        doc = raw.decode(errors="replace")
    return r.status, doc, hdrs


def _poll(gw, rid, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st, doc, _ = rpc(gw, "GET", f"/v1/requests/{rid}")
        if not (isinstance(doc, dict) and doc.get("status") == "pending"):
            return st, doc
        time.sleep(0.02)
    raise TimeoutError(f"request {rid} still pending")


# ---------------------------------------------------------------------------
# runtime registration: cold-start image parity + in-flight swap
# ---------------------------------------------------------------------------
def test_register_then_invoke_parity_and_generation_swap(gw_main):
    """The acceptance sentence in one flow: long requests go in flight
    on generation N, a module registers over HTTP (generation N+1,
    cold-start concatenated image), the NEW module serves bit-identical
    to a solo execute_batch run, the OLD generation's in-flight
    requests complete unperturbed on the old image, and the drained
    generation is reaped."""
    gw = gw_main
    st, doc, _ = rpc(gw, "GET", "/v1/status")
    gen0 = doc["generation"]

    # occupy generation N's two lanes with long requests (async so the
    # handler threads don't serialize them) + one queued behind
    ids = []
    for n in (17, 16, 15):
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [n], "async": True})
        assert st == 202, doc
        ids.append(doc["request_id"])

    # register mid-flight
    st, doc, _ = rpc(gw, "POST", "/v1/modules",
                     {"name": "dbl",
                      "wasm_b64": base64.b64encode(build_dbl()).decode()})
    assert st == 201, doc
    assert doc["generation"] == gen0 + 1
    assert doc["modules"][-1] == "dbl"
    assert doc["exports"] == ["dbl"]

    # the new module serves on the new generation immediately
    ds = [3, 1000, 7]
    got_dbl = []
    for n in ds:
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"module": "dbl", "func": "dbl", "args": [n]})
        assert st == 200 and doc["ok"], doc
        assert doc["generation"] == gen0 + 1
        got_dbl.append(doc["result"][0])
    # ... and the old module still serves (same pool, qualified route)
    st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                     {"module": "fib", "func": "fib", "args": [11]})
    assert st == 200 and doc["result"] == [89], doc

    # in-flight generation-N requests complete with correct results,
    # attributed to the OLD generation
    for rid, n in zip(ids, (17, 16, 15)):
        st, doc = _poll(gw, rid)
        assert st == 200 and doc["ok"], doc
        assert doc["result"] == [_fib(n)]
        assert doc["generation"] == gen0

    # the drained old generation is eventually reaped
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        st, doc, _ = rpc(gw, "GET", "/v1/status")
        if doc["draining_generations"] == 0:
            break
        time.sleep(0.05)
    assert doc["draining_generations"] == 0
    assert doc["generation"] == gen0 + 1

    # bit-identical to a solo execute_batch run of the runtime-
    # registered module alone (cold-start parity)
    import numpy as np

    from wasmedge_tpu.vm import VM

    vm = VM(_conf())
    vm.load_wasm(build_dbl())
    vm.validate()
    vm.instantiate()
    solo = vm.execute_batch("dbl", [np.asarray(ds, np.int64)],
                            lanes=len(ds))
    assert solo.completed.all()
    assert got_dbl == [int(x) for x in solo.results[0]]
    assert got_dbl == [2 * n + 7 for n in ds]


# ---------------------------------------------------------------------------
# rejection taxonomy on the wire
# ---------------------------------------------------------------------------
def test_unknown_module_bad_wasm_and_conflict_rejection(gw_main):
    gw = gw_main
    st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                     {"module": "nope", "func": "f", "args": []})
    assert st == 404 and not doc["ok"], doc
    st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                     {"module": "fib", "func": "nofunc"})
    assert st == 404, doc
    st, doc, _ = rpc(gw, "GET", "/v1/requests/999999")
    assert st == 404, doc

    # garbage bytes: LoadError taxonomy in the body
    st, doc, _ = rpc(gw, "POST", "/v1/modules",
                     {"name": "junk",
                      "wasm_b64":
                      base64.b64encode(b"not wasm at all").decode()})
    assert st == 400, doc
    assert doc["err"]["retryable"] is False
    assert "code" in doc["err"] and "name" in doc["err"]

    # well-formed but unlinkable (unknown import): still 400, and the
    # module must NOT have been registered
    st, doc, _ = rpc(gw, "POST", "/v1/modules",
                     {"name": "orphan",
                      "wasm_b64":
                      base64.b64encode(build_unlinkable()).decode()})
    assert st == 400, doc
    st, doc, _ = rpc(gw, "GET", "/v1/status")
    assert "orphan" not in doc["modules"]
    assert "junk" not in doc["modules"]

    # duplicate name -> 409
    st, doc, _ = rpc(gw, "POST", "/v1/modules",
                     {"name": "fib",
                      "wasm_b64": base64.b64encode(build_fib()).decode()})
    assert st == 409, doc
    assert doc["err"]["name"] == "ModuleNameConflict"

    # malformed requests -> 400
    st, doc, _ = rpc(gw, "POST", "/v1/invoke", b"{not json",
                     headers={"Content-Type": "application/json"})
    assert st == 400, doc
    st, doc, _ = rpc(gw, "POST", "/v1/invoke", {"args": [1]})
    assert st == 400, doc  # missing func


# ---------------------------------------------------------------------------
# r13 surface on the shared gateway: truthful /healthz + durability fields
# ---------------------------------------------------------------------------
def test_healthz_and_status_carry_machine_readable_health(gw_main):
    """/healthz is no longer a liveness stub: the body carries the
    per-check breakdown (driver/queue/checkpoint), /v1/status embeds
    the same health block plus the durability flag, and the restart/
    rollback counters always render in /metrics (zero-valued on a
    fresh non-durable gateway)."""
    gw = gw_main
    st, doc, _ = rpc(gw, "GET", "/healthz")
    assert st == 200, doc
    assert doc["ok"] is True
    assert doc["status"] in ("healthy", "degraded")
    for check in ("driver", "queue", "checkpoint"):
        assert check in doc["checks"]
        assert set(doc["checks"][check]) == {"ok", "level", "detail"}

    st, doc, _ = rpc(gw, "GET", "/v1/status")
    assert st == 200
    assert doc["health"]["status"] in ("healthy", "degraded")
    assert doc["durable"] is False   # no state_dir on the shared gw
    assert "rollbacks" in doc["gateway"]
    assert "restarts" in doc["gateway"]

    st, text, _ = rpc(gw, "GET", "/metrics")
    assert st == 200
    assert "wasmedge_gateway_restarts_total" in text
    assert "wasmedge_generation_rollbacks_total" in text


# ---------------------------------------------------------------------------
# observability: gateway spans + http_requests_total
# ---------------------------------------------------------------------------
def test_gateway_obs_spans_and_metrics(gw_main):
    from wasmedge_tpu.obs.metrics import parse_prometheus

    gw = gw_main
    svc = gw.service
    for n, tenant in ((9, "obs-a"), (6, "obs-b")):
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [n], "tenant": tenant})
        assert st == 200, doc
    names = [e["name"] for e in svc.obs.events]
    assert "gateway_receive" in names
    assert "gateway/obs-a" in names and "gateway/obs-b" in names
    spans = [e for e in svc.obs.events
             if e["name"] in ("gateway/obs-a", "gateway/obs-b")]
    assert all(e["track"] == "gateway" and e["args"]["ok"]
               for e in spans)

    st, text, _ = rpc(gw, "GET", "/metrics")
    assert st == 200
    parsed = parse_prometheus(text)
    key = ("wasmedge_gateway_http_requests_total",
           frozenset({("code", "200")}))
    assert parsed[key] >= 2.0


# ---------------------------------------------------------------------------
# auth + per-tenant policy enforcement
# ---------------------------------------------------------------------------
def test_auth_and_quota_enforcement(tmp_path):
    policy = {
        "require_auth": True,
        "tenants": {
            "alice": {"api_key": "sk-alice", "weight": 2.0, "quota": 2},
            "bob": {"api_key": "sk-bob", "can_register": False,
                    "rate_per_s": 1000.0, "burst": 3},
        },
    }
    pf = tmp_path / "tenants.json"
    pf.write_text(json.dumps(policy))
    tenants = GatewayTenants.from_file(str(pf))
    gw = _gateway(lanes=2, tenants=tenants)
    svc = gw.service
    try:
        # quota/weight made it onto the FairQueue admission substrate
        srv = svc.current.server
        assert srv.queue.quotas == {"alice": 2}
        assert srv.queue.weights == {"alice": 2.0, "bob": 1.0}

        # no key -> 401; unknown key -> 401; key/tenant mismatch -> 401
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [5]})
        assert st == 401, doc
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [5]},
                         headers={"Authorization": "Bearer sk-wrong"})
        assert st == 401, doc
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [5], "tenant": "bob"},
                         headers={"Authorization": "Bearer sk-alice"})
        assert st == 401, doc

        # a good key resolves the tenant (either header form)
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [10]},
                         headers={"Authorization": "Bearer sk-alice"})
        assert st == 200 and doc["result"] == [55], doc
        assert doc["tenant"] == "alice"
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [6]},
                         headers={"X-Api-Key": "sk-bob"})
        assert st == 200 and doc["tenant"] == "bob", doc

        # registration permission is per tenant (a 403 here must NOT
        # consume the name: alice's retry of the same name succeeds)
        wasm64 = base64.b64encode(build_dbl()).decode()
        st, doc, _ = rpc(gw, "POST", "/v1/modules",
                         {"name": "dbl", "wasm_b64": wasm64},
                         headers={"X-Api-Key": "sk-bob"})
        assert st == 403, doc
        st, doc, _ = rpc(gw, "POST", "/v1/modules",
                         {"name": "dbl", "wasm_b64": wasm64},
                         headers={"X-Api-Key": "sk-alice"})
        assert st == 201, doc

        # bob's token bucket enforced at the edge: stop refills, flood
        tenants._buckets["bob"].rate = 0.001
        saw_429 = None
        for _ in range(8):
            st, doc, hdrs = rpc(gw, "POST", "/v1/invoke",
                                {"func": "fib", "args": [4],
                                 "async": True},
                                headers={"X-Api-Key": "sk-bob"})
            if st == 429:
                saw_429 = (doc, hdrs)
                break
        assert saw_429 is not None
        doc, hdrs = saw_429
        assert doc["err"]["name"] == "RateLimited"
        assert doc["err"]["retryable"] is True
        assert "Retry-After" in hdrs
        assert svc.counters["rate_limited"] >= 1

        # obs is off by default here — yet the HTTP tally still lands
        # in the Prometheus text (bookkeeping, not tracing)
        assert svc.obs.enabled is False
        st, text, _ = rpc(gw, "GET", "/metrics")
        assert "wasmedge_gateway_http_requests_total" in text
    finally:
        gw.shutdown()


# ---------------------------------------------------------------------------
# deadline / backpressure status codes over a real socket
# ---------------------------------------------------------------------------
def test_deadline_and_backpressure_status_codes():
    conf = _conf()
    conf.serve.queue_capacity = 2
    gw = _gateway(conf=conf, lanes=1)
    try:
        # occupy the single lane, then fill the bounded queue: the
        # next submission must draw 429 + Retry-After (QueueSaturated
        # is the retryable class).  Admission runs on the driver
        # thread, so flood until the queue is provably full.
        st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                         {"func": "fib", "args": [17], "async": True})
        assert st == 202, doc
        long_id = doc["request_id"]
        saw_429 = None
        spill_ids = []
        for _ in range(12):
            st, doc, hdrs = rpc(gw, "POST", "/v1/invoke",
                                {"func": "fib", "args": [15],
                                 "async": True})
            if st == 429:
                saw_429 = (doc, hdrs)
                break
            spill_ids.append(doc["request_id"])
        assert saw_429 is not None, "queue never saturated"
        doc, hdrs = saw_429
        assert "Retry-After" in hdrs
        assert doc["err"]["retryable"] is True
        assert doc["err"]["code"] == int(ErrCode.CostLimitExceeded)

        # deadline: a queued request behind the long ones expires ->
        # 504 with the DeadlineExceeded taxonomy (non-retryable).  The
        # queue may still be saturated — honor the 429 contract and
        # retry until admitted (exactly what a well-behaved client
        # does with Retry-After)
        deadline = time.monotonic() + 60.0
        while True:
            st, doc, _ = rpc(gw, "POST", "/v1/invoke",
                             {"func": "fib", "args": [17],
                              "deadline_ms": 1})
            if st != 429:
                break
            assert time.monotonic() < deadline, "queue never drained"
            time.sleep(0.05)
        assert st == 504, doc
        assert doc["err"]["retryable"] is False
        assert doc["err"]["code"] == int(ErrCode.Terminated)

        # the occupying + spilled requests still complete correctly
        st, doc = _poll(gw, long_id)
        assert st == 200 and doc["result"] == [_fib(17)], doc
        for rid in spill_ids:
            st, doc = _poll(gw, rid)
            assert st == 200 and doc["result"] == [_fib(15)], doc
    finally:
        gw.shutdown()


# ---------------------------------------------------------------------------
# swap race: a submit that captured the old generation retries on the new
# ---------------------------------------------------------------------------
def test_submit_racing_a_generation_swap_lands_on_the_successor():
    """submit() resolves the current generation, then calls its server
    outside the gateway lock; a registration landing in that window
    makes the captured generation reject with a permanent 'draining'
    error.  That rejection belongs to the stale generation — the
    service must retry on the successor, never surface a non-retryable
    error for a servable request."""
    svc = GatewayService(conf=_conf(), lanes=2)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gen1_server = svc.current.server
    orig_submit = gen1_server.submit
    fired = {}

    def racing_submit(*a, **kw):
        if not fired:
            # the swap happens "between" the service's current-read and
            # the server call: generation 2 installs, generation 1
            # starts draining and rejects
            fired["yes"] = True
            svc.register_module("dbl", wasm_bytes=build_dbl(),
                                source="boot")
            raise WasmError(ErrCode.Terminated,
                            "server is draining; submissions closed")
        return orig_submit(*a, **kw)

    gen1_server.submit = racing_submit
    try:
        req = svc.submit("fib", [10], module="fib")
        assert req.gen_id == 2          # routed to the successor
        assert svc.wait(req, timeout_s=120.0)
        assert req.future.result(0) == [55]
        assert svc.counters["rejected"] == 0
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# the machine-readable rejection contract (in-process half)
# ---------------------------------------------------------------------------
def test_keyed_tenant_requires_its_key_even_without_require_auth():
    """A tenant with an api_key configured cannot be claimed keyless
    just because global require_auth is off — the key would otherwise
    silently protect nothing (weight/quota/can_register hijack)."""
    from wasmedge_tpu.gateway.tenants import AuthError

    t = GatewayTenants.from_dict({"tenants": {
        "keyed": {"api_key": "sk-k", "weight": 3.0},
        "open": {},
    }})
    assert t.require_auth is False
    assert t.authenticate("sk-k", None) == "keyed"
    assert t.authenticate(None, "open") == "open"
    assert t.authenticate(None, None) == "default"
    with pytest.raises(AuthError):
        t.authenticate(None, "keyed")


def test_gateway_closed_maps_to_503():
    """Lifecycle-terminated (gateway shutting down) is 503, never the
    admission-block 403 — a client must keep retrying a restarting
    gateway."""
    from wasmedge_tpu.gateway.http import submit_status_of
    from wasmedge_tpu.gateway.service import GatewayClosed

    assert submit_status_of(GatewayClosed()) == 503
    # the admission block (same ErrCode) stays 403
    assert submit_status_of(WasmError(ErrCode.Terminated)) == 403
    svc = GatewayService(conf=_conf(), lanes=2)
    svc.shutdown()
    with pytest.raises(GatewayClosed):
        svc.submit("fib", [1])
    with pytest.raises(GatewayClosed):
        svc.register_module("m", wasm_bytes=build_fib())


def test_structured_rejection_contract():
    from wasmedge_tpu.serve.queue import DeadlineExceeded, QueueSaturated

    qs = QueueSaturated(retry_after_s=0.25)
    assert qs.retryable is True
    info = rejection_info(qs)
    assert info["code"] == int(ErrCode.CostLimitExceeded)
    assert info["name"] == "CostLimitExceeded"
    assert info["retryable"] is True
    assert info["retry_after_s"] == 0.25

    dl = DeadlineExceeded()
    assert dl.retryable is False
    assert rejection_info(dl)["retryable"] is False

    # plain WasmErrors (permanent conditions) default non-retryable
    assert WasmError(ErrCode.Terminated).retryable is False
    # non-WasmError exceptions normalize into the same shape
    info = rejection_info(RuntimeError("boom"))
    assert info["retryable"] is False
    assert info["code"] == int(ErrCode.ExecutionFailed)

    # lifecycle rejections (guest never ran) are 503 at resolution,
    # never presented as a guest trap (200 ok:false)
    from types import SimpleNamespace

    from wasmedge_tpu.gateway.http import result_response
    from wasmedge_tpu.serve.queue import ServeRejected

    fake = SimpleNamespace(id=1, func="f", tenant="t", gen_id=1,
                           future=SimpleNamespace(
                               error=ServeRejected("server shut down")))
    assert result_response(fake)[0] == 503
    fake.future.error = WasmError(ErrCode.Unreachable)  # a real trap
    assert result_response(fake)[0] == 200

    # args that don't fit a 64-bit lane cell are rejected at
    # SUBMISSION (ValueError -> 400), never on the serving thread
    from wasmedge_tpu.serve.queue import ServeRequest

    with pytest.raises(ValueError):
        ServeRequest("f", (1 << 80,))
    ServeRequest("f", ((1 << 63) - 1, -(1 << 63)))  # extremes fit

    # fleet routing (r16): a request whose rendezvous owner is a
    # SUSPECT peer refuses retryably with Retry-After — 503 at the
    # edge with detail "peer_suspect", never a bare 503 string (the
    # over-the-wire half is pinned in tests/test_fleet.py)
    from wasmedge_tpu.fleet import PeerSuspect
    from wasmedge_tpu.gateway.http import retry_after_of, \
        submit_status_of

    ps = PeerSuspect("10.0.0.2:8080", 41)
    assert ps.retryable is True
    info = rejection_info(ps)
    assert info["retryable"] is True
    assert info["retry_after_s"] > 0
    assert info["detail"] == "peer_suspect"
    assert submit_status_of(ps) == 503
    assert retry_after_of(ps) is not None

    # strict journal replication failure withdraws the acceptance with
    # the same retryable contract as a failed local journal write
    from wasmedge_tpu.fleet import ReplicationFailed

    rf = ReplicationFailed("no peer reachable")
    assert rejection_info(rf)["retryable"] is True


def test_server_submit_rejections_carry_the_flag():
    """BatchServer.submit's two rejection classes are distinguishable
    by flag alone — the gateway's status mapping and the CLI retry
    loop both branch on it, never on strings."""
    from tests.test_serve import _server

    conf = _conf()
    conf.serve.queue_capacity = 1
    srv = _server(conf=conf, lanes=1, quotas={"blocked": 0})
    # permanent admission block: non-retryable
    with pytest.raises(WasmError) as exc:
        srv.submit("fib", [5], tenant="blocked")
    assert exc.value.retryable is False
    # transient backpressure: retryable (fill the 1-slot queue without
    # stepping, so nothing is admitted meanwhile)
    srv.submit("fib", [10])
    with pytest.raises(WasmError) as exc:
        srv.submit("fib", [10])
    assert exc.value.retryable is True
    srv.run_until_idle()
    srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# CLI entry
# ---------------------------------------------------------------------------
def test_cli_gateway_command(tmp_path):
    """Startup line with the bound address + modules, clean --duration
    exit with the summary line.  Deliberately NO invoke: serving is
    covered above, and an invoke would compile a default-geometry
    engine just for this test."""
    import io

    from wasmedge_tpu.cli import gateway_command

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    wasm2 = tmp_path / "dbl.wasm"
    wasm2.write_bytes(build_dbl())
    out, errs = io.StringIO(), io.StringIO()
    rc = gateway_command(
        [str(wasm), "--port", "0", "--lanes", "2",
         "--module", f"second={wasm2}",
         "--duration", "0.2"], out=out, err=errs)
    assert rc == 0, errs.getvalue()
    lines = out.getvalue().splitlines()
    startup = json.loads(lines[0])
    assert startup["modules"] == ["main", "second"]
    assert startup["listening"].startswith("http://127.0.0.1:")
    assert startup["lanes"] == 2
    # the boot health gate ran and the startup line reports it
    assert startup["health"] == "healthy"
    assert startup["durable"] is False and startup["restarts"] == 0
    summary = json.loads(lines[-1])
    assert summary["metric"] == "gateway_exit"
    assert summary["received"] == 0
    # the whole boot set shares ONE generation (no build-and-drain
    # churn per --module)
    assert summary["generations"] == 1

    rc2 = gateway_command(["--module", "badspec"], out=io.StringIO(),
                          err=errs)
    assert rc2 == 2
    assert "badspec" in errs.getvalue()
