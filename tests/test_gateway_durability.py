"""Durable gateway: crash/restart resume, swap rollback, health-gated
shedding (wasmedge_tpu/gateway/durable.py + health.py, marker `serve`).

Pins the r13 acceptance contract:

  - a deterministic generation build/swap fault rolls back ATOMICALLY:
    the prior generation keeps serving bit-identically, the failed
    registration returns a retryable 503-class GenerationBuildFailed,
    the probe-cache stash makes the retry skip the re-lowering, and
    the rollback is counted + flight-recorded
  - a wedged generation build hits the build timeout and rolls back
    the same way (the registration lock is never held unboundedly)
  - kill (no drain, no flush) + resume over the same state_dir brings
    back the module set under one boot generation, replays resolved
    ids from the durable result cache (exactly-once), and re-queues
    unresolved ids under their ORIGINAL ids (at-least-once)
  - a faulted durable journal write REJECTS the submission retryably
    (the 202 id is never issued undurably) and degrades health
  - /healthz is truthful: dead driver / failed generation -> 503,
    rollback/journal trouble -> degraded-200 with machine-readable
    checks; the CLI gateway command exits non-zero on an unhealthy boot
  - degraded gateways shed lowest-weight-tier traffic with retryable
    429s (ShedLoad, detail "shed"), never sole-tier traffic
  - a pruned async id answers 404 with the distinct "pruned" detail,
    and result_cache is a working config knob

Speed discipline: tier-1 fast — tiny geometry, the module-scoped JAX
persistent cache shared with tests/test_gateway.py's idiom, and HTTP
only where the wire contract itself is under test.
"""

import json
import tempfile
import time

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import WasmError, rejection_info
from wasmedge_tpu.gateway import (
    Gateway,
    GatewayService,
    GatewayTenants,
    GenerationBuildFailed,
)
from wasmedge_tpu.gateway.health import ShedLoad
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from wasmedge_tpu.utils.builder import ModuleBuilder

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="gateway-durable-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    return conf


def build_dbl() -> bytes:
    b = ModuleBuilder()
    b.add_function(["i64"], ["i64"], [],
                   [("local.get", 0), ("i64.const", 2), "i64.mul",
                    ("i64.const", 7), "i64.add"],
                   export="dbl")
    return b.build()


def _invoke(svc, func, args, module=None, tenant="default"):
    req = svc.submit(func, args, module=module, tenant=tenant)
    assert svc.wait(req, timeout_s=120.0)
    return req.future.result(0)


# ---------------------------------------------------------------------------
# swap rollback: deterministic fault, atomic, retryable, stash reused
# ---------------------------------------------------------------------------
def test_generation_build_fault_rolls_back_atomically():
    inj = FaultInjector([Fault(point="generation_build", at=1)])
    svc = GatewayService(conf=_conf(obs=True), lanes=2, faults=inj)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        before = _invoke(svc, "fib", [12], module="fib")
        gen_before = svc.generation
        lowered_before = svc.registry.lowered_count

        with pytest.raises(GenerationBuildFailed) as exc:
            svc.register_module("dbl", wasm_bytes=build_dbl())
        # retryable 503 class with a Retry-After hint on the wire
        assert exc.value.retryable is True
        info = rejection_info(exc.value)
        assert info["retryable"] is True
        from wasmedge_tpu.gateway.http import (
            retry_after_of,
            submit_status_of,
        )

        assert submit_status_of(exc.value) == 503
        assert retry_after_of(exc.value) is not None

        # atomic: no half-swapped pointer, module set unchanged, the
        # prior generation serves bit-identically
        assert svc.generation == gen_before
        assert svc.registry.names == ["fib"]
        assert _invoke(svc, "fib", [12], module="fib") == before \
            == [_fib(12)]
        assert svc.counters["rollbacks"] == 1
        assert svc.last_swap is not None and not svc.last_swap["ok"]
        assert "generation_rollback" in svc.obs.event_names()

        # one registration lowered dbl exactly once; the rolled-back
        # engine is stashed, so the retry adopts it instead of
        # re-lowering — and then the swap succeeds
        assert svc.registry.lowered_count == lowered_before + 1
        out = svc.register_module("dbl", wasm_bytes=build_dbl())
        assert out["generation"] == gen_before + 1
        assert svc.registry.lowered_count == lowered_before + 1
        assert svc.last_swap["ok"] is True
        assert _invoke(svc, "dbl", [5], module="dbl") == [17]
    finally:
        svc.shutdown()


def test_generation_swap_fault_never_half_swaps():
    """The swap seam fires before the server starts or the pointer
    moves: an injected swap fault leaves the submit pointer on the
    prior generation, which keeps serving."""
    inj = FaultInjector([Fault(point="generation_swap", at=1)])
    svc = GatewayService(conf=_conf(), lanes=2, faults=inj)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        gen_before = svc.generation
        with pytest.raises(GenerationBuildFailed):
            svc.register_module("dbl", wasm_bytes=build_dbl())
        assert svc.generation == gen_before
        assert len(svc._gens) == 1   # nothing half-installed
        assert svc.registry.names == ["fib"]
        assert _invoke(svc, "fib", [10], module="fib") == [55]
    finally:
        svc.shutdown()


def test_build_timeout_rolls_back_and_recovers(monkeypatch):
    from wasmedge_tpu.gateway.registry import ModuleRegistry

    svc = GatewayService(conf=_conf(), lanes=2, build_timeout_s=0.2)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    orig = ModuleRegistry.build_engine
    calls = []

    def wedged(self, conf, lanes):
        calls.append(1)
        time.sleep(1.5)   # a wedged compile, well past the timeout
        return orig(self, conf, lanes)

    try:
        monkeypatch.setattr(ModuleRegistry, "build_engine", wedged)
        t0 = time.monotonic()
        with pytest.raises(GenerationBuildFailed) as exc:
            svc.register_module("dbl", wasm_bytes=build_dbl())
        # the registration lock was released at the TIMEOUT, not when
        # the wedged build eventually finished
        assert time.monotonic() - t0 < 1.2
        assert "timeout" in str(exc.value)
        assert exc.value.retryable is True
        assert svc.counters["rollbacks"] == 1
        monkeypatch.setattr(ModuleRegistry, "build_engine", orig)
        # the abandoned build thread committed nothing; a clean retry
        # swaps in generation 2 and both modules serve
        out = svc.register_module("dbl", wasm_bytes=build_dbl())
        assert out["generation"] == 2
        assert _invoke(svc, "fib", [10], module="fib") == [55]
        assert _invoke(svc, "dbl", [4], module="dbl") == [15]
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# durability: kill -> resume brings back modules, ids, results
# ---------------------------------------------------------------------------
def test_kill_resume_restores_modules_and_request_ids(tmp_path):
    d = str(tmp_path / "state")
    svc = GatewayService(conf=_conf(), lanes=2, state_dir=d)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    done = svc.submit("fib", [10], module="fib")
    assert svc.wait(done, timeout_s=120.0)
    assert done.future.result(0) == [55]
    done_id = done.id
    # a long request left unresolved at the kill
    pending = svc.submit("fib", [24], module="fib")
    pending_id = pending.id
    time.sleep(0.2)   # give the serving loop a round or two
    svc.kill()        # no drain, no flush — the honest crash

    svc2 = GatewayService(conf=_conf(obs=True), lanes=2, state_dir=d,
                          resume=True)
    try:
        # module set back under one boot generation
        assert svc2.registry.names == ["fib"]
        assert svc2.counters["generations"] == 1
        assert svc2.counters["restarts"] == 1
        assert svc2.counters["resumed"] >= 1
        assert "gateway_resume" in svc2.obs.event_names()

        # resolved-before-crash id replays from the durable result
        # cache: exactly-once (same result, NOT re-counted as new work)
        state, req = svc2.request_state(done_id)
        assert state == "ok"
        assert req.future.done and req.future.result(0) == [55]
        assert svc2.counters["completed"] == 0

        # the unresolved id survives under its ORIGINAL id and
        # resolves (re-queued or adopted; at-least-once)
        state, req2 = svc2.request_state(pending_id)
        assert state == "ok"
        assert req2.future.wait(120.0)
        assert req2.future.error is None
        assert req2.future.result(0) == [_fib(24)]

        # fresh submissions never collide with restored ids
        fresh = svc2.submit("fib", [9], module="fib")
        assert fresh.id > pending_id
        assert svc2.wait(fresh, timeout_s=120.0)
    finally:
        svc2.shutdown()

    # a second restart keeps counting (the manifest carries the tally)
    svc3 = GatewayService(conf=_conf(), lanes=2, state_dir=d,
                          resume=True)
    try:
        assert svc3.counters["restarts"] == 2
        assert svc3.registry.names == ["fib"]
    finally:
        svc3.shutdown()


def test_corrupt_newest_journal_falls_back(tmp_path):
    """The durable snapshots ride the lineage contract: a torn/corrupt
    newest member is skipped (and counted), the previous one loads."""
    import os

    from wasmedge_tpu.gateway.durable import DurableStore

    d = str(tmp_path)
    store = DurableStore(d)
    store.write_journal([{"id": 1, "func": "f", "args": []}], [])
    store.write_journal([{"id": 2, "func": "f", "args": []}], [])
    newest = sorted(fn for fn in os.listdir(d)
                    if fn.startswith("journal-"))[-1]
    with open(os.path.join(d, newest), "w") as f:
        f.write('{"truncated')
    store2 = DurableStore(d)
    _, journal = store2.load()
    assert journal["unresolved"][0]["id"] == 1
    assert store2.load_errors == 1


def test_journal_write_fault_rejects_submission(tmp_path):
    """A submit whose durable journal write faults is rejected with a
    retryable DurabilityError — the id is NEVER accepted undurably,
    and the acceptance is WITHDRAWN (out of the stash, out of the
    received tally, pulled back from the serving queue so the guest
    does not run disowned work) — and health degrades until a write
    succeeds."""
    from wasmedge_tpu.gateway.durable import DurabilityError

    inj = FaultInjector([Fault(point="journal_write", at=0,
                               match={"kind": "journal"})])
    svc = GatewayService(conf=_conf(), lanes=2, faults=inj,
                         state_dir=str(tmp_path / "state"))
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        with pytest.raises(DurabilityError) as exc:
            svc.submit("fib", [8], module="fib")
        assert exc.value.retryable is True
        from wasmedge_tpu.gateway.http import submit_status_of

        assert submit_status_of(exc.value) == 503
        assert svc.counters["journal_errors"] == 1
        # the acceptance was fully withdrawn
        assert svc.counters["received"] == 0
        assert len(svc._requests) == 0
        h = svc.health()
        assert h["status"] == "degraded"
        assert h["checks"]["journal"]["ok"] is False
        # the next submit journals fine and health recovers
        req = svc.submit("fib", [8], module="fib")
        assert svc.wait(req, timeout_s=120.0)
        assert svc.health()["checks"]["journal"]["ok"] is True
    finally:
        svc.shutdown()


def test_withdraw_pulls_a_queued_request_back():
    """BatchServer.withdraw removes a not-yet-admitted request from
    the queue (counted rejected, counters reconcile); an already-
    admitted id reports False and is left to finish."""
    from tests.test_serve import _server

    srv = _server(lanes=1)
    # no driver thread: nothing gets admitted until we step
    f1 = srv.submit("fib", [10])
    f2 = srv.submit("fib", [11])
    assert srv.withdraw(f2.request_id) is True
    assert srv.withdraw(f2.request_id) is False   # already gone
    assert len(srv.queue) == 1
    srv.run_until_idle()
    assert f1.result(0) == [55]
    assert not f2.done   # withdrawn, never ran
    c = srv.counters
    assert c["rejected"] == 1
    assert c["submitted"] == c["completed"] + c["rejected"]
    srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# truthful health + CLI boot gate
# ---------------------------------------------------------------------------
def test_healthz_truthful_over_http():
    from wasmedge_tpu.common.errors import EngineFailure

    svc = GatewayService(conf=_conf(), lanes=2)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gw = Gateway(svc, port=0).start()
    try:
        from tests.test_gateway import rpc

        st, doc, _ = rpc(gw, "GET", "/healthz")
        assert st == 200 and doc["ok"] and doc["status"] == "healthy"
        assert doc["checks"]["driver"]["ok"] is True

        # degraded (failed last swap): still 200, machine-readable why
        svc.last_swap = {"ok": False, "generation": 1,
                         "error": "InjectedFault('generation_build')",
                         "t": 0.0}
        st, doc, _ = rpc(gw, "GET", "/healthz")
        assert st == 200 and doc["status"] == "degraded"
        assert doc["checks"]["last_swap"]["ok"] is False
        svc.last_swap = None

        # unhealthy (terminally failed generation): 503 — the r11 stub
        # would have said 200 here
        srv = svc.current.server
        srv.failed = EngineFailure("driver dead for the test")
        st, doc, _ = rpc(gw, "GET", "/healthz")
        assert st == 503 and not doc["ok"]
        assert doc["status"] == "unhealthy"
        assert doc["checks"]["driver"]["ok"] is False
        srv.failed = None
        st, doc, _ = rpc(gw, "GET", "/healthz")
        assert st == 200
    finally:
        gw.shutdown(drain=False)


def test_cli_resume_reuses_the_same_command_line(tmp_path):
    """A restart runs the SAME command line (systemd et al.): boot
    modules the manifest already restored must be skipped, not
    re-registered into a ModuleNameConflict."""
    import io

    from wasmedge_tpu.cli import gateway_command

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    d = str(tmp_path / "state")
    argv = [str(wasm), "--port", "0", "--lanes", "2",
            "--state-dir", d, "--duration", "0.1"]
    out, errs = io.StringIO(), io.StringIO()
    assert gateway_command(argv, out=out, err=errs) == 0, errs.getvalue()
    out2, errs2 = io.StringIO(), io.StringIO()
    rc = gateway_command(argv + ["--resume"], out=out2, err=errs2)
    assert rc == 0, errs2.getvalue()
    startup = json.loads(out2.getvalue().splitlines()[0])
    assert startup["modules"] == ["main"]
    assert startup["restarts"] == 1 and startup["durable"] is True
    # --resume without --state-dir is a usage error
    rc = gateway_command(["--resume"], out=io.StringIO(),
                         err=(e3 := io.StringIO()))
    assert rc == 2 and "--state-dir" in e3.getvalue()


def test_cli_gateway_exits_nonzero_on_unhealthy_boot(tmp_path,
                                                    monkeypatch):
    import io

    from wasmedge_tpu.cli import gateway_command

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())

    def unhealthy(self, fresh=True):
        return {"ok": False, "status": "unhealthy", "checks": {
            "driver": {"ok": False, "level": "unhealthy",
                       "detail": "driver thread died at boot"}}}

    monkeypatch.setattr(GatewayService, "health", unhealthy)
    out, errs = io.StringIO(), io.StringIO()
    rc = gateway_command([str(wasm), "--port", "0", "--lanes", "2",
                          "--duration", "0.1"], out=out, err=errs)
    assert rc == 1
    assert "unhealthy" in errs.getvalue()
    assert "driver thread died" in errs.getvalue()


# ---------------------------------------------------------------------------
# health-gated shedding
# ---------------------------------------------------------------------------
def test_degraded_gateway_sheds_lowest_weight_tier():
    tenants = GatewayTenants.from_dict({"tenants": {
        "gold": {"weight": 3.0},
    }})
    # tiers: {3.0, 1.0-default} -> floor 1.0: default-tier tenants shed
    assert tenants.shed_weight_floor() == 1.0
    svc = GatewayService(conf=_conf(), lanes=2, tenants=tenants)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        svc.force_degraded = True
        with pytest.raises(ShedLoad) as exc:
            svc.submit("fib", [8], module="fib", tenant="bronze")
        assert exc.value.retryable is True
        info = rejection_info(exc.value)
        assert info["retryable"] is True and info["detail"] == "shed"
        from wasmedge_tpu.gateway.http import (
            retry_after_of,
            submit_status_of,
        )

        assert submit_status_of(exc.value) == 429
        assert retry_after_of(exc.value) is not None
        assert svc.counters["shed"] == 1
        assert svc.shed_counts == {"bronze": 1}
        # gold traffic keeps flowing while degraded
        assert _invoke(svc, "fib", [10], module="fib",
                       tenant="gold") == [55]
        # the per-tenant counter lands in the Prometheus export
        text = svc.metrics_text()
        assert 'wasmedge_gateway_shed_total{tenant="bronze"} 1' in text
        # recovery: healthy again -> the shed tenant serves
        svc.force_degraded = False
        assert _invoke(svc, "fib", [9], module="fib",
                       tenant="bronze") == [34]
    finally:
        svc.shutdown()


def test_single_tier_never_sheds():
    """With every tenant on one weight tier there is no 'lowest' to
    sacrifice — shedding everyone would turn degradation into an
    outage, so the gateway falls back to ordinary backpressure."""
    tenants = GatewayTenants()
    assert tenants.shed_weight_floor() is None
    # under require_auth the phantom 1.0 default tier must not count:
    # two authenticated tenants both at 0.5 are ONE tier, unsheddable
    closed = GatewayTenants.from_dict({
        "require_auth": True,
        "tenants": {"a": {"api_key": "ka", "weight": 0.5},
                    "b": {"api_key": "kb", "weight": 0.5}}})
    assert closed.shed_weight_floor() is None
    # the same weights in an OPEN config shed (unlisted tenants ride
    # the 1.0 default tier above them)
    open_ = GatewayTenants.from_dict({
        "tenants": {"a": {"weight": 0.5}, "b": {"weight": 0.5}}})
    assert open_.shed_weight_floor() == 0.5
    svc = GatewayService(conf=_conf(), lanes=2, tenants=tenants)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        svc.force_degraded = True
        assert svc.health()["status"] == "degraded"
        assert _invoke(svc, "fib", [8], module="fib") == [21]
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# stash pruning vs polling clients + result_cache knob
# ---------------------------------------------------------------------------
def test_pruned_async_id_distinct_404_and_result_cache_knob():
    svc = GatewayService(conf=_conf(), lanes=2, result_cache=2)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    gw = Gateway(svc, port=0).start()
    try:
        ids = []
        for n in (8, 9, 10):
            req = svc.submit("fib", [n], module="fib")
            assert svc.wait(req, timeout_s=120.0)
            ids.append(req.id)
        # result_cache=2: the oldest resolved id was pruned
        assert svc.request_state(ids[0]) == ("pruned", None)
        state, req = svc.request_state(ids[2])
        assert state == "ok" and req.future.result(0) == [55]

        from tests.test_gateway import rpc

        # distinct machine-readable detail for the pruned id ...
        st, doc, _ = rpc(gw, "GET", f"/v1/requests/{ids[0]}")
        assert st == 404
        assert doc["err"]["detail"] == "pruned"
        assert doc["err"]["name"] == "NotFound"
        # ... which a never-issued id does NOT carry
        st, doc, _ = rpc(gw, "GET", "/v1/requests/999999")
        assert st == 404
        assert "detail" not in doc["err"]
        # live ids still poll fine
        st, doc, _ = rpc(gw, "GET", f"/v1/requests/{ids[2]}")
        assert st == 200 and doc["ok"] and doc["result"] == [55]
    finally:
        gw.shutdown(drain=False)


def test_aged_out_id_answers_pruned_after_resume(tmp_path):
    """A resolved id whose entry aged out of the durable result cache
    still answers the PRUNED 404 detail after a restart (the journaled
    max-id floor marks it issued-and-aged) — never the generic
    unknown-id message a client would read as 'my 202 never existed'."""
    d = str(tmp_path / "state")
    svc = GatewayService(conf=_conf(), lanes=2, state_dir=d,
                         result_cache=1)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    first = svc.submit("fib", [8], module="fib")
    assert svc.wait(first, timeout_s=120.0)
    second = svc.submit("fib", [9], module="fib")
    assert svc.wait(second, timeout_s=120.0)
    # result_cache=1: first's durable entry was displaced by second's
    assert svc.request_state(first.id) == ("pruned", None)
    svc.kill()
    svc2 = GatewayService(conf=_conf(), lanes=2, state_dir=d,
                          result_cache=1, resume=True)
    try:
        assert svc2.request_state(first.id) == ("pruned", None)
        state, req = svc2.request_state(second.id)
        assert state == "ok" and req.future.result(0) == [34]
        # a genuinely never-issued id stays "unknown"
        assert svc2.request_state(999999) == ("unknown", None)
        # fresh ids allocate above the journaled floor
        fresh = svc2.submit("fib", [8], module="fib")
        assert fresh.id > second.id
        assert svc2.wait(fresh, timeout_s=120.0)
    finally:
        svc2.shutdown()


def test_gateway_closed_is_retryable_with_retry_after():
    """'Gateway shutting down' carries the full retryable contract
    (503 + Retry-After): the same request is welcome at the restarted
    gateway — while the permanent admission block (same ErrCode) stays
    non-retryable."""
    from wasmedge_tpu.gateway.http import retry_after_of
    from wasmedge_tpu.gateway.service import GatewayClosed

    from wasmedge_tpu.common.errors import ErrCode

    exc = GatewayClosed()
    assert exc.retryable is True
    assert rejection_info(exc)["retryable"] is True
    assert retry_after_of(exc) is not None
    assert WasmError(ErrCode.Terminated).retryable is False


# ---------------------------------------------------------------------------
# chaos plumbing: seeded schedule + restart counters in the export
# ---------------------------------------------------------------------------
def test_gateway_chaos_schedule_is_deterministic():
    from wasmedge_tpu.testing.faults import gateway_chaos_schedule

    a = gateway_chaos_schedule(13)
    b = gateway_chaos_schedule(13)
    assert [(f.point, f.at) for f in a] == [(f.point, f.at) for f in b]
    points = {f.point for f in a}
    assert points & {"launch", "serve"}
    assert points & {"generation_build", "generation_swap"}
    assert "journal_write" in points
    # the swap fault targets the FIRST runtime registration (arrival 0
    # is the boot build), so one registration deterministically draws it
    swap = [f for f in a
            if f.point in ("generation_build", "generation_swap")]
    assert all(f.at == 1 + 2 * k for k, f in enumerate(swap))
    # drops only ever target the (retried-harmlessly) polling route
    for f in a:
        if f.point == "http_response_drop":
            assert f.match == {"route": "requests"}


def test_restart_and_rollback_counters_in_prometheus():
    from wasmedge_tpu.obs.metrics import parse_prometheus

    svc = GatewayService(conf=_conf(), lanes=2)
    svc.register_module("fib", wasm_bytes=build_fib(), source="boot")
    try:
        parsed = parse_prometheus(svc.metrics_text())
        assert parsed[("wasmedge_gateway_restarts_total",
                       frozenset())] == 0.0
        assert parsed[("wasmedge_generation_rollbacks_total",
                       frozenset())] == 0.0
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# parked sessions (effects/): kill/restart with a majority-parked
# population resumes every session exactly-once (r23)
# ---------------------------------------------------------------------------
def _await_mod() -> bytes:
    """wait(n) -> await_event(buf=64, len=8, nwritten=32); returns
    first-payload-word + n (delivery AND guest-state survival)."""
    b = ModuleBuilder()
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i64"], ["i64"], [], [
        ("i32.const", 64), ("i32.const", 8), ("i32.const", 32),
        ("call", 0), "drop",
        ("i32.const", 64), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="wait")
    return b.build()


def test_kill_resume_resumes_parked_sessions_exactly_once(tmp_path):
    """Majority-parked kill/restart: 3 of 4 lanes park on await_event,
    the gateway dies without drain, and the resumed process restores
    EVERY parked session exactly-once — adopted as parked (parks stays
    0 on the new server: nothing re-executed from scratch), unresolved
    until its wake arrives, then bit-identical to a never-killed run."""
    import struct

    d = str(tmp_path / "state")

    def conf():
        c = _conf()
        c.effects.suspend = True
        return c

    svc = GatewayService(conf=conf(), lanes=4, state_dir=d)
    svc.register_module("awaitmod", wasm_bytes=_await_mod(),
                        source="boot")
    ids = [svc.submit("wait", [10 + i], module="awaitmod").id
           for i in range(3)]
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if svc.status().get("sessions", {}).get("parked") == 3:
            break
        time.sleep(0.02)
    else:
        raise TimeoutError("sessions never parked")
    # cadence-1 serve checkpoint (state_dir forces it) lands at the
    # parking round's boundary; give the drive loop a beat to write it
    time.sleep(0.3)
    svc.kill()

    svc2 = GatewayService(conf=conf(), lanes=4, state_dir=d,
                          resume=True)
    try:
        sessions = svc2.status()["sessions"]
        # exactly-once restore: the full parked population is back as
        # PARKED state (no re-execution — a re-run would re-park and
        # bump the new process's park counter)
        assert sessions["parked"] == 3
        assert sessions["parks"] == 0
        for rid in ids:
            state, req = svc2.request_state(rid)
            assert state == "ok" and not req.future.done
        # each wake resolves its ORIGINAL id exactly once
        reqs = []
        for i, rid in enumerate(ids):
            out = svc2.wake(rid, struct.pack("<I", 100 + i))
            assert out["ok"] and out["state"] == "parked"
            reqs.append(svc2.request_state(rid)[1])
        for i, req in enumerate(reqs):
            assert svc2.wait(req, timeout_s=120.0)
            assert req.future.result(0) == [100 + i + 10 + i]
        final = svc2.status()["sessions"]
        assert final["parked"] == 0
        assert final["resumes"] == 3
        assert svc2.counters["restarts"] == 1
        # fresh ids allocate above the adopted window
        fresh = svc2.submit("wait", [1], module="awaitmod")
        assert fresh.id > max(ids)
        assert svc2.wake(fresh.id, struct.pack("<I", 7))["ok"]
        assert svc2.wait(fresh, timeout_s=120.0)
        assert fresh.future.result(0) == [8]
    finally:
        svc2.shutdown()
