"""Device→host outcall channel: batched host-function calls.

BASELINE config 4's shape (WASI echo, batched) — modules importing host
functions now run on the batch engines: lanes park at the HOSTCALL stub,
the host drains them through the same runtime/hostfunc.py layer the
scalar engine calls, and results/memory effects land back in the SoA
state lane by lane (wasmedge_tpu/batch/hostcall.py; the reference analog
is the AOT intrinsics escape, lib/executor/engine/proxy.cpp:45-71).
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.host.wasi import WasiModule
from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

LANES = 8


def make_batch(data, imports, conf=None, lanes=LANES, pallas=False):
    conf = conf or Configure()
    conf.batch.steps_per_launch = 10_000
    ex, store, inst = instantiate(data, conf, imports=imports)
    if pallas:
        from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine

        eng = PallasUniformEngine(inst, store=store, conf=conf, lanes=lanes,
                                  interpret=True)
        assert eng.eligible, eng.ineligible_reason
    else:
        from wasmedge_tpu.batch import BatchEngine

        eng = BatchEngine(inst, store=store, conf=conf, lanes=lanes)
    return ex, store, inst, eng


def _double_module():
    b = ModuleBuilder()
    b.import_func("env", "double", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("call", 0),
        ("i32.const", 1), "i32.add",
    ], export="f")
    return b.build()


def _host_double():
    imp = ImportObject("env")
    calls = []

    def double(mem, x):
        calls.append(x)
        return x * 2

    imp.add_func("double", PyHostFunction(double, ["i32"], ["i32"]))
    return imp, calls


@pytest.mark.parametrize("pallas", [False, True])
def test_simple_hostcall_per_lane(pallas):
    imp, calls = _host_double()
    ex, store, inst, eng = make_batch(_double_module(), [imp], pallas=pallas)
    args = np.arange(LANES, dtype=np.int64) * 10
    res = eng.run("f", [args], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == args * 2 + 1).all()
    assert sorted(calls) == sorted(args.tolist())


def test_hostcall_memory_effects():
    """Host writes into each lane's isolated linear memory."""
    imp = ImportObject("env")

    def poke(mem, addr, val):
        mem.store(addr, 4, val & 0xFFFFFFFF)
        return val + 1

    imp.add_func("poke", PyHostFunction(poke, ["i32", "i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "poke", ["i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i32"], ["i32"], [], [
        ("i32.const", 64), ("local.get", 0), ("call", 0),
        ("i32.const", 64), ("i32.load", 2, 0), "i32.add",
    ], export="f")
    ex, store, inst, eng = make_batch(b.build(), [imp])
    vals = np.arange(LANES, dtype=np.int64) + 100
    res = eng.run("f", [vals], max_steps=10_000)
    assert (res.trap == -1).all()
    # poke returns val+1; load returns val -> result = 2*val + 1
    assert (res.results[0] == 2 * vals + 1).all()


def test_hostcall_trap_propagates():
    from wasmedge_tpu.common.errors import ErrCode, trap

    imp = ImportObject("env")

    def bad(mem, x):
        if x == 3:
            trap(ErrCode.ExecutionFailed)
        return x

    imp.add_func("id_or_trap", PyHostFunction(bad, ["i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "id_or_trap", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="f")
    ex, store, inst, eng = make_batch(b.build(), [imp])
    args = np.arange(LANES, dtype=np.int64)
    res = eng.run("f", [args], max_steps=10_000)
    assert res.trap[3] == int(ErrCode.ExecutionFailed)
    ok = [i for i in range(LANES) if i != 3]
    assert (res.trap[ok] == -1).all()
    assert (res.results[0][ok] == args[ok]).all()


def test_wasi_echo_batched_matches_scalar(tmp_path):
    """BASELINE config 4: WASI echo with --batch semantics.

    Each lane writes its own memory's message via fd_write to a shared
    capture file; the batch output must be the scalar instance's output
    once per lane."""
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_active_data(0, [("i32.const", 64)], b"hello from wasm\n")
    b.add_function([], ["i32"], [], [
        # iovec at 0: {buf=64, len=16}
        ("i32.const", 0), ("i32.const", 64), ("i32.store", 2, 0),
        ("i32.const", 4), ("i32.const", 16), ("i32.store", 2, 0),
        ("i32.const", 1),   # fd: stdout
        ("i32.const", 0),   # iovs
        ("i32.const", 1),   # iovs_len
        ("i32.const", 32),  # nwritten ptr
        ("call", 0),
    ], export="echo")
    data = b.build()

    # scalar reference output
    scal_out = tmp_path / "scalar.out"
    with open(scal_out, "w+b") as fh:
        wasi = WasiModule()
        wasi.init_wasi()
        wasi.env.fds[1].os_fd = fh.fileno()  # capture guest stdout
        ex, store, inst = instantiate(data, Configure(), imports=[wasi])
        r = ex.invoke(store, inst.find_func("echo"), [])
        assert r == [0]
    expected = open(scal_out, "rb").read()
    assert expected == b"hello from wasm\n"

    batch_out = tmp_path / "batch.out"
    with open(batch_out, "w+b") as fh:
        wasi = WasiModule()
        wasi.init_wasi()
        wasi.env.fds[1].os_fd = fh.fileno()
        ex, store, inst, eng = make_batch(data, [wasi])
        res = eng.run("echo", [], max_steps=100_000)
        assert (res.trap == -1).all()
        assert (res.results[0] == 0).all()
    assert open(batch_out, "rb").read() == expected * LANES


def test_hostcall_loop_bounded_by_max_steps():
    """A guest looping over host calls must stop at max_steps (pallas)."""
    imp = ImportObject("env")
    imp.add_func("h", PyHostFunction(lambda mem: None, [], []))
    b = ModuleBuilder()
    b.import_func("env", "h", [], [])
    b.add_function([], [], [],
                   [("loop", None), ("call", 0), ("br", 0), "end"],
                   export="spin")
    conf = Configure()
    conf.batch.steps_per_launch = 50
    ex, store, inst, eng = make_batch(b.build(), [imp], conf=conf,
                                      pallas=True)
    res = eng.run("spin", [], max_steps=400)
    assert res.steps <= 500  # bounded, not hung


def test_hostcall_mixed_traps_no_duplicate_calls():
    """Served lanes' host calls must not re-run after a mixed-trap
    handoff (side effects would double)."""
    from wasmedge_tpu.common.errors import ErrCode, trap

    calls = []
    imp = ImportObject("env")

    def bad(mem, x):
        calls.append(x)
        if x == 3:
            trap(ErrCode.ExecutionFailed)
        return x

    imp.add_func("f", PyHostFunction(bad, ["i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "f", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="g")
    ex, store, inst, eng = make_batch(b.build(), [imp], pallas=True)
    res = eng.run("g", [np.arange(LANES, dtype=np.int64)], max_steps=10_000)
    assert sorted(calls) == list(range(LANES))
    assert res.trap[3] == int(ErrCode.ExecutionFailed)
    ok = [i for i in range(LANES) if i != 3]
    assert (res.results[0][ok] == np.arange(LANES)[ok]).all()


def test_hostcall_grow_beyond_watermark_fails_cleanly():
    """A host function growing memory past the pallas watermark plane
    must get -1 (clean failure), never silent truncation of its writes
    (the plane holds mem_pages_init pages; grown-page bytes would be
    dropped by store_lane_memory)."""
    from wasmedge_tpu.common.configure import Configure

    imp = ImportObject("env")
    grow_results = []

    def grow_and_write(mem, _x):
        r = mem.grow(1)
        grow_results.append(r)
        mem.store(64, 4, 0x1234)      # write within the existing page
        if r >= 0:
            mem.store(65536, 4, 0xABCD)   # write into the grown page
        return 1 if r >= 0 else 0

    imp.add_func("gw", PyHostFunction(grow_and_write, ["i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "gw", ["i32"], ["i32"])
    b.add_memory(1, 3)   # declared max 3 > watermark capacity 1
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("call", 0),
        ("i32.const", 64), ("i32.load", 2, 0), "i32.add",
    ], export="f")
    conf = Configure()
    conf.batch.memory_pages_per_lane = 3
    ex, store, inst, eng = make_batch(b.build(), [imp], conf=conf,
                                      pallas=True)
    res = eng.run("f", [np.zeros(LANES, np.int64)], max_steps=10_000)
    assert (res.trap == -1).all()
    # grow failed cleanly on every lane; the in-page write survived
    assert all(r == -1 for r in grow_results)
    assert (res.results[0] == 0x1234).all()
