"""Three-tier hostcall pipeline tests (batch/hostcall.py).

Tier 0: pure WASI calls retired inside the SIMT kernel with ZERO
device<->host round trips (witnessed by serve_rounds == 0).
Tier 1: parked lanes drained by SoA-vectorized WASI implementations
(host/wasi/vectorized.py), byte-identical with the scalar oracle.
Tier 2: the block scheduler overlaps CPU drain with device compute —
covered here end-to-end through the Pallas(interpret) engine.

Fast by design (a few hundred calls, CPU backend): this is the tier-1
smoke coverage for the pipeline.
"""

import os

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.host.wasi import WasiModule
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate
from tests.test_hostcall import make_batch

LANES = 8

WASI = "wasi_snapshot_preview1"


def _echo_module(msg=b"hello from wasm\n", iovs=1):
    """fd_write(1, iov, iovs, nw_ptr) of `msg` (split across `iovs`
    iovecs), returning the errno."""
    assert len(msg) % iovs == 0
    part = len(msg) // iovs
    b = ModuleBuilder()
    b.import_func(WASI, "fd_write", ["i32"] * 4, ["i32"])
    b.add_memory(1, 1)
    b.add_active_data(0, [("i32.const", 64)], msg)
    body = []
    for k in range(iovs):
        body += [
            ("i32.const", 8 * k), ("i32.const", 64 + k * part),
            ("i32.store", 2, 0),
            ("i32.const", 8 * k + 4), ("i32.const", part),
            ("i32.store", 2, 0),
        ]
    body += [
        ("i32.const", 1), ("i32.const", 0), ("i32.const", iovs),
        ("i32.const", 48), ("call", 0),
    ]
    b.add_function([], ["i32"], [], body, export="echo")
    return b.build()


def _scalar_output(data, tmp_path, name, args=()):
    out = tmp_path / f"{name}.scalar"
    with open(out, "w+b") as fh:
        wasi = WasiModule()
        wasi.init_wasi()
        wasi.env.fds[1].os_fd = fh.fileno()
        ex, store, inst = instantiate(data, Configure(), imports=[wasi])
        r = ex.invoke(store, inst.find_func("echo"), list(args))
        assert r == [0]
    return open(out, "rb").read()


def _batch_output(data, tmp_path, name, pallas, conf=None, args=None,
                  lanes=LANES):
    out = tmp_path / f"{name}.batch"
    with open(out, "w+b") as fh:
        wasi = WasiModule()
        wasi.init_wasi()
        wasi.env.fds[1].os_fd = fh.fileno()
        ex, store, inst, eng = make_batch(data, [wasi], conf=conf,
                                          lanes=lanes, pallas=pallas)
        res = eng.run("echo", args or [], max_steps=100_000)
        assert (res.trap == -1).all()
    return open(out, "rb").read(), eng


def test_tier0_fdwrite_zero_roundtrips(tmp_path):
    """Acceptance: tier-0 fd_write completes with ZERO device<->host
    round trips — no serve round ever runs, yet the bytes land."""
    data = _echo_module()
    expected = _scalar_output(data, tmp_path, "t0")
    got, eng = _batch_output(data, tmp_path, "t0", pallas=False)
    assert got == expected * LANES
    st = eng.hostcall_stats
    assert st["tier0_fd_write"] == LANES
    assert st["serve_rounds"] == 0
    assert st["tier1_calls"] == 0


def test_echo_parity_scalar_simt_pallas(tmp_path):
    """Echo output is byte-identical across scalar, SIMT, and Pallas
    (block scheduler with overlapped serve) engines."""
    data = _echo_module()
    expected = _scalar_output(data, tmp_path, "par")
    simt, _ = _batch_output(data, tmp_path, "par_simt", pallas=False)
    pall, _ = _batch_output(data, tmp_path, "par_pallas", pallas=True)
    assert simt == expected * LANES
    assert pall == expected * LANES


def test_tier1_vectorized_multi_iovec_parity(tmp_path):
    """iovs_len=2 is not tier-0-eligible: lanes park and the tier-1
    vectorized drain must reproduce the scalar bytes exactly."""
    data = _echo_module(iovs=2)
    expected = _scalar_output(data, tmp_path, "t1")
    got, eng = _batch_output(data, tmp_path, "t1", pallas=False)
    assert got == expected * LANES
    st = eng.hostcall_stats
    assert st["tier1_vectorized"] == LANES
    assert st["serve_rounds"] >= 1


def test_tier0_disabled_matches(tmp_path):
    """tier0_hostcalls=False forces everything through tier 1 with the
    same observable bytes."""
    data = _echo_module()
    expected = _scalar_output(data, tmp_path, "off")
    conf = Configure()
    conf.batch.tier0_hostcalls = False
    got, eng = _batch_output(data, tmp_path, "off", pallas=False,
                             conf=conf)
    assert got == expected * LANES
    assert eng.hostcall_stats["tier0_calls"] == 0
    assert eng.hostcall_stats["tier1_calls"] == LANES


def _ordering_module(iters):
    """Per iteration: write byte (arg) then byte (64+arg) to fd 1 —
    per-lane call ordering is observable in the interleaved output."""
    b = ModuleBuilder()
    b.import_func(WASI, "fd_write", ["i32"] * 4, ["i32"])
    b.add_memory(1, 1)
    body = [
        # msg A at 128 = arg; msg B at 129 = 64 + arg
        ("i32.const", 128), ("local.get", 0), ("i32.store8", 0, 0),
        ("i32.const", 129), ("local.get", 0), ("i32.const", 64),
        "i32.add", ("i32.store8", 0, 0),
        # iovec A at 0: {128, 1}; iovec B at 8: {129, 1}
        ("i32.const", 0), ("i32.const", 128), ("i32.store", 2, 0),
        ("i32.const", 4), ("i32.const", 1), ("i32.store", 2, 0),
        ("i32.const", 8), ("i32.const", 129), ("i32.store", 2, 0),
        ("i32.const", 12), ("i32.const", 1), ("i32.store", 2, 0),
        ("block", None), ("loop", None),
        ("local.get", 1), ("i32.const", iters), "i32.ge_u", ("br_if", 1),
        ("i32.const", 1), ("i32.const", 0), ("i32.const", 1),
        ("i32.const", 48), ("call", 0), "drop",
        ("i32.const", 1), ("i32.const", 8), ("i32.const", 1),
        ("i32.const", 48), ("call", 0), "drop",
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0), "end", "end",
        ("i32.const", 0),
    ]
    b.add_function(["i32"], ["i32"], ["i32"], body, export="echo")
    return b.build()


@pytest.mark.parametrize("pallas", [False, True])
def test_per_lane_ordering(tmp_path, pallas):
    """Per-lane WASI call ordering is preserved by both the tier-0
    buffer flush and the tier-1 vectorized drain, for every engine."""
    iters = 5
    data = _ordering_module(iters)
    args = [np.arange(LANES, dtype=np.int64)]
    got, _ = _batch_output(data, tmp_path, f"ord{pallas}", pallas=pallas,
                           args=args)
    assert len(got) == LANES * iters * 2
    for lane in range(LANES):
        a, bch = lane, 64 + lane
        seq = [c for c in got if c in (a, bch)]
        assert seq == [a, bch] * iters, f"lane {lane} order broken"


def _clock_module():
    """Two monotonic clock reads; returns (t1 < t2) as i32."""
    b = ModuleBuilder()
    b.import_func(WASI, "clock_time_get", ["i32", "i64", "i32"], ["i32"])
    b.add_memory(1, 1)
    body = [
        ("i32.const", 1), ("i64.const", 0), ("i32.const", 64),
        ("call", 0), "drop",
        ("i32.const", 1), ("i64.const", 0), ("i32.const", 72),
        ("call", 0), "drop",
        ("i32.const", 64), ("i64.load", 3, 0),
        ("i32.const", 72), ("i64.load", 3, 0),
        "i64.lt_u",
    ]
    b.add_function([], ["i32"], [], body, export="f")
    return b.build()


def test_tier0_clock_monotonic():
    """In-kernel clock_time_get: strictly increasing per lane, zero
    round trips."""
    ex, store, inst, eng = make_batch(_clock_module(), [WasiModule()])
    res = eng.run("f", [], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == 1).all()
    assert eng.hostcall_stats["tier0_clock"] == 2 * LANES
    assert eng.hostcall_stats["serve_rounds"] == 0


def test_tier0_clock_bad_id_errno():
    """Invalid clock id returns EINVAL (28) in-kernel; cputime ids park
    and are served on tier 1 — both without wrong answers."""
    b = ModuleBuilder()
    b.import_func(WASI, "clock_time_get", ["i32", "i64", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i64.const", 0), ("i32.const", 64), ("call", 0),
    ], export="f")
    ex, store, inst, eng = make_batch(b.build(), [WasiModule()])
    ids = np.array([0, 1, 2, 3, 9, 1, 0, 2], np.int64)
    res = eng.run("f", [ids], max_steps=10_000)
    assert (res.trap == -1).all()
    expect = np.where(ids == 9, 28, 0)
    assert (res.results[0] == expect).all()


def _random_module(nbytes):
    """Returns first_word ^ (errno << 24): errno SUCCESS = raw word."""
    b = ModuleBuilder()
    b.import_func(WASI, "random_get", ["i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function([], ["i32"], ["i32"], [
        ("i32.const", 64), ("i32.const", nbytes), ("call", 0),
        ("local.set", 0),
        ("i32.const", 64), ("i32.load", 2, 0),
        ("local.get", 0), ("i32.const", 24), "i32.shl", "i32.xor",
    ], export="f")
    return b.build()


def _run_random(nbytes, seed=None, uniform=False):
    conf = Configure()
    conf.batch.steps_per_launch = 10_000
    if seed is not None:
        conf.batch.rng_seed = seed
    if uniform:
        from wasmedge_tpu.batch.uniform import UniformBatchEngine

        ex, store, inst = instantiate(_random_module(nbytes), conf,
                                      imports=[WasiModule()])
        eng = UniformBatchEngine(inst, store=store, conf=conf,
                                 lanes=LANES)
        stats_eng = eng.simt
    else:
        ex, store, inst, eng = make_batch(_random_module(nbytes),
                                          [WasiModule()], conf=conf)
        stats_eng = eng
    res = eng.run("f", [], max_steps=10_000)
    assert (res.trap == -1).all()
    return np.asarray(res.results[0]), stats_eng


def test_tier0_random_deterministic_under_seed():
    """In-kernel random_get: deterministic per (seed, lane, call), with
    per-lane distinct streams and zero round trips."""
    w1, eng = _run_random(16, seed=0xABC)
    w2, _ = _run_random(16, seed=0xABC)
    w3, _ = _run_random(16, seed=0xDEF)
    assert (w1 == w2).all()
    assert not (w1 == w3).all()
    assert len(set(w1.tolist())) > 1        # lanes get distinct bytes
    assert eng.hostcall_stats["tier0_random"] == LANES
    assert eng.hostcall_stats["serve_rounds"] == 0


def test_tier0_random_unseeded_is_fresh_entropy():
    """Without an explicit rng_seed, every Configure draws fresh
    entropy — guests must not see a predictable stream by default."""
    u1, _ = _run_random(16)
    u2, _ = _run_random(16)
    assert not (u1 == u2).all()


def test_tier0_random_uniform_simt_bit_identical():
    """The uniform fast path and the SIMT engine hand-maintain twin
    tier-0 implementations; this pins the documented contract that the
    random stream is bit-identical across them (a divergence handoff
    mid-workload must continue the same stream)."""
    ws, _ = _run_random(16, seed=0x1234)
    wu, eng_simt = _run_random(16, seed=0x1234, uniform=True)
    assert (ws == wu).all()
    # the uniform engine retired the calls itself (no SIMT fallback)
    assert eng_simt.hostcall_stats["tier0_random"] == LANES
    assert eng_simt.hostcall_stats["serve_rounds"] == 0


def test_random_oversized_falls_to_tier1():
    """Requests beyond tier0_random_max park and drain vectorized."""
    words, eng = _run_random(4096)
    assert eng.hostcall_stats["tier0_random"] == 0
    assert eng.hostcall_stats["tier1_vectorized"] == LANES


def test_sched_yield_tier0():
    b = ModuleBuilder()
    b.import_func(WASI, "sched_yield", [], ["i32"])
    b.add_function([], ["i32"], [], [("call", 0)], export="f")
    ex, store, inst, eng = make_batch(b.build(), [WasiModule()])
    res = eng.run("f", [], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == 0).all()
    assert eng.hostcall_stats["tier0_sys"] == LANES
    assert eng.hostcall_stats["serve_rounds"] == 0


@pytest.mark.parametrize("tier0", [True, False])
def test_proc_exit_terminates_lanes(tier0):
    """proc_exit terminates the lane with ErrCode.Terminated on both
    the in-kernel and the vectorized tier-1 paths (the per-lane legacy
    loop used to let WasiExit escape and kill the whole batch)."""
    b = ModuleBuilder()
    b.import_func(WASI, "proc_exit", ["i32"], [])
    b.add_memory(1, 1)
    b.add_function(["i32"], [], [], [
        ("local.get", 0), ("call", 0),
    ], export="f")
    conf = Configure()
    conf.batch.steps_per_launch = 10_000
    conf.batch.tier0_hostcalls = tier0
    wasi = WasiModule()
    ex, store, inst, eng = make_batch(b.build(), [wasi], conf=conf)
    res = eng.run("f", [np.full(LANES, 7, np.int64)], max_steps=10_000)
    assert (res.trap == int(ErrCode.Terminated)).all()
    if not tier0:
        assert wasi.env.exited and wasi.env.exit_code == 7


def test_hostcall_smoke_few_hundred_calls(tmp_path):
    """Fast pipeline smoke: a few hundred calls through all three
    tiers' machinery on the CPU backend (tier-1 CI regression net)."""
    iters = 8
    lanes = 32
    data = _ordering_module(iters)
    args = [np.arange(lanes, dtype=np.int64) % 50]
    got, eng = _batch_output(data, tmp_path, "smoke", pallas=False,
                             args=args, lanes=lanes)
    assert len(got) == lanes * iters * 2
    st = eng.hostcall_stats
    assert st["tier0_calls"] + st["tier1_calls"] == lanes * iters * 2


def test_v128_residue_quarantine():
    """A long-divergent v128 tenant must not run the SIMT fallback
    unbounded (it faults TPU workers): the residue step-cap quarantines
    survivors onto the scalar engine, results stay correct."""
    b = ModuleBuilder()
    b.add_memory(1, 2)
    body = [
        # memory.grow beyond the pallas watermark plane: the kernel
        # stops ST_REGROW and the scheduler hands the whole block to
        # the SIMT residue (the designated big-plane engine)
        ("i32.const", 1), "memory.grow", "drop",
        ("local.get", 0), ("i32.const", 4), "i32.mul",
        ("i32.const", 256), "i32.add",
        ("local.get", 0), ("i32.store", 2, 0),
        # v128 spin: trip count scales with the argument
        ("block", None), ("loop", None),
        ("local.get", 1),
        # 40 trips per unit of the argument: long enough that even the
        # fused SIMT build (batch/fuse.py retires whole straight-line
        # runs per dispatch) overruns the capped residue window
        ("local.get", 0), ("i32.const", 40), "i32.mul",
        "i32.ge_u", ("br_if", 1),
        ("local.get", 1), "i32x4.splat", "v128.any_true", "drop",
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0), "end", "end",
        ("local.get", 0), ("i32.const", 4), "i32.mul",
        ("i32.const", 256), "i32.add", ("i32.load", 2, 0),
        ("local.get", 0), "i32.add",
    ]
    b.add_function(["i32"], ["i32"], ["i32"], body, export="f")
    data = b.build()
    conf = Configure()
    conf.batch.steps_per_launch = 2_000
    conf.batch.v128_residue_step_cap = 1_000
    conf.batch.memory_pages_per_lane = 2
    args = np.array([2, 3, 2, 3, 60, 80, 60, 80], np.int64)
    ex, store, inst, eng = make_batch(data, [], conf=conf, pallas=True)
    res = eng.run("f", [args], max_steps=5_000_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == 2 * args).all()
    assert getattr(eng, "quarantined", 0) > 0
