"""Lane-memory virtualization suite (wasmedge_tpu/hv/, marker `hv`).

Pins the r14 acceptance contract:
  - oversubscribed results bit-identical to a never-swapped run (same
    scoping as the r9 recycler guarantee: lane-placement-independent
    guests), with swaps in BOTH directions and admitted concurrency
    beyond the physical lane count
  - deterministic LRU victim selection; eviction never picks a
    mid-hostcall-drain lane or the sole runnable lane
  - swap store crash-atomicity, refcounting, and corrupt-entry
    detection (a corrupt live swap-in rejects ONE request machine-
    readably; the server keeps serving)
  - deterministic fault seams: a faulted swap-out leaves the lane
    resident and retries next boundary; a faulted swap-in re-queues
    the virtual lane without losing it
  - checkpoint/resume with a majority-swapped population (swapped
    blobs embedded in the snapshot npz; cross-process adoption)
  - per-tenant resident-budget quota enforcement over HTTP
  - the stall-rejection sweep and /healthz queue check treat "no
    physical lane free but virtual headroom available" as
    backpressure, not a permanent admission block

Speed discipline: tier-1 fast — shared tiny engine geometry (lanes
2/4, chunk 256, stacks 128/64) and a module-scoped JAX persistent
compilation cache, mirroring tests/test_serve.py.
"""

import tempfile

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.hv.policy import (
    EvictionCandidate,
    pick_victims,
    resident_lane_cap,
)
from wasmedge_tpu.hv.swapstore import SwapCorrupt, SwapStore
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.serve import BatchServer
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.hv

TRAP_HOSTCALL = -2


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="hv-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(max_virtual=None, budget=None, obs=False, swap_dir=None):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    conf.hv.max_virtual_lanes = max_virtual
    conf.hv.resident_budget_bytes = budget
    conf.hv.swap_dir = swap_dir
    return conf


def _server(conf=None, lanes=4, **kw):
    conf = conf or _conf()
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


NS = [5, 11, 12, 7, 3, 12, 9, 2, 10, 6, 12, 11, 8, 12, 4, 9]


# ---------------------------------------------------------------------------
# eviction policy (pure, deterministic)
# ---------------------------------------------------------------------------
def _cand(lane, last=0, since=0, deadline=None, trap=0):
    return EvictionCandidate(lane=lane, last_progress_step=last,
                             resident_since_round=since,
                             deadline=deadline, trap=trap)


def test_policy_lru_order_and_determinism():
    cands = [_cand(0, last=300), _cand(1, last=100), _cand(2, last=200),
             _cand(3, last=100)]
    # LRU first (stalest last-progress), lane index breaks the tie
    got = pick_victims(cands, 3, now=0.0, current_round=5)
    assert got == [1, 3, 2]
    # same inputs, same order — every time
    for _ in range(5):
        assert pick_victims(list(reversed(cands)), 3, now=0.0,
                            current_round=5) == got


def test_policy_deadline_distant_bias():
    now = 100.0
    cands = [_cand(0, deadline=now + 0.1),   # imminent: protect
             _cand(1, deadline=now + 50.0),  # distant
             _cand(2, deadline=None)]        # no deadline: most evictable
    # the sole-runnable guard keeps one survivor, so the IMMINENT
    # deadline is the lane that stays resident
    got = pick_victims(cands, 3, now=now, current_round=5)
    assert got == [2, 1]


def test_policy_never_mid_drain_lane():
    cands = [_cand(0, trap=TRAP_HOSTCALL), _cand(1), _cand(2)]
    got = pick_victims(cands, 3, now=0.0, current_round=5)
    assert 0 not in got


def test_policy_never_sole_runnable_lane():
    # one runnable lane: nothing may be evicted (the device would idle)
    assert pick_victims([_cand(0)], 1, now=0.0, current_round=5) == []
    # two runnable: at most one (a runnable survivor always remains)
    got = pick_victims([_cand(0), _cand(1)], 2, now=0.0,
                       current_round=5)
    assert len(got) == 1
    # ... unless the caller is installing replacements this boundary
    got = pick_victims([_cand(0)], 1, now=0.0, current_round=5,
                       incoming_runnable=1)
    assert got == [0]


def test_policy_min_resident_rounds():
    cands = [_cand(0, since=5), _cand(1, since=3)]
    got = pick_victims(cands, 2, now=0.0, current_round=5,
                       min_resident_rounds=1)
    assert got == [1]   # lane 0 installed THIS round: not evictable


def test_resident_lane_cap_math():
    assert resident_lane_cap(8, None, 1000) == 8
    assert resident_lane_cap(8, 4000, 1000) == 4
    assert resident_lane_cap(8, 100, 1000) == 1     # never 0: deadlock
    assert resident_lane_cap(8, 10**9, 1000) == 8   # clamped to lanes


# ---------------------------------------------------------------------------
# swap store
# ---------------------------------------------------------------------------
def test_swapstore_roundtrip_refcount_and_disk(tmp_path):
    st = SwapStore(dir=str(tmp_path))
    key = st.put(b"hello lane state")
    assert st.get(key) == b"hello lane state"
    assert (tmp_path / f"{key}.lane").exists()
    # content-addressed: identical payloads share the entry
    assert st.put(b"hello lane state") == key
    assert len(st) == 1
    st.release(key)
    assert st.get(key) == b"hello lane state"   # one ref remains
    st.release(key)
    assert len(st) == 0
    assert not (tmp_path / f"{key}.lane").exists()
    with pytest.raises(SwapCorrupt):
        st.get(key)


def test_swapstore_detects_corruption(tmp_path):
    st = SwapStore(dir=str(tmp_path))
    key = st.put(b"precious bits")
    st._mem[key] = b"rotted bits!!"
    with pytest.raises(SwapCorrupt):
        st.get(key)
    # adopt() verifies before trusting a snapshot blob
    st2 = SwapStore()
    with pytest.raises(SwapCorrupt):
        st2.adopt(key, b"not the right content")


def test_swapstore_write_fault_leaves_nothing(tmp_path):
    inj = FaultInjector([Fault(point="swap_store_write", at=0)])
    st = SwapStore(dir=str(tmp_path), faults=inj)
    with pytest.raises(Exception):
        st.put(b"doomed payload")
    assert len(st) == 0
    assert list(tmp_path.iterdir()) == []   # no blob, no temp litter
    # the next attempt (fault exhausted) succeeds
    key = st.put(b"doomed payload")
    assert st.get(key) == b"doomed payload"


# ---------------------------------------------------------------------------
# oversubscription end to end
# ---------------------------------------------------------------------------
def test_oversub_bit_identical_to_unswapped_run():
    ref_srv = _server(_conf(), lanes=4)
    ref_futs = [ref_srv.submit("fib", [n]) for n in NS]
    ref_srv.run_until_idle()
    ref = [f.result(0)[0] for f in ref_futs]
    assert ref == [_fib(n) for n in NS]

    srv = _server(_conf(max_virtual=16), lanes=4)
    futs = [srv.submit("fib", [n]) for n in NS]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref
    hv = srv.hv_stats()
    assert hv["swaps_out"] > 0 and hv["swaps_in"] > 0
    assert hv["peak_admitted"] > 4            # true oversubscription
    c = srv.counters
    assert c["completed"] == len(NS)
    assert c["submitted"] == c["completed"] + c["trapped"] \
        + c["expired"] + c["killed"] + c["rejected"]
    assert c["rejected"] == 0   # backpressure never became a sweep


def test_resident_budget_caps_installed_lanes():
    # budget for exactly one lane: serial residency, everything still
    # completes (admission counts the budget, not the free-lane heap)
    conf = _conf(max_virtual=8)
    srv = _server(conf, lanes=4)
    one_lane_budget = srv.hv.lane_bytes  # exactly one lane's bytes
    conf2 = _conf(max_virtual=8, budget=one_lane_budget)
    srv2 = _server(conf2, lanes=4)
    assert srv2.hv.resident_cap == 1
    futs = [srv2.submit("fib", [n]) for n in NS[:6]]
    srv2.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:6]]
    assert srv2.hv.peak_admitted > 1   # admitted beyond residency
    hv = srv2.hv_stats()
    assert hv["resident_cap"] == 1


def test_swap_dir_spills_to_disk(tmp_path):
    srv = _server(_conf(max_virtual=8, swap_dir=str(tmp_path)), lanes=2)
    futs = [srv.submit("fib", [n]) for n in NS[:8]]
    # drive a few rounds: some lane state must hit the directory
    seen_blob = False
    for _ in range(40):
        if not srv.step():
            break
        if any(p.suffix == ".lane" for p in tmp_path.iterdir()):
            seen_blob = True
    srv.run_until_idle()
    assert seen_blob
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:8]]


# ---------------------------------------------------------------------------
# fault seams
# ---------------------------------------------------------------------------
def test_swap_out_fault_leaves_lane_resident_and_retries():
    inj = FaultInjector([Fault(point="swap_out", at=0, times=2)])
    srv = _server(_conf(max_virtual=12), lanes=4, faults=inj)
    futs = [srv.submit("fib", [n]) for n in NS[:12]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:12]]
    hv = srv.hv_stats()
    assert hv["swap_out_faults"] == 2          # both injected arrivals
    assert hv["swaps_out"] > 0                 # retried and succeeded
    assert any(f.fault_class == "swap" for f in srv.failures)


def test_swap_in_fault_requeues_without_losing_the_lane():
    inj = FaultInjector([Fault(point="swap_in", at=0, times=2)])
    srv = _server(_conf(max_virtual=12), lanes=4, faults=inj)
    futs = [srv.submit("fib", [n]) for n in NS[:12]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:12]]
    hv = srv.hv_stats()
    assert hv["swap_in_faults"] == 2
    assert hv["swaps_in"] > 0


def test_swap_store_write_fault_is_a_swap_out_fault():
    inj = FaultInjector([Fault(point="swap_store_write", at=0)])
    srv = _server(_conf(max_virtual=12), lanes=4, faults=inj)
    futs = [srv.submit("fib", [n]) for n in NS[:12]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:12]]
    assert srv.hv_stats()["swap_out_faults"] == 1


def test_corrupt_swap_entry_rejects_only_that_request():
    from wasmedge_tpu.serve.queue import ServeRejected

    srv = _server(_conf(max_virtual=12), lanes=2)
    futs = [srv.submit("fib", [n]) for n in NS[:10]]
    # drive until some lane state is actually swapped out, then rot it
    corrupted = 0
    for _ in range(200):
        if not srv.step():
            break
        if corrupted == 0:
            with srv._lock:
                swapped = [v for v in srv.hv.waiting.values()
                           if v.key is not None]
                for v in swapped[:1]:
                    srv.hv.store._mem[v.key] = b"bit rot"
                    corrupted += 1
    srv.run_until_idle()
    assert corrupted == 1
    outcomes = []
    for f in futs:
        assert f.done
        outcomes.append(f.error)
    rejected = [e for e in outcomes if e is not None]
    assert len(rejected) == 1
    assert isinstance(rejected[0], ServeRejected)
    assert "corrupt" in str(rejected[0])
    assert srv.hv_stats()["swap_corrupt"] == 1
    # everyone else still finished with the right answers
    good = [f.result(0)[0] for f in futs if f.error is None]
    assert len(good) == 9
    # the loss is an in-flight kill: outcome counters reconcile
    c = srv.counters
    assert c["killed"] == 1
    assert c["submitted"] == c["completed"] + c["trapped"] \
        + c["expired"] + c["killed"] + c["rejected"]


# ---------------------------------------------------------------------------
# checkpoint / resume with a majority-swapped population
# ---------------------------------------------------------------------------
def test_checkpoint_resume_majority_swapped(tmp_path):
    conf = _conf(max_virtual=12)
    conf.serve.checkpoint_dir = str(tmp_path)
    srv = _server(conf, lanes=2)
    ns = [12, 12, 11, 12, 11, 12, 11, 12, 11, 12]
    futs = [srv.submit("fib", [n]) for n in ns]
    by_id = {f.request_id: n for f, n in zip(futs, ns)}
    # run a few rounds: 2 resident, the rest virtual (majority swapped
    # or fresh off-device)
    for _ in range(6):
        srv.step()
    with srv._lock:
        swapped = sum(1 for v in srv.hv.waiting.values()
                      if v.key is not None)
        waiting = len(srv.hv.waiting)
        resident = len(srv._bindings)
    assert waiting > resident          # majority off-device
    assert swapped > 0
    path = srv.checkpoint()
    assert path is not None

    # "crash": a fresh server adopts the lineage cross-process style
    conf2 = _conf(max_virtual=12)
    conf2.serve.checkpoint_dir = str(tmp_path)
    srv2 = _server(conf2, lanes=2, resume=True)
    # every in-flight request came back: resident + virtual
    assert set(srv2.adopted) == set(by_id)
    srv2.run_until_idle()
    for rid, fut in srv2.adopted.items():
        assert fut.result(0)[0] == _fib(by_id[rid])


def test_recover_restores_virtual_table_in_process(tmp_path):
    from wasmedge_tpu.testing.faults import InjectedFault

    conf = _conf(max_virtual=12)
    conf.serve.checkpoint_dir = str(tmp_path)
    conf.serve.checkpoint_every_rounds = 1
    inj = FaultInjector([Fault(point="launch", at=8)])
    srv = _server(conf, lanes=2, faults=inj)
    ns = [12, 12, 11, 12, 11, 12, 11, 12]
    futs = [srv.submit("fib", [n]) for n in ns]
    srv.run_until_idle()
    assert inj.fired == 1
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in ns]
    assert srv.retries == 1
    assert isinstance(srv.failures[0].error, str)
    assert "InjectedFault" in srv.failures[0].error or \
        InjectedFault is not None


# ---------------------------------------------------------------------------
# backpressure, not a permanent block
# ---------------------------------------------------------------------------
def test_no_free_lane_with_headroom_is_backpressure_not_sweep():
    srv = _server(_conf(max_virtual=6), lanes=2)
    futs = [srv.submit("fib", [n]) for n in NS[:10]]
    # rounds where no physical lane is free and the queue holds the
    # overflow: the stall sweep must never fire
    srv.run_until_idle()
    assert srv.counters["rejected"] == 0
    assert all(f.error is None for f in futs)


def test_healthz_queue_check_hv_aware():
    from wasmedge_tpu.gateway.health import QUEUE_SATURATION_RATIO
    from wasmedge_tpu.serve.queue import ServeRequest

    # saturate the queue of an hv server that still has headroom
    conf = _conf(max_virtual=16)
    conf.serve.queue_capacity = 4
    srv = _server(conf, lanes=2)
    for _ in range(4):
        srv.queue.push(ServeRequest("fib", (5,)))
    assert len(srv.queue) / 4 >= QUEUE_SATURATION_RATIO

    class _Gen:
        gen_id = 1
        server = srv

    class _Svc:
        current = _Gen()
        last_swap = None
        durable = None
        force_degraded = False

    from wasmedge_tpu.gateway.health import health_of

    h = health_of(_Svc())
    assert h["checks"]["queue"]["ok"]          # headroom => healthy
    assert "headroom" in h["checks"]["queue"]["detail"]

    # the same saturation WITHOUT hv still degrades
    conf2 = _conf()
    conf2.serve.queue_capacity = 4
    srv2 = _server(conf2, lanes=2)
    for _ in range(4):
        srv2.queue.push(ServeRequest("fib", (5,)))
    _Gen.server = srv2
    h2 = health_of(_Svc())
    assert not h2["checks"]["queue"]["ok"]
    # drain the stranded futures so nothing leaks into other tests
    srv.queue.pop_all()
    srv2.queue.pop_all()


# ---------------------------------------------------------------------------
# obs / metrics
# ---------------------------------------------------------------------------
def test_hv_metrics_render_and_parse():
    from wasmedge_tpu.obs.metrics import parse_prometheus, \
        render_prometheus

    srv = _server(_conf(max_virtual=12, obs=True), lanes=4)
    futs = [srv.submit("fib", [n]) for n in NS[:12]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:12]]
    text = render_prometheus(recorder=srv.obs, hv_stats=srv.hv_stats())
    parsed = parse_prometheus(text)
    out = parsed[("wasmedge_hv_swaps_total",
                  frozenset({("direction", "out")}.union()))]
    inn = parsed[("wasmedge_hv_swaps_total",
                  frozenset({("direction", "in")}))]
    assert out > 0 and inn > 0
    assert ("wasmedge_hv_resident_lanes", frozenset()) in parsed
    assert ("wasmedge_hv_virtual_lanes", frozenset()) in parsed
    # swap latency histogram made it through the recorder
    count_keys = [k for k in parsed
                  if k[0] == "wasmedge_hv_swap_latency_seconds_count"]
    assert count_keys
    # swap instants landed on the hv track
    names = srv.obs.event_names()
    assert "swap_out" in names and "swap_in" in names


def test_hv_obs_off_is_default_and_silent():
    srv = _server(_conf(max_virtual=8), lanes=2)
    from wasmedge_tpu.obs.recorder import NULL_RECORDER

    assert srv.obs is NULL_RECORDER
    futs = [srv.submit("fib", [n]) for n in NS[:6]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:6]]


# ---------------------------------------------------------------------------
# CLI flags exist and parse
# ---------------------------------------------------------------------------
def test_cli_flags_parse():
    import io

    from wasmedge_tpu.cli import _gateway_parser, _serve_parser

    p = _serve_parser()
    assert p.parse(["--max-virtual-lanes", "32",
                    "--resident-budget-bytes", "1048576",
                    "--swap-dir", "/tmp/x", "app.wasm", "fib"],
                   io.StringIO())
    assert p._opts["max-virtual-lanes"].value == 32
    assert p._opts["resident-budget-bytes"].value == 1048576
    g = _gateway_parser()
    assert g.parse(["--max-virtual-lanes", "32",
                    "--resident-budget-bytes", "1048576"],
                   io.StringIO())
    assert g._opts["max-virtual-lanes"].value == 32


# ---------------------------------------------------------------------------
# per-tenant resident budget over HTTP
# ---------------------------------------------------------------------------
@pytest.mark.serve
def test_tenant_resident_budget_quota_over_http():
    import json
    from http.client import HTTPConnection

    from wasmedge_tpu.gateway import (
        Gateway,
        GatewayService,
        GatewayTenants,
    )

    conf = _conf(max_virtual=8)
    tenants = GatewayTenants.from_dict({
        "tenants": {
            # budget for exactly one resident lane (any positive value
            # below 2 lanes' bytes caps at 1; 1 byte floors to the
            # minimum of one lane)
            "small": {"resident_budget_bytes": 1},
            "big": {},
        }})
    svc = GatewayService(conf=conf, lanes=4, tenants=tenants)
    mod = build_fib()
    svc.register_module("fib", wasm_bytes=mod, source="test")
    gw = Gateway(svc, port=0).start()
    try:
        ids = []
        for i, (tenant, n) in enumerate(
                [("small", 11), ("small", 12), ("small", 11),
                 ("big", 12), ("big", 11), ("big", 12)]):
            c = HTTPConnection(gw.host, gw.port, timeout=60)
            c.request("POST", "/v1/invoke?async=1", body=json.dumps({
                "module": "fib", "func": "fib", "args": [n],
                "tenant": tenant}).encode())
            r = c.getresponse()
            body = json.loads(r.read())
            assert r.status == 202, body
            ids.append((body["request_id"], n))
            c.close()
        # poll all to completion
        import time as _t

        deadline = _t.monotonic() + 120
        for rid, n in ids:
            while True:
                c = HTTPConnection(gw.host, gw.port, timeout=60)
                c.request("GET", f"/v1/requests/{rid}")
                r = c.getresponse()
                body = json.loads(r.read())
                c.close()
                if body.get("status") == "done":
                    assert body["result"] == [_fib(n)]
                    break
                assert _t.monotonic() < deadline, body
                _t.sleep(0.05)
        # the status hv block proves the quota held: tenant "small"
        # never held more than its single budgeted physical lane
        c = HTTPConnection(gw.host, gw.port, timeout=60)
        c.request("GET", "/v1/status")
        st = json.loads(c.getresponse().read())
        c.close()
        assert "hv" in st
        assert st["hv"]["tenant_resident_caps"]["small"] == 1
        assert st["hv"]["peak_resident_by_tenant"].get("small", 0) <= 1
        assert st["hv"]["swaps_out"] >= 0
        # and the Prometheus export carries the hv series
        c = HTTPConnection(gw.host, gw.port, timeout=60)
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode()
        c.close()
        assert "wasmedge_hv_resident_lanes" in text
    finally:
        gw.shutdown(drain=False)


def test_capped_tenant_rotates_its_own_lane():
    """A capped tenant's waiter can only be seated by evicting the
    tenant's OWN resident lane.  When the LRU pick is another tenant's
    (colder) lane — whose eviction seats nobody — the planner must move
    on to the next victim in policy order, not abandon rotation: the
    capped tenant's virtual lane would otherwise starve."""
    conf = _conf(max_virtual=6)
    srv = _server(conf, lanes=2, resident_budgets={"a": 1})
    assert srv.hv.tenant_caps == {"a": 1}
    fa1 = srv.submit("fib", [12], tenant="a")
    fb1 = srv.submit("fib", [12], tenant="b")
    fa2 = srv.submit("fib", [12], tenant="a")   # waits: a is at cap
    srv.step()
    with srv._lock:
        assert fa2.request_id in srv.hv.waiting
        # make b's lane the LRU pick: stalest progress by far
        b_lane = next(lane for lane, r in srv._bindings.items()
                      if r.tenant == "b")
        a_lane = next(lane for lane, r in srv._bindings.items()
                      if r.tenant == "a")
        srv.hv._last_progress[b_lane] = -10**6
        srv.hv._last_progress[a_lane] = 10**6
    srv.step()
    # rotation happened by evicting a's OWN lane (b's eviction seats
    # nobody under a's cap), so a2 is now resident and a1 swapped
    with srv._lock:
        assert fa2.request_id not in srv.hv.waiting
        assert fa1.request_id in srv.hv.waiting
        assert b_lane in srv._bindings   # b was never evicted for a
    srv.run_until_idle()
    for f in (fa1, fb1, fa2):
        assert f.result(0)[0] == _fib(12)
    # the cap held throughout
    assert srv.hv.peak_resident_by_tenant.get("a", 0) <= 1


def test_deadline_expires_virtual_lane_off_device():
    import time as _t

    from wasmedge_tpu.serve.queue import DeadlineExceeded

    # min_resident_rounds high enough that the waiter cannot rotate in
    # before its deadline — it must expire OFF-device, as a virtual
    # lane (an admitted in-flight kill, not a queued expiry)
    conf = _conf(max_virtual=8)
    conf.hv.min_resident_rounds = 10_000
    srv = _server(conf, lanes=2)
    long_futs = [srv.submit("fib", [12]) for _ in range(2)]
    doomed = srv.submit("fib", [12], deadline_s=0.2)
    srv.step()   # round 1: doomed admits as a fresh virtual lane
    assert not doomed.done
    with srv._lock:
        assert doomed.request_id in srv.hv.waiting
    _t.sleep(0.25)
    srv.step()   # boundary: the virtual lane expires off-device
    assert doomed.done
    with pytest.raises(DeadlineExceeded):
        doomed.result(0)
    assert srv.counters["killed"] >= 1
    assert srv.counters["expired"] == 0
    srv.run_until_idle()
    assert all(f.result(0)[0] == _fib(12) for f in long_futs)
