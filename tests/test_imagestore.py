"""Imagestore: segmented images, the persistent compile cache, and
pre-initialized lane snapshots (wasmedge_tpu/imagestore/, marker
`serve`).

Pins the r22 acceptance contract:

  - segmented generation builds: registering module N+1 re-lowers
    NOTHING (lowered_count pin) and rebuilds no existing segment (the
    SegmentCache hit/build counters prove every prior module's segment
    was reused verbatim)
  - segmented-off bit-identity: the cached concatenation produces the
    exact image (fingerprint over every plane) and bases the r21
    inline path produces
  - snapshot-admitted results are bit-identical to template-init
    admission for a module with a nontrivial `_initialize`
  - the compile cache survives a kill/resume round trip: the resumed
    gateway registers its whole module set with ZERO fresh lowerings
  - a corrupt cache entry and a faulted cache read each fall back to a
    fresh lower (counted, correct results — never wrong code)
  - a faulted snapshot install falls back to template init (counted,
    correct results)
  - all knobs off is r21: no coldstart status block, no new metric
    families, no cache dir, no segment cache

Speed discipline: tier-1 fast — tiny geometry, module-scoped JAX
persistent cache, no HTTP (the wire rides gateway/http tests).
"""

import os
import tempfile

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.gateway import GatewayService
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from wasmedge_tpu.utils.builder import ModuleBuilder

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="imagestore-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _conf(segmented=False, compile_cache=False, snapshots=False,
          cache_dir=None):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = False
    conf.imagestore.segmented = segmented
    conf.imagestore.compile_cache = compile_cache
    conf.imagestore.compile_cache_dir = cache_dir
    conf.imagestore.snapshots = snapshots
    return conf


def build_affine(mul: int, add: int) -> bytes:
    b = ModuleBuilder()
    b.add_function(["i64"], ["i64"], [],
                   [("local.get", 0), ("i64.const", mul), "i64.mul",
                    ("i64.const", add), "i64.add"],
                   export="f")
    return b.build()


def build_lazyinit() -> bytes:
    """Nontrivial `_initialize`: sets a mutable global, writes memory,
    and flips an init flag.  `compute` lazily initializes, so the
    template-init path (runs init inside the first call) and the
    snapshot path (init already captured, flag set) must return
    bit-identical results."""
    b = ModuleBuilder()
    b.add_memory(1)
    b.add_global("i32", True, [("i32.const", 0)])   # init flag
    b.add_global("i64", True, [("i64.const", 0)])   # g
    b.add_function([], [], [],
                   [("i32.const", 1), ("global.set", 0),
                    ("i64.const", 7), ("global.set", 1),
                    ("i32.const", 0), ("i64.const", 42),
                    ("i64.store", 3, 0)],
                   export="_initialize")
    b.add_function(["i64"], ["i64"], [],
                   [("global.get", 0), "i32.eqz",
                    ("if", None), ("call", 0), "end",
                    ("local.get", 0), ("global.get", 1), "i64.add",
                    ("i32.const", 0), ("i64.load", 3, 0), "i64.add"],
                   export="compute")
    return b.build()


def _invoke(svc, func, args, module=None):
    req = svc.submit(func, args, module=module, tenant="default")
    assert svc.wait(req, timeout_s=120.0)
    return req.future.result(0)


# ---------------------------------------------------------------------------
# segmented device image: zero re-lowering / zero segment rebuilds
# ---------------------------------------------------------------------------
def test_segmented_registration_rebuilds_nothing():
    svc = GatewayService(conf=_conf(segmented=True), lanes=2)
    try:
        mods = [(f"m{k}", build_affine(2 + k, 7 * (k + 1)))
                for k in range(3)]
        for name, data in mods:
            svc.register_module(name, wasm_bytes=data)
        # each module lowered exactly once across ALL three generation
        # builds (registering N+1 re-lowers nothing) ...
        assert svc.registry.lowered_count == 3
        # ... and each module's SEGMENT was built exactly once: gen1
        # builds m0; gen2 reuses m0, builds m1; gen3 reuses m0+m1,
        # builds m2 -> 3 builds, 3 hits
        stats = svc.registry.segment_cache.stats()
        assert stats["builds"] == 3
        assert stats["hits"] == 3
        for k, (name, _) in enumerate(mods):
            assert _invoke(svc, "f", [10], module=name) == \
                [10 * (2 + k) + 7 * (k + 1)]
        assert "coldstart" in svc.status()
    finally:
        svc.shutdown()


def test_segmented_off_bitidentical():
    """The cached concatenation must produce the EXACT image and bases
    the r21 inline path produces — fingerprint over every plane."""
    from wasmedge_tpu.batch.image import image_fingerprint
    from wasmedge_tpu.gateway.registry import ModuleRegistry
    from wasmedge_tpu.imagestore import SegmentCache

    datas = [("m0", build_affine(3, 1)), ("m1", build_fib()),
             ("m2", build_lazyinit())]
    engines = []
    for seg in (False, True):
        conf = _conf()
        reg = ModuleRegistry(conf=conf)
        if seg:
            reg.segment_cache = SegmentCache()
        for name, data in datas:
            reg.add_wasm(name, data)
        engines.append(reg.build_engine(conf, 2))
    a, b = engines
    assert image_fingerprint(a.img) == image_fingerprint(b.img)
    assert a.bases == b.bases
    # and the cache actually mediated the second build
    # (one lookup per tenant, all misses on a cold cache)


# ---------------------------------------------------------------------------
# pre-initialized snapshots: bit-identical to template-init admission
# ---------------------------------------------------------------------------
def test_snapshot_bitidentical_to_template_init():
    want = [int(i) + 7 + 42 for i in (0, 5, 100)]
    got = {}
    for snap in (False, True):
        svc = GatewayService(conf=_conf(snapshots=snap), lanes=2)
        try:
            svc.register_module("lazy", wasm_bytes=build_lazyinit())
            got[snap] = [
                _invoke(svc, "compute", [i], module="lazy")[0]
                for i in (0, 5, 100)]
            if snap:
                counts = dict(svc.snapshot_counts)
                assert counts.get("captured") == 1
                assert counts.get("installs", 0) >= 3
                assert svc.registry.get("lazy").snapshot is not None
        finally:
            svc.shutdown()
    assert got[False] == got[True] == want


def test_snapshot_install_fault_falls_back_to_template():
    inj = FaultInjector([Fault(point="snapshot_install", at=0)])
    svc = GatewayService(conf=_conf(snapshots=True), lanes=2,
                         faults=inj)
    try:
        svc.register_module("lazy", wasm_bytes=build_lazyinit())
        # the overlay decode faulted: this generation admits through
        # template init — still correct, counted, never wrong state
        assert _invoke(svc, "compute", [5], module="lazy") == [54]
        counts = dict(svc.snapshot_counts)
        assert counts.get("install_faults", 0) >= 1
        assert counts.get("installs", 0) == 0
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# persistent compile cache: restart round trip, corruption, read faults
# ---------------------------------------------------------------------------
def test_compile_cache_restart_roundtrip():
    with tempfile.TemporaryDirectory() as state_dir:
        svc = GatewayService(conf=_conf(compile_cache=True), lanes=2,
                             state_dir=state_dir)
        try:
            svc.register_module("fib", wasm_bytes=build_fib())
            svc.register_module("aff", wasm_bytes=build_affine(2, 7))
            before = _invoke(svc, "fib", [12], module="fib")
            assert svc.registry.lowered_count == 2
            assert svc.registry.compile_cache.counts["stores"] == 2
        finally:
            svc.kill()
        svc2 = GatewayService(conf=_conf(compile_cache=True), lanes=2,
                              state_dir=state_dir, resume=True)
        try:
            # the WHOLE module set came back without one fresh lower
            assert svc2.registry.names == ["fib", "aff"]
            assert svc2.registry.lowered_count == 0
            assert svc2.registry.compile_cache.counts["disk_hits"] == 2
            assert _invoke(svc2, "fib", [12], module="fib") == before
            assert _invoke(svc2, "f", [10], module="aff") == [27]
        finally:
            svc2.shutdown()


def test_corrupt_cache_entry_lowers_fresh():
    with tempfile.TemporaryDirectory() as cache_dir:
        data = build_affine(5, 3)
        svc = GatewayService(
            conf=_conf(compile_cache=True, cache_dir=cache_dir),
            lanes=2)
        try:
            svc.register_module("aff", wasm_bytes=data)
            assert svc.registry.lowered_count == 1
        finally:
            svc.shutdown()
        entries = [fn for fn in os.listdir(cache_dir)
                   if fn.endswith(".img")]
        assert len(entries) == 1
        with open(os.path.join(cache_dir, entries[0]), "wb") as f:
            f.write(b"garbage" * 64)
        svc2 = GatewayService(
            conf=_conf(compile_cache=True, cache_dir=cache_dir),
            lanes=2)
        try:
            svc2.register_module("aff", wasm_bytes=data)
            # corrupt entry -> counted miss -> fresh lower, right code
            assert svc2.registry.lowered_count == 1
            assert svc2.registry.compile_cache.counts["corrupt"] >= 1
            assert _invoke(svc2, "f", [10], module="aff") == [53]
        finally:
            svc2.shutdown()


def test_cache_read_fault_lowers_fresh():
    with tempfile.TemporaryDirectory() as cache_dir:
        data = build_affine(4, 9)
        svc = GatewayService(
            conf=_conf(compile_cache=True, cache_dir=cache_dir),
            lanes=2)
        try:
            svc.register_module("aff", wasm_bytes=data)
        finally:
            svc.shutdown()
        inj = FaultInjector([Fault(point="cache_read", at=0)])
        svc2 = GatewayService(
            conf=_conf(compile_cache=True, cache_dir=cache_dir),
            lanes=2, faults=inj)
        try:
            svc2.register_module("aff", wasm_bytes=data)
            assert svc2.registry.lowered_count == 1
            assert svc2.registry.compile_cache.counts[
                "read_faults"] >= 1
            assert _invoke(svc2, "f", [10], module="aff") == [49]
        finally:
            svc2.shutdown()


# ---------------------------------------------------------------------------
# observability + all-knobs-off bit-identity
# ---------------------------------------------------------------------------
def test_imagestore_metrics_render():
    from wasmedge_tpu.obs.metrics import parse_prometheus

    svc = GatewayService(conf=_conf(segmented=True, compile_cache=True,
                                    snapshots=True), lanes=2)
    try:
        svc.register_module("lazy", wasm_bytes=build_lazyinit())
        svc.register_module("lazy2", wasm_bytes=build_lazyinit()
                            + b"")  # same bytes, new name
        parsed = parse_prometheus(svc.metrics_text())
        hits = {k: v for k, v in parsed.items()
                if k[0] == "wasmedge_compile_cache_hits_total"}
        assert hits  # probe/disk tiers both present
        # the second registration of identical bytes came off the cache
        assert sum(hits.values()) >= 1
        assert ("wasmedge_snapshot_installs_total",
                frozenset()) in parsed
        cs = svc.status()["coldstart"]
        assert cs["lowered_count"] == 1
        assert cs["compile_cache"]["enabled"] is True
    finally:
        svc.shutdown()


def test_knobs_off_is_r21():
    svc = GatewayService(conf=_conf(), lanes=2)
    try:
        svc.register_module("fib", wasm_bytes=build_fib())
        assert svc.imagestore_enabled is False
        assert svc.registry.segment_cache is None
        assert svc.registry.compile_cache.enabled is False
        assert svc.snapshot_store is None
        assert "coldstart" not in svc.status()
        text = svc.metrics_text()
        assert "wasmedge_compile_cache" not in text
        assert "wasmedge_snapshot" not in text
        assert _invoke(svc, "fib", [12], module="fib") == [144]
    finally:
        svc.shutdown()
