"""End-to-end integrity defense against silent corruption (r24,
wasmedge_tpu/integrity/, marker `integrity`).

Pins the r24 acceptance contract:

  - shadow-audit sampling is deterministic under a fixed seed (same
    boundaries -> same lane subsets, across sampler instances)
  - a clean audited run matches bit-exactly (zero divergence counted)
    and returns results bit-identical to the audit-off run
  - a bit flip injected into a BatchState lane plane is DETECTED by
    the shadow audit, recorded as an "integrity" FailureRecord, rolled
    back, and masked: final results stay bit-correct
  - a corrupted compile-cache entry is caught by the at-rest scrubber
    and evicted; the next registration lowers fresh, correct code
  - a corrupted parked-session blob is repaired from a fleet peer
    replica (GET /v1/fleet/blob/<key>) BEFORE the wake needs it, over
    real sockets, resolving bit-identically
  - a checkpoint member whose sha256 sidecar mismatches is quarantined
    (renamed `.corrupt`) so the recovery walk falls back
  - integrity off (the default) arms no hooks, adds no status block
    and no metric families — bit-identical r23 by construction

Fast by construction: tiny lane counts, short chunks, module-scoped
JAX persistent cache for the gateway legs.
"""

import os
import struct
import tempfile
import time

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.supervisor import BatchSupervisor
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.fleet import FleetConfig
from wasmedge_tpu.gateway import Gateway, GatewayService
from wasmedge_tpu.integrity import AuditSampler, Scrubber
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.testing.faults import BitFlip, FaultInjector, \
    flip_bit_bytes, flip_file
from tests.helpers import instantiate

pytestmark = pytest.mark.integrity

LANES = 16


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="integrity-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def make_conf(audit=False, **integ):
    conf = Configure()
    conf.batch.steps_per_launch = 100
    conf.batch.rng_seed = 7
    conf.supervisor.backoff_base_s = 0.0
    conf.supervisor.checkpoint_every_steps = 200
    conf.integrity.audit = audit
    if audit:
        conf.integrity.audit_every = 1     # audit every boundary
        conf.integrity.audit_lanes = 4
    for k, v in integ.items():
        setattr(conf.integrity, k, v)
    return conf


def make_engine(data, conf, lanes=LANES):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def fib_ref(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


FIB_ARGS = [(np.arange(LANES) % 11).astype(np.int64)]
FIB_WANT = np.array([fib_ref(n % 11) for n in range(LANES)])


# ---------------------------------------------------------------------------
# shadow-audit sampling: seeded, deterministic, bounded
# ---------------------------------------------------------------------------
def test_audit_sampler_deterministic_under_fixed_seed():
    a = AuditSampler(seed=5, every=4, lanes_per_audit=3)
    b = AuditSampler(seed=5, every=4, lanes_per_audit=3)
    picks_a = [a.pick(t, LANES) for t in range(64)]
    picks_b = [b.pick(t, LANES) for t in range(64)]
    for pa, pb in zip(picks_a, picks_b):
        if pa is None:
            assert pb is None
        else:
            assert (pa == pb).all()
    sampled = [p for p in picks_a if p is not None]
    assert sampled, "every=4 over 64 boundaries must sample some"
    assert len(sampled) < 64, "every=4 must not sample EVERY boundary"
    for p in sampled:
        assert len(p) == 3 and len(set(p.tolist())) == 3
        assert list(p) == sorted(p)          # stable gather order
        assert all(0 <= int(x) < LANES for x in p)
    # a different seed draws a different schedule (overwhelmingly)
    other = [AuditSampler(seed=6, every=4, lanes_per_audit=3)
             .pick(t, LANES) for t in range(64)]
    assert [None if p is None else p.tolist() for p in picks_a] != \
           [None if p is None else p.tolist() for p in other]


def test_audited_clean_run_matches_and_is_bit_identical(tmp_path):
    ref = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          checkpoint_dir=str(tmp_path / "ref"))
    rres = ref.run("fib", FIB_ARGS, max_steps=500_000)

    sup = BatchSupervisor(make_engine(build_fib(), make_conf(audit=True)),
                          checkpoint_dir=str(tmp_path / "a"))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    stats = sup.engine._audit_hook.stats
    assert stats["audits"] >= 1
    assert stats["match"] == stats["audits"]
    assert stats["divergence"] == 0
    assert not sup.failures
    # audit-on returns the exact bits audit-off returns
    assert (res.results[0] == rres.results[0]).all()
    assert (res.results[0] == FIB_WANT).all()
    assert (res.trap == rres.trap).all()
    assert (res.retired == rres.retired).all()


# ---------------------------------------------------------------------------
# detection: an injected lane-plane bit flip cannot survive silently
# ---------------------------------------------------------------------------
def test_audit_detects_plane_flip_rolls_back_and_masks(tmp_path):
    inj = FaultInjector([], flips=[
        BitFlip(point="corrupt_plane", at=1, seed=42)])
    sup = BatchSupervisor(make_engine(build_fib(), make_conf(audit=True)),
                          faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert inj.flipped == 1
    stats = sup.engine._audit_hook.stats
    assert stats["divergence"] >= 1
    assert "integrity" in [f.fault_class for f in sup.failures]
    # rollback + re-execution MASKED the corruption: exact results
    assert res.completed.all()
    assert (res.results[0] == FIB_WANT).all()


def test_audit_attributes_device_and_feeds_quarantine(tmp_path):
    inj = FaultInjector([], flips=[
        BitFlip(point="corrupt_plane", at=1, seed=9)])
    sup = BatchSupervisor(make_engine(build_fib(),
                                      make_conf(audit=True,
                                                quarantine_threshold=1)),
                          faults=inj, checkpoint_dir=str(tmp_path))
    sup.run("fib", FIB_ARGS, max_steps=500_000)
    q = sup.engine._audit_hook.quarantine.snapshot()
    assert sum(q["counts"].values()) >= 1, \
        "divergence must attribute to a device counter"


# ---------------------------------------------------------------------------
# at-rest scrub: compile cache
# ---------------------------------------------------------------------------
def test_corrupt_cache_entry_scrubbed_then_relowered_fresh():
    with tempfile.TemporaryDirectory() as cache_dir:
        def conf():
            c = Configure()
            c.batch.steps_per_launch = 256
            c.batch.value_stack_depth = 128
            c.batch.call_stack_depth = 64
            c.imagestore.compile_cache = True
            c.imagestore.compile_cache_dir = cache_dir
            c.integrity.scrub = True
            return c

        data = build_fib()
        svc = GatewayService(conf=conf(), lanes=2)
        try:
            svc.register_module("fib", wasm_bytes=data)
            assert svc.registry.lowered_count == 1
            shas = svc.registry.compile_cache.known_shas()
            assert len(shas) == 1
            # clean pass: entry verifies, nothing moves
            delta = svc.scrub_once()
            assert delta["entries"] >= 1 and delta["corrupt"] == 0
            # rot the persistent entry (disk + in-memory tier)
            entry = [fn for fn in os.listdir(cache_dir)
                     if fn.endswith(".img")][0]
            flip_file(os.path.join(cache_dir, entry), seed=11)
            cc = svc.registry.compile_cache
            with cc._lock:               # the disk copy is the truth now
                cc._payloads.pop(shas[0], None)
            delta = svc.scrub_once()
            assert delta["corrupt"] == 1
            assert delta["evicted"] == 1    # no fleet: evict, not repair
            assert shas[0] not in cc.known_shas()
        finally:
            svc.shutdown()
        # next registration over the scrubbed dir lowers FRESH and runs
        # the right code — rot never becomes servable state
        svc2 = GatewayService(conf=conf(), lanes=2)
        try:
            svc2.register_module("fib", wasm_bytes=data)
            assert svc2.registry.lowered_count == 1
            req = svc2.submit("fib", [12], module="fib",
                              tenant="default")
            assert svc2.wait(req, timeout_s=120.0)
            assert req.future.result(0) == [144]
        finally:
            svc2.shutdown()


# ---------------------------------------------------------------------------
# at-rest scrub: checkpoint lineage sidecars
# ---------------------------------------------------------------------------
def test_corrupt_checkpoint_member_quarantined(tmp_path):
    sup = BatchSupervisor(
        make_engine(build_fib(), make_conf()),
        checkpoint_dir=str(tmp_path))
    sup.run("fib", FIB_ARGS, max_steps=500_000)
    members = [str(tmp_path / fn) for fn in sorted(os.listdir(tmp_path))
               if fn.endswith(".npz")]
    assert members, "the run must have checkpointed"
    victim = members[-1]
    assert os.path.exists(victim + ".sha256"), \
        "checkpoint.save must write the integrity sidecar"
    flip_file(victim, seed=21)
    scrub = Scrubber(Configure().integrity,
                     checkpoints=lambda: members)
    delta = scrub.scrub_once()
    assert delta["quarantined_members"] == 1
    assert not os.path.exists(victim)
    assert os.path.exists(victim + ".corrupt")
    # older members are untouched — the recovery walk falls back
    for m in members[:-1]:
        assert os.path.exists(m)


# ---------------------------------------------------------------------------
# at-rest scrub: parked-session blob repaired from a fleet peer replica
# ---------------------------------------------------------------------------
def _fleet_cfg(peers=(), **kw):
    kw.setdefault("auto_tick", False)
    kw.setdefault("backoff_base_s", 0.0)
    return FleetConfig(peers=peers, **kw)


def test_corrupt_parked_blob_repaired_from_peer_before_wake():
    from tests.test_fleet import _await_mod, _drain

    def conf():
        c = Configure()
        c.batch.steps_per_launch = 256
        c.batch.value_stack_depth = 64
        c.batch.call_stack_depth = 32
        c.effects.suspend = True
        c.integrity.scrub = True
        return c

    svc_a = GatewayService(conf=conf(), lanes=2, fleet=_fleet_cfg())
    gw_a = Gateway(svc_a, port=0).start()
    svc_a.register_module("awaitmod", wasm_bytes=_await_mod(),
                          source="boot")
    svc_b = GatewayService(
        conf=conf(), lanes=2,
        fleet=_fleet_cfg([f"{gw_a.host}:{gw_a.port}"]))
    gw_b = Gateway(svc_b, port=0).start()
    try:
        svc_b.fleet.tick()   # learn manifest + replicate awaitmod
        svc_b.fleet.tick()
        req = svc_a._submit_local("wait", [5], module="awaitmod")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if req.id in svc_a.current.server.list_swapped():
                break
            time.sleep(0.01)
        else:
            raise TimeoutError("session never parked")
        store_a = svc_a.current.server.effects.store
        (key,) = store_a.scrub_keys()
        payload = store_a.get(key)
        # B holds a verified replica (the migration/adoption channel)
        svc_b.current.server.effects.store.adopt(key, payload)
        # rot A's only copy; get() would now refuse the wake's swap-in
        store_a._mem[key] = flip_bit_bytes(store_a._mem[key], seed=3)
        delta = svc_a.scrub_once()
        assert delta["corrupt"] == 1 and delta["repaired"] == 1
        assert store_a.get(key) == payload   # repaired bit-exact
        assert svc_b.fleet.counters["blob_repairs_served"] == 1
        # the wake rides the repaired blob to a bit-correct resolution
        svc_a.wake(req.id, struct.pack("<I", 900))
        _drain(svc_a, [req], timeout_s=120.0)
        assert req.future.result(0) == [905]
        # telemetry: status block + metric family present when on
        assert svc_a.status()["integrity"]["scrub"]["repaired"] == 1
        assert "wasmedge_integrity_scrub_entries_total" \
            in svc_a.metrics_text()
    finally:
        gw_b.shutdown()
        gw_a.shutdown()


# ---------------------------------------------------------------------------
# integrity off IS r23: no hooks, no status block, no metric families
# ---------------------------------------------------------------------------
def test_integrity_off_is_inert(tmp_path):
    conf = make_conf()
    assert conf.integrity.active is False
    sup = BatchSupervisor(make_engine(build_fib(), conf),
                          checkpoint_dir=str(tmp_path))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert (res.results[0] == FIB_WANT).all()
    assert getattr(sup.engine, "_audit_hook", None) is None
    assert getattr(sup.engine, "_flip_hook", None) is None

    svc = GatewayService(conf=Configure(), lanes=2)
    try:
        svc.register_module("fib", wasm_bytes=build_fib())
        assert svc.scrubber is None
        assert svc.integrity_stats() is None
        assert "integrity" not in svc.status()
        assert "wasmedge_integrity" not in svc.metrics_text()
    finally:
        svc.shutdown()
