"""Loader unit tests — byte-level decode with handcrafted binaries, the
reference's test/loader pattern (sectionTest.cpp, filemgrTest.cpp,
instructionTest.cpp)."""

import pytest

from wasmedge_tpu.common.errors import ErrCode, LoadError
from wasmedge_tpu.common.opcodes import Op, name_of
from wasmedge_tpu.common.types import ValType
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.loader.filemgr import FileMgr
from wasmedge_tpu.utils.builder import ModuleBuilder, uleb, sleb


class TestFileMgr:
    def test_uleb_basic(self):
        assert FileMgr(b"\x00").read_u32() == 0
        assert FileMgr(b"\x7f").read_u32() == 127
        assert FileMgr(b"\x80\x01").read_u32() == 128
        assert FileMgr(b"\xff\xff\xff\xff\x0f").read_u32() == 0xFFFFFFFF

    def test_uleb_too_long(self):
        with pytest.raises(LoadError) as e:
            FileMgr(b"\xff\xff\xff\xff\xff\x0f").read_u32()
        assert e.value.code == ErrCode.IntegerTooLong

    def test_uleb_unused_bits(self):
        # 5th byte may only contribute 4 bits for u32
        with pytest.raises(LoadError) as e:
            FileMgr(b"\xff\xff\xff\xff\x1f").read_u32()
        assert e.value.code == ErrCode.IntegerTooLarge

    def test_sleb_basic(self):
        assert FileMgr(b"\x00").read_s32() == 0
        assert FileMgr(b"\x7f").read_s32() == -1
        assert FileMgr(b"\x40").read_s32() == -64
        assert FileMgr(b"\xc0\x00").read_s32() == 64
        assert FileMgr(sleb(-(2**31))).read_s32() == -(2**31)
        assert FileMgr(sleb(2**31 - 1)).read_s32() == 2**31 - 1

    def test_sleb_sign_bits(self):
        # -2^31 encoded, then corrupt final byte sign-extension
        with pytest.raises(LoadError):
            FileMgr(b"\xff\xff\xff\xff\x4f").read_s32()

    def test_sleb64_roundtrip(self):
        for v in (0, 1, -1, 2**62, -(2**63), 2**63 - 1, 123456789012345):
            assert FileMgr(sleb(v)).read_s64() == v

    def test_truncated(self):
        with pytest.raises(LoadError) as e:
            FileMgr(b"\x80").read_u32()
        assert e.value.code == ErrCode.UnexpectedEnd

    def test_name_utf8(self):
        fm = FileMgr(uleb(2) + b"\xc3\xa9")
        assert fm.read_name() == "é"
        with pytest.raises(LoadError) as e:
            FileMgr(uleb(1) + b"\xff").read_name()
        assert e.value.code == ErrCode.MalformedUTF8


class TestHeaders:
    def test_bad_magic(self):
        with pytest.raises(LoadError) as e:
            Loader().parse_module(b"\x00msa\x01\x00\x00\x00")
        assert e.value.code == ErrCode.MalformedMagic

    def test_bad_version(self):
        with pytest.raises(LoadError) as e:
            Loader().parse_module(b"\x00asm\x02\x00\x00\x00")
        assert e.value.code == ErrCode.MalformedVersion

    def test_empty_module(self):
        mod = Loader().parse_module(b"\x00asm\x01\x00\x00\x00")
        assert mod.types == [] and mod.functions == []

    def test_section_out_of_order(self):
        # function section (3) before type section (1)
        raw = b"\x00asm\x01\x00\x00\x00" + b"\x03\x02\x01\x00" + b"\x01\x04\x01\x60\x00\x00"
        with pytest.raises(LoadError) as e:
            Loader().parse_module(raw)
        assert e.value.code == ErrCode.JunkSection

    def test_section_size_mismatch(self):
        # type section claims 5 bytes but content is 4
        raw = b"\x00asm\x01\x00\x00\x00" + b"\x01\x05\x01\x60\x00\x00"
        with pytest.raises(LoadError):
            Loader().parse_module(raw)

    def test_func_code_mismatch(self):
        b = ModuleBuilder()
        b.add_function([], [], [], [])
        raw = bytearray(b.build())
        # strip the code section (last section) entirely
        # find code section: id 10
        i = 8
        while i < len(raw):
            sid = raw[i]
            size = raw[i + 1]
            if sid == 10:
                del raw[i:]
                break
            i += 2 + size
        with pytest.raises(LoadError) as e:
            Loader().parse_module(bytes(raw))
        assert e.value.code == ErrCode.IncompatibleFuncCode


class TestSections:
    def test_type_section(self):
        b = ModuleBuilder()
        b.add_type(["i32", "i64"], ["f32"])
        mod = Loader().parse_module(b.build())
        assert mod.types[0].params == (ValType.I32, ValType.I64)
        assert mod.types[0].results == (ValType.F32,)

    def test_import_section(self):
        b = ModuleBuilder()
        b.import_func("env", "f", ["i32"], [])
        b.import_memory("env", "m", 1, 4)
        b.import_global("env", "g", "i64", mutable=True)
        b.import_table("env", "t", "funcref", 2, 10)
        mod = Loader().parse_module(b.build())
        assert len(mod.imports) == 4
        assert mod.imports[0].kind == 0
        assert mod.imports[1].memory_type.limit.max == 4
        assert mod.imports[2].global_type.mutable
        assert mod.imports[3].table_type.limit.min == 2

    def test_memory_global_export_start(self):
        b = ModuleBuilder()
        b.add_memory(2, 8, export="mem")
        b.add_global("i32", True, [("i32.const", 41)], export="g")
        f = b.add_function([], [], [], [])
        b.set_start(f)
        mod = Loader().parse_module(b.build())
        assert mod.memories[0].limit.min == 2
        assert mod.globals[0].type.mutable
        assert mod.start == f
        assert {e.name for e in mod.exports} == {"mem", "g"}

    def test_elem_and_data(self):
        b = ModuleBuilder()
        b.add_table("funcref", 4)
        f = b.add_function([], [], [], [])
        b.add_active_elem(0, [("i32.const", 1)], [f])
        b.add_memory(1)
        b.add_active_data(0, [("i32.const", 0)], b"hello")
        b.data_count = 1
        mod = Loader().parse_module(b.build())
        assert mod.elements[0].mode == 0
        assert len(mod.elements[0].init_exprs) == 1
        assert mod.datas[0].data == b"hello"

    def test_custom_section_anywhere(self):
        raw = b"\x00asm\x01\x00\x00\x00" + b"\x00\x05\x04name" + b"\x01\x04\x01\x60\x00\x00"
        mod = Loader().parse_module(raw)
        assert mod.customs[0].name == "name"


class TestInstructions:
    def test_jump_precompute(self):
        b = ModuleBuilder()
        b.add_function([], [], [], [
            ("block", None), ("block", None), ("br", 1), "end", "end",
        ])
        mod = Loader().parse_module(b.build())
        body = mod.codes[0].body
        names = [name_of(i.op) for i in body]
        assert names == ["block", "block", "br", "end", "end", "end"]
        assert body[0].jump_end == 4
        assert body[1].jump_end == 2

    def test_if_else_jumps(self):
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("if", "i32"), ("i32.const", 1),
            "else", ("i32.const", 2), "end",
        ])
        mod = Loader().parse_module(b.build())
        body = mod.codes[0].body
        if_i = 1
        assert name_of(body[if_i].op) == "if"
        assert body[if_i].jump_else == 2
        assert body[if_i].jump_end == 4

    def test_illegal_opcode(self):
        # handcrafted: one void function whose body is [0x27 (illegal), end]
        raw = (b"\x00asm\x01\x00\x00\x00"
               b"\x01\x04\x01\x60\x00\x00"
               b"\x03\x02\x01\x00"
               b"\x0a\x05\x01\x03\x00\x27\x0b")
        with pytest.raises(LoadError) as e:
            Loader().parse_module(raw)
        assert e.value.code == ErrCode.IllegalOpCode

    def test_proposal_gating(self):
        from wasmedge_tpu.common.configure import Configure, Proposal
        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [("local.get", 0), "i32.extend8_s"])
        conf = Configure()
        conf.remove_proposal(Proposal.SignExtensionOperators)
        with pytest.raises(LoadError) as e:
            Loader(conf).parse_module(b.build())
        assert e.value.code == ErrCode.IllegalOpCode
        # default conf allows it
        Loader().parse_module(b.build())

    def test_br_table_decode(self):
        b = ModuleBuilder()
        b.add_function(["i32"], [], [], [
            ("block", None), ("block", None),
            ("local.get", 0), ("br_table", [0, 1], 1),
            "end", "end",
        ])
        mod = Loader().parse_module(b.build())
        bt = [i for i in mod.codes[0].body if name_of(i.op) == "br_table"][0]
        assert bt.targets == [0, 1] and bt.target_idx == 1

    def test_const_immediates(self):
        b = ModuleBuilder()
        b.add_function([], ["f64"], [], [("f64.const", 3.14159)])
        mod = Loader().parse_module(b.build())
        import struct
        bits = mod.codes[0].body[0].imm
        assert struct.unpack("<d", struct.pack("<Q", bits))[0] == pytest.approx(3.14159)


def test_aot_fused_planes_roundtrip():
    """tpu.aot artifacts carry the Pallas fused encoding; it must
    round-trip bit-exactly, verify by regeneration, and a tampered
    section must be refused (verify_fused False)."""
    import numpy as np

    from wasmedge_tpu.aot import (
        compile_module, deserialize_image, extract_precompiled,
        fused_planes_for, verify_fused)
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    twasm = compile_module(build_fib(), conf)
    mod = Loader(conf).parse_module(twasm)
    payload = extract_precompiled(
        mod.source_bytes, [(c.name, c.data, c.start) for c in mod.customs])
    assert payload is not None
    img = deserialize_image(payload)
    assert getattr(img, "fused", None) is not None
    src = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    regen = fused_planes_for(src.lowered, src)
    for k in regen:
        assert np.array_equal(img.fused[k], regen[k]), k
    assert verify_fused(img, mod)
    # tamper: redirect a fused branch -> must be refused
    img.fused["a"] = img.fused["a"].copy()
    img.fused["a"][0] ^= 1
    assert not verify_fused(img, mod)


def test_aot_fused_planes_consumed_by_engine():
    """Loading a tpu.aot artifact end-to-end: the Pallas engine must see
    the fused section and verify it against regeneration — including for
    call_indirect modules, whose table window size comes from the
    DECLARED table (no table mutation in the batch subset)."""
    import numpy as np

    from wasmedge_tpu.aot import compile_module
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.builder import ModuleBuilder
    from wasmedge_tpu.validator import Validator

    b = ModuleBuilder()
    f_dbl = b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 2), "i32.mul"])
    b.add_table("funcref", 3)
    b.add_active_elem(0, [("i32.const", 1)], [f_dbl])
    ti = b.add_type(["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 1), ("call_indirect", ti, 0),
    ], export="f")
    conf = Configure()
    conf.batch.steps_per_launch = 10_000
    twasm = compile_module(b.build(), conf)
    mod = Validator(conf).validate(Loader(conf).parse_module(twasm))
    assert getattr(mod.lowered, "fused", None) is not None
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    eng = PallasUniformEngine(inst, store=store, conf=conf, lanes=8,
                              interpret=True)
    res = eng.run("f", [np.arange(8, dtype=np.int64)], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) == np.arange(8) * 2).all()
    assert eng.aot_fused_verified is True
