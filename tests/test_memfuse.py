"""Memory-run fusion (batch/fuse.py + analysis/absint.py) — r19.

The consumer half of the abstract interpreter: straight-line
load/store runs whose every access carries an absint license (proven
in-bounds + aligned, i.e. trap-free) compile into fused dispatch
cells doing one gather/scatter per access — and one dispatch per run
— instead of the per-op three-word RMW window.  Pins the r17 bar for
the new run class:

  - memfuse on/off bit-identical to each other AND the scalar engine
    (results, traps, retired) on the licensed workload — with strictly
    fewer dispatches when on;
  - the same parity on the 8-device shard mesh and a multi-tenant
    concatenated image;
  - the adversarial fixtures: misaligned and OOB-adjacent accesses
    REVERT to the per-op path (license refused) and trap identically
    on and off;
  - fuel exhaustion lands at the correct op (fused lanes pre-gate on
    whole-run fuel) and the opcode histogram equals retired;
  - licensed-vs-reverted counters reach the Prometheus export.

Fast by construction (tiny lanes, small word counts): tier-1.
"""

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fib, build_memfuse_workload
from tests.helpers import instantiate, run_wasm

pytestmark = pytest.mark.fuse

LANES = 8


def checksum_ref(n_words: int, passes: int = 1) -> int:
    acc = np.uint32(0)
    i = np.arange(n_words, dtype=np.uint32)
    for p in range(passes, 0, -1):
        acc ^= np.bitwise_xor.reduce(
            (i * np.uint32(0x9E3779B1)) ^ np.uint32(p - 1))
    return int(acc)   # u32 domain (compare masked)


def make_conf(memfuse=True, **batch):
    conf = Configure()
    conf.batch.fuse_memory_runs = memfuse
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    return conf


def make_engine(data, conf, lanes=LANES, mesh=None):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes,
                       mesh=mesh)


def assert_results_identical(a, b):
    assert (a.trap == b.trap).all()
    assert (a.retired == b.retired).all()
    for ra, rb in zip(a.results, b.results):
        assert (ra == rb).all()


class TestBitExact:
    def test_memfuse_matches_unfused_and_scalar(self):
        data = build_memfuse_workload(96, passes=2)
        res = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(memfuse))
            res[memfuse] = eng.run(
                "memfuse", [np.zeros(LANES, np.int64)],
                max_steps=200_000)
            if memfuse:
                mem = eng.img.fusion_report["memory"]
                assert mem["mem_runs"] > 0
                assert mem["licensed_sites"] == 2
                assert mem["unlicensed_sites"] == 0
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert res[True].steps < res[False].steps
        expect = checksum_ref(96, 2)
        assert (np.asarray(res[True].results[0], np.int64)
                & 0xFFFFFFFF == expect).all()
        # scalar engine agrees
        assert int(run_wasm(data, "memfuse", [0])[0]) \
            & 0xFFFFFFFF == expect

    def test_sub_word_stores_fuse_bit_exact(self):
        """store16 RMW keeps neighbouring bytes: fused vs per-op."""
        data = build_memfuse_workload(64, store_width=2)
        res = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(memfuse))
            res[memfuse] = eng.run(
                "memfuse", [np.zeros(LANES, np.int64)],
                max_steps=200_000)
            if memfuse:
                assert eng.img.fusion_report["memory"]["mem_runs"] > 0
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert int(run_wasm(data, "memfuse", [0])[0]) \
            == int(np.asarray(res[True].results[0])[0])

    def test_v128_runs_license_and_fuse_bit_exact(self):
        """r20 satellite: licensed v128 load/store sites join memory
        runs as four-whole-word cells — bit-identical to the per-op
        path with strictly fewer dispatches."""
        from wasmedge_tpu.batch.image import CLS_VLOAD, CLS_VSTORE
        from wasmedge_tpu.models import build_simd_memfuse_workload

        data = build_simd_memfuse_workload(64, passes=2)
        res = {}
        steps = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(
                memfuse, steps_per_launch=4096, tierup=False))
            res[memfuse] = eng.run(
                "simd_memfuse", [np.zeros(LANES, np.int64)],
                max_steps=500_000)
            steps[memfuse] = res[memfuse].steps
            if memfuse:
                mem = eng.img.fusion_report["memory"]
                assert mem["licensed_sites"] == 2
                assert mem["unlicensed_sites"] == 0
                assert mem["mem_runs"] >= 2
                vcls = {c for p in eng.img.fuse_patterns or ()
                        for c, _ in p}
                assert CLS_VLOAD in vcls and CLS_VSTORE in vcls
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert steps[True] < steps[False]
        assert int(run_wasm(data, "simd_memfuse", [0])[0]) \
            == int(np.asarray(res[True].results[0])[0])

    def test_knob_off_plans_nothing(self):
        eng = make_engine(build_memfuse_workload(64),
                          make_conf(memfuse=False))
        eng._plan_fusion()
        rep = eng.img.fusion_report
        assert rep["memory"]["mem_runs"] == 0
        assert rep["mem_runs"] == []


class TestReverts:
    def test_misaligned_reverts_to_per_op(self):
        data = build_memfuse_workload(64, byte_offset=2)
        res = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(memfuse))
            res[memfuse] = eng.run(
                "memfuse", [np.zeros(LANES, np.int64)],
                max_steps=200_000)
            if memfuse:
                mem = eng.img.fusion_report["memory"]
                assert mem["mem_runs"] == 0          # license refused
                assert mem["unlicensed_sites"] == 2
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])

    def test_oob_adjacent_traps_identically(self):
        """The write loop runs off the single page: the trap must land
        at the same op with the same retired count, fusion on or off
        (the license refused the site, so both run per-op)."""
        data = build_memfuse_workload(16385)
        res = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(
                memfuse, steps_per_launch=4096))
            res[memfuse] = eng.run(
                "memfuse", [np.zeros(LANES, np.int64)],
                max_steps=2_000_000)
            if memfuse:
                assert eng.img.fusion_report["memory"]["mem_runs"] == 0
        assert (np.asarray(res[True].trap)
                == int(ErrCode.MemoryOutOfBounds)).all()
        assert_results_identical(res[True], res[False])


class TestGas:
    def test_fuel_exhaustion_lands_per_op(self):
        """A fuel budget that dies mid-run: fused lanes pre-gate on
        whole-run fuel, so exhaustion executes the original per-op
        cells and lands at the same op either way."""
        data = build_memfuse_workload(64)
        res = {}
        for memfuse in (True, False):
            eng = make_engine(data, make_conf(
                memfuse, fuel_per_launch=137, steps_per_launch=64))
            res[memfuse] = eng.run(
                "memfuse", [np.zeros(LANES, np.int64)],
                max_steps=10_000)
        assert (np.asarray(res[True].trap)
                == int(ErrCode.CostLimitExceeded)).all()
        assert_results_identical(res[True], res[False])


@pytest.mark.obs
class TestObs:
    def test_histogram_equals_retired_and_metrics(self):
        from wasmedge_tpu.obs.metrics import render_prometheus

        conf = make_conf(True)
        conf.obs.enabled = True
        conf.obs.opcode_histogram = True
        eng = make_engine(build_memfuse_workload(64), conf)
        res = eng.run("memfuse", [np.zeros(LANES, np.int64)],
                      max_steps=200_000)
        assert res.completed.all()
        hist = eng.obs.opcode_counts
        assert hist is not None
        assert int(hist.sum()) == int(np.asarray(res.retired,
                                                 np.int64).sum())
        fused = eng.obs.fused_counts
        assert fused["dispatches"] > 0
        assert fused["retired_fused"] > 0
        text = render_prometheus(eng.obs)
        assert 'wasmedge_memfuse_runs{verdict="licensed"}' in text
        assert 'verdict="reverted_sites"' in text


class TestComposition:
    def test_shard_drive_memfuse_parity(self):
        from wasmedge_tpu.parallel.mesh import lane_mesh

        data = build_memfuse_workload(48)
        args = [np.zeros(32, np.int64)]
        out = {}
        for memfuse in (True, False):
            out[memfuse] = make_engine(
                data, make_conf(memfuse), lanes=32,
                mesh=lane_mesh(8)).run("memfuse", args,
                                       max_steps=200_000)
        solo = make_engine(data, make_conf(True), lanes=32).run(
            "memfuse", args, max_steps=200_000)
        assert out[True].completed.all()
        assert_results_identical(out[True], out[False])
        assert_results_identical(out[True], solo)

    def test_multitenant_concat_memfuse_parity(self):
        from wasmedge_tpu.batch.multitenant import (
            MultiTenantBatchEngine, Tenant)

        L = 8
        data = build_memfuse_workload(48)
        out = {}
        for memfuse in (True, False):
            conf = make_conf(memfuse)
            tenants = []
            for mod_data, fn, args in (
                    (data, "memfuse", [np.zeros(L, np.int64)]),
                    (build_fib(), "fib",
                     [np.full(L, 10, np.int64)])):
                ex, store, inst = instantiate(mod_data, conf)
                tenants.append(Tenant(
                    engine=BatchEngine(inst, store=store, conf=conf,
                                       lanes=L),
                    func_name=fn, args_lanes=args, lanes=L))
            mt = MultiTenantBatchEngine(tenants, conf=conf)
            if memfuse:
                # the concatenated planes carry the per-tenant mem
                # runs (pattern ids remapped into the merged table)
                from wasmedge_tpu.batch.fuse import pattern_has_mem

                assert any(pattern_has_mem(p)
                           for p in mt.img.fuse_patterns)
            out[memfuse] = mt.run_tenants(max_steps=200_000)
        for a, b in zip(out[True], out[False]):
            assert a.completed.all()
            assert_results_identical(a, b)
        assert (np.asarray(out[True][0].results[0], np.int64)
                & 0xFFFFFFFF == checksum_ref(48)).all()
