"""Multi-chip lane sharding over the virtual 8-device CPU mesh.

SURVEY.md §2.10 item 2 / VERDICT round-1 item 8: the mesh path must be
exercised by pytest, not only by the driver's dryrun.  conftest.py forces
`--xla_force_host_platform_device_count=8`, so these tests run the real
pjit/NamedSharding machinery on 8 XLA devices."""

import numpy as np
import pytest

from wasmedge_tpu.batch import BatchEngine
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fib, build_memory_workload
from wasmedge_tpu.parallel.mesh import lane_mesh, shard_batch_state, state_shardings
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate


def make_engine(data, lanes, n_devices=8, conf=None, imports=None):
    import jax

    assert len(jax.devices()) >= n_devices, "virtual device mesh missing"
    conf = conf or Configure()
    conf.batch.steps_per_launch = 4000
    ex, store, inst = instantiate(data, conf, imports=imports)
    mesh = lane_mesh(n_devices)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=lanes, mesh=mesh)
    return ex, store, inst, eng


def _fib(n):
    return n if n < 2 else _fib(n - 1) + _fib(n - 2)


def test_sharded_fib_4096_lanes_8_devices():
    """The VERDICT-prescribed scale: 4096 lanes over 8 virtual devices."""
    ex, store, inst, eng = make_engine(build_fib(), lanes=4096)
    ns = (np.arange(4096) % 11).astype(np.int64)
    res = eng.run("fib", [ns], max_steps=300_000)
    assert (res.trap == -1).all()
    expect = np.array([_fib(int(n)) for n in range(11)], np.int64)
    assert (res.results[0] == expect[ns % 11]).all()


def test_sharding_layout():
    """State arrays really are lane-sharded across all 8 devices."""
    import jax

    ex, store, inst, eng = make_engine(build_fib(), lanes=64)
    state = eng.initial_state(inst.exports["fib"][1],
                              [np.zeros(64, np.int64)])
    mesh = lane_mesh(8)
    sharded = shard_batch_state(state, mesh)
    shardings = state_shardings(mesh, state)
    from jax.sharding import PartitionSpec as P
    assert shardings.stack_lo.spec == P(None, "lanes")
    assert shardings.pc.spec == P("lanes")
    stack = sharded.stack_lo
    assert len(stack.sharding.device_set) == 8
    # lane (last) dim split 8 ways, row dim replicated
    shard_shape = stack.sharding.shard_shape(stack.shape)
    assert shard_shape == (stack.shape[0], stack.shape[1] // 8)


def test_uneven_lane_count():
    """Lanes not divisible by the device count still run correctly (the
    engine pads or XLA handles the ragged shard)."""
    for lanes in (24, 40):
        ex, store, inst, eng = make_engine(build_fib(), lanes=lanes,
                                           n_devices=8)
        ns = (np.arange(lanes) % 9).astype(np.int64)
        res = eng.run("fib", [ns], max_steps=100_000)
        assert (res.trap == -1).all()
        for lane in range(lanes):
            assert res.results[0][lane] == _fib(int(ns[lane]))


def test_mesh_with_fuel():
    """Fuel accounting composes with lane sharding: exhausted lanes trap
    with CostLimitExceeded while cheap lanes complete."""
    conf = Configure()
    conf.batch.fuel_per_launch = 2000
    ex, store, inst, eng = make_engine(build_fib(), lanes=16, conf=conf)
    ns = np.where(np.arange(16) % 2 == 0, 3, 16).astype(np.int64)
    res = eng.run("fib", [ns], max_steps=100_000)
    cheap = np.arange(16) % 2 == 0
    assert (res.trap[cheap] == -1).all()
    assert (res.trap[~cheap] == int(ErrCode.CostLimitExceeded)).all()


def test_mesh_memory_and_traps():
    """Per-lane memory planes shard on the lane dim; traps stay per-lane."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1), ("i32.store", 2, 0),
        ("local.get", 0), ("i32.load", 2, 0),
    ], export="f")
    ex, store, inst, eng = make_engine(b.build(), lanes=32)
    addrs = (np.arange(32, dtype=np.int64) * 8) % 128
    addrs[5] = 0x10000  # OOB lane
    vals = np.arange(32, dtype=np.int64) * 3 + 1
    res = eng.run("f", [addrs, vals], max_steps=10_000)
    assert res.trap[5] == int(ErrCode.MemoryOutOfBounds)
    ok = [i for i in range(32) if i != 5]
    assert (res.results[0][ok] == vals[ok]).all()


def test_mesh_hostcall_roundtrip():
    """The device-to-host outcall channel works on sharded state."""
    from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction

    imp = ImportObject("env")
    imp.add_func("triple", PyHostFunction(lambda mem, x: x * 3,
                                          ["i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "triple", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="f")
    ex, store, inst, eng = make_engine(b.build(), lanes=16, imports=[imp])
    args = np.arange(16, dtype=np.int64)
    res = eng.run("f", [args], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == args * 3).all()


def test_pallas_sharded_over_virtual_devices():
    """The Pallas warp-interpreter sharded across the 8 virtual CPU
    devices: per-device engines + block schedulers, concurrent launches,
    merged lane-ordered results — including divergent inputs resolved by
    each device's own scheduler."""
    import jax
    import numpy as np

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    devices = jax.devices()[:8]
    assert len(devices) == 8
    conf = Configure()
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)

    lanes = 256
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 6  # divergent inputs
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=devices, max_steps=2_000_000,
                             interpret=True)
    fib = [0, 1]
    for _ in range(12):
        fib.append(fib[-1] + fib[-2])
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([fib[int(n)] for n in ns])).all()


def test_pallas_sharded_1000_lanes_8_devices():
    """ISSUE 5 padding satellite, at scale: 1000 lanes across 8 fake
    devices through the unsupervised pallas drive, merged lane-ordered."""
    import jax

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 1000
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 6
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=jax.devices()[:8],
                             max_steps=2_000_000, interpret=True)
    assert res.trap.shape == (lanes,)
    assert res.results[0].shape == (lanes,)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([_fib(int(n)) for n in ns])).all()


def test_pallas_sharded_pads_uneven_lanes():
    """30 lanes on 8 devices: the old `lanes % n_devices` hard error is
    lifted — the drive splits lanes into contiguous near-equal ranges
    (4x4 + 4x3 here) and merges them back in original lane order."""
    import jax

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.batch.steps_per_launch = 5_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 30
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 5
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=jax.devices()[:8],
                             max_steps=500_000, interpret=True)
    assert res.trap.shape == (lanes,)
    assert res.results[0].shape == (lanes,)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([_fib(int(n)) for n in ns])).all()


def test_sharded_drive_overlaps_devices(monkeypatch):
    """The threaded sharded drive must actually interleave devices: with
    8 schedulers, kernel launches from different devices must overlap in
    wall time instead of running strictly one-device-after-another.
    Instrumented at the launch seam (structure proof — virtual CPU
    devices share host cores, so timing ratios would be meaningless)."""
    import threading
    import time

    import jax
    import numpy as np

    from wasmedge_tpu.batch import scheduler as sched_mod
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    devices = jax.devices()[:4]
    spans = []
    lock = threading.Lock()
    orig = sched_mod.BlockScheduler.run

    def spy_run(self):
        t0 = time.perf_counter()
        try:
            return orig(self)
        finally:
            with lock:
                spans.append((t0, time.perf_counter(),
                              threading.get_ident()))

    monkeypatch.setattr(sched_mod.BlockScheduler, "run", spy_run)

    conf = Configure()
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 4 * len(devices)
    res = run_pallas_sharded(
        inst, store, conf, "fib", [np.full(lanes, 15, np.int64)],
        devices=devices, max_steps=500_000, interpret=True)
    assert (np.asarray(res.results[0]) == 610).all()
    assert len(spans) == len(devices)
    # distinct threads drove the schedulers...
    assert len({tid for _, _, tid in spans}) == len(devices)
    # ...and their lifetimes overlap pairwise (concurrent, not serial)
    overlapping = sum(
        1 for i in range(len(spans)) for j in range(i + 1, len(spans))
        if spans[i][0] < spans[j][1] and spans[j][0] < spans[i][1])
    assert overlapping >= len(devices) - 1, spans
