"""Multi-chip lane sharding over the virtual 8-device CPU mesh.

SURVEY.md §2.10 item 2 / VERDICT round-1 item 8: the mesh path must be
exercised by pytest, not only by the driver's dryrun.  conftest.py forces
`--xla_force_host_platform_device_count=8`, so these tests run the real
pjit/NamedSharding machinery on 8 XLA devices."""

import numpy as np
import pytest

from wasmedge_tpu.batch import BatchEngine
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fib, build_memory_workload
from wasmedge_tpu.parallel.mesh import lane_mesh, shard_batch_state, state_shardings
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate


def make_engine(data, lanes, n_devices=8, conf=None, imports=None):
    import jax

    assert len(jax.devices()) >= n_devices, "virtual device mesh missing"
    conf = conf or Configure()
    conf.batch.steps_per_launch = 4000
    ex, store, inst = instantiate(data, conf, imports=imports)
    mesh = lane_mesh(n_devices)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=lanes, mesh=mesh)
    return ex, store, inst, eng


def _fib(n):
    return n if n < 2 else _fib(n - 1) + _fib(n - 2)


def test_sharded_fib_4096_lanes_8_devices():
    """The VERDICT-prescribed scale: 4096 lanes over 8 virtual devices."""
    ex, store, inst, eng = make_engine(build_fib(), lanes=4096)
    ns = (np.arange(4096) % 11).astype(np.int64)
    res = eng.run("fib", [ns], max_steps=300_000)
    assert (res.trap == -1).all()
    expect = np.array([_fib(int(n)) for n in range(11)], np.int64)
    assert (res.results[0] == expect[ns % 11]).all()


def test_sharding_layout():
    """State arrays really are lane-sharded across all 8 devices."""
    import jax

    ex, store, inst, eng = make_engine(build_fib(), lanes=64)
    state = eng.initial_state(inst.exports["fib"][1],
                              [np.zeros(64, np.int64)])
    mesh = lane_mesh(8)
    sharded = shard_batch_state(state, mesh)
    shardings = state_shardings(mesh, state)
    from jax.sharding import PartitionSpec as P
    assert shardings.stack_lo.spec == P(None, "lanes")
    assert shardings.pc.spec == P("lanes")
    stack = sharded.stack_lo
    assert len(stack.sharding.device_set) == 8
    # lane (last) dim split 8 ways, row dim replicated
    shard_shape = stack.sharding.shard_shape(stack.shape)
    assert shard_shape == (stack.shape[0], stack.shape[1] // 8)


def test_uneven_lane_count():
    """Lanes not divisible by the device count still run correctly (the
    engine pads or XLA handles the ragged shard)."""
    for lanes in (24, 40):
        ex, store, inst, eng = make_engine(build_fib(), lanes=lanes,
                                           n_devices=8)
        ns = (np.arange(lanes) % 9).astype(np.int64)
        res = eng.run("fib", [ns], max_steps=100_000)
        assert (res.trap == -1).all()
        for lane in range(lanes):
            assert res.results[0][lane] == _fib(int(ns[lane]))


def test_mesh_with_fuel():
    """Fuel accounting composes with lane sharding: exhausted lanes trap
    with CostLimitExceeded while cheap lanes complete."""
    conf = Configure()
    conf.batch.fuel_per_launch = 2000
    ex, store, inst, eng = make_engine(build_fib(), lanes=16, conf=conf)
    ns = np.where(np.arange(16) % 2 == 0, 3, 16).astype(np.int64)
    res = eng.run("fib", [ns], max_steps=100_000)
    cheap = np.arange(16) % 2 == 0
    assert (res.trap[cheap] == -1).all()
    assert (res.trap[~cheap] == int(ErrCode.CostLimitExceeded)).all()


def test_mesh_memory_and_traps():
    """Per-lane memory planes shard on the lane dim; traps stay per-lane."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1), ("i32.store", 2, 0),
        ("local.get", 0), ("i32.load", 2, 0),
    ], export="f")
    ex, store, inst, eng = make_engine(b.build(), lanes=32)
    addrs = (np.arange(32, dtype=np.int64) * 8) % 128
    addrs[5] = 0x10000  # OOB lane
    vals = np.arange(32, dtype=np.int64) * 3 + 1
    res = eng.run("f", [addrs, vals], max_steps=10_000)
    assert res.trap[5] == int(ErrCode.MemoryOutOfBounds)
    ok = [i for i in range(32) if i != 5]
    assert (res.results[0][ok] == vals[ok]).all()


def test_mesh_hostcall_roundtrip():
    """The device-to-host outcall channel works on sharded state."""
    from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction

    imp = ImportObject("env")
    imp.add_func("triple", PyHostFunction(lambda mem, x: x * 3,
                                          ["i32"], ["i32"]))
    b = ModuleBuilder()
    b.import_func("env", "triple", ["i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="f")
    ex, store, inst, eng = make_engine(b.build(), lanes=16, imports=[imp])
    args = np.arange(16, dtype=np.int64)
    res = eng.run("f", [args], max_steps=10_000)
    assert (res.trap == -1).all()
    assert (res.results[0] == args * 3).all()


def test_pallas_sharded_over_virtual_devices():
    """The Pallas warp-interpreter sharded across the 8 virtual CPU
    devices: per-device engines + block schedulers, concurrent launches,
    merged lane-ordered results — including divergent inputs resolved by
    each device's own scheduler."""
    import jax
    import numpy as np

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    devices = jax.devices()[:8]
    assert len(devices) == 8
    conf = Configure()
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)

    lanes = 256
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 6  # divergent inputs
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=devices, max_steps=2_000_000,
                             interpret=True)
    fib = [0, 1]
    for _ in range(12):
        fib.append(fib[-1] + fib[-2])
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([fib[int(n)] for n in ns])).all()


def test_pallas_sharded_1000_lanes_8_devices():
    """ISSUE 5 padding satellite, at scale: 1000 lanes across 8 fake
    devices through the unsupervised pallas drive, merged lane-ordered."""
    import jax

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 1000
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 6
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=jax.devices()[:8],
                             max_steps=2_000_000, interpret=True)
    assert res.trap.shape == (lanes,)
    assert res.results[0].shape == (lanes,)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([_fib(int(n)) for n in ns])).all()


def test_pallas_sharded_pads_uneven_lanes():
    """30 lanes on 8 devices: the old `lanes % n_devices` hard error is
    lifted — the drive splits lanes into contiguous near-equal ranges
    (4x4 + 4x3 here) and merges them back in original lane order."""
    import jax

    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = Configure()
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.batch.steps_per_launch = 5_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 30
    ns = (np.arange(lanes, dtype=np.int64) % 5) + 5
    res = run_pallas_sharded(inst, store, conf, "fib", [ns],
                             devices=jax.devices()[:8],
                             max_steps=500_000, interpret=True)
    assert res.trap.shape == (lanes,)
    assert res.results[0].shape == (lanes,)
    assert (res.trap == -1).all()
    assert (np.asarray(res.results[0]) ==
            np.asarray([_fib(int(n)) for n in ns])).all()


def test_sharded_drive_overlaps_devices(monkeypatch):
    """The threaded sharded drive must actually interleave devices: with
    8 schedulers, kernel launches from different devices must overlap in
    wall time instead of running strictly one-device-after-another.
    Instrumented at the launch seam (structure proof — virtual CPU
    devices share host cores, so timing ratios would be meaningless)."""
    import threading
    import time

    import jax
    import numpy as np

    from wasmedge_tpu.batch import scheduler as sched_mod
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.models import build_fib
    from wasmedge_tpu.parallel.mesh import run_pallas_sharded
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    devices = jax.devices()[:4]
    spans = []
    lock = threading.Lock()
    orig = sched_mod.BlockScheduler.run

    def spy_run(self):
        t0 = time.perf_counter()
        try:
            return orig(self)
        finally:
            with lock:
                spans.append((t0, time.perf_counter(),
                              threading.get_ident()))

    monkeypatch.setattr(sched_mod.BlockScheduler, "run", spy_run)

    conf = Configure()
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.batch.steps_per_launch = 20_000
    conf.batch.interpret = True
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    lanes = 4 * len(devices)
    res = run_pallas_sharded(
        inst, store, conf, "fib", [np.full(lanes, 15, np.int64)],
        devices=devices, max_steps=500_000, interpret=True)
    assert (np.asarray(res.results[0]) == 610).all()
    assert len(spans) == len(devices)
    # distinct threads drove the schedulers...
    assert len({tid for _, _, tid in spans}) == len(devices)
    # ...and their lifetimes overlap pairwise (concurrent, not serial)
    overlapping = sum(
        1 for i in range(len(spans)) for j in range(i + 1, len(spans))
        if spans[i][0] < spans[j][1] and spans[j][0] < spans[i][1])
    assert overlapping >= len(devices) - 1, spans


# ---------------------------------------------------------------------------
# r15: single-program shard drive (parallel/shard_drive.py)
# ---------------------------------------------------------------------------
def _shard_conf(chunk=2000):
    conf = Configure()
    conf.batch.steps_per_launch = chunk
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    return conf


def _fib_inst(conf):
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    return Executor(conf).instantiate(store, mod), store


def test_shard_drive_bit_identical_across_drives_and_device_counts():
    """The r15 acceptance pin: the single-program shard drive's merged
    BatchResult is bit-identical to single-device execute_batch AND to
    the threaded per-device drive, across device counts — results,
    trap, and retired planes all equal."""
    import jax

    from wasmedge_tpu.parallel.shard_drive import ShardDrive

    conf = _shard_conf()
    inst, store = _fib_inst(conf)
    lanes = 64
    ns = (np.arange(lanes, dtype=np.int64) % 11)
    ref = BatchEngine(inst, store=store, conf=conf, lanes=lanes).run(
        "fib", [ns], max_steps=300_000)
    for n in (2, 4, 8):
        res = ShardDrive(inst, store=store, conf=conf,
                         devices=jax.devices()[:n]).run(
            "fib", [ns], max_steps=300_000)
        assert (res.results[0] == ref.results[0]).all(), f"{n} devices"
        assert (res.trap == ref.trap).all()
        assert (res.retired == ref.retired).all()
    # threaded rung (supervised SIMT tier, per-device engines)
    from wasmedge_tpu.parallel.supervisor import MeshSupervisor

    conf_t = _shard_conf()
    conf_t.supervisor.use_kernel_tier = False
    conf_t.supervisor.backoff_base_s = 0.0
    tres = MeshSupervisor(inst, store=store, conf=conf_t,
                          devices=jax.devices()[:8],
                          drive="threaded").run(
        "fib", [ns], max_steps=300_000)
    assert (tres.results[0] == ref.results[0]).all()
    assert (tres.trap == ref.trap).all()
    assert (tres.retired == ref.retired).all()


def test_shard_drive_uneven_split_pads_never_retire():
    """lanes % n_devices != 0: the global array pads up to a device
    multiple, pad lanes are born parked — the merged result has exactly
    `lanes` entries and the retired plane matches single-device
    bit-for-bit (a pad lane retiring even one instruction would show)."""
    import jax

    from wasmedge_tpu.parallel.shard_drive import (
        ShardDrive, padded_lanes, shard_slices)

    assert padded_lanes(30, 8) == 32
    assert [s.stop - s.start for s in shard_slices(32, 8)] == [4] * 8
    conf = _shard_conf()
    inst, store = _fib_inst(conf)
    for lanes in (30, 13):
        ns = (np.arange(lanes, dtype=np.int64) % 9)
        ref = BatchEngine(inst, store=store, conf=conf, lanes=lanes).run(
            "fib", [ns], max_steps=200_000)
        drv = ShardDrive(inst, store=store, conf=conf,
                         devices=jax.devices()[:8])
        res = drv.run("fib", [ns], max_steps=200_000)
        assert drv.engine.lanes == padded_lanes(lanes, 8)
        assert res.trap.shape == (lanes,)
        assert (res.results[0] == ref.results[0]).all()
        assert (res.trap == ref.trap).all()
        assert (res.retired == ref.retired).all()


def _lane_stamp_module():
    """Each lane fd_writes its 4-byte little-endian argument once —
    a self-identifying WASI record for byte-parity pins."""
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i32"], ["i32"], ["i32"], [
        ("i32.const", 128), ("local.get", 0), ("i32.store", 2, 0),
        ("i32.const", 64), ("i32.const", 128), ("i32.store", 2, 0),
        ("i32.const", 68), ("i32.const", 4), ("i32.store", 2, 0),
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 1),
        ("local.get", 0),
    ], export="stamp")
    return b.build()


def _stamp_run(run_fn, lanes, tmp_path, tag):
    """Instantiate the lane-stamp module with fd 1 redirected to a
    file, run `run_fn(inst, store, conf, args)`, return (result,
    bytes_written)."""
    import os

    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf = _shard_conf(chunk=200)
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="stamp")
    path = str(tmp_path / f"stamp-{tag}.bin")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
    wasi.env.fds[1].os_fd = fd
    mod = Validator(conf).validate(
        Loader(conf).parse_module(_lane_stamp_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    args = np.arange(lanes, dtype=np.int64) + 1000
    res = run_fn(inst, store, conf, args)
    os.close(fd)
    with open(path, "rb") as f:
        return res, f.read()


def test_shard_drive_wasi_echo_byte_parity(tmp_path):
    """WASI byte parity on an UNEVEN split (20 lanes / 8 devices): the
    shard drive's stdout stream is byte-identical to single-device
    execute_batch (global lane order restores single-device
    determinism), every lane's record appears exactly once (pad lanes
    never duplicate WASI side effects), and the threaded rung emits the
    same record multiset (its cross-device flush interleaving is
    scheduler-dependent, so only per-lane attribution is pinned there)."""
    import jax

    from wasmedge_tpu.parallel.shard_drive import ShardDrive
    from wasmedge_tpu.parallel.supervisor import MeshSupervisor

    lanes = 20

    def single(inst, store, conf, args):
        return BatchEngine(inst, store=store, conf=conf,
                           lanes=lanes).run("stamp", [args],
                                            max_steps=100_000)

    def shard(inst, store, conf, args):
        return ShardDrive(inst, store=store, conf=conf,
                          devices=jax.devices()[:8]).run(
            "stamp", [args], max_steps=100_000)

    def threaded(inst, store, conf, args):
        conf.supervisor.use_kernel_tier = False
        conf.supervisor.backoff_base_s = 0.0
        return MeshSupervisor(inst, store=store, conf=conf,
                              devices=jax.devices()[:8],
                              drive="threaded").run(
            "stamp", [args], max_steps=100_000)

    ref, ref_bytes = _stamp_run(single, lanes, tmp_path, "single")
    sres, s_bytes = _stamp_run(shard, lanes, tmp_path, "shard")
    tres, t_bytes = _stamp_run(threaded, lanes, tmp_path, "threaded")
    assert (ref.trap == -1).all()
    expect = np.frombuffer(ref_bytes, np.int32)
    assert sorted(expect) == sorted(range(1000, 1000 + lanes))
    # shard drive: exact byte-for-byte stream parity with single-device
    assert s_bytes == ref_bytes
    # threaded rung: same records, each exactly once (attribution pin)
    assert sorted(np.frombuffer(t_bytes, np.int32).tolist()) \
        == sorted(expect.tolist())
    for res in (sres, tres):
        assert (res.results[0] == ref.results[0]).all()
        assert (res.trap == ref.trap).all()
        assert (res.retired == ref.retired).all()


def test_shard_drive_mesh_round_spans_per_device():
    """obs satellite: on the shard drive there is ONE driving thread,
    so per-device attribution comes from mesh_round spans — one per
    (round, device) on the mesh/devN tracks, carrying per-shard
    occupancy args (lanes / live_lanes / parked_lanes / pad_lanes)."""
    import jax

    from wasmedge_tpu.parallel.shard_drive import ShardDrive

    conf = _shard_conf(chunk=500)
    conf.obs.enabled = True
    inst, store = _fib_inst(conf)
    lanes = 30   # uneven: dev7's shard carries the 2 pad lanes
    ns = (np.arange(lanes, dtype=np.int64) % 9)
    drv = ShardDrive(inst, store=store, conf=conf,
                     devices=jax.devices()[:8])
    res = drv.run("fib", [ns], max_steps=200_000)
    assert (res.trap == -1).all()
    rounds = [e for e in drv.engine.obs.events
              if e["name"] == "mesh_round"]
    assert rounds, "no mesh_round spans recorded"
    tracks = {e["track"] for e in rounds}
    assert tracks == {f"mesh/dev{i}" for i in range(8)}
    for e in rounds:
        args = e["args"]
        assert args["lanes"] == 4
        assert 0 <= args["live_lanes"] <= 4
        assert args["pad_lanes"] == (2 if e["track"] == "mesh/dev7"
                                     else 0)


def test_run_mesh_default_is_shard_drive(monkeypatch):
    """Drive selection: run_mesh's default never touches the threaded
    drive; drive='threaded' dispatches to it explicitly."""
    import jax

    from wasmedge_tpu.parallel import mesh as mesh_mod

    conf = _shard_conf()
    inst, store = _fib_inst(conf)
    ns = np.arange(16, dtype=np.int64) % 9

    def boom(*a, **k):
        raise AssertionError("threaded drive used on the default path")

    monkeypatch.setattr(mesh_mod, "run_pallas_sharded", boom)
    res = mesh_mod.run_mesh(inst, store, conf, "fib", [ns],
                            devices=jax.devices()[:4],
                            max_steps=200_000)
    assert (res.trap == -1).all()

    sentinel = object()
    monkeypatch.setattr(mesh_mod, "run_pallas_sharded",
                        lambda *a, **k: sentinel)
    assert mesh_mod.run_mesh(inst, store, conf, "fib", [ns],
                             devices=jax.devices()[:4],
                             drive="threaded") is sentinel
    with pytest.raises(ValueError):
        mesh_mod.run_mesh(inst, store, conf, "fib", [ns],
                          devices=jax.devices()[:4], drive="bogus")
