"""Mesh-level fault tolerance under deterministic fault injection.

ISSUE 5 acceptance: with one injected device failure on an 8-fake-device
run, the supervised sharded drive completes and its merged BatchResult
(results/trap/retired) is BIT-IDENTICAL to the unfaulted run; a
full-process crash + resume from a coordinated mesh checkpoint is
likewise bit-identical.  The suite also pins device ejection + lane
migration (elastic shrink), cooperative cancellation stopping sibling
devices, per-device error aggregation in the unsupervised drive
(MeshDriveError), and the lifted lanes-%-devices restriction (1000
lanes on 8 fake devices).

Runs on the conftest-forced 8-device virtual CPU mesh
(`--xla_force_host_platform_device_count=8`).  Fast by construction
(tiny lane counts, short chunks, SIMT supervision tier): stays inside
the tier-1 `-m 'not slow'` budget.
"""

import os

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import EngineFailure
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.parallel.mesh import MeshDriveError, run_pallas_sharded
from wasmedge_tpu.parallel.supervisor import MeshSupervisor
from wasmedge_tpu.testing.faults import Fault, FaultInjector, InjectedFault
from tests.helpers import instantiate

pytestmark = pytest.mark.faults

LANES = 32


def make_conf(**sup):
    conf = Configure()
    conf.batch.steps_per_launch = 100
    conf.batch.rng_seed = 7  # deterministic tier-0 streams across engines
    # small stack planes: n_devices engines compile per supervised run
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.supervisor.backoff_base_s = 0.0  # no sleeping in tests
    conf.supervisor.checkpoint_every_steps = 200
    for k, v in sup.items():
        setattr(conf.supervisor, k, v)
    return conf


def make_inst(data, conf, imports=None):
    ex, store, inst = instantiate(data, conf, imports=imports)
    return store, inst


def devices(n):
    import jax

    devs = jax.devices()[:n]
    assert len(devs) == n, "virtual device mesh missing"
    return devs


def fib_ref(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def assert_results_identical(a, b):
    for ra, rb in zip(a.results, b.results):
        assert (ra == rb).all()
    assert (a.trap == b.trap).all()
    assert (a.retired == b.retired).all()


FIB_ARGS = [(np.arange(LANES) % 11).astype(np.int64)]
FIB_EXPECT = np.array([fib_ref(n % 11) for n in range(LANES)], np.int64)


@pytest.fixture(scope="module")
def fib_ref_result(tmp_path_factory):
    """The unfaulted supervised 8-device run every bit-identity test
    compares against (computed once per module)."""
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(
        inst, store=store, conf=conf, devices=devices(8),
        checkpoint_dir=str(tmp_path_factory.mktemp("ref")))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert not sup.failures
    assert (res.results[0] == FIB_EXPECT).all()
    return res


# ---------------------------------------------------------------------------
# device failure detection: retry-then-recover
# ---------------------------------------------------------------------------
def test_device_launch_fault_retry_recover_bitmatch(tmp_path,
                                                    fib_ref_result):
    """ISSUE 5 acceptance pin: one injected device failure on an
    8-fake-device run — the supervised drive completes bit-identical to
    the unfaulted run."""
    inj = FaultInjector([Fault(point="device_launch", at=0,
                               match={"device": 2})])
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(8),
                         faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert inj.fired == 1
    assert_results_identical(res, fib_ref_result)
    assert [f.fault_class for f in sup.failures] == ["device_launch"]
    assert "device 2" in sup.failures[0].error
    # retried, never ejected
    assert not sup._bad_devices


def test_device_serve_fault_retry_recover(tmp_path):
    """A mid-serve host exception on one device's hostcall drain is
    retried from that device's snapshot; a pure host import replays
    deterministically, so the merged result matches the unfaulted run."""
    from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
    from wasmedge_tpu.utils.builder import ModuleBuilder

    def build():
        imp = ImportObject("env")
        imp.add_func("triple", PyHostFunction(lambda mem, x: x * 3,
                                              ["i32"], ["i32"]))
        b = ModuleBuilder()
        b.import_func("env", "triple", ["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [],
                       [("local.get", 0), ("call", 0)], export="f")
        return b.build(), imp

    args = [np.arange(LANES, dtype=np.int64)]

    data, imp = build()
    conf = make_conf()
    store, inst = make_inst(data, conf, imports=[imp])
    ref = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         checkpoint_dir=str(tmp_path / "ref")).run(
        "f", args, max_steps=50_000)
    assert (ref.results[0] == args[0] * 3).all()

    data, imp = build()
    conf = make_conf()
    store, inst = make_inst(data, conf, imports=[imp])
    inj = FaultInjector([Fault(point="device_serve", at=0,
                               match={"device": 1})])
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path / "s"))
    res = sup.run("f", args, max_steps=50_000)
    assert inj.fired == 1
    assert_results_identical(res, ref)
    assert [f.fault_class for f in sup.failures] == ["device_serve"]


# ---------------------------------------------------------------------------
# quarantine + lane migration (elastic shrink)
# ---------------------------------------------------------------------------
def test_device_ejection_migrates_lanes_bitmatch(tmp_path,
                                                 fib_ref_result):
    """A device that keeps failing is ejected; its lanes migrate to
    surviving devices and the merged result stays bit-identical — here
    even across device counts (a 2-device elastic-shrunk run vs the
    8-device reference): per-lane outcomes are placement-independent."""
    inj = FaultInjector([Fault(point="device_launch", times=99,
                               match={"device": 1})])
    conf = make_conf(max_device_retries=1)
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert_results_identical(res, fib_ref_result)
    classes = {f.fault_class for f in sup.failures}
    assert "device_quarantine" in classes
    assert "lane_migrate" in classes
    assert sup._bad_devices == {1}
    # the ejected device's lanes were re-packed onto OTHER devices
    orig = next(s for s in sup.shards if s.dev_index == 1)
    moved = [s for s in sup.shards if s.di != orig.di
             and np.isin(s.lane_ids, orig.lane_ids).any()]
    assert moved and all(s.dev_index != 1 for s in moved)
    assert all(s.done for s in moved)


def test_every_device_ejected_raises(tmp_path):
    """When no healthy device remains to migrate to, the run raises
    EngineFailure instead of losing lanes silently."""
    inj = FaultInjector([Fault(point="device_launch", times=9999)])
    conf = make_conf(max_device_retries=1)
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path))
    with pytest.raises(EngineFailure):
        sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert len(sup._bad_devices) == 2


# ---------------------------------------------------------------------------
# coordinated mesh checkpointing: crash + resume
# ---------------------------------------------------------------------------
def test_mesh_checkpoint_crash_resume_bitmatch(tmp_path, fib_ref_result):
    """ISSUE 5 acceptance pin: full-process crash after a coordinated
    mesh checkpoint, then resume=True — bit-identical to the
    uninterrupted run."""
    # SystemExit models the process dying: the supervisor re-raises it
    # (fatal, not retried), leaving the coordinated lineage on disk
    # arrival 20 lands in round 2, AFTER round 1's coordinated
    # checkpoint barrier (8 devices x 2 launches per slice per round)
    inj = FaultInjector([Fault(point="device_launch", at=20,
                               exc=lambda ctx: SystemExit("crash"))])
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(8),
                         faults=inj, checkpoint_dir=str(tmp_path))
    with pytest.raises(SystemExit):
        sup.run("fib", FIB_ARGS, max_steps=500_000)
    members = [m for m in os.listdir(tmp_path) if m.startswith("mesh-")]
    assert members, "crash happened before any coordinated checkpoint"
    # shards + manifest + partial merge inside one atomic member
    newest = sorted(members)[-1]
    files = os.listdir(tmp_path / newest)
    assert "manifest.json" in files and "merged.npz" in files

    conf2 = make_conf()
    store2, inst2 = make_inst(build_fib(), conf2)
    sup2 = MeshSupervisor(inst2, store=store2, conf=conf2,
                          devices=devices(8),
                          checkpoint_dir=str(tmp_path), resume=True)
    res = sup2.run("fib", FIB_ARGS, max_steps=500_000)
    assert sup2.resumed
    assert_results_identical(res, fib_ref_result)


def test_corrupt_mesh_member_skipped_on_resume(tmp_path, fib_ref_result):
    """A corrupt newest mesh member is recorded + skipped; resume walks
    to an older good member (or starts fresh) and still completes
    bit-identical."""
    # arrival 6 is in round 2 for 2 devices (2 x 2 arrivals per round)
    inj = FaultInjector([Fault(point="device_launch", at=6,
                               exc=lambda ctx: SystemExit("crash"))])
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path))
    with pytest.raises(SystemExit):
        sup.run("fib", FIB_ARGS, max_steps=500_000)
    newest = sorted(m for m in os.listdir(tmp_path)
                    if m.startswith("mesh-"))[-1]
    with open(tmp_path / newest / "manifest.json", "w") as f:
        f.write("{corrupt")

    conf2 = make_conf()
    store2, inst2 = make_inst(build_fib(), conf2)
    sup2 = MeshSupervisor(inst2, store=store2, conf=conf2,
                          devices=devices(2),
                          checkpoint_dir=str(tmp_path), resume=True)
    res = sup2.run("fib", FIB_ARGS, max_steps=500_000)
    assert_results_identical(res, fib_ref_result)
    assert any(f.fault_class == "mesh_checkpoint" for f in sup2.failures)


def test_resume_refuses_other_invocation(tmp_path):
    """A mesh lineage taken for different arguments must not be adopted
    (invocation fingerprint mismatch) — the run starts fresh instead of
    continuing someone else's answer."""
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         checkpoint_dir=str(tmp_path))
    sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert any(m.startswith("mesh-") for m in os.listdir(tmp_path))

    other = [np.full(LANES, 9, np.int64)]
    conf2 = make_conf()
    store2, inst2 = make_inst(build_fib(), conf2)
    sup2 = MeshSupervisor(inst2, store=store2, conf=conf2,
                          devices=devices(2),
                          checkpoint_dir=str(tmp_path), resume=True)
    res = sup2.run("fib", other, max_steps=500_000)
    assert not sup2.resumed
    assert (res.results[0] == fib_ref(9)).all()
    assert any(f.fault_class == "mesh_checkpoint" for f in sup2.failures)


# ---------------------------------------------------------------------------
# cooperative cancellation
# ---------------------------------------------------------------------------
def test_cancellation_stops_siblings(tmp_path):
    """eject_devices=False: a device exhausting its retries cancels the
    whole mesh run — sibling devices stop at their next launch boundary
    with work still unfinished instead of running to completion."""
    inj = FaultInjector([Fault(point="device_launch", times=99,
                               match={"device": 0})])
    conf = make_conf(max_device_retries=1, eject_devices=False)
    # long workload + small slices: siblings need many rounds, so the
    # cancel flag must be what stops them
    conf.supervisor.checkpoint_every_steps = 100
    store, inst = make_inst(build_fib(), conf)
    args = [np.full(LANES, 14, np.int64)]
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path))
    with pytest.raises(EngineFailure) as ei:
        sup.run("fib", args, max_steps=5_000_000)
    assert "device 0" in str(ei.value)
    assert not sup._bad_devices  # fail-fast, not elastic shrink
    siblings = [s for s in sup.shards if s.dev_index != 0]
    assert any(not s.done for s in siblings), \
        "siblings ran to completion despite cancellation"


# ---------------------------------------------------------------------------
# uneven lane counts: lanes % n_devices lifted
# (the unsupervised pallas-drive tests — 1000 lanes on 8 fake devices,
#  uneven 30-on-8 — live with the other run_pallas_sharded coverage in
#  tests/test_mesh.py)
# ---------------------------------------------------------------------------
def test_supervised_pads_uneven_lanes(tmp_path):
    """The supervised drive takes uneven lane counts: 29 lanes on 2
    devices split 15+14 — no clone/pad lane ever executes, results
    merge in original lane order."""
    lanes = 29
    args = [(np.arange(lanes) % 11).astype(np.int64)]
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.trap.shape == (lanes,)
    assert (res.trap == -1).all()
    assert (res.results[0] ==
            np.array([fib_ref(n % 11) for n in range(lanes)])).all()


# ---------------------------------------------------------------------------
# error aggregation in the unsupervised drive
# ---------------------------------------------------------------------------
def _tiny_pallas_conf():
    conf = Configure()
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    conf.batch.steps_per_launch = 1000
    conf.batch.interpret = True
    return conf


def test_mesh_drive_error_aggregates_all_devices(monkeypatch):
    """The threaded drive reports EVERY failed device, not errs[0]."""
    from wasmedge_tpu.batch import scheduler as sched_mod

    def boom(self):
        raise RuntimeError("injected drive failure")

    monkeypatch.setattr(sched_mod.BlockScheduler, "run", boom)
    conf = _tiny_pallas_conf()
    store, inst = make_inst(build_fib(), conf)
    devs = devices(2)
    with pytest.raises(MeshDriveError) as ei:
        run_pallas_sharded(inst, store, conf, "fib",
                           [np.full(8, 5, np.int64)], devices=devs,
                           max_steps=10_000, interpret=True)
    err = ei.value
    assert len(err.failures) == 2
    assert {str(d) for d, _ in err.failures} == {str(d) for d in devs}
    assert all(isinstance(e, RuntimeError) for _, e in err.failures)


def test_serial_drive_error_names_device(monkeypatch):
    """The non-threaded drive wraps its exception with device
    attribution too (it used to escape raw)."""
    from wasmedge_tpu.batch import scheduler as sched_mod

    def boom(self):
        raise RuntimeError("injected launch failure")

    monkeypatch.setattr(sched_mod.BlockScheduler, "launch", boom)
    conf = _tiny_pallas_conf()
    store, inst = make_inst(build_fib(), conf)
    with pytest.raises(MeshDriveError) as ei:
        run_pallas_sharded(inst, store, conf, "fib",
                           [np.full(8, 5, np.int64)], devices=devices(2),
                           max_steps=10_000, interpret=True,
                           threaded=False)
    assert len(ei.value.failures) == 1
    dev, exc = ei.value.failures[0]
    assert dev is not None
    assert isinstance(exc, RuntimeError)


# ---------------------------------------------------------------------------
# fault-injection seams
# ---------------------------------------------------------------------------
def test_fault_match_counts_own_arrivals():
    """`match` faults index their OWN arrivals: "device 2's first
    launch" is deterministic regardless of the interleaving of other
    devices' arrivals at the shared seam."""
    inj = FaultInjector([Fault(point="device_launch", at=1,
                               match={"device": 2})])
    # other devices' arrivals don't advance device 2's counter
    inj.fire("device_launch", device=0)
    inj.fire("device_launch", device=1)
    inj.fire("device_launch", device=2)   # device 2 arrival 0: no fire
    inj.fire("device_launch", device=0)
    with pytest.raises(InjectedFault):
        inj.fire("device_launch", device=2)   # device 2 arrival 1: fires
    assert inj.fired == 1
    assert inj.log == [("device_launch", 1)]


def test_mesh_checkpoint_save_fault_never_kills_run(tmp_path,
                                                    fib_ref_result):
    """A failed coordinated snapshot is recorded, not raised — the
    healthy run continues to a bit-identical merge."""
    inj = FaultInjector([Fault(point="mesh_checkpoint_save", at=0)])
    conf = make_conf()
    store, inst = make_inst(build_fib(), conf)
    sup = MeshSupervisor(inst, store=store, conf=conf, devices=devices(2),
                         faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", FIB_ARGS, max_steps=500_000)
    assert inj.fired == 1
    assert_results_identical(res, fib_ref_result)
    assert any(f.fault_class == "mesh_checkpoint" for f in sup.failures)


# ---------------------------------------------------------------------------
# r15: shard-drive rung of the degradation ladder
# ---------------------------------------------------------------------------
def test_shard_drive_fault_falls_back_to_threaded_rung():
    """An injected shard-drive failure demotes the supervised run to
    the threaded per-device rung: the run completes bit-identical to
    an unfaulted one, with a FailureRecord('shard_drive') attributing
    the demotion."""
    conf = make_conf(checkpoint_every_steps=None)
    store, inst = make_inst(build_fib(), conf)
    ref = MeshSupervisor(inst, store=store, conf=conf,
                         devices=devices(4)).run(
        "fib", FIB_ARGS, max_steps=200_000)
    assert (ref.results[0] == FIB_EXPECT).all()

    inj = FaultInjector([Fault(point="shard_launch", at=0)])
    sup = MeshSupervisor(inst, store=store, conf=conf,
                         devices=devices(4), faults=inj)
    res = sup.run("fib", FIB_ARGS, max_steps=200_000)
    assert inj.fired == 1
    assert any(f.fault_class == "shard_drive" for f in sup.failures)
    assert_results_identical(res, ref)


def test_shard_drive_skipped_when_cadence_configured():
    """A checkpoint cadence needs the per-device SIMT tier (the
    coordinated mesh snapshots slice per-device states), so the shard
    tier must not even be attempted — an armed shard fault never
    fires."""
    conf = make_conf()   # checkpoint_every_steps=200 (cadence on)
    store, inst = make_inst(build_fib(), conf)
    inj = FaultInjector([Fault(point="shard_launch", at=0)])
    import tempfile

    with tempfile.TemporaryDirectory(prefix="mesh-ckpt-") as d:
        sup = MeshSupervisor(inst, store=store, conf=conf,
                             devices=devices(2), faults=inj,
                             checkpoint_dir=d)
        res = sup.run("fib", FIB_ARGS, max_steps=200_000)
    assert inj.fired == 0
    assert (res.results[0] == FIB_EXPECT).all()


def test_shard_drive_threaded_param_skips_shard_tier():
    """MeshSupervisor(drive='threaded') never attempts the shard rung
    even with the knob on; use_shard_drive=False does the same through
    the Configure."""
    conf = make_conf(checkpoint_every_steps=None)
    store, inst = make_inst(build_fib(), conf)
    inj = FaultInjector([Fault(point="shard_launch", at=0, times=99)])
    res = MeshSupervisor(inst, store=store, conf=conf,
                         devices=devices(2), faults=inj,
                         drive="threaded").run(
        "fib", FIB_ARGS, max_steps=200_000)
    assert inj.fired == 0
    assert (res.results[0] == FIB_EXPECT).all()

    conf2 = make_conf(checkpoint_every_steps=None,
                      use_shard_drive=False)
    store2, inst2 = make_inst(build_fib(), conf2)
    res2 = MeshSupervisor(inst2, store=store2, conf=conf2,
                          devices=devices(2), faults=inj).run(
        "fib", FIB_ARGS, max_steps=200_000)
    assert inj.fired == 0
    assert (res2.results[0] == FIB_EXPECT).all()


def test_unsupervised_shard_drive_wraps_failures():
    """The unsupervised shard drive wraps any drive failure in
    ShardDriveError with the cause chained (run_mesh's documented
    contract: the fallback ladder lives in the supervisor)."""
    from wasmedge_tpu.parallel.mesh import run_mesh
    from wasmedge_tpu.parallel.shard_drive import ShardDriveError

    conf = make_conf(checkpoint_every_steps=None)
    store, inst = make_inst(build_fib(), conf)
    inj = FaultInjector([Fault(point="shard_launch", at=0)])
    with pytest.raises(ShardDriveError) as ei:
        run_mesh(inst, store, conf, "fib", FIB_ARGS,
                 devices=devices(2), max_steps=200_000, faults=inj)
    assert isinstance(ei.value.__cause__, InjectedFault)


# ---------------------------------------------------------------------------
# threaded-rung stdout semantics across a device restore (ROADMAP #1
# carry-over, pinned in r16): at-least-once with a BOUNDED window
# ---------------------------------------------------------------------------
def _repeat_stamp_module():
    """Each lane fd_writes its 4-byte little-endian id `iters` times —
    a repeating self-identifying WASI record stream, so duplicated
    flushes are countable per lane."""
    from wasmedge_tpu.utils.builder import ModuleBuilder

    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i32", "i32"], ["i32"], ["i32", "i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 2), ("local.get", 1), "i32.ge_u", ("br_if", 1),
        ("i32.const", 128), ("local.get", 0), ("i32.store", 2, 0),
        ("i32.const", 64), ("i32.const", 128), ("i32.store", 2, 0),
        ("i32.const", 68), ("i32.const", 4), ("i32.store", 2, 0),
        ("i32.const", 1), ("i32.const", 64), ("i32.const", 1),
        ("i32.const", 32), ("call", 0), ("local.set", 3),
        ("local.get", 2), ("i32.const", 1), "i32.add",
        ("local.set", 2),
        ("br", 0),
        "end",
        "end",
        ("local.get", 0),
    ], export="stamp")
    return b.build()


def _stamp_wasi_run(tmp_path, tag, conf, run_fn, lanes, iters):
    """Instantiate the repeat-stamp module with fd 1 redirected to a
    file; returns (result, per-lane-id record counts)."""
    from collections import Counter

    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="stamp")
    path = str(tmp_path / f"rstamp-{tag}.bin")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
    wasi.env.fds[1].os_fd = fd
    mod = Validator(conf).validate(
        Loader(conf).parse_module(_repeat_stamp_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    ids = np.arange(lanes, dtype=np.int64) + 1000
    res = run_fn(inst, store, [ids, np.full(lanes, iters, np.int64)])
    os.close(fd)
    with open(path, "rb") as f:
        records = np.frombuffer(f.read(), np.int32)
    return res, Counter(int(r) for r in records)


def test_threaded_restore_stdout_at_least_once_window_bounded(tmp_path):
    """The threaded rung's documented stdout caveat, pinned instead of
    folklore: a device restore replays tier-0 stdout AT-LEAST-ONCE,
    and the duplicated-flush window is BOUNDED by the region replayed
    since the restore point (here: the faulted device's single
    pre-fault launch — no mesh checkpoint exists yet, so the retry
    restores its initial sub-state).  Assertions:

      - every lane's records appear at least its true count (nothing
        is ever lost)
      - lanes on UNAFFECTED devices appear exactly once per write (the
        failure domain is one device)
      - the faulted device's extra records are bounded by what ONE
        launch window can flush per lane
      - results stay bit-identical to the unfaulted run (the replay is
        output-duplication only, never state corruption)

    (The shard drive resolved this caveat structurally — one engine,
    one stdout cursor; see README 'Single-program mesh'.)"""
    # 12 iterations: the faulted device must reach a SECOND launch
    # (chunk 100) even with r19 memory-run fusion retiring the stamp
    # loop's licensed stores in fused dispatch cells
    lanes, iters, chunk = 8, 12, 100
    dev_n = 4

    def base_conf():
        conf = make_conf(checkpoint_every_steps=None)
        conf.batch.steps_per_launch = chunk
        return conf

    def single(inst, store, args):
        from wasmedge_tpu.batch.engine import BatchEngine

        return BatchEngine(inst, store=store, conf=base_conf(),
                           lanes=lanes).run("stamp", args,
                                            max_steps=100_000)

    ref, ref_counts = _stamp_wasi_run(tmp_path, "single", base_conf(),
                                      single, lanes, iters)
    assert (ref.trap == -1).all()
    assert all(ref_counts[1000 + k] == iters for k in range(lanes))
    # DISPATCH steps one loop iteration takes (from the oracle run):
    # the launch-window write bound below derives from it.  Steps, not
    # retired — under superinstruction/memory-run fusion one dispatch
    # retires a whole run, and the launch window is denominated in
    # dispatches
    spi = int(ref.steps) // iters
    w_max = chunk // max(spi, 1) + 1   # writes one launch can flush

    fault_dev = 2
    inj = FaultInjector([Fault(point="device_launch", at=1,
                               match={"device": fault_dev})])

    def threaded(inst, store, args):
        conf = base_conf()
        conf.supervisor.use_kernel_tier = False
        return MeshSupervisor(inst, store=store, conf=conf,
                              devices=devices(dev_n), faults=inj,
                              drive="threaded",
                              checkpoint_dir=str(tmp_path)).run(
            "stamp", args, max_steps=100_000)

    res, counts = _stamp_wasi_run(tmp_path, "threaded", base_conf(),
                                  threaded, lanes, iters)
    assert inj.fired == 1, "the restore must actually have happened"
    # state recovery is bit-identical regardless of the stdout caveat
    assert (np.asarray(res.results[0])
            == np.asarray(ref.results[0])).all()
    assert (np.asarray(res.trap) == np.asarray(ref.trap)).all()
    # contiguous split: device d owns lanes [d*2, d*2+2) for 16/8
    per_dev = lanes // dev_n
    lo, hi = fault_dev * per_dev, (fault_dev + 1) * per_dev
    for k in range(lanes):
        n = counts[1000 + k]
        assert n >= iters, f"lane {k} lost stdout records"
        if lo <= k < hi:
            # the at-least-once window: bounded by one launch's flushes
            assert n <= iters + w_max, \
                f"lane {k} duplicated beyond the replay window"
        else:
            assert n == iters, \
                f"lane {k} is outside the failure domain but duplicated"
