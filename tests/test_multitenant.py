"""Multi-tenant heterogeneous batching (BASELINE config 5's shape):
different modules in one SIMT batch, per-lane results correct."""

import numpy as np
import pytest

from wasmedge_tpu.batch.multitenant import run_mixed
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fac, build_fib, build_loop_sum
from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate


def _inst(data, conf=None, imports=None):
    ex, store, inst = instantiate(data, conf or Configure(), imports=imports)
    return inst, store


def test_three_modules_one_batch():
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    fib_i, fib_s = _inst(build_fib())
    fac_i, fac_s = _inst(build_fac())
    sum_i, sum_s = _inst(build_loop_sum())
    fib_args = np.array([5, 8, 10, 11], np.int64)
    fac_args = np.array([3, 6, 10], np.int64)
    sum_args = np.array([10, 100, 1000, 17, 4], np.int64)
    res = run_mixed([
        (fib_i, fib_s, "fib", [fib_args], 4),
        (fac_i, fac_s, "fac", [fac_args], 3),
        (sum_i, sum_s, "loop_sum", [sum_args], 5),
    ], conf=conf, max_steps=500_000)
    assert (res[0].trap == -1).all()
    assert res[0].results[0].tolist() == [5, 21, 55, 89]
    import math
    assert res[1].results[0].tolist() == [6, 720, 3628800]
    assert res[2].results[0].tolist() == [45, 4950, 499500, 136, 6]


def test_mixed_globals_memory_and_tables():
    """Tenants with clashing index spaces: globals, memories, indirect
    calls through per-tenant tables."""
    conf = Configure()
    conf.batch.steps_per_launch = 5000

    def module_a():
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", 1000)])
        b.add_memory(1, 1)
        f0 = b.add_function(["i32"], ["i32"], [],
                            [("local.get", 0), ("i32.const", 3), "i32.mul"])
        b.add_table("funcref", 2)
        b.add_active_elem(0, [("i32.const", 0)], [f0])
        ti = b.add_type(["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            # mem[8] = arg; g += arg; return table[0](arg) + g + mem[8]
            ("i32.const", 8), ("local.get", 0), ("i32.store", 2, 0),
            ("global.get", 0), ("local.get", 0), "i32.add",
            ("global.set", 0),
            ("local.get", 0), ("i32.const", 0), ("call_indirect", ti, 0),
            ("global.get", 0), "i32.add",
            ("i32.const", 8), ("i32.load", 2, 0), "i32.add",
        ], export="go")
        return b.build()

    def module_b():
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", -5)])
        f0 = b.add_function(["i32"], ["i32"], [],
                            [("local.get", 0), ("i32.const", 7), "i32.add"])
        b.add_table("funcref", 1)
        b.add_active_elem(0, [("i32.const", 0)], [f0])
        ti = b.add_type(["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.const", 0), ("call_indirect", ti, 0),
            ("global.get", 0), "i32.add",
        ], export="go")
        return b.build()

    a_i, a_s = _inst(module_a())
    b_i, b_s = _inst(module_b())
    a_args = np.array([1, 2, 3], np.int64)
    b_args = np.array([10, 20], np.int64)
    res = run_mixed([
        (a_i, a_s, "go", [a_args], 3),
        (b_i, b_s, "go", [b_args], 2),
    ], conf=conf, max_steps=100_000)
    # A: 3x + (1000 + x) + x = 1000 + 5x
    assert res[0].results[0].tolist() == [1005, 1010, 1015]
    # B: (x + 7) + (-5) = x + 2
    assert res[1].results[0].tolist() == [12, 22]


def test_mixed_with_hostcalls_and_traps():
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    imp = ImportObject("env")
    imp.add_func("bump", PyHostFunction(lambda mem, x: x + 1,
                                        ["i32"], ["i32"]))
    hb = ModuleBuilder()
    hb.import_func("env", "bump", ["i32"], ["i32"])
    hb.add_function(["i32"], ["i32"], [],
                    [("local.get", 0), ("call", 0)], export="f")
    h_i, h_s = _inst(hb.build(), conf, imports=[imp])

    tb = ModuleBuilder()
    tb.add_function(["i32", "i32"], ["i32"], [],
                    [("local.get", 0), ("local.get", 1), ("i32.div_s",)],
                    export="div")
    t_i, t_s = _inst(tb.build())

    res = run_mixed([
        (h_i, h_s, "f", [np.array([100, 200], np.int64)], 2),
        (t_i, t_s, "div",
         [np.array([10, 9, 8], np.int64), np.array([2, 0, 4], np.int64)], 3),
    ], conf=conf, max_steps=100_000)
    assert res[0].results[0].tolist() == [101, 201]
    assert res[1].trap[1] == int(ErrCode.DivideByZero)
    assert res[1].results[0][[0, 2]].tolist() == [5, 2]


def test_pallas_multitenant_path():
    """Tenant blocks through the Pallas kernel (interpret mode on CPU):
    heterogeneous per-block entries, same results as the SIMT path."""
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    conf.batch.interpret = True
    conf.batch.use_pallas = True
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.multitenant import (
        MultiTenantBatchEngine, Tenant)

    fib_i, fib_s = _inst(build_fib())
    fac_i, fac_s = _inst(build_fac())
    tenants = [
        Tenant(engine=BatchEngine(fib_i, store=fib_s, conf=conf, lanes=8),
               func_name="fib", args_lanes=[np.full(8, 10, np.int64)],
               lanes=8),
        Tenant(engine=BatchEngine(fac_i, store=fac_s, conf=conf, lanes=8),
               func_name="fac", args_lanes=[np.full(8, 10, np.int64)],
               lanes=8),
    ]
    mt = MultiTenantBatchEngine(tenants, conf=conf)
    res = mt.run_tenants(max_steps=200_000)
    assert mt.used_pallas
    assert (res[0].results[0] == 55).all()
    assert (res[1].results[0] == 3628800).all()
