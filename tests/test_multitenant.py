"""Multi-tenant heterogeneous batching (BASELINE config 5's shape):
different modules in one SIMT batch, per-lane results correct."""

import numpy as np
import pytest

from wasmedge_tpu.batch.multitenant import run_mixed
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fac, build_fib, build_loop_sum
from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate


def _inst(data, conf=None, imports=None):
    ex, store, inst = instantiate(data, conf or Configure(), imports=imports)
    return inst, store


def test_three_modules_one_batch():
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    fib_i, fib_s = _inst(build_fib())
    fac_i, fac_s = _inst(build_fac())
    sum_i, sum_s = _inst(build_loop_sum())
    fib_args = np.array([5, 8, 10, 11], np.int64)
    fac_args = np.array([3, 6, 10], np.int64)
    sum_args = np.array([10, 100, 1000, 17, 4], np.int64)
    res = run_mixed([
        (fib_i, fib_s, "fib", [fib_args], 4),
        (fac_i, fac_s, "fac", [fac_args], 3),
        (sum_i, sum_s, "loop_sum", [sum_args], 5),
    ], conf=conf, max_steps=500_000)
    assert (res[0].trap == -1).all()
    assert res[0].results[0].tolist() == [5, 21, 55, 89]
    import math
    assert res[1].results[0].tolist() == [6, 720, 3628800]
    assert res[2].results[0].tolist() == [45, 4950, 499500, 136, 6]


def test_mixed_globals_memory_and_tables():
    """Tenants with clashing index spaces: globals, memories, indirect
    calls through per-tenant tables."""
    conf = Configure()
    conf.batch.steps_per_launch = 5000

    def module_a():
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", 1000)])
        b.add_memory(1, 1)
        f0 = b.add_function(["i32"], ["i32"], [],
                            [("local.get", 0), ("i32.const", 3), "i32.mul"])
        b.add_table("funcref", 2)
        b.add_active_elem(0, [("i32.const", 0)], [f0])
        ti = b.add_type(["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            # mem[8] = arg; g += arg; return table[0](arg) + g + mem[8]
            ("i32.const", 8), ("local.get", 0), ("i32.store", 2, 0),
            ("global.get", 0), ("local.get", 0), "i32.add",
            ("global.set", 0),
            ("local.get", 0), ("i32.const", 0), ("call_indirect", ti, 0),
            ("global.get", 0), "i32.add",
            ("i32.const", 8), ("i32.load", 2, 0), "i32.add",
        ], export="go")
        return b.build()

    def module_b():
        b = ModuleBuilder()
        b.add_global("i32", True, [("i32.const", -5)])
        f0 = b.add_function(["i32"], ["i32"], [],
                            [("local.get", 0), ("i32.const", 7), "i32.add"])
        b.add_table("funcref", 1)
        b.add_active_elem(0, [("i32.const", 0)], [f0])
        ti = b.add_type(["i32"], ["i32"])
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.const", 0), ("call_indirect", ti, 0),
            ("global.get", 0), "i32.add",
        ], export="go")
        return b.build()

    a_i, a_s = _inst(module_a())
    b_i, b_s = _inst(module_b())
    a_args = np.array([1, 2, 3], np.int64)
    b_args = np.array([10, 20], np.int64)
    res = run_mixed([
        (a_i, a_s, "go", [a_args], 3),
        (b_i, b_s, "go", [b_args], 2),
    ], conf=conf, max_steps=100_000)
    # A: 3x + (1000 + x) + x = 1000 + 5x
    assert res[0].results[0].tolist() == [1005, 1010, 1015]
    # B: (x + 7) + (-5) = x + 2
    assert res[1].results[0].tolist() == [12, 22]


def test_mixed_with_hostcalls_and_traps():
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    imp = ImportObject("env")
    imp.add_func("bump", PyHostFunction(lambda mem, x: x + 1,
                                        ["i32"], ["i32"]))
    hb = ModuleBuilder()
    hb.import_func("env", "bump", ["i32"], ["i32"])
    hb.add_function(["i32"], ["i32"], [],
                    [("local.get", 0), ("call", 0)], export="f")
    h_i, h_s = _inst(hb.build(), conf, imports=[imp])

    tb = ModuleBuilder()
    tb.add_function(["i32", "i32"], ["i32"], [],
                    [("local.get", 0), ("local.get", 1), ("i32.div_s",)],
                    export="div")
    t_i, t_s = _inst(tb.build())

    res = run_mixed([
        (h_i, h_s, "f", [np.array([100, 200], np.int64)], 2),
        (t_i, t_s, "div",
         [np.array([10, 9, 8], np.int64), np.array([2, 0, 4], np.int64)], 3),
    ], conf=conf, max_steps=100_000)
    assert res[0].results[0].tolist() == [101, 201]
    assert res[1].trap[1] == int(ErrCode.DivideByZero)
    assert res[1].results[0][[0, 2]].tolist() == [5, 2]


def test_pallas_multitenant_path():
    """Tenant blocks through the Pallas kernel (interpret mode on CPU):
    heterogeneous per-block entries, same results as the SIMT path."""
    conf = Configure()
    conf.batch.steps_per_launch = 5000
    conf.batch.interpret = True
    conf.batch.use_pallas = True
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.multitenant import (
        MultiTenantBatchEngine, Tenant)

    fib_i, fib_s = _inst(build_fib())
    fac_i, fac_s = _inst(build_fac())
    tenants = [
        Tenant(engine=BatchEngine(fib_i, store=fib_s, conf=conf, lanes=8),
               func_name="fib", args_lanes=[np.full(8, 10, np.int64)],
               lanes=8),
        Tenant(engine=BatchEngine(fac_i, store=fac_s, conf=conf, lanes=8),
               func_name="fac", args_lanes=[np.full(8, 10, np.int64)],
               lanes=8),
    ]
    mt = MultiTenantBatchEngine(tenants, conf=conf)
    res = mt.run_tenants(max_steps=200_000)
    assert mt.used_pallas
    assert (res[0].results[0] == 55).all()
    assert (res[1].results[0] == 3628800).all()


def test_per_tenant_wasi_isolation(tmp_path):
    """BASELINE config 5's sandbox requirement: each tenant gets its OWN
    WASI environ (preopens, fd table) — reference analog: per-VM
    WASI::Environ (environ.h:38-1156).  Two tenants with disjoint
    preopened directories must not see each other's files through the
    batched outcall channel."""
    import numpy as np

    from wasmedge_tpu.batch.multitenant import run_mixed
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.host.wasi.wasi_abi import Rights
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.utils.builder import ModuleBuilder
    from wasmedge_tpu.validator import Validator

    dir_a = tmp_path / "a"
    dir_b = tmp_path / "b"
    dir_a.mkdir()
    dir_b.mkdir()
    (dir_a / "s").write_bytes(b"AAAA")
    (dir_b / "s").write_bytes(b"BBBB")
    (dir_a / "t").write_bytes(b"ONLY")   # exists only for tenant A

    rights = int(Rights.FILE_BASE | Rights.DIR_BASE)

    def build_reader(path_byte):
        b = ModuleBuilder()
        b.import_func("wasi_snapshot_preview1", "path_open",
                      ["i32", "i32", "i32", "i32", "i32", "i64", "i64",
                       "i32", "i32"], ["i32"])
        b.import_func("wasi_snapshot_preview1", "fd_read",
                      ["i32", "i32", "i32", "i32"], ["i32"])
        b.add_memory(1, 1)
        b.add_function(["i32"], ["i32"], ["i32"], [
            ("i32.const", 100), ("i32.const", path_byte), ("i32.store8", 0, 0),
            ("i32.const", 3), ("i32.const", 1),
            ("i32.const", 100), ("i32.const", 1), ("i32.const", 0),
            ("i64.const", rights), ("i64.const", rights), ("i32.const", 0),
            ("i32.const", 200), ("call", 0),
            ("local.tee", 1),
            ("if", None),
            ("i32.const", 0), ("local.get", 1), "i32.sub", "return",
            "end",
            # iovec at 64 -> buf 300 len 4
            ("i32.const", 64), ("i32.const", 300), ("i32.store", 2, 0),
            ("i32.const", 68), ("i32.const", 4), ("i32.store", 2, 0),
            ("i32.const", 200), ("i32.load", 2, 0),
            ("i32.const", 64), ("i32.const", 1), ("i32.const", 0),
            ("call", 1),
            ("local.tee", 1),
            ("if", None),
            ("i32.const", -1000), ("local.get", 1), "i32.sub", "return",
            "end",
            ("i32.const", 300), ("i32.load", 2, 0),
        ], export="f")
        return b.build()

    conf = Configure()
    conf.batch.steps_per_launch = 10_000

    def tenant(data, host_dir):
        wasi = WasiModule()
        wasi.init_wasi(dirs=[f"/:{host_dir}"])
        mod = Validator(conf).validate(Loader(conf).parse_module(data))
        store = StoreManager()
        ex = Executor(conf)
        ex.register_import_object(store, wasi)
        inst = ex.instantiate(store, mod)
        return inst, store

    L = 8
    ia, sa = tenant(build_reader(ord("s")), dir_a)
    ib, sb = tenant(build_reader(ord("s")), dir_b)
    ic, sc = tenant(build_reader(ord("t")), dir_b)  # B's environ, A's file
    out = run_mixed([
        (ia, sa, "f", [np.zeros(L, np.int64)], L),
        (ib, sb, "f", [np.zeros(L, np.int64)], L),
        (ic, sc, "f", [np.zeros(L, np.int64)], L),
    ], conf=conf, max_steps=200_000)
    word_a = int.from_bytes(b"AAAA", "little")
    word_b = int.from_bytes(b"BBBB", "little")
    assert (np.asarray(out[0].results[0]) == word_a).all()
    assert (np.asarray(out[1].results[0]) == word_b).all()
    # tenant C shares B's preopen root: file "t" must NOT be visible
    # (result is -errno as a raw 32-bit cell; 44 = NOENT)
    got_c = np.asarray(out[2].results[0], np.int64).astype(
        np.uint32).view(np.int32)
    assert (got_c == -44).all(), got_c
