"""Native C++ engine parity suite: EngineKind.NATIVE vs the Python oracle.

The native engine runs the identical lowered image through the C++ dispatch
loop (wasmedge_tpu/native/engine.cpp); these tests drive the same modules
through both engines via the Configure seam and require identical results,
trap codes, and post-run instance state (globals/memory) — the engine-swap
discipline of the reference's SpecTest seam (test/spec/spectest.h:62-90).
"""

import threading

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure, EngineKind
from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.common.opcodes import OPCODES
from wasmedge_tpu.models import (
    build_coremark_kernel,
    build_fac,
    build_fib,
    build_loop_sum,
    build_memory_workload,
)
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

native = pytest.importorskip("wasmedge_tpu.native")


def run_engine(data, func, args, kind):
    conf = Configure()
    conf.engine = kind
    ex, store, inst = instantiate(data, conf)
    fi = inst.find_func(func)
    out = ex.invoke(store, fi, list(args))
    return out, inst, getattr(ex, "native_fallback_reason", None)


def check_parity(data, func, argsets):
    for args in argsets:
        n_out = n_exc = s_out = s_exc = None
        try:
            n_out, n_inst, _ = run_engine(data, func, args, EngineKind.NATIVE)
        except TrapError as te:
            n_exc = te.code
            n_inst = None
        try:
            s_out, s_inst, _ = run_engine(data, func, args, EngineKind.SCALAR)
        except TrapError as te:
            s_exc = te.code
            s_inst = None
        assert n_exc == s_exc, f"{func}{args}: trap {n_exc} != {s_exc}"
        assert n_out == s_out, f"{func}{args}: {n_out} != {s_out}"
        if n_inst is not None and s_inst is not None:
            for gn, gs in zip(n_inst.globals, s_inst.globals):
                assert gn.value == gs.value
            for mn, ms in zip(n_inst.memories, s_inst.memories):
                assert bytes(mn.data) == bytes(ms.data)


def test_workload_parity():
    check_parity(build_fib(), "fib", [(0,), (1,), (10,), (17,)])
    check_parity(build_fac(), "fac", [(12,), (20,)])
    check_parity(build_loop_sum(), "loop_sum", [(1,), (100000,)])
    check_parity(build_memory_workload(), "mem_checksum", [(64,), (1000,)])
    check_parity(build_coremark_kernel(), "coremark", [(16,), (64,)])


def test_native_actually_used():
    conf = Configure()
    conf.engine = EngineKind.NATIVE
    ex, store, inst = instantiate(build_fib(), conf)
    ex.invoke(store, inst.find_func("fib"), [10])
    nm = getattr(inst, "_native_module", None)
    assert nm is not None and nm is not False and nm.eligible


def test_op_level_parity_scalar_numerics():
    """Every native-supported plain numeric op, over edge inputs."""
    from tests.test_batch_parity import _EDGES, _SIG_STR, _cells

    supported = native.supported_op_ids()
    from wasmedge_tpu.common.opcodes import NAME_TO_ID
    b = ModuleBuilder()
    names = []
    for info in OPCODES:
        if info.imm != "none" or info.sig is None:
            continue
        if NAME_TO_ID[info.name] not in supported:
            continue
        pops, pushes = info.sig.split("->")
        if any(c not in "iIfF" for c in pops + pushes):
            continue
        params = [_SIG_STR.get(c, "f64") for c in pops]
        results = [_SIG_STR.get(c, "f64") for c in pushes]
        body = [("local.get", i) for i in range(len(pops))] + [info.name]
        b.add_function(params, results, [], body, export=info.name)
        names.append((info.name, pops))
    data = b.build()

    f64_edges = [0x0000000000000000, 0x8000000000000000,
                 0x3FF0000000000000, 0xBFF0000000000000,
                 0x7FF0000000000000, 0xFFF0000000000000,
                 0x7FF8000000000000, 0x7FF8000000000001,
                 0x0000000000000001, 0x41EFFFFFFFE00000,
                 0xC1E0000000000000, 0x4045000000000000]
    edges = dict(_EDGES)
    edges["F"] = f64_edges

    conf_n = Configure(); conf_n.engine = EngineKind.NATIVE
    conf_s = Configure(); conf_s.engine = EngineKind.SCALAR
    ex_n, st_n, in_n = instantiate(data, conf_n)
    ex_s, st_s, in_s = instantiate(data, conf_s)
    checked = 0
    for name, pops in names:
        fi_n = in_n.find_func(name)
        fi_s = in_s.find_func(name)
        pool = [edges[c] for c in pops]
        # pairwise zip of edge vectors (not full product: keep it fast)
        cases = []
        if len(pool) == 1:
            cases = [(v,) for v in pool[0]]
        else:
            for i, a in enumerate(pool[0]):
                for bv in (pool[1][i % len(pool[1])],
                           pool[1][(i * 7 + 3) % len(pool[1])]):
                    cases.append((a, bv))
        for vals in cases:
            raw = []
            for c, v in zip(pops, vals):
                raw.append(_cells(c, [v])[0] if c in "iI" else v)
            rn = re_ = None
            try:
                rn = ex_n.invoke_raw(st_n, fi_n, list(raw))
            except TrapError as te:
                rn = ("trap", te.code)
            try:
                re_ = ex_s.invoke_raw(st_s, fi_s, list(raw))
            except TrapError as te:
                re_ = ("trap", te.code)
            assert rn == re_, f"{name}{vals}: native {rn} != scalar {re_}"
            checked += 1
    assert checked > 1500


def test_traps_and_call_indirect():
    b = ModuleBuilder()
    add = b.add_function(["i32", "i32"], ["i32"], [],
                         [("local.get", 0), ("local.get", 1), "i32.add"])
    voidf = b.add_function([], [], [], [])
    b.add_table("funcref", 5)
    b.add_active_elem(0, [("i32.const", 0)], [add, voidf])
    ti = b.add_type(["i32", "i32"], ["i32"])
    b.add_function(["i32"], ["i32"], [], [
        ("i32.const", 30), ("i32.const", 12),
        ("local.get", 0), ("call_indirect", ti, 0),
    ], export="dispatch")
    check_parity(b.build(), "dispatch", [(0,), (1,), (3,), (99,)])


def test_memory_grow_and_oob():
    b = ModuleBuilder()
    b.add_memory(1, 4)
    b.add_function(["i32"], ["i32"], [], [
        ("i32.const", 1), "memory.grow", "drop",
        ("local.get", 0), ("i32.load", 0, 2),
    ], export="f")
    check_parity(b.build(), "f", [(0,), (65532,), (0x20000 - 4,), (0x20000,)])


def test_unbounded_recursion_exhausts():
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [],
                   [("local.get", 0), ("call", 0)], export="f")
    check_parity(b.build(), "f", [(1,)])


def test_stop_token_terminates_native():
    b = ModuleBuilder()
    # infinite loop: block/loop br 0
    b.add_function([], [], [], [("loop",), ("br", 0), ("end",)], export="spin")
    conf = Configure()
    conf.engine = EngineKind.NATIVE
    ex, store, inst = instantiate(b.build(), conf)
    fi = inst.find_func("spin")
    err = []

    def run():
        try:
            ex.invoke(store, fi, [])
        except TrapError as te:
            err.append(te.code)

    t = threading.Thread(target=run)
    t.start()
    import time
    time.sleep(0.3)
    ex.stop()
    t.join(timeout=10)
    assert not t.is_alive()
    assert err == [ErrCode.Terminated]


def test_simd_module_falls_back():
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), "i32x4.splat", ("i32x4.extract_lane", 2),
    ], export="f")
    conf = Configure()
    conf.engine = EngineKind.NATIVE
    ex, store, inst = instantiate(b.build(), conf)
    out = ex.invoke(store, inst.find_func("f"), [7])
    assert out == [7]
    assert "unsupported op" in (ex.native_fallback_reason or "")


def test_native_table_mutation_and_persistence():
    """r05: the C++ loop runs the table family in-loop (reference
    tableInstr.cpp) and mutations persist on the instance across
    invokes and across ENGINES (scalar <-> native interleave)."""
    import numpy as np  # noqa: F401
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.native import NativeModule
    from wasmedge_tpu.utils.wat import parse_wat
    from tests.helpers import instantiate

    wat = """(module (table 4 8 funcref)
      (func $a (result i32) (i32.const 7))
      (elem $e func $a)
      (func (export "go") (result i32)
        (table.init $e (i32.const 1) (i32.const 0) (i32.const 1))
        (table.set (i32.const 2) (ref.func $a))
        (drop (table.grow (ref.null func) (i32.const 2)))
        (i32.add (i32.mul (table.size) (i32.const 100))
                 (call_indirect (result i32) (i32.const 2)))))"""
    conf = Configure()
    ex, st, inst = instantiate(parse_wat(wat), conf)
    nm = NativeModule(inst, st)
    assert nm.eligible, nm.reason
    go = inst.exports["go"][1]
    assert nm.invoke(go, [])[0] == [607]          # size 4 -> 6
    assert ex.invoke_raw(st, inst.find_func("go"), []) == [807]  # 6 -> 8
    assert nm.invoke(go, [])[0] == [807]          # grow at max fails
    # elem.drop persistence: init after drop traps on both engines
    wat2 = """(module (table 2 funcref)
      (func $a (result i32) (i32.const 1))
      (elem $e func $a)
      (func (export "drop") (elem.drop $e))
      (func (export "init")
        (table.init $e (i32.const 0) (i32.const 0) (i32.const 1))))"""
    ex2, st2, in2 = instantiate(parse_wat(wat2), conf)
    nm2 = NativeModule(in2, st2)
    assert nm2.eligible, nm2.reason
    nm2.invoke(in2.exports["drop"][1], [])
    from wasmedge_tpu.common.errors import ErrCode, TrapError
    import pytest as _pytest
    with _pytest.raises(TrapError) as e1:
        nm2.invoke(in2.exports["init"][1], [])
    assert e1.value.code == ErrCode.TableOutOfBounds
    with _pytest.raises(TrapError) as e2:
        ex2.invoke_raw(st2, in2.find_func("init"), [])
    assert e2.value.code == ErrCode.TableOutOfBounds


def test_native_tail_calls_deep():
    """return_call frame replacement in C++: depth far beyond the frame
    array, plus return_call_indirect through the table."""
    from wasmedge_tpu.common.configure import Configure, Proposal
    from wasmedge_tpu.native import NativeModule
    from wasmedge_tpu.utils.wat import parse_wat
    from tests.helpers import instantiate

    wat = """(module
      (table 1 funcref)
      (type $t (func (param i32 i64) (result i64)))
      (func $sum (type $t)
        (if (result i64) (i32.eqz (local.get 0))
          (then (local.get 1))
          (else (return_call_indirect (type $t)
            (i32.sub (local.get 0) (i32.const 1))
            (i64.add (local.get 1) (i64.extend_i32_u (local.get 0)))
            (i32.const 0)))))
      (elem (i32.const 0) $sum)
      (func (export "go") (param i32) (result i64)
        (return_call $sum (local.get 0) (i64.const 0))))"""
    conf = Configure()
    conf.add_proposal(Proposal.TailCall)
    ex, st, inst = instantiate(parse_wat(wat), conf)
    nm = NativeModule(inst, st)
    assert nm.eligible, nm.reason
    n = 200_000  # >> max_call_depth: only O(1) frames completes this
    out, retired = nm.invoke(inst.exports["go"][1], [n], max_call_depth=512)
    assert out[0] == n * (n + 1) // 2
