"""Numeric-semantics tests: table-driven exactness checks for the scalar
oracle — div/rem traps, shift/rotate, clz/ctz, float NaN policy, rounding,
trunc bounds, conversions (the reference's *.ipp coverage)."""

import math
import struct

import pytest

from wasmedge_tpu.common.errors import ErrCode, TrapError
from tests.helpers import run_wasm, single_func


def op1(op, ty_in, ty_out, x):
    data = single_func([ty_in], [ty_out], [], [("local.get", 0), op])
    return run_wasm(data, "f", [x])[0]


def op2(op, ty, x, y, ty_out=None):
    data = single_func([ty, ty], [ty_out or ty], [],
                       [("local.get", 0), ("local.get", 1), op])
    return run_wasm(data, "f", [x, y])[0]


def f32bits(x):
    return struct.unpack("<I", struct.pack("<f", x))[0]


def f64bits(x):
    return struct.unpack("<Q", struct.pack("<d", x))[0]


class TestI32:
    def test_add_wrap(self):
        assert op2("i32.add", "i32", 2**31 - 1, 1) == -(2**31)

    def test_mul_wrap(self):
        masked = (0x12345678 * 0x9ABCDEF0) & 0xFFFFFFFF
        expect = masked - 2**32 if masked >= 2**31 else masked
        assert op2("i32.mul", "i32", 0x12345678, 0x9ABCDEF0 - 2**32) == expect

    def test_div_s_trunc(self):
        assert op2("i32.div_s", "i32", -7, 2) == -3
        assert op2("i32.div_s", "i32", 7, -2) == -3

    def test_div_by_zero(self):
        with pytest.raises(TrapError) as e:
            op2("i32.div_u", "i32", 1, 0)
        assert e.value.code == ErrCode.DivideByZero

    def test_div_overflow(self):
        with pytest.raises(TrapError) as e:
            op2("i32.div_s", "i32", -(2**31), -1)
        assert e.value.code == ErrCode.IntegerOverflow

    def test_rem_s(self):
        assert op2("i32.rem_s", "i32", -7, 2) == -1
        assert op2("i32.rem_s", "i32", 7, -2) == 1
        assert op2("i32.rem_s", "i32", -(2**31), -1) == 0

    def test_shifts(self):
        assert op2("i32.shl", "i32", 1, 33) == 2  # count mod 32
        assert op2("i32.shr_s", "i32", -8, 1) == -4
        assert op2("i32.shr_u", "i32", -8, 1) == 0x7FFFFFFC
        assert op2("i32.rotl", "i32", 0x80000001 - 2**32, 1) == 3
        assert op2("i32.rotr", "i32", 3, 1) == 0x80000001 - 2**32

    def test_clz_ctz_popcnt(self):
        assert op1("i32.clz", "i32", "i32", 0) == 32
        assert op1("i32.clz", "i32", "i32", 1) == 31
        assert op1("i32.ctz", "i32", "i32", 0) == 32
        assert op1("i32.ctz", "i32", "i32", 8) == 3
        assert op1("i32.popcnt", "i32", "i32", -1) == 32

    def test_cmp_signed_unsigned(self):
        assert op2("i32.lt_s", "i32", -1, 0) == 1
        assert op2("i32.lt_u", "i32", -1, 0) == 0  # 0xFFFFFFFF > 0

    def test_extend8_s(self):
        assert op1("i32.extend8_s", "i32", "i32", 0x80) == -128
        assert op1("i32.extend16_s", "i32", "i32", 0x8000) == -32768


class TestI64:
    def test_add_wrap(self):
        assert op2("i64.add", "i64", 2**63 - 1, 1) == -(2**63)

    def test_mul(self):
        assert op2("i64.mul", "i64", 0x123456789ABCDEF, 0x100000001) == \
            ((0x123456789ABCDEF * 0x100000001) & (2**64 - 1)) - 2**64

    def test_div_rem(self):
        assert op2("i64.div_s", "i64", -(10**18), 7) == -(10**18 // 7)
        with pytest.raises(TrapError):
            op2("i64.div_s", "i64", -(2**63), -1)
        assert op2("i64.rem_s", "i64", -(2**63), -1) == 0

    def test_clz(self):
        assert op1("i64.clz", "i64", "i64", 0) == 64
        assert op1("i64.clz", "i64", "i64", 2**40) == 23

    def test_extend32_s(self):
        assert op1("i64.extend32_s", "i64", "i64", 0x80000000) == -(2**31)


class TestF32:
    def test_add(self):
        assert op2("f32.add", "f32", 1.5, 2.25) == 3.75

    def test_rounding_f32(self):
        # 16777217 not representable in f32: correct rounding check
        r = op2("f32.add", "f32", 16777216.0, 1.0)
        assert float(r) == 16777216.0

    def test_nan_canonical(self):
        r = op2("f32.div", "f32", 0.0, 0.0)
        assert math.isnan(float(r))

    def test_min_max_zeros(self):
        # min(-0, +0) must be -0
        r = op2("f32.min", "f32", -0.0, 0.0)
        assert math.copysign(1, float(r)) == -1
        r = op2("f32.max", "f32", -0.0, 0.0)
        assert math.copysign(1, float(r)) == 1

    def test_min_nan(self):
        r = op2("f32.min", "f32", float("nan"), 1.0)
        assert math.isnan(float(r))

    def test_abs_neg_preserve_payload(self):
        # abs/neg are bit-level: NaN payload preserved
        b = single_func([], ["i32"], [], [
            ("f32.const", 0xFFC00001), "f32.abs", "i32.reinterpret_f32",
        ])
        assert run_wasm(b, "f")[0] == 0x7FC00001

    def test_nearest_half_even(self):
        assert float(op1("f32.nearest", "f32", "f32", 2.5)) == 2.0
        assert float(op1("f32.nearest", "f32", "f32", 3.5)) == 4.0
        assert float(op1("f32.nearest", "f32", "f32", -0.5)) == 0.0

    def test_sqrt_neg(self):
        assert math.isnan(float(op1("f32.sqrt", "f32", "f32", -1.0)))

    def test_copysign(self):
        assert float(op2("f32.copysign", "f32", 3.0, -1.0)) == -3.0


class TestF64:
    def test_div(self):
        assert float(op2("f64.div", "f64", 1.0, 3.0)) == 1.0 / 3.0

    def test_trunc_floor_ceil(self):
        assert float(op1("f64.trunc", "f64", "f64", -1.7)) == -1.0
        assert float(op1("f64.floor", "f64", "f64", -1.2)) == -2.0
        assert float(op1("f64.ceil", "f64", "f64", 1.2)) == 2.0


class TestConversions:
    def test_trunc_in_range(self):
        assert op1("i32.trunc_f32_s", "f32", "i32", -2.9) == -2
        assert op1("i32.trunc_f64_u", "f64", "i32", 4294967295.0) == -1

    def test_trunc_nan_traps(self):
        with pytest.raises(TrapError) as e:
            op1("i32.trunc_f32_s", "f32", "i32", float("nan"))
        assert e.value.code == ErrCode.InvalidConvToInt

    def test_trunc_overflow_traps(self):
        with pytest.raises(TrapError) as e:
            op1("i32.trunc_f32_s", "f32", "i32", 2147483648.0)
        assert e.value.code == ErrCode.IntegerOverflow
        with pytest.raises(TrapError):
            op1("i32.trunc_f64_s", "f64", "i32", -2147483649.0)
        # boundary allowed
        assert op1("i32.trunc_f64_s", "f64", "i32", -2147483648.0) == -(2**31)

    def test_trunc_sat(self):
        assert op1("i32.trunc_sat_f32_s", "f32", "i32", float("nan")) == 0
        assert op1("i32.trunc_sat_f32_s", "f32", "i32", 1e10) == 2**31 - 1
        assert op1("i32.trunc_sat_f32_s", "f32", "i32", -1e10) == -(2**31)
        assert op1("i32.trunc_sat_f32_u", "f32", "i32", -5.0) == 0

    def test_i64_trunc_f64(self):
        assert op1("i64.trunc_f64_s", "f64", "i64", -9e15) == -9000000000000000
        with pytest.raises(TrapError):
            op1("i64.trunc_f64_s", "f64", "i64", 9.3e18)

    def test_convert(self):
        assert float(op1("f64.convert_i32_s", "i32", "f64", -42)) == -42.0
        assert float(op1("f64.convert_i32_u", "i32", "f64", -1)) == 4294967295.0
        assert float(op1("f32.convert_i32_s", "i32", "f32", 16777217)) == 16777216.0

    def test_convert_i64_u_to_f64(self):
        # 2^64 - 1 rounds to 2^64
        assert float(op1("f64.convert_i64_u", "i64", "f64", -1)) == 2.0**64

    def test_i64_to_f32_correct_rounding(self):
        # 2^53 + 2^29 + 1: a via-f64 conversion double-rounds down to 2^53;
        # the correctly-rounded single conversion gives 2^53 + 2^30.
        v = (1 << 53) + (1 << 29) + 1
        got = op1("f32.convert_i64_s", "i64", "f32", v)
        assert float(got) == float((1 << 53) + (1 << 30))
        assert float(got) != struct.unpack("<f", struct.pack("<f", float(v)))[0]

    def test_wrap_extend(self):
        assert op1("i32.wrap_i64", "i64", "i32", 0x1_FFFF_FFFF) == -1
        assert op1("i64.extend_i32_s", "i32", "i64", -5) == -5
        assert op1("i64.extend_i32_u", "i32", "i64", -5) == 0xFFFFFFFB

    def test_demote_promote(self):
        assert float(op1("f32.demote_f64", "f64", "f32", 1.0000000001)) == 1.0
        assert float(op1("f64.promote_f32", "f32", "f64", 0.5)) == 0.5

    def test_reinterpret(self):
        assert op1("i32.reinterpret_f32", "f32", "i32", 1.0) == 0x3F800000
        got = op1("f64.reinterpret_i64", "i64", "f64", f64bits(2.5) - 2**64)
        assert float(got) == 2.5
