"""Batch observability subsystem (wasmedge_tpu/obs/): flight recorder,
Chrome trace export, Prometheus metrics, device opcode histogram, and
cross-process resume.

ISSUE 3 acceptance, pinned here:
  - obs-DISABLED runs produce bit-identical results to the seed engines
    (guard-object pattern: no recorder, no behavior change),
  - trace export is deterministic under testing/faults.py seeds (same
    seed => same event sequence modulo timestamps),
  - the Chrome trace validates against the trace_event schema,
  - Prometheus output parses and includes every failure class,
  - the Supervisor adopts an existing checkpoint_dir lineage at startup
    (--resume), recording corrupt members as FailureRecord("checkpoint").

Fast by construction (tiny lane counts, short chunks): tier-1 budget.
"""

import io
import json
import os

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.supervisor import BatchSupervisor
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.statistics import FailureRecord, Statistics
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.obs import (
    NULL_RECORDER,
    FlightRecorder,
    chrome_trace,
    parse_prometheus,
    recorder_of,
    render_prometheus,
    validate_chrome_trace,
)
from wasmedge_tpu.testing.faults import (
    Fault,
    FaultInjector,
    corrupt_checkpoint,
)
from tests.helpers import instantiate

pytestmark = pytest.mark.obs

LANES = 16

ALL_FAULT_CLASSES = ("launch", "serve", "checkpoint", "poison_lane",
                     "runaway", "demote", "scalar_rerun")


def make_conf(obs=False, **kw):
    conf = Configure()
    conf.batch.steps_per_launch = 100
    conf.batch.rng_seed = 7
    conf.supervisor.backoff_base_s = 0.0
    conf.supervisor.checkpoint_every_steps = 200
    conf.obs.enabled = obs
    for k, v in kw.items():
        setattr(conf.obs, k, v)
    return conf


def make_engine(data, conf, lanes=LANES):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def echo_engine(conf, lanes=LANES, iters=2):
    """fd_write echo module, tier 0 off so calls hit the tier-1 drain."""
    import bench_echo

    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf.batch.tier0_hostcalls = False
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    sink = os.open(os.devnull, os.O_WRONLY)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(
        Loader(conf).parse_module(bench_echo.build_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    eng = BatchEngine(inst, store=store, conf=conf, lanes=lanes)
    return eng, np.full(lanes, iters, np.int64)


def assert_results_identical(a, b):
    for ra, rb in zip(a.results, b.results):
        assert (ra == rb).all()
    assert (a.trap == b.trap).all()
    assert (a.retired == b.retired).all()


# ---------------------------------------------------------------------------
# guard object / zero-overhead contract
# ---------------------------------------------------------------------------
def test_disabled_obs_is_null_recorder():
    eng = make_engine(build_fib(), make_conf(obs=False))
    assert eng.obs is NULL_RECORDER
    assert not eng.obs.enabled
    # the guard object records nothing, ever
    eng.obs.instant("x")
    eng.obs.counter("y", 1)
    with eng.obs.timed("z"):
        pass


def test_obs_enabled_output_bit_identical_to_disabled():
    """The recorder must observe, never perturb: identical BatchResults
    with obs on and off (the seed-engine bit-identical contract)."""
    args = [(np.arange(LANES) % 11).astype(np.int64)]
    r_off = make_engine(build_fib(), make_conf(obs=False)).run(
        "fib", args, max_steps=500_000)
    r_on = make_engine(
        build_fib(), make_conf(obs=True, opcode_histogram=True)).run(
        "fib", args, max_steps=500_000)
    assert_results_identical(r_off, r_on)


def test_shared_recorder_identity_across_deepcopy():
    import copy

    conf = make_conf(obs=True)
    rec = recorder_of(conf)
    assert recorder_of(copy.deepcopy(conf)) is rec


# ---------------------------------------------------------------------------
# launch spans, occupancy, retired deltas
# ---------------------------------------------------------------------------
def test_launch_spans_and_occupancy_counters():
    eng = make_engine(build_fib(), make_conf(obs=True))
    res = eng.run("fib", [np.full(LANES, 12, np.int64)],
                  max_steps=500_000)
    assert res.completed.all()
    rec = eng.obs
    launches = [e for e in rec.events if e["name"] == "launch"]
    assert launches, "no per-launch spans recorded"
    for e in launches:
        assert e["ph"] == "X" and e["dur"] >= 0
        assert "live_lanes" in e["args"]
    # retired deltas across launch spans sum to the run's total retired
    assert sum(e["args"]["retired_delta"] for e in launches) \
        == int(np.asarray(res.retired, np.int64).sum())
    assert any(e["name"] == "live_lanes" and e["ph"] == "C"
               for e in rec.events)


def test_hostcall_drain_latency_histogram():
    eng, args = echo_engine(make_conf(obs=True))
    res = eng.run("echo", [args], max_steps=1_000_000)
    assert res.completed.all()
    rec = eng.obs
    assert "fd_write" in rec.hostcalls
    h = rec.hostcalls["fd_write"]
    assert h.count > 0 and h.lanes > 0 and h.sum_s >= 0
    # cumulative buckets are monotone and end at the observation count
    cum = h.cumulative()
    assert all(b >= a for (_, a), (_, b) in zip(cum, cum[1:]))
    assert any(e["name"] == "serve" for e in rec.events)
    assert any(e["name"] == "hostcall_queue_depth" for e in rec.events)


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------
def test_trace_export_valid_schema(tmp_path):
    eng, args = echo_engine(make_conf(obs=True))
    eng.run("echo", [args], max_steps=1_000_000)
    from wasmedge_tpu.obs import export_chrome_trace

    path = tmp_path / "trace.json"
    export_chrome_trace(eng.obs, str(path))
    obj = json.loads(path.read_text())
    assert validate_chrome_trace(obj) == []
    names = {e["name"] for e in obj["traceEvents"]}
    assert {"launch", "serve", "live_lanes", "process_name",
            "thread_name"} <= names
    # spans carry microsecond timestamps and durations
    x = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert x and all("dur" in e for e in x)


def test_trace_deterministic_under_seeded_faults(tmp_path):
    """Same fault schedule => same event sequence (modulo timestamps)."""
    def one_run(sub):
        conf = make_conf(obs=True)
        inj = FaultInjector([Fault(point="launch", at=2)])
        sup = BatchSupervisor(make_engine(build_fib(), conf), conf=conf,
                              faults=inj,
                              checkpoint_dir=str(tmp_path / sub))
        res = sup.run("fib", [(np.arange(LANES) % 9).astype(np.int64)],
                      max_steps=500_000)
        assert res.completed.all() and inj.fired == 1
        return sup.obs.event_names()

    assert one_run("a") == one_run("b")


def test_validator_rejects_malformed_trace():
    assert validate_chrome_trace([]) == ["top level is not an object"]
    assert validate_chrome_trace({}) == ["missing traceEvents array"]
    bad = {"traceEvents": [{"name": "x", "ph": "X", "pid": 1, "tid": 1,
                            "ts": 0.0}]}  # X without dur
    assert validate_chrome_trace(bad)


# ---------------------------------------------------------------------------
# prometheus export
# ---------------------------------------------------------------------------
def test_prometheus_includes_all_failure_classes():
    rec = FlightRecorder()
    stats = Statistics()
    for fc in ALL_FAULT_CLASSES:
        r = FailureRecord(fault_class=fc).stamp()
        rec.failure(r)
        stats.add_failure(r)
    text = render_prometheus(recorder=rec, stats=stats)
    parsed = parse_prometheus(text)
    for fc in ALL_FAULT_CLASSES:
        key = ("wasmedge_failures_total",
               frozenset({("fault_class", fc)}))
        # the SAME record is mirrored into recorder and stats: the
        # export must count each incident once, not per source
        assert parsed[key] == 1.0, (fc, parsed.get(key))
    # a class only one source observed still shows up
    stats.add_failure(FailureRecord(fault_class="launch").stamp())
    only = FlightRecorder()
    parsed = parse_prometheus(render_prometheus(recorder=only,
                                                stats=stats))
    assert parsed[("wasmedge_failures_total",
                   frozenset({("fault_class", "launch")}))] == 2.0


def test_prometheus_snapshot_parses_end_to_end():
    eng, args = echo_engine(make_conf(obs=True))
    eng.run("echo", [args], max_steps=1_000_000)
    text = render_prometheus(recorder=eng.obs, stats=Statistics(),
                             hostcall_stats=eng.hostcall_stats)
    parsed = parse_prometheus(text)
    name = "wasmedge_hostcall_drain_latency_seconds"
    cnt = parsed[(f"{name}_count", frozenset({("kind", "fd_write")}))]
    inf = parsed[(f"{name}_bucket",
                  frozenset({("kind", "fd_write"), ("le", "+Inf")}))]
    assert cnt == inf > 0
    assert (f"{name}_sum", frozenset({("kind", "fd_write")})) in parsed
    assert parsed[("wasmedge_hostcall_pipeline_total",
                   frozenset({("counter", "tier1_calls")}))] > 0


# ---------------------------------------------------------------------------
# device opcode histogram plane
# ---------------------------------------------------------------------------
def test_opcode_histogram_counts_match_retired():
    conf = make_conf(obs=True, opcode_histogram=True)
    eng = make_engine(build_fib(), conf)
    res = eng.run("fib", [np.full(LANES, 10, np.int64)],
                  max_steps=500_000)
    assert res.completed.all()
    counts = eng.obs.opcode_counts
    assert counts is not None
    assert int(counts.sum()) == int(np.asarray(res.retired,
                                               np.int64).sum())
    # fold into Statistics cost_table accounting
    stats = Statistics()
    stats.add_opcode_counts(counts)
    dump = stats.dump()
    assert sum(dump["opcode_counts"].values()) == int(counts.sum())
    assert dump["opcode_cost"] == int(counts.sum())  # flat-1 table


# ---------------------------------------------------------------------------
# supervisor events + failure mirroring
# ---------------------------------------------------------------------------
def test_supervisor_mirrors_failures_and_tiers(tmp_path):
    conf = make_conf(obs=True)
    inj = FaultInjector([Fault(point="launch", at=1)])
    sup = BatchSupervisor(make_engine(build_fib(), conf), conf=conf,
                          faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", [np.full(LANES, 20, np.int64)],
                  max_steps=500_000)
    assert res.completed.all()
    names = sup.obs.event_names()
    assert "failure/launch" in names
    assert "retry" in names
    assert "tier/simt" in names
    assert sup.obs.failure_counts.get("launch") == 1
    assert sup.obs.tier_seconds.get("simt", 0) > 0


def test_failure_record_monotonic_stamp():
    rec = FailureRecord(fault_class="launch").stamp()
    assert rec.time_s > 0 and rec.mono_s > 0
    # idempotent: a second stamp never rewrites the clocks
    t, m = rec.time_s, rec.mono_s
    rec.stamp()
    assert rec.time_s == t and rec.mono_s == m


# ---------------------------------------------------------------------------
# cross-process resume
# ---------------------------------------------------------------------------
def _interrupted_then_resume(tmp_path, corrupt_newest=False):
    """Process 1 runs out of budget mid-run (leaving its lineage);
    process 2 adopts the dir and completes."""
    args = [(np.arange(LANES) % 7 + 10).astype(np.int64)]
    d = str(tmp_path / "lineage")

    ref = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          checkpoint_dir=str(tmp_path / "ref"))
    rres = ref.run("fib", args, max_steps=500_000)
    assert rres.completed.all()

    sup1 = BatchSupervisor(make_engine(build_fib(), make_conf()),
                           checkpoint_dir=d)
    r1 = sup1.run("fib", args, max_steps=600)  # "crash": budget cut
    assert not r1.completed.all()
    members = sorted(f for f in os.listdir(d) if f.endswith(".npz"))
    assert members, "interrupted run left no lineage to adopt"
    if corrupt_newest:
        corrupt_checkpoint(os.path.join(d, members[-1]))

    conf2 = make_conf(obs=True)
    sup2 = BatchSupervisor(make_engine(build_fib(), conf2), conf=conf2,
                           checkpoint_dir=d, resume=True)
    r2 = sup2.run("fib", args, max_steps=500_000)
    return rres, r2, sup2, members


def test_resume_adopts_existing_lineage(tmp_path):
    rres, r2, sup2, _ = _interrupted_then_resume(tmp_path)
    assert sup2._resumed
    assert r2.completed.all()
    assert_results_identical(rres, r2)
    assert "resume_adopted" in sup2.obs.event_names()
    assert not [f for f in sup2.failures
                if f.fault_class == "checkpoint"]


def test_resume_skips_corrupt_newest_member(tmp_path):
    rres, r2, sup2, members = _interrupted_then_resume(
        tmp_path, corrupt_newest=True)
    assert r2.completed.all()
    assert_results_identical(rres, r2)
    recs = [f for f in sup2.failures if f.fault_class == "checkpoint"]
    assert len(recs) == 1 and members[-1] in recs[0].checkpoint
    if len(members) > 1:
        assert sup2._resumed  # older good member adopted


def test_reused_supervisor_second_run_starts_fresh(tmp_path):
    """A second run() on the same supervisor must NOT restore the first
    run's leftover checkpoint lineage (only --resume adopts state)."""
    conf = make_conf()
    conf.supervisor.checkpoint_every_steps = 100
    sup = BatchSupervisor(make_engine(build_fib(), conf), conf=conf,
                          checkpoint_dir=str(tmp_path))
    r1 = sup.run("fib", [np.full(LANES, 15, np.int64)],
                 max_steps=500_000)
    assert r1.completed.all() and sup._lineage  # lineage left behind
    r2 = sup.run("fib", [np.full(LANES, 6, np.int64)],
                 max_steps=500_000)
    assert r2.completed.all()
    assert (r2.results[0] == 8).all()  # fib(6), not run 1's state


def test_resume_refuses_different_invocation(tmp_path):
    """A lineage taken for f(args A) must not answer f(args B): the
    invocation fingerprint in the checkpoint metadata is checked at
    adoption, mismatched members are recorded and skipped."""
    d = str(tmp_path / "lineage")
    args_a = [np.full(LANES, 12, np.int64)]
    sup1 = BatchSupervisor(make_engine(build_fib(), make_conf()),
                           checkpoint_dir=d)
    sup1.run("fib", args_a, max_steps=600)  # interrupted, lineage left
    assert os.listdir(d)

    conf2 = make_conf()
    sup2 = BatchSupervisor(make_engine(build_fib(), conf2), conf=conf2,
                           checkpoint_dir=d, resume=True)
    args_b = [np.full(LANES, 6, np.int64)]
    r2 = sup2.run("fib", args_b, max_steps=500_000)
    assert not sup2._resumed  # every member is for args A: all refused
    assert r2.completed.all() and (r2.results[0] == 8).all()  # fib(6)
    recs = [f for f in sup2.failures if f.fault_class == "checkpoint"]
    assert recs and all("invocation" in f.error for f in recs)


def test_resume_with_empty_dir_starts_fresh(tmp_path):
    conf = make_conf()
    sup = BatchSupervisor(make_engine(build_fib(), conf), conf=conf,
                          checkpoint_dir=str(tmp_path), resume=True)
    res = sup.run("fib", [np.full(LANES, 9, np.int64)],
                  max_steps=500_000)
    assert not sup._resumed
    assert res.completed.all()


# ---------------------------------------------------------------------------
# VM + CLI plumbing
# ---------------------------------------------------------------------------
def test_vm_execute_batch_exports_trace_and_metrics(tmp_path):
    from wasmedge_tpu.vm import VM

    trace_path = tmp_path / "run.trace.json"
    metrics_path = tmp_path / "run.prom"
    conf = Configure()
    conf.batch.steps_per_launch = 100
    vm = VM(conf)
    vm.load_wasm(build_fib()).validate().instantiate()
    res = vm.execute_batch("fib", [np.full(8, 10, np.int64)], lanes=8,
                           trace_out=str(trace_path),
                           metrics_out=str(metrics_path))
    assert res.completed.all()
    obj = json.loads(trace_path.read_text())
    assert validate_chrome_trace(obj) == []
    parsed = parse_prometheus(metrics_path.read_text())
    assert ("wasmedge_obs_events_total", frozenset()) in parsed


def test_export_to_filelike():
    from wasmedge_tpu.obs import export_chrome_trace, export_prometheus

    rec = FlightRecorder()
    rec.instant("x", cat="test")
    buf = io.StringIO()
    export_chrome_trace(rec, buf)
    assert validate_chrome_trace(json.loads(buf.getvalue())) == []
    buf2 = io.StringIO()
    export_prometheus(buf2, recorder=rec)
    assert parse_prometheus(buf2.getvalue())


def test_ring_bounded_with_drop_count():
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.instant(f"e{i}")
    assert len(rec.events) == 8
    assert rec.dropped == 12
    assert rec.event_names()[0] == "e12"  # oldest dropped first
