"""Optimistic-convergence mode: rollback/recheck correctness.

The optimistic kernel takes every block-level decision from lane 0 and
accumulates a divergence canary instead of reducing across lanes per
instruction (see _build_kernel's docstring).  These tests force each
rollback trigger — divergent branch conds, semantically-equal-but-
bitwise-different conds, partial-lane traps, divergent load addresses —
and check the recovered results stay lane-exact against the scalar
oracle, with the careful-kernel recheck path actually exercised.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

LANES = 8


def make_engine(data, conf=None, lanes=LANES, hbm=None):
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine

    conf = conf or Configure()
    conf.batch.steps_per_launch = 50_000
    conf.batch.mem_hbm = hbm
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 32
    ex, store, inst = instantiate(data, conf)
    eng = PallasUniformEngine(inst, store=store, conf=conf, lanes=lanes,
                              interpret=True)
    return ex, store, inst, eng


def test_grouped_divergent_args_never_roll_back():
    """Entry grouping packs same-arg lanes into uniform blocks, so mixed
    args with repeats run divergence-free even optimistically."""
    ex, store, inst, eng = make_engine(build_fib())
    assert eng.optimistic
    args = np.array([3, 3, 9, 9, 11, 3, 9, 11], np.int64)
    res = eng.run("fib", [args], max_steps=500_000)
    assert np.asarray(res.results[0]).tolist() == \
        [2, 2, 34, 34, 89, 2, 34, 89]


def test_divergent_branch_recovers_via_recheck():
    """All-distinct args defeat entry grouping (groups of one lane): the
    block genuinely diverges mid-run, triggering a canary rollback and a
    careful-kernel recheck round."""
    ex, store, inst, eng = make_engine(build_fib())
    assert eng.optimistic
    args = np.arange(3, 11, dtype=np.int64)
    res = eng.run("fib", [args], max_steps=500_000)
    assert np.asarray(res.results[0]).tolist() == \
        [2, 3, 5, 8, 13, 21, 34, 55]
    assert eng.recheck_rounds >= 1


def test_semantic_agreement_bitwise_differs_no_false_divergence():
    """br_if conds that are nonzero-but-different agree semantically;
    the zeroness canary must not flag them."""
    b = ModuleBuilder()
    # loop n times where the continue-cond is the (varying) counter
    b.add_function(["i32"], ["i32"], ["i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("local.get", 0), ("local.get", 1), "i32.sub",
        ("br_if", 0),   # cond = n - i: nonzero differs per iteration
        "end",
        "end",
        ("local.get", 1),
    ], export="f")
    ex, store, inst, eng = make_engine(b.build())
    res = eng.run("f", [np.full(LANES, 50, np.int64)], max_steps=100_000)
    assert (np.asarray(res.results[0]) == 50).all()
    assert eng.recheck_rounds == 0


def test_partial_lane_div_by_zero_rolls_back():
    b = ModuleBuilder()
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1), "i32.div_u",
    ], export="f")
    ex, store, inst, eng = make_engine(b.build())
    num = np.full(LANES, 100, np.int64)
    den = np.array([5, 5, 0, 5, 0, 5, 5, 5], np.int64)
    res = eng.run("f", [num, den], max_steps=10_000)
    for lane in range(LANES):
        if den[lane] == 0:
            assert res.trap[lane] == int(ErrCode.DivideByZero), lane
        else:
            assert res.trap[lane] == -1
            assert int(res.results[0][lane]) == 20


def test_partial_lane_oob_load_rolls_back():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.load", 2, 0),
    ], export="f")
    for hbm in (False, True):
        ex, store, inst, eng = make_engine(b.build(), hbm=hbm)
        addr = np.array([0, 4, 8, 70000, 0, 4, 70000, 8], np.int64)
        res = eng.run("f", [addr], max_steps=10_000)
        for lane in range(LANES):
            if addr[lane] >= 65536:
                assert res.trap[lane] == int(ErrCode.MemoryOutOfBounds), \
                    (hbm, lane)
            else:
                assert res.trap[lane] == -1, (hbm, lane)


def test_divergent_load_addresses_lane_exact():
    """Per-lane different addresses: the optimistic kernel rolls back
    and the careful/SIMT path computes each lane exactly."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    # store lane-arg at its own address, read it back
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 0), ("i32.store", 2, 0),
        ("local.get", 0), ("i32.load", 2, 0),
    ], export="f")
    for hbm in (False, True):
        ex, store, inst, eng = make_engine(b.build(), hbm=hbm)
        addr = (np.arange(LANES, dtype=np.int64) * 512) % 65000
        res = eng.run("f", [addr], max_steps=10_000)
        got = np.asarray(res.results[0], np.int64)
        assert (got == addr).all(), (hbm, got.tolist())


def test_careful_mode_forced_off():
    """cfg.optimistic=False runs the per-step-checked kernel only."""
    conf = Configure()
    conf.batch.optimistic = False
    ex, store, inst, eng = make_engine(build_fib(), conf=conf)
    assert not eng.optimistic
    res = eng.run("fib", [np.full(LANES, 10, np.int64)],
                  max_steps=100_000)
    assert (np.asarray(res.results[0]) == 55).all()
    assert eng.recheck_rounds == 0


def test_retired_counts_match_careful():
    """Rollbacks must not inflate or lose retired-instruction counts on
    a clean run (uniform args: canary never fires)."""
    conf_o = Configure()
    ex, store, inst, eng_o = make_engine(build_fib(), conf=conf_o)
    conf_c = Configure()
    conf_c.batch.optimistic = False
    ex, store, inst, eng_c = make_engine(build_fib(), conf=conf_c)
    a = np.full(LANES, 14, np.int64)
    r_o = eng_o.run("fib", [a], max_steps=500_000)
    r_c = eng_c.run("fib", [a], max_steps=500_000)
    assert np.asarray(r_o.retired).sum() == np.asarray(r_c.retired).sum()


def test_snapshot_interval_commits():
    """A run far longer than SNAP_STEPS crosses many periodic commits;
    results stay exact (exercises snapshot/flush cadence)."""
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine

    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(["i32"], ["i32"], ["i32", "i32"], [
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        ("local.get", 1), ("i32.const", 4), "i32.mul",
        ("local.get", 1), ("i32.const", 0x55AA55), "i32.xor",
        ("i32.store", 2, 0),
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 4), "i32.mul", ("i32.load", 2, 0),
        "i32.add", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0),
        "end", "end",
        ("local.get", 2),
    ], export="f")
    data = b.build()
    for hbm in (False, True):
        ex, store, inst, eng = make_engine(data, hbm=hbm)
        # force frequent commits so pytest-scale runs cross many
        eng.SNAP_STEPS = 64
        n = 500
        res = eng.run("f", [np.full(LANES, n, np.int64)],
                      max_steps=2_000_000)
        s_ex, s_store, s_inst = instantiate(data, Configure())
        expect = s_ex.invoke(s_store, s_inst.find_func("f"), [n])[0]
        got = np.asarray(res.results[0], np.int64) & 0xFFFFFFFF
        assert (got == (int(expect) & 0xFFFFFFFF)).all(), (hbm, got[0])
