"""Pallas warp-interpreter parity suite (interpret mode on CPU).

The Pallas engine must agree lane-by-lane with the scalar oracle through
the same staging — the engine-swap discipline of the reference's SpecTest
seam (/root/reference/test/spec/spectest.h:62-90).  On CPU the kernel runs
in pallas interpret mode, which executes the identical kernel program the
TPU runs (minus Mosaic lowering), so the dispatch-loop logic, the
divergence bail-outs, and the SIMT handoff are all exercised by pytest.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.models import (
    build_coremark_kernel,
    build_fac,
    build_fib,
    build_loop_sum,
    build_memory_workload,
)
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

LANES = 8


def make_engine(data: bytes, lanes=LANES, chunk=50_000, conf=None):
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine

    conf = conf or Configure()
    conf.batch.steps_per_launch = chunk
    ex, store, inst = instantiate(data, conf)
    eng = PallasUniformEngine(inst, store=store, conf=conf, lanes=lanes,
                              interpret=True)
    return ex, store, inst, eng


def scalar_call(ex, store, inst, func, args):
    fi = inst.find_func(func)
    return ex.invoke(store, fi, [int(a) for a in args])


def check_parity(data, func, per_lane_args, max_steps=2_000_000,
                 conf=None):
    """Run batch vs scalar; compare per-lane values and trap codes.

    Each lane gets a *fresh* scalar instance: batch lanes are independent
    instances, so scalar state (globals/memory) must not leak across the
    per-lane oracle calls."""
    ex, store, inst, eng = make_engine(data, conf=conf)
    args = [np.asarray(a, np.int64) for a in per_lane_args]
    res = eng.run(func, args, max_steps=max_steps)
    for lane in range(LANES):
        lane_args = [int(a[lane]) for a in args]
        s_ex, s_store, s_inst = instantiate(data, conf or Configure())
        try:
            expect = scalar_call(s_ex, s_store, s_inst, func, lane_args)
            assert res.trap[lane] == -1, \
                f"lane {lane}: batch trapped {res.trap[lane]}, scalar ok"
            from wasmedge_tpu.common.types import typed_to_bits

            rtypes = s_inst.find_func(func).functype.results
            for ri, val in enumerate(expect):
                got = int(res.results[ri][lane]) & ((1 << 64) - 1)
                want = typed_to_bits(rtypes[ri], val)
                assert got == want, \
                    f"lane {lane}: got {got:#x}, scalar {want:#x} ({val})"
        except TrapError as te:
            assert res.trap[lane] == int(te.code), \
                f"lane {lane}: batch trap {res.trap[lane]} != scalar {te.code}"
    return eng, res


def test_fib_uniform_stays_on_pallas():
    eng, res = check_parity(build_fib(), "fib",
                            [np.full(LANES, 10, np.int64)])
    assert not eng.fell_back_to_simt
    assert res.results[0][0] == 55


def test_fib_divergent_args_split_on_kernel():
    # different n per lane -> control divergence -> the block scheduler
    # splits blocks at the divergent branch and keeps everything on the
    # Pallas kernel (no whole-batch SIMT abandonment)
    ns = np.array([3, 5, 8, 2, 9, 4, 7, 6], np.int64)
    eng, res = check_parity(build_fib(), "fib", [ns])
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_fac_i64_uniform():
    eng, res = check_parity(build_fac(), "fac",
                            [np.full(LANES, 12, np.int64)])
    assert res.results[0][0] == 479001600


def test_loop_sum():
    check_parity(build_loop_sum(), "loop_sum",
                 [np.full(LANES, 1000, np.int64)])


def test_memory_workload_uniform():
    # loads/stores with lane-uniform addresses stay on the pallas path
    eng, res = check_parity(build_memory_workload(), "mem_checksum",
                            [np.full(LANES, 64, np.int64)])
    assert not eng.fell_back_to_simt


def test_coremark_kernel():
    check_parity(build_coremark_kernel(), "coremark",
                 [np.full(LANES, 8, np.int64)])


def test_div_by_zero_all_lanes():
    b = ModuleBuilder()
    b.add_function(("i32",), ("i32",), (),
                   [("local.get", 0), ("i32.const", 0), ("i32.div_s",)],
                   export="f")
    check_parity(b.build(), "f", [np.full(LANES, 7, np.int64)])


def test_div_by_zero_some_lanes_diverges():
    # lane-dependent divisor: lanes 0,4 trap, others don't
    b = ModuleBuilder()
    b.add_function(("i32", "i32"), ("i32",), (),
                   [("local.get", 0), ("local.get", 1), ("i32.div_s",)],
                   export="f")
    divisors = np.array([0, 1, 2, 3, 0, 5, 6, 7], np.int64)
    eng, res = check_parity(b.build(), "f",
                            [np.full(LANES, 42, np.int64), divisors])
    # the scheduler peels the trapped lanes off; no SIMT pass needed
    assert not eng.fell_back_to_simt
    assert res.trap[0] == int(ErrCode.DivideByZero)
    assert res.trap[1] == -1


def test_unreachable_traps():
    b = ModuleBuilder()
    b.add_function((), ("i32",), (), [("unreachable",)], export="f")
    check_parity(b.build(), "f", [])


def test_call_indirect_parity():
    b = ModuleBuilder()
    b.add_function(("i32",), ("i32",), (),
                   [("local.get", 0), ("i32.const", 10), ("i32.add",)])
    b.add_function(("i32",), ("i32",), (),
                   [("local.get", 0), ("i32.const", 3), ("i32.mul",)])
    ti = b.add_type(("i32",), ("i32",))
    b.add_table("funcref", 2)
    b.add_active_elem(0, [("i32.const", 0)], [0, 1])
    b.add_function(("i32", "i32"), ("i32",), (),
                   [("local.get", 0), ("local.get", 1),
                    ("call_indirect", ti, 0)], export="dispatch")
    check_parity(b.build(), "dispatch",
                 [np.full(LANES, 5, np.int64), np.full(LANES, 1, np.int64)])


def test_br_table_uniform():
    b = ModuleBuilder()
    b.add_function(
        ("i32",), ("i32",), (),
        [("block",), ("block",), ("block",),
         ("local.get", 0), ("br_table", [0, 1], 2),
         ("end",), ("i32.const", 100), ("return",),
         ("end",), ("i32.const", 200), ("return",),
         ("end",), ("i32.const", 300)],
        export="f")
    for sel in (0, 1, 7):
        check_parity(b.build(), "f", [np.full(LANES, sel, np.int64)])


def test_globals_and_memory_grow():
    b = ModuleBuilder()
    b.add_memory(1, 3)
    b.add_global("i32", True, [("i32.const", 5)])
    b.add_function(
        ("i32",), ("i32",), (),
        [("global.get", 0), ("local.get", 0), ("i32.add",),
         ("global.set", 0),
         ("i32.const", 1), ("memory.grow",), ("drop",),
         ("memory.size",), ("global.get", 0), ("i32.add",)],
        export="f")
    conf = Configure()
    # static batch memory: the knob must cover the workload's peak pages
    # for grow parity (documented knob-dependent semantics, engine.py)
    conf.batch.memory_pages_per_lane = 3
    check_parity(b.build(), "f", [np.full(LANES, 3, np.int64)], conf=conf)


def test_unaligned_and_subword_memory():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(
        ("i32", "i32"), ("i32",), (),
        [("local.get", 0), ("local.get", 1), ("i32.store", 0, 1),
         ("local.get", 0), ("i32.load", 0, 1),
         ("local.get", 0), ("i32.load8_u", 0, 3), ("i32.add",),
         ("local.get", 0), ("i32.load16_s", 0, 1), ("i32.add",)],
        export="f")
    # odd base address -> unaligned store/load spanning words
    check_parity(b.build(), "f",
                 [np.full(LANES, 13, np.int64),
                  np.full(LANES, 0x7F61_43A5, np.int64)])


def test_divergent_addresses_gathered():
    """Per-lane addresses differ: compare-reduce gather path (W small)."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(
        ("i32", "i32"), ("i32",), (),
        [("local.get", 0), ("local.get", 1), ("i32.store", 0, 2),
         ("local.get", 0), ("i32.load", 0, 2)],
        export="f")
    addrs = np.array([0, 8, 16, 24, 4, 12, 20, 28], np.int64)
    vals = np.arange(LANES, dtype=np.int64) * 1000 + 7
    eng, res = check_parity(b.build(), "f", [addrs, vals])
    # divergent addresses are data divergence, not control divergence:
    # the gather path keeps the block on-device
    assert not eng.fell_back_to_simt


def test_memory_oob_some_lanes():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(
        ("i32",), ("i32",), (),
        [("local.get", 0), ("i32.load", 0, 2)],
        export="f")
    addrs = np.array([0, 4, 8, 0x10000, 12, 16, 0xFFFFF0, 20], np.int64)
    eng, res = check_parity(b.build(), "f", [addrs])
    assert res.trap[3] == int(ErrCode.MemoryOutOfBounds)
    assert res.trap[0] == -1


def test_deep_recursion_call_stack_exhausted():
    conf = Configure()
    conf.batch.call_stack_depth = 16
    b = ModuleBuilder()
    b.add_function(("i32",), ("i32",), (),
                   [("local.get", 0), ("i32.const", 1), ("i32.add",),
                    ("call", 0)], export="f")
    ex, store, inst, eng = make_engine(b.build(), conf=conf)
    res = eng.run("f", [np.zeros(LANES, np.int64)], max_steps=100_000)
    assert (res.trap == int(ErrCode.CallStackExhausted)).all()


def test_steps_match_xla_uniform_engine():
    """Retired-step parity with the XLA uniform engine on the same run."""
    from wasmedge_tpu.batch.uniform import UniformBatchEngine

    data = build_fib()
    conf = Configure()
    conf.batch.steps_per_launch = 50_000
    conf.batch.use_pallas = False   # reference engine must stay XLA
    ex, store, inst = instantiate(data, conf)
    xla = UniformBatchEngine(inst, store=store, conf=conf, lanes=LANES)
    r1 = xla.run("fib", [np.full(LANES, 9, np.int64)], max_steps=200_000)
    ex2, store2, inst2, eng = make_engine(data)
    r2 = eng.run("fib", [np.full(LANES, 9, np.int64)], max_steps=200_000)
    assert r1.steps == r2.steps
    assert (np.asarray(r1.results[0]) == np.asarray(r2.results[0])).all()


def test_bulk_memory_fill_and_copy():
    """memory.fill/copy on the batch engines vs the scalar oracle,
    including overlapping copies (memmove semantics) and per-lane args."""
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(
        ("i32", "i32", "i32"), ("i32",), (),
        [("local.get", 0), ("local.get", 1), ("local.get", 2),
         ("memory.fill",),
         # copy [dst+2, dst+2+n) <- [dst, dst+n) (overlap forward)
         ("local.get", 0), ("i32.const", 2), ("i32.add",),
         ("local.get", 0), ("local.get", 2), ("memory.copy",),
         # checksum a window
         ("local.get", 0), ("i32.load", 0, 2),
         ("local.get", 0), ("i32.load", 0, 6), ("i32.add",),
         ("local.get", 0), ("i32.load8_u", 0, 11), ("i32.add",)],
        export="f")
    dsts = np.array([0, 8, 13, 100, 255, 1000, 4093, 64], np.int64)
    vals = np.arange(LANES, dtype=np.int64) + 0xA0
    ns = np.array([4, 9, 16, 3, 8, 32, 1, 64], np.int64)
    eng, res = check_parity(b.build(), "f", [dsts, vals, ns])


def test_bulk_memory_oob_lanes():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(("i32", "i32"), (), (),
                   [("local.get", 0), ("i32.const", 0x5A),
                    ("local.get", 1), ("memory.fill",)], export="f")
    dsts = np.array([0, 0xFFF0, 0, 4, 8, 12, 16, 20], np.int64)
    ns = np.array([4, 0x20, 0, 4, 4, 4, 4, 4], np.int64)  # lane 1 OOB
    eng, res = check_parity(b.build(), "f", [dsts, ns])
    assert res.trap[1] == int(ErrCode.MemoryOutOfBounds)


def test_fill_and_copy_stay_on_pallas():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(("i32",), ("i32",), (),
                   [("i32.const", 16), ("local.get", 0), ("i32.const", 8),
                    ("memory.fill",),
                    ("i32.const", 16), ("i32.load", 0, 2)], export="fill")
    eng, res = check_parity(b.build(), "fill",
                            [np.full(LANES, 0x7F, np.int64)])
    assert not eng.fell_back_to_simt

    b2 = ModuleBuilder()
    b2.add_memory(1, 1)
    b2.add_function(("i32",), ("i32",), (),
                    [("i32.const", 0), ("local.get", 0), ("i32.store", 2, 0),
                     ("i32.const", 32), ("i32.const", 0), ("i32.const", 4),
                     ("memory.copy",),
                     ("i32.const", 32), ("i32.load", 0, 2)], export="cp")
    eng2, res2 = check_parity(b2.build(), "cp",
                              [np.full(LANES, 0xBEEF, np.int64)])
    assert not eng2.fell_back_to_simt  # uniform-delta copy runs in-kernel


def test_memcopy_unaligned_overlap_in_kernel():
    # per-lane dst with a uniform (src - dst) delta, including overlapping
    # forward and backward moves and sub-word byte shifts
    for delta in (5, -5, 3, -3, 64, -64, 1, 0):
        dsts = np.array([100 + k for k in range(LANES)], np.int64)
        srcs = dsts + delta
        ns = np.array([1, 2, 3, 4, 7, 9, 16, 31], np.int64)
        b3 = ModuleBuilder()
        b3.add_memory(1, 1)
        body = []
        for i in range(0, 128, 4):
            body += [("i32.const", i),
                     ("i32.const", (i * 0x01010101 + 0x0F1E2D3C) & 0x7FFFFFFF),
                     ("i32.store", 2, 0)]
        body += [("local.get", 0), ("local.get", 1), ("local.get", 2),
                 ("memory.copy",),
                 ("local.get", 0), ("i32.load", 0, 0)]
        b3.add_function(("i32", "i32", "i32"), ("i32",), (), body,
                        export="cp")
        eng, res = check_parity(b3.build(), "cp", [dsts, srcs, ns])
        assert not eng.fell_back_to_simt, f"delta {delta} fell back"


def test_memcopy_divergent_delta_falls_back():
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(("i32", "i32"), ("i32",), (),
                   [("i32.const", 0), ("i32.const", 0x11223344),
                    ("i32.store", 2, 0),
                    ("i32.const", 64), ("i32.const", 0x55667788),
                    ("i32.store", 2, 0),
                    ("local.get", 0), ("local.get", 1), ("i32.const", 4),
                    ("memory.copy",),
                    ("local.get", 0), ("i32.load", 0, 2)], export="cp")
    dsts = np.array([128, 128, 132, 132, 136, 140, 144, 148], np.int64)
    srcs = np.array([0, 64, 0, 64, 0, 64, 0, 64], np.int64)  # mixed deltas
    eng, res = check_parity(b.build(), "cp", [dsts, srcs])
    assert eng.fell_back_to_simt


def test_fuel_on_pallas_path():
    # fuel metering now runs in the kernel carry: the block trap is
    # CostLimitExceeded and the engine stays on the fast path
    conf = Configure()
    conf.batch.fuel_per_launch = 1000
    ex, store, inst, eng = make_engine(build_fib(), conf=conf)
    assert eng.eligible, eng.ineligible_reason
    res = eng.run("fib", [np.full(LANES, 25, np.int64)], max_steps=500_000)
    assert (res.trap == int(ErrCode.CostLimitExceeded)).all()

    conf2 = Configure()
    conf2.batch.fuel_per_launch = 10_000_000
    ex, store, inst, eng2 = make_engine(build_fib(), conf=conf2)
    res2 = eng2.run("fib", [np.full(LANES, 10, np.int64)],
                    max_steps=500_000)
    assert (res2.trap == -1).all()
    s_ex, s_store, s_inst = instantiate(build_fib(), Configure())
    expect = scalar_call(s_ex, s_store, s_inst, "fib", [10])
    assert int(res2.results[0][0]) == expect[0]


def test_memgrow_regrow_beyond_watermark():
    # init 1 page, declared max 3: the watermark plane holds 1 page, so a
    # legal grow to 2 pages must leave the kernel (ST_REGROW) and finish
    # on the SIMT engine with the right result
    conf = Configure()
    conf.batch.memory_pages_per_lane = 3
    b = ModuleBuilder()
    b.add_memory(1, 3)
    b.add_function((), ("i32",), (),
                   [("i32.const", 1), ("memory.grow",), "drop",
                    ("i32.const", 70000), ("i32.const", 0xCAFE),
                    ("i32.store", 2, 0),
                    ("i32.const", 70000), ("i32.load", 0, 2),
                    "drop",
                    ("memory.size",)], export="g")
    eng, res = check_parity(b.build(), "g", [], conf=conf)
    assert eng.fell_back_to_simt  # regrow handled by the big-plane engine


def _simd_wat_module():
    from wasmedge_tpu.utils.wat import parse_wat

    return parse_wat("""
(module
  (memory 1)
  (func (export "vmix") (param i32) (result i32)
    (local $acc v128)
    (local $i i32)
    (local.set $acc (v128.const i32x4 1 2 3 4))
    (block (loop
      (br_if 1 (i32.ge_u (local.get $i) (local.get 0)))
      (local.set $acc
        (i32x4.add (local.get $acc) (i32x4.splat (local.get $i))))
      (local.set $acc
        (v128.xor (local.get $acc)
                  (i8x16.shuffle 4 5 6 7 0 1 2 3 12 13 14 15 8 9 10 11
                                 (local.get $acc) (local.get $acc))))
      (local.set $i (i32.add (local.get $i) (i32.const 1)))
      (br 0)))
    ;; unaligned v128 store + load round-trip
    (v128.store offset=3 (i32.const 64) (local.get $acc))
    (local.set $acc (v128.load offset=3 (i32.const 64)))
    (i32.add
      (i32x4.extract_lane 1 (local.get $acc))
      (i32.add
        (i32x4.extract_lane 2
          (v128.bitselect (local.get $acc)
                          (v128.const i32x4 -1 -1 -1 -1)
                          (v128.const i32x4 0xFF00FF00 0x00FF00FF
                                            0xF0F0F0F0 0x0F0F0F0F)))
        (i32x4.extract_lane 3 (local.get $acc))))))
""")


def test_v128_through_pallas_kernel():
    # the v128 page runs IN the pallas kernel (handlers + 4-plane cells
    # + unaligned v128 load/store through the memory machinery)
    eng, res = check_parity(_simd_wat_module(), "vmix",
                            [np.full(LANES, 9, np.int64)])
    assert eng.eligible, eng.ineligible_reason
    assert not eng.fell_back_to_simt


def test_v128_divergent_lanes_recheck():
    # divergent per-lane loop counts force optimistic rollback + careful
    # recheck with v128 state riding the rollback shadow planes
    args = np.array([3, 3, 9, 9, 15, 15, 21, 21], np.int64)[:LANES]
    eng, res = check_parity(_simd_wat_module(), "vmix", [args])
    assert eng.eligible, eng.ineligible_reason


def test_v128_select_and_global_in_fused_block():
    # regression: fused-block select over v128 cells and global.get
    # feeding local.set must push full-width cells in simd modules
    from wasmedge_tpu.utils.wat import parse_wat

    wasm = parse_wat("""
(module
  (global $g (mut i32) (i32.const 7))
  (func (export "f") (param i32) (result i32)
    (local $v v128)
    (local $x i32)
    (local.set $v (v128.const i32x4 9 8 7 6))
    (local.set $v (select (local.get $v)
                          (v128.const i32x4 1 1 1 1)
                          (local.get 0)))
    (local.set $x (global.get $g))
    (i32.add (local.get $x)
             (i32x4.extract_lane 2 (local.get $v)))))
""")
    for arg in (0, 1):
        eng, res = check_parity(wasm, "f", [np.full(LANES, arg, np.int64)])
        assert eng.eligible, eng.ineligible_reason
