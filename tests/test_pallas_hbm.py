"""HBM-resident memory mode parity (interpret mode on CPU).

Re-runs every memory-touching test from tests/test_pallas_engine.py with
`cfg.batch.mem_hbm = True`, forcing the Pallas kernel's window-cache
memory path (HBM-resident plane + 2-way VMEM window LRU) even at the
tiny geometries pytest uses, where the auto rule would pick the
VMEM-resident slab.  The kernel program is identical to the TPU one
(minus Mosaic lowering), so window fills, write-backs, the
single-resident-copy eviction rule, in-window divergent gathers and the
beyond-window SIMT handoff are all exercised lane-exactly against the
scalar oracle.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure

import tests.test_pallas_engine as tpe

# every test in the base suite that drives linear memory (plus coremark,
# whose single store exercises the store path after a long ALU run)
_MEM_TESTS = sorted(
    name for name in dir(tpe)
    if name.startswith("test_") and any(
        k in name for k in ("memory", "memcopy", "bulk", "coremark",
                            "unaligned", "divergent_addresses",
                            "memgrow", "fill"))
)


class _HbmConfigure(Configure):
    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.batch.mem_hbm = True


@pytest.fixture(autouse=True)
def _force_hbm(monkeypatch):
    monkeypatch.setattr(tpe, "Configure", _HbmConfigure)


def test_collected_the_suite():
    # if the base suite is refactored this file must not silently shrink
    assert len(_MEM_TESTS) >= 10, _MEM_TESTS


@pytest.mark.parametrize("name", _MEM_TESTS)
def test_hbm_mode(name):
    getattr(tpe, name)()


def test_hbm_mode_engaged():
    """The forced conf actually selects the window-cache kernel."""
    from wasmedge_tpu.models import build_memory_workload

    conf = _HbmConfigure()
    conf.batch.steps_per_launch = 50_000
    ex, store, inst, eng = tpe.make_engine(build_memory_workload(),
                                           conf=conf)
    assert eng._mem_mode() is True
    res = eng.run("mem_checksum", [np.full(tpe.LANES, 200, np.int64)],
                  max_steps=2_000_000)
    assert bool(res.completed.all()) and not eng.fell_back_to_simt


def test_hbm_window_boundary_stores():
    """Stores that straddle the CW-row window boundary (i64 at the edge
    of a 128-row window) are the alignment-slack case the fits check
    guards; run a stride walk that crosses several window boundaries."""
    b = tpe.ModuleBuilder()
    b.add_memory(1, 2)
    # sum = xor of i64 loads at addr = i*520 for i in 0..n  (crosses the
    # 512-byte window every iteration, alternating ways)
    b.add_function(["i32"], ["i64"], ["i32", "i64"], [
        ("block", None),
        ("loop", None),
        ("local.get", 1), ("local.get", 0), "i32.ge_u", ("br_if", 1),
        # store i64 pattern at i*520 + 6 (unaligned, spans 3 words)
        ("local.get", 1), ("i32.const", 520), "i32.mul",
        ("local.get", 1), ("i64.extend_i32_u",),
        ("i64.const", 0x0123456789ABCDEF), "i64.xor",
        ("i64.store", 3, 6),
        # load it back and fold
        ("local.get", 2),
        ("local.get", 1), ("i32.const", 520), "i32.mul",
        ("i64.load", 3, 6),
        "i64.xor", ("local.set", 2),
        ("local.get", 1), ("i32.const", 1), "i32.add", ("local.set", 1),
        ("br", 0),
        "end",
        "end",
        ("local.get", 2),
    ], export="edgewalk")
    conf = _HbmConfigure()
    tpe.check_parity(b.build(), "edgewalk",
                     [np.full(tpe.LANES, 60, np.int64)], conf=conf)
