"""Block-scheduler suite: entry grouping, divergence splits, SIMT residue.

The scheduler (batch/scheduler.py) is what turns the block-uniform Pallas
kernel into a general engine: lanes with equal inputs share blocks, data
divergence splits blocks at the stopped instruction, and only genuinely
per-lane work lands on the SIMT engine.  Every case here checks
bit-parity against the scalar oracle per lane AND asserts the scheduling
outcome (stayed-on-kernel / split count / residue use) so regressions in
either dimension are caught.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import instantiate

LANES = 32


def make_engine(data, lanes=LANES, chunk=50_000, conf=None):
    from wasmedge_tpu.batch.pallas_engine import PallasUniformEngine

    conf = conf or Configure()
    conf.batch.steps_per_launch = chunk
    ex, store, inst = instantiate(data, conf)
    eng = PallasUniformEngine(inst, store=store, conf=conf, lanes=lanes,
                              interpret=True)
    assert eng.eligible, eng.ineligible_reason
    return ex, store, inst, eng


def run_and_check(data, func, per_lane_args, lanes=LANES,
                  max_steps=2_000_000, conf=None):
    ex, store, inst, eng = make_engine(data, lanes=lanes, conf=conf)
    args = [np.asarray(a, np.int64) for a in per_lane_args]
    res = eng.run(func, args, max_steps=max_steps)
    for lane in range(lanes):
        lane_args = [int(a[lane]) for a in args]
        s_ex, s_store, s_inst = instantiate(data, conf or Configure())
        try:
            expect = s_ex.invoke(s_store, s_inst.find_func(func), lane_args)
            assert res.trap[lane] == -1, \
                f"lane {lane}: trap {res.trap[lane]}, expected result"
            for r, e in zip(res.results, expect):
                got = int(r[lane]) & 0xFFFFFFFFFFFFFFFF
                want = int(e) & 0xFFFFFFFFFFFFFFFF
                assert got == want, f"lane {lane}: {got:#x} != {want:#x}"
        except TrapError as te:
            assert res.trap[lane] == int(te.code), \
                f"lane {lane}: trap {res.trap[lane]} != {te.code}"
    return eng, res


def test_entry_grouping_avoids_all_splits():
    # two arg populations, each >= MIN_GROUP_LANES: the scheduler packs
    # them into separate blocks, so no divergence ever occurs
    ns = np.concatenate([np.full(LANES // 2, 12, np.int64),
                         np.full(LANES // 2, 7, np.int64)])
    rng = np.random.default_rng(7)
    rng.shuffle(ns)
    eng, res = run_and_check(build_fib(), "fib", [ns])
    assert not eng.fell_back_to_simt
    assert eng.splits == 0


def test_many_groups_split_then_converge():
    # 7 shattered fib arg groups (median < MIN_GROUP_LANES -> identity
    # packing): the block MUST diverge mid-recursion and split, carrying
    # live call frames into the children, then run converged
    ns = (np.arange(LANES, dtype=np.int64) % 7) + 4
    eng, res = run_and_check(build_fib(), "fib", [ns])
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_divergent_br_table_splits():
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("block", None), ("block", None), ("block", None),
        ("local.get", 0), ("br_table", [0, 1], 2),
        "end", ("i32.const", 100), "return",
        "end", ("i32.const", 200), "return",
        "end", ("i32.const", 300),
    ], export="f")
    # 6 values -> median group size < MIN_GROUP_LANES: identity packing,
    # so the br_table itself must diverge and split in-flight
    sel = np.arange(LANES, dtype=np.int64) % 6
    eng, res = run_and_check(b.build(), "f", [sel])
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_divergent_call_indirect_with_traps():
    b = ModuleBuilder()
    f_add = b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 10), "i32.add"])
    f_mul = b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 3), "i32.mul"])
    f_other = b.add_function([], [], [], ["nop"])  # wrong signature
    b.add_table("funcref", 5)
    b.add_active_elem(0, [("i32.const", 0)], [f_add, f_mul])
    b.add_active_elem(0, [("i32.const", 3)], [f_other])
    ti = b.add_type(["i32"], ["i32"])
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1),
        ("call_indirect", ti, 0),
    ], export="f")
    data = b.build()
    # idx 0/1: ok; 2: uninitialized; 3: type mismatch; 9: undefined
    idx = np.asarray([0, 1, 2, 3, 9, 0, 1, 0] * (LANES // 8), np.int64)
    x = np.arange(LANES, dtype=np.int64)
    eng, res = run_and_check(data, "f", [x, idx])
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_divergent_memgrow_splits():
    b = ModuleBuilder()
    b.add_memory(1, 2)
    b.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("memory.grow",), "drop",
        ("memory.size",),
    ], export="g")
    conf = Configure()
    conf.batch.memory_pages_per_lane = 2
    # 5 shattered delta groups (median < MIN_GROUP_LANES -> identity
    # packing -> in-flight split); 0 succeeds in place, the rest exceed
    # the declared max and fail with -1.  grow(1) would REGROW past the
    # 1-page watermark plane — covered by the regrow test instead.
    deltas = (np.arange(LANES, dtype=np.int64) % 5) * 100000
    eng, res = run_and_check(b.build(), "g", [deltas], conf=conf)
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_partial_div_by_zero_splits_traps():
    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("i32.const", 100), ("local.get", 0), "i32.div_u",
    ], export="f")
    divs = np.asarray([1, 2, 0, 4] * (LANES // 4), np.int64)
    eng, res = run_and_check(b.build(), "f", [divs])
    assert not eng.fell_back_to_simt
    assert (res.trap[divs == 0] == int(ErrCode.DivideByZero)).all()
    assert (res.trap[divs != 0] == -1).all()


def test_simt_residue_isolated_to_bad_group():
    # lane-divergent memory.copy deltas force those lanes to the SIMT
    # residue; everything else must stay on the kernel and ALL lanes
    # must still be bit-correct
    b = ModuleBuilder()
    b.add_memory(1, 1)
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("i32.const", 0), ("i32.const", 0x11AA22BB), ("i32.store", 2, 0),
        ("i32.const", 64), ("i32.const", 0x33CC44DD), ("i32.store", 2, 0),
        ("local.get", 0), ("local.get", 1), ("i32.const", 4),
        ("memory.copy",),
        ("local.get", 0), ("i32.load", 0, 2),
    ], export="f")
    # per-lane-unique args force identity packing; the per-lane deltas
    # then diverge inside the block and cannot be split (memory-data
    # divergence), so those lanes finish on the SIMT residue
    dst = 128 + np.arange(LANES, dtype=np.int64) * 8
    src = np.where(np.arange(LANES) % 2 == 0, dst, 0)
    eng, res = run_and_check(b.build(), "f", [dst, src])
    assert eng.fell_back_to_simt  # residue ran


def test_deep_split_cascade_recursion():
    # shattered args force identity packing; lanes at different recursion
    # depths split exactly where the depths first disagree; both sides
    # complete on the kernel with live call frames carried through
    ns = np.asarray([11, 13, 9, 12, 10, 14] * 6 or [], np.int64)[:LANES]
    ns = np.concatenate([ns, np.full(LANES - len(ns), 8, np.int64)])
    eng, res = run_and_check(build_fib(), "fib", [ns])
    assert not eng.fell_back_to_simt
    assert eng.splits > 0


def test_max_steps_reports_running_lanes():
    ns = np.full(LANES, 30, np.int64)
    ex, store, inst, eng = make_engine(build_fib())
    res = eng.run("fib", [ns], max_steps=1000)
    assert (res.trap == 0).all()  # still running
    assert not res.completed.any()


def test_partial_trap_followed_by_branch_keeps_codes():
    """Regression (r3 review): a div-by-zero stop advances control to a
    branch; the splitter must peel the trapped lanes FIRST instead of
    resolving the branch and carrying trap-coded lanes into RUNNING
    children (which harvested them as successes)."""
    b = ModuleBuilder()
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1), "i32.div_u",
        ("if", "i32"),
        ("i32.const", 111),
        "else",
        ("i32.const", 222),
        "end",
    ], export="f")
    xs = np.full(LANES, 100, np.int64)
    ys = np.asarray([5, 0, 200, 5, 0, 200, 5, 200] * (LANES // 8), np.int64)
    eng, res = run_and_check(b.build(), "f", [xs, ys])
    assert (res.trap[ys == 0] == int(ErrCode.DivideByZero)).all()
    assert (res.trap[ys != 0] == -1).all()
    assert (np.asarray(res.results[0])[ys == 5] == 111).all()
    assert (np.asarray(res.results[0])[ys == 200] == 222).all()
