"""Continuous-batching serving layer (wasmedge_tpu/serve/, marker `serve`).

Pins the r9 acceptance contract:
  - per-request results bit-identical to solo execute_batch runs
  - lane recycling actually happens (freed lanes re-initialized in
    place with queued requests, not parked until batch drain)
  - deterministic admission under a seeded arrival schedule
  - weighted-fair admission: a flooding tenant cannot starve a quota'd
    one
  - deadline expiry (queued and in-flight) and queue-full rejection
  - crash/resume with in-flight requests (testing/faults.py), in
    process and across processes
  - exactly-once tier-0 stdout across supervisor restores (the flush
    cursor journaled in checkpoints)

Speed discipline: the suite is tier-1 fast.  Tests share two engine
geometries (lanes 4 and lanes 1, chunk 256) and a module-scoped JAX
persistent compilation cache, so identical engine builds deserialize
instead of recompiling (the engines' donation guard already handles
the cache-dir configuration on CPU).
"""

import os
import tempfile

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.serve import (
    BatchServer,
    DeadlineExceeded,
    FairQueue,
    QueueSaturated,
    ServeRequest,
)
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    """Module-scoped persistent compilation cache: the suite builds
    many engines of identical geometry; cache hits turn recompiles into
    deserializations.  Restored afterwards so other suites keep their
    configuration."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="serve-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    return conf


def _fib_inst(conf):
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return inst, store


def _server(conf=None, lanes=4, **kw):
    conf = conf or _conf()
    inst, store = _fib_inst(conf)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


# ---------------------------------------------------------------------------
# results parity + recycling + reuse
# ---------------------------------------------------------------------------
def test_results_bit_identical_to_solo_execute_batch():
    ns = [5, 11, 12, 7, 3, 12, 9, 2, 10, 6]
    srv = _server(lanes=4)
    futs = [srv.submit("fib", [n]) for n in ns]
    srv.run_until_idle()
    got = [f.result(0)[0] for f in futs]

    # the same requests through the stock one-shot batch entry
    from wasmedge_tpu.vm import VM

    vm = VM(_conf())
    vm.load_wasm(build_fib())
    vm.validate()
    vm.instantiate()
    solo = vm.execute_batch("fib", [np.asarray(ns, np.int64)],
                            lanes=len(ns))
    assert solo.completed.all()
    assert got == [int(x) for x in solo.results[0]]
    # continuous batching actually recycled lanes (10 requests, 4 lanes)
    assert srv.counters["recycled_lanes"] >= 6
    assert srv.counters["completed"] == len(ns)

    # the drained server is reusable: a second wave on now-idle lanes
    f2 = srv.submit("fib", [13])
    srv.run_until_idle()
    assert f2.result(0)[0] == _fib(13)


# ---------------------------------------------------------------------------
# deterministic admission
# ---------------------------------------------------------------------------
def _seeded_drive(seed, srv):
    """Interleaved submit/step schedule; returns (admission order,
    results by submission index)."""
    rng = np.random.RandomState(seed)
    futs = []
    for wave in range(5):
        for _ in range(int(rng.randint(1, 4))):
            n = int(rng.randint(3, 12))
            futs.append(srv.submit("fib", [n],
                                   tenant=f"t{int(rng.randint(2))}"))
        srv.step()
    srv.run_until_idle()
    admits = [(e["args"]["tenant"], e["args"]["lane"])
              for e in srv.obs.events if e["name"] == "admit"]
    return admits, [f.result(0)[0] for f in futs]


def test_deterministic_admission_under_seeded_schedule():
    s1 = _server(conf=_conf(obs=True), lanes=2)
    a1, r1 = _seeded_drive(42, s1)
    s2 = _server(conf=_conf(obs=True), lanes=2)
    a2, r2 = _seeded_drive(42, s2)
    assert a1 == a2
    assert r1 == r2
    assert len(a1) == len(r1) > 0


# ---------------------------------------------------------------------------
# fairness
# ---------------------------------------------------------------------------
def test_flooding_tenant_cannot_starve_quota_tenant():
    conf = _conf(obs=True)
    srv = _server(conf=conf, lanes=4,
                  quotas={"flood": 2, "blocked": 0})
    # a tenant configured out of admission is rejected at submit, not
    # stranded with a future that can never resolve — and NOT with
    # QueueSaturated: that means "try later", and this never clears
    from wasmedge_tpu.common.errors import WasmError

    with pytest.raises(WasmError) as exc:
        srv.submit("fib", [5], tenant="blocked")
    assert not isinstance(exc.value, QueueSaturated)
    flood = [srv.submit("fib", [9], tenant="flood") for _ in range(16)]
    paid = [srv.submit("fib", [5], tenant="paid") for _ in range(5)]
    max_flood_in_flight = 0
    while srv.step():
        flight = srv._flight_by_tenant()
        max_flood_in_flight = max(max_flood_in_flight,
                                  flight.get("flood", 0))
    # quota pins the flood below full occupancy; the paid tenant's
    # requests are admitted alongside, not after, the flood
    assert max_flood_in_flight <= 2
    admits = [e["args"]["tenant"] for e in srv.obs.events
              if e["name"] == "admit"]
    last_paid = max(i for i, t in enumerate(admits) if t == "paid")
    assert last_paid < 14, admits  # all 5 paid admits inside the flood
    for f in flood + paid:
        assert f.result(0) is not None


def test_weighted_drr_queue_order():
    q = FairQueue(capacity=100, weights={"a": 2.0, "b": 1.0})
    for i in range(6):
        q.push(ServeRequest("f", (i,), tenant="a"))
    for i in range(6):
        q.push(ServeRequest("f", (100 + i,), tenant="b"))
    picks = q.pop(9, {})
    by_tenant = ["a" if r.tenant == "a" else "b" for r in picks]
    # weight 2:1 — tenant a gets two admissions per DRR round to b's one
    assert by_tenant[:3] == ["a", "a", "b"]
    assert by_tenant.count("a") == 6
    assert by_tenant.count("b") == 3
    # FIFO within each tenant
    assert [r.args[0] for r in picks if r.tenant == "b"] == [100, 101, 102]
    # a tiny-but-positive weight is served slowly, never starved (the
    # DRR catch-up pop, not the stall sweep)
    q2 = FairQueue(10, weights={"tiny": 0.0005})
    q2.push(ServeRequest("f", (1,), tenant="tiny"))
    assert len(q2.pop(1, {})) == 1


# ---------------------------------------------------------------------------
# deadlines + backpressure (shared lanes=1 geometry)
# ---------------------------------------------------------------------------
def test_queued_deadline_expiry_and_queue_full():
    conf = _conf()
    conf.serve.queue_capacity = 2
    srv = _server(conf=conf, lanes=1)
    long = srv.submit("fib", [14])
    srv.step()                       # the only lane is now busy
    doomed = srv.submit("fib", [5], deadline_s=0.0)
    srv.step()                       # expires unadmitted
    with pytest.raises(DeadlineExceeded):
        doomed.result(0)
    assert srv.counters["expired"] == 1
    srv.submit("fib", [5])
    srv.submit("fib", [5])
    with pytest.raises(QueueSaturated):
        srv.submit("fib", [5])       # bounded queue: reject, not drop
    srv.run_until_idle()
    assert long.result(0)[0] == _fib(14)
    assert srv.counters["completed"] == 3


def test_in_flight_deadline_kill_and_step_budget():
    conf = _conf()
    conf.serve.max_steps_per_request = 512
    srv = _server(conf=conf, lanes=4)
    doomed = srv.submit("fib", [18], deadline_s=0.0005)
    big = srv.submit("fib", [20])     # far beyond 512 steps
    ok = srv.submit("fib", [6])
    srv.run_until_idle()
    assert ok.result(0)[0] == _fib(6)
    with pytest.raises(DeadlineExceeded):
        doomed.result(0)
    assert srv.counters["killed"] >= 2
    from wasmedge_tpu.common.errors import ErrCode, WasmError

    assert isinstance(big.error, WasmError)
    assert big.error.code == ErrCode.CostLimitExceeded
    # killed lanes are recyclable: a new request lands on one
    again = srv.submit("fib", [7])
    srv.run_until_idle()
    assert again.result(0)[0] == _fib(7)


# ---------------------------------------------------------------------------
# crash / resume with in-flight requests
# ---------------------------------------------------------------------------
def test_crash_restore_from_checkpoint_in_flight():
    ns = [6, 12, 14, 4, 9, 13, 5, 11]
    conf = _conf()
    conf.serve.checkpoint_every_rounds = 2
    conf.serve.backoff_base_s = 0.0
    inj = FaultInjector([Fault(point="launch", at=4)])
    with tempfile.TemporaryDirectory(prefix="serve-ckpt-") as d:
        srv = _server(conf=conf, lanes=4, faults=inj, checkpoint_dir=d)
        futs = [srv.submit("fib", [n]) for n in ns]
        srv.run_until_idle()
        assert inj.fired == 1
        assert srv.retries == 1
        assert any(f.fault_class == "launch" for f in srv.failures)
        assert [f.result(0)[0] for f in futs] == [_fib(n) for n in ns]


def test_crash_requeue_without_checkpoint():
    # no lineage at all: recovery re-queues every in-flight request at
    # the head of the queue and replays from scratch
    ns = [7, 13, 5, 10, 14, 6]
    conf = _conf()
    conf.serve.backoff_base_s = 0.0
    inj = FaultInjector([Fault(point="launch", at=3)])
    srv = _server(conf=conf, lanes=4, faults=inj)
    futs = [srv.submit("fib", [n]) for n in ns]
    srv.run_until_idle()
    assert inj.fired == 1
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in ns]


def test_terminal_failure_rejects_futures():
    conf = _conf()
    conf.serve.max_retries = 1
    conf.serve.backoff_base_s = 0.0
    inj = FaultInjector([Fault(point="launch", at=0, times=99)])
    srv = _server(conf=conf, lanes=4, faults=inj)
    futs = [srv.submit("fib", [12]) for _ in range(3)]
    from wasmedge_tpu.common.errors import EngineFailure

    with pytest.raises(EngineFailure):
        srv.run_until_idle()
    for f in futs:
        assert isinstance(f.error, EngineFailure)
    with pytest.raises(EngineFailure):
        srv.submit("fib", [5])


def test_cross_process_resume_adopts_in_flight():
    ns = [9, 14, 6, 13, 7, 11]
    conf = _conf()
    with tempfile.TemporaryDirectory(prefix="serve-resume-") as d:
        srv = _server(conf=conf, lanes=4, checkpoint_dir=d)
        futs = [srv.submit("fib", [n]) for n in ns]
        for _ in range(2):
            srv.step()
        srv.checkpoint()
        bound = {lane: req.args[0]
                 for lane, req in srv._bindings.items()}
        assert bound  # something was in flight at the snapshot
        del srv, futs  # "process" dies

        conf2 = _conf()
        inst2, store2 = _fib_inst(conf2)
        srv2 = BatchServer(inst2, store=store2, conf=conf2, lanes=4,
                           checkpoint_dir=d, resume=True)
        assert len(srv2.adopted) == len(bound)
        srv2.run_until_idle()
        for fut in srv2.adopted.values():
            assert fut.done and fut.error is None
        # adopted requests finish with the right answers for the args
        # the journal recorded
        got = sorted(f.result(0)[0] for f in srv2.adopted.values())
        assert got == sorted(_fib(n) for n in bound.values())
        # the adopting process's fresh submissions must id-order AFTER
        # the adopted requests (the global counter advances past the
        # journal): id order is what crash-recovery requeue sorts by,
        # and a duplicated id would shadow a future in `adopted`
        fresh = srv2.submit("fib", [4])
        assert fresh.request_id > max(srv2.adopted)
        srv2.run_until_idle()
        assert fresh.result(0)[0] == _fib(4)


# ---------------------------------------------------------------------------
# exactly-once tier-0 stdout across restores
# ---------------------------------------------------------------------------
def _echo_engine(conf, lanes, sink_path):
    import bench_echo
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.host.wasi import WasiModule

    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    sink = os.open(sink_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(
        Loader(conf).parse_module(bench_echo.build_module()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes), sink


def _run_echo_supervised(tmp, name, faults, ckpt_cadence=40):
    from wasmedge_tpu.batch.supervisor import BatchSupervisor

    conf = Configure()
    conf.batch.steps_per_launch = 40
    conf.supervisor.checkpoint_every_steps = ckpt_cadence
    conf.supervisor.backoff_base_s = 0.0
    path = os.path.join(tmp, name)
    eng, sink = _echo_engine(conf, lanes=4, sink_path=path)
    try:
        d = os.path.join(tmp, name + ".ckpt")
        sup = BatchSupervisor(eng, conf=conf, faults=faults,
                              checkpoint_dir=d)
        # 5 echo iterations: enough launches (chunk 40) that the
        # at=2 launch fault still fires now that r19 memory-run
        # fusion retires the message-building stores in fused cells
        res = sup.run("echo", [np.full(4, 5, np.int64)],
                      max_steps=1_000_000)
        assert res.completed.all()
    finally:
        os.close(sink)
    with open(path, "rb") as f:
        return f.read(), sup


_CLEAN_ECHO = {}


def _clean_echo_bytes(tmp):
    """Clean-run baseline bytes, computed once for the module (the
    output is deterministic; both exactly-once tests compare to it)."""
    if "bytes" not in _CLEAN_ECHO:
        _CLEAN_ECHO["bytes"] = _run_echo_supervised(tmp, "clean",
                                                    faults=None)[0]
    return _CLEAN_ECHO["bytes"]


def test_stdout_exactly_once_across_restore_to_initial():
    with tempfile.TemporaryDirectory(prefix="serve-stdout-") as tmp:
        clean = _clean_echo_bytes(tmp)
        assert clean  # the workload actually writes
        # every checkpoint save fails -> the launch fault restores to
        # the INITIAL state and replays output already flushed
        inj = FaultInjector([
            Fault(point="checkpoint_save", at=0, times=99),
            Fault(point="launch", at=1),
        ])
        faulted, sup = _run_echo_supervised(tmp, "faulted", faults=inj)
        assert any(f.fault_class == "launch" for f in sup.failures)
        assert faulted == clean


def test_stdout_exactly_once_across_checkpoint_restore():
    with tempfile.TemporaryDirectory(prefix="serve-stdout2-") as tmp:
        clean = _clean_echo_bytes(tmp)
        # a good checkpoint exists (cadence 40); the fault on a later
        # launch restores it — output flushed after the snapshot must
        # not be written twice (the journaled cursor rewinds, the
        # high-water mark survives)
        inj = FaultInjector([Fault(point="launch", at=2)])
        faulted, sup = _run_echo_supervised(tmp, "faulted", faults=inj)
        assert any(f.fault_class == "launch" for f in sup.failures)
        assert faulted == clean


# ---------------------------------------------------------------------------
# autotune + observability + drain
# ---------------------------------------------------------------------------
def test_autotune_feedback_rule():
    from types import SimpleNamespace

    from wasmedge_tpu.obs.recorder import FlightRecorder
    from wasmedge_tpu.serve.autotune import ChunkAutotuner

    rec = FlightRecorder(capacity=128)
    eng = SimpleNamespace(
        cfg=SimpleNamespace(steps_per_launch=1024),
        _run_chunk=object(), _step=object())
    k = Configure().serve
    tuner = ChunkAutotuner(eng, k, rec)
    # expensive drains vs the launch -> grow (and invalidate the jit)
    rec.hostcall("fd_write", 0.2, lanes=8)
    assert tuner.observe(launch_s=0.1, parked_lanes=8) == 2048
    assert eng._run_chunk is None and eng._step is None
    assert eng.cfg.steps_per_launch == 2048
    # cheap drains with parked lanes -> shrink
    rec.hostcall("fd_write", 0.0001, lanes=8)
    assert tuner.observe(launch_s=1.0, parked_lanes=8) == 1024
    # no new drain observations -> no adjustment
    assert tuner.observe(launch_s=1.0, parked_lanes=8) is None
    # clamping at the floor
    eng.cfg.steps_per_launch = k.autotune_min_chunk
    rec.hostcall("fd_write", 0.0001, lanes=8)
    assert tuner.observe(launch_s=1.0, parked_lanes=8) is None
    assert eng.cfg.steps_per_launch == k.autotune_min_chunk
    names = [e["name"] for e in rec.events]
    assert names.count("autotune") == tuner.adjustments == 2
    # off by default
    assert Configure().serve.autotune is False


def test_serve_observability_metrics_and_drain():
    import io

    from wasmedge_tpu.obs.metrics import parse_prometheus, \
        render_prometheus

    conf = _conf(obs=True)
    srv = _server(conf=conf, lanes=4)
    futs = [srv.submit("fib", [n], tenant=f"t{i % 2}")
            for i, n in enumerate((6, 9, 11, 5, 8))]
    assert srv.drain()               # graceful: serve everything queued
    for f, n in zip(futs, (6, 9, 11, 5, 8)):
        assert f.result(0)[0] == _fib(n)
    from wasmedge_tpu.common.errors import WasmError

    with pytest.raises(WasmError):
        srv.submit("fib", [5])       # draining: submissions closed
    names = [e["name"] for e in srv.obs.events]
    assert "serve_queue_depth" in names
    assert "serve_live_lanes" in names
    assert any(n.startswith("request/") for n in names)
    assert srv.obs.admission.count == 5
    text = render_prometheus(recorder=srv.obs)
    parsed = parse_prometheus(text)
    key = ("wasmedge_serve_admission_latency_seconds_count",
           frozenset())
    assert parsed[key] == 5.0
    # chrome trace export stays schema-valid with serve-track events
    from wasmedge_tpu.obs.trace import export_chrome_trace, \
        validate_chrome_trace

    buf = io.StringIO()
    obj = export_chrome_trace(srv.obs, buf)
    assert validate_chrome_trace(obj) == []
    srv.shutdown(drain=False)


def test_parked_deadline_pauses_for_explicit_wake():
    """ISSUE 19 satellite: a session parked in `await_event` must not
    burn its deadline budget while waiting on an explicit wake — the
    clock pauses at park and re-arms at install.  (Timer sleeps keep
    their absolute deadline; tests/test_effects.py pins that half.)"""
    import struct

    from wasmedge_tpu.effects import effects_import_object
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.utils.builder import ModuleBuilder

    conf = _conf()
    conf.effects.suspend = True
    b = ModuleBuilder()
    b.import_func("wasmedge", "await_event",
                  ["i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1)
    b.add_function(["i64"], ["i64"], [], [
        ("i32.const", 64), ("i32.const", 8), ("i32.const", 32),
        ("call", 0), "drop",
        ("i32.const", 64), ("i32.load", 2, 0), "i64.extend_i32_u",
        ("local.get", 0), "i64.add",
    ], export="wait")
    mod = Validator(conf).validate(Loader(conf).parse_module(b.build()))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, effects_import_object())
    inst = ex.instantiate(store, mod)
    srv = BatchServer(inst, store=store, conf=conf, lanes=2)
    import time as _t

    fut = srv.submit("wait", [3], deadline_s=0.15)
    srv.run_until_idle()                  # parks awaiting the wake
    assert srv.effects.in_flight() == 1
    _t.sleep(0.25)                        # wall clock sails PAST 0.15s
    srv.step()                            # boundary: must NOT expire it
    assert not fut.done
    assert srv.wake(fut.request_id, struct.pack("<I", 5)) == "parked"
    srv.run_until_idle()
    assert fut.result(0)[0] == 8          # resolved, not DeadlineExceeded
    assert srv.counters["killed"] == 0
    # the re-armed budget is live again after install: a request woken
    # with (nearly) spent budget still gets its full remainder, so the
    # paused window really was excluded from the accounting
    st = srv.session_stats()
    assert st["resumes"] == 1 and st["parked"] == 0


def test_cli_serve_options_after_positionals(tmp_path):
    """`wasmedge-tpu serve app.wasm func --lanes 2 --requests 3` — the
    documented form — must honor trailing options (the shared parser
    stops at the last positional for `run`'s guest-argv payload; serve
    re-parses the remainder) and reject stray positionals."""
    import io
    import json

    from wasmedge_tpu.cli import serve_command

    wasm = tmp_path / "fib.wasm"
    wasm.write_bytes(build_fib())
    out, errs = io.StringIO(), io.StringIO()
    rc = serve_command([str(wasm), "fib", "--lanes", "2",
                        "--requests", "3", "--arg-min", "4",
                        "--arg-max", "6"], out=out, err=errs)
    assert rc == 0, errs.getvalue()
    summary = json.loads(out.getvalue())
    assert summary["requests"] == 3
    assert summary["completed"] == 3

    rc = serve_command([str(wasm), "fib", "--lanes", "2", "stray"],
                       out=io.StringIO(), err=errs)
    assert rc == 2
    assert "stray" in errs.getvalue()
