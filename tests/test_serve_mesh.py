"""Serving on the single-program mesh drive (r15).

The serving stack rides the lane-sharded mesh engine: BatchServer
(and the gateway above it) submits into a lane-sharded state for
mesh-tier continuous batching, the LaneRecycler's column installs and
the hv column sets address GLOBAL lane indices — so a recycled request
or a virtual lane's SwapStore blob can land on ANY device's shard, and
the merged outcomes stay bit-identical to a single-device server.

Runs on the conftest-forced 8-device virtual CPU mesh.  Speed
discipline mirrors tests/test_serve.py / test_hv.py: tiny geometry and
a module-scoped JAX persistent compilation cache.
"""

import tempfile

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.executor import Executor
from wasmedge_tpu.loader import Loader
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.runtime.store import StoreManager
from wasmedge_tpu.serve import BatchServer
from wasmedge_tpu.validator import Validator

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module", autouse=True)
def _compile_cache():
    import jax

    prev = jax.config.jax_compilation_cache_dir
    d = tempfile.mkdtemp(prefix="serve-mesh-jit-cache-")
    jax.config.update("jax_compilation_cache_dir", d)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _conf(hv_virtual=None, obs=False):
    conf = Configure()
    conf.batch.steps_per_launch = 256
    conf.batch.value_stack_depth = 128
    conf.batch.call_stack_depth = 64
    conf.obs.enabled = obs
    if hv_virtual is not None:
        conf.hv.max_virtual_lanes = hv_virtual
    return conf


def _server(conf, lanes, **kw):
    mod = Validator(conf).validate(Loader(conf).parse_module(build_fib()))
    store = StoreManager()
    inst = Executor(conf).instantiate(store, mod)
    return BatchServer(inst, store=store, conf=conf, lanes=lanes, **kw)


NS = [5, 11, 12, 7, 3, 12, 9, 2, 10, 6, 12, 11, 8, 12, 4, 9]


def _mesh_devices(n):
    import jax

    devs = jax.devices()[:n]
    assert len(devs) == n, "virtual device mesh missing"
    return devs


def test_serve_on_mesh_bit_identical_with_recycling():
    """`--serve-smoke`-shaped run with devices>1: continuous batching
    over the lane-sharded mesh engine — recycling installs land on
    whatever shard freed a lane, and every outcome matches the
    single-device server bit-for-bit."""
    ref_srv = _server(_conf(), lanes=8)
    ref_futs = [ref_srv.submit("fib", [n]) for n in NS]
    ref_srv.run_until_idle()
    ref = [f.result(0)[0] for f in ref_futs]
    assert ref == [_fib(n) for n in NS]

    srv = _server(_conf(), lanes=8, devices=_mesh_devices(4))
    assert srv.engine.mesh is not None
    assert srv.lanes == 8   # already a device multiple
    futs = [srv.submit("fib", [n]) for n in NS]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref
    c = srv.counters
    assert c["recycled_lanes"] > 0          # continuous batching, not drain
    assert c["completed"] == len(NS)
    assert c["submitted"] == c["completed"] + c["trapped"] \
        + c["expired"] + c["killed"] + c["rejected"]


def test_serve_on_mesh_rounds_lanes_up_to_device_multiple():
    srv = _server(_conf(), lanes=6, devices=_mesh_devices(4))
    assert srv.lanes == 8
    futs = [srv.submit("fib", [n]) for n in NS[:10]]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == [_fib(n) for n in NS[:10]]


def test_hv_swap_in_lands_on_a_different_devices_shard():
    """r15 pin: with one lane per device shard (4 lanes / 4 devices)
    and hv oversubscription, some virtual lane's SwapStore blob must
    reinstall on a DIFFERENT device's shard than the lane it swapped
    out from — and the results stay bit-identical to the unswapped
    single-device reference."""
    ref_srv = _server(_conf(), lanes=4)
    ref_futs = [ref_srv.submit("fib", [n]) for n in NS]
    ref_srv.run_until_idle()
    ref = [f.result(0)[0] for f in ref_futs]

    conf = _conf(hv_virtual=16, obs=True)
    srv = _server(conf, lanes=4, devices=_mesh_devices(4))
    futs = [srv.submit("fib", [n]) for n in NS]
    srv.run_until_idle()
    assert [f.result(0)[0] for f in futs] == ref
    hv = srv.hv_stats()
    assert hv["swaps_out"] > 0 and hv["swaps_in"] > 0
    assert hv["peak_admitted"] > 4

    # lane == shard here (1 lane per device): pair each request's
    # swap-out lane with its next swap-in lane from the obs stream and
    # require at least one cross-shard reinstall
    events = [e for e in srv.obs.events
              if e["name"] in ("swap_out", "swap_in")]
    assert events
    out_lane = {}
    cross = 0
    for e in events:
        rid = e["args"]["id"]
        lane = e["args"]["lane"]
        if e["name"] == "swap_out":
            out_lane[rid] = lane
        elif rid in out_lane:
            if lane != out_lane.pop(rid):
                cross += 1
    assert cross > 0, "every swap-in landed on its original shard"


def test_gateway_on_mesh_drive():
    """The gateway's generation engine builds over the mesh: lanes
    round up to a device multiple and multi-module requests resolve
    bit-identically."""
    from wasmedge_tpu.gateway.service import GatewayService

    gw = GatewayService(conf=_conf(), lanes=6,
                        devices=_mesh_devices(4))
    try:
        gw.register_module("fib", build_fib())
        srv = gw.current.server
        assert srv.engine.mesh is not None
        assert srv.lanes == 8
        reqs = [gw.submit("fib", [n], module="fib")
                for n in (9, 10, 11, 7)]
        srv.run_until_idle()
        assert [r.future.result(5)[0] for r in reqs] \
            == [_fib(n) for n in (9, 10, 11, 7)]
    finally:
        gw.shutdown()
