"""SIMD v128: loader/validator/scalar-engine coverage of the 0xFD page.

Mirrors the reference's SIMD spec-test coverage (test/spec proposal dirs,
engine.cpp v128 block). Values cross the API as 128-bit ints; lane math
is recomputed independently here (struct/numpy) and compared bit-exactly.
"""

import struct

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure, Proposal
from wasmedge_tpu.common.errors import (
    ErrCode,
    LoadError,
    TrapError,
    ValidationError,
)
from tests.helpers import load_validate, run_wasm, single_func
from wasmedge_tpu.utils.builder import ModuleBuilder


def vi(fmt, *vals):
    """Pack lanes little-endian into a 128-bit int. fmt like '16b','8h',
    '4i','2q','4f','2d'."""
    n = int(fmt[:-1])
    code = fmt[-1]
    return int.from_bytes(struct.pack(f"<{n}{code}", *vals), "little")


def lanes_of(v, fmt):
    n = int(fmt[:-1])
    code = fmt[-1]
    return list(struct.unpack(f"<{n}{code}", int(v).to_bytes(16, "little")))


def run1(body, result="v128", params=(), args=(), locals_=()):
    data = single_func(list(params), [result], list(locals_), list(body))
    return run_wasm(data, "f", list(args))[0]


# ---------------------------------------------------------------------------
# const / splat / lanes
# ---------------------------------------------------------------------------
def test_v128_const_roundtrip():
    k = vi("4i", 1, -2, 3, -4)
    assert run1([("v128.const", k)]) == k


def test_splats():
    assert lanes_of(run1([("i32.const", 7), "i8x16.splat"]), "16b") == [7] * 16
    assert lanes_of(run1([("i32.const", -300), "i16x8.splat"]), "8h") == [-300] * 8
    assert lanes_of(run1([("i32.const", 123456), "i32x4.splat"]), "4i") == [123456] * 4
    assert lanes_of(run1([("i64.const", 2**40), "i64x2.splat"]), "2q") == [2**40] * 2
    assert lanes_of(run1([("f32.const", 1.5), "f32x4.splat"]), "4f") == [1.5] * 4
    assert lanes_of(run1([("f64.const", -2.25), "f64x2.splat"]), "2d") == [-2.25] * 2


def test_extract_replace():
    k = vi("16b", *range(-8, 8))
    assert run_wasm(single_func([], ["i32"], [], [
        ("v128.const", k), ("i8x16.extract_lane_s", 0)]), "f")[0] == -8
    assert run_wasm(single_func([], ["i32"], [], [
        ("v128.const", k), ("i8x16.extract_lane_u", 0)]), "f")[0] == 0xF8
    got = run1([("v128.const", k), ("i32.const", 99), ("i8x16.replace_lane", 3)])
    exp = lanes_of(k, "16b")
    exp[3] = 99
    assert lanes_of(got, "16b") == exp
    # i64x2 + f64x2
    k2 = vi("2q", 10, -20)
    assert run_wasm(single_func([], ["i64"], [], [
        ("v128.const", k2), ("i64x2.extract_lane", 1)]), "f")[0] == -20
    kf = vi("2d", 1.5, 2.5)
    assert run_wasm(single_func([], ["f64"], [], [
        ("v128.const", kf), ("f64x2.extract_lane", 1)]), "f")[0] == 2.5


def test_shuffle_swizzle():
    a = vi("16b", *range(16))
    b = vi("16b", *range(16, 32))
    got = run1([("v128.const", a), ("v128.const", b),
                ("i8x16.shuffle", list(range(8)) + list(range(16, 24)))])
    assert lanes_of(got, "16b") == list(range(8)) + list(range(16, 24))
    # swizzle: out-of-range -> 0
    idx = vi("16b", 0, 2, 4, 6, 8, 10, 12, 14, 16, 31, 1, 1, 1, 1, 1, 127 - 128)
    got = run1([("v128.const", a), ("v128.const", idx), "i8x16.swizzle"])
    assert lanes_of(got, "16b") == [0, 2, 4, 6, 8, 10, 12, 14, 0, 0, 1, 1, 1, 1, 1, 0]


# ---------------------------------------------------------------------------
# integer arithmetic
# ---------------------------------------------------------------------------
def test_int_add_sub_wrap():
    a = vi("4i", 2**31 - 1, -5, 100, 0)
    b = vi("4i", 1, 5, -100, 0)
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "i32x4.add"]),
                    "4i") == [-(2**31), 0, 0, 0]
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "i32x4.sub"]),
                    "4i") == [2**31 - 2, -10, 200, 0]


def test_sat_arith():
    a = vi("16b", 120, -120, 100, 0, *[0] * 12)
    b = vi("16b", 20, -20, 100, 0, *[0] * 12)
    assert lanes_of(run1([("v128.const", a), ("v128.const", b),
                          "i8x16.add_sat_s"]), "16b")[:3] == [127, -128, 127]
    au = vi("16b", -1, 10, 0, 0, *[0] * 12)  # 255 unsigned
    bu = vi("16b", 1, -1, 0, 0, *[0] * 12)
    assert lanes_of(run1([("v128.const", au), ("v128.const", bu),
                          "i8x16.add_sat_u"]), "16b")[:2] == [-1, -1]  # 255 sat
    # lane0: 1-255 saturates to 0; lane1: 255-10 = 245 (=-11 signed view)
    assert lanes_of(run1([("v128.const", bu), ("v128.const", au),
                          "i8x16.sub_sat_u"]), "16b")[:2] == [0, -11]


def test_mul_min_max_avgr():
    a = vi("8h", 1000, -1000, 7, 0, 1, 2, 3, 4)
    b = vi("8h", 100, 100, -7, 0, 1, 2, 3, 4)
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "i16x8.mul"]),
                    "8h")[:3] == [-31072, 31072, -49]  # wrap mod 2^16
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "i16x8.min_s"]),
                    "8h")[:3] == [100, -1000, -7]
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "i16x8.max_u"]),
                    "8h")[:3] == [1000, -1000, -7]  # unsigned view
    x = vi("16b", 1, 2, 3, 4, *[0] * 12)
    y = vi("16b", 2, 3, 4, 5, *[0] * 12)
    assert lanes_of(run1([("v128.const", x), ("v128.const", y),
                          "i8x16.avgr_u"]), "16b")[:4] == [2, 3, 4, 5]


def test_abs_neg_popcnt():
    a = vi("4i", -5, 5, -(2**31), 0)
    assert lanes_of(run1([("v128.const", a), "i32x4.abs"]), "4i") == \
        [5, 5, -(2**31), 0]  # INT_MIN stays (wraps)
    assert lanes_of(run1([("v128.const", a), "i32x4.neg"]), "4i") == \
        [5, -5, -(2**31), 0]
    p = vi("16b", 0, 1, 3, 7, 15, 31, 63, 127, -1, 0, 0, 0, 0, 0, 0, 0)
    assert lanes_of(run1([("v128.const", p), "i8x16.popcnt"]), "16b")[:9] == \
        [0, 1, 2, 3, 4, 5, 6, 7, 8]


def test_shifts():
    a = vi("4i", 1, -8, 2**30, 5)
    assert lanes_of(run1([("v128.const", a), ("i32.const", 2), "i32x4.shl"]),
                    "4i") == [4, -32, 0, 20]
    assert lanes_of(run1([("v128.const", a), ("i32.const", 1), "i32x4.shr_s"]),
                    "4i") == [0, -4, 2**29, 2]
    assert lanes_of(run1([("v128.const", a), ("i32.const", 1), "i32x4.shr_u"]),
                    "4i") == [0, 2**31 - 4, 2**29, 2]
    # shift amount mod lane width (i8: 8)
    assert lanes_of(run1([("v128.const", vi("16b", *[1] * 16)),
                          ("i32.const", 9), "i8x16.shl"]), "16b") == [2] * 16


def test_compares_and_reductions():
    a = vi("4i", 1, 2, 3, 4)
    b = vi("4i", 1, 5, 2, 4)
    eq = run1([("v128.const", a), ("v128.const", b), "i32x4.eq"])
    assert lanes_of(eq, "4i") == [-1, 0, 0, -1]
    lt = run1([("v128.const", a), ("v128.const", b), "i32x4.lt_s"])
    assert lanes_of(lt, "4i") == [0, -1, 0, 0]
    r = run_wasm(single_func([], ["i32"], [], [
        ("v128.const", a), "i32x4.all_true"]), "f")[0]
    assert r == 1
    r = run_wasm(single_func([], ["i32"], [], [
        ("v128.const", vi("4i", 1, 0, 1, 1)), "i32x4.all_true"]), "f")[0]
    assert r == 0
    r = run_wasm(single_func([], ["i32"], [], [
        ("v128.const", vi("4i", -1, 1, -3, 7)), "i32x4.bitmask"]), "f")[0]
    assert r == 0b0101
    r = run_wasm(single_func([], ["i32"], [], [
        ("v128.const", 0), "v128.any_true"]), "f")[0]
    assert r == 0


def test_bitwise():
    a = vi("2q", 0xF0F0, 0x1234)
    b = vi("2q", 0x0FF0, 0xFFFF)
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "v128.and"]),
                    "2q") == [0x00F0, 0x1234]
    assert lanes_of(run1([("v128.const", a), ("v128.const", b), "v128.andnot"]),
                    "2q") == [0xF000, 0]
    got = run1([("v128.const", a), ("v128.const", b), ("v128.const", vi("2q", -1, 0)),
                "v128.bitselect"])
    assert lanes_of(got, "2q") == [0xF0F0, 0xFFFF]
    assert lanes_of(run1([("v128.const", a), "v128.not"]), "2q") == \
        [~0xF0F0, ~0x1234]


# ---------------------------------------------------------------------------
# narrow / extend / extmul / pairwise / q15 / dot
# ---------------------------------------------------------------------------
def test_narrow():
    a = vi("8h", 300, -300, 100, -100, 0, 127, -128, 1)
    b = vi("8h", 1000, -1000, 5, 6, 7, 8, 9, 10)
    s = run1([("v128.const", a), ("v128.const", b), "i8x16.narrow_i16x8_s"])
    assert lanes_of(s, "16b") == [127, -128, 100, -100, 0, 127, -128, 1,
                                  127, -128, 5, 6, 7, 8, 9, 10]
    u = run1([("v128.const", a), ("v128.const", b), "i8x16.narrow_i16x8_u"])
    assert lanes_of(u, "16b") == [-1, 0, 100, 0, 0, 127, 0, 1,
                                  -1, 0, 5, 6, 7, 8, 9, 10]  # 255 = -1 signed view


def test_extend_extmul():
    a = vi("16b", *range(-8, 8))
    lo = run1([("v128.const", a), "i16x8.extend_low_i8x16_s"])
    assert lanes_of(lo, "8h") == list(range(-8, 0))
    hi = run1([("v128.const", a), "i16x8.extend_high_i8x16_u"])
    assert lanes_of(hi, "8h") == list(range(0, 8))
    b = vi("16b", *[3] * 16)
    m = run1([("v128.const", a), ("v128.const", b), "i16x8.extmul_low_i8x16_s"])
    assert lanes_of(m, "8h") == [x * 3 for x in range(-8, 0)]


def test_extadd_q15_dot():
    a = vi("16b", *range(16))
    got = run1([("v128.const", a), "i16x8.extadd_pairwise_i8x16_s"])
    assert lanes_of(got, "8h") == [1, 5, 9, 13, 17, 21, 25, 29]
    x = vi("8h", 16384, -16384, 32767, 100, 0, 0, 0, 0)
    y = vi("8h", 16384, 16384, 32767, 200, 0, 0, 0, 0)
    got = run1([("v128.const", x), ("v128.const", y), "i16x8.q15mulr_sat_s"])
    assert lanes_of(got, "8h")[:4] == [8192, -8192, 32766, 1]
    d = run1([("v128.const", vi("8h", 1, 2, 3, 4, 5, 6, 7, 8)),
              ("v128.const", vi("8h", 10, 20, 30, 40, 50, 60, 70, 80)),
              "i32x4.dot_i16x8_s"])
    assert lanes_of(d, "4i") == [1 * 10 + 2 * 20, 3 * 30 + 4 * 40,
                                 5 * 50 + 6 * 60, 7 * 70 + 8 * 80]


# ---------------------------------------------------------------------------
# floats
# ---------------------------------------------------------------------------
def test_float_arith_and_nan_canon():
    a = vi("4f", 1.5, -2.0, float("inf"), 0.0)
    b = vi("4f", 2.5, 4.0, float("-inf"), 0.0)
    s = run1([("v128.const", a), ("v128.const", b), "f32x4.add"])
    ls = lanes_of(s, "4f")
    assert ls[0] == 4.0 and ls[1] == 2.0 and np.isnan(ls[2]) and ls[3] == 0.0
    # inf + -inf -> canonical NaN bits
    bits = (int(s) >> 64) & 0xFFFFFFFF
    assert bits == 0x7FC00000


def test_float_minmax_zero_signs():
    nz = struct.unpack("<I", struct.pack("<f", -0.0))[0]
    pz = 0
    a = vi("4f", -0.0, 0.0, 1.0, 5.0)
    b = vi("4f", 0.0, -0.0, 2.0, 3.0)
    mn = lanes_of(run1([("v128.const", a), ("v128.const", b), "f32x4.min"]), "4f")
    assert struct.pack("<f", mn[0]) == struct.pack("<f", -0.0)
    mx = lanes_of(run1([("v128.const", a), ("v128.const", b), "f32x4.max"]), "4f")
    assert struct.pack("<f", mx[0]) == struct.pack("<f", 0.0)
    assert mn[2:] == [1.0, 3.0] and mx[2:] == [2.0, 5.0]
    # pmin/pmax: b<a / a<b select, -0.0 == 0.0 so no swap
    pm = lanes_of(run1([("v128.const", a), ("v128.const", b), "f32x4.pmin"]), "4f")
    assert struct.pack("<f", pm[0]) == struct.pack("<f", -0.0)  # a kept


def test_float_rounding_sqrt():
    a = vi("4f", 1.5, 2.5, -1.5, 4.0)
    assert lanes_of(run1([("v128.const", a), "f32x4.nearest"]), "4f") == \
        [2.0, 2.0, -2.0, 4.0]
    assert lanes_of(run1([("v128.const", a), "f32x4.floor"]), "4f") == \
        [1.0, 2.0, -2.0, 4.0]
    assert lanes_of(run1([("v128.const", vi("4f", 4.0, 9.0, 2.0, 0.0)),
                          "f32x4.sqrt"]), "4f")[:2] == [2.0, 3.0]
    d = vi("2d", 2.5, -2.5)
    assert lanes_of(run1([("v128.const", d), "f64x2.nearest"]), "2d") == \
        [2.0, -2.0]


def test_float_compares():
    a = vi("4f", 1.0, float("nan"), 3.0, 4.0)
    b = vi("4f", 1.0, 1.0, 2.0, 5.0)
    eq = lanes_of(run1([("v128.const", a), ("v128.const", b), "f32x4.eq"]), "4i")
    assert eq == [-1, 0, 0, 0]
    ne = lanes_of(run1([("v128.const", a), ("v128.const", b), "f32x4.ne"]), "4i")
    assert ne == [0, -1, -1, -1]


# ---------------------------------------------------------------------------
# conversions
# ---------------------------------------------------------------------------
def test_trunc_sat_and_convert():
    a = vi("4f", 1.9, -1.9, 3e9, float("nan"))
    s = lanes_of(run1([("v128.const", a), "i32x4.trunc_sat_f32x4_s"]), "4i")
    assert s == [1, -1, 2**31 - 1, 0]
    u = lanes_of(run1([("v128.const", a), "i32x4.trunc_sat_f32x4_u"]), "4i")
    assert u == [1, 0, 3000000000 - 2**32, 0]
    c = lanes_of(run1([("v128.const", vi("4i", -1, 2, 3, 2**31 - 1)),
                       "f32x4.convert_i32x4_s"]), "4f")
    assert c[0] == -1.0 and c[1] == 2.0
    cu = lanes_of(run1([("v128.const", vi("4i", -1, 0, 0, 0)),
                        "f32x4.convert_i32x4_u"]), "4f")
    assert cu[0] == np.float32(2**32 - 1)


def test_demote_promote_zero():
    d = vi("2d", 1.5, 2.5)
    f = lanes_of(run1([("v128.const", d), "f32x4.demote_f64x2_zero"]), "4f")
    assert f == [1.5, 2.5, 0.0, 0.0]
    f32 = vi("4f", 1.5, -2.5, 99.0, 99.0)
    p = lanes_of(run1([("v128.const", f32), "f64x2.promote_low_f32x4"]), "2d")
    assert p == [1.5, -2.5]
    z = lanes_of(run1([("v128.const", vi("2d", 1.9, -5e12)),
                       "i32x4.trunc_sat_f64x2_s_zero"]), "4i")
    assert z == [1, -(2**31), 0, 0]
    cl = lanes_of(run1([("v128.const", vi("4i", -7, 8, 1, 1)),
                        "f64x2.convert_low_i32x4_s"]), "2d")
    assert cl == [-7.0, 8.0]


# ---------------------------------------------------------------------------
# memory
# ---------------------------------------------------------------------------
def _mem_mod(body, result="v128", data=None):
    b = ModuleBuilder()
    b.add_memory(1, 1)
    if data:
        b.add_active_data(0, [("i32.const", 0)], data)
    b.add_function([], [result], [], body, export="f")
    return b.build()


def test_v128_load_store():
    data = bytes(range(16))
    got = run_wasm(_mem_mod([("i32.const", 0), ("v128.load", 0, 0)],
                            data=data), "f")[0]
    assert got == int.from_bytes(data, "little")
    # store then load back at offset 32
    got = run_wasm(_mem_mod([
        ("i32.const", 32), ("v128.const", vi("4i", 1, 2, 3, 4)),
        ("v128.store", 0, 0),
        ("i32.const", 32), ("v128.load", 0, 0)]), "f")[0]
    assert lanes_of(got, "4i") == [1, 2, 3, 4]


def test_v128_ext_splat_zero_loads():
    data = struct.pack("<8b", -1, 2, -3, 4, -5, 6, -7, 8)
    got = run_wasm(_mem_mod([("i32.const", 0), ("v128.load8x8_s", 0, 0)],
                            data=data), "f")[0]
    assert lanes_of(got, "8h") == [-1, 2, -3, 4, -5, 6, -7, 8]
    got = run_wasm(_mem_mod([("i32.const", 0), ("v128.load8x8_u", 0, 0)],
                            data=data), "f")[0]
    assert lanes_of(got, "8h") == [255, 2, 253, 4, 251, 6, 249, 8]
    got = run_wasm(_mem_mod([("i32.const", 0), ("v128.load32_splat", 0, 0)],
                            data=b"\x01\x02\x03\x04"), "f")[0]
    assert lanes_of(got, "4i") == [0x04030201] * 4
    got = run_wasm(_mem_mod([("i32.const", 0), ("v128.load64_zero", 0, 0)],
                            data=b"\xff" * 8), "f")[0]
    assert lanes_of(got, "2q") == [-1, 0]


def test_v128_lane_memory():
    got = run_wasm(_mem_mod([
        ("i32.const", 0),
        ("v128.const", vi("4i", 9, 9, 9, 9)),
        ("v128.load32_lane", 0, 0, 2)], data=b"\x2a\x00\x00\x00"), "f")[0]
    assert lanes_of(got, "4i") == [9, 9, 42, 9]
    got = run_wasm(_mem_mod([
        ("i32.const", 8),
        ("v128.const", vi("2q", 0x1122334455667788, -1)),
        ("v128.store64_lane", 0, 0, 0),
        ("i32.const", 0), ("v128.load", 0, 0)]), "f")[0]
    assert lanes_of(got, "2q")[1] == 0x1122334455667788


def test_v128_load_oob_traps():
    with pytest.raises(TrapError) as e:
        run_wasm(_mem_mod([("i32.const", 65535), ("v128.load", 0, 0)]), "f")
    assert e.value.code == ErrCode.MemoryOutOfBounds


# ---------------------------------------------------------------------------
# validation / gating
# ---------------------------------------------------------------------------
def test_bad_lane_index_rejected():
    data = single_func([], ["i32"], [], [
        ("v128.const", 0), ("i8x16.extract_lane_s", 16)])
    with pytest.raises(ValidationError) as e:
        load_validate(data)
    assert e.value.code == ErrCode.InvalidLaneIdx


def test_bad_shuffle_mask_rejected():
    data = single_func([], ["v128"], [], [
        ("v128.const", 0), ("v128.const", 0), ("i8x16.shuffle", [32] + [0] * 15)])
    with pytest.raises(ValidationError):
        load_validate(data)


def test_simd_alignment_over_natural_rejected():
    data = _mem_mod([("i32.const", 0), ("v128.load", 5, 0)])
    with pytest.raises(ValidationError) as e:
        load_validate(data)
    assert e.value.code == ErrCode.InvalidAlignment


def test_simd_disabled_proposal():
    conf = Configure()
    conf.remove_proposal(Proposal.SIMD)
    # v128 in a signature is refused as a malformed type under the gate
    data = single_func([], ["v128"], [], [("v128.const", 1)])
    with pytest.raises(LoadError) as e:
        load_validate(data, conf)
    assert e.value.code == ErrCode.MalformedValType
    # and 0xFD-page opcodes are refused at decode
    data = single_func([], ["i32"], [], [
        ("v128.const", 1), ("i32x4.extract_lane", 0)])
    with pytest.raises(LoadError) as e:
        load_validate(data, conf)
    assert e.value.code == ErrCode.IllegalOpCode


def test_type_mismatch_v128():
    data = single_func([], ["i32"], [], [("v128.const", 1)])
    with pytest.raises(ValidationError):
        load_validate(data)


def test_v128_local_and_select():
    got = run1([
        ("v128.const", vi("4i", 1, 2, 3, 4)), ("local.set", 0),
        ("local.get", 0), ("local.get", 0), "i32x4.add",
    ], locals_=["v128"])
    assert lanes_of(got, "4i") == [2, 4, 6, 8]


def test_aot_artifact_with_simd():
    from wasmedge_tpu import aot

    data = single_func([], ["v128"], [], [
        ("v128.const", vi("4i", 5, 6, 7, 8)),
        ("v128.const", vi("4i", 1, 1, 1, 1)), "i32x4.add"])
    art = aot.compile_module(data)
    assert lanes_of(run_wasm(art, "f")[0], "4i") == [6, 7, 8, 9]
