"""Softfloat binary64 differential suite: bit-exact vs numpy float64.

Every op/edge tested against the host's IEEE doubles (an independent
oracle): signed zeros, subnormals, infs, NaN canonicalization, RNE ties,
and 20k random bit patterns biased toward interesting exponents.  The
batch engines consume these kernels via laneops.alu2_fns/alu1_fns; the
engine-level parity suite (test_batch_parity.py) separately pins them to
the scalar oracle through the full pipeline.
"""

import numpy as np
import pytest

import jax

from wasmedge_tpu.batch import softfloat as sf

EDGES = np.array([
    0.0, -0.0, 1.0, -1.0, 0.5, -0.5, 1.5, 2.0, 3.141592653589793,
    1e308, -1e308, 1e-308, 5e-324, -5e-324, 2.2250738585072014e-308,
    np.inf, -np.inf, np.nan, 1e16, 1e16 + 2, 0.1, 0.2, 1 / 3, 2.0**52,
    2.0**53, 2.0**53 + 2.0, -2.0**52 - 0.5, 6.283185307179586, 1e-30,
    -7.25e-12, 4503599627370495.5, 0.49999999999999994, 2.5, 3.5, -2.5,
], np.float64)


def bits_of(x):
    b = np.asarray(x, np.float64).view(np.uint64)
    return ((b & 0xFFFFFFFF).astype(np.uint32).view(np.int32),
            (b >> 32).astype(np.uint32).view(np.int32))


def u64(lo, hi):
    return (np.asarray(lo).view(np.uint32).astype(np.uint64)
            | (np.asarray(hi).view(np.uint32).astype(np.uint64)
               << np.uint64(32)))


def canon(x):
    x = np.asarray(x, np.float64).copy()
    b = x.view(np.uint64)
    b[np.isnan(x)] = 0x7FF8000000000000
    return b.view(np.float64)


def rand_doubles(n, seed=42):
    rng = np.random.default_rng(seed)
    bits = rng.integers(0, 2**64, n, dtype=np.uint64)
    mask = rng.random(n) < 0.3
    e = rng.integers(1015, 1035, n, dtype=np.uint64) << 52
    bits = np.where(mask, (bits & ~(np.uint64(0x7FF) << 52)) | e, bits)
    return bits.view(np.float64)


def pairs():
    n = len(EDGES)
    a = np.concatenate([np.repeat(EDGES, n), rand_doubles(20000)])
    b = np.concatenate([np.tile(EDGES, n), rand_doubles(20000, seed=7)])
    return a, b


def check_bin(swfn, npfn):
    a, b = pairs()
    alo, ahi = bits_of(a)
    blo, bhi = bits_of(b)
    rlo, rhi = jax.jit(swfn)(alo, ahi, blo, bhi)
    with np.errstate(all="ignore"):
        want = canon(npfn(a, b)).view(np.uint64)
    got = u64(rlo, rhi)
    bad = got != want
    assert not bad.any(), (
        f"{a[bad][0]!r} op {b[bad][0]!r}: got 0x{got[bad][0]:016x} "
        f"want 0x{want[bad][0]:016x}")


def check_un(swfn, npfn, vals=None):
    a = np.concatenate([EDGES, rand_doubles(20000)]) if vals is None else vals
    alo, ahi = bits_of(a)
    rlo, rhi = jax.jit(swfn)(alo, ahi)
    with np.errstate(all="ignore"):
        want = canon(npfn(a)).view(np.uint64)
    got = u64(rlo, rhi)
    bad = got != want
    assert not bad.any(), (
        f"op({a[bad][0]!r}): got 0x{got[bad][0]:016x} "
        f"want 0x{want[bad][0]:016x}")


def wasm_min(x, y):
    out = np.where(np.isnan(x) | np.isnan(y), np.nan, np.minimum(x, y))
    bz = (x == 0) & (y == 0)
    neg = np.signbit(x) | np.signbit(y)
    return np.where(bz & ~np.isnan(x) & ~np.isnan(y),
                    np.where(neg, -0.0, 0.0), out)


def wasm_max(x, y):
    out = np.where(np.isnan(x) | np.isnan(y), np.nan, np.maximum(x, y))
    bz = (x == 0) & (y == 0)
    pos = ~np.signbit(x) | ~np.signbit(y)
    return np.where(bz & ~np.isnan(x) & ~np.isnan(y),
                    np.where(pos, 0.0, -0.0), out)


def test_add():
    check_bin(sf.f64_add, np.add)


def test_sub():
    check_bin(sf.f64_sub, np.subtract)


def test_mul():
    check_bin(sf.f64_mul, np.multiply)


def test_div():
    check_bin(sf.f64_div, np.divide)


def test_min_max():
    check_bin(sf.f64_min, wasm_min)
    check_bin(sf.f64_max, wasm_max)


def test_sqrt():
    check_un(sf.f64_sqrt, np.sqrt)


def test_roundings():
    check_un(sf.f64_trunc, np.trunc)
    check_un(sf.f64_floor, np.floor)
    check_un(sf.f64_ceil, np.ceil)
    check_un(sf.f64_nearest, np.rint)


def test_int_conversions():
    rng = np.random.default_rng(3)
    iv = np.concatenate([
        np.array([0, 1, -1, 2**63 - 1, -2**63, 2**52, 2**53, 2**53 + 1,
                  2**62, -2**62 - 12345], np.int64),
        rng.integers(-2**63, 2**63 - 1, 5000, dtype=np.int64)])
    ilo = (iv & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    ihi = ((iv >> 32) & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    rl, rh = jax.jit(lambda a, b: sf.f64_from_i64(a, b, True))(ilo, ihi)
    assert (u64(rl, rh) == iv.astype(np.float64).view(np.uint64)).all()
    rl, rh = jax.jit(lambda a, b: sf.f64_from_i64(a, b, False))(ilo, ihi)
    uv = iv.view(np.uint64)
    assert (u64(rl, rh) == uv.astype(np.float64).view(np.uint64)).all()
    r32 = jax.jit(lambda a, b: sf.f32_from_i64(a, b, True))(ilo, ihi)
    assert (np.asarray(r32).view(np.uint32)
            == iv.astype(np.float32).view(np.uint32)).all()


def test_trunc_to_i64():
    fv = np.concatenate([EDGES, rand_doubles(10000),
                         np.array([2.0**63, -(2.0**63), 2.0**63 - 2048.0,
                                   1.8446744073709552e19, -1.5])])
    flo, fhi = bits_of(fv)
    olo, ohi, ok_s, ok_u, nan = jax.jit(sf.f64_to_i64_trunc)(flo, fhi)
    with np.errstate(all="ignore"):
        tr = np.trunc(fv)
        want_ok_s = ~np.isnan(fv) & (tr >= -2.0**63) & (tr < 2.0**63)
        want_ok_u = ~np.isnan(fv) & (tr > -1.0) & (tr < 2.0**64)
    assert (np.asarray(ok_s) == want_ok_s).all()
    assert (np.asarray(ok_u) == want_ok_u).all()
    got = u64(olo, ohi).view(np.int64)
    sel = want_ok_s
    assert (got[sel] == tr[sel].astype(np.int64)).all()


def test_demote_promote():
    fv = np.concatenate([EDGES, rand_doubles(10000)])
    flo, fhi = bits_of(fv)
    r32 = jax.jit(sf.f64_to_f32)(flo, fhi)
    with np.errstate(all="ignore"):
        want32 = fv.astype(np.float32)
    want32 = np.where(np.isnan(want32), np.float32(np.nan),
                      want32).view(np.uint32)
    assert (np.asarray(r32).view(np.uint32) == want32).all()

    rng = np.random.default_rng(9)
    f32v = rng.integers(0, 2**32, 10000,
                        dtype=np.uint64).astype(np.uint32).view(np.float32)
    pl_, ph = jax.jit(sf.f32_to_f64)(f32v.view(np.int32))
    with np.errstate(all="ignore"):
        want = f32v.astype(np.float64)
    want = np.where(np.isnan(want), np.nan, want).view(np.uint64)
    assert (u64(pl_, ph) == want).all()
