"""Spawn-time regression tests: scalar/native paths never pay the JAX
import tax (AOT_r05.json python_spawn_floor attribution).

The assertions run fresh interpreters, so the suite marks them slow;
tier-1 CI keeps the cheap in-process guard at the bottom.
"""

import os
import subprocess
import sys
import textwrap

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _run_py(code: str) -> str:
    out = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env={**os.environ, "PYTHONPATH": ROOT},
    )
    assert out.returncode == 0, out.stderr
    return out.stdout


@pytest.mark.slow
def test_package_import_stays_light():
    """`import wasmedge_tpu` must not pull jax/jaxlib/numpy."""
    stdout = _run_py("""
        import sys
        import wasmedge_tpu
        print(sorted(m for m in ("jax", "jaxlib", "numpy")
                     if m in sys.modules))
    """)
    assert stdout.strip() == "[]"


@pytest.mark.slow
def test_scalar_cli_run_skips_jax():
    """A scalar-engine CLI run end-to-end must never import jax: the
    JAX import tax belongs to the batch engines only."""
    stdout = _run_py("""
        import sys
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.executor import Executor
        from wasmedge_tpu.loader import Loader
        from wasmedge_tpu.runtime.store import StoreManager
        from wasmedge_tpu.utils.builder import ModuleBuilder
        from wasmedge_tpu.validator import Validator

        b = ModuleBuilder()
        b.add_function(["i32"], ["i32"], [], [
            ("local.get", 0), ("i32.const", 1), "i32.add",
        ], export="inc")
        conf = Configure()
        mod = Validator(conf).validate(Loader(conf).parse_module(b.build()))
        store = StoreManager()
        ex = Executor(conf)
        inst = ex.instantiate(store, mod)
        assert ex.invoke(store, inst.find_func("inc"), [41]) == [42]
        print("jax" in sys.modules or "jaxlib" in sys.modules)
    """)
    assert stdout.strip() == "False"


def test_inprocess_lazy_surface():
    """Cheap tier-1 guard: the lazy re-exports resolve and the eager
    import surface of wasmedge_tpu stays numpy/jax-free (checked via
    module dependency scan, not a fresh interpreter)."""
    import importlib.util

    for mod in ("wasmedge_tpu", "wasmedge_tpu.common.configure",
                "wasmedge_tpu.common.errors", "wasmedge_tpu.common.types",
                "wasmedge_tpu.cli"):
        spec = importlib.util.find_spec(mod)
        assert spec is not None
        src = open(spec.origin).read()
        for heavy in ("\nimport jax", "\nimport numpy",
                      "\nfrom jax", "\nfrom numpy"):
            assert heavy not in src, f"{mod} imports eagerly: {heavy!r}"
    import wasmedge_tpu

    assert wasmedge_tpu.VM is not None
    assert wasmedge_tpu.make_engine is not None
