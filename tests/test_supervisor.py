"""Supervised batch execution under deterministic fault injection.

ISSUE 2 acceptance: the suite covers four fault classes — launch-time
device error, mid-serve host exception, corrupted/truncated checkpoint,
and runaway/poison lanes — and proves the supervisor recovers or cleanly
degrades on each, with crash/resume runs BIT-IDENTICAL to uninterrupted
runs for both single-module and multi-tenant engines.

Fast by construction (tiny lane counts, short chunks): stays inside the
tier-1 `-m 'not slow'` budget.
"""

import os

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.multitenant import MultiTenantBatchEngine, Tenant
from wasmedge_tpu.batch.supervisor import BatchSupervisor, scalar_rerun
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import EngineFailure, ErrCode
from wasmedge_tpu.models import build_fib, build_loop_sum
from wasmedge_tpu.testing.faults import (
    Fault,
    FaultInjector,
    InjectedFault,
    build_selective_runaway,
    corrupt_checkpoint,
    seeded_faults,
)
from tests.helpers import instantiate

pytestmark = pytest.mark.faults

LANES = 16


def make_conf(**sup):
    conf = Configure()
    conf.batch.steps_per_launch = 100
    conf.batch.rng_seed = 7  # deterministic tier-0 streams across engines
    conf.supervisor.backoff_base_s = 0.0  # no sleeping in tests
    conf.supervisor.checkpoint_every_steps = 200
    for k, v in sup.items():
        setattr(conf.supervisor, k, v)
    return conf


def make_engine(data, conf, lanes=LANES):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def fib_ref(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def assert_results_identical(a, b):
    for ra, rb in zip(a.results, b.results):
        assert (ra == rb).all()
    assert (a.trap == b.trap).all()
    assert (a.retired == b.retired).all()


# ---------------------------------------------------------------------------
# fault class 1: launch-time device error
# ---------------------------------------------------------------------------
def test_launch_fault_resume_bitmatch(tmp_path):
    args = [(np.arange(LANES) % 11).astype(np.int64)]
    ref = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          checkpoint_dir=str(tmp_path / "ref"))
    rres = ref.run("fib", args, max_steps=500_000)
    assert not ref.failures

    inj = FaultInjector([Fault(point="launch", at=3)])
    sup = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          faults=inj, checkpoint_dir=str(tmp_path / "a"))
    res = sup.run("fib", args, max_steps=500_000)
    assert inj.fired == 1
    assert res.completed.all()
    assert (res.results[0] == [fib_ref(n % 11) for n in range(LANES)]).all()
    assert_results_identical(res, rres)
    assert [f.fault_class for f in sup.failures] == ["launch"]
    # the restore came from the checkpoint lineage, not a fresh start
    assert sup.failures[0].retry == 1


def test_launch_fault_before_first_checkpoint(tmp_path):
    # failure before any checkpoint exists: restore = initial state
    args = [np.full(LANES, 9, np.int64)]
    inj = FaultInjector([Fault(point="launch", at=0)])
    sup = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.completed.all()
    assert (res.results[0] == fib_ref(9)).all()


# ---------------------------------------------------------------------------
# fault class 2: mid-serve host exception (tier-1 hostcall drain)
# ---------------------------------------------------------------------------
def _echo_setup(conf, lanes, sink_path):
    """fd_write echo module with fd 1 routed to a file; tier 0 disabled
    so every call parks on the tier-1 serve path (the injection seam)."""
    import bench_echo

    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.host.wasi import WasiModule
    from wasmedge_tpu.loader import Loader
    from wasmedge_tpu.runtime.store import StoreManager
    from wasmedge_tpu.validator import Validator

    conf.batch.tier0_hostcalls = False
    data = bench_echo.build_module()
    wasi = WasiModule()
    wasi.init_wasi(dirs=[], prog_name="echo")
    sink = os.open(sink_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC)
    wasi.env.fds[1].os_fd = sink
    mod = Validator(conf).validate(Loader(conf).parse_module(data))
    store = StoreManager()
    ex = Executor(conf)
    ex.register_import_object(store, wasi)
    inst = ex.instantiate(store, mod)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes), sink


def test_serve_fault_resume_bitmatch(tmp_path):
    lanes, iters = 8, 2
    args = [np.full(lanes, iters, np.int64)]

    ref_eng, ref_sink = _echo_setup(make_conf(), lanes,
                                    str(tmp_path / "ref.out"))
    rres = BatchSupervisor(ref_eng,
                           checkpoint_dir=str(tmp_path / "r")).run(
        "echo", args, max_steps=200_000)
    os.close(ref_sink)
    assert rres.completed.all()

    # fault fires at the FIRST serve — before any bytes reach the fd —
    # so recovery replays the writes exactly once
    inj = FaultInjector([Fault(point="serve", at=0)])
    eng, sink = _echo_setup(make_conf(), lanes, str(tmp_path / "sup.out"))
    sup = BatchSupervisor(eng, faults=inj,
                          checkpoint_dir=str(tmp_path / "s"))
    res = sup.run("echo", args, max_steps=200_000)
    os.close(sink)
    assert inj.fired == 1
    assert res.completed.all()
    assert [f.fault_class for f in sup.failures] == ["serve"]
    assert_results_identical(res, rres)
    ref_bytes = (tmp_path / "ref.out").read_bytes()
    sup_bytes = (tmp_path / "sup.out").read_bytes()
    assert sup_bytes == ref_bytes  # stdout byte-identical, no duplicates
    assert sup_bytes.count(b"hello wasi echo\n") == lanes * iters * 2


# ---------------------------------------------------------------------------
# fault class 3: corrupted / truncated checkpoint
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["truncate", "flip"])
def test_corrupt_checkpoint_refused(tmp_path, mode):
    from wasmedge_tpu.batch.checkpoint import load, save

    conf = make_conf()
    eng = make_engine(build_fib(), conf)
    state = eng.initial_state(eng.inst.exports["fib"][1],
                              [np.full(LANES, 9, np.int64)])
    state, total = eng.run_from_state(state, 0, 300)
    p = tmp_path / "c.npz"
    save(p, eng, state, total)
    corrupt_checkpoint(p, mode=mode)
    with pytest.raises(Exception):
        load(p, make_engine(build_fib(), conf))


def test_corrupt_checkpoint_lineage_fallback(tmp_path):
    """The newest checkpoint is corrupted just before the restore; the
    supervisor must record it, fall back to the older lineage member,
    and still finish bit-identical to the uninterrupted run."""
    args = [(np.arange(LANES) % 12).astype(np.int64)]
    ref = BatchSupervisor(
        make_engine(build_fib(), make_conf(keep_checkpoints=3)),
        checkpoint_dir=str(tmp_path / "ref"))
    rres = ref.run("fib", args, max_steps=500_000)

    ckdir = tmp_path / "sup"

    def corrupt_newest():
        cks = sorted(ckdir.glob("ckpt-*.npz"))
        assert cks, "fault fired before any checkpoint existed"
        corrupt_checkpoint(cks[-1], mode="truncate")

    inj = FaultInjector([
        Fault(point="launch", at=4, before=corrupt_newest)])
    sup = BatchSupervisor(
        make_engine(build_fib(), make_conf(keep_checkpoints=3)),
        faults=inj, checkpoint_dir=str(ckdir))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.completed.all()
    assert_results_identical(res, rres)
    classes = [f.fault_class for f in sup.failures]
    assert "launch" in classes and "checkpoint" in classes
    bad = [f for f in sup.failures if f.fault_class == "checkpoint"]
    assert bad[0].checkpoint  # lineage member named in the record


def test_injected_checkpoint_load_fault(tmp_path):
    # same fallback path, driven through the harness seam instead of
    # file corruption
    args = [np.full(LANES, 10, np.int64)]
    inj = FaultInjector([Fault(point="launch", at=4),
                         Fault(point="checkpoint_load", at=0)])
    sup = BatchSupervisor(
        make_engine(build_fib(), make_conf(keep_checkpoints=3)),
        faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.completed.all()
    assert (res.results[0] == fib_ref(10)).all()
    classes = [f.fault_class for f in sup.failures]
    assert classes.count("checkpoint") == 1


def test_wall_clock_cadence_fires_with_large_step_cadence(tmp_path):
    # cadences are "whichever fires first": a huge step cadence must not
    # starve the wall-clock one of its per-chunk boundary checks
    sup = BatchSupervisor(
        make_engine(build_fib(),
                    make_conf(checkpoint_every_steps=10 ** 9,
                              checkpoint_every_s=1e-9)),
        checkpoint_dir=str(tmp_path))
    res = sup.run("fib", [np.full(LANES, 11, np.int64)],
                  max_steps=500_000)
    assert res.completed.all()
    assert list(tmp_path.glob("ckpt-*.npz"))


def test_checkpoint_save_failure_is_nonfatal(tmp_path):
    args = [np.full(LANES, 10, np.int64)]
    inj = FaultInjector([Fault(point="checkpoint_save", at=0, times=99)])
    sup = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.completed.all()
    assert all(f.fault_class == "checkpoint" for f in sup.failures)
    assert not list(tmp_path.glob("ckpt-*.npz"))


# ---------------------------------------------------------------------------
# fault class 4a: poison lane (lane-attributed repeated kernel fault)
# ---------------------------------------------------------------------------
def test_poison_lane_demoted_to_scalar(tmp_path):
    args = [(np.arange(LANES) % 11).astype(np.int64)]
    # the same lane-attributed fault fires poison_lane_retries times:
    # lane 3 must be quarantined — demoted to the scalar engine (fib has
    # no host imports) — and the batch must finish correctly
    inj = FaultInjector([Fault(point="launch", at=2, times=2,
                               lanes=(3,))])
    sup = BatchSupervisor(
        make_engine(build_fib(), make_conf(poison_lane_retries=2)),
        faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert inj.fired == 2
    assert res.completed.all()  # incl. lane 3, via the scalar rung
    assert (res.results[0] == [fib_ref(n % 11) for n in range(LANES)]).all()
    poisons = [f for f in sup.failures if f.fault_class == "poison_lane"]
    assert len(poisons) == 1 and poisons[0].lanes == (3,)
    assert poisons[0].tier == "scalar"


def test_poison_lane_terminated_with_host_imports(tmp_path):
    # a module WITH host imports cannot be scalar-demoted (WASI side
    # effects would double-apply): the poisoned lane is terminated
    lanes, iters = 8, 2
    inj = FaultInjector([Fault(point="launch", at=1, times=2,
                               lanes=(2,))])
    eng, sink = _echo_setup(make_conf(poison_lane_retries=2), lanes,
                            os.devnull)
    sup = BatchSupervisor(eng, faults=inj,
                          checkpoint_dir=str(tmp_path))
    res = sup.run("echo", [np.full(lanes, iters, np.int64)],
                  max_steps=200_000)
    os.close(sink)
    assert res.trap[2] == int(ErrCode.Terminated)
    done = np.ones(lanes, bool)
    done[2] = False
    assert res.completed[done].all()
    poisons = [f for f in sup.failures if f.fault_class == "poison_lane"]
    assert len(poisons) == 1 and poisons[0].lanes == (2,)
    assert poisons[0].tier == "simt"


# ---------------------------------------------------------------------------
# fault class 4b: runaway lane (lane_step_cap)
# ---------------------------------------------------------------------------
def test_runaway_lane_terminated(tmp_path):
    args = np.arange(LANES).astype(np.int64)
    args[5] = -1  # lane 5 loops forever
    sup = BatchSupervisor(
        make_engine(build_selective_runaway(),
                    make_conf(lane_step_cap=5_000)),
        checkpoint_dir=str(tmp_path))
    res = sup.run("work", [args], max_steps=10_000_000)
    assert res.trap[5] == int(ErrCode.Terminated)
    others = np.ones(LANES, bool)
    others[5] = False
    assert res.completed[others].all()
    expect = np.array([n * (n - 1) // 2 for n in range(LANES)])
    assert (res.results[0][others] == expect[others]).all()
    runaways = [f for f in sup.failures if f.fault_class == "runaway"]
    assert len(runaways) == 1 and runaways[0].lanes == (5,)
    # the batch finished well under the (huge) step budget: the runaway
    # did not pin the device loop
    assert res.steps < 10_000_000


# ---------------------------------------------------------------------------
# degradation ladder: SIMT tier exhausted -> gas-metered scalar engine
# ---------------------------------------------------------------------------
def test_ladder_demotes_to_scalar_engine(tmp_path):
    args = [(np.arange(LANES) % 9).astype(np.int64)]
    inj = FaultInjector([Fault(point="launch", at=0, times=1000)])
    sup = BatchSupervisor(
        make_engine(build_fib(), make_conf(max_retries=2)),
        faults=inj, checkpoint_dir=str(tmp_path))
    res = sup.run("fib", args, max_steps=500_000)
    assert res.completed.all()
    assert (res.results[0] == [fib_ref(n % 9) for n in range(LANES)]).all()
    classes = [f.fault_class for f in sup.failures]
    assert "demote" in classes
    # max_retries + 1 per SIMT rung; fib fuses by default, so the
    # ladder now walks fused -> unfused SIMT -> scalar (batch/fuse.py)
    assert classes.count("launch") == 6


def test_ladder_exhaustion_raises_engine_failure(tmp_path):
    # echo has host imports: no scalar rung; permanent launch failure
    # must surface as EngineFailure carrying the FailureRecord taxonomy
    eng, sink = _echo_setup(make_conf(max_retries=1), 8, os.devnull)
    inj = FaultInjector([Fault(point="launch", at=0, times=1000)])
    sup = BatchSupervisor(eng, faults=inj, checkpoint_dir=str(tmp_path))
    with pytest.raises(EngineFailure) as ei:
        sup.run("echo", [np.full(8, 1, np.int64)], max_steps=100_000)
    os.close(sink)
    assert any(f.fault_class == "demote" for f in ei.value.failures)


# ---------------------------------------------------------------------------
# multi-tenant: crash/resume bit-exactness across tenants
# ---------------------------------------------------------------------------
def _mt_engine(conf):
    exf, storef, instf = instantiate(build_fib(), conf)
    exl, storel, instl = instantiate(build_loop_sum(), conf)
    t0 = Tenant(engine=BatchEngine(instf, store=storef, conf=conf,
                                   lanes=8),
                func_name="fib",
                args_lanes=[(np.arange(8) % 10).astype(np.int64)],
                lanes=8)
    t1 = Tenant(engine=BatchEngine(instl, store=storel, conf=conf,
                                   lanes=8),
                func_name="loop_sum",
                args_lanes=[(np.arange(8) * 7).astype(np.int64)],
                lanes=8)
    return MultiTenantBatchEngine([t0, t1], conf=conf)


def test_multitenant_fault_resume_bitmatch(tmp_path):
    ref = BatchSupervisor(_mt_engine(make_conf()),
                          checkpoint_dir=str(tmp_path / "ref"))
    rres = ref.run(max_steps=500_000)
    assert not ref.failures

    inj = FaultInjector([Fault(point="launch", at=2)])
    sup = BatchSupervisor(_mt_engine(make_conf()), faults=inj,
                          checkpoint_dir=str(tmp_path / "sup"))
    res = sup.run(max_steps=500_000)
    assert inj.fired == 1
    assert len(res) == len(rres) == 2
    for a, b in zip(res, rres):
        assert a.completed.all()
        assert_results_identical(a, b)
    # spot-check semantics, not just self-consistency
    assert (res[0].results[0] == [fib_ref(n % 10) for n in range(8)]).all()
    assert (res[1].results[0]
            == [sum(range(n * 7)) for n in range(8)]).all()


# ---------------------------------------------------------------------------
# harness determinism + misc
# ---------------------------------------------------------------------------
def test_injector_is_deterministic():
    def schedule():
        inj = FaultInjector(seeded_faults(seed=42, n=3))
        seen = []
        for i in range(8):
            for point in ("launch", "serve"):
                try:
                    inj.fire(point)
                except InjectedFault as e:
                    seen.append((e.point, e.index))
        assert inj.fired == len(seen)
        return seen

    first = schedule()
    assert first  # the seeded plan actually fires
    assert schedule() == first  # same seed -> same incident schedule
    other = FaultInjector(seeded_faults(seed=43, n=3))
    assert [(f.point, f.at) for f in other.faults] \
        != [(f.point, f.at) for f in
            FaultInjector(seeded_faults(seed=42, n=3)).faults]


def test_scalar_rerun_reports_real_trap_codes():
    # a lane whose scalar re-run genuinely traps keeps its trap code
    from wasmedge_tpu.utils.builder import ModuleBuilder

    b = ModuleBuilder()
    b.add_function(["i32"], ["i32"], [], [
        ("i32.const", 1), ("local.get", 0), "i32.div_s",
    ], export="inv")
    conf = make_conf()
    ex, store, inst = instantiate(b.build(), conf)
    fidx = inst.exports["inv"][1]
    cells, trap, recs = scalar_rerun(
        inst, conf, "inv", fidx, [np.array([2, 0], np.int64)],
        np.array([0, 1], np.int64), 10_000)
    assert not recs
    from wasmedge_tpu.batch.image import TRAP_DONE

    assert trap[0] == TRAP_DONE and cells[0, 0] == 0  # 1 // 2
    assert trap[1] == int(ErrCode.DivideByZero)


def test_supervisor_records_land_in_statistics(tmp_path):
    from wasmedge_tpu.common.statistics import Statistics

    stats = Statistics()
    inj = FaultInjector([Fault(point="launch", at=1)])
    sup = BatchSupervisor(make_engine(build_fib(), make_conf()),
                          stats=stats, faults=inj,
                          checkpoint_dir=str(tmp_path))
    res = sup.run("fib", [np.full(LANES, 8, np.int64)],
                  max_steps=500_000)
    assert res.completed.all()
    assert [f.fault_class for f in stats.failures] == ["launch"]
    dumped = stats.dump()
    assert dumped["failures"][0]["fault_class"] == "launch"
