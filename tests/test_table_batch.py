"""Batch-engine table/segment/tail-call families (r05).

The reference runs these in its one dispatch loop
(/root/reference/lib/executor/engine/engine.cpp:181-205,
lib/executor/engine/tableInstr.cpp, and the tail-call frame replacement
include/runtime/stackmgr.h:80-98); here they are SIMT handlers over a
per-lane table plane and per-lane segment-dropped flags
(batch/engine.py).  Scalar-engine parity is the oracle throughout.
"""

import numpy as np
import pytest

from wasmedge_tpu.common.configure import Configure, Proposal
from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.utils.wat import parse_wat
from tests.helpers import instantiate


def _conf():
    conf = Configure()
    conf.add_proposal(Proposal.TailCall)
    conf.batch.steps_per_launch = 20000
    return conf


def _run_batch(wat, fn, args, lanes=8, conf=None):
    from wasmedge_tpu.batch.uniform import UniformBatchEngine

    conf = conf or _conf()
    ex, st, inst = instantiate(parse_wat(wat), conf)
    eng = UniformBatchEngine(inst, store=st, conf=conf, lanes=lanes)
    return eng.run(fn, [np.asarray(a, np.int64) for a in args])


def _scalar(wat, fn, args, conf=None):
    ex, st, inst = instantiate(parse_wat(wat), conf or _conf())
    return ex.invoke_raw(st, inst.find_func(fn), list(args))


def _parity(wat, fn, per_lane_args, lanes=8):
    """Batch lanes vs the scalar oracle, values and trap codes."""
    res = _run_batch(wat, fn, per_lane_args, lanes=lanes)
    for lane in range(lanes):
        largs = [int(a[lane]) for a in per_lane_args]
        try:
            exp = _scalar(wat, fn, largs)
            assert res.trap[lane] == -1, \
                f"lane {lane}: trapped {res.trap[lane]}, want {exp}"
            got = [int(r[lane]) & ((1 << 64) - 1) for r in res.results]
            assert got == [v & ((1 << 64) - 1) for v in exp], \
                f"lane {lane}: {got} != {exp}"
        except TrapError as te:
            assert res.trap[lane] == int(te.code), \
                f"lane {lane}: trap {res.trap[lane]}, want {te.code}"


WAT_SETGET = """(module
  (table 4 8 funcref)
  (func $f1 (result i32) (i32.const 11))
  (func $f2 (result i32) (i32.const 22))
  (elem $decl func $f1 $f2)
  (elem (i32.const 0) $f1)
  (func (export "go") (param i32 i32) (result i32)
    (if (i32.eqz (local.get 0))
      (then (table.set (local.get 1) (ref.func $f1)))
      (else (table.set (local.get 1) (ref.func $f2))))
    (i32.add
      (i32.mul (i32.const 100) (table.size))
      (call_indirect (result i32) (local.get 1)))))"""


def test_table_set_get_call_indirect_divergent():
    _parity(WAT_SETGET, "go",
            [np.array([0, 1, 0, 1, 0, 1, 0, 1], np.int64),
             np.array([2, 2, 3, 3, 1, 1, 9, 0], np.int64)])


WAT_BULK = """(module
  (table 2 funcref)
  (func $a (result i32) (i32.const 1))
  (func $b (result i32) (i32.const 2))
  (elem $seg func $a $b)
  (func (export "go") (param i32) (result i32)
    (local $r i32)
    (local.set $r (table.grow (ref.null func) (local.get 0)))
    (table.init $seg (i32.const 0) (i32.const 0) (i32.const 2))
    (table.copy (i32.const 2) (i32.const 0) (i32.const 2))
    (elem.drop $seg)
    (i32.add (i32.mul (local.get $r) (i32.const 1000))
      (i32.add (i32.mul (i32.const 10)
                        (call_indirect (result i32) (i32.const 2)))
               (call_indirect (result i32) (i32.const 3))))))"""


def test_table_grow_init_copy_drop():
    # divergent grow deltas: some lanes' copy targets stay OOB
    _parity(WAT_BULK, "go", [np.array([4, 4, 2, 4, 0, 4, 4, 1], np.int64)])


def test_table_grow_caps():
    wat = """(module (table 2 4 funcref)
      (func (export "go") (param i32) (result i32)
        (table.grow (ref.null func) (local.get 0))))"""
    _parity(wat, "go", [np.array([0, 1, 2, 3, 2, 1, 0, 100], np.int64)])


WAT_MEMINIT = """(module (memory 1)
  (data $d "\\41\\42\\43\\44\\45\\46\\47\\48")
  (func (export "go") (param i32 i32) (result i32)
    (if (local.get 1) (then (data.drop $d)))
    (memory.init $d (local.get 0) (i32.const 2) (i32.const 4))
    (i32.load (local.get 0))))"""


def test_memory_init_and_drop_divergent():
    # odd lanes drop the segment first -> init of 4 bytes traps OOB
    _parity(WAT_MEMINIT, "go",
            [np.arange(8, dtype=np.int64) * 16,
             (np.arange(8, dtype=np.int64) % 2)])


WAT_TAIL = """(module
  (func $loop (param i32 i64) (result i64)
    (if (result i64) (i32.eqz (local.get 0))
      (then (local.get 1))
      (else (return_call $loop (i32.sub (local.get 0) (i32.const 1))
                        (i64.add (local.get 1)
                                 (i64.extend_i32_u (local.get 0)))))))
  (func (export "go") (param i32) (result i64)
    (return_call $loop (local.get 0) (i64.const 0))))"""


def test_return_call_deeper_than_call_stack():
    # depth 5000 >> call_stack_depth: only frame replacement survives
    n = 5000
    res = _run_batch(WAT_TAIL, "go", [np.full(8, n, np.int64)])
    assert res.completed.all()
    assert (res.results[0] == n * (n + 1) // 2).all()


def test_return_call_indirect_parity():
    wat = """(module
      (table 2 funcref)
      (type $t (func (param i32 i64) (result i64)))
      (func $acc (type $t)
        (if (result i64) (i32.eqz (local.get 0))
          (then (local.get 1))
          (else (return_call_indirect (type $t)
            (i32.sub (local.get 0) (i32.const 1))
            (i64.add (local.get 1) (i64.const 3))
            (i32.const 0)))))
      (elem (i32.const 0) $acc)
      (func (export "go") (param i32) (result i64)
        (return_call_indirect (type $t)
          (local.get 0) (i64.const 0) (local.get 0))))"""
    # lane arg doubles as the table index: 0 -> $acc, 1 -> null,
    # >=2 -> undefined
    _parity(wat, "go", [np.array([0, 1, 2, 0, 5, 0, 1, 0], np.int64)])


def test_table_ops_trap_codes():
    wat = """(module (table 2 funcref)
      (func (export "go") (param i32) (result i32)
        (table.get (local.get 0)) (ref.is_null)))"""
    res = _run_batch(wat, "go",
                     [np.array([0, 1, 2, 5, 0, 1, 2, 5], np.int64)])
    assert (res.trap[[0, 1, 4, 5]] == -1).all()
    assert (res.trap[[2, 3, 6, 7]] == int(ErrCode.TableOutOfBounds)).all()


def test_multitenant_table_mutating_tenant():
    """A table-mutating tenant beside arithmetic tenants — the verdict's
    config-5 criterion (each tenant's mutations stay in its own table
    slot of the concatenated plane)."""
    from wasmedge_tpu.batch.engine import BatchEngine
    from wasmedge_tpu.batch.multitenant import (
        MultiTenantBatchEngine, Tenant)
    from wasmedge_tpu.models import build_fib

    conf = _conf()
    tenants = []
    ex1, st1, in1 = instantiate(build_fib(), conf)
    tenants.append(Tenant(BatchEngine(in1, store=st1, conf=conf, lanes=4),
                          "fib", [np.full(4, 12, np.int64)], 4))
    ex2, st2, in2 = instantiate(parse_wat(WAT_SETGET), conf)
    tenants.append(Tenant(BatchEngine(in2, store=st2, conf=conf, lanes=4),
                          "go",
                          [np.array([0, 1, 0, 1], np.int64),
                           np.array([2, 2, 3, 0], np.int64)], 4))
    ex3, st3, in3 = instantiate(parse_wat(WAT_BULK), conf)
    tenants.append(Tenant(BatchEngine(in3, store=st3, conf=conf, lanes=4),
                          "go", [np.array([4, 2, 0, 4], np.int64)], 4))
    eng = MultiTenantBatchEngine(tenants, conf=conf)
    outs = eng.run_tenants(max_steps=3_000_000)
    # tenant 0: fib(12)
    assert (outs[0].results[0] == 144).all()
    # tenant 1: scalar oracle per lane
    for lane, (sel, idx) in enumerate(((0, 2), (1, 2), (0, 3), (1, 0))):
        exp = _scalar(WAT_SETGET, "go", [sel, idx])
        assert int(outs[1].results[0][lane]) == exp[0]
    # tenant 2 lane-wise vs oracle (incl. trapping lanes)
    for lane, n in enumerate((4, 2, 0, 4)):
        try:
            exp = _scalar(WAT_BULK, "go", [n])
            assert outs[2].trap[lane] == -1
            assert int(outs[2].results[0][lane]) == exp[0]
        except TrapError as te:
            assert outs[2].trap[lane] == int(te.code)


def test_checkpoint_roundtrip_with_table_planes(tmp_path):
    from wasmedge_tpu.batch import checkpoint
    from wasmedge_tpu.batch.engine import BatchEngine

    conf = _conf()
    conf.batch.steps_per_launch = 8  # snapshot mid-flight
    ex, st, inst = instantiate(parse_wat(WAT_BULK), conf)
    eng = BatchEngine(inst, store=st, conf=conf, lanes=4)
    state = eng.initial_state(
        inst.exports["go"][1], [np.full(4, 4, np.int64)])
    state, total = eng.run_from_state(state, 0, 8)
    assert (np.asarray(state.trap) == 0).all()  # still running
    p = tmp_path / "tab.ckpt"
    checkpoint.save(p, eng, state, total)
    state2, total2 = checkpoint.load(p, eng)
    assert total2 == total
    assert np.array_equal(np.asarray(state.tab), np.asarray(state2.tab))
    state2, _ = eng.run_from_state(state2, total2, 3_000_000)
    lo = np.asarray(state2.stack_lo)[0].view(np.uint32).astype(np.int64)
    assert (lo == 2012).all()


def test_checkpoint_missing_table_planes_refused(tmp_path):
    """A pre-r05 (plane-less) checkpoint against a table-mutating image
    must be refused, like the SIMD-plane guard."""
    import io
    import json

    from wasmedge_tpu.batch import checkpoint
    from wasmedge_tpu.batch.engine import BatchEngine

    conf = _conf()
    ex, st, inst = instantiate(parse_wat(WAT_BULK), conf)
    eng = BatchEngine(inst, store=st, conf=conf, lanes=4)
    state = eng.initial_state(
        inst.exports["go"][1], [np.full(4, 4, np.int64)])
    buf = io.BytesIO()
    checkpoint.save(buf, eng, state, 0)
    buf.seek(0)
    with np.load(buf, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != "meta"}
        meta = json.loads(str(z["meta"]))
    for k in ("state_tab", "state_tsize"):
        arrays.pop(k)
    crippled = io.BytesIO()
    np.savez_compressed(crippled, meta=json.dumps(meta), **arrays)
    crippled.seek(0)
    with pytest.raises(ValueError, match="lacks planes"):
        checkpoint.load(crippled, eng)
