"""Whole-function tier-up (batch/tierup.py) — r20.

The compiled-function tier: hot, provably-safe functions (leaf, pure
cells + licensed memory, structured control flow, finite absint cost
bound) compile into lane-masked jitted bodies dispatched ONCE per
function call instead of once per op — counted loops run as bounded
device loops under their r19 trip-bound license.  Pins the r17/r19
bar for the new tier:

  - tierup on/off bit-identical (results, traps, retired) with
    strictly fewer steps on — and the scalar engine agrees;
  - the canonical counted loop promotes with >= 1 bounded device
    loop, and the off-knob build plans nothing (seed path by
    construction);
  - per-function-call dispatch accounting: the tu_ctr counter plane
    reaches the flight recorder and the Prometheus export, and the
    opcode histogram still equals retired;
  - a fuel budget below the promoted fuel bound refuses promotion at
    runtime and lands the exhaustion trap per-op, bit-identically;
  - the FULL demotion ladder: a compiled-tier fault walks
    compiled-fn -> fused SIMT -> unfused SIMT -> scalar, adopting the
    newest checkpoint at each SIMT rung, bit-identical to the
    unfaulted run (deterministic via the testing/faults.py seams).

Fast by construction (tiny lanes, small trip counts): tier-1.
"""

import numpy as np
import pytest

from wasmedge_tpu.batch.engine import BatchEngine
from wasmedge_tpu.batch.supervisor import BatchSupervisor
from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.models import build_call_counted_loop, build_counted_loop
from wasmedge_tpu.testing.faults import Fault, FaultInjector
from tests.helpers import instantiate, run_wasm

pytestmark = pytest.mark.tierup

LANES = 8
N, CALLS = 32, 48                       # driver/leaf cadence fixture
LEAF_SUM = N * (N - 1) // 2


def make_conf(tierup=True, sup=(), **batch):
    conf = Configure()
    conf.batch.tierup = tierup
    conf.batch.steps_per_launch = 100
    conf.batch.value_stack_depth = 64
    conf.batch.call_stack_depth = 16
    conf.batch.rng_seed = 7
    for k, v in batch.items():
        setattr(conf.batch, k, v)
    conf.supervisor.backoff_base_s = 0.0
    conf.supervisor.checkpoint_every_steps = 200
    for k, v in dict(sup).items():
        setattr(conf.supervisor, k, v)
    return conf


def make_engine(data, conf, lanes=LANES):
    ex, store, inst = instantiate(data, conf)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes)


def assert_results_identical(a, b):
    assert (np.asarray(a.trap) == np.asarray(b.trap)).all()
    assert (np.asarray(a.retired) == np.asarray(b.retired)).all()
    for ra, rb in zip(a.results, b.results):
        assert (np.asarray(ra) == np.asarray(rb)).all()


class TestBitExact:
    def test_counted_loop_promotes_as_bounded_device_loop(self):
        """The canonical absint fixture compiles whole: one dispatch
        retires the entire function, with its counted latch licensed
        as a bounded lax.while_loop (device_loops >= 1)."""
        data = build_counted_loop(64)
        args = [np.arange(LANES, dtype=np.int64)]
        res = {}
        for tierup in (True, False):
            eng = make_engine(data, make_conf(tierup))
            res[tierup] = eng.run("count", args, max_steps=100_000)
            if tierup:
                rep = eng.img.tierup_report
                assert rep["promoted"], "nothing promoted"
                p = rep["promoted"][0]
                assert p["cost_bound"] == 770   # absint exact bound
                assert p["fuel_bound"] >= p["cost_bound"]
                assert p["device_loops"] >= 1   # trip-bound license
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert res[True].steps < res[False].steps
        # arg is ignored by the loop: every lane returns sum(0..63)
        assert (np.asarray(res[True].results[0]) == 64 * 63 // 2).all()
        assert int(run_wasm(data, "count", [0])[0]) == 64 * 63 // 2

    def test_driver_leaf_calls_bit_identical_across_launches(self):
        """Per-CALL dispatch cadence: a non-promotable driver calls the
        promoted leaf CALLS times, spanning several launch boundaries
        in both modes."""
        data = build_call_counted_loop(N, CALLS)
        args = [np.arange(LANES, dtype=np.int64)]
        res = {}
        for tierup in (True, False):
            eng = make_engine(data, make_conf(tierup))
            res[tierup] = eng.run("call_count", args,
                                  max_steps=2_000_000)
            if tierup:
                rep = eng.img.tierup_report
                # the driver has CALL ops: leaf-only verdict promotes
                # exactly the leaf
                assert [p["idx"] for p in rep["promoted"]] == [1]
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert res[True].steps < res[False].steps
        expect = np.arange(LANES) + CALLS * LEAF_SUM
        assert (np.asarray(res[True].results[0]) == expect).all()

    def test_knob_off_plans_nothing(self):
        """tierup=False is the seed path by construction: no tier
        planes exist, so the step builder compiles the identical
        program it did before r20."""
        eng = make_engine(build_counted_loop(64), make_conf(False))
        res = eng.run("count", [np.zeros(LANES, np.int64)],
                      max_steps=100_000)
        assert res.completed.all()
        assert getattr(eng.img, "tier_fn", None) is None


class TestGas:
    def test_tight_fuel_refuses_promotion_lands_per_op(self):
        """fuel <= fuel_bound: the runtime gate keeps every lane on
        the per-op path, so exhaustion lands at the same op with the
        same retired count whether the tier is on or off."""
        data = build_counted_loop(64)
        res = {}
        for tierup in (True, False):
            eng = make_engine(data, make_conf(
                tierup, fuel_per_launch=300))
            res[tierup] = eng.run("count", [np.zeros(LANES, np.int64)],
                                  max_steps=100_000)
        assert (np.asarray(res[True].trap)
                == int(ErrCode.CostLimitExceeded)).all()
        assert_results_identical(res[True], res[False])

    def test_ample_fuel_still_promotes(self):
        data = build_counted_loop(64)
        res = {}
        for tierup in (True, False):
            eng = make_engine(data, make_conf(
                tierup, fuel_per_launch=100_000))
            res[tierup] = eng.run("count", [np.zeros(LANES, np.int64)],
                                  max_steps=100_000)
        assert res[True].completed.all()
        assert_results_identical(res[True], res[False])
        assert res[True].steps < res[False].steps


@pytest.mark.obs
class TestObs:
    def test_dispatch_per_call_counters_and_histogram(self):
        from wasmedge_tpu.obs.metrics import render_prometheus

        conf = make_conf(True)
        conf.obs.enabled = True
        conf.obs.opcode_histogram = True
        eng = make_engine(build_call_counted_loop(N, CALLS), conf)
        res = eng.run("call_count",
                      [np.arange(LANES, dtype=np.int64)],
                      max_steps=2_000_000)
        assert res.completed.all()
        retired = int(np.asarray(res.retired, np.int64).sum())
        hist = eng.obs.opcode_counts
        assert hist is not None and int(hist.sum()) == retired
        tu = eng.obs.tierup_counts
        # ONE compiled-body dispatch per function call per lane — the
        # r20 dispatch contract
        assert tu["dispatches"] == LANES * CALLS
        assert 0 < tu["retired_comp"] <= tu["retired_total"]
        assert tu["retired_total"] == retired
        text = render_prometheus(eng.obs)
        assert "wasmedge_tierup_dispatches_total" in text
        assert 'wasmedge_tierup_retired_total{tier="compiled"}' in text
        assert 'wasmedge_tierup_functions{kind="promoted"} 1' in text


@pytest.mark.faults
class TestLadder:
    """compiled-fn -> fused SIMT -> unfused SIMT -> scalar."""

    ARGS = [np.arange(LANES, dtype=np.int64)]
    EXPECT = np.arange(LANES) + CALLS * LEAF_SUM

    def _ref(self, tmp_path):
        sup = BatchSupervisor(
            make_engine(build_call_counted_loop(N, CALLS), make_conf()),
            checkpoint_dir=str(tmp_path / "ref"))
        res = sup.run("call_count", list(self.ARGS),
                      max_steps=2_000_000)
        assert res.completed.all()
        assert (np.asarray(res.results[0]) == self.EXPECT).all()
        return res

    def test_demote_nocomp_adopts_checkpoint(self, tmp_path):
        """One compiled-tier fault after two clean launches: the
        simt_nocomp rung must adopt the compiled rung's checkpoint
        (not replay from scratch) and finish bit-identical."""
        rres = self._ref(tmp_path)
        inj = FaultInjector([Fault(point="launch", at=2)])
        sup = BatchSupervisor(
            make_engine(build_call_counted_loop(N, CALLS),
                        make_conf(sup={"max_retries": 0})),
            faults=inj, checkpoint_dir=str(tmp_path / "sup"))
        res = sup.run("call_count", list(self.ARGS),
                      max_steps=2_000_000)
        assert inj.fired == 1
        assert res.completed.all()
        assert_results_identical(res, rres)
        demotes = [f for f in sup.failures if f.fault_class == "demote"]
        assert [f.tier for f in demotes] == ["simt"]
        # checkpoint adoption: the demoted rung resumed mid-stream
        assert sup._restored_from is not None

    def test_full_ladder_to_scalar(self, tmp_path):
        """Three consecutive launch faults exhaust every SIMT rung in
        order; the scalar rung finishes the batch correctly."""
        rres = self._ref(tmp_path)
        inj = FaultInjector([Fault(point="launch", at=2, times=3)])
        sup = BatchSupervisor(
            make_engine(build_call_counted_loop(N, CALLS),
                        make_conf(sup={"max_retries": 0})),
            faults=inj, checkpoint_dir=str(tmp_path / "sup"))
        res = sup.run("call_count", list(self.ARGS),
                      max_steps=2_000_000)
        assert inj.fired == 3
        assert res.completed.all()
        # scalar rung reports zero retired (no device state): compare
        # results + traps against the unfaulted reference
        assert (np.asarray(res.trap) == np.asarray(rres.trap)).all()
        assert (np.asarray(res.results[0]) == self.EXPECT).all()
        demotes = [f for f in sup.failures if f.fault_class == "demote"]
        assert [f.tier for f in demotes] == \
            ["simt", "simt_nocomp", "simt_unfused"]
        launches = [f for f in sup.failures
                    if f.fault_class == "launch"]
        assert len(launches) == 3   # max_retries=0: one per SIMT rung

    def test_unpromoted_module_skips_nocomp_rung(self, tmp_path):
        """A module that promotes nothing (recursive fib) must fall
        straight through simt_nocomp: the rung is ineligible, not a
        retry burner."""
        from wasmedge_tpu.models import build_fib

        inj = FaultInjector([Fault(point="launch", at=0, times=2)])
        sup = BatchSupervisor(
            make_engine(build_fib(), make_conf(sup={"max_retries": 0})),
            faults=inj, checkpoint_dir=str(tmp_path))
        res = sup.run("fib", [np.full(LANES, 9, np.int64)],
                      max_steps=500_000)
        assert inj.fired == 2
        assert res.completed.all()
        demotes = [f.tier for f in sup.failures
                   if f.fault_class == "demote"]
        # simt fails, nocomp skipped (nothing promoted), unfused fails,
        # scalar finishes
        assert demotes == ["simt", "simt_unfused"]
