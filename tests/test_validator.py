"""Validator tests: type checking, control typing, polymorphic unreachable
code, const exprs, module-level checks — reference FormChecker coverage."""

import pytest

from wasmedge_tpu.common.errors import ErrCode, ValidationError
from wasmedge_tpu.utils.builder import ModuleBuilder
from tests.helpers import load_validate, single_func


def check(data):
    return load_validate(data)


def check_fails(data, code=None):
    with pytest.raises(ValidationError) as e:
        load_validate(data)
    if code is not None:
        assert e.value.code == code
    return e.value


class TestTyping:
    def test_stack_underflow(self):
        check_fails(single_func([], [], [], ["i32.add"]))

    def test_type_mismatch(self):
        check_fails(single_func([], ["i32"], [], [
            ("i32.const", 1), ("f32.const", 1.0), "i32.add",
        ]))

    def test_result_missing(self):
        check_fails(single_func([], ["i32"], [], []))

    def test_result_extra(self):
        check_fails(single_func([], [], [], [("i32.const", 1)]))

    def test_local_index(self):
        check_fails(single_func([], [], [], [("local.get", 0)]),
                    ErrCode.InvalidLocalIdx)

    def test_block_result(self):
        check(single_func([], ["i32"], [], [
            ("block", "i32"), ("i32.const", 1), "end",
        ]))
        check_fails(single_func([], ["i32"], [], [
            ("block", "i32"), "end",
        ]))

    def test_if_without_else_needs_balanced_types(self):
        check_fails(single_func(["i32"], ["i32"], [], [
            ("local.get", 0), ("if", "i32"), ("i32.const", 1), "end",
        ]))

    def test_branch_depth(self):
        check_fails(single_func([], [], [], [
            ("block", None), ("br", 5), "end",
        ]), ErrCode.InvalidLabelIdx)

    def test_unreachable_polymorphism(self):
        # after unreachable, anything validates (even bogus stack use)
        check(single_func([], ["i32"], [], [
            "unreachable", "i32.add",
        ]))
        # br makes rest of block polymorphic
        check(single_func([], ["i32"], [], [
            ("block", "i32"), ("i32.const", 1), ("br", 0), "i32.add", "end",
        ]))

    def test_br_value_type(self):
        check_fails(single_func([], ["i32"], [], [
            ("block", "i32"), ("f32.const", 1.0), ("br", 0), "end",
        ]))

    def test_br_table_arity_mismatch(self):
        check_fails(single_func(["i32"], [], [], [
            ("block", "i32"),
            ("block", None),
            ("i32.const", 0), ("local.get", 0), ("br_table", [1], 0),
            "drop",
            "end",
            ("i32.const", 1),
            "end",
            "drop",
        ]))

    def test_select_needs_same_types(self):
        check_fails(single_func([], ["i32"], [], [
            ("i32.const", 1), ("f64.const", 1.0), ("i32.const", 0), "select",
        ]))

    def test_call_arg_types(self):
        b = ModuleBuilder()
        b.add_function(["i64"], [], [], [("local.get", 0), "drop"])
        b.add_function([], [], [], [("i32.const", 1), ("call", 0)], export="f")
        with pytest.raises(ValidationError):
            load_validate(b.build())

    def test_global_set_immutable(self):
        b = ModuleBuilder()
        b.add_global("i32", False, [("i32.const", 1)])
        b.add_function([], [], [], [("i32.const", 2), ("global.set", 0)])
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.ImmutableGlobal

    def test_alignment_limit(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function([], ["i32"], [], [
            ("i32.const", 0), ("i32.load", 3, 0),  # 2^3=8 > 4
        ])
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.InvalidAlignment

    def test_memory_required_for_load(self):
        check_fails(single_func([], ["i32"], [], [
            ("i32.const", 0), ("i32.load", 2, 0),
        ]), ErrCode.InvalidMemoryIdx)


class TestModuleLevel:
    def test_duplicate_export(self):
        b = ModuleBuilder()
        b.add_function([], [], [], [], export="f")
        b.add_function([], [], [], [], export="f")
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.DupExportName

    def test_export_bad_index(self):
        b = ModuleBuilder()
        b.export_func("f", 3)
        with pytest.raises(ValidationError):
            load_validate(b.build())

    def test_start_must_be_void(self):
        b = ModuleBuilder()
        f = b.add_function(["i32"], [], [], [("local.get", 0), "drop"])
        b.set_start(f)
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.InvalidStartFunc

    def test_const_expr_rejects_non_const(self):
        b = ModuleBuilder()
        b.add_global("i32", False, [("i32.const", 1), ("i32.const", 2), "i32.add"])
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.ConstExprRequired

    def test_const_expr_type(self):
        b = ModuleBuilder()
        b.add_global("i32", False, [("f32.const", 1.0)])
        with pytest.raises(ValidationError):
            load_validate(b.build())

    def test_memory_page_limit(self):
        b = ModuleBuilder()
        b.add_memory(70000)
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.InvalidMemPages

    def test_data_count_required_for_memory_init(self):
        b = ModuleBuilder()
        b.add_memory(1)
        b.add_function([], [], [], [
            ("i32.const", 0), ("i32.const", 0), ("i32.const", 0),
            ("memory.init", 0),
        ])
        b.add_passive_data(b"x")  # data section present but no datacount
        with pytest.raises(ValidationError) as e:
            load_validate(b.build())
        assert e.value.code == ErrCode.DataCountRequired


class TestLoweringShape:
    def test_max_height_and_locals(self):
        mod = check(single_func(["i32"], ["i32"], ["i64", "f32"], [
            ("local.get", 0), ("i32.const", 1), "i32.add",
            ("i32.const", 2), "i32.mul",
        ]))
        meta = mod.lowered.funcs[0]
        assert meta.nparams == 1 and meta.nlocals == 3
        assert meta.max_height == 2
        assert meta.nresults == 1

    def test_branch_descriptors_cut_stack(self):
        # br out of a block that has operands on the stack: pop_to must cut
        mod = check(single_func([], ["i32"], [], [
            ("block", "i32"),
            ("i32.const", 10),      # operand that must be discarded on br
            ("i32.const", 7),
            ("br", 0),              # carries 1 value, cuts to height 0
            "end",
        ]))
        from wasmedge_tpu.validator.image import LOP_BR
        image = mod.lowered
        sites = [i for i, o in enumerate(image.op) if o == LOP_BR]
        assert sites, "lowered br missing"
        s = sites[0]
        assert image.b[s] == 1 and image.c[s] == 0

    def test_loop_branch_targets_backward(self):
        mod = check(single_func([], [], [], [
            ("loop", None), "nop", "end",
        ]))
        # simple shape sanity: lowered image ends with return
        from wasmedge_tpu.executor.engine import OP_RETURN
        assert mod.lowered.op[-1] == OP_RETURN
